"""Continuous-batching serve-tier load test (the ROADMAP "hundreds of
concurrent generate streams" proof).

Spins up a ServeLoop (inference/serving.py) over a tiny GPT and drives
SERVE_LOAD_STREAMS concurrent generate streams from SERVE_LOAD_CLIENTS
client threads with jittered arrivals — far more streams than decode
slots, so the run exercises admission scheduling, pool backpressure and
retire-then-admit churn, not just the fused decode step. Reports
tokens/s, p50/p99 time-to-first-token and p50/p99 per-token latency, the
serve.* gauge snapshot, and FAILS (exit 1) on any request error. With
SERVE_LOAD_VERIFY=N, N randomly chosen streams are cross-checked
token-for-token against per-request sequential `GPT.generate` — the
continuous-batching correctness oracle running inside the load test
itself.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/serve_load_test.py

Env knobs (defaults are the CPU-valid tier-1 shape):
  SERVE_LOAD_STREAMS=256   concurrent generate streams
  SERVE_LOAD_CLIENTS=32    client threads submitting them
  SERVE_LOAD_PROMPT=12     max prompt length (ragged 4..PROMPT)
  SERVE_LOAD_NEW=16        tokens generated per stream
  SERVE_LOAD_SLOTS=64      decode slots (ServeConfig.max_active)
  SERVE_LOAD_BLOCKS=160    KV pool blocks
  SERVE_LOAD_BLOCK_SIZE=16 tokens per pool block
  SERVE_LOAD_VERIFY=4      streams cross-checked vs sequential generate

framework_lint TOOL_CROSS_CHECKS runs self_check() here: the
FLAGS_serve_* defaults, bench.py's BENCH_SERVE_* serve-mode knobs,
tools/hlo_evidence.py's SERVE_CFG, and docs/serving.md must agree.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import numpy as np  # noqa: E402

STREAMS = int(os.environ.get("SERVE_LOAD_STREAMS", 256))
CLIENTS = int(os.environ.get("SERVE_LOAD_CLIENTS", 32))
PROMPT = int(os.environ.get("SERVE_LOAD_PROMPT", 12))
NEW = int(os.environ.get("SERVE_LOAD_NEW", 16))
SLOTS = int(os.environ.get("SERVE_LOAD_SLOTS", 64))
BLOCKS = int(os.environ.get("SERVE_LOAD_BLOCKS", 160))
BLOCK_SIZE = int(os.environ.get("SERVE_LOAD_BLOCK_SIZE", 16))
VERIFY = int(os.environ.get("SERVE_LOAD_VERIFY", 4))

# flag defaults this tool (and docs/serving.md's flag table) are written
# against; drift means the doc + this header need an update
SERVE_FLAG_DEFAULTS = {
    "FLAGS_use_paged_attention": True,
    "FLAGS_serve_block_size": 0,
    "FLAGS_serve_kv_blocks": 512,
    "FLAGS_serve_max_active": 64,
}

# bench.py serve-mode env defaults (BENCH_MODE=serve); self_check pins
# them so the bench line and this drill describe the same tier
BENCH_SERVE_DEFAULTS = {
    "BENCH_SERVE_REQUESTS": 256,
    "BENCH_SERVE_PROMPT": 32,
    "BENCH_SERVE_NEW": 64,
    "BENCH_SERVE_SLOTS": 64,
    "BENCH_SERVE_BLOCKS": 512,
}


def run():
    import paddle_tpu as paddle
    from paddle_tpu.core import monitor
    from paddle_tpu.inference import ServeConfig, ServeLoop
    from paddle_tpu.text.models.gpt import GPT, GPTConfig
    from paddle_tpu.traffic import harness

    paddle.seed(0)
    cfg = GPTConfig.tiny()
    net = GPT(cfg)
    net.eval()
    cap = min(cfg.max_seq_len, PROMPT + NEW + BLOCK_SIZE)
    loop = ServeLoop(net, ServeConfig(max_active=SLOTS, kv_blocks=BLOCKS,
                                      block_size=BLOCK_SIZE,
                                      max_seq_len=cap))
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size,
                           (int(rng.randint(4, PROMPT + 1)),))
               .astype(np.int64) for _ in range(STREAMS)]

    # compile outside the timed window: the decode step plus ONE prefill
    # per bucket the ragged prompts can land in (a cold bucket would put
    # an XLA compile inside the timed p99)
    buckets = {}
    for p in prompts:
        b = 8
        while b < p.size:
            b *= 2
        buckets.setdefault(b, p)
    for p in buckets.values():
        loop.serve([p], max_new_tokens=2)
    monitor.reset(prefix="serve.")
    loop.start()

    # same jitter stream the hand-rolled client loop drew: client `cid`
    # takes the stride cid, cid+CLIENTS, ... and sleeps a fresh
    # RandomState(1000+cid).uniform(0, 2ms) before each submit — the
    # harness honors per-submission delays in that exact stride order
    delays = [0.0] * STREAMS
    for cid in range(CLIENTS):
        crng = np.random.RandomState(1000 + cid)
        for i in range(cid, STREAMS, CLIENTS):
            delays[i] = float(crng.uniform(0, 0.002))
    stats = harness.drive_serve(
        loop, harness.submissions_from_prompts(prompts, NEW, delays),
        clients=CLIENTS, wait="result", result_timeout_s=600.0)
    loop.stop()
    outs = stats.outs
    toks = stats.tokens
    ttfts, per_tok = stats.ttfts_ms, stats.token_ms
    errors = stats.errors
    dt = stats.wall_s

    verified = 0
    if VERIFY:
        idxs = np.random.RandomState(7).choice(
            STREAMS, size=min(VERIFY, STREAMS), replace=False)
        for i in sorted(int(x) for x in idxs):
            if outs[i] is None:
                continue
            ref = np.asarray(net.generate(
                paddle.to_tensor(prompts[i][None]), max_new_tokens=NEW,
                temperature=0, use_cache=True).numpy())[0,
                                                        prompts[i].size:]
            if not np.array_equal(outs[i], ref):
                errors.append(
                    f"verify[{i}]: serve tokens != sequential generate "
                    f"({outs[i].tolist()} vs {ref.tolist()})")
            else:
                verified += 1

    # ONE percentile estimator across serve_load_test / ps_load_test /
    # online_drill (core/slo.py) — the numbers in the three reports are
    # comparable because they share the implementation
    from paddle_tpu.core.slo import percentile

    def pct(xs, p):
        return percentile(xs, p, ndigits=3)

    snap = {k: v for k, v in monitor.stats("serve.").items()}
    report = {
        "tool": "tools/serve_load_test.py",
        "streams": STREAMS,
        "clients": CLIENTS,
        "slots": SLOTS,
        "kv_blocks": BLOCKS,
        "block_size": BLOCK_SIZE,
        "tokens": toks,
        "tokens_per_s": round(toks / dt, 2),
        "wall_s": round(dt, 3),
        "ttft_ms": {"p50": pct(ttfts, 50), "p99": pct(ttfts, 99)},
        "token_ms": {"p50": pct(per_tok, 50), "p99": pct(per_tok, 99)},
        "preempted": int(snap.get("serve.preempted", 0)),
        "completed": int(snap.get("serve.requests_completed", 0)),
        "verified_vs_generate": verified,
        "request_errors": len(errors),
    }
    print(json.dumps(report, indent=1))
    for e in errors[:10]:
        print("ERROR:", e, file=sys.stderr)
    return 1 if errors else 0


# --------------------------------------------------------------------------
# framework_lint cross-check (TOOL_CROSS_CHECKS)
# --------------------------------------------------------------------------

def self_check():
    """Serve knobs <-> flag defaults <-> bench serve config <->
    hlo_evidence serve_decode config <-> docs. Returns violations."""
    problems = []
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        from paddle_tpu.core import flags as _flags
    except Exception as e:  # pragma: no cover
        return [f"serve_load_test: paddle_tpu import failed: {e!r}"]
    for name, want in SERVE_FLAG_DEFAULTS.items():
        defn = _flags._DEFS.get(name)
        if defn is None:
            problems.append(f"serve_load_test: flag {name} is no longer "
                            "defined in core/flags.py")
        elif defn[1] != want:
            problems.append(
                f"serve_load_test: {name} default drifted "
                f"({defn[1]!r} != {want!r}) — update SERVE_FLAG_DEFAULTS "
                "and docs/serving.md")
    # bench.py serve-mode env defaults
    import re
    with open(os.path.join(repo, "bench.py")) as f:
        src = f.read()
    for env, want in BENCH_SERVE_DEFAULTS.items():
        m = re.search(r'os\.environ\.get\("%s",\s*([0-9]+)\)' % env, src)
        if not m:
            problems.append(
                f"serve_load_test: bench.py no longer reads {env}")
        elif int(m.group(1)) != want:
            problems.append(
                f"serve_load_test: bench.py default {env}={m.group(1)} "
                f"but this tool assumes {want}")
    # the bench serve slots/blocks defaults must BE the flag defaults —
    # one serving shape across bench, flags and the evidence tool
    if BENCH_SERVE_DEFAULTS["BENCH_SERVE_SLOTS"] != \
            SERVE_FLAG_DEFAULTS["FLAGS_serve_max_active"]:
        problems.append("serve_load_test: BENCH_SERVE_SLOTS != "
                        "FLAGS_serve_max_active default")
    if BENCH_SERVE_DEFAULTS["BENCH_SERVE_BLOCKS"] != \
            SERVE_FLAG_DEFAULTS["FLAGS_serve_kv_blocks"]:
        problems.append("serve_load_test: BENCH_SERVE_BLOCKS != "
                        "FLAGS_serve_kv_blocks default")
    # hlo_evidence's serve_decode config
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import hlo_evidence
        scfg = hlo_evidence.SERVE_CFG
        if scfg["slots"] != SERVE_FLAG_DEFAULTS["FLAGS_serve_max_active"]:
            problems.append(
                "serve_load_test: hlo_evidence SERVE_CFG slots "
                f"{scfg['slots']} != FLAGS_serve_max_active default")
        if scfg["blocks"] != SERVE_FLAG_DEFAULTS["FLAGS_serve_kv_blocks"]:
            problems.append(
                "serve_load_test: hlo_evidence SERVE_CFG blocks "
                f"{scfg['blocks']} != FLAGS_serve_kv_blocks default")
    except Exception as e:  # pragma: no cover
        problems.append(
            f"serve_load_test: cannot cross-check hlo_evidence: {e!r}")
    # docs
    doc_path = os.path.join(repo, "docs", "serving.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return problems + [f"serve_load_test: cannot read {doc_path}: {e}"]
    for name in SERVE_FLAG_DEFAULTS:
        if name not in doc:
            problems.append(f"serve_load_test: flag {name} is not "
                            "documented in docs/serving.md")
    for token in ("serve_load_test", "BENCH_MODE=serve"):
        if token not in doc:
            problems.append(
                f"serve_load_test: docs/serving.md no longer mentions "
                f"`{token}`")
    # the p50/p99 lines must come from the shared estimator, and it must
    # round-trip the exact values this report's pins were written against
    try:
        from paddle_tpu.core.slo import percentile
        if percentile([1.0, 2.0, 3.0, 4.0], 50, ndigits=3) != 2.5:
            problems.append("serve_load_test: core.slo.percentile no "
                            "longer matches np.percentile semantics")
        if percentile([], 99, ndigits=3) is not None:
            problems.append("serve_load_test: core.slo.percentile([]) "
                            "must be None (empty stream)")
    except Exception as e:
        problems.append(
            f"serve_load_test: shared percentile estimator gone: {e!r}")
    with open(os.path.abspath(__file__)) as f:
        self_src = f.read()
    if "from paddle_tpu.core.slo import percentile" not in self_src:
        problems.append("serve_load_test: report percentiles must come "
                        "from core.slo.percentile (shared estimator)")
    if "harness.drive_serve" not in self_src:
        problems.append("serve_load_test: the client submit loop must be "
                        "the shared paddle_tpu.traffic.harness.drive_serve")
    return problems


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-check" in argv or "--self_check" in argv:
        problems = self_check()
        for p in problems:
            print(p)
        print("serve_load_test self-check:",
              "clean" if not problems else f"{len(problems)} problem(s)")
        return 1 if problems else 0
    return run()


if __name__ == "__main__":
    sys.exit(main())
