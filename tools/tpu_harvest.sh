#!/bin/bash
# One-shot TPU measurement harvest for round 4 (run when the chip is live):
#   1. full bench (bert + resnet + decode + longseq) -> stdout JSON lines
#   2. profiler breakdown artifact -> BENCH_PROFILE_r04.txt (VERDICT item 7)
# Usage: bash tools/tpu_harvest.sh
set -u
cd "$(dirname "$0")/.."

echo "== probe ==" >&2
timeout 120 python -c "
import jax, jax.numpy as jnp, numpy as np
print('tpu:', jax.devices())
print('warm:', float(np.asarray((jnp.ones((8,8))@jnp.ones((8,8))).sum())))" || {
  echo "TPU unreachable" >&2; exit 1; }

echo "== bench (all modes) ==" >&2
timeout 3000 python bench.py 2>bench_r04_stderr.log
tail -5 bench_r04_stderr.log >&2 || true

echo "== profile artifact ==" >&2
BENCH_PROFILE=1 BENCH_MODE=bert BENCH_STEPS=20 timeout 1200 \
  python bench.py 2>BENCH_PROFILE_r04.txt 1>/dev/null || true
grep -c . BENCH_PROFILE_r04.txt >&2 || true

echo "== flash block sanity at long seq ==" >&2
timeout 900 python - <<'EOF' 2>/dev/null || true
import time, jax, jax.numpy as jnp, numpy as np
import paddle_tpu as paddle
paddle.set_flags({"FLAGS_flash_min_seq": 0})
from paddle_tpu.nn import functional as F
def timeit(f, *a, n=20):
    o = f(*a); _ = float(np.asarray(o.reshape(-1)[0], np.float32))
    t0 = time.perf_counter()
    for _ in range(n): o = f(o, *a[1:])
    _ = float(np.asarray(o.reshape(-1)[0], np.float32))
    return (time.perf_counter()-t0)/n*1000
key = jax.random.PRNGKey(0)
for s in (2048, 4096):
    q = jax.random.normal(key, (1, 12, s, 64), jnp.bfloat16)
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    fl = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    print(f"s={s}: flash fwd {timeit(fl, q, q, q):.2f} ms")
EOF
echo "harvest done" >&2
