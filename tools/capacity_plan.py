#!/usr/bin/env python
"""Serve capacity planner: analytic TTFT/p99 prediction + closed-loop
validation against the telemetry hub (ISSUE 18 tentpole, side 2).

Modes:

  python tools/capacity_plan.py                      # analytic report
  python tools/capacity_plan.py --validate           # closed loop (CPU)
  python tools/capacity_plan.py --self-check

**Report** (default): prices the serve loop from the STATIC cost models
alone — the HLO-evidence `serve_decode` roofline split into weight-read
floor + per-stream slope, prefill via the analyzer's per-op FLOPs
registry, hot-swap publish wire cost over the PR 16 DCN tier — and
sweeps offered load up to and past the saturation knee, printing
predicted p50/p99, utilization rho, and the M/G/k wait rail per rate.
No hardware, no serving, deterministic.

**Validate**: calibrates a DeviceProfile from the live CPU tiny-GPT
loop (static/capacity.calibrate_cpu), then for each builtin workload
spec (steady Poisson / diurnal wave / flash crowd) replays the SAME
deterministic schedule twice — once through the beat simulation
(prediction), once through the real ServeLoop via traffic/harness with
a TelemetryHub scoring the run from its merged histograms — and
asserts hub-observed throughput + TTFT/token p50 land within
FLAGS_capacity_p50_band_pct of prediction and the p99s within
FLAGS_capacity_p99_band_pct. The achieved headroom is written to
HLO_EVIDENCE.json `graphs.capacity_validation.band_headroom_x` and
gated >= 1.0 by framework_lint.check_perf_floors.

Flag/doc/bench pins live in self_check (TOOL_CROSS_CHECKS).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the bands and knobs this tool runs with; self_check pins them against
# core/flags.py defaults and the docs/traffic_lab.md flag table
CAPACITY_FLAG_DEFAULTS = {
    "FLAGS_capacity_p50_band_pct": 25.0,
    "FLAGS_capacity_p99_band_pct": 40.0,
    "FLAGS_capacity_knee_rho": 0.85,
    "FLAGS_capacity_calib_beats": 32,
}
TRAFFIC_FLAG_DEFAULTS = {
    "PADDLE_TRAFFIC_SEED": 0,
    "PADDLE_TRAFFIC_TIME_SCALE": 1.0,
    "PADDLE_TRAFFIC_CLIENTS": 4,
}

# the validation operating point: builtin specs at this rate/duration
# against the harness's default tiny serve shape (build_tiny_loop)
VALIDATE_SPECS = ("steady", "diurnal", "flash")
VALIDATE_RATE = 40.0
VALIDATE_DURATION_S = 10.0
VALIDATE_SEED = 7
VALIDATE_SERVE = {"max_active": 8, "kv_blocks": 48, "block_size": 8,
                  "max_seq_len": 48}

_HEADROOM_CAP = 99.0


def _load_evidence(path):
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# report (analytic, no hardware)
# ---------------------------------------------------------------------------

def report(evidence_path, device="tpu-v3", rate=None, duration_s=4.0,
           seed=None):
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.static import capacity as C
    from paddle_tpu.traffic import workload as W

    if seed is None:
        seed = int(_flags.flag("PADDLE_TRAFFIC_SEED"))
    ev = _load_evidence(evidence_path)
    prof = C.analytic_profile(ev, device=device)
    probe = W.builtin_spec("steady", rate=rate or 100.0,
                           duration_s=duration_s)
    events = W.schedule(probe, seed)
    import numpy as np
    mean_new = float(np.mean([e.new_tokens for e in events]))
    mean_prompt = float(np.mean([e.prompt.size for e in events]))
    slots = VALIDATE_SERVE["max_active"]
    knee = C.knee_rps(prof, slots=slots, mean_new=mean_new,
                      mean_prompt=mean_prompt)
    knee_rho = float(_flags.flag("FLAGS_capacity_knee_rho"))
    sweep = []
    for frac in (0.25, 0.5, 0.75, 0.9, 1.0, 1.1):
        r = max(0.5, knee * frac)
        spec = W.builtin_spec("steady", rate=r, duration_s=duration_s)
        p = C.predict(spec, seed, prof, slots=slots,
                      kv_blocks=VALIDATE_SERVE["kv_blocks"],
                      block_size=VALIDATE_SERVE["block_size"])
        p["over_knee"] = p["rho"] > knee_rho
        sweep.append(p)
    from paddle_tpu.text.models.gpt import GPT, GPTConfig
    net = GPT(GPTConfig.tiny())
    params, _ = net.functional_state()
    param_bytes = float(sum(int(np.prod(v.shape)) * 4
                            for v in params.values()))
    return {
        "tool": "capacity_plan",
        "device": device,
        "profile": prof.as_dict(),
        "knee_rps": round(knee, 3),
        "knee_rho": knee_rho,
        "sweep": sweep,
        "fleet": {"param_bytes": param_bytes,
                  "publish_wire_ms_x4_replicas":
                      round(C.publish_wire_ms(param_bytes, 4), 3)},
    }


# ---------------------------------------------------------------------------
# closed-loop validation (the proof)
# ---------------------------------------------------------------------------

def _err_pct(pred, obs):
    if pred in (None, 0) or obs is None:
        return None
    return round(100.0 * abs(obs - pred) / abs(pred), 1)


def validate(evidence_path=None, update_evidence=True):
    """Calibrate, predict each builtin spec, replay it through the real
    harness with the hub scoring, and hold the observation to the
    bands. Returns the capacity_validation section (ok=False if any
    metric lands outside its band)."""
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.static import capacity as C

    band50 = float(_flags.flag("FLAGS_capacity_p50_band_pct"))
    band99 = float(_flags.flag("FLAGS_capacity_p99_band_pct"))
    attempts = 0
    while True:
        attempts += 1
        prof = C.calibrate_cpu(VALIDATE_SERVE)
        section = _validate_once(prof, band50, band99)
        # CPU wall-clock drifts at minute scale with background load; a
        # profile calibrated in a slow window mispredicts a fast one.
        # One recalibrate-and-retry (fresh profile, fresh observations —
        # never fitted on the scored runs) absorbs that drift.
        if section["ok"] or attempts >= 2:
            break
    section["attempts"] = attempts
    section["profile"] = prof.as_dict()
    if update_evidence:
        path = evidence_path or os.path.join(REPO, "HLO_EVIDENCE.json")
        ev = _load_evidence(path)
        ev["graphs"]["capacity_validation"] = section
        with open(path, "w") as f:
            json.dump(ev, f, indent=1, sort_keys=True)
            f.write("\n")
    return section


def _validate_once(prof, band50, band99):
    from paddle_tpu.core import telemetry
    from paddle_tpu.static import capacity as C
    from paddle_tpu.traffic import harness as H
    from paddle_tpu.traffic import workload as W

    specs = {}
    worst = {"p50_class": 0.0, "p99_class": 0.0}
    ok = True
    for name in VALIDATE_SPECS:
        spec = W.builtin_spec(name, rate=VALIDATE_RATE,
                              duration_s=VALIDATE_DURATION_S)
        pred = C.predict(spec, VALIDATE_SEED, prof,
                         slots=VALIDATE_SERVE["max_active"],
                         kv_blocks=VALIDATE_SERVE["kv_blocks"],
                         block_size=VALIDATE_SERVE["block_size"])
        hub = telemetry.TelemetryHub(eval_s=5.0)
        try:
            obs = H.run_spec(spec, seed=VALIDATE_SEED,
                             serve_cfg=VALIDATE_SERVE, hub=hub)
        finally:
            hub.stop()
        errs = {
            "throughput_rps": _err_pct(pred["throughput_rps"],
                                       obs.throughput_rps),
            "ttft_p50": _err_pct(pred["ttft_ms"]["p50"],
                                 obs.ttft_ms.get("p50")),
            "ttft_p99": _err_pct(pred["ttft_ms"]["p99"],
                                 obs.ttft_ms.get("p99")),
            "token_p50": _err_pct(pred["token_ms"]["p50"],
                                  obs.token_ms.get("p50")),
            "token_p99": _err_pct(pred["token_ms"]["p99"],
                                  obs.token_ms.get("p99")),
        }
        spec_ok = (obs.scored_by == "hub" and obs.errors == 0
                   and obs.completed == obs.events)
        for key, e in errs.items():
            band = band99 if key.endswith("p99") else band50
            cls = "p99_class" if key.endswith("p99") else "p50_class"
            if e is None:
                spec_ok = False
                continue
            worst[cls] = max(worst[cls], e)
            if e > band:
                spec_ok = False
        ok = ok and spec_ok
        specs[name] = {
            "predicted": {k: pred[k] for k in
                          ("throughput_rps", "ttft_ms", "token_ms",
                           "offered_rps", "rho", "knee_rps",
                           "backpressure_ticks", "events")},
            "observed": {"throughput_rps": obs.throughput_rps,
                         "ttft_ms": obs.ttft_ms,
                         "token_ms": obs.token_ms,
                         "completed": obs.completed,
                         "errors": obs.errors,
                         "backpressure_waits": obs.backpressure_waits,
                         "scored_by": obs.scored_by,
                         "schedule_digest": obs.schedule_digest[:16]},
            "err_pct": errs,
            "ok": spec_ok,
        }
    headroom = min(
        band50 / max(worst["p50_class"], band50 / _HEADROOM_CAP),
        band99 / max(worst["p99_class"], band99 / _HEADROOM_CAP))
    return {
        "config": dict(VALIDATE_SERVE, rate_rps=VALIDATE_RATE,
                       duration_s=VALIDATE_DURATION_S,
                       seed=VALIDATE_SEED),
        "bands_pct": {"p50": band50, "p99": band99},
        "specs": specs,
        "worst_err_pct": {k: round(v, 1) for k, v in worst.items()},
        "band_headroom_x": round(headroom if ok else 0.0, 3),
        "ok": ok,
    }


# ---------------------------------------------------------------------------
# self-check (TOOL_CROSS_CHECKS)
# ---------------------------------------------------------------------------

def self_check():
    """Pin flag defaults <-> this tool's knobs <-> docs <-> bench <->
    committed evidence. Run by framework_lint.check_registered_tools."""
    problems = []
    from paddle_tpu.core import flags as _flags

    for table in (CAPACITY_FLAG_DEFAULTS, TRAFFIC_FLAG_DEFAULTS):
        for name, want in table.items():
            defn = _flags._DEFS.get(name)
            if defn is None:
                problems.append(
                    f"capacity_plan: flag {name} not defined in "
                    "core/flags.py")
            elif defn[1] != want:
                problems.append(
                    f"capacity_plan: default drift for {name} "
                    f"({defn[1]!r} != {want!r}) — update the table here "
                    "and docs/traffic_lab.md together")

    # the validation serve shape must be the harness's default tiny
    # shape — a drift here validates a loop nobody else runs
    import inspect

    from paddle_tpu.traffic import harness as H
    src = inspect.getsource(H.build_tiny_loop)
    for key, want in VALIDATE_SERVE.items():
        token = f'setdefault("{key}", {want})'
        if token not in src:
            problems.append(
                f"capacity_plan: VALIDATE_SERVE[{key!r}]={want} not the "
                f"harness build_tiny_loop default ({token} missing)")

    # docs: flag table rows + the terms the model is explained with
    doc = os.path.join(REPO, "docs", "traffic_lab.md")
    try:
        with open(doc) as f:
            text = f.read()
        for tok in ("capacity_plan", "--validate", "band_headroom_x",
                    "BENCH_MODE=traffic", "splitmix64",
                    *CAPACITY_FLAG_DEFAULTS, *TRAFFIC_FLAG_DEFAULTS):
            if tok not in text:
                problems.append(
                    f"capacity_plan: docs/traffic_lab.md lost {tok!r}")
    except OSError as e:
        problems.append(f"capacity_plan: cannot read {doc}: {e}")

    # bench env knobs: the traffic mode line reads these defaults
    import re
    bench_src = os.path.join(REPO, "bench.py")
    try:
        with open(bench_src) as f:
            btext = f.read()
        for env, want in (("BENCH_TRAFFIC_REQUESTS", 96),
                          ("BENCH_TRAFFIC_RATE", 40),
                          ("BENCH_TRAFFIC_NEW", 8),
                          ("BENCH_TRAFFIC_CLIENTS", 4)):
            pat = r'os\.environ\.get\("%s",\s*([0-9]+)\)' % env
            m = re.search(pat, btext)
            if not m:
                problems.append(
                    f"capacity_plan: bench.py lost the {env} knob")
            elif int(m.group(1)) != want:
                problems.append(
                    f"capacity_plan: bench.py {env} default "
                    f"{m.group(1)} != pinned {want}")
    except OSError as e:
        problems.append(f"capacity_plan: cannot read bench.py: {e}")

    # committed evidence: bands recorded there must be the flag bands,
    # and the perf floor gates headroom >= 1.0 (framework_lint)
    try:
        ev = _load_evidence(os.path.join(REPO, "HLO_EVIDENCE.json"))
        cv = ev.get("graphs", {}).get("capacity_validation")
        if cv is None:
            problems.append(
                "capacity_plan: HLO_EVIDENCE.json has no "
                "graphs.capacity_validation — run "
                "`python tools/capacity_plan.py --validate`")
        else:
            for key, flag in (("p50", "FLAGS_capacity_p50_band_pct"),
                              ("p99", "FLAGS_capacity_p99_band_pct")):
                want = CAPACITY_FLAG_DEFAULTS[flag]
                got = cv.get("bands_pct", {}).get(key)
                if got != want:
                    problems.append(
                        f"capacity_plan: evidence band {key}={got} != "
                        f"flag default {want} — re-run --validate")
            for name in VALIDATE_SPECS:
                if name not in cv.get("specs", {}):
                    problems.append(
                        f"capacity_plan: evidence missing validated "
                        f"spec {name!r}")
    except OSError as e:
        problems.append(f"capacity_plan: cannot read evidence: {e}")

    # shared estimator: this tool must not grow a private percentile
    with open(os.path.abspath(__file__)) as f:
        own = f.read()
    if ("def " + "percentile") in own:  # split so the pin can't self-match
        problems.append(
            "capacity_plan: grew a private percentile — use "
            "paddle_tpu.core.slo")
    return problems


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--validate", action="store_true")
    p.add_argument("--evidence",
                   default=os.path.join(REPO, "HLO_EVIDENCE.json"))
    p.add_argument("--device", default="tpu-v3")
    p.add_argument("--rate", type=float, default=None)
    p.add_argument("--no-update", action="store_true",
                   help="validate without rewriting HLO_EVIDENCE.json")
    p.add_argument("--self-check", "--self_check", action="store_true",
                   dest="self_check")
    args = p.parse_args(argv)
    if args.self_check:
        problems = self_check()
        for prob in problems:
            print(f"SELF-CHECK FAIL: {prob}")
        if problems:
            return 1
        print("capacity_plan self-check OK")
        return 0
    if args.validate:
        section = validate(args.evidence,
                           update_evidence=not args.no_update)
        print(json.dumps(section, indent=1, sort_keys=True))
        return 0 if section["ok"] else 1
    print(json.dumps(report(args.evidence, device=args.device,
                            rate=args.rate), indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
