"""Tunnel-independent HLO evidence for the Pallas kernel tier.

The only recorded MFU for this repo (BENCH_r03) was measured with both
Pallas kernels crashed out, and later bench rounds never ran — so "are the
kernels even in the compiled graphs, and what do they save?" had zero
recorded evidence. This tool produces that evidence WITHOUT a TPU or the
tunnel, the same optimize-inside-the-compiler-stack / verify-at-the-HLO
posture as EQuARX (arXiv:2506.17615):

1. AOT-lowers the bench graphs for a TPU target on any dev box
   (`jax.jit(f).trace(...).lower(lowering_platforms=("tpu",))` — Mosaic
   lowering needs no TPU, only *running* does; FLAGS_pallas_force_compile
   keeps the kernels out of interpreter mode off-TPU);
2. asserts the flash-attention / fused-CE / decode custom calls are
   present in the lowered StableHLO (`kernel_name = "..."` on the
   tpu_custom_call backend config);
3. records XLA cost-analysis FLOPs/bytes for each lowered step, plus an
   analytic per-step *attention* accounting for the decode step (the
   kernel's block-skip arithmetic vs the `_sdpa` full-cache stream —
   XLA's analysis can't see inside an opaque custom call, so the
   attention-specific comparison is derived from the kernel's own grid
   math and stated as such);
4. writes HLO_EVIDENCE.json.

Graphs lowered (configs mirror bench.py; framework_lint's
TOOL_CROSS_CHECKS runs self_check() so the two can't drift):

- bert_train_step   — BERT-base MLM fused-CE head, b32 s128 bf16
                      (fused-CE fwd+bwd custom calls; flash gated off by
                      FLAGS_flash_min_seq at s=128, recorded as such)
- gpt_longseq_train_step — GPT-124M s4096 causal train step (flash
                      fwd+bwd custom calls — the long-context regime the
                      kernel exists for)
- gpt_decode_step   — one GPT-124M StaticKVCache decode step at the
                      bench decode config (decode custom call), lowered
                      twice: kernel on vs FLAGS_use_decode_attention=0
                      (_sdpa full-cache path) for the cost comparison.

Usage:
  python tools/hlo_evidence.py [--out HLO_EVIDENCE.json] [--tiny]

--tiny swaps in toy configs (same graph structure, seconds instead of
minutes) — what tests/test_hlo_evidence.py runs in tier-1.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)
if REPO not in sys.path:  # `python tools/hlo_evidence.py` from anywhere
    sys.path.insert(0, REPO)

# ---- canonical bench configs (self_check() lints these against bench.py) --
BERT_CFG = {"batch": 32, "seq": 128, "dtype": "bfloat16"}
DECODE_CFG = {"batch": 8, "prompt": 32, "new": 128, "max_seq_len": 1024}
LONGSEQ_CFG = {"batch": 1, "seq": 4096}
# train-mode pipeline scan-megastep config. Deliberately an INDEPENDENT
# literal: tools/pipeline_lint.py (a TOOL_CROSS_CHECKS sibling) compares
# it against its own canonical copy and bench.py's env defaults, so a
# drift in any one of the three actually fires the lint.
PIPELINE_CFG = {"batch": 256, "hidden": 64, "steps": 200, "scan_k": 8,
                "inflight": 2}
TINY_PIPELINE_CFG = {"batch": 8, "hidden": 4, "steps": 8, "scan_k": 4,
                     "inflight": 2}

TINY_BERT_CFG = {"batch": 2, "seq": 16, "dtype": "float32"}
TINY_DECODE_CFG = {"batch": 2, "prompt": 4, "new": 8, "max_seq_len": 64}
TINY_LONGSEQ_CFG = {"batch": 1, "seq": 128}

# serving-tier fused decode step (inference/serving.py over the paged
# KV pool): slots/blocks mirror the FLAGS_serve_* defaults and bench.py's
# BENCH_SERVE_* env defaults (serve_load_test.self_check pins all three)
SERVE_CFG = {"slots": 64, "blocks": 512, "block_size": 128,
             "max_seq_len": 1024, "prompt": 32, "new": 64}
TINY_SERVE_CFG = {"slots": 2, "blocks": 6, "block_size": 16,
                  "max_seq_len": 64, "prompt": 4, "new": 8}

# kernel function names as they appear in `kernel_name = "..."` in the
# TPU-lowered StableHLO custom calls
KERNEL_NAMES = {
    "flash_attention": ["_flash_fwd_kernel", "_flash_bwd_dq_kernel",
                        "_flash_bwd_dkv_kernel"],
    "fused_ce": ["_ce_fwd_kernel", "_ce_bwd_dh_kernel",
                 "_ce_bwd_dw_kernel"],
    "decode_attention": ["_decode_attn_kernel"],
    "paged_decode_attention": ["_paged_decode_attn_kernel"],
}

_KERNEL_RE = re.compile(r'kernel_name = "([^"]+)"')


def _lower_tpu(fn, *args):
    import jax
    return jax.jit(fn).trace(*args).lower(lowering_platforms=("tpu",))


def _with_big_stack(thunk, stack_bytes=512 * 1024 * 1024):
    """Run thunk on a thread with a large stack: Mosaic kernel lowering
    recurses inside the already-deep train-step trace, exhausting both
    the 1000-frame Python limit and (if only the limit is raised) the
    default 8 MB C stack — a 20000-frame limit on the main thread
    segfaults instead of raising."""
    import threading
    result = {}

    def target():
        try:
            result["value"] = thunk()
        except BaseException as e:  # re-raised on the caller thread
            result["error"] = e

    old = threading.stack_size(stack_bytes)
    try:
        t = threading.Thread(target=target)
        t.start()
        t.join()
    finally:
        threading.stack_size(old)
    if "error" in result:
        raise result["error"]
    return result["value"]


def _evidence_from_lowered(lowered):
    text = lowered.as_text()
    calls = {}
    for name in _KERNEL_RE.findall(text):
        calls[name] = calls.get(name, 0) + 1
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        cost = {"flops": float(ca.get("flops", -1)),
                "bytes_accessed": float(ca.get("bytes accessed", -1))}
    except Exception as e:  # cost analysis is evidence, not a gate
        cost = {"error": f"{type(e).__name__}: {e}"}
    return calls, cost


def _pallas_counters():
    from paddle_tpu.core import monitor
    return {k: int(v) for k, v in monitor.stats("pallas.").items()}


def _reset_counters():
    from paddle_tpu.core import monitor
    monitor.reset(prefix="pallas.")


# --------------------------------------------------------------------------
# graph builders
# --------------------------------------------------------------------------

def lower_bert_train(cfg):
    """The bench_bert train step (fused-CE head), lowered for TPU."""
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, REPO)
    import bench
    from paddle_tpu.text.models.bert import BertConfig

    bert_cfg = BertConfig.bert_base() if cfg["seq"] >= 128 \
        else BertConfig.tiny()
    saved_dtype = bench.DTYPE
    try:
        bench.DTYPE = cfg["dtype"]
        step, params, slots, n_params = bench._build(bert_cfg,
                                                     use_fused_head=True)
    finally:
        bench.DTYPE = saved_dtype
    ids = jnp.zeros((cfg["batch"], cfg["seq"]), jnp.int32)
    labels = jnp.zeros((cfg["batch"], cfg["seq"]), jnp.int32)
    lr = jnp.asarray(1e-4, jnp.float32)
    t = jnp.asarray(1, jnp.int32)
    key = jax.random.PRNGKey(0)
    # step is already jitted; re-trace the underlying function for AOT
    fn = step.__wrapped__ if hasattr(step, "__wrapped__") else step
    return _lower_tpu(fn, params, slots, ids, labels, lr, t, key)


def lower_gpt_longseq_train(cfg):
    """The bench_longseq train step (flash attention + fused-CE head)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import optimizer as opt_mod
    from paddle_tpu.core import rng as _rng
    from paddle_tpu.core import tape as _tape
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.text.models.gpt import GPT, GPTConfig

    seq, batch = cfg["seq"], cfg["batch"]
    gcfg = GPTConfig(max_seq_len=seq, dropout=0.0) if seq >= 1024 else \
        GPTConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                  num_heads=2, intermediate_size=128, max_seq_len=seq,
                  dropout=0.0)
    paddle.seed(0)
    net = GPT(gcfg)
    net.train()
    optimizer = opt_mod.AdamW(learning_rate=1e-4,
                              parameters=net.parameters(),
                              multi_precision=True)
    params, buffers = net.functional_state()
    params = {k: v.astype(jnp.bfloat16) if v.dtype == jnp.float32 else v
              for k, v in params.items()}
    named = dict(net.named_parameters())
    optimizer._ensure_slots(params)
    slots = dict(optimizer._slots)
    meta = optimizer._param_meta(named)

    def train_step(params, slots, ids, labels, lr, t, key):
        with _rng.rng_state(key), _tape.no_grad():
            def loss_of(p):
                net.load_functional_state(p, buffers)
                loss = net(Tensor(ids, _internal=True),
                           labels=Tensor(labels, _internal=True))
                return loss._value.mean().astype(jnp.float32)

            loss, grads = jax.value_and_grad(loss_of)(params)
            new_params, new_slots = optimizer.apply_gradients_pure(
                params, grads, slots, lr, t, param_meta=meta)
        return loss, new_params, new_slots

    ids = jnp.zeros((batch, seq), jnp.int32)
    labels = jnp.zeros((batch, seq), jnp.int32)
    lr = jnp.asarray(1e-4, jnp.float32)
    t = jnp.asarray(1, jnp.int32)
    key = jax.random.PRNGKey(0)
    try:
        return _lower_tpu(train_step, params, slots, ids, labels, lr, t,
                          key)
    finally:
        net.load_functional_state(params, buffers)


def lower_gpt_decode_step(cfg, use_kernel):
    """ONE incremental decode step (s=1 against the StaticKVCache) at the
    bench decode config — the body the generation scan repeats `new`
    times. Lowered with the decode kernel on or forced to the jnp _sdpa
    full-cache path."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core import tape as _tape
    from paddle_tpu.text.models.gpt import GPT, GPTConfig

    b, total = cfg["batch"], cfg["max_seq_len"]
    gcfg = GPTConfig(max_seq_len=total) if total >= 1024 else \
        GPTConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                  num_heads=2, intermediate_size=128, max_seq_len=total)
    gcfg.dropout = 0.0
    paddle.seed(0)
    net = GPT(gcfg)
    net.eval()
    params, buffers = net.functional_state()
    caches = [blk.attn.gen_static_cache(b, total, jnp.float32)
              for blk in net.blocks]

    def decode_step(params, buffers, tok, caches, index):
        with _tape.no_grad():
            net.load_functional_state(params, buffers)
            logits, new_caches = net._forward_cached(tok, caches, index)
        return logits, new_caches

    tok = jnp.zeros((b, 1), jnp.int32)
    index = jnp.int32(cfg["prompt"])
    paddle.set_flags({"FLAGS_use_decode_attention": bool(use_kernel)})
    try:
        return _lower_tpu(decode_step, params, buffers, tok, caches, index)
    finally:
        paddle.set_flags({"FLAGS_use_decode_attention": True})
        net.load_functional_state(params, buffers)


def lower_serve_decode_step(cfg, use_kernel=True):
    """ONE fused continuous-batching decode step (inference/serving.py):
    every active slot advances one token against the shared paged KV
    arena through the block-table kernel. Lowers the PRODUCTION step
    builder (serving.build_decode_step), so the evidence cannot drift
    from the serve loop. Arenas/tables are passed as ShapeDtypeStructs —
    lowering needs avals, not the multi-GB buffers."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import build_decode_step
    from paddle_tpu.text.models.gpt import GPT, GPTConfig

    A, bs = cfg["slots"], cfg["block_size"]
    total = cfg["max_seq_len"]
    nb = cfg["blocks"]
    mb = -(-total // bs)
    gcfg = GPTConfig(max_seq_len=total) if total >= 1024 else \
        GPTConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                  num_heads=2, intermediate_size=128, max_seq_len=total)
    gcfg.dropout = 0.0
    paddle.seed(0)
    net = GPT(gcfg)
    net.eval()
    params, buffers = net.functional_state()
    heads = gcfg.num_heads
    hd = gcfg.hidden_size // heads
    arena = jax.ShapeDtypeStruct((nb + 1, heads, bs, hd), jnp.float32)
    arenas = [(arena, arena) for _ in range(gcfg.num_layers)]
    bt = jax.ShapeDtypeStruct((A, mb), jnp.int32)
    lens = jax.ShapeDtypeStruct((A,), jnp.int32)
    toks = jax.ShapeDtypeStruct((A,), jnp.int32)
    keys = jax.ShapeDtypeStruct((A, 2), jnp.uint32)
    step = build_decode_step(net, temperature=0.0, top_k=None)
    paddle.set_flags({"FLAGS_use_paged_attention": bool(use_kernel)})
    try:
        return _lower_tpu(step, params, buffers, arenas, bt, lens, toks,
                          keys)
    finally:
        paddle.set_flags({"FLAGS_use_paged_attention": True})
        net.load_functional_state(params, buffers)


def serve_decode_bytes_model(cfg, heads, head_dim, layers,
                             dtype_bytes=4):
    """Per-step attention KV-read accounting for the PAGED kernel: the
    clamped block-table index map DMAs ceil(live/bs) physical blocks per
    slot, so per-step KV bytes are a function of each request's LIVE
    length — the full-cache jnp path (and a StaticKVCache sized to
    max_seq_len) streams max_seq_len columns per slot regardless. Stated
    at several fill levels to show the scaling law, plus the reduction
    at the serve config's typical fill (prompt + new/2)."""
    A, bs, L = cfg["slots"], cfg["block_size"], cfg["max_seq_len"]
    nb_req = -(-L // bs)

    def kv_bytes(cols):
        return 2.0 * A * heads * cols * head_dim * dtype_bytes * layers

    fills = sorted({1, max(nb_req // 4, 1), max(nb_req // 2, 1), nb_req})
    scaling = [{"live_blocks": n, "live_cols": n * bs,
                "kv_bytes_per_step": kv_bytes(n * bs)} for n in fills]
    typical = min(cfg["prompt"] + cfg["new"] // 2, L)
    typ_cols = min(-(-typical // bs), nb_req) * bs
    return {
        "model": "per-step KV reads: paged kernel = ceil(live/bs)*bs "
                 "cols per slot (clamped block-table index map skips "
                 "dead-block DMA); full-cache path = max_seq_len cols "
                 "per slot at any fill",
        "block_size": bs,
        "slots": A,
        "bytes_by_live_blocks": scaling,
        "full_cache_bytes_per_step": kv_bytes(L),
        "typical_fill_tokens": typical,
        "typical_live_cols": typ_cols,
        "typical_kv_bytes_per_step": kv_bytes(typ_cols),
        "bytes_reduction_x_at_typical_fill":
            round(kv_bytes(L) / kv_bytes(typ_cols), 2),
    }


def lower_pipeline_scan(cfg):
    """The scan-fused K-step executor megastep
    (static/pipeline_runner.py): lax.scan over the compiled train step.
    Returns (lowered, info) where info proves the fusion at the jaxpr
    level — ONE scan primitive of length K, i.e. one dispatched
    computation where the serial loop dispatches K."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn, ops, optimizer, static
    from paddle_tpu.core import rng as _rng

    batch, hidden, k = cfg["batch"], cfg["hidden"], cfg["scan_k"]
    paddle.enable_static()
    try:
        paddle.seed(0)
        prog = static.Program("hlo_pipeline")
        with static.program_guard(prog):
            x = static.data("x", [-1, hidden], "float32")
            y = static.data("y", [-1, 1], "float32")
            h = ops.relu(nn.Linear(hidden, hidden)(x))
            loss = ops.mse_loss(nn.Linear(hidden, 1)(h), y)
            optimizer.Adam(learning_rate=1e-3).minimize(loss)
        exe = static.Executor()
        feed = {"x": jnp.zeros((batch, hidden), jnp.float32),
                "y": jnp.zeros((batch, 1), jnp.float32)}
        entry = exe._prepare(prog, feed, [loss], False)
        # the PRODUCTION scan body, not a copy — evidence can't drift
        from paddle_tpu.static.executor import make_scan_step
        scan_fn = make_scan_step(entry.step_fn)

        scope = static.global_scope()
        scope_vals = {n: scope.get(n) for n in entry.read_names}
        entry.opt._ensure_slots(
            {n: scope_vals[n] for n in entry.opt_pnames})
        slots = {n: entry.opt._slots[n] for n in entry.opt_pnames}
        feeds = tuple(jnp.zeros((k,) + tuple(feed[n].shape), jnp.float32)
                      for n in entry.feed_names)
        lrs = jnp.full((k,), 1e-3, jnp.float32)
        ts = jnp.arange(1, k + 1, dtype=jnp.int32)
        keys = jnp.stack([_rng.next_key() for _ in range(k)])

        jaxpr = jax.make_jaxpr(scan_fn)(feeds, scope_vals, slots, lrs,
                                        ts, keys)
        scan_eqns = [e for e in jaxpr.jaxpr.eqns
                     if e.primitive.name == "scan"]
        info = {
            "scan_eqns": len(scan_eqns),
            "scan_length": int(scan_eqns[0].params["length"])
            if scan_eqns else 0,
            "k": k,
        }
        lowered = _lower_tpu(scan_fn, feeds, scope_vals, slots, lrs, ts,
                             keys)
        info["while_ops"] = lowered.as_text().count("stablehlo.while")
        return lowered, info
    finally:
        paddle.disable_static()


# --------------------------------------------------------------------------
# analytic decode-attention accounting
# --------------------------------------------------------------------------

def decode_attention_model(cfg, heads, head_dim, layers, bk,
                           dtype_bytes=4):
    """Per-step attention FLOPs/HBM-bytes, averaged over the `new`
    generated tokens: the _sdpa path streams all max_seq_len padded K/V
    columns every step; the kernel reads ceil(live/bk) blocks (clamped
    index map skips dead-block DMA) and computes only those columns.
    FLOPs are per live query row (both paths pad the single decode row to
    the 8-sublane tile in hardware); bytes count the K+V cache reads that
    dominate decode HBM traffic."""
    L, prompt, new = cfg["max_seq_len"], cfg["prompt"], cfg["new"]
    b = cfg["batch"]
    nk = -(-L // bk)

    def per_step(cols):
        return {
            "flops": 4.0 * b * heads * cols * head_dim * layers,
            "hbm_bytes": 2.0 * b * heads * cols * head_dim * dtype_bytes
                         * layers,
        }

    kern_cols = [min(-(-(prompt + i + 1) // bk), nk) * bk
                 for i in range(new)]
    avg_cols = sum(kern_cols) / max(len(kern_cols), 1)
    sdpa = per_step(L)
    kern = per_step(avg_cols)
    return {
        "model": "attention cols per decode step: sdpa=max_seq_len; "
                 "kernel=ceil((prompt+i+1)/bk)*bk averaged over i<new; "
                 "flops=4*b*h*cols*d per layer (QK^T + PV), "
                 "hbm_bytes=K+V cache reads",
        "block_k": bk,
        "avg_live_cols_kernel": round(avg_cols, 1),
        "sdpa_full_cache": sdpa,
        "decode_kernel": kern,
        "flops_reduction_x": round(sdpa["flops"] / kern["flops"], 2),
        "bytes_reduction_x": round(sdpa["hbm_bytes"] / kern["hbm_bytes"],
                                   2),
    }


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

def run(out_path="HLO_EVIDENCE.json", tiny=False):
    import paddle_tpu as paddle
    from paddle_tpu.core import flags as _flags

    # Mosaic kernel lowering runs nested inside the (already deep)
    # train-step trace stack; the default 1000-frame limit exhausts there
    if sys.getrecursionlimit() < 20000:
        sys.setrecursionlimit(20000)

    bert_cfg = TINY_BERT_CFG if tiny else BERT_CFG
    decode_cfg = TINY_DECODE_CFG if tiny else DECODE_CFG
    longseq_cfg = TINY_LONGSEQ_CFG if tiny else LONGSEQ_CFG

    saved = {k: _flags.flag(k) for k in
             ("FLAGS_pallas_force_compile", "FLAGS_pallas_autotune",
              "FLAGS_use_flash_attention", "FLAGS_use_fused_ce",
              "FLAGS_use_decode_attention", "FLAGS_flash_min_seq",
              "FLAGS_pallas_strict")}
    paddle.set_flags({
        "FLAGS_pallas_force_compile": True,   # Mosaic lowering off-TPU
        "FLAGS_pallas_autotune": False,       # lowering must not measure
        "FLAGS_use_flash_attention": True,
        "FLAGS_use_fused_ce": True,
        "FLAGS_use_decode_attention": True,
        # evidence must fail loudly, not silently lower the fallback graph
        "FLAGS_pallas_strict": True,
    })
    if tiny:
        paddle.set_flags({"FLAGS_flash_min_seq": 64})

    report = {"tool": "tools/hlo_evidence.py", "tiny": bool(tiny),
              "platform": "tpu", "graphs": {}, "assertions": []}

    def record(name, lowered, config, extra=None):
        calls, cost = _evidence_from_lowered(lowered)
        entry = {"config": config, "custom_calls": calls,
                 "cost_analysis": cost,
                 "pallas_counters": _pallas_counters()}
        entry.update(extra or {})
        report["graphs"][name] = entry
        return entry

    def check(name, ok, detail=""):
        report["assertions"].append(
            {"name": name, "ok": bool(ok), "detail": detail})

    try:
        # ---- BERT train step (fused CE) -------------------------------
        _reset_counters()
        bert = record("bert_train_step",
                      _with_big_stack(lambda: lower_bert_train(bert_cfg)),
                      bert_cfg)
        for kn in KERNEL_NAMES["fused_ce"]:
            check(f"bert_train_step has {kn}",
                  bert["custom_calls"].get(kn, 0) > 0)

        # ---- GPT long-seq train step (flash attention) ----------------
        _reset_counters()
        ls = record("gpt_longseq_train_step",
                    _with_big_stack(
                        lambda: lower_gpt_longseq_train(longseq_cfg)),
                    longseq_cfg)
        for kn in KERNEL_NAMES["flash_attention"]:
            check(f"gpt_longseq_train_step has {kn}",
                  ls["custom_calls"].get(kn, 0) > 0)

        # ---- GPT decode step: kernel vs _sdpa full cache --------------
        _reset_counters()
        dec = record("gpt_decode_step",
                     _with_big_stack(lambda: lower_gpt_decode_step(
                         decode_cfg, use_kernel=True)),
                     decode_cfg)
        kn = KERNEL_NAMES["decode_attention"][0]
        check(f"gpt_decode_step has {kn}",
              dec["custom_calls"].get(kn, 0) > 0)

        _reset_counters()
        sdpa_lowered = _with_big_stack(
            lambda: lower_gpt_decode_step(decode_cfg, use_kernel=False))
        sdpa_calls, sdpa_cost = _evidence_from_lowered(sdpa_lowered)
        dec["sdpa_custom_calls"] = sdpa_calls
        dec["sdpa_cost_analysis"] = sdpa_cost
        check("sdpa decode graph has no decode kernel",
              sdpa_calls.get(kn, 0) == 0)

        heads = 12 if not tiny else 2
        head_dim = 64 if not tiny else 32
        layers = 12 if not tiny else 2
        from paddle_tpu.core import flags as _f
        bk = int(_f.flag("FLAGS_decode_block_k") or 0) or \
            min(128, decode_cfg["max_seq_len"])
        dec["attention_per_step"] = decode_attention_model(
            decode_cfg, heads, head_dim, layers, bk)
        # the >=2x acceptance bar is about the DEFAULT bench config; its
        # model is pure arithmetic, so evaluate it even in --tiny (a
        # 64-slot tiny cache is a single block — no reduction to show)
        full = dec["attention_per_step"] if not tiny else \
            decode_attention_model(
                DECODE_CFG, 12, 64, 12,
                int(_f.flag("FLAGS_decode_block_k") or 0)
                or min(128, DECODE_CFG["max_seq_len"]))
        if tiny:
            dec["attention_per_step_full_config"] = full
        check("decode attention flops reduced >= 2x (default bench cfg)",
              full["flops_reduction_x"] >= 2.0,
              f"{full['flops_reduction_x']}x")
        check("decode attention bytes reduced >= 2x (default bench cfg)",
              full["bytes_reduction_x"] >= 2.0,
              f"{full['bytes_reduction_x']}x")

        # ---- serving: fused continuous-batching paged decode step -----
        scfg = TINY_SERVE_CFG if tiny else SERVE_CFG
        _reset_counters()
        srv = record("serve_decode",
                     _with_big_stack(
                         lambda: lower_serve_decode_step(scfg)),
                     scfg)
        pkn = KERNEL_NAMES["paged_decode_attention"][0]
        check(f"serve_decode has {pkn}",
              srv["custom_calls"].get(pkn, 0) > 0)
        s_heads = 12 if not tiny else 2
        s_hd = 64 if not tiny else 32
        s_layers = 12 if not tiny else 2
        srv["kv_bytes_per_step"] = serve_decode_bytes_model(
            scfg, s_heads, s_hd, s_layers)
        # the scaling bar is about the DEFAULT serve config; its model is
        # pure arithmetic, so evaluate it even in --tiny
        full_srv = srv["kv_bytes_per_step"] if not tiny else \
            serve_decode_bytes_model(SERVE_CFG, 12, 64, 12)
        if tiny:
            srv["kv_bytes_per_step_full_config"] = full_srv
        sc = full_srv["bytes_by_live_blocks"]
        linear = all(
            abs(e["kv_bytes_per_step"]
                - sc[0]["kv_bytes_per_step"] * e["live_blocks"]) < 1e-6
            for e in sc)
        check("serve decode per-step KV bytes scale with live blocks "
              "(default serve cfg)", linear,
              f"{[e['live_blocks'] for e in sc]} blocks -> "
              f"{[e['kv_bytes_per_step'] for e in sc]} bytes")
        check("serve decode KV bytes reduced >= 2x vs max_seq_len at "
              "typical fill (default serve cfg)",
              full_srv["bytes_reduction_x_at_typical_fill"] >= 2.0,
              f"{full_srv['bytes_reduction_x_at_typical_fill']}x")

        # ---- scan-fused executor megastep (async pipelined hot loop) --
        _reset_counters()  # the serve lowering's hits are not this graph's
        pcfg = TINY_PIPELINE_CFG if tiny else PIPELINE_CFG
        lowered, info = _with_big_stack(
            lambda: lower_pipeline_scan(pcfg))
        pipe = record("pipeline_scan_megastep", lowered, pcfg)
        pipe["scan"] = info
        # the serial loop dispatches K XLA executions per K steps; the
        # scan-fused megastep dispatches ONE (the scan body runs as K
        # iterations of a single compiled loop) — the dispatch model is
        # arithmetic, so state the DEFAULT bench config's number even in
        # --tiny
        k_full = PIPELINE_CFG["scan_k"]
        pipe["dispatch_model"] = {
            "model": "host dispatches per K train steps: serial "
                     "Executor.run = K; scan-fused megastep = 1 "
                     "(lax.scan compiles the step into one while loop)",
            "serial_dispatches_per_k": k_full,
            "scan_dispatches_per_k": 1,
            "dispatch_reduction_x": float(k_full),
        }
        check("scan-fused K-step lowers to ONE scan of K iterations",
              info["scan_eqns"] == 1
              and info["scan_length"] == pcfg["scan_k"],
              f"{info['scan_eqns']} scan eqn(s), length "
              f"{info['scan_length']} (want {pcfg['scan_k']})")
        check("scan-fused megastep lowers to a single fused loop "
              "computation", info["while_ops"] >= 1,
              f"{info['while_ops']} while op(s)")
        check("dispatches per K steps reduced >= 2x (default bench cfg)",
              k_full >= 2, f"{k_full}x")

        # ---- two-tier topology: hierarchical dp gradient sync ---------
        # analytic wire model (SpmdReport.hierarchical_sync over the
        # spmd_plan topology golden: outer 'pod' axis on the slow DCN
        # tier, inner 'dp' on ICI). Pure ring arithmetic over the planned
        # layout's gradient bytes — no lowering involved, so the DEFAULT
        # golden prices even in --tiny.
        if TOOLS_DIR not in sys.path:
            sys.path.insert(0, TOOLS_DIR)
        import importlib
        spmd_plan = importlib.import_module("spmd_plan")
        tplan, _, _ = spmd_plan.build_topology_plan()
        gs = dict(tplan.grad_sync or {})
        gs["model"] = (
            "per-device ring all-reduce of B grad bytes over s devices "
            "moves 2*B*(s-1)/s; flat crosses DCN with the full B while "
            "hierarchical reduce-scatters intra-pod first and ships only "
            "the B/n shard inter-pod (localsgd divides the whole sync "
            "by k steps); cost_us = bytes / (link_gbps * 1e3)")
        report["graphs"]["hierarchical_sync"] = {
            "config": {
                "mesh": {ax: ({"size": n, **tplan.mesh_tiers[ax]}
                              if ax in tplan.mesh_tiers else n)
                         for ax, n in tplan.mesh_axes.items()},
                "workload": "spmd_plan topology golden GPT "
                            "(build_topology_plan defaults)",
            },
            "wire_model": gs,
        }
        n_xtier = sum(d.code == "cross-tier"
                      for d in tplan.report.diagnostics)
        check("topology-planned golden keeps model parallelism "
              "intra-pod (zero cross-tier diagnostics)",
              n_xtier == 0 and not tplan.report.diagnostics,
              f"{len(tplan.report.diagnostics)} diagnostic(s), "
              f"{n_xtier} cross-tier")
        check("hierarchical dp sync cuts inter-pod wire bytes >= 2x "
              "vs flat", gs.get("inter_pod_reduction_x", 0.0) >= 2.0,
              f"{gs.get('inter_pod_reduction_x')}x, recommendation="
              f"{gs.get('recommendation')}")
    finally:
        paddle.set_flags({k: v for k, v in saved.items()})

    report["ok"] = all(a["ok"] for a in report["assertions"])
    # sections other tools own ride through a regeneration: the capacity
    # validation record (tools/capacity_plan.py --validate) is gated by
    # check_perf_floors, so dropping it here would fail the build
    try:
        with open(out_path) as f:
            prior = json.load(f)
        for key in ("capacity_validation",):
            if key in prior.get("graphs", {}):
                report["graphs"].setdefault(key, prior["graphs"][key])
    except (OSError, ValueError):
        pass
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
    return report


# --------------------------------------------------------------------------
# framework_lint cross-check (TOOL_CROSS_CHECKS)
# --------------------------------------------------------------------------

def _bench_source():
    with open(os.path.join(REPO, "bench.py")) as f:
        return f.read()


def self_check():
    """Fast config-drift + gate lint (no lowering): the tool's canonical
    configs must match bench.py's env-var defaults, and the kernel
    eligibility gates must pass for every bench shape — otherwise the
    'evidence' would be for graphs the bench never runs."""
    problems = []
    src = _bench_source()

    def bench_default(env, want):
        m = re.search(r'os\.environ\.get\("%s",\s*([0-9]+)\)' % env, src)
        if not m:
            problems.append(f"hlo_evidence: bench.py no longer reads {env}")
            return
        if int(m.group(1)) != want:
            problems.append(
                f"hlo_evidence: bench.py default {env}={m.group(1)} but "
                f"tools/hlo_evidence.py assumes {want} — update the "
                "canonical config")

    bench_default("BENCH_BATCH", BERT_CFG["batch"])
    bench_default("BENCH_SEQ", BERT_CFG["seq"])
    bench_default("BENCH_DECODE_BATCH", DECODE_CFG["batch"])
    bench_default("BENCH_DECODE_PROMPT", DECODE_CFG["prompt"])
    bench_default("BENCH_DECODE_NEW", DECODE_CFG["new"])
    bench_default("BENCH_LONGSEQ", LONGSEQ_CFG["seq"])
    bench_default("BENCH_SERVE_SLOTS", SERVE_CFG["slots"])
    bench_default("BENCH_SERVE_BLOCKS", SERVE_CFG["blocks"])
    bench_default("BENCH_SERVE_PROMPT", SERVE_CFG["prompt"])
    bench_default("BENCH_SERVE_NEW", SERVE_CFG["new"])
    if f"max_seq_len={DECODE_CFG['max_seq_len']}" not in src:
        problems.append(
            "hlo_evidence: bench.py decode config no longer uses "
            f"max_seq_len={DECODE_CFG['max_seq_len']}")

    # eligibility gates for the bench shapes (pure static predicates).
    # importlib by dotted path: the package __init__ shadows the
    # decode_attention/flash_attention module names with the functions
    try:
        import importlib
        fc = importlib.import_module("paddle_tpu.ops.pallas.fused_ce")
        fa = importlib.import_module(
            "paddle_tpu.ops.pallas.flash_attention")
        da = importlib.import_module(
            "paddle_tpu.ops.pallas.decode_attention")
    except Exception as e:
        return problems + [f"hlo_evidence: kernel imports failed: {e!r}"]

    n_tok = BERT_CFG["batch"] * BERT_CFG["seq"]
    if not fc.supported(n_tok, 768, 30522):
        problems.append("hlo_evidence: fused_ce gate rejects the BERT "
                        f"bench shape (n={n_tok}, H=768, V=30522)")
    s = LONGSEQ_CFG["seq"]
    if not fa.supported((LONGSEQ_CFG["batch"], 12, s, 64),
                        (LONGSEQ_CFG["batch"], 12, s, 64),
                        (LONGSEQ_CFG["batch"], 12, s, 64)):
        problems.append("hlo_evidence: flash gate rejects the longseq "
                        f"bench shape (s={s})")
    b, L = DECODE_CFG["batch"], DECODE_CFG["max_seq_len"]
    if not da.supported((b, 12, 1, 64), (b, 12, L, 64)):
        problems.append("hlo_evidence: decode gate rejects the decode "
                        f"bench shape (b={b}, L={L})")
    sA, sbs, snb = SERVE_CFG["slots"], SERVE_CFG["block_size"], \
        SERVE_CFG["blocks"]
    if not da.paged_supported((sA, 12, 1, 64), (snb + 1, 12, sbs, 64)):
        problems.append("hlo_evidence: paged-decode gate rejects the "
                        f"serve config (slots={sA}, bs={sbs})")
    n_tok_gpt = LONGSEQ_CFG["batch"] * s
    if not fc.supported(n_tok_gpt, 768, 50304):
        problems.append("hlo_evidence: fused_ce gate rejects the GPT "
                        f"longseq loss shape (n={n_tok_gpt})")
    return problems


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--out", default=os.path.join(REPO, "HLO_EVIDENCE.json"))
    p.add_argument("--tiny", action="store_true",
                   help="toy configs (fast; used by tier-1 tests)")
    p.add_argument("--self-check", action="store_true",
                   help="config-drift lint only (what framework_lint runs)")
    args = p.parse_args(argv)
    if args.self_check:
        problems = self_check()
        for prob in problems:
            print(prob)
        print("hlo_evidence self-check:",
              "clean" if not problems else f"{len(problems)} problem(s)")
        return 1 if problems else 0
    report = run(args.out, tiny=args.tiny)
    for a in report["assertions"]:
        print(("PASS " if a["ok"] else "FAIL ") + a["name"]
              + (f" ({a['detail']})" if a["detail"] else ""))
    print(f"wrote {args.out}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
