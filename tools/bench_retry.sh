#!/bin/bash
# Round-5 bench retry loop: BENCH_r04 failed only because the TPU tunnel was
# unreachable, so this keeps attempting the full bench until it lands
# (VERDICT r04 next-round item 1). Run under tmux; writes
# BENCH_r05_local.json on success.
cd /root/repo || exit 1
for i in $(seq 1 200); do
  echo "=== attempt $i $(date) ===" >> /root/repo/bench_r05_log.txt
  BENCH_INIT_TIMEOUT=180 BENCH_MODE=all timeout 3600 \
    python bench.py > /root/repo/BENCH_r05_local.json.tmp \
    2>> /root/repo/bench_r05_log.txt
  rc=$?
  if [ $rc -eq 0 ] && grep -q '"mfu"' /root/repo/BENCH_r05_local.json.tmp; then
    mv /root/repo/BENCH_r05_local.json.tmp /root/repo/BENCH_r05_local.json
    echo "SUCCESS $(date)" >> /root/repo/bench_r05_log.txt
    exit 0
  fi
  echo "attempt $i rc=$rc; sleeping 600s" >> /root/repo/bench_r05_log.txt
  sleep 600
done
