"""Audit OP_REGISTRY + the public API surface against the reference's
operator registrations (VERDICT r04 item 3).

Extracts every REGISTER_OPERATOR / REGISTER_OP_WITHOUT_GRADIENT first
argument from /root/reference/paddle/fluid/operators/**, classifies each
family as covered / waived / missing, and writes tools/op_coverage.md.

Coverage test: a registration counts as covered when (a) its name (or a
known alias) is in OP_REGISTRY, (b) it is reachable as a public paddle_tpu
API (ops.*, nn.functional.*, paddle.*), or (c) it is an infrastructure op
whose job the TPU runtime design makes moot (feed/fetch, memcpy, NCCL
init, …) — those are waived with a reason, not counted as implemented.

Run: python tools/op_coverage.py   (writes the md, prints a summary line;
exits nonzero if non-waived coverage < 90%).
"""
from __future__ import annotations

import os
import re
import sys
from collections import OrderedDict

REF = "/root/reference/paddle/fluid/operators"
OUT = os.path.join(os.path.dirname(__file__), "op_coverage.md")

# -- 1. harvest reference registrations -------------------------------------

_REG_RE = re.compile(
    r"REGISTER_OPERATOR(?:_WITH_GRADIENT)?\s*\(\s*([A-Za-z0-9_]+)\s*,")
_REG_NOGRAD_RE = re.compile(
    r"REGISTER_OP_WITHOUT_GRADIENT\s*\(\s*([A-Za-z0-9_]+)\s*,")


def harvest():
    regs = {}
    for root, _dirs, files in os.walk(REF):
        for f in files:
            if not f.endswith((".cc", ".cu")):
                continue
            p = os.path.join(root, f)
            try:
                text = open(p, encoding="utf-8", errors="ignore").read()
            except OSError:
                continue
            rel = os.path.relpath(p, REF)
            for m in _REG_RE.finditer(text):
                regs.setdefault(m.group(1), rel)
            for m in _REG_NOGRAD_RE.finditer(text):
                regs.setdefault(m.group(1), rel)
    return OrderedDict(sorted(regs.items()))


# -- 2. the implementation surface ------------------------------------------

def implementation_surface():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")
    import paddle_tpu as paddle
    from paddle_tpu import nn, ops
    from paddle_tpu.ops import OP_REGISTRY

    names = set(OP_REGISTRY)
    mods = [ops, nn.functional, paddle]
    for sub in ("linalg", "sparse", "signal", "fft", "distributed", "amp",
                "metric", "optimizer", "incubate"):
        try:
            mods.append(getattr(paddle, sub))
        except AttributeError:
            pass
    try:
        mods.append(paddle.vision.ops)
    except AttributeError:
        pass
    for mod in mods:
        names |= {n for n in dir(mod) if not n.startswith("_")}
        names |= {n for n in getattr(mod, "__all__", ()) or ()}
    # layer classes answer for their op families (conv2d <- nn.Conv2D …)
    names |= {n.lower() for n in dir(nn) if not n.startswith("_")}
    names |= {n.lower() for n in dir(paddle.optimizer)
              if not n.startswith("_")}
    try:
        from paddle_tpu import fluid
        names |= {n for n in dir(fluid.layers) if not n.startswith("_")}
    except Exception:
        pass
    # the generated API surface (lazy __getattr__ entries dir() misses)
    spec = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "API.spec")
    if os.path.exists(spec):
        for line in open(spec):
            sym = line.split()[0] if line.strip() else ""
            if sym.startswith("paddle_tpu."):
                leaf = sym.rsplit(".", 1)[-1]
                names.add(leaf)
                names.add(leaf.lower())
    return names


# grad registrations and internal mechanics that exist only because of the
# reference's op-per-kernel architecture; autodiff here is jax.vjp and the
# runtime is XLA, so these are satisfied by construction, not by an op.
_WAIVE_PATTERNS = [
    (re.compile(r".*_grad(_grad)?(2)?$"),
     "grad op: autodiff is jax.vjp per op (core/tape.py), grad kernels "
     "are not separate registrations"),
    (re.compile(r"^(feed|fetch)$"),
     "executor IO: the whole Program compiles to one jitted function; "
     "feed/fetch are its arguments/results (static/executor.py)"),
    (re.compile(r"^(memcpy|fill_memory)"),
     "device copies are XLA/PJRT transfers"),
    (re.compile(r"^c_(gen_nccl_id|comm_init|comm_init_all|sync_calc_stream"
                r"|sync_comm_stream|wait_calc|wait_comm)$"),
     "NCCL bootstrap/stream-sync: mesh axes + XLA collectives need no "
     "runtime comm registry (distributed/mesh.py; SURVEY §2.3)"),
    (re.compile(r"^(gen_nccl_id|nccl_init|ncclAllReduce|ncclInit)"),
     "NCCL runtime: replaced by jax.distributed + mesh axes"),
    (re.compile(r"^(create_.*reader|read|read_from_array|py_reader"
                r"|double_buffer)"),
     "reader ops: io/dataloader.py host pipeline feeds arrays directly"),
    (re.compile(r"^(go|channel_send|channel_recv|channel_close"
                r"|channel_create|select)$"),
     "CSP/goroutine experiment ops (removed upstream too)"),
    (re.compile(r"^(listen_and_serv|send|recv|send_barrier|recv_save"
                r"|fetch_barrier|send_and_recv|heter_listen_and_serv)$"),
     "PS v1 RPC ops: distributed/ps/{rpc,server,client}.py is the "
     "transport (real TCP RPC), not graph ops"),
    (re.compile(r"^(distributed_lookup_table|lookup_sparse_table"
                r"|distributed_push_sparse)"),
     "PS sparse access: ps/table.py pull/push API"),
    (re.compile(r"^(checkpoint_notify|pull_box_sparse|push_box_sparse"
                r"|pull_box_extended_sparse|push_box_extended_sparse"
                r"|pull_gpups_sparse|push_gpups_sparse|pull_sparse"
                r"|push_sparse|pull_sparse_v2|push_sparse_v2"
                r"|pyramid_hash)$"),
     "BoxPS/PSLib binary-blob integrations (reference links vendor "
     "binaries; out of scope per SURVEY §2.2 HeterPS row)"),
    (re.compile(r"^(enqueue|dequeue)$"),
     "trainer channel mechanics: fleet_dataset.py channels"),
    (re.compile(r"^(conditional_block|while|recurrent|increment_by"
                r"|get_places|parallel_do)$"),
     "control-flow blocks: static/control_flow.py cond/while lower to "
     "lax.cond/while_loop HLO (sub-block ops, jit/dy2static.py)"),
    (re.compile(r"^(fused_|fusion_)"),
     "fusion ops: XLA fuses automatically; the profitable exceptions "
     "(attention, CE) are Pallas kernels (ops/pallas/)"),
    (re.compile(r"^(cudnn_|mkldnn_|ngraph_)"),
     "vendor-library binding variants: XLA owns kernel selection"),
    (re.compile(r"^(quantize|dequantize|requantize)$"),
     "mkldnn int8 pipeline ops: quantization/ QAT + PTQ is the "
     "TPU-native path"),
    (re.compile(r"^(faster_tokenizer|mars|resnet_unit|resnet_basic_block"
                r"|sparse_attention)$"),
     "external-lib experiments not in this snapshot's API surface"),
    (re.compile(r"^(dgc|dgc_momentum|dgc_clip_by_norm)$"),
     "deep gradient compression: deliberately inert under SPMD "
     "(fleet/strategy.py documents why; VERDICT accepts)"),
    (re.compile(r"^(ref_by_trainer_id|split_byref|split_ids|merge_ids"
                r"|prefetch|push_dense|queue_generator|fake_init"
                r"|fl_listen_and_serv|sparse_tensor_load|delete_var)$"),
     "PS/trainer plumbing: no program splitting or var lifecycle ops "
     "in SPMD (ps/ package + XLA buffer lifetime)"),
    (re.compile(r"^(array_to_lod_tensor|lod_tensor_to_array"
                r"|lod_array_length|max_sequence_len|shrink_rnn_memory"
                r"|rnn_memory_helper|reorder_lod_tensor_by_rank"
                r"|write_to_array|read_from_array|tensor_array_to_tensor"
                r"|merge_lod_tensor_infer|select_input|select_output"
                r"|conditional_block_infer)$"),
     "ProgramDesc while/RNN TensorArray plumbing: lax.scan/while own the "
     "loop state (static/control_flow.py); LoDTensorArray is a host "
     "container"),
    (re.compile(r"^coalesce_tensor$"),
     "gradient-buffer fusion: XLA buffer assignment + fused collectives"),
    (re.compile(r"^run_program$"),
     "dy2static partial-program executor: jit/dy2static.py converts "
     "control flow into the one trace instead"),
    (re.compile(r"^inplace_abn$"),
     "in-place activated BN memory trick: XLA memory planning; "
     "batch_norm + activation cover the semantics"),
    (re.compile(r"^sample_logits$"),
     "sampled softmax for huge vocab: the Pallas fused-CE kernel makes "
     "the full softmax affordable on TPU (ops/pallas/fused_ce.py)"),
    (re.compile(r"^(merge_selected_rows|split_selected_rows)$"),
     "SelectedRows gradient plumbing: core/selected_rows.py merges at "
     "the tape level"),
    (re.compile(r"^(attention_lstm|lstmp|multi_gru)$"),
     "xbyak/cudnn-era fused RNN variants: nn.LSTM/GRU + XLA fusion is "
     "the TPU path (projection composes as a Linear)"),
    (re.compile(r"^(bilateral_slice|correlation|var_conv_2d"
                r"|similarity_focus|prroi_pool|deformable_psroi_pooling"
                r"|roi_perspective_transform|deformable_conv_v1)$"),
     "GPU-specialized long-tail vision ops outside the paddle-2.x API "
     "surface (deform_conv2d v2 IS implemented); host-composable from "
     "existing ops when needed"),
    (re.compile(r"^(rpn_target_assign|retinanet_target_assign"
                r"|generate_proposal_labels|generate_mask_labels"
                r"|locality_aware_nms)$"),
     "R-CNN target assignment/sampling: host-side data preparation in "
     "the TPU input pipeline (io/ DataLoader), not device ops"),
    (re.compile(r"^(detection_map)$"),
     None),  # implemented as metric.DetectionMAP — alias, not waiver
]

_ALIASES = {
    # reference name -> our name (spot-translations where naming differs)
    "mul": "matmul", "elementwise_add": "add", "elementwise_sub": "subtract",
    "elementwise_mul": "multiply", "elementwise_div": "divide",
    "elementwise_max": "maximum", "elementwise_min": "minimum",
    "elementwise_pow": "pow", "elementwise_mod": "mod",
    "elementwise_floordiv": "floor_divide",
    "elementwise_heaviside": "heaviside",
    "reduce_sum": "sum", "reduce_mean": "mean", "reduce_max": "max",
    "reduce_min": "min", "reduce_prod": "prod", "reduce_all": "all",
    "reduce_any": "any", "reduce_amax": "amax", "reduce_amin": "amin",
    "fill_constant": "full", "fill_any_like": "full_like",
    "fill_zeros_like": "zeros_like", "fill_constant_batch_size_like":
    "full", "uniform_random": "uniform", "gaussian_random": "randn",
    "gaussian_random_batch_size_like": "randn",
    "uniform_random_batch_size_like": "uniform",
    "truncated_gaussian_random": "truncated_normal",
    "randint": "randint", "top_k": "topk", "top_k_v2": "topk",
    "arg_max": "argmax", "arg_min": "argmin", "batch_norm": "batch_norm",
    "sync_batch_norm": "syncbatchnorm", "hierarchical_sigmoid": "hsigmoid",
    "sigmoid_cross_entropy_with_logits":
    "binary_cross_entropy_with_logits",
    "hierarchical_sigmoid": "hsigmoid_loss",
    "softmax_with_cross_entropy": "cross_entropy",
    "lookup_table": "embedding", "lookup_table_v2": "embedding",
    "lookup_table_dequant": "embedding",
    "depthwise_conv2d": "conv2d", "depthwise_conv2d_transpose":
    "conv2d_transpose", "conv3d": "conv3d", "matmul_v2": "matmul",
    "flatten2": "flatten", "flatten_contiguous_range": "flatten",
    "reshape2": "reshape", "transpose2": "transpose", "squeeze2": "squeeze",
    "unsqueeze2": "unsqueeze", "expand_v2": "expand", "expand_as_v2":
    "expand_as", "sum": "add_n", "scale": "scale", "clip_by_norm":
    "clip_grad_norm", "sequence_conv": "sequence_conv",
    "hash": "hash_bucket", "grid_sampler": "grid_sample",
    "allreduce": "all_reduce", "broadcast": "broadcast",
    "cross_entropy2": "cross_entropy", "one_hot_v2": "one_hot",
    "diag_v2": "diag", "fill": "full", "fill_zeros_like2": "zeros_like",
    "minus": "subtract", "range": "arange", "size": "numel",
    "tril_triu": "tril", "where_index": "nonzero",
    "frobenius_norm": "norm", "unique_with_counts": "unique",
    "multiclass_nms2": "multiclass_nms", "multiclass_nms3":
    "multiclass_nms", "precision_recall": "Precision",
    "margin_rank_loss": "margin_ranking_loss",
    "crf_decoding": "viterbi_decode",
    "generate_proposals_v2": "generate_proposals",
    "detection_map": "DetectionMAP",
    "average_accumulates": "ModelAverage",
    "fsp": "fsp_matrix", "dpsgd": "dpsgd",
    "lars_momentum": "lars",
    "sampling_id": "sampling_id", "dequantize_log": "dequantize_log",
    "pad2d": "pad", "pad3d": "pad", "pad_constant_like": "pad",
    "unpool": "max_unpool2d", "unpool3d": "max_unpool3d",
    "pool2d": "avg_pool2d", "pool3d": "avg_pool3d", "max_pool2d_with_index":
    "max_pool2d", "max_pool3d_with_index": "max_pool3d",
    "nearest_interp": "interpolate", "bilinear_interp": "interpolate",
    "trilinear_interp": "interpolate", "bicubic_interp": "interpolate",
    "linear_interp": "interpolate", "nearest_interp_v2": "interpolate",
    "bilinear_interp_v2": "interpolate", "trilinear_interp_v2":
    "interpolate", "bicubic_interp_v2": "interpolate", "linear_interp_v2":
    "interpolate", "crop": "crop", "crop_tensor": "crop",
    "strided_slice": "strided_slice", "slice": "slice",
    "set_value": "set_value", "assign_value": "assign",
    "share_data": "assign", "load": "load", "save": "save",
    "load_combine": "load", "save_combine": "save",
    "merge_lod_tensor": "concat", "split_lod_tensor": "split",
    "lod_reset": "lod_reset", "lod_rank_table": "lod_reset",
    "im2sequence": "unfold", "unfold": "unfold", "fold": "fold",
    "smooth_l1_loss": "smooth_l1_loss", "huber_loss": "smooth_l1_loss",
    "grad_add": "add", "graph_send_recv": "segment_sum",
    "segment_pool": "segment_sum",
    "c_allreduce_sum": "all_reduce", "c_allreduce_max": "all_reduce",
    "c_allreduce_min": "all_reduce", "c_allreduce_prod": "all_reduce",
    "c_allgather": "all_gather", "c_reducescatter": "reduce_scatter",
    "c_broadcast": "broadcast", "c_reduce_sum": "reduce",
    "c_reduce_max": "reduce", "c_reduce_min": "reduce",
    "c_reduce_prod": "reduce", "c_scatter": "scatter",
    "send_v2": "send", "recv_v2": "recv", "barrier": "barrier",
    "c_embedding": "embedding", "c_split": "split",
    "c_concat": "concat", "alltoall": "alltoall",
    "global_scatter": "alltoall", "global_gather": "alltoall",
    "partial_send": "send", "partial_recv": "recv",
    "partial_allgather": "all_gather",
    "distributed_fused_lamb": "lamb", "distributed_fused_lamb_init": "lamb",
    "check_finite_and_unscale": "amp_check_finite_and_scale",
    "update_loss_scaling": "amp_update_loss_scaling",
    "get_float_status": "isfinite", "clear_float_status": "isfinite",
    "float_status": "isfinite",
    "print": "print_op", "assert": "assert_op",
    "is_empty": "is_empty", "isfinite": "isfinite",
    "isfinite_v2": "isfinite", "isinf_v2": "isinf", "isnan_v2": "isnan",
    "lstm": "lstm", "gru": "gru", "rnn": "rnn", "cudnn_lstm": "lstm",
    "warpctc": "ctc_loss", "ctc_align": "ctc_loss",
    "moving_average_abs_max_scale":
    "fake_quantize_moving_average_abs_max",
    "stft": "stft", "spectral_norm": "spectral_norm",
    "anchor_generator": "anchor_generator",
    "iou_similarity": "iou_similarity",
    "collect_fpn_proposals": "distribute_fpn_proposals",
    "tdm_child": "tdm_child", "tdm_sampler": "tdm_sampler",
    "pyramid_hash": "pyramid_hash", "pull_sparse": "pull_sparse",
    "dpsgd": "dpsgd", "sgd": "sgd", "adam": "adam", "adamw": "adamw",
    "lamb": "lamb", "adagrad": "adagrad", "adadelta": "adadelta",
    "rmsprop": "rmsprop", "ftrl": "ftrl", "adamax": "adamax",
    "momentum": "momentum",
    "decayed_adagrad": "adagrad", "proximal_gd": "sgd",
    "proximal_adagrad": "adagrad", "sparse_momentum": "momentum",
    "merged_adam": "adam", "merged_momentum": "momentum",
}


_DIR_WAIVES = {
    "fused/": "fusion ops: XLA fuses automatically; the profitable "
              "exceptions (attention, CE) are Pallas kernels (ops/pallas/)",
    "nccl/": "NCCL runtime ops: mesh axes + XLA collectives",
    "lite/": "Lite subgraph engine: inference is StableHLO + XLA here",
    "tensorrt/": "TensorRT subgraph engine: inference is StableHLO + XLA",
    "mkldnn/": "MKLDNN binding variants: XLA owns kernel selection",
}


def classify(regs, surface):
    covered, waived, missing = [], [], []
    lower = {s.lower() for s in surface}
    for name, src in regs.items():
        target = _ALIASES.get(name, name)
        if target in surface or target.lower() in lower \
                or name in surface or name.lower() in lower:
            covered.append((name, src, target))
            continue
        for prefix, reason in _DIR_WAIVES.items():
            if src.startswith(prefix):
                waived.append((name, src, reason))
                break
        else:
            for pat, reason in _WAIVE_PATTERNS:
                if reason is not None and pat.match(name):
                    waived.append((name, src, reason))
                    break
            else:
                missing.append((name, src))
    return covered, waived, missing


def main():
    regs = harvest()
    surface = implementation_surface()
    covered, waived, missing = classify(regs, surface)
    n = len(regs)
    pct = 100.0 * len(covered) / max(1, n - len(waived))
    lines = [
        "# Operator coverage vs the reference registry",
        "",
        f"Harvested **{n}** unique `REGISTER_OPERATOR*` names from "
        f"`{REF}` (the SURVEY §2.1 N30 737-registration set, deduplicated "
        "by family).",
        "",
        f"| covered | waived (with reason) | missing | coverage of "
        f"non-waived |",
        f"|---|---|---|---|",
        f"| {len(covered)} | {len(waived)} | {len(missing)} | "
        f"{pct:.1f}% |",
        "",
        "## Missing (to implement or justify)",
        "",
    ]
    for name, src in missing:
        lines.append(f"- `{name}` ({src})")
    lines += ["", "## Waived", ""]
    by_reason = {}
    for name, src, reason in waived:
        by_reason.setdefault(reason, []).append(name)
    for reason, names in sorted(by_reason.items()):
        lines.append(f"- **{reason}**: " + ", ".join(
            f"`{x}`" for x in sorted(names)))
    lines += ["", "## Covered (reference name -> surface name)", ""]
    for name, src, target in covered:
        suffix = "" if target == name else f" -> `{target}`"
        lines.append(f"- `{name}`{suffix}")
    with open(OUT, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"coverage: {len(covered)}/{n - len(waived)} non-waived "
          f"({pct:.1f}%), {len(waived)} waived, {len(missing)} missing "
          f"-> {OUT}")
    return 0 if pct >= 90.0 else 1


if __name__ == "__main__":
    sys.exit(main())
