"""Online-learning loop drill (the ROADMAP "close the serve->train
loop" proof, runnable as an operator tool).

Drives the full closed loop from docs/online_learning.md end to end:
a ServeLoop over a tiny GPT emits completion records at retire; a
dataset/streaming.StreamingDataset turns the deliberately-duplicated
record feed into exactly-once training batches; the continuous Downpour
trainer (ps_config mode="online") pushes replay-keyed deltas into a
3-server replicated geo_sparse cluster; EmbeddingSnapshotPublisher cuts
versioned snapshots and ServeLoop.publish_weights hot-swaps them
between decode beats. The whole run executes under seeded RESET+DROP
transport chaos, and (with >=2 rounds) a shard primary is killed
PERMANENTLY mid-drill — the trainer rides the failover re-route and the
publisher fetches through the promoted backup.

FAILS (exit 1) unless all of:
  - zero serve requests dropped or errored across every hot-swap
  - stream accounting exact: every record accepted once, every
    duplicate rejected, every batch delivered once
  - exactly-once delta accounting: per-server `table.applied` matches
    the flush schedule replayed against the membership timeline
  - the served model measurably moved toward the traffic: the versioned
    eval metric strictly decreases across the published snapshots

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/online_drill.py

Env knobs (defaults are the CPU-valid tier-1 shape):
  ONLINE_DRILL_ROUNDS=3     serve->train->publish rounds (>=2 kills a
                            shard primary after round 1's train)
  ONLINE_DRILL_REQS=6       serve requests per round
  ONLINE_DRILL_NEW=6        tokens generated per request
  ONLINE_DRILL_BATCH=3      records per training batch (divides REQS)
  ONLINE_DRILL_SEED=11      chaos seed
  ONLINE_DRILL_CHAOS_PCT=2  per-event %% probability of RESET and DROP

framework_lint TOOL_CROSS_CHECKS runs self_check() here: the
PADDLE_STREAM_* / PADDLE_ONLINE_* flag defaults, bench.py's
BENCH_ONLINE_* online-mode knobs, and docs/online_learning.md must
agree.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import numpy as np  # noqa: E402

ROUNDS = int(os.environ.get("ONLINE_DRILL_ROUNDS", 3))
REQS = int(os.environ.get("ONLINE_DRILL_REQS", 6))
NEW = int(os.environ.get("ONLINE_DRILL_NEW", 6))
BATCH = int(os.environ.get("ONLINE_DRILL_BATCH", 3))
SEED = int(os.environ.get("ONLINE_DRILL_SEED", 11))
CHAOS_PCT = float(os.environ.get("ONLINE_DRILL_CHAOS_PCT", 2))

# flag defaults this tool (and docs/online_learning.md's flag table)
# are written against; drift means the doc + this header need an update
ONLINE_FLAG_DEFAULTS = {
    "PADDLE_STREAM_QUEUE_CAP": 1024,
    "PADDLE_STREAM_DEDUPE_WINDOW": 4096,
    "PADDLE_ONLINE_SYNC_EVERY": 1,
    "PADDLE_ONLINE_STALENESS_BATCHES": 4,
}

# bench.py online-mode env defaults (BENCH_MODE=online); self_check pins
# them so the bench line and this drill describe the same loop
BENCH_ONLINE_DEFAULTS = {
    "BENCH_ONLINE_RECORDS": 512,
    "BENCH_ONLINE_BATCH": 16,
    "BENCH_ONLINE_SYNC_EVERY": 4,
    "BENCH_ONLINE_PUBLISH_EVERY": 8,
}

FAST = dict(timeout=2.0, max_retries=2, backoff_base=0.01,
            backoff_max=0.05, connect_retry_s=5.0)
HB = dict(heartbeat_s=0.1, heartbeat_timeout_s=0.7)


def run():
    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.core import monitor
    from paddle_tpu.dataset import StreamingDataset
    from paddle_tpu.distributed.ps import (EmbeddingPrefetcher,
                                           EmbeddingSnapshotPublisher,
                                           HeterPSCache, PSClient,
                                           PSServer, ShardMap)
    from paddle_tpu import nn, optimizer
    from paddle_tpu.inference import ServeConfig, ServeLoop
    from paddle_tpu.testing import faults
    from paddle_tpu.text.models.gpt import GPT, GPTConfig
    from paddle_tpu.traffic import harness

    if REQS % BATCH:
        print(f"ONLINE_DRILL_REQS={REQS} must be a multiple of "
              f"ONLINE_DRILL_BATCH={BATCH}", file=sys.stderr)
        return 2
    violations = []

    paddle.seed(0)
    cfg = GPTConfig.tiny()
    gpt = GPT(cfg)
    gpt.eval()
    vocab, dim = cfg.vocab_size, cfg.hidden_size
    target = np.random.RandomState(77).uniform(
        -0.5, 0.5, (vocab, dim)).astype(np.float32)

    n_srv = 3
    specs = {"wte": {"type": "geo_sparse", "dim": dim, "init": "zeros"}}
    servers = [PSServer("127.0.0.1:0", specs) for _ in range(n_srv)]
    eps = [s.start() for s in servers]
    smap = ShardMap.create(eps, n_backups=1)
    for s in servers:
        s.enable_replication(shard_map=smap, peers=eps, n_backups=1,
                             rpc_opts=dict(FAST), **HB)

    trained_ids = set()

    def _collate(recs):
        ids = np.concatenate([np.asarray(r["prompt"] + r["tokens"],
                                         np.int64) for r in recs])
        trained_ids.update(int(t) for t in ids)
        return {"ids": ids, "target": target[ids]}

    ds = StreamingDataset(batch_size=BATCH, collate=_collate,
                          name="online_drill")

    def _on_complete(rec):   # at-least-once transport: every record twice
        ds.offer(rec)
        ds.offer(rec)

    loop = ServeLoop(gpt, ServeConfig(max_active=4, kv_blocks=16,
                                      block_size=16, max_seq_len=64),
                     on_complete=_on_complete)
    wte_key = next(k for k, v in loop._params.items()
                   if tuple(v.shape) == (vocab, dim))
    wte0 = np.asarray(loop._params[wte_key]).copy()

    paddle.enable_static()
    main_prog = static.Program("online_drill")
    with static.program_guard(main_prog):
        ids_v = static.data("ids", [-1], "int64")
        tgt_v = static.data("target", [-1, dim], "float32")
        emb = nn.Embedding(vocab, dim)
        diff = emb(ids_v) - tgt_v
        # mean over tokens, sum over dim: per-occurrence row movement is
        # 2*lr*n/N <= 2*lr — a contraction toward the target for lr<0.5
        loss = paddle.ops.mean(paddle.ops.sum(diff * diff, axis=-1))
        optimizer.SGD(learning_rate=0.25).minimize(loss)
    emb_name = emb.weight.scope_name
    exe = static.Executor()

    client_t = PSClient(eps, **FAST)
    client_p = PSClient(eps, **FAST)
    cache = HeterPSCache(client_p, "wte", dim, capacity=256, host_rows=0)
    pub = EmbeddingSnapshotPublisher(client_p, "wte", cache=cache)
    prefetchers = []
    window = harness.Window(ds)
    holder = {}
    all_reqs = []
    snaps = []
    state = None

    def serve_phase(k):
        rng = np.random.RandomState(1000 + k)
        prompts = [rng.randint(0, 48, 4).astype(np.int64)
                   for _ in range(REQS)]
        stats = harness.drive_serve(
            loop, harness.submissions_from_prompts(prompts, NEW),
            wait="idle")
        for e in stats.errors:
            violations.append(f"serve phase {k}: {e}")
        all_reqs.extend(r for r in stats.requests if r is not None)

    def train_phase(n_batches):
        pf = EmbeddingPrefetcher(client_t, table="wte")
        prefetchers.append(pf)
        ps_cfg = {"client": client_t, "mode": "online", "sync_every": 1,
                  "trainer_id": 7,
                  "sparse": [{"param": emb_name, "slot": "ids",
                              "table": "wte", "prefetcher": pf}],
                  "on_batch": lambda d: holder.update(drv=d)}
        if state is not None:
            ps_cfg["state"] = state["online"]
        exe.train_from_dataset(
            program=main_prog, dataset=window.take(n_batches),
            ps_config=ps_cfg,
            start_batch=ds.stats()["delivered_batches"])
        drv = holder["drv"]
        if any(f is not None for f in drv._frozen):
            violations.append("a flush payload was still frozen "
                              "(un-acked) at end of a train phase")
        return {"online": drv.online_state(), "ds": ds.state_dict()}

    def publish_and_swap():
        version, _ = pub.publish()
        snap = pub.materialize(np.asarray(loop._params[wte_key]))
        loop.publish_weights(version, {wte_key: snap})
        loop.run_until_idle()               # applies between beats
        if loop.model_version != version:
            violations.append(
                f"hot-swap did not land: model_version "
                f"{loop.model_version} != published {version}")
        snaps.append(snap)

    kill_round = 1 if ROUNDS >= 2 else None
    k_kill = None
    before = monitor.stats("serve.")
    t0 = time.perf_counter()
    p = CHAOS_PCT / 100.0
    try:
        with faults.inject(seed=SEED, p={faults.RESET: p,
                                         faults.DROP: p}) as inj:
            for k in range(ROUNDS):
                serve_phase(k)
                state = train_phase(REQS // BATCH)
                if k == kill_round:
                    # a shard primary dies PERMANENTLY; the trainer and
                    # publisher ride the failover to the promoted backup
                    k_kill = len(holder["drv"].flush_log)
                    servers[0].shutdown()
                    deadline = time.perf_counter() + 15.0
                    while time.perf_counter() < deadline:
                        try:
                            client_t.refresh_shard_map()
                        except Exception:
                            pass
                        if eps[0] not in client_t.shard_map.servers:
                            break
                        time.sleep(0.1)
                    else:
                        violations.append(
                            f"no promotion after killing {eps[0]}")
                publish_and_swap()
            chaos_fired = {"reset": inj.fired(faults.RESET),
                           "drop": inj.fired(faults.DROP)}
    finally:
        for c in (client_t, client_p, *prefetchers):
            try:
                c.close()
            except Exception:
                pass
        for j, s in enumerate(servers):
            if kill_round is not None and j == 0:
                continue
            s.shutdown()
        paddle.disable_static()

    # ---- zero dropped serve requests across the hot-swaps ----
    want_reqs = ROUNDS * REQS
    done = sum(1 for r in all_reqs
               if r.done and len(r.result(timeout=0)) == NEW)
    if done != want_reqs:
        violations.append(f"{want_reqs - done} of {want_reqs} serve "
                          "requests dropped or truncated")
    errored = int(monitor.stat_get("serve.requests_errored")
                  - before.get("serve.requests_errored", 0))
    if errored:
        violations.append(f"{errored} serve requests errored")
    swaps = int(monitor.stat_get("serve.hot_swaps")
                - before.get("serve.hot_swaps", 0))
    if swaps != ROUNDS:
        violations.append(f"{swaps} hot-swaps landed, wanted {ROUNDS}")

    # ---- exactly-once stream accounting ----
    st = ds.stats()
    if not (st["accepted"] == want_reqs
            and st["duplicates"] == want_reqs
            and st["delivered_records"] == want_reqs
            and st["backlog"] == 0):
        violations.append(f"stream accounting off: {st}")

    # ---- exactly-once delta accounting: replay the flush schedule
    # against the membership timeline ----
    log = holder["drv"].flush_log
    if [seq for _, seq, _ in log] != list(range(len(log))):
        violations.append(f"flush seqs not contiguous: "
                          f"{[s for _, s, _ in log]}")
    expected = {ep: 0 for ep in eps}
    for _, seq, idlist in log:
        for s in sorted({int(i) % n_srv for i in idlist}):
            for ep in (eps[s], eps[(s + 1) % n_srv]):
                if k_kill is not None and seq >= k_kill and ep == eps[0]:
                    continue
                expected[ep] += 1
    applied = {}
    for j, s in enumerate(servers):
        if kill_round is not None and j == 0:
            continue
        applied[eps[j]] = s.table("wte").applied
        if applied[eps[j]] != expected[eps[j]]:
            violations.append(
                f"server {j} applied {applied[eps[j]]} deltas, schedule "
                f"replay expects {expected[eps[j]]} — exactly-once "
                "accounting broken")

    # ---- the served model measurably shifted toward the traffic ----
    ev = np.fromiter(sorted(trained_ids), np.int64)
    metric = [round(float(np.square(w[ev] - target[ev]).mean()), 6)
              for w in [wte0] + snaps]
    if any(b >= a for a, b in zip(metric, metric[1:])):
        violations.append(f"eval metric not strictly decreasing across "
                          f"snapshot versions: {metric}")

    # serving-tier latency through the SHARED estimator (core/slo.py) —
    # comparable with serve_load_test's p50/p99 because the
    # implementation is the same
    from paddle_tpu.core.slo import percentile
    ttfts = [r.ttft_s * 1e3 for r in all_reqs
             if getattr(r, "ttft_s", None) is not None]
    report = {
        "tool": "tools/online_drill.py",
        "rounds": ROUNDS,
        "requests": want_reqs,
        "completed": done,
        "ttft_ms": {"p50": percentile(ttfts, 50, ndigits=3),
                    "p99": percentile(ttfts, 99, ndigits=3)},
        "hot_swaps": swaps,
        "model_version": loop.model_version,
        "chaos_fired": chaos_fired,
        "primary_killed": kill_round is not None,
        "stream": {k: st[k] for k in ("accepted", "duplicates",
                                      "delivered_records",
                                      "delivered_batches", "backlog")},
        "flushes": len(log),
        "applied_per_server": {ep: int(n) for ep, n in applied.items()},
        "eval_metric_by_version": metric,
        "wall_s": round(time.perf_counter() - t0, 3),
        "violations": len(violations),
    }
    print(json.dumps(report, indent=1))
    for v in violations[:10]:
        print("VIOLATION:", v, file=sys.stderr)
    return 1 if violations else 0


# --------------------------------------------------------------------------
# framework_lint cross-check (TOOL_CROSS_CHECKS)
# --------------------------------------------------------------------------

def self_check():
    """Online-loop knobs <-> flag defaults <-> bench online config <->
    docs. Returns violations."""
    problems = []
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        from paddle_tpu.core import flags as _flags
    except Exception as e:  # pragma: no cover
        return [f"online_drill: paddle_tpu import failed: {e!r}"]
    for name, want in ONLINE_FLAG_DEFAULTS.items():
        defn = _flags._DEFS.get(name)
        if defn is None:
            problems.append(f"online_drill: flag {name} is no longer "
                            "defined in core/flags.py")
        elif defn[1] != want:
            problems.append(
                f"online_drill: {name} default drifted "
                f"({defn[1]!r} != {want!r}) — update ONLINE_FLAG_DEFAULTS "
                "and docs/online_learning.md")
    # bench.py online-mode env defaults
    import re
    with open(os.path.join(repo, "bench.py")) as f:
        src = f.read()
    for env, want in BENCH_ONLINE_DEFAULTS.items():
        m = re.search(r'os\.environ\.get\("%s",\s*([0-9]+)\)' % env, src)
        if not m:
            problems.append(
                f"online_drill: bench.py no longer reads {env}")
        elif int(m.group(1)) != want:
            problems.append(
                f"online_drill: bench.py default {env}={m.group(1)} "
                f"but this tool assumes {want}")
    # the bench's flush cadence must stay legal under the default
    # staleness bound — otherwise BENCH_MODE=online benches a config the
    # trainer would fail-stop on
    if BENCH_ONLINE_DEFAULTS["BENCH_ONLINE_SYNC_EVERY"] > \
            ONLINE_FLAG_DEFAULTS["PADDLE_ONLINE_STALENESS_BATCHES"]:
        problems.append("online_drill: BENCH_ONLINE_SYNC_EVERY exceeds "
                        "the PADDLE_ONLINE_STALENESS_BATCHES default")
    # docs
    doc_path = os.path.join(repo, "docs", "online_learning.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return problems + [f"online_drill: cannot read {doc_path}: {e}"]
    for name in ONLINE_FLAG_DEFAULTS:
        if name not in doc:
            problems.append(f"online_drill: flag {name} is not "
                            "documented in docs/online_learning.md")
    for token in ("online_drill", "BENCH_MODE=online"):
        if token not in doc:
            problems.append(
                f"online_drill: docs/online_learning.md no longer "
                f"mentions `{token}`")
    # ttft percentiles must come from the shared core/slo.py estimator
    with open(os.path.abspath(__file__)) as f:
        self_src = f.read()
    if "from paddle_tpu.core.slo import percentile" not in self_src:
        problems.append("online_drill: report ttft percentiles must "
                        "come from core.slo.percentile")
    for token in ("harness.drive_serve", "harness.Window"):
        if token not in self_src:
            problems.append(f"online_drill: the serve/window plumbing "
                            f"must come from paddle_tpu.traffic.harness "
                            f"(`{token}` missing)")
    return problems


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-check" in argv or "--self_check" in argv:
        problems = self_check()
        for p in problems:
            print(p)
        print("online_drill self-check:",
              "clean" if not problems else f"{len(problems)} problem(s)")
        return 1 if problems else 0
    return run()


if __name__ == "__main__":
    sys.exit(main())
