"""Repo-level framework lint (reference tools/check_op_desc.py +
tools/check_api_compatible.py discipline, folded into one gate).

Two families of checks, both pure-Python and fast enough for tier-1:

1. Registry <-> surface cross-check: every `@defop`-registered op must be
   visible in the committed API.spec (an op added without regenerating
   the spec is invisible to API review), no spec entry may be MISSING
   (dead surface), and each op's (signature, version) pair must match the
   committed OP_VERSIONS.json snapshot — changing an op's signature
   WITHOUT bumping `@defop(version=...)` is version drift: saved
   .pdmodel artifacts would replay the op under new semantics with no
   load-time warning (framework/program_serde.py op-version check).

2. Tracer-concretization hazard scan: AST-walk every `@defop` body for
   patterns that crash or silently specialize under jit/eval_shape
   tracing — `if`/`while` on a tensor argument, `float()`/`int()`/
   `bool()` of a tensor argument, and `.item()` anywhere. Tensor
   arguments are approximated as positional parameters without defaults
   (attrs carry defaults by convention). Deliberate host-side ops mark
   the line with `# lint: concretization-ok`.

Usage:
  python tools/framework_lint.py            # check; exit 1 on violations
  python tools/framework_lint.py --update   # rewrite OP_VERSIONS.json
"""
from __future__ import annotations

import ast
import inspect
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SPEC_PATH = os.path.join(REPO, "API.spec")
VERSIONS_PATH = os.path.join(REPO, "OP_VERSIONS.json")
OPS_DIR = os.path.join(REPO, "paddle_tpu", "ops")

PRAGMA = "lint: concretization-ok"

def _defop_modules():
    """Every paddle_tpu module that registers ops — found by source scan,
    so the lint's registry view does not depend on import order."""
    pkg_root = os.path.join(REPO, "paddle_tpu")
    mods = []
    for root, _dirs, files in os.walk(pkg_root):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            with open(path) as f:
                if "defop" not in f.read():
                    continue
            rel = os.path.relpath(path, REPO)[:-3].replace(os.sep, ".")
            if rel.endswith(".__init__"):
                rel = rel[: -len(".__init__")]
            mods.append(rel)
    return sorted(mods)


def _registry():
    # import the complete op-defining surface first: op registration is
    # an import side effect, and the lint must see the SAME registry no
    # matter what the test process imported beforehand
    import importlib
    for mod in _defop_modules():
        try:
            importlib.import_module(mod)
        except Exception:
            pass  # optional deps (pallas on TPU etc.) may be absent
    from paddle_tpu.ops import OP_REGISTRY
    return OP_REGISTRY


def _sig(fn):
    try:
        return str(inspect.signature(fn))
    except (TypeError, ValueError):
        return "(...)"


def _is_static_registration(fn):
    """True for ops the version-snapshot discipline binds: defined at
    module level of a repo module (registered by importing the library).
    Runtime registrations — user custom ops (`register_custom_op`) and
    kernels minted inside functions/classes (e.g. moe_layer) — are
    process-local and cannot be snapshot-pinned."""
    raw = getattr(fn, "raw", fn)
    try:
        path = inspect.getsourcefile(raw)
        lines, _ = inspect.getsourcelines(raw)
    except (TypeError, OSError):
        return False
    if not path or not os.path.abspath(path).startswith(
            os.path.join(REPO, "paddle_tpu") + os.sep):
        return False
    first = next((ln for ln in lines if ln.strip()), "")
    return not first.startswith((" ", "\t"))  # column-0 def/decorator


# ---------------------------------------------------------------------------
# check 1: registry vs API.spec vs OP_VERSIONS.json
# ---------------------------------------------------------------------------

def spec_leaf_names(spec_path=SPEC_PATH):
    """Leaf names with at least one committed `def`/`class` entry."""
    names = set()
    missing = []
    with open(spec_path) as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            head = line.split(" ", 1)[0]
            leaf = head.rsplit(".", 1)[-1]
            if " MISSING" in line:
                missing.append(head)
            else:
                names.add(leaf)
    return names, missing


def _public_surface_leaves():
    """Leaf names of the LIVE public surface (the same sweep
    gen_api_spec commits to API.spec). Ops outside it are internal
    kernels (serde-registered dispatch heads etc.) and owe the spec
    nothing — but a publicly exported op missing from the committed spec
    is an unreviewed surface change."""
    import gen_api_spec
    names = set()
    for line in gen_api_spec.collect().splitlines():
        head = line.split(" ", 1)[0]
        names.add(head.rsplit(".", 1)[-1])
    return names


def check_registry_spec(spec_path=SPEC_PATH, versions_path=VERSIONS_PATH):
    """Returns a list of violation strings (empty = clean)."""
    reg = _registry()
    problems = []
    leaves, spec_missing = spec_leaf_names(spec_path)
    for head in spec_missing:
        problems.append(f"API.spec entry '{head}' is MISSING — dead "
                        "surface; regenerate with tools/gen_api_spec.py")
    public = _public_surface_leaves()
    for name in sorted(reg):
        if name in public and name not in leaves:
            problems.append(
                f"op '{name}' is in OP_REGISTRY but absent from API.spec "
                "— regenerate the spec (tools/gen_api_spec.py --update) "
                "or export the op")
    try:
        with open(versions_path) as f:
            snapshot = json.load(f)
    except FileNotFoundError:
        return problems + [
            f"{os.path.basename(versions_path)} not found — generate it "
            "with `python tools/framework_lint.py --update`"]
    for name, fn in sorted(reg.items()):
        if not _is_static_registration(fn):
            continue
        live_v = int(getattr(fn, "op_version", 1))
        live_sig = _sig(fn)
        snap = snapshot.get(name)
        if snap is None:
            problems.append(
                f"op '{name}' has no OP_VERSIONS.json entry — run "
                "`python tools/framework_lint.py --update`")
            continue
        if live_v < int(snap["version"]):
            problems.append(
                f"op '{name}' version regressed: snapshot v{snap['version']}"
                f" but @defop declares v{live_v}")
        elif live_v > int(snap["version"]):
            # a stale snapshot would disarm the drift check for every
            # future signature change to this op
            problems.append(
                f"op '{name}' was bumped to v{live_v} but OP_VERSIONS.json "
                f"still records v{snap['version']} — run "
                "`python tools/framework_lint.py --update` to re-pin it")
        elif live_sig != snap["sig"]:
            problems.append(
                f"op '{name}' signature drifted ({snap['sig']} -> "
                f"{live_sig}) without a version bump — bump "
                f"@defop(version={live_v + 1}) so program_serde flags old "
                "artifacts, then --update the snapshot")
    for name in sorted(set(snapshot) - set(reg)):
        problems.append(
            f"OP_VERSIONS.json lists op '{name}' which is no longer "
            "registered — removed ops break saved artifacts; run --update "
            "if the removal is deliberate")
    return problems


def update_versions(versions_path=VERSIONS_PATH):
    reg = _registry()
    snap = {name: {"version": int(getattr(fn, "op_version", 1)),
                   "sig": _sig(fn)}
            for name, fn in sorted(reg.items())
            if _is_static_registration(fn)}
    with open(versions_path, "w") as f:
        json.dump(snap, f, indent=0, sort_keys=True)
        f.write("\n")
    return len(snap)


# ---------------------------------------------------------------------------
# check 2: tracer-concretization hazards in @defop bodies
# ---------------------------------------------------------------------------

def _is_defop_decorator(dec):
    if isinstance(dec, ast.Name) and dec.id == "defop":
        return True
    if isinstance(dec, ast.Call):
        return _is_defop_decorator(dec.func)
    if isinstance(dec, ast.Attribute) and dec.attr == "defop":
        return True
    return False


_ARRAY_ROOTS = {"jnp", "jax", "lax"}


def _call_root(func):
    while isinstance(func, ast.Attribute):
        func = func.value
    return func.id if isinstance(func, ast.Name) else None


def _tensor_params(fdef: ast.FunctionDef):
    """Parameters that flow into jnp/jax/lax as the FIRST positional
    bare-name argument of a call — the dataflow approximation of 'this
    is the traced array', robust against int-like attrs (`axis`,
    `num_classes`) that a signature-position heuristic misclassifies."""
    params = {a.arg for a in fdef.args.posonlyargs + fdef.args.args}
    tensors = set()
    for node in ast.walk(fdef):
        if isinstance(node, ast.Call) and node.args \
                and _call_root(node.func) in _ARRAY_ROOTS \
                and isinstance(node.args[0], ast.Name) \
                and node.args[0].id in params:
            tensors.add(node.args[0].id)
    return tensors


_STATIC_CALLS = {"isinstance", "len", "getattr", "hasattr", "type"}


def _value_names(node, out=None):
    """Names used in VALUE position: excludes attribute access
    (`x.dtype`, `x.shape[i]` — static metadata), `is`/`is not`
    comparisons, and isinstance/len/… introspection calls, all of which
    are legitimate at trace time."""
    if out is None:
        out = set()
    if isinstance(node, ast.Name):
        out.add(node.id)
        return out
    if isinstance(node, ast.Attribute):
        return out  # x.anything — metadata/method access, not the value
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in _STATIC_CALLS:
            return out
        for a in node.args:
            _value_names(a, out)
        for k in node.keywords:
            _value_names(k.value, out)
        return out
    if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
        return out  # `x is None` — identity test, never concretizes
    for child in ast.iter_child_nodes(node):
        _value_names(child, out)
    return out


class _HazardVisitor(ast.NodeVisitor):
    def __init__(self, path, src_lines, fdef):
        self.path = path
        self.lines = src_lines
        self.fdef = fdef
        self.tensors = _tensor_params(fdef)
        self.hits = []

    def _pragma(self, node):
        line = self.lines[node.lineno - 1] if node.lineno - 1 < len(
            self.lines) else ""
        return PRAGMA in line

    def _hit(self, node, what):
        if not self._pragma(node):
            self.hits.append(
                f"{os.path.relpath(self.path, REPO)}:{node.lineno} "
                f"[{self.fdef.name}] {what}")

    def visit_If(self, node):
        bad = _value_names(node.test) & self.tensors
        if bad:
            self._hit(node, "`if` on traced tensor argument "
                            f"({', '.join(sorted(bad))}) — the branch is "
                            "baked at trace time; use jnp.where/lax.cond")
        self.generic_visit(node)

    def visit_While(self, node):
        bad = _value_names(node.test) & self.tensors
        if bad:
            self._hit(node, "`while` on traced tensor argument "
                            f"({', '.join(sorted(bad))}) — use "
                            "lax.while_loop")
        self.generic_visit(node)

    def visit_Call(self, node):
        if isinstance(node.func, ast.Name) \
                and node.func.id in ("float", "int", "bool") and node.args:
            bad = _value_names(node.args[0]) & self.tensors
            if bad:
                self._hit(node, f"`{node.func.id}()` concretizes traced "
                                f"tensor argument ({', '.join(sorted(bad))})")
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item":
            self._hit(node, "`.item()` concretizes a traced value")
        self.generic_visit(node)


def check_concretization(ops_dir=OPS_DIR):
    """AST-scan @defop bodies; returns a list of violation strings."""
    hits = []
    for root, _dirs, files in os.walk(ops_dir):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            with open(path) as f:
                src = f.read()
            try:
                tree = ast.parse(src)
            except SyntaxError as e:
                hits.append(f"{path}: unparseable ({e})")
                continue
            src_lines = src.splitlines()
            for node in ast.walk(tree):
                if isinstance(node, ast.FunctionDef) and any(
                        _is_defop_decorator(d) for d in node.decorator_list):
                    v = _HazardVisitor(path, src_lines, node)
                    for stmt in node.body:
                        v.visit(stmt)
                    hits.extend(v.hits)
    return hits


# ---------------------------------------------------------------------------
# check 3: sibling lint tools (each exposes self_check() -> [violations])
# ---------------------------------------------------------------------------

# Cross-check registry: domain lints that ride along with the framework
# gate. Each module lives in tools/, exposes `self_check()` returning a
# list of violation strings, and `main(argv)` for standalone use.
TOOL_CROSS_CHECKS = ["spmd_lint", "spmd_plan", "hlo_evidence",
                     "pipeline_lint", "obs_report", "ps_load_test",
                     "elastic_drill", "serve_load_test",
                     "pp_schedule_report", "online_drill",
                     "cluster_obs_drill", "capacity_plan"]


def check_tool_registry(tools_dir=None):
    """Every tools/*.py that defines a top-level self_check() must be
    listed in TOOL_CROSS_CHECKS — an unregistered self_check is a lint
    nobody runs, which is how cross-checks silently rot."""
    import ast
    problems = []
    tools_dir = tools_dir or os.path.dirname(os.path.abspath(__file__))
    for fname in sorted(os.listdir(tools_dir)):
        if not fname.endswith(".py"):
            continue
        mod_name = fname[:-3]
        if mod_name == "framework_lint":
            continue          # the registry itself, not a registrant
        try:
            with open(os.path.join(tools_dir, fname)) as f:
                tree = ast.parse(f.read(), filename=fname)
        except SyntaxError as e:
            problems.append(f"tool registry: tools/{fname} does not "
                            f"parse: {e}")
            continue
        has_self_check = any(
            isinstance(node, ast.FunctionDef) and node.name == "self_check"
            for node in tree.body)
        if has_self_check and mod_name not in TOOL_CROSS_CHECKS:
            problems.append(
                f"tool registry: tools/{fname} defines self_check() but "
                "is not listed in framework_lint.TOOL_CROSS_CHECKS — "
                "register it so the gate actually runs it")
    return problems


def check_registered_tools():
    problems = []
    tools_dir = os.path.dirname(os.path.abspath(__file__))
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    for mod_name in TOOL_CROSS_CHECKS:
        try:
            import importlib
            mod = importlib.import_module(mod_name)
        except Exception as e:
            problems.append(f"cross-check tool '{mod_name}' failed to "
                            f"import: {e!r}")
            continue
        if not callable(getattr(mod, "self_check", None)):
            problems.append(f"cross-check tool '{mod_name}' has no "
                            "self_check()")
            continue
        problems.extend(mod.self_check())
    return problems


# ---------------------------------------------------------------------------
# check 4: perf floors over the committed HLO evidence
# ---------------------------------------------------------------------------

EVIDENCE_PATH = os.path.join(REPO, "HLO_EVIDENCE.json")

# The committed HLO_EVIDENCE.json is the repo's perf record of truth
# while the live-TPU bench tunnel is down (ROADMAP). These are the
# headline ratios each kernel PR proved; a regenerated evidence file
# that regresses below a floor FAILS the build instead of silently
# rewriting the record. (label, path-into-the-json, floor)
PERF_FLOORS = [
    ("decode-attention FLOPs reduction",
     ("graphs", "gpt_decode_step", "attention_per_step",
      "flops_reduction_x"), 2.0),
    ("decode-attention bytes reduction",
     ("graphs", "gpt_decode_step", "attention_per_step",
      "bytes_reduction_x"), 2.0),
    ("serve_decode KV-bytes reduction",
     ("graphs", "serve_decode", "kv_bytes_per_step",
      "bytes_reduction_x_at_typical_fill"), 2.0),
    ("scan-fused dispatch reduction",
     ("graphs", "pipeline_scan_megastep", "dispatch_model",
      "dispatch_reduction_x"), 2.0),
    ("hierarchical dp sync inter-pod wire-bytes reduction",
     ("graphs", "hierarchical_sync", "wire_model",
      "inter_pod_reduction_x"), 2.0),
    # capacity model held inside its declared error bands when last
    # validated against the hub (tools/capacity_plan.py --validate);
    # headroom < 1.0 means a metric escaped its band
    ("capacity model validated within band",
     ("graphs", "capacity_validation", "band_headroom_x"), 1.0),
]


def check_perf_floors(evidence_path=EVIDENCE_PATH, floors=None):
    """Returns a list of violation strings (empty = clean)."""
    problems = []
    try:
        with open(evidence_path) as f:
            evidence = json.load(f)
    except FileNotFoundError:
        return [f"{os.path.basename(evidence_path)} not found — the "
                "committed HLO evidence is the perf record of truth; "
                "regenerate with `python tools/hlo_evidence.py`"]
    except json.JSONDecodeError as e:
        return [f"{os.path.basename(evidence_path)} is not valid JSON "
                f"({e}) — regenerate with `python tools/hlo_evidence.py`"]
    missing = object()  # distinct from a legitimately-null JSON leaf
    for label, path, floor in (PERF_FLOORS if floors is None else floors):
        node = evidence
        for key in path:
            if not isinstance(node, dict) or key not in node:
                problems.append(
                    f"perf floor '{label}': {'/'.join(path)} missing from "
                    f"{os.path.basename(evidence_path)} — the evidence "
                    "record lost a headline metric; regenerate with "
                    "`python tools/hlo_evidence.py` (a restructure needs "
                    "a matching PERF_FLOORS update)")
                node = missing
                break
            node = node[key]
        if node is missing:
            continue
        try:
            value = float(node)
        except (TypeError, ValueError):
            problems.append(
                f"perf floor '{label}': {'/'.join(path)} is "
                f"non-numeric ({node!r})")
            continue
        if value < floor:
            problems.append(
                f"perf floor '{label}': {value}x regressed below the "
                f"{floor}x floor — an evidence regeneration may not "
                "silently rewrite the perf record; fix the kernel path "
                "or justify a floor change in the PR")
    return problems


# ---------------------------------------------------------------------------
# check 5: doc flag tables may not drift from core/flags.py
# ---------------------------------------------------------------------------

DOCS_DIR = os.path.join(REPO, "docs")

# a markdown flag-table row: first cell is a backticked PADDLE_*/FLAGS_*
# name (the convention every docs/*.md flag table follows)
_DOC_FLAG_ROW = re.compile(r"^\| *`((?:PADDLE_|FLAGS_)[A-Za-z0-9_]+)`")


def check_doc_flags(docs_dir=DOCS_DIR):
    """Every flag a docs/*.md table documents must still exist in
    core/flags.py — a renamed or deleted flag whose doc row survives is
    operator-facing misinformation (the doc tells someone to set an env
    var nothing reads). Returns a list of violation strings."""
    problems = []
    try:
        from paddle_tpu.core import flags as _flags
    except Exception as e:  # pragma: no cover
        return [f"doc-flag check: paddle_tpu import failed: {e!r}"]
    for fname in sorted(os.listdir(docs_dir)):
        if not fname.endswith(".md"):
            continue
        path = os.path.join(docs_dir, fname)
        with open(path) as f:
            for lineno, line in enumerate(f, 1):
                m = _DOC_FLAG_ROW.match(line)
                if m and m.group(1) not in _flags._DEFS:
                    problems.append(
                        f"docs/{fname}:{lineno} documents flag "
                        f"{m.group(1)} which is not defined in "
                        "core/flags.py — update the doc table or "
                        "restore the flag")
    return problems


# ---------------------------------------------------------------------------
# check 6: the traffic lab must stay deterministic
# ---------------------------------------------------------------------------

TRAFFIC_DIR = os.path.join(REPO, "paddle_tpu", "traffic")

# suppression pragma for a deliberate, reviewed exception
_DETERMINISM_PRAGMA = "lint: traffic-determinism-ok"


def _attr_chain(node):
    """Dotted name of an attribute access ('np.random.RandomState'),
    or None for anything fancier than Name.attr.attr..."""
    import ast
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def check_traffic_determinism(traffic_dir=None):
    """Replayability is paddle_tpu/traffic/'s contract: every draw comes
    from a named, seeded stream. This AST lint forbids the ambient
    entropy sources that silently break byte-identical replay:

      - `time.time()` / `time.time_ns()` (wall clock in generated data;
        `time.perf_counter`/`time.sleep` pacing is fine)
      - any call through the stdlib `random` module (global PRNG)
      - `numpy.random` module-level draws (`np.random.rand(...)` uses
        global state) and UNSEEDED constructors (`np.random.RandomState()`
        / `np.random.default_rng()` with no arguments)

    A deliberate exception carries the `# lint: traffic-determinism-ok`
    pragma on the offending line."""
    import ast
    problems = []
    traffic_dir = traffic_dir or TRAFFIC_DIR
    if not os.path.isdir(traffic_dir):
        return [f"traffic determinism: {traffic_dir} missing"]
    seeded_ctors = {"RandomState", "default_rng", "Generator",
                    "SeedSequence"}
    for fname in sorted(os.listdir(traffic_dir)):
        if not fname.endswith(".py"):
            continue
        path = os.path.join(traffic_dir, fname)
        with open(path) as f:
            src = f.read()
        lines = src.splitlines()
        try:
            tree = ast.parse(src, filename=fname)
        except SyntaxError as e:
            problems.append(
                f"traffic determinism: {fname} does not parse: {e}")
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if chain is None:
                continue
            bad = None
            if chain in ("time.time", "time.time_ns"):
                bad = f"{chain}() (wall clock)"
            elif chain.startswith("random."):
                bad = f"{chain}() (global stdlib PRNG)"
            if bad is None:
                head, _, tail = chain.rpartition(".")
                if head in ("np.random", "numpy.random"):
                    if tail in seeded_ctors:
                        if not node.args and not node.keywords:
                            bad = (f"{chain}() without a seed "
                                   "(nondeterministic entropy)")
                    else:
                        bad = f"{chain}() (global numpy PRNG state)"
            if bad is None:
                continue
            line = lines[node.lineno - 1] if node.lineno <= len(lines) \
                else ""
            if _DETERMINISM_PRAGMA in line:
                continue
            problems.append(
                f"traffic determinism: paddle_tpu/traffic/{fname}:"
                f"{node.lineno} calls {bad} — every draw must come from "
                "a named seeded stream (workload.Stream); add "
                f"`# {_DETERMINISM_PRAGMA}` only for a reviewed "
                "exception")
    return problems


# ---------------------------------------------------------------------------

def run_lint(spec_path=SPEC_PATH, versions_path=VERSIONS_PATH,
             ops_dir=OPS_DIR):
    problems = check_registry_spec(spec_path, versions_path)
    problems += check_concretization(ops_dir)
    problems += check_perf_floors()
    problems += check_tool_registry()
    problems += check_registered_tools()
    problems += check_doc_flags()
    problems += check_traffic_determinism()
    return problems


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--update" in argv:
        n = update_versions()
        print(f"wrote {VERSIONS_PATH} ({n} ops)")
        return 0
    problems = run_lint()
    if problems:
        print(f"framework_lint: {len(problems)} violation(s)")
        for p in problems:
            print(f"  {p}")
        return 1
    print("framework_lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
