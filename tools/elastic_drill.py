"""Elastic kill-and-resume drill (ISSUE 8): prove, end to end, that a
trainer SIGKILLed mid-epoch — no grace, not SIGTERM — is a non-event.

The drill spins an in-process PS cluster, then supervises a trainer
SUBPROCESS (distributed/elastic.py Supervisor) running a PS-backed,
pipelined training loop (static PipelineRunner hot loop + per-step
PSClient pushes under checkpoint-persisted replay keys, verified
auto-checkpoints every few steps). On its first attempt the trainer
SIGKILLs itself at the seeded kill step; the supervisor restarts it; the
restarted trainer restores the newest VERIFIED checkpoint (params,
optimizer slots, rng chain, PSClient replay identity, data cursor),
replays its in-doubt steps — whose re-sent pushes DEDUPE server-side —
and finishes. The drill then asserts the final params and every server's
`table.applied` counters are bitwise-equal to an uninterrupted reference
run, and reports the recovery timeline.

Run:  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/elastic_drill.py
Also: python tools/elastic_drill.py trainer     # internal (subprocess)
      python tools/elastic_drill.py self_check  # lint cross-check

framework_lint TOOL_CROSS_CHECKS runs self_check() here: the
PADDLE_ELASTIC_*/PADDLE_CKPT_* flag defaults, this drill's knobs,
docs/fault_tolerance.md's trainer-recovery section, and the chaos marker
on tests/test_elastic_resume.py must all agree.
"""
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import numpy as np  # noqa: E402

# ---------------------------------------------------------------- knobs
# (env-overridable; the test pins the same schedule)
DRILL_STEPS = int(os.environ.get("PADDLE_DRILL_STEPS", 14))
DRILL_SAVE_EVERY = int(os.environ.get("PADDLE_DRILL_SAVE_EVERY", 4))
DRILL_SEED = int(os.environ.get("PADDLE_DRILL_SEED", 11))
DRILL_BATCH = 8
DRILL_VOCAB = 40
DRILL_DIM = 4

# flag defaults this drill (and the docs flag table) are written
# against; drift means docs/fault_tolerance.md + this header need an
# update — self_check() pins all three together
ELASTIC_FLAG_DEFAULTS = {
    "PADDLE_ELASTIC_MAX_RESTARTS": 3,
    "PADDLE_ELASTIC_RESTART_BACKOFF_S": 1.0,
    "PADDLE_ELASTIC_STALL_TIMEOUT_S": 300.0,
    "PADDLE_ELASTIC_HEARTBEAT_TIMEOUT_S": 60.0,
    "PADDLE_CKPT_VERIFY": True,
}

FAST_RPC = dict(timeout=10.0, max_retries=2, backoff_base=0.01,
                backoff_max=0.05, connect_retry_s=10.0)


def kill_step_for(seed, steps=None, save_every=None):
    """The seeded mid-epoch kill step: strictly after the first
    checkpoint, strictly before the epoch end, and NOT on a checkpoint
    boundary — the in-doubt replay window is what the drill exists to
    exercise."""
    steps = steps or DRILL_STEPS
    save_every = save_every or DRILL_SAVE_EVERY
    rng = np.random.RandomState(seed)
    while True:
        k = int(rng.randint(save_every + 1, steps - 1))
        if k % save_every:
            return k


def table_specs():
    return {"emb": {"type": "sparse", "dim": DRILL_DIM,
                    "optimizer": "sgd", "lr": 1.0, "init": "zeros"},
            "dense0": {"type": "dense", "shape": (3, DRILL_DIM),
                       "optimizer": "sgd", "lr": 0.1, "init": "zeros"}}


# ------------------------------------------------------------- trainer

def run_trainer():
    """The supervised trainer: static pipelined executor + per-step PS
    pushes + verified auto-checkpoints + heartbeat. Reads its wiring
    from PADDLE_DRILL_* env (set by the supervisor side)."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn, ops, optimizer, static
    from paddle_tpu.core import rng as _rng
    from paddle_tpu.distributed.elastic import Heartbeat
    from paddle_tpu.distributed.ps import PSClient
    from paddle_tpu.incubate.checkpoint import TrainingCheckpoint
    from paddle_tpu.static import PipelineRunner

    eps = os.environ["PADDLE_DRILL_ENDPOINTS"].split(",")
    ckpt_dir = os.environ["PADDLE_DRILL_CKPT"]
    out_path = os.environ["PADDLE_DRILL_OUT"]
    steps = int(os.environ.get("PADDLE_DRILL_STEPS", DRILL_STEPS))
    save_every = int(os.environ.get("PADDLE_DRILL_SAVE_EVERY",
                                    DRILL_SAVE_EVERY))
    kill_step = int(os.environ.get("PADDLE_DRILL_KILL_STEP", -1))
    marker = os.environ.get("PADDLE_DRILL_KILL_MARKER", "")
    hb_dir = os.environ.get("PADDLE_ELASTIC_HEARTBEAT_DIR", "")

    paddle.enable_static()
    paddle.seed(1234)
    prog = static.Program("elastic_drill")
    with static.program_guard(prog):
        x = static.data("x", [-1, 4], "float32")
        y = static.data("y", [-1, 1], "float32")
        h = ops.relu(nn.Linear(4, 8)(x))
        loss = ops.mse_loss(nn.Linear(8, 1)(h), y)
        opt = optimizer.Adam(learning_rate=0.05)
        opt.minimize(loss)
    exe = static.Executor()
    scope = static.global_scope()
    param_names = list(prog.persist_ids)

    # deterministic data schedule: batch k is a fixed slice, so a
    # restarted trainer replays the exact batches (the data cursor IS
    # the step counter here; DataLoader-based jobs checkpoint
    # state_dict() instead)
    drng = np.random.RandomState(7)
    X = drng.rand(steps * DRILL_BATCH, 4).astype(np.float32)
    Y = drng.rand(steps * DRILL_BATCH, 1).astype(np.float32)

    # JOB-stable replay identity: (client_id, step-key) must name the
    # same logical mutation across process death — a restart that finds
    # no committed checkpoint yet (death raced the first async save)
    # still dedupes its re-sent pushes. The checkpointed replay_state
    # then carries the auto-minted seq forward too.
    client = PSClient(eps, client_id="drill-trainer-0", **FAST_RPC)
    ckpt = TrainingCheckpoint(ckpt_dir, keep=3, async_save=True)

    def capture(done):
        rs = client.replay_state()
        return {
            "params": {n: np.asarray(scope.get(n)) for n in param_names},
            "optimizer": opt.state_dict(),
            "rng_key": np.asarray(_rng.default_generator()._key),
            "ps": {"client_id": np.frombuffer(
                       rs["client_id"].encode("ascii"),
                       np.uint8).copy(),
                   "seq": int(rs["seq"])},
            "counters": {"step": int(done)},
            "data": {"cursor": int(done)},
        }

    start_step = 0
    state = ckpt.restore()   # verified; walks back over corrupt steps
    if state is not None:
        for n in param_names:
            scope.set(n, jnp.asarray(np.asarray(state["params"][n])))
        opt.set_state_dict(state["optimizer"])
        _rng.default_generator().seat(jnp.asarray(
            np.asarray(state["rng_key"], np.uint32)))
        client.load_replay_state(state["ps"])
        start_step = int(np.asarray(state["counters"]["step"]))
        print(f"[drill-trainer] resumed from step {start_step}",
              flush=True)

    hb = None
    if hb_dir:
        hb = Heartbeat(hb_dir, rank=0, interval_s=0.2).start()

    def ps_step(step):
        """Deterministic PS traffic whose grads depend on PULLED state —
        one lost or double-applied push poisons every later step. The
        replay key is (client_id, step): persisted through the
        checkpoint, so re-sent in-doubt pushes dedupe server-side."""
        r = np.random.RandomState(1000 + step)
        ids = r.randint(0, DRILL_VOCAB, size=8).astype(np.int64)
        rows = client.pull_sparse("emb", ids)
        grads = rows * 0.05 + r.randn(len(ids), DRILL_DIM).astype(
            np.float32)
        client.push_sparse_grad("emb", ids, grads,
                                request_key=f"step{step}")
        dense = client.pull_dense("dense0")
        client.push_dense_grad(
            "dense0",
            dense * 0.05 + r.randn(3, DRILL_DIM).astype(np.float32),
            request_key=f"step{step}")

    with PipelineRunner(exe, prog, fetch_list=[loss],
                        max_inflight=2) as runner:
        for step in range(start_step, steps):
            lo = step * DRILL_BATCH
            runner.submit({"x": X[lo:lo + DRILL_BATCH],
                           "y": Y[lo:lo + DRILL_BATCH]})
            ps_step(step)
            done = step + 1
            if marker and done == kill_step \
                    and not os.path.exists(marker):
                # die for real: SIGKILL, no grace, mid-epoch, with the
                # steps since the last checkpoint in doubt. Waiting out
                # the previous ASYNC commit first only makes the test
                # deterministic about which checkpoint survives — the
                # in-doubt replay window is untouched (death racing the
                # commit itself is test_sigkill_during_async_save's job)
                ckpt.wait()
                with open(marker, "w") as f:
                    f.write(str(done))
                os.kill(os.getpid(), 9)
            if done % save_every == 0 or done == steps:
                runner.sync()   # drain in-flight, write back the carry
                ckpt.save(done, capture(done))
    ckpt.wait()
    if hb is not None:
        hb.stop()
    np.savez(out_path,
             **{f"param_{i}": np.asarray(scope.get(n))
                for i, n in enumerate(param_names)})
    client.close()
    return 0


# ----------------------------------------------------- supervisor side

def start_cluster():
    from paddle_tpu.distributed.ps import PSServer
    servers = [PSServer("127.0.0.1:0", table_specs()) for _ in range(2)]
    eps = [s.start() for s in servers]
    return servers, eps


def final_ps_state(eps):
    from paddle_tpu.distributed.ps import PSClient
    c = PSClient(eps, **FAST_RPC)
    try:
        sparse = c.pull_sparse("emb",
                               np.arange(DRILL_VOCAB, dtype=np.int64))
        dense = c.pull_dense("dense0")
        return np.asarray(sparse).copy(), np.asarray(dense).copy()
    finally:
        c.close()


def run_supervised(workdir, kill=True, steps=DRILL_STEPS,
                   save_every=DRILL_SAVE_EVERY, seed=DRILL_SEED,
                   max_restarts=3):
    """One full supervised run against a fresh in-process cluster;
    returns (params dict, sparse, dense, applied {server: {table: n}},
    supervisor events)."""
    import subprocess

    from paddle_tpu.distributed.elastic import Supervisor

    servers, eps = start_cluster()
    tag = "chaos" if kill else "ref"
    out = os.path.join(workdir, f"out_{tag}.npz")
    hb_dir = os.path.join(workdir, f"hb_{tag}")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               PADDLE_DRILL_ENDPOINTS=",".join(eps),
               PADDLE_DRILL_CKPT=os.path.join(workdir, f"ckpt_{tag}"),
               PADDLE_DRILL_OUT=out,
               PADDLE_DRILL_STEPS=str(steps),
               PADDLE_DRILL_SAVE_EVERY=str(save_every),
               PADDLE_ELASTIC_HEARTBEAT_DIR=hb_dir)
    if kill:
        env["PADDLE_DRILL_KILL_STEP"] = str(
            kill_step_for(seed, steps, save_every))
        env["PADDLE_DRILL_KILL_MARKER"] = os.path.join(
            workdir, f"killed_{tag}")
    try:
        def start_rank(rank):
            return subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "trainer"],
                env=env, cwd=REPO)

        sup = Supervisor(start_rank, nranks=1, heartbeat_dir=hb_dir,
                         max_restarts=max_restarts, backoff_s=0.1,
                         heartbeat_timeout_s=60.0,
                         stall_timeout_s=300.0, poll_s=0.1)
        rc = sup.run()
        assert rc == 0
        with np.load(out) as z:
            params = {k: z[k].copy() for k in z.files}
        sparse, dense = final_ps_state(eps)
        applied = {i: {t: s.table(t).applied for t in ("emb", "dense0")}
                   for i, s in enumerate(servers)}
        return params, sparse, dense, applied, list(sup.events)
    finally:
        for s in servers:
            s.shutdown()


def run_drill(workdir=None):
    import tempfile

    from paddle_tpu.core import monitor

    workdir = workdir or tempfile.mkdtemp(prefix="elastic_drill_")
    k = kill_step_for(DRILL_SEED)
    print(f"[drill] workdir={workdir} steps={DRILL_STEPS} "
          f"save_every={DRILL_SAVE_EVERY} kill_step={k}")

    t0 = time.perf_counter()
    ref = run_supervised(workdir, kill=False)
    t_ref = time.perf_counter() - t0
    print(f"[drill] reference run: {t_ref:.1f}s, "
          f"applied={ref[3]}")

    replays0 = monitor.stat_get("ps.rpc.replays")
    t0 = time.perf_counter()
    chaos = run_supervised(workdir, kill=True)
    t_chaos = time.perf_counter() - t0
    replays = monitor.stat_get("ps.rpc.replays") - replays0

    problems = []
    if not chaos[4]:
        problems.append("supervisor recorded no restart")
    for key in ref[0]:
        if not np.array_equal(ref[0][key], chaos[0][key]):
            problems.append(f"param {key} differs from fault-free run")
    if not np.array_equal(ref[1], chaos[1]):
        problems.append("sparse table differs from fault-free run")
    if not np.array_equal(ref[2], chaos[2]):
        problems.append("dense table differs from fault-free run")
    if ref[3] != chaos[3]:
        problems.append(f"applied counters differ: ref={ref[3]} "
                        f"chaos={chaos[3]}")
    if replays < 1:
        problems.append("no server-side replay was exercised — the kill "
                        "left no in-doubt pushes (bad kill placement?)")

    print(f"[drill] chaos run: {t_chaos:.1f}s "
          f"(+{t_chaos - t_ref:.1f}s recovery overhead), "
          f"restarts={[e[2] for e in chaos[4]]}, "
          f"in-doubt replays deduped={int(replays)}")
    if problems:
        print("[drill] FAILED:")
        for p in problems:
            print(f"  - {p}")
        return 1
    print("[drill] OK: SIGKILL at a mid-epoch step was a non-event — "
          "params and per-server applied counters bitwise-equal")
    return 0


# ----------------------------------------------------------- self_check

def self_check():
    """framework_lint cross-check: flag defaults <-> this drill's knobs
    <-> docs/fault_tolerance.md <-> the chaos marker on the kill tests.
    Returns a list of violations."""
    problems = []
    from paddle_tpu.core import flags as _flags
    for name, want in ELASTIC_FLAG_DEFAULTS.items():
        defn = _flags._DEFS.get(name)
        if defn is None:
            problems.append(f"elastic_drill: flag {name} is no longer "
                            "defined in core/flags.py")
            continue
        if defn[1] != want:
            problems.append(
                f"elastic_drill: {name} default drifted "
                f"({defn[1]!r} != {want!r}) — update "
                "ELASTIC_FLAG_DEFAULTS and docs/fault_tolerance.md")
    doc_path = os.path.join(REPO, "docs", "fault_tolerance.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return problems + [f"elastic_drill: cannot read {doc_path}: {e}"]
    for name in ELASTIC_FLAG_DEFAULTS:
        if name not in doc:
            problems.append(f"elastic_drill: flag {name} is not "
                            "documented in docs/fault_tolerance.md")
    for token in ("elastic_drill", "Trainer recovery", "manifest"):
        if token.lower() not in doc.lower():
            problems.append(
                f"elastic_drill: docs/fault_tolerance.md no longer "
                f"mentions {token!r} — the trainer-recovery section "
                "must document the drill, the manifest format, and the "
                "supervisor")
    test_path = os.path.join(REPO, "tests", "test_elastic_resume.py")
    try:
        with open(test_path) as f:
            test_src = f.read()
    except OSError:
        problems.append("elastic_drill: tests/test_elastic_resume.py is "
                        "missing — the SIGKILL recovery proof must stay "
                        "tier-1")
        return problems
    if "pytest.mark.chaos" not in test_src:
        problems.append("elastic_drill: tests/test_elastic_resume.py "
                        "lost its `chaos` marker — tier-1 must run the "
                        "kill tests deterministically")
    return problems


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "trainer":
        return run_trainer()
    if argv and argv[0] == "self_check":
        problems = self_check()
        for p in problems:
            print(p)
        print("elastic_drill self_check: "
              + ("clean" if not problems else f"{len(problems)} issue(s)"))
        return 1 if problems else 0
    return run_drill(argv[0] if argv else None)


if __name__ == "__main__":
    sys.exit(main())
