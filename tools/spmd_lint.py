"""SPMD sharding lint — CLI front-end for static/spmd_analyzer.py.

Builds the GPT tensor-parallel workload (BASELINE config-5 territory) as
a static Program under an ABSTRACT mesh ({axis: size} — no TPUs or
spoofed devices needed, so a pod layout lints from any dev box), derives
PartitionSpecs from the sharding-rule name patterns, and prints the
analyzer's report: the implied collective table, bytes/step, the
per-device HBM estimate vs the replicated baseline, the pipeline-wire
cost when --pp is given, and every diagnostic. Exit 1 on findings.

  python tools/spmd_lint.py                    # tiny GPT, tp=2: clean
  python tools/spmd_lint.py --tp 4 --layers 12 --hidden 768 --heads 12
  python tools/spmd_lint.py --inject unbound-axis   # demo a finding

tests/test_spmd_lint.py runs `self_check()` in tier-1 (the
framework_lint.py cross-check list also pulls it in), so a propagation
rule that stops resolving the TP golden path breaks the build.
"""
from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

INJECTIONS = ("unbound-axis", "non-divisible", "duplicate-axis",
              "spec-rank", "cross-tier")


def build_gpt_program(layers=2, hidden=64, heads=2, vocab=1024, batch=2,
                      seq=16, name="spmd_lint_gpt"):
    """Trace the GPT forward statically (the shared golden workload —
    tools/spmd_plan.py plans the same program this lint prices).
    Returns (program, net, logits_var); restores the caller's mode."""
    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.text.models.gpt import GPT, GPTConfig

    was_static = static.in_static_mode()
    paddle.enable_static()
    try:
        main = static.Program(name)
        with static.program_guard(main):
            ids = static.data("input_ids", [batch, seq], "int64")
            net = GPT(GPTConfig(vocab_size=vocab, hidden_size=hidden,
                                num_layers=layers, num_heads=heads,
                                intermediate_size=4 * hidden,
                                max_seq_len=max(seq, 8)))
            logits = net(ids)
        main._jit_fetch_vars = [logits]
        return main, net, logits
    finally:
        if not was_static:
            paddle.disable_static()


class _AvalView:
    """Persistable stand-in carrying a DIFFERENT aval. The --inject
    non-divisible seam used to overwrite the real Variable's aval in
    place — corrupting the net and program for every later
    `build_report` in the same process; the view (on a cloned Program)
    leaves the original untouched."""

    def __init__(self, pv, aval):
        self.name = pv.name
        self.scope_name = pv.scope_name
        self.aval = aval


def build_report(tp=2, dp=1, layers=2, hidden=64, heads=2, vocab=1024,
                 batch=2, seq=16, inject=None):
    """Trace the GPT forward statically and analyze it. Returns
    (report, program, logits_var)."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed import sharding
    from paddle_tpu.static import spmd_analyzer as spmd

    main, net, logits = build_gpt_program(layers=layers, hidden=hidden,
                                          heads=heads, vocab=vocab,
                                          batch=batch, seq=seq)
    mesh = {}
    if dp > 1:
        mesh["dp"] = dp
    if tp > 1:
        mesh["tp"] = tp
    specs = sharding.named_param_specs(net, mesh)
    if inject:
        # demo/self-test seams: corrupt ONE spec the named way
        name = next(n for n in specs
                    if n == net.wte.weight.scope_name)
        specs[name] = {
            "unbound-axis": P("mp", None),
            "duplicate-axis": P("tp", "tp"),
            "non-divisible": None,  # handled below via odd vocab
            "spec-rank": P("tp", None, "tp"),
            # a persistable sharded over the slow DCN axis: the embedding
            # gather's all-reduce then rides the inter-pod link every
            # step — the layout mistake the topology cost model exists
            # to catch (model parallelism must stay intra-pod)
            "cross-tier": P("pod", None),
        }[inject]
        if inject == "cross-tier":
            mesh["pod"] = {"size": 2, "tier": "dcn"}
        if inject == "non-divisible":
            # a vocab the tp axis cannot divide — swapped in as a view
            # on a CLONED program; the real Variable keeps its aval
            import jax
            main = main.clone()
            pv = main.persistable_vars[name]
            main.persistable_vars[name] = _AvalView(
                pv, jax.ShapeDtypeStruct(
                    (pv.aval.shape[0] + 1, pv.aval.shape[1]),
                    pv.aval.dtype))
            specs[name] = P("tp", None)
    data_specs = {"input_ids": P("dp")} if dp > 1 else None
    report = spmd.analyze_program(main, mesh=mesh, param_specs=specs,
                                  data_specs=data_specs)
    return report, main, logits


def self_check():
    """Violation strings for framework_lint's cross-check registry: the
    golden TP config must resolve with zero diagnostics and exactly the
    expected collective set (one all-reduce per row-parallel projection
    plus the vocab-parallel embedding gather)."""
    layers = 2
    try:
        report, _, logits = build_report(tp=2, layers=layers)
    except Exception as e:  # noqa: BLE001 - a lint must not crash the gate
        return [f"spmd_lint self-check failed to build/analyze: {e!r}"]
    problems = [f"spmd_lint golden TP config: {d}"
                for d in report.diagnostics]
    ar = [c for c in report.collectives if c.kind == "all_reduce"]
    want = 2 * layers + 1
    if len(ar) != want:
        problems.append(
            f"spmd_lint golden TP config: expected {want} all-reduces "
            f"(2/block + vocab-parallel embedding), analyzer found "
            f"{len(ar)}")
    if any(c.axis != "tp" for c in ar):
        problems.append("spmd_lint golden TP config: a collective left "
                        "the tp axis")
    if report.spec_of(logits)[-1] != ("tp",):
        problems.append("spmd_lint golden TP config: logits lost the "
                        "vocab (column-parallel) sharding")
    return problems


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="static SPMD sharding lint (collectives, per-device "
                    "HBM, diagnostics) for the GPT TP workload")
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1,
                    help="report the pipeline ppermute wire cost for this "
                    "many stages (schedule accounting only)")
    ap.add_argument("--micro", type=int, default=8,
                    help="pipeline microbatches (with --pp)")
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seq", type=int, default=16)
    ap.add_argument("--inject", choices=INJECTIONS, default=None,
                    help="corrupt one spec to demo the named diagnostic")
    args = ap.parse_args(argv)

    report, _, _ = build_report(
        tp=args.tp, dp=args.dp, layers=args.layers, hidden=args.hidden,
        heads=args.heads, vocab=args.vocab, batch=args.batch,
        seq=args.seq, inject=args.inject)
    report.publish()
    print(report.render())
    if args.pp > 1:
        from paddle_tpu.distributed.pipeline import schedule_collectives
        import numpy as np
        hidden_bytes = (args.batch // max(args.dp, 1)) * args.seq \
            * args.hidden * np.dtype("float32").itemsize // max(args.micro, 1)
        pc = schedule_collectives(args.micro, args.pp, hidden_bytes)
        print(f"pipeline wire cost ({args.pp} stages, {args.micro} "
              f"microbatches): {pc['count']} ppermute ticks x "
              f"{pc['bytes_per_tick']} B = {pc['total_bytes']} B/step "
              "(forward)")
    return 1 if report.diagnostics else 0


if __name__ == "__main__":
    sys.exit(main())
