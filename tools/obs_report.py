"""Observability report: render a flight-recorder dump (or a live run).

Turns the always-on telemetry (core/trace.py span ring + core/monitor
typed metrics) into the four answers an operator actually asks after a
failed or slow run:

  1. per-step TIMELINE — dispatch / retire / materialize spans of the
     async pipeline, with durations and the thread that ran each;
  2. HOST-OVERHEAD breakdown — aggregate span table (the profiler
     summary, but from the flight recorder, so it works post-mortem);
  3. PS HEALTH — retries / reconnects / deadline-exceeded / replays /
     bad frames, plus RPC latency histogram when present;
  4. PALLAS fallback rates — per-kernel hit / fallback / gate-reject
     with reasons.

Usage:
  python tools/obs_report.py DUMP.json          # render a dump
  python tools/obs_report.py --live             # snapshot this process
  python tools/obs_report.py DUMP.json --trace out.json
                                # also convert the dump's spans to a
                                # Chrome trace (chrome://tracing)
  python tools/obs_report.py --incident incident_<id>.json
                                # render a MERGED incident dump from the
                                # telemetry hub: alert + member tables,
                                # stitched cross-process trace chains,
                                # then each member's full report
                                # (--trace writes the merged cluster
                                # timeline with per-process lanes)

`self_check()` is registered in tools/framework_lint.py TOOL_CROSS_CHECKS
so tier-1 pins the three encodings of the observability config against
each other: the flight-recorder dump schema this renderer expects, the
core flag defaults (ring/series sizes), and bench.py's per-mode metrics
snapshot emission.
"""
from __future__ import annotations

import json
import os
import sys
from collections import defaultdict

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# canonical observability config: the flag DEFAULTS (core/flags.py) must
# match, and the dump schema version must match the recorder's
OBS_CFG = {"ring": 4096, "series": 256, "schema": 2}

# dump keys this renderer reads; self_check pins them against
# flight_recorder.SCHEMA_KEYS so the two cannot drift.  Schema v2 adds
# the cluster-identity fields (incident_id/role/peer_members); render()
# only prints them when present, so committed v1 dumps render unchanged
# (tests/fixtures/obsdump_v1.json pins that).
EXPECTED_KEYS = ("schema", "reason", "time", "pid", "argv", "exception",
                 "spans", "metrics", "flags", "env", "extra",
                 "incident_id", "role", "peer_members")

# merged-incident files (telemetry hub) the --incident mode reads;
# pinned against core.telemetry.INCIDENT_SCHEMA in self_check
INCIDENT_SCHEMA = 1

_STEP_SPANS = ("pipeline/dispatch", "pipeline/dispatch_scan",
               "pipeline/retire", "pipeline/materialize")


def load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def live_record() -> dict:
    """A dump-shaped record of the CURRENT process (no file involved)."""
    from paddle_tpu.core import flight_recorder
    return flight_recorder.record("live")


# -- sections ----------------------------------------------------------------

def _fmt_table(headers, rows):
    if not rows:
        return "  (none)"
    widths = [max(len(str(h)), *(len(str(r[i])) for r in rows))
              for i, h in enumerate(headers)]
    out = ["  " + "  ".join(f"{h:<{w}}" for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  " + "  ".join(f"{str(c):<{w}}"
                                    for c, w in zip(r, widths)))
    return "\n".join(out)


def _steps_of(span):
    a = span.get("attrs", {})
    if "step" in a:
        return [a["step"]]
    if "step_first" in a:
        return list(range(int(a["step_first"]), int(a["step_last"]) + 1))
    return []


def step_timeline(spans) -> str:
    """Rows: step -> when each pipeline phase touched it, on which
    thread, how long."""
    per_step = defaultdict(dict)
    threads = defaultdict(set)
    for sp in spans:
        name = sp.get("name")
        if name not in _STEP_SPANS:
            continue
        phase = {"pipeline/dispatch": "dispatch",
                 "pipeline/dispatch_scan": "dispatch",
                 "pipeline/retire": "retire",
                 "pipeline/materialize": "materialize"}[name]
        for step in _steps_of(sp):
            cur = per_step[step].get(phase)
            if cur is None or sp["ts_us"] < cur["ts_us"]:
                per_step[step][phase] = sp
            threads[step].add(sp.get("thread"))
    rows = []
    for step in sorted(per_step):
        phases = per_step[step]
        row = [step]
        for ph in ("dispatch", "retire", "materialize"):
            sp = phases.get(ph)
            row.append("-" if sp is None
                       else f"{sp['ts_us'] / 1e3:.2f}+"
                            f"{sp['dur_us'] / 1e3:.2f}ms")
        err = next((p["attrs"]["error"] for p in phases.values()
                    if p.get("attrs", {}).get("error")), "")
        row.append(err)
        row.append(len([t for t in threads[step] if t]))
        rows.append(row)
    return _fmt_table(
        ["step", "dispatch", "retire", "materialize", "error", "threads"],
        rows)


def host_breakdown(spans) -> str:
    agg = defaultdict(lambda: [0, 0.0, 0.0])  # calls, total_ms, max_ms
    for sp in spans:
        ms = sp.get("dur_us", 0) / 1e3
        a = agg[sp.get("name", "?")]
        a[0] += 1
        a[1] += ms
        a[2] = max(a[2], ms)
    rows = [[name, n, f"{tot:.3f}", f"{tot / n:.3f}", f"{mx:.3f}"]
            for name, (n, tot, mx) in
            sorted(agg.items(), key=lambda kv: -kv[1][1])]
    return _fmt_table(["span", "calls", "total_ms", "avg_ms", "max_ms"],
                      rows)


def ps_health(metrics) -> str:
    values = metrics.get("values", {})
    rows = [[k, v] for k, v in sorted(values.items())
            if k.startswith(("ps.rpc.", "ps.communicator."))]
    out = [_fmt_table(["counter", "value"], rows)]
    lat = metrics.get("histograms", {}).get("ps.rpc/latency_ms")
    if lat:
        out.append(f"  rpc latency: n={lat['count']} "
                   f"avg={lat['avg']:.3f}ms min={lat['min']:.3f}ms "
                   f"max={lat['max']:.3f}ms")
    return "\n".join(out)


def pallas_rates(metrics) -> str:
    """Per-kernel engagement: pallas.hit.K / pallas.fallback.K.reason /
    pallas.gate_reject.K.reason -> hit/fallback/reject counts + rate."""
    per = defaultdict(lambda: {"hit": 0.0, "fallback": 0.0,
                               "gate_reject": 0.0, "reasons": []})
    for name, v in metrics.get("values", {}).items():
        if not name.startswith("pallas."):
            continue
        parts = name.split(".")
        kind = parts[1]
        if kind == "hit" and len(parts) >= 3:
            per[parts[2]]["hit"] += v
        elif kind in ("fallback", "gate_reject") and len(parts) >= 4:
            per[parts[2]][kind] += v
            per[parts[2]]["reasons"].append(
                f"{kind}:{'.'.join(parts[3:])}={int(v)}")
    rows = []
    for k in sorted(per):
        d = per[k]
        total = d["hit"] + d["fallback"]
        rate = (d["fallback"] / total) if total else 0.0
        rows.append([k, int(d["hit"]), int(d["fallback"]),
                     int(d["gate_reject"]), f"{rate:.1%}",
                     " ".join(d["reasons"])])
    return _fmt_table(
        ["kernel", "hits", "fallbacks", "gate_rejects", "fallback_rate",
         "detail"], rows)


# gauge/counter names the serving section renders; self_check pins them
# against inference/serving.py GAUGES/COUNTERS so the two cannot drift
SERVE_GAUGES = ("serve.queue_depth", "serve.active_slots",
                "serve.kv_pool_used_blocks", "serve.kv_pool_free_blocks",
                "serve.model_version")
SERVE_COUNTERS = ("serve.preempted", "serve.tokens_generated",
                  "serve.requests_completed", "serve.requests_errored",
                  "serve.hot_swaps", "serve.completion_log_errors",
                  "serve.backpressure_waits")
_SERVE_SPANS = ("serve/admit", "serve/prefill", "serve/decode_step",
                "serve/retire", "serve/evict", "serve/hot_swap")


def serving_section(metrics, spans) -> str:
    """Continuous-batching serve tier: pool/queue gauges, stream
    counters, TTFT/per-token latency histograms, and the per-phase span
    table (admit/prefill/decode_step/retire/evict)."""
    values = metrics.get("values", {})
    rows = [[k, values[k]] for k in SERVE_GAUGES + SERVE_COUNTERS
            if k in values]
    out = [_fmt_table(["metric", "value"], rows)]
    for hname, label in (("serve/ttft_ms", "ttft"),
                         ("serve/token_ms", "per-token")):
        h = metrics.get("histograms", {}).get(hname)
        if h:
            out.append(f"  {label}: n={h['count']} avg={h['avg']:.3f}ms "
                       f"min={h['min']:.3f}ms max={h['max']:.3f}ms")
    agg = defaultdict(lambda: [0, 0.0])
    for sp in spans:
        if sp.get("name") in _SERVE_SPANS:
            a = agg[sp["name"]]
            a[0] += 1
            a[1] += sp.get("dur_us", 0) / 1e3
    if agg:
        out.append(_fmt_table(
            ["phase", "calls", "total_ms"],
            [[n, c, f"{t:.3f}"] for n, (c, t) in sorted(agg.items())]))
    return "\n".join(out)


def render(dump: dict) -> str:
    out = []
    exc = dump.get("exception")
    out.append("== flight-recorder dump "
               f"(schema {dump.get('schema')}) ==")
    out.append(f"  reason: {dump.get('reason')}  pid: {dump.get('pid')}")
    # schema-2 cluster identity: only printed when present, so v1 dumps
    # (and solo v2 dumps) render byte-identically to before
    if dump.get("role"):
        peers = dump.get("peer_members") or []
        out.append(f"  role: {dump['role']}"
                   + (f"  peers: {', '.join(str(p) for p in peers)}"
                      if peers else ""))
    if dump.get("incident_id"):
        out.append(f"  incident: {dump['incident_id']}")
    if exc:
        out.append(f"  exception: {exc.get('type')}: {exc.get('message')}")
    extra = dump.get("extra") or {}
    if extra:
        out.append(f"  extra: {json.dumps(extra, default=str)}")
    spans = dump.get("spans", [])
    metrics = dump.get("metrics", {})
    out.append(f"\n== step timeline ({len(spans)} spans recorded) ==")
    out.append(step_timeline(spans))
    out.append("\n== host overhead ==")
    out.append(host_breakdown(spans))
    out.append("\n== ps health ==")
    out.append(ps_health(metrics))
    out.append("\n== pallas kernels ==")
    out.append(pallas_rates(metrics))
    out.append("\n== serving ==")
    out.append(serving_section(metrics, spans))
    return "\n".join(out)


def render_incident(inc: dict) -> str:
    """A merged incident dump from the telemetry hub: the cluster-level
    story first (alerts, members, stitched cross-process trace chains),
    then every member's full per-process report."""
    from paddle_tpu.core.telemetry import stitch_incident
    out = []
    out.append(f"== incident {inc.get('incident_id')} "
               f"(schema {inc.get('schema')}) ==")
    out.append(f"  reason: {inc.get('reason')}  time: {inc.get('time')}")
    trig = inc.get("triggers") or []
    if trig:
        out.append("  triggers: "
                   + "; ".join(json.dumps(t, default=str, sort_keys=True)
                               for t in trig))
    alerts = inc.get("alerts") or []
    out.append(f"\n== slo alerts ({len(alerts)}) ==")
    out.append(_fmt_table(
        ["slo", "metric", "burn_fast", "burn_slow", "bad/total"],
        [[a.get("slo"), a.get("metric"),
          f"{(a.get('burn') or {}).get('fast', 0.0):.2f}",
          f"{(a.get('burn') or {}).get('slow', 0.0):.2f}",
          f"{a.get('bad')}/{a.get('total')}"] for a in alerts]))
    members = inc.get("members") or {}
    out.append(f"\n== members ({len(members)}) ==")
    out.append(_fmt_table(
        ["member", "role", "pid", "reason", "spans"],
        [[m, (r or {}).get("role", ""), (r or {}).get("pid"),
          (r or {}).get("reason"), len((r or {}).get("spans") or ())]
         for m, r in sorted(members.items())]))
    chains = stitch_incident(inc)
    out.append(f"\n== cross-process trace chains ({len(chains)}) ==")
    rows = []
    for c in chains:
        hops = " -> ".join(f"{r or m}({p})" for m, r, p in
                           zip(c["members"], c["roles"], c["pids"]))
        rows.append([c["trace_id"], hops, c["spans"],
                     " ".join(c["span_names"][:6])])
    out.append(_fmt_table(["trace", "path", "spans", "span_names"], rows))
    for m, record in sorted(members.items()):
        out.append(f"\n{'=' * 12} member {m} {'=' * 12}")
        out.append(render(record or {}))
    return "\n".join(out)


def incident_to_chrome_trace(inc: dict, path: str):
    """Merged cluster timeline: one Chrome-trace lane per member process,
    so a client->primary->backup incident reads as one picture."""
    from paddle_tpu.core import trace as _trace
    events = []
    for m, record in sorted((inc.get("members") or {}).items()):
        pid = (record or {}).get("pid", 0)
        role = (record or {}).get("role", "")
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"{role or 'member'} {m}"}})
        events.extend(_trace.to_chrome_events(
            (record or {}).get("spans") or [], pid=pid))
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path


def dump_to_chrome_trace(dump: dict, path: str):
    """Convert a dump's serialized spans into a Chrome trace file, via
    the one encoder in core/trace.py (span_dict records are accepted
    directly, so the slice/flow/instant/thread-name treatment cannot
    drift from live exports)."""
    from paddle_tpu.core import trace as _trace
    events = _trace.to_chrome_events(dump.get("spans", []),
                                     pid=dump.get("pid", 0))
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return path


# -- framework_lint cross-check ---------------------------------------------

def self_check():
    problems = []
    try:
        from paddle_tpu.core import flight_recorder, monitor
        from paddle_tpu.core import flags as _flags
    except Exception as e:
        return [f"obs_report: paddle_tpu import failed: {e!r}"]
    # dump schema <-> renderer expectations
    if tuple(flight_recorder.SCHEMA_KEYS) != EXPECTED_KEYS:
        problems.append(
            "obs_report: flight_recorder.SCHEMA_KEYS "
            f"{flight_recorder.SCHEMA_KEYS} != renderer EXPECTED_KEYS "
            f"{EXPECTED_KEYS} — update both together")
    if flight_recorder.SCHEMA_VERSION != OBS_CFG["schema"]:
        problems.append(
            f"obs_report: dump schema v{flight_recorder.SCHEMA_VERSION} "
            f"!= renderer v{OBS_CFG['schema']}")
    # merged-incident files (--incident) <-> the hub's writer
    try:
        from paddle_tpu.core import telemetry as _telemetry
        if _telemetry.INCIDENT_SCHEMA != INCIDENT_SCHEMA:
            problems.append(
                f"obs_report: telemetry.INCIDENT_SCHEMA "
                f"{_telemetry.INCIDENT_SCHEMA} != renderer "
                f"{INCIDENT_SCHEMA} — update both together")
    except Exception as e:
        problems.append(
            f"obs_report: cannot cross-check telemetry incident "
            f"schema: {e!r}")
    # flag DECLARED defaults (not live values — a test may have set them)
    defs = _flags._DEFS
    for name, want in (("FLAGS_trace_ring_size", OBS_CFG["ring"]),
                       ("FLAGS_monitor_series_len", OBS_CFG["series"])):
        if name not in defs:
            problems.append(f"obs_report: flag {name} is gone but the "
                            "tracer/monitor depend on it")
        elif int(defs[name][1]) != want:
            problems.append(
                f"obs_report: flag {name} default {defs[name][1]} != "
                f"OBS_CFG {want} — update the canonical config")
    # serving section <-> the serve loop's published names
    try:
        from paddle_tpu.inference import serving
        if tuple(serving.GAUGES) != SERVE_GAUGES:
            problems.append(
                f"obs_report: serving.GAUGES {serving.GAUGES} != "
                f"renderer SERVE_GAUGES {SERVE_GAUGES} — update both")
        if tuple(serving.COUNTERS) != SERVE_COUNTERS:
            problems.append(
                f"obs_report: serving.COUNTERS {serving.COUNTERS} != "
                f"renderer SERVE_COUNTERS {SERVE_COUNTERS}")
    except Exception as e:
        problems.append(
            f"obs_report: cannot cross-check serving gauges: {e!r}")
    # monitor export surface the dump format relies on
    for fn in ("snapshot", "export_jsonl", "prometheus_text", "observe"):
        if not callable(getattr(monitor, fn, None)):
            problems.append(f"obs_report: core.monitor.{fn}() is gone "
                            "but the dump/report format depends on it")
    # bench must snapshot the counters per mode (BENCH_*.json carries
    # them); pin the emission the same way pipeline_lint pins env vars
    with open(os.path.join(REPO, "bench.py")) as f:
        src = f.read()
    if "metrics_snapshot" not in src or "monitor.snapshot" not in src:
        problems.append(
            "obs_report: bench.py no longer emits the per-mode "
            "metrics_snapshot line (monitor.snapshot) — BENCH_*.json "
            "would lose the counters")
    return problems


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-check" in argv:
        problems = self_check()
        for p in problems:
            print(p)
        return 1 if problems else 0
    trace_out = None
    if "--trace" in argv:
        i = argv.index("--trace")
        trace_out = argv[i + 1]
        del argv[i:i + 2]
    if "--incident" in argv:
        i = argv.index("--incident")
        inc = load(argv[i + 1])
        print(render_incident(inc))
        if trace_out:
            incident_to_chrome_trace(inc, trace_out)
            print(f"\nchrome trace written to {trace_out}")
        return 0
    if "--live" in argv:
        dump = live_record()
    elif argv:
        dump = load(argv[0])
    else:
        print(__doc__)
        return 2
    print(render(dump))
    if trace_out:
        dump_to_chrome_trace(dump, trace_out)
        print(f"\nchrome trace written to {trace_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
