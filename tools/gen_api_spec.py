"""API-surface snapshot (reference tools/ check scripts +
paddle/fluid/API.spec: every public API recorded with its signature, so
surface changes are deliberate and reviewed).

Usage:
  python tools/gen_api_spec.py            # print current spec
  python tools/gen_api_spec.py --update   # rewrite API.spec
The test suite diffs the live surface against the committed API.spec.
"""
from __future__ import annotations

import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))  # runnable as `python tools/gen_api_spec.py`

NAMESPACES = [
    "paddle_tpu", "paddle_tpu.nn", "paddle_tpu.nn.functional",
    "paddle_tpu.nn.utils",
    "paddle_tpu.optimizer", "paddle_tpu.optimizer.lr", "paddle_tpu.static",
    "paddle_tpu.static.nn", "paddle_tpu.distributed",
    "paddle_tpu.distributed.fleet", "paddle_tpu.amp", "paddle_tpu.metric",
    "paddle_tpu.io", "paddle_tpu.jit", "paddle_tpu.inference",
    "paddle_tpu.profiler", "paddle_tpu.memory", "paddle_tpu.quantization",
    "paddle_tpu.distribution", "paddle_tpu.incubate.checkpoint",
    "paddle_tpu.vision.ops", "paddle_tpu.utils", "paddle_tpu.callbacks",
    "paddle_tpu.onnx", "paddle_tpu.reader", "paddle_tpu.traffic",
]


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"


def _resolve(ns):
    """Import the namespace; object-valued namespaces (static.nn is an
    instance) resolve by getattr from the parent module."""
    import importlib
    try:
        return importlib.import_module(ns)
    except ModuleNotFoundError:
        parent, _, leaf = ns.rpartition(".")
        return getattr(importlib.import_module(parent), leaf)


def collect():
    lines = []
    for ns in NAMESPACES:
        mod = _resolve(ns)
        names = getattr(mod, "__all__", None)
        if names is None:
            names = [n for n in dir(mod) if not n.startswith("_")]
        # PEP-562 lazy attributes are invisible to dir() until first touch,
        # which would make the snapshot depend on import order; modules
        # declare them in __all_lazy__ so the surface is deterministic.
        names = list(names) + list(getattr(mod, "__all_lazy__", ()))
        lazy = set(getattr(mod, "__all_lazy__", ()))
        for name in sorted(set(names)):
            try:
                obj = getattr(mod, name)
            except (AttributeError, ImportError):
                lines.append(f"{ns}.{name} MISSING")
                continue
            if inspect.ismodule(obj):
                if name in lazy:
                    # a declared lazy NAME resolving to a module means a
                    # submodule shadowed the public object — surface it
                    lines.append(f"{ns}.{name} MISSING")
                continue
            if inspect.isclass(obj):
                lines.append(f"{ns}.{name} class{_sig(obj)}")
            elif callable(obj):
                lines.append(f"{ns}.{name} def{_sig(obj)}")
            else:
                lines.append(f"{ns}.{name} value:{type(obj).__name__}")
    return "\n".join(lines) + "\n"


def main():
    spec = collect()
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "API.spec")
    if "--update" in sys.argv:
        with open(path, "w") as f:
            f.write(spec)
        print(f"wrote {path} ({spec.count(chr(10))} entries)")
    else:
        sys.stdout.write(spec)


if __name__ == "__main__":
    main()
