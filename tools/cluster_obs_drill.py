"""Cluster observability drill: the telemetry plane's end-to-end proof.

Spawns a REAL multi-process fleet — 3 replicated PS shard servers (1
backup each) and a serve+online-train client, each its own process with
its own monitor registry and trace ring — around an in-process
TelemetryHub (core/telemetry.py). Then breaks it on purpose:

  - every member ships metrics/spans to the hub through the
    exactly-once `(member, seq)`-keyed shipping protocol, the client
    under seeded RESET chaos and the servers under seeded reply-DROP
    chaos, so retries and replays are guaranteed to happen;
  - a scripted STALL at the serve decode beat inflates TTFT long enough
    to breach the declared `serve_ttft` SLO (and ONLY that SLO — a
    second, lenient error-budget spec rides along to prove silence);
  - the shard-0 primary is killed PERMANENTLY mid-run; the client rides
    the failover while its flight-recorder triggers (and the hub's own
    SLO breach) coalesce into ONE incident that every member joins,
    producing a single merged `incident_<id>.json`.

FAILS (exit 1) unless all of:
  - exactly ONE incident was opened, and its merged dump carries
    flight-recorder records (with spans) from >= 3 distinct processes;
  - >= 1 trace id in the merged dump crosses client -> primary ->
    backup (telemetry.stitch_incident finds a >=3-member chain with the
    client and two different servers on it);
  - the hub's counter totals are BITWISE equal to the sum of every
    member's final local monitor counters — exactly-once shipping held
    through resets, drops, reconnects and the primary kill;
  - the SLO alert stream is exactly the scripted breach: >= 1
    `serve_ttft` alert, zero alerts for anything else, and the scripted
    STALL actually fired.

Render the merged incident with
  python tools/obs_report.py --incident <dir>/incident_<id>.json

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/cluster_obs_drill.py

Env knobs (defaults are the CPU-valid tier-1 shape):
  CLUSTER_OBS_REQS=4        serve requests per round (2 rounds)
  CLUSTER_OBS_NEW=4         tokens generated per request
  CLUSTER_OBS_BATCH=2       records per training batch (divides REQS)
  CLUSTER_OBS_SEED=11       chaos seed
  CLUSTER_OBS_STALLS=6      scripted serve-beat STALL count
  CLUSTER_OBS_STALL_S=0.4   seconds per STALL (vs the 250ms SLO)
  CLUSTER_OBS_DIR=          incident/dump dir (default: a temp dir)

framework_lint TOOL_CROSS_CHECKS runs self_check() here: the
PADDLE_TELEMETRY_* / PADDLE_SLO_* flag defaults and the
docs/observability.md flag table must agree.
"""
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import numpy as np  # noqa: E402

N_SRV = 3
REQS = int(os.environ.get("CLUSTER_OBS_REQS", 4))
NEW = int(os.environ.get("CLUSTER_OBS_NEW", 4))
BATCH = int(os.environ.get("CLUSTER_OBS_BATCH", 2))
SEED = int(os.environ.get("CLUSTER_OBS_SEED", 11))
STALLS = int(os.environ.get("CLUSTER_OBS_STALLS", 6))
STALL_S = float(os.environ.get("CLUSTER_OBS_STALL_S", 0.4))

# the scripted breach: TTFT objective the STALL is sized to violate
TTFT_SLO_MS = 250.0
TTFT_OBJECTIVE = 0.05

# flag defaults the telemetry plane (and docs/observability.md's flag
# table) are written against; drift means the doc needs an update
TELEMETRY_FLAG_DEFAULTS = {
    "PADDLE_TELEMETRY_HUB": "",
    "PADDLE_TELEMETRY_FLUSH_S": 0.5,
    "PADDLE_TELEMETRY_SPAN_BUFFER": 2048,
    "PADDLE_TELEMETRY_INCIDENT_WINDOW_S": 10.0,
    "PADDLE_SLO_EVAL_S": 1.0,
    "PADDLE_SLO_FAST_WINDOW_S": 60.0,
    "PADDLE_SLO_SLOW_WINDOW_S": 300.0,
}

FAST = dict(timeout=2.0, max_retries=2, backoff_base=0.01,
            backoff_max=0.05, connect_retry_s=5.0)
HB = dict(heartbeat_s=0.1, heartbeat_timeout_s=0.7)


def _say(obj):
    sys.stdout.write(json.dumps(obj, default=str) + "\n")
    sys.stdout.flush()


def _read_cmd():
    line = sys.stdin.readline()
    if not line:
        return {"cmd": "stop"}          # parent died: shut down clean
    return json.loads(line)


def _final_counters():
    from paddle_tpu.core import monitor
    snap = monitor.snapshot(include_series=False)
    return {n: snap["values"][n] for n, t in snap["types"].items()
            if t == "counter"}


# --------------------------------------------------------------------------
# member processes
# --------------------------------------------------------------------------

def member_server(idx, hub_ep, dim):
    """One replicated PS shard server + telemetry shipper, driven over
    stdin/stdout by the drill parent."""
    from paddle_tpu.core import telemetry
    from paddle_tpu.distributed.ps import PSServer, ShardMap
    from paddle_tpu.testing import faults

    srv = PSServer("127.0.0.1:0", {"wte": {"type": "geo_sparse",
                                           "dim": dim, "init": "zeros"}})
    ep = srv.start()
    _say({"ep": ep})
    cmd = _read_cmd()                                 # {"cmd": "enable"}
    eps = cmd["eps"]
    smap = ShardMap.create(eps, n_backups=1)
    srv.enable_replication(shard_map=smap, peers=eps, n_backups=1,
                           rpc_opts=dict(FAST), **HB)
    _say({"enabled": True})
    _read_cmd()                                       # {"cmd": "arm"}
    # armed only once the fleet is settled and the client is warm:
    # bring-up races must not open the incident — the drill's incident
    # is the scripted mid-traffic breach, with every ring full of the
    # client<->primary<->backup traffic the stitcher needs
    shipper = telemetry.TelemetryShipper(
        hub_ep, member_id=f"server{idx}", role=f"server{idx}",
        peers=eps, flush_s=0.2).start()
    # seeded reply-DROP chaos: the applied-but-lost case replay exists
    # for, fired from the server side of every member's traffic
    inj = faults.FaultInjector(seed=100 + idx, p={faults.DROP: 0.02})
    faults.install(inj)
    _say({"ready": True})
    killed = False
    while True:
        cmd = _read_cmd()
        if cmd["cmd"] == "kill":
            faults.uninstall()
            srv.shutdown()                 # permanent: process survives
            killed = True                  # to drain + report
            _say({"ack": "kill"})
        elif cmd["cmd"] == "stop":
            break
    if not killed:
        faults.uninstall()
        srv.shutdown()
    drained = shipper.close(drain_timeout=20.0)
    _say({"stats": _final_counters(), "drained": drained,
          "dropped_replies": inj.fired(faults.DROP)})
    return 0


def member_client(eps, hub_ep):
    """The serve + online-train member: a tiny-GPT ServeLoop feeding a
    StreamingDataset feeding the continuous Downpour trainer, run under
    seeded RESET chaos plus the scripted serve-beat STALL, riding the
    shard-0 primary kill mid-run."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer, static
    from paddle_tpu.core import monitor, telemetry
    from paddle_tpu.dataset import StreamingDataset
    from paddle_tpu.distributed.ps import EmbeddingPrefetcher, PSClient
    from paddle_tpu.inference import ServeConfig, ServeLoop
    from paddle_tpu.testing import faults
    from paddle_tpu.text.models.gpt import GPT, GPTConfig
    from paddle_tpu.traffic import harness

    paddle.seed(0)
    cfg = GPTConfig.tiny()
    gpt = GPT(cfg)
    gpt.eval()
    vocab, dim = cfg.vocab_size, cfg.hidden_size
    target = np.random.RandomState(77).uniform(
        -0.5, 0.5, (vocab, dim)).astype(np.float32)

    def _collate(recs):
        ids = np.concatenate([np.asarray(r["prompt"] + r["tokens"],
                                         np.int64) for r in recs])
        return {"ids": ids, "target": target[ids]}

    ds = StreamingDataset(batch_size=BATCH, collate=_collate,
                          name="cluster_obs_drill")
    loop = ServeLoop(gpt, ServeConfig(max_active=4, kv_blocks=16,
                                      block_size=16, max_seq_len=64),
                     on_complete=ds.offer)

    paddle.enable_static()
    prog = static.Program("cluster_obs_drill")
    with static.program_guard(prog):
        ids_v = static.data("ids", [-1], "int64")
        tgt_v = static.data("target", [-1, dim], "float32")
        emb = nn.Embedding(vocab, dim)
        diff = emb(ids_v) - tgt_v
        loss = paddle.ops.mean(paddle.ops.sum(diff * diff, axis=-1))
        optimizer.SGD(learning_rate=0.25).minimize(loss)
    emb_name = emb.weight.scope_name
    exe = static.Executor()
    client_t = PSClient(eps, **FAST)
    window = harness.Window(ds)
    holder = {}
    state = None

    def serve_phase(k):
        rng = np.random.RandomState(1000 + k)
        prompts = [rng.randint(0, 48, 4).astype(np.int64)
                   for _ in range(REQS)]
        stats = harness.drive_serve(
            loop, harness.submissions_from_prompts(prompts, NEW),
            wait="idle+result", result_timeout_s=300.0)
        if stats.errors:      # parent records the crash as a violation
            raise RuntimeError("; ".join(stats.errors))

    def train_phase(n_batches):
        nonlocal state
        pf = EmbeddingPrefetcher(client_t, table="wte")
        ps_cfg = {"client": client_t, "mode": "online", "sync_every": 1,
                  "trainer_id": 7,
                  "sparse": [{"param": emb_name, "slot": "ids",
                              "table": "wte", "prefetcher": pf}],
                  "on_batch": lambda d: holder.update(drv=d)}
        if state is not None:
            ps_cfg["state"] = state
        exe.train_from_dataset(
            program=prog, dataset=window.take(n_batches),
            ps_config=ps_cfg,
            start_batch=ds.stats()["delivered_batches"])
        state = holder["drv"].online_state()
        try:
            pf.close()
        except Exception:
            pass

    # warmup OUTSIDE the measured window: XLA compiles (prefill bucket,
    # decode step, train step) would otherwise pollute the TTFT
    # histogram the SLO judges and the counters the hub totals
    serve_phase(99)
    train_phase(REQS // BATCH)
    monitor.reset()

    shipper = telemetry.TelemetryShipper(
        hub_ep, member_id="client", role="client", peers=eps,
        flush_s=0.2).start()
    _say({"ready": True})

    _read_cmd()                    # {"cmd": "go"}: the fleet is armed
    stall = faults.Fault("serve", "beat", faults.STALL, method="tick",
                         after=0, times=STALLS, delay=STALL_S)
    with faults.inject(stall, seed=SEED,
                       p={faults.RESET: 0.02}) as inj:
        # round A: the scripted STALL lands on the first measured beats,
        # so every round-A request's TTFT blows the 250ms objective
        serve_phase(0)
        train_phase(REQS // BATCH)
        _say({"phase_a": True})            # parent kills the primary now
        _read_cmd()                        # {"cmd": "go"}
        # round B: clean-latency traffic THROUGH the failover
        serve_phase(1)
        train_phase(REQS // BATCH)
        stall_fired = inj.fired(faults.STALL)
        reset_fired = inj.fired(faults.RESET)
    client_t.close()
    paddle.disable_static()
    drained = shipper.close(drain_timeout=20.0)
    _say({"stats": _final_counters(), "drained": drained,
          "stall_fired": stall_fired, "reset_fired": reset_fired})
    return 0


# --------------------------------------------------------------------------
# parent / orchestrator
# --------------------------------------------------------------------------

def _spawn(argv, dump_dir):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PADDLE_TPU_DUMP_DIR=dump_dir)
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)] + argv,
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        bufsize=1, env=env)


def _await(proc, key, timeout=300.0, label=""):
    """Read stdout lines until a JSON object with `key` appears."""
    deadline = time.monotonic() + timeout
    out = {}

    def _pump():
        while True:
            line = proc.stdout.readline()
            if not line:
                return
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if key in obj:
                out.update(obj)
                return

    t = threading.Thread(target=_pump, daemon=True)
    t.start()
    t.join(max(0.0, deadline - time.monotonic()))
    if key not in out:
        raise TimeoutError(
            f"cluster_obs_drill: {label or key} not reported within "
            f"{timeout}s (member exited: {proc.poll()})")
    return out


def _send(proc, obj):
    proc.stdin.write(json.dumps(obj) + "\n")
    proc.stdin.flush()


def run():
    from paddle_tpu.core import slo, telemetry

    dump_dir = os.environ.get("CLUSTER_OBS_DIR") or tempfile.mkdtemp(
        prefix="cluster_obs_")
    specs = [
        slo.SLOSpec("serve_ttft", "latency", "serve/ttft_ms",
                    objective=TTFT_OBJECTIVE, threshold_ms=TTFT_SLO_MS,
                    description="95% of TTFTs under 250ms"),
        # deliberately lenient: proves the engine stays silent on specs
        # the scripted fault does not violate
        slo.SLOSpec("ps_deadline_budget", "rate",
                    "ps.rpc.deadline_exceeded", objective=50.0,
                    description="under 50 deadline-exceeded per second"),
    ]
    hub = telemetry.TelemetryHub(
        specs=specs, dump_dir=dump_dir, fast_s=1.5, slow_s=6.0,
        eval_s=0.2, incident_window_s=90.0)
    violations = []
    servers = []
    client = None
    member_stats = {}
    chains = []
    inc = None
    # the embedding width is GPTConfig.tiny().hidden_size; resolve it
    # here once so every server builds its table with the right dim
    from paddle_tpu.text.models.gpt import GPTConfig
    dim = GPTConfig.tiny().hidden_size
    t0 = time.perf_counter()
    try:
        servers = [_spawn(["--member", f"server{i}", "--hub",
                           hub.endpoint, "--dim", str(dim)], dump_dir)
                   for i in range(N_SRV)]
        eps = [_await(p, "ep", label=f"server{i} endpoint")["ep"]
               for i, p in enumerate(servers)]
        for p in servers:
            _send(p, {"cmd": "enable", "eps": eps})
        for i, p in enumerate(servers):
            _await(p, "enabled", label=f"server{i} replication")
        print(f"# fleet up: {eps} (hub {hub.endpoint})", file=sys.stderr)

        client = _spawn(["--member", "client", "--hub", hub.endpoint,
                         "--eps", ",".join(eps)], dump_dir)
        _await(client, "ready", label="client warmup")
        # arm shippers + chaos only now: the incident must open on the
        # scripted breach, with warm rings behind every member record
        for p in servers:
            _send(p, {"cmd": "arm"})
        for i, p in enumerate(servers):
            _await(p, "ready", label=f"server{i} armed")
        _send(client, {"cmd": "go"})
        print("# client warm; round A (scripted STALL) begins",
              file=sys.stderr)
        _await(client, "phase_a", label="round A")
        print("# round A done; killing shard-0 primary", file=sys.stderr)
        _send(servers[0], {"cmd": "kill"})
        _await(servers[0], "ack", label="primary kill")
        _send(client, {"cmd": "go"})
        crep = _await(client, "stats", label="client finish")
        member_stats = {"client": crep}
        for i, p in enumerate(servers):
            _send(p, {"cmd": "stop"})
        for i, p in enumerate(servers):
            member_stats[f"server{i}"] = _await(
                p, "stats", label=f"server{i} finish")
        for p in [client] + servers:
            p.stdin.close()
            p.wait(timeout=60)
    except Exception as e:
        violations.append(f"drill run failed: {type(e).__name__}: {e}")
    finally:
        for p in [c for c in [client] + servers if c is not None]:
            if p.poll() is None:
                p.kill()

    snapshot = hub.snapshot()
    incidents = hub.incidents()
    hub.stop()

    if not violations:
        # ---- every member drained: the accounting below is closed ----
        for m, rep in member_stats.items():
            if not rep.get("drained"):
                violations.append(f"{m} failed to drain its shipper")

        # ---- exactly ONE incident, merged dump from >= 3 processes ----
        if len(incidents) != 1:
            violations.append(
                f"expected exactly 1 incident, got {len(incidents)}: "
                f"{[(i, v['reason']) for i, v in incidents.items()]}")
        inc_path = None
        if incidents:
            iid = next(iter(incidents))
            inc_path = os.path.join(dump_dir, f"incident_{iid}.json")
            try:
                with open(inc_path) as f:
                    inc = json.load(f)
            except OSError as e:
                violations.append(f"merged incident file missing: {e}")
        if inc is not None:
            with_spans = {m: r for m, r in inc["members"].items()
                          if (r or {}).get("spans")}
            pids = {r["pid"] for r in with_spans.values()}
            if len(pids) < 3:
                violations.append(
                    f"incident has span-bearing records from only "
                    f"{len(pids)} process(es): {sorted(with_spans)}")
            # ---- >= 1 trace id crossing client -> primary -> backup ----
            chains = telemetry.stitch_incident(inc)
            crossing = [
                c for c in chains
                if len(c["members"]) >= 3 and "client" in c["roles"]
                and len({r for r in c["roles"]
                         if r.startswith("server")}) >= 2]
            if not crossing:
                violations.append(
                    "no trace id crosses client -> primary -> backup "
                    f"(chains: {[(c['trace_id'], c['roles']) for c in chains[:5]]})")

        # ---- exactly-once: hub totals == sum of member finals ----
        expected = {}
        for m, rep in member_stats.items():
            for name, v in (rep.get("stats") or {}).items():
                expected[name] = expected.get(name, 0.0) + v
        hub_counters = snapshot["counters"]
        for name in sorted(set(expected) | set(hub_counters)):
            want = expected.get(name, 0.0)
            got = hub_counters.get(name, 0.0)
            if want != got:
                violations.append(
                    f"counter {name}: hub total {got!r} != member sum "
                    f"{want!r} — exactly-once shipping broken")

        # ---- the alert stream is exactly the scripted breach ----
        slos_fired = {a["slo"] for a in snapshot["alerts"]}
        if "serve_ttft" not in slos_fired:
            violations.append(
                "the scripted STALL did not breach serve_ttft "
                f"(alerts: {snapshot['alerts']})")
        if slos_fired - {"serve_ttft"}:
            violations.append(
                f"unscripted SLO(s) breached: "
                f"{sorted(slos_fired - {'serve_ttft'})}")
        if not member_stats.get("client", {}).get("stall_fired"):
            violations.append("the scripted serve-beat STALL never fired")

    report = {
        "tool": "tools/cluster_obs_drill.py",
        "servers": N_SRV,
        "hub": hub.endpoint,
        "incidents": len(incidents),
        "incident_members": sorted(
            next(iter(incidents.values()))["members"]) if incidents
        else [],
        "cross_process_chains": len(chains),
        "alerts": [a["slo"] for a in snapshot["alerts"]],
        "hub_counter_names": len(snapshot["counters"]),
        "stall_fired": member_stats.get("client", {}).get("stall_fired"),
        "reset_fired": member_stats.get("client", {}).get("reset_fired"),
        "dump_dir": dump_dir,
        "wall_s": round(time.perf_counter() - t0, 3),
        "violations": len(violations),
    }
    print(json.dumps(report, indent=1))
    for v in violations[:10]:
        print("VIOLATION:", v, file=sys.stderr)
    return 1 if violations else 0


# --------------------------------------------------------------------------
# framework_lint cross-check (TOOL_CROSS_CHECKS)
# --------------------------------------------------------------------------

def self_check():
    """Telemetry/SLO flag defaults <-> this drill's pins <-> the
    docs/observability.md flag table. Returns violations."""
    problems = []
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        from paddle_tpu.core import flags as _flags
    except Exception as e:  # pragma: no cover
        return [f"cluster_obs_drill: paddle_tpu import failed: {e!r}"]
    for name, want in TELEMETRY_FLAG_DEFAULTS.items():
        defn = _flags._DEFS.get(name)
        if defn is None:
            problems.append(f"cluster_obs_drill: flag {name} is no "
                            "longer defined in core/flags.py")
        elif defn[1] != want:
            problems.append(
                f"cluster_obs_drill: {name} default drifted "
                f"({defn[1]!r} != {want!r}) — update "
                "TELEMETRY_FLAG_DEFAULTS and docs/observability.md")
    doc_path = os.path.join(repo, "docs", "observability.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return problems + [
            f"cluster_obs_drill: cannot read {doc_path}: {e}"]
    for name in TELEMETRY_FLAG_DEFAULTS:
        if name not in doc:
            problems.append(f"cluster_obs_drill: flag {name} is not "
                            "documented in docs/observability.md")
    for token in ("cluster_obs_drill", "--incident",
                  "telemetry.dropped_batches"):
        if token not in doc:
            problems.append(
                f"cluster_obs_drill: docs/observability.md no longer "
                f"mentions `{token}`")
    # the hub's incident schema must match what obs_report renders
    try:
        from paddle_tpu.core import telemetry
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import obs_report
        if telemetry.INCIDENT_SCHEMA != obs_report.INCIDENT_SCHEMA:
            problems.append(
                "cluster_obs_drill: telemetry.INCIDENT_SCHEMA != "
                "obs_report.INCIDENT_SCHEMA — update both together")
    except Exception as e:  # pragma: no cover
        problems.append(
            f"cluster_obs_drill: incident schema cross-check failed: "
            f"{e!r}")
    with open(os.path.abspath(__file__)) as f:
        self_src = f.read()
    for token in ("harness.drive_serve", "harness.Window"):
        if token not in self_src:
            problems.append(f"cluster_obs_drill: the serve/window "
                            f"plumbing must come from "
                            f"paddle_tpu.traffic.harness (`{token}` "
                            f"missing)")
    return problems


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-check" in argv or "--self_check" in argv:
        problems = self_check()
        for p in problems:
            print(p)
        print("cluster_obs_drill self-check:",
              "clean" if not problems else f"{len(problems)} problem(s)")
        return 1 if problems else 0
    if "--member" in argv:
        member = argv[argv.index("--member") + 1]
        hub_ep = argv[argv.index("--hub") + 1]
        if member == "client":
            eps = argv[argv.index("--eps") + 1].split(",")
            return member_client(eps, hub_ep)
        dim = int(argv[argv.index("--dim") + 1])
        return member_server(int(member.replace("server", "")), hub_ep,
                             dim)
    return run()


if __name__ == "__main__":
    sys.exit(main())
