"""PS transport throughput measurement (VERDICT r04 item 9).

N worker threads x M rounds of pull_sparse + push_sparse_grad of
realistic batches against a local PSServer; reports rows/sec per op and
aggregate. Reference design point: distributed/communicator.cc (brpc,
millions of sparse rows/sec across a cluster); this measures our
pickle-frames-over-TCP transport on one host and records the number
in docs/ps_throughput.md so regressions are visible.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/ps_load_test.py

Modes (env):
  PS_LOAD_CHAOS=<seed>  throughput UNDER seeded resets + dropped replies
                        (the retry/replay path's overhead).
  PS_LOAD_FAILOVER=1    replicated-storage failover drill: a 3-server /
                        1-backup cluster under worker load, one primary
                        killed mid-run; reports promotion latency, the
                        ps.replica.* counters, and rows/sec through the
                        outage. Workers must finish with zero errors —
                        the live proof behind docs/fault_tolerance.md's
                        storage-tier section.
  PS_LOAD_SHARDED=1     sharded-embedding drill: workers train through
                        the FULL engine — batched deduped cross-shard
                        lookups, the tiered HeterPS LRU cache, and the
                        async prefetch stage — against a 3-shard-server
                        / 1-backup cluster, with one shard primary
                        killed mid-run. Reports per-shard rows/s, cache
                        hit rate, prefetch overlap ratio, and promotion
                        latency; zero worker errors required.

framework_lint TOOL_CROSS_CHECKS runs self_check() here: the
PADDLE_PS_REPLICA_*/PADDLE_PS_HEARTBEAT_*/PADDLE_PS_FAILOVER_* +
PADDLE_PS_{FANOUT,PREFETCH,HETER}* flag defaults, this tool's
failover/sharded-mode knobs, and docs/fault_tolerance.md must agree.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import numpy as np  # noqa: E402

from paddle_tpu.distributed.ps import PSClient, PSServer  # noqa: E402

VOCAB = 200_000
DIM = int(os.environ.get("PS_LOAD_DIM", 16))
WORKERS = int(os.environ.get("PS_LOAD_WORKERS", 4))
ROUNDS = int(os.environ.get("PS_LOAD_ROUNDS", 50))
BATCH_IDS = int(os.environ.get("PS_LOAD_BATCH", 2048))

# failover-drill knobs (PS_LOAD_FAILOVER mode); the heartbeat pair is
# deliberately faster than the PADDLE_PS_HEARTBEAT_* prod defaults —
# self_check() pins BOTH against docs/fault_tolerance.md
FAILOVER_SERVERS = int(os.environ.get("PS_LOAD_SERVERS", 3))
FAILOVER_HB_S = float(os.environ.get("PS_LOAD_HB_S", 0.1))
FAILOVER_HB_TIMEOUT_S = float(os.environ.get("PS_LOAD_HB_TIMEOUT_S", 0.7))

# sharded-embedding-drill cache bound (PS_LOAD_SHARDED mode): small
# enough that the random workload exercises LRU eviction + the host tier
SHARDED_CACHE_ROWS = int(os.environ.get("PS_LOAD_CACHE_ROWS", 8192))

# flag defaults this tool (and the docs flag table) are written against;
# drift here means docs/fault_tolerance.md + this header need an update
REPLICA_FLAG_DEFAULTS = {
    "PADDLE_PS_REPLICA_BACKUPS": 0,
    "PADDLE_PS_REPLICA_QUORUM": 0,
    "PADDLE_PS_REPLICA_DELTA_LOG": 512,
    "PADDLE_PS_HEARTBEAT_S": 0.5,
    "PADDLE_PS_HEARTBEAT_TIMEOUT_S": 3.0,
    "PADDLE_PS_FAILOVER_RETRIES": 8,
    "PADDLE_PS_FAILOVER_BACKOFF_S": 0.25,
    # sharded embedding engine (PS_LOAD_SHARDED drill)
    "PADDLE_PS_FANOUT_THREADS": 4,
    "PADDLE_PS_PREFETCH_DEPTH": 2,
    "PADDLE_PS_HETER_CACHE_ROWS": 65536,
    "PADDLE_PS_HETER_HOST_ROWS": 262144,
}


def run_worker(endpoints, wid, results):
    client = PSClient(endpoints)
    rng = np.random.RandomState(wid)
    pulled = pushed = 0
    round_ms = []
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        tr = time.perf_counter()
        ids = np.unique(rng.randint(0, VOCAB, BATCH_IDS).astype(np.int64))
        rows = client.pull_sparse("emb", ids)
        pulled += len(ids)
        grads = np.asarray(rows, np.float32) * 0 + 0.01
        client.push_sparse_grad("emb", ids, grads)
        pushed += len(ids)
        round_ms.append((time.perf_counter() - tr) * 1e3)
    dt = time.perf_counter() - t0
    results[wid] = (pulled, pushed, dt, round_ms)
    client.close()


def run_failover():
    """PS_LOAD_FAILOVER: kill-and-promote under load. Reports the
    promotion latency (kill -> ps.replica.promotions tick), replica
    counters, and aggregate rows/sec through the outage."""
    from paddle_tpu.core import monitor
    from paddle_tpu.distributed.ps import ShardMap

    spec = {"emb": {"type": "sparse", "dim": DIM, "optimizer": "sgd",
                    "lr": 0.1, "init": "zeros"}}
    servers = [PSServer("127.0.0.1:0", dict(spec))
               for _ in range(FAILOVER_SERVERS)]
    eps = [s.start() for s in servers]
    smap = ShardMap.create(eps, n_backups=1)
    fast = dict(timeout=5.0, max_retries=2, backoff_base=0.01,
                backoff_max=0.05)
    for s in servers:
        s.enable_replication(shard_map=smap, peers=eps, n_backups=1,
                             heartbeat_s=FAILOVER_HB_S,
                             heartbeat_timeout_s=FAILOVER_HB_TIMEOUT_S,
                             rpc_opts=dict(fast))

    errors = []
    results = {}

    def worker(wid):
        client = PSClient(eps, **fast)
        rng = np.random.RandomState(wid)
        pushed = 0
        t0 = time.perf_counter()
        try:
            for _ in range(ROUNDS):
                ids = np.unique(rng.randint(0, VOCAB, BATCH_IDS)
                                .astype(np.int64))
                rows = client.pull_sparse("emb", ids)
                client.push_sparse_grad(
                    "emb", ids, np.asarray(rows, np.float32) * 0 + 0.01)
                pushed += len(ids)
        except Exception as e:  # noqa: BLE001 — reported below
            errors.append(f"worker {wid}: {type(e).__name__}: {e}")
        results[wid] = (pushed, time.perf_counter() - t0)
        client.close()

    from paddle_tpu.traffic import harness
    pool = harness.run_worker_pool(worker, WORKERS, kill_after_s=0.5,
                                   on_kill=servers[0].shutdown)
    promote_latency = pool.promote_latency_s
    wall = pool.wall_s
    for s in servers[1:]:
        s.shutdown()

    total = sum(r[0] for r in results.values())
    replica = {k: int(v) for k, v in
               sorted(monitor.stats("ps.replica.").items())}
    print(f"failover drill: {FAILOVER_SERVERS} servers, 1 backup, "
          f"{WORKERS} workers x {ROUNDS} rounds, primary killed at 0.5s")
    print(f"promotion latency: "
          f"{'NONE RECORDED' if promote_latency is None else f'{promote_latency * 1000:.0f}ms'}"
          f" (heartbeat {FAILOVER_HB_S}s, deadline "
          f"{FAILOVER_HB_TIMEOUT_S}s)")
    print(f"rows pushed through the outage: {total:,} "
          f"({total / wall:,.0f} rows/sec aggregate)")
    print(f"replica counters: {replica}")
    if errors:
        print("worker errors:\n  " + "\n  ".join(errors))
        return 1
    if promote_latency is None:
        print("ERROR: no promotion was recorded")
        return 1
    print("all workers finished with zero errors")
    return 0


def run_sharded():
    """PS_LOAD_SHARDED: the full sharded-embedding engine under load +
    a kill-one-shard-primary drill. Workers pull through
    EmbeddingPrefetcher -> HeterPSCache -> PSClient's cross-shard
    fan-out and push merged grads back; shard 0's primary dies mid-run.
    Reports per-shard rows/s, cache hit rate, prefetch overlap ratio,
    promotion latency, and the replica counters."""
    from paddle_tpu.core import monitor
    from paddle_tpu.distributed.ps import (EmbeddingPrefetcher,
                                           HeterPSCache, ShardMap)

    spec = {"emb": {"type": "sparse", "dim": DIM, "optimizer": "sgd",
                    "lr": 0.1, "init": "uniform", "seed": 7}}
    servers = [PSServer("127.0.0.1:0", dict(spec))
               for _ in range(FAILOVER_SERVERS)]
    eps = [s.start() for s in servers]
    smap = ShardMap.create(eps, n_backups=1)
    fast = dict(timeout=5.0, max_retries=2, backoff_base=0.01,
                backoff_max=0.05)
    for s in servers:
        s.enable_replication(shard_map=smap, peers=eps, n_backups=1,
                             heartbeat_s=FAILOVER_HB_S,
                             heartbeat_timeout_s=FAILOVER_HB_TIMEOUT_S,
                             rpc_opts=dict(fast))

    errors = []
    results = {}

    def worker(wid):
        client = PSClient(eps, **fast)
        cache = HeterPSCache(client, "emb", DIM,
                             capacity=SHARDED_CACHE_ROWS)
        pf = EmbeddingPrefetcher(cache)
        rng = np.random.RandomState(wid)
        batches = [np.unique(rng.randint(0, VOCAB, BATCH_IDS)
                             .astype(np.int64)) for _ in range(ROUNDS)]
        pulled = 0
        # per-worker shard tally, merged after join — a shared
        # read-modify-write across worker threads would lose updates
        my_shard_rows = np.zeros(FAILOVER_SERVERS, np.int64)
        t0 = time.perf_counter()
        try:
            pf.prefetch(batches[0])
            for r in range(ROUNDS):
                ids = batches[r]
                rows = pf.get(ids)
                if r + 1 < ROUNDS:
                    pf.prefetch(batches[r + 1])
                pulled += len(ids)
                my_shard_rows += np.bincount(ids % FAILOVER_SERVERS,
                                             minlength=FAILOVER_SERVERS)
                pf.push_grad(ids, np.asarray(rows, np.float32) * 0 + 0.01)
        except Exception as e:  # noqa: BLE001 — reported below
            errors.append(f"worker {wid}: {type(e).__name__}: {e}")
        finally:
            stats = pf.stats()
            try:
                pf.close()
            except Exception:
                pass
            client.close()
        results[wid] = (pulled, time.perf_counter() - t0, stats,
                        my_shard_rows)

    from paddle_tpu.traffic import harness
    pool = harness.run_worker_pool(worker, WORKERS, kill_after_s=0.5,
                                   on_kill=servers[0].shutdown)
    promote_latency = pool.promote_latency_s
    wall = pool.wall_s
    for s in servers[1:]:
        s.shutdown()

    total = sum(r[0] for r in results.values())
    shard_rows = np.sum([r[3] for r in results.values()], axis=0) \
        if results else np.zeros(FAILOVER_SERVERS, np.int64)
    hits = monitor.stat_get("ps.heter.hits")
    host_hits = monitor.stat_get("ps.heter.host_hits")
    misses = monitor.stat_get("ps.heter.misses")
    hit_rate = (hits + host_hits) / max(1, hits + host_hits + misses)
    overlaps = [r[2]["overlap_ratio"] for r in results.values()
                if r[2].get("pull_s")]
    print(f"sharded-embedding drill: {FAILOVER_SERVERS} shard servers, "
          f"1 backup each, {WORKERS} workers x {ROUNDS} rounds, shard-0 "
          "primary killed at 0.5s")
    print(f"promotion latency: "
          f"{'NONE RECORDED' if promote_latency is None else f'{promote_latency * 1000:.0f}ms'}"
          f" (heartbeat {FAILOVER_HB_S}s, deadline "
          f"{FAILOVER_HB_TIMEOUT_S}s)")
    print(f"rows pulled through the engine: {total:,} "
          f"({total / wall:,.0f} rows/sec aggregate)")
    for s in range(FAILOVER_SERVERS):
        print(f"  shard {s}: {int(shard_rows[s]):,} rows "
              f"({shard_rows[s] / wall:,.0f} rows/sec)")
    print(f"cache hit rate: {hit_rate:.1%} "
          f"(device {hits:,} + host {host_hits:,} hits, {misses:,} "
          "PS misses)")
    if overlaps:
        print(f"prefetch overlap ratio: {sum(overlaps) / len(overlaps):.2f}"
              f" (mean across {len(overlaps)} workers)")
    replica = {k: int(v) for k, v in
               sorted(monitor.stats("ps.replica.").items())}
    print(f"replica counters: {replica}")
    if errors:
        print("worker errors:\n  " + "\n  ".join(errors))
        return 1
    if promote_latency is None:
        print("ERROR: no promotion was recorded")
        return 1
    print("all workers finished with zero errors")
    return 0


def self_check():
    """framework_lint cross-check: flag defaults <-> this tool's knobs
    <-> docs/fault_tolerance.md. Returns a list of violations."""
    problems = []
    from paddle_tpu.core import flags as _flags
    for name, want in REPLICA_FLAG_DEFAULTS.items():
        defn = _flags._DEFS.get(name)
        if defn is None:
            problems.append(f"ps_load_test: flag {name} is no longer "
                            "defined in core/flags.py")
            continue
        if defn[1] != want:
            problems.append(
                f"ps_load_test: {name} default drifted "
                f"({defn[1]!r} != {want!r}) — update "
                "REPLICA_FLAG_DEFAULTS and docs/fault_tolerance.md")
    doc_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "docs", "fault_tolerance.md")
    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return problems + [f"ps_load_test: cannot read {doc_path}: {e}"]
    for name in REPLICA_FLAG_DEFAULTS:
        if name not in doc:
            problems.append(f"ps_load_test: flag {name} is not "
                            "documented in docs/fault_tolerance.md")
    if "PS_LOAD_FAILOVER" not in doc:
        problems.append("ps_load_test: the PS_LOAD_FAILOVER drill is not "
                        "documented in docs/fault_tolerance.md")
    if "PS_LOAD_SHARDED" not in doc:
        problems.append("ps_load_test: the PS_LOAD_SHARDED sharded-"
                        "embedding drill is not documented in "
                        "docs/fault_tolerance.md")
    for token in (f"heartbeat_s={FAILOVER_HB_S}",
                  f"heartbeat_timeout_s={FAILOVER_HB_TIMEOUT_S}"):
        if token not in doc:
            problems.append(
                f"ps_load_test: docs/fault_tolerance.md no longer states "
                f"the drill timing `{token}` — keep the doc's failover "
                "timeline in sync with PS_LOAD_HB_S/PS_LOAD_HB_TIMEOUT_S")
    # latency percentiles must come from the shared core/slo.py
    # estimator (same implementation as serve_load_test/online_drill)
    with open(os.path.abspath(__file__)) as f:
        self_src = f.read()
    if "from paddle_tpu.core.slo import percentile" not in self_src:
        problems.append("ps_load_test: round-latency percentiles must "
                        "come from core.slo.percentile")
    if "harness.run_worker_pool" not in self_src:
        problems.append("ps_load_test: the worker pool / kill-and-promote "
                        "loop must be the shared "
                        "paddle_tpu.traffic.harness.run_worker_pool")
    return problems


def main():
    if os.environ.get("PS_LOAD_SHARDED"):
        return run_sharded()
    if os.environ.get("PS_LOAD_FAILOVER"):
        return run_failover()
    srv = PSServer(tables={
        "emb": {"type": "sparse", "dim": DIM, "optimizer": "sgd",
                "lr": 0.1, "init": "zeros"}})
    srv.start()
    # PS_LOAD_CHAOS=<seed> measures throughput UNDER seeded faults
    # (resets + dropped replies), i.e. the retry/replay path's overhead
    chaos_seed = os.environ.get("PS_LOAD_CHAOS")
    if chaos_seed is not None:
        from paddle_tpu.testing import faults
        faults.install(faults.FaultInjector(
            seed=chaos_seed, p={faults.RESET: 0.01, faults.DROP: 0.01}))
    try:
        endpoints = [srv.endpoint]
        results = {}
        from paddle_tpu.traffic import harness
        wall = harness.run_worker_pool(
            lambda wid: run_worker(endpoints, wid, results),
            WORKERS).wall_s
    finally:
        srv.shutdown()

    total_pulled = sum(r[0] for r in results.values())
    total_pushed = sum(r[1] for r in results.values())
    rows_sec = (total_pulled + total_pushed) / wall
    pull_sec = total_pulled / wall
    push_sec = total_pushed / wall
    print(f"workers={WORKERS} rounds={ROUNDS} batch~{BATCH_IDS} dim={DIM}")
    print(f"pull rows/sec: {pull_sec:,.0f}")
    print(f"push rows/sec: {push_sec:,.0f}")
    print(f"aggregate rows/sec: {rows_sec:,.0f} (wall {wall:.2f}s)")
    # per-round (pull+push) latency through the SHARED estimator
    # (core/slo.py) so this line is comparable with serve_load_test's
    # ttft percentiles and online_drill's round percentiles
    from paddle_tpu.core.slo import percentile
    round_ms = [ms for r in results.values() for ms in r[3]]
    print(f"round latency ms: p50={percentile(round_ms, 50, ndigits=3)} "
          f"p99={percentile(round_ms, 99, ndigits=3)} "
          f"(n={len(round_ms)})")
    from paddle_tpu.core import monitor
    health = {k: int(v) for k, v in sorted(monitor.stats("ps.").items())}
    print(f"transport health counters: {health or 'all zero'}")

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "ps_throughput.md")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        f.write(
            "# PS transport throughput\n\n"
            "Measured by `tools/ps_load_test.py` (local PSServer, "
            f"{WORKERS} worker threads x {ROUNDS} rounds of pull+push of "
            f"~{BATCH_IDS} unique rows, dim={DIM}, sgd accessor):\n\n"
            f"| pull rows/s | push rows/s | aggregate rows/s |\n"
            f"|---|---|---|\n"
            f"| {pull_sec:,.0f} | {push_sec:,.0f} | {rows_sec:,.0f} |\n\n"
            "Context: the reference's brpc Communicator targets millions "
            "of rows/sec across a cluster of servers; this single-host "
            "pickle-frame TCP transport serves the functional PS story "
            "(tables, accessors, geo/async modes). The dense-training "
            "path never touches it — embeddings ride XLA. Scaling knobs "
            "if it ever gates a workload: batch frames are already one "
            "roundtrip per table op; next would be multi-connection "
            "striping per server.\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
