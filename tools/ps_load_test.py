"""PS transport throughput measurement (VERDICT r04 item 9).

N worker threads x M rounds of pull_sparse + push_sparse_grad of
realistic batches against a local PSServer; reports rows/sec per op and
aggregate. Reference design point: distributed/communicator.cc (brpc,
millions of sparse rows/sec across a cluster); this measures our
pickle-frames-over-TCP transport on one host and records the number
in docs/ps_throughput.md so regressions are visible.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/ps_load_test.py
"""
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import numpy as np  # noqa: E402

from paddle_tpu.distributed.ps import PSClient, PSServer  # noqa: E402

VOCAB = 200_000
DIM = int(os.environ.get("PS_LOAD_DIM", 16))
WORKERS = int(os.environ.get("PS_LOAD_WORKERS", 4))
ROUNDS = int(os.environ.get("PS_LOAD_ROUNDS", 50))
BATCH_IDS = int(os.environ.get("PS_LOAD_BATCH", 2048))


def run_worker(endpoints, wid, results):
    client = PSClient(endpoints)
    rng = np.random.RandomState(wid)
    pulled = pushed = 0
    t0 = time.perf_counter()
    for _ in range(ROUNDS):
        ids = np.unique(rng.randint(0, VOCAB, BATCH_IDS).astype(np.int64))
        rows = client.pull_sparse("emb", ids)
        pulled += len(ids)
        grads = np.asarray(rows, np.float32) * 0 + 0.01
        client.push_sparse_grad("emb", ids, grads)
        pushed += len(ids)
    dt = time.perf_counter() - t0
    results[wid] = (pulled, pushed, dt)
    client.close()


def main():
    srv = PSServer(tables={
        "emb": {"type": "sparse", "dim": DIM, "optimizer": "sgd",
                "lr": 0.1, "init": "zeros"}})
    srv.start()
    # PS_LOAD_CHAOS=<seed> measures throughput UNDER seeded faults
    # (resets + dropped replies), i.e. the retry/replay path's overhead
    chaos_seed = os.environ.get("PS_LOAD_CHAOS")
    if chaos_seed is not None:
        from paddle_tpu.testing import faults
        faults.install(faults.FaultInjector(
            seed=chaos_seed, p={faults.RESET: 0.01, faults.DROP: 0.01}))
    try:
        endpoints = [srv.endpoint]
        results = {}
        threads = [threading.Thread(target=run_worker,
                                    args=(endpoints, w, results))
                   for w in range(WORKERS)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
    finally:
        srv.shutdown()

    total_pulled = sum(r[0] for r in results.values())
    total_pushed = sum(r[1] for r in results.values())
    rows_sec = (total_pulled + total_pushed) / wall
    pull_sec = total_pulled / wall
    push_sec = total_pushed / wall
    print(f"workers={WORKERS} rounds={ROUNDS} batch~{BATCH_IDS} dim={DIM}")
    print(f"pull rows/sec: {pull_sec:,.0f}")
    print(f"push rows/sec: {push_sec:,.0f}")
    print(f"aggregate rows/sec: {rows_sec:,.0f} (wall {wall:.2f}s)")
    from paddle_tpu.core import monitor
    health = {k: int(v) for k, v in sorted(monitor.stats("ps.").items())}
    print(f"transport health counters: {health or 'all zero'}")

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "ps_throughput.md")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        f.write(
            "# PS transport throughput\n\n"
            "Measured by `tools/ps_load_test.py` (local PSServer, "
            f"{WORKERS} worker threads x {ROUNDS} rounds of pull+push of "
            f"~{BATCH_IDS} unique rows, dim={DIM}, sgd accessor):\n\n"
            f"| pull rows/s | push rows/s | aggregate rows/s |\n"
            f"|---|---|---|\n"
            f"| {pull_sec:,.0f} | {push_sec:,.0f} | {rows_sec:,.0f} |\n\n"
            "Context: the reference's brpc Communicator targets millions "
            "of rows/sec across a cluster of servers; this single-host "
            "pickle-frame TCP transport serves the functional PS story "
            "(tables, accessors, geo/async modes). The dense-training "
            "path never touches it — embeddings ride XLA. Scaling knobs "
            "if it ever gates a workload: batch frames are already one "
            "roundtrip per table op; next would be multi-connection "
            "striping per server.\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
