"""Async-executor pipeline config lint (framework_lint cross-check).

Owns the canonical train-mode pipeline bench config (PIPELINE_CFG) and
checks, without running anything expensive, that the three places it is
encoded cannot drift apart:

1. bench.py's BENCH_PIPE_* env-var defaults (the measured evidence),
2. core/flags.py FLAGS_executor_* declared defaults (the runtime
   behavior every training loop actually gets), and
3. tools/hlo_evidence.py's scan-megastep evidence config (the lowered
   proof that K steps become one computation).

Registered in tools/framework_lint.py TOOL_CROSS_CHECKS, so tier-1 runs
it on every change (tests/test_framework_lint.py).

Usage:
  python tools/pipeline_lint.py          # standalone; exit 1 on drift
"""
from __future__ import annotations

import os
import re
import sys

TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(TOOLS_DIR)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# canonical train-mode pipeline bench config (bench.py bench_pipeline)
PIPELINE_CFG = {"batch": 256, "hidden": 64, "steps": 200, "scan_k": 8,
                "inflight": 2}
TINY_PIPELINE_CFG = {"batch": 8, "hidden": 4, "steps": 8, "scan_k": 4,
                     "inflight": 2}


def _bench_source():
    with open(os.path.join(REPO, "bench.py")) as f:
        return f.read()


def self_check():
    problems = []
    src = _bench_source()

    def bench_default(env, want):
        m = re.search(r'os\.environ\.get\("%s",\s*([0-9]+)\)' % env, src)
        if not m:
            problems.append(
                f"pipeline_lint: bench.py no longer reads {env}")
            return
        if int(m.group(1)) != want:
            problems.append(
                f"pipeline_lint: bench.py default {env}={m.group(1)} but "
                f"PIPELINE_CFG says {want} — update the canonical config")

    bench_default("BENCH_PIPE_BATCH", PIPELINE_CFG["batch"])
    bench_default("BENCH_PIPE_HIDDEN", PIPELINE_CFG["hidden"])
    bench_default("BENCH_PIPE_STEPS", PIPELINE_CFG["steps"])
    bench_default("BENCH_PIPE_SCAN_K", PIPELINE_CFG["scan_k"])
    bench_default("BENCH_PIPE_INFLIGHT", PIPELINE_CFG["inflight"])

    # flag DECLARED defaults (not live values — a test may have set them)
    try:
        from paddle_tpu.core import flags as _flags
        defs = _flags._DEFS
    except Exception as e:
        return problems + [f"pipeline_lint: flags import failed: {e!r}"]
    for name in ("FLAGS_executor_max_inflight", "FLAGS_executor_scan_steps",
                 "FLAGS_executor_cache_size"):
        if name not in defs:
            problems.append(f"pipeline_lint: flag {name} is gone but the "
                            "pipeline runner / bench still depend on it")
    if "FLAGS_executor_max_inflight" in defs and \
            int(defs["FLAGS_executor_max_inflight"][1]) != \
            PIPELINE_CFG["inflight"]:
        problems.append(
            "pipeline_lint: FLAGS_executor_max_inflight default "
            f"{defs['FLAGS_executor_max_inflight'][1]} != bench inflight "
            f"{PIPELINE_CFG['inflight']} — the bench would measure a "
            "pipeline depth users don't get by default")
    if "FLAGS_executor_scan_steps" in defs and \
            int(defs["FLAGS_executor_scan_steps"][1]) != 0:
        problems.append(
            "pipeline_lint: FLAGS_executor_scan_steps default must stay 0 "
            "(scan fusion is opt-in; docs/async_executor.md) — bench/"
            "evidence pass K explicitly")

    # hlo_evidence keeps an INDEPENDENT literal of this config for its
    # scan-megastep section (importing ours here would make this check
    # compare an object against itself)
    try:
        if TOOLS_DIR not in sys.path:
            sys.path.insert(0, TOOLS_DIR)
        import hlo_evidence
        if getattr(hlo_evidence, "PIPELINE_CFG", None) != PIPELINE_CFG:
            problems.append(
                "pipeline_lint: tools/hlo_evidence.py PIPELINE_CFG "
                f"{getattr(hlo_evidence, 'PIPELINE_CFG', None)} != "
                f"{PIPELINE_CFG} — the lowered scan evidence no longer "
                "matches the measured bench config")
        if PIPELINE_CFG["scan_k"] < 2:
            problems.append(
                "pipeline_lint: scan_k must be >= 2 — the '>=2x fewer "
                "dispatches per K steps' acceptance bar is vacuous below "
                "that")
    except Exception as e:
        problems.append(f"pipeline_lint: hlo_evidence import failed: "
                        f"{e!r}")
    return problems


def main(argv=None):
    problems = self_check()
    for p in problems:
        print(p)
    print("pipeline_lint:",
          "clean" if not problems else f"{len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
