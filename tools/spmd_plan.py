"""Auto-sharding plan CLI — front-end for static/spmd_planner.py.

Plans the GPT workload (the same program tools/spmd_lint.py prices) for
a given `{axis: size}` mesh — device-free, so a pod layout plans from
any dev box — and prints the searched plan as a human-auditable rule
list next to a predicted-cost table: planned layout vs the hand-written
`sharding.py` preset vs full replication. Exit 1 when the plan carries
diagnostics or loses to the preset on either predicted metric.

  python tools/spmd_plan.py                  # tiny GPT, tp=2
  python tools/spmd_plan.py --tp 4 --dp 2 --layers 12 --hidden 768
  python tools/spmd_plan.py --tp 2 --dp 2 --sp 2   # hybrid mesh
  python tools/spmd_plan.py --json           # stable output for CI
  python tools/spmd_plan.py --topology --pods 2 --dp 2 --tp 2
                                             # two-tier wire-cost report

`--topology` plans the same GPT on a nested two-tier mesh (a `pod` axis
on the slow DCN tier over the ICI axes) and renders the per-tier
wire-bytes table: flat dp all-reduce vs the hierarchical decomposition
(reduce-scatter intra-pod -> inter-pod all-reduce of the 1/n shard ->
all-gather) vs LocalSGD. Exit 1 if the planner leaves tp/sp crossing
the slow tier (any `cross-tier` diagnostic) or the hierarchical scheme
fails to cut inter-pod bytes by >= 2x.

`self_check()` (registered in tools/framework_lint.py TOOL_CROSS_CHECKS
and run by tests/test_spmd_planner.py in tier-1) pins the golden
rediscovery: on a tp-only mesh the search must reproduce the Megatron
layout (qkv/fc1 column-parallel, out-proj/fc2 row-parallel, wte
vocab-parallel) with zero diagnostics at preset-or-better predicted
cost, a dp×tp mesh must shard the `input_ids` feed on dp, and the
two-tier `{pod:2, dp:2, tp:2}` mesh must keep tp intra-pod with the
hierarchical dp sync recommended.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))  # sibling spmd_lint


def build_plan(tp=2, dp=1, sp=1, layers=2, hidden=64, heads=2, vocab=1024,
               batch=2, seq=16, beam=None, coll_weight=None,
               hbm_weight=None, zero_dp=False):
    """Plan the GPT workload. Returns (plan, preset_report,
    replicated_report, program, net, logits)."""
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed import sharding
    from paddle_tpu.static import spmd_analyzer as spmd
    from paddle_tpu.static import spmd_planner
    from spmd_lint import build_gpt_program

    mesh = {}
    if dp > 1:
        mesh["dp"] = dp
    if tp > 1:
        mesh["tp"] = tp
    if sp > 1:
        mesh["sp"] = sp
    program, net, logits = build_gpt_program(
        layers=layers, hidden=hidden, heads=heads, vocab=vocab,
        batch=batch, seq=seq, name="spmd_plan_gpt")
    plan = spmd_planner.plan_program(
        program, mesh, layer=net, beam=beam, coll_weight=coll_weight,
        hbm_weight=hbm_weight, zero_dp=zero_dp)
    preset_specs = sharding.named_param_specs(net, mesh)
    preset_data = {"input_ids": P("dp")} if dp > 1 else None
    preset = spmd.analyze_program(program, mesh=mesh,
                                  param_specs=preset_specs,
                                  data_specs=preset_data)
    replicated = spmd.analyze_program(program, mesh=mesh, param_specs={})
    return plan, preset, replicated, program, net, logits


def _metrics(report):
    return {"collective_bytes": report.collective_bytes(),
            "hbm_peak": report.hbm["peak_bytes"] if report.hbm else 0,
            "diagnostics": len(report.diagnostics)}


def build_topology_plan(pods=2, dp=2, tp=2, sp=1, layers=2, hidden=64,
                        heads=2, vocab=1024, batch=8, seq=16, beam=None,
                        coll_weight=None, hbm_weight=None):
    """Plan the GPT workload on a nested two-tier mesh: a `pod` axis on
    the slow DCN tier over the usual ICI axes. Returns (plan, program,
    net); the plan carries `mesh_tiers`/`grad_sync` (the priced
    flat/hierarchical/localsgd dp sync schemes)."""
    from paddle_tpu.static import spmd_planner
    from spmd_lint import build_gpt_program

    mesh = {"pod": {"size": pods, "tier": "dcn"}}
    if dp > 1:
        mesh["dp"] = dp
    if tp > 1:
        mesh["tp"] = tp
    if sp > 1:
        mesh["sp"] = sp
    program, net, _logits = build_gpt_program(
        layers=layers, hidden=hidden, heads=heads, vocab=vocab,
        batch=batch, seq=seq, name="spmd_plan_topo_gpt")
    plan = spmd_planner.plan_program(
        program, mesh, layer=net, beam=beam, coll_weight=coll_weight,
        hbm_weight=hbm_weight)
    return plan, program, net


def topology_json(plan) -> dict:
    """Stable JSON for CI: the plan (with its `topology` block) + the
    acceptance verdict — zero diagnostics (so no tp/sp collective
    crosses the slow tier), a dp sync priced hierarchically, and the
    hierarchical scheme cutting inter-pod wire bytes >= 2x vs flat."""
    out = plan.to_json()
    rep = plan.report
    out["cross_tier"] = sum(1 for d in (rep.diagnostics if rep else [])
                            if d.code == "cross-tier")
    gs = plan.grad_sync or {}
    hier_2x = False
    if gs:
        flat_dcn = gs["schemes"]["flat"]["wire_bytes"]["dcn"]
        hier_dcn = gs["schemes"]["hierarchical"]["wire_bytes"]["dcn"]
        hier_2x = hier_dcn * 2 <= flat_dcn
    out["ok"] = bool(
        out["predicted"]["diagnostics"] == 0
        and out["cross_tier"] == 0
        and gs and hier_2x
        and gs.get("recommendation") in ("hierarchical", "localsgd"))
    return out


def render_topology(plan) -> str:
    lines = [plan.render()]
    tb = plan.predicted.get("tier_bytes") or {}
    if tb:
        lines.append("step collectives per tier: " + ", ".join(
            f"{t}={b} B" for t, b in sorted(tb.items())))
    gs = plan.grad_sync
    if not gs:
        lines.append("dp gradient sync: n/a (no pure-dp axis)")
        return "\n".join(lines)
    lines.append("per-tier wire bytes (dp gradient sync, per device):")
    lines.append(f"  {'scheme':<14}{'ici B':>14}{'dcn B':>14}"
                 f"{'cost us':>12}")
    for name in ("flat", "hierarchical", "localsgd"):
        s = gs["schemes"][name]
        lines.append(f"  {name:<14}{s['wire_bytes']['ici']:>14}"
                     f"{s['wire_bytes']['dcn']:>14}"
                     f"{s['total_cost_us']:>12.1f}")
    lines.append(
        f"recommendation: {gs['recommendation']} (hierarchical cuts "
        f"inter-pod bytes {gs['inter_pod_reduction_x']:.1f}x, localsgd "
        f"amortizes 1/{gs['localsgd_k']})")
    return "\n".join(lines)


def build_moe_program(layers=4, hidden=64, experts=4, d_hidden=None,
                      batch=4, seq=16, name="spmd_plan_moe"):
    """A dense+MoE stack (the expert-parallel workload): `layers` blocks
    of Linear -> tanh -> MoELayer. Returns (program, names) with
    dotted display names for the rule templates."""
    import paddle_tpu as paddle
    from paddle_tpu import ops, static
    from paddle_tpu import nn
    from paddle_tpu.distributed.moe import MoELayer

    was_static = static.in_static_mode()
    paddle.enable_static()
    try:
        main = static.Program(name)
        names = {}
        with static.program_guard(main):
            x = static.data("x", [batch, seq, hidden], "float32")
            h = x
            for i in range(layers):
                lin = nn.Linear(hidden, hidden)
                moe = MoELayer(hidden, d_hidden or 2 * hidden, experts,
                               axis="ep")
                h = ops.tanh(lin(h))
                h = moe(h)
                for suffix, p in (("fc.weight", lin.weight),
                                  ("fc.bias", lin.bias),
                                  ("moe.gate.weight", moe.gate.weight),
                                  ("moe.w_up", moe.w_up),
                                  ("moe.b_up", moe.b_up),
                                  ("moe.w_down", moe.w_down),
                                  ("moe.b_down", moe.b_down)):
                    names[p.scope_name] = f"blocks.{i}.{suffix}"
        main._jit_fetch_vars = [h]
        return main, names
    finally:
        if not was_static:
            paddle.disable_static()


def build_pipeline_plan(pp=4, dp=1, tp=1, ep=1, micro=8, virtual=1,
                        layers=None, hidden=64, heads=2, vocab=1024,
                        batch=8, seq=16, experts=4):
    """Plan a pipeline partition of the golden workload: the GPT
    program (spmd_lint's) for dense meshes, the MoE stack when an `ep`
    axis is requested. Returns the PipelinePlan (its `.inner` carries
    the non-pp SPMD plan, expert placement included)."""
    from paddle_tpu.static import spmd_planner
    from spmd_lint import build_gpt_program

    mesh = {}
    if pp > 1:
        mesh["pp"] = pp
    if dp > 1:
        mesh["dp"] = dp
    if tp > 1:
        mesh["tp"] = tp
    if ep > 1:
        mesh["ep"] = ep
    if ep > 1:
        program, names = build_moe_program(
            layers=layers or 4, hidden=hidden, experts=experts,
            batch=batch, seq=seq)
        return spmd_planner.plan_pipeline(
            program, mesh, num_micro=micro, num_virtual=virtual,
            names=names)
    program, net, _logits = build_gpt_program(
        layers=layers or 4, hidden=hidden, heads=heads, vocab=vocab,
        batch=batch, seq=seq, name="spmd_plan_pp_gpt")
    return spmd_planner.plan_pipeline(
        program, mesh, num_micro=micro, num_virtual=virtual, layer=net)


def pipeline_json(plan) -> dict:
    """Stable JSON for CI: the stage table + wire/bubble/objective and
    the acceptance verdict — zero diagnostics AND the planner's cut
    matches-or-beats the hand (equal-segments) cut on the weighted
    objective."""
    out = plan.to_json()
    hand_obj = plan.hand.get("objective")
    out["ok"] = bool(
        not plan.diagnostics
        and all(s.diagnostics == 0 for s in plan.stages)
        and (hand_obj is None or plan.objective <= hand_obj + 1e-9))
    return out


def plan_json(plan, preset, replicated) -> dict:
    """Stable JSON for CI: the plan's rule list + the three-way cost
    table + the acceptance verdict."""
    out = plan.to_json()
    out["preset"] = _metrics(preset)
    out["replicated"] = _metrics(replicated)
    p = out["predicted"]
    out["ok"] = bool(
        p["diagnostics"] == 0
        and p["collective_bytes"] <= out["preset"]["collective_bytes"]
        and p["hbm_peak"] <= out["preset"]["hbm_peak"])
    return out


def render_table(plan, preset, replicated) -> str:
    rows = [("planned", plan.predicted),
            ("preset", _metrics(preset)),
            ("replicated", _metrics(replicated))]
    lines = ["predicted cost (collective B/step, peak HBM B/device, "
             "diagnostics):"]
    lines.append(f"  {'layout':<12}{'collective':>14}{'peak HBM':>14}"
                 f"{'diags':>8}")
    for name, m in rows:
        lines.append(f"  {name:<12}{m['collective_bytes']:>14}"
                     f"{m['hbm_peak']:>14}{m['diagnostics']:>8}")
    return "\n".join(lines)


def self_check():
    """Violation strings for framework_lint's cross-check registry."""
    from jax.sharding import PartitionSpec as P
    try:
        plan, preset, replicated, _prog, _net, logits = build_plan(tp=2)
    except Exception as e:  # noqa: BLE001 - a lint must not crash the gate
        return [f"spmd_plan self-check failed to build/plan: {e!r}"]
    problems = []
    pm, bm = plan.predicted, _metrics(preset)
    if pm["diagnostics"]:
        problems.append("spmd_plan golden TP config: plan carries "
                        f"{pm['diagnostics']} diagnostic(s)")
    if pm["collective_bytes"] > bm["collective_bytes"]:
        problems.append(
            "spmd_plan golden TP config: planned collective bytes "
            f"{pm['collective_bytes']} exceed the hand-written preset's "
            f"{bm['collective_bytes']}")
    if pm["hbm_peak"] > bm["hbm_peak"]:
        problems.append(
            "spmd_plan golden TP config: planned peak HBM "
            f"{pm['hbm_peak']} exceeds the hand-written preset's "
            f"{bm['hbm_peak']}")
    megatron = {
        "blocks.0.attn.qkv_proj.weight": P(None, "tp"),
        "blocks.1.attn.out_proj.weight": P("tp", None),
        "blocks.0.fc1.weight": P(None, "tp"),
        "blocks.1.fc2.weight": P("tp", None),
        "wte.weight": P("tp", None),
    }
    for name, want in megatron.items():
        got = plan.spec_for(name, 2)
        if got != want:
            problems.append(
                f"spmd_plan golden TP config: {name} planned as {got}, "
                f"the Megatron layout is {want}")
    ar = [c for c in plan.report.collectives if c.kind == "all_reduce"]
    if len(ar) != 5 or any(c.axis != "tp" for c in ar):
        problems.append(
            "spmd_plan golden TP config: expected 2L+1=5 tp all-reduces, "
            f"planner's layout implies {len(ar)}")
    try:
        plan2, _, _, _, _, _ = build_plan(tp=2, dp=2)
    except Exception as e:  # noqa: BLE001
        return problems + [f"spmd_plan dp x tp self-check crashed: {e!r}"]
    ids_spec = tuple(plan2.data_specs.get("input_ids", P()))
    if not ids_spec or ids_spec[0] != "dp":
        problems.append(
            "spmd_plan dp x tp config: input_ids not sharded on dp "
            f"(got {ids_spec})")
    # the pipeline golden: {pp: 4} on the GPT workload must produce a
    # clean 4-stage partition that matches-or-beats the hand
    # (equal-segments) cut on the weighted objective
    try:
        pplan = build_pipeline_plan(pp=4)
    except Exception as e:  # noqa: BLE001
        return problems + [f"spmd_plan --pipeline self-check crashed: "
                           f"{e!r}"]
    payload = pipeline_json(pplan)
    if not payload["ok"]:
        problems.append(
            "spmd_plan pipeline golden {pp:4}: plan not ok — "
            f"diagnostics {pplan.diagnostics}, objective "
            f"{pplan.objective} vs hand {pplan.hand.get('objective')}")
    if len(pplan.stages) != 4:
        problems.append(
            f"spmd_plan pipeline golden {{pp:4}}: {len(pplan.stages)} "
            "stages planned, expected 4")
    # the topology golden: {pod:2(dcn), dp:2, tp:2} must keep tp
    # intra-pod from cost alone (zero cross-tier diagnostics), shard the
    # batch over (pod, dp), and price the hierarchical dp sync at >= 2x
    # less inter-pod wire than the flat all-reduce
    try:
        tplan, _tprog, _tnet = build_topology_plan(pods=2, dp=2, tp=2,
                                                   batch=8)
    except Exception as e:  # noqa: BLE001
        return problems + [f"spmd_plan --topology self-check crashed: "
                           f"{e!r}"]
    tpayload = topology_json(tplan)
    if not tpayload["ok"]:
        problems.append(
            "spmd_plan topology golden {pod:2,dp:2,tp:2}: plan not ok — "
            f"diagnostics {tpayload['predicted']['diagnostics']}, "
            f"cross-tier {tpayload['cross_tier']}, grad_sync "
            f"{tplan.grad_sync and tplan.grad_sync.get('recommendation')}")
    gs = tplan.grad_sync or {}
    if gs.get("recommendation") != "hierarchical":
        problems.append(
            "spmd_plan topology golden: expected the hierarchical dp "
            f"sync recommendation, got {gs.get('recommendation')!r}")
    if float(gs.get("inter_pod_reduction_x", 0)) < 2.0:
        problems.append(
            "spmd_plan topology golden: hierarchical sync cuts inter-pod "
            f"bytes only {gs.get('inter_pod_reduction_x')}x, need >= 2x")
    tids = tuple(tplan.data_specs.get("input_ids", P()))
    if not tids or tids[0] != ("pod", "dp"):
        problems.append(
            "spmd_plan topology golden: input_ids batch dim not sharded "
            f"over (pod, dp) (got {tids})")
    return problems


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="auto-sharding planner (search PartitionSpec plans "
                    "against the SPMD analyzer's cost model) for the GPT "
                    "workload")
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--layers", type=int, default=None,
                    help="transformer layers (default: 2, or 4 in "
                         "--pipeline mode)")
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seq", type=int, default=16)
    ap.add_argument("--beam", type=int, default=None,
                    help="beam width (default FLAGS_spmd_plan_beam)")
    ap.add_argument("--coll-weight", type=float, default=None,
                    help="objective weight on collective bytes/step")
    ap.add_argument("--hbm-weight", type=float, default=None,
                    help="objective weight on peak per-device HBM")
    ap.add_argument("--zero-dp", action="store_true",
                    help="offer ZeRO-style dim-0 dp sharding candidates")
    ap.add_argument("--json", action="store_true",
                    help="stable JSON on stdout (CI consumption)")
    ap.add_argument("--pipeline", action="store_true",
                    help="plan pipeline stage cuts (and MoE expert "
                         "placement with --ep) instead of a single-SPMD "
                         "layout; --pp sets the stage count")
    ap.add_argument("--topology", action="store_true",
                    help="plan on a nested two-tier mesh (--pods on the "
                         "slow DCN tier over the ICI axes) and render "
                         "the per-tier wire-bytes table: flat vs "
                         "hierarchical vs localsgd dp sync")
    ap.add_argument("--pods", type=int, default=2,
                    help="slow-tier (DCN) pod count (--topology mode)")
    ap.add_argument("--pp", type=int, default=4,
                    help="pipeline stages (--pipeline mode)")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert-parallel degree: >1 plans the MoE "
                         "workload with experts sharded over 'ep'")
    ap.add_argument("--micro", type=int, default=8,
                    help="microbatches priced per step")
    ap.add_argument("--virtual", type=int, default=1,
                    help="virtual chunks per rank (interleaved 1F1B)")
    args = ap.parse_args(argv)

    if args.pipeline:
        plan = build_pipeline_plan(
            pp=args.pp, dp=args.dp, tp=args.tp, ep=args.ep,
            micro=args.micro, virtual=args.virtual, layers=args.layers,
            hidden=args.hidden, heads=args.heads, vocab=args.vocab,
            batch=max(args.batch, args.micro), seq=args.seq)
        payload = pipeline_json(plan)
        if args.json:
            print(json.dumps(payload, sort_keys=True, indent=1))
        else:
            print(plan.stage_table())
            print(f"search: {plan.evaluations} stage evaluations, "
                  f"{plan.inner.evaluations if plan.inner else 0} "
                  "layout evaluations")
        return 0 if payload["ok"] else 1

    if args.topology:
        dp = args.dp if args.dp > 1 else 2
        batch = args.batch if args.batch % (args.pods * dp) == 0 \
            else 2 * args.pods * dp
        plan, _prog, _net = build_topology_plan(
            pods=args.pods, dp=dp, tp=args.tp, sp=args.sp,
            layers=2 if args.layers is None else args.layers,
            hidden=args.hidden, heads=args.heads, vocab=args.vocab,
            batch=batch, seq=args.seq, beam=args.beam,
            coll_weight=args.coll_weight, hbm_weight=args.hbm_weight)
        payload = topology_json(plan)
        if args.json:
            print(json.dumps(payload, sort_keys=True, indent=1))
        else:
            print(render_topology(plan))
            print(f"search: {plan.evaluations} analyzer evaluations")
        return 0 if payload["ok"] else 1

    plan, preset, replicated, _prog, _net, _logits = build_plan(
        tp=args.tp, dp=args.dp, sp=args.sp,
        layers=2 if args.layers is None else args.layers,
        hidden=args.hidden, heads=args.heads, vocab=args.vocab,
        batch=args.batch, seq=args.seq, beam=args.beam,
        coll_weight=args.coll_weight, hbm_weight=args.hbm_weight,
        zero_dp=args.zero_dp)
    payload = plan_json(plan, preset, replicated)
    if args.json:
        print(json.dumps(payload, sort_keys=True, indent=1))
    else:
        print(plan.render())
        print(render_table(plan, preset, replicated))
        print(f"search: {plan.evaluations} analyzer evaluations")
    return 0 if payload["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
