"""Pipeline-schedule quality report (VERDICT r04 item 7 'Done' criterion):
compare gpipe vs 1f1b-remat vs interleaved on step-time and compiled
memory on the virtual 8-CPU mesh, verifying grads match the non-pipelined
reference for every schedule. Writes docs/pp_schedules.md.

Run: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python tools/pp_schedule_report.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import jax                                    # noqa: E402
import jax.numpy as jnp                       # noqa: E402
import numpy as np                            # noqa: E402
from jax.sharding import PartitionSpec as P   # noqa: E402

from paddle_tpu.distributed import mesh as mesh_mod          # noqa: E402
from paddle_tpu.distributed.pipeline import (bubble_fraction,  # noqa: E402
                                             micro_batch, pipeline_loss,
                                             schedule_ticks)

N_STAGES = 4
N_VIRTUAL = 2
N_MICRO = 16
D = 256
MB = 8       # microbatch size
CHUNK_DEPTH = 3  # applications of the chunk matmul: intra-chunk
# activations dominate the stash, so 1f1b's rematerialization is visible


def _apply_chunk(h, w):
    for _ in range(CHUNK_DEPTH):
        h = jnp.tanh(h @ w)
    return h


def build(schedule):
    mesh = mesh_mod.init_mesh({"pp": N_STAGES}, name="default")
    rng = np.random.RandomState(0)
    n_global = N_STAGES * N_VIRTUAL
    ws = (rng.randn(n_global, D, D) * (1.0 / np.sqrt(D))).astype("float32")
    x = rng.randn(N_MICRO * MB, D).astype("float32")
    y = rng.randn(N_MICRO * MB, D).astype("float32")
    xm = micro_batch(jnp.asarray(x), N_MICRO)
    ym = micro_batch(jnp.asarray(y), N_MICRO)

    if schedule == "interleaved":
        # chunk c on rank r = global stage c*n + r
        ws_by_rank = np.stack(
            [np.stack([ws[c * N_STAGES + r] for c in range(N_VIRTUAL)])
             for r in range(N_STAGES)])
        arg = jnp.asarray(ws_by_rank)          # [n, v, D, D]

        def spmd(wr, xm_l, ym_l):
            chunks = [lambda h, c=c: _apply_chunk(h, wr[0, c])
                      for c in range(N_VIRTUAL)]
            return pipeline_loss(chunks, lambda h, t: jnp.mean((h - t) ** 2),
                                 xm_l, ym_l, axis="pp",
                                 schedule="interleaved")
    else:
        # each rank runs its v chunks back-to-back as one deep stage:
        # contiguous layer blocks (global layer r*v + c), unlike the
        # interleaved round-robin assignment (c*n + r)
        ws_by_rank = np.stack(
            [np.stack([ws[r * N_VIRTUAL + c] for c in range(N_VIRTUAL)])
             for r in range(N_STAGES)])
        arg = jnp.asarray(ws_by_rank)

        def spmd(wr, xm_l, ym_l):
            def stage(h):
                for c in range(N_VIRTUAL):
                    h = _apply_chunk(h, wr[0, c])
                return h
            return pipeline_loss(stage, lambda h, t: jnp.mean((h - t) ** 2),
                                 xm_l, ym_l, axis="pp", schedule=schedule)

    def outer(a):
        return mesh_mod.shard_map(spmd, mesh=mesh,
                                  in_specs=(P("pp"), P(), P()),
                                  out_specs=P())(a, xm, ym).mean()

    fn = jax.jit(jax.value_and_grad(outer))
    return fn, arg, ws, x, y


def reference(ws, x, y):
    def loss_fn(ws_all):
        h = jnp.asarray(x)
        for s in range(ws.shape[0]):
            h = _apply_chunk(h, ws_all[s])
        return jnp.mean((h - jnp.asarray(y)) ** 2)
    l, g = jax.value_and_grad(loss_fn)(jnp.asarray(ws))
    return float(l), np.asarray(g)


def grads_to_global(schedule, g):
    out = np.zeros((N_STAGES * N_VIRTUAL, D, D), "float32")
    for r in range(N_STAGES):
        for c in range(N_VIRTUAL):
            s = (c * N_STAGES + r if schedule == "interleaved"
                 else r * N_VIRTUAL + c)
            out[s] = g[r, c]
    return out


def stage_program_estimate():
    """Program-level liveness estimate of ONE stage's activation
    footprint (static/shape_infer.py analyze_memory) — the build-time
    number to sanity-check XLA's measured temp buffers against: the
    estimator never sees fusion/remat, so it upper-bounds a single
    chunk's stash."""
    import paddle_tpu as paddle
    from paddle_tpu import ops, static

    paddle.enable_static()
    try:
        main_prog = static.Program("pp_stage")
        with static.program_guard(main_prog):
            h = static.data("h", [MB, D], "float32")
            w = static.data("w", [D, D], "float32")
            for _ in range(CHUNK_DEPTH):
                h = ops.tanh(ops.matmul(h, w))
        main_prog._jit_fetch_vars = [h]
        est = static.analyze_memory(main_prog)
        return est
    finally:
        paddle.disable_static()


def self_check():
    """Violation strings for framework_lint's TOOL_CROSS_CHECKS: pins
    this report's mesh/microbatch constants against pipeline.py's
    schedule accounting and the stage-cut planner's objective knobs, so
    the three can't drift apart silently (this was the only pipeline
    tool outside the lint net)."""
    problems = []
    from paddle_tpu.core.flags import flag
    from paddle_tpu.distributed.pipeline import (bubble_fraction,
                                                 schedule_collectives,
                                                 schedule_ticks)

    # the report's schedule set is exactly what schedule_ticks accounts
    # (all three rows pin the v=N_VIRTUAL formulae the report prints)
    for schedule in ("gpipe", "1f1b", "interleaved"):
        ticks = schedule_ticks(N_MICRO, N_STAGES, schedule, N_VIRTUAL)
        want = (N_VIRTUAL * N_MICRO + N_STAGES - 1
                if schedule == "interleaved"
                else N_VIRTUAL * (N_MICRO + N_STAGES - 1))
        if ticks != want:
            problems.append(
                f"pp_schedule_report: schedule_ticks({schedule}) = "
                f"{ticks}, report math expects {want} — the report's "
                "tick column no longer matches pipeline.py")
        bub = bubble_fraction(N_MICRO, N_STAGES, schedule, N_VIRTUAL)
        if not (0.0 <= bub < 1.0):
            problems.append(
                f"pp_schedule_report: bubble_fraction({schedule}) = "
                f"{bub} out of [0, 1)")
    # degenerate shapes must price sanely (the cost model feeds the
    # planner: a crash here is a crash in plan_pipeline)
    if bubble_fraction(N_MICRO, 1) != 0.0:
        problems.append("pp_schedule_report: single-stage bubble != 0")
    if schedule_collectives(N_MICRO, 1, 1024)["total_bytes"] != 0:
        problems.append(
            "pp_schedule_report: single-stage pipeline prices nonzero "
            "ppermute wire")
    if schedule_ticks(2, N_STAGES) != 2 + N_STAGES - 1:
        problems.append(
            "pp_schedule_report: num_micro < num_stages must still "
            "price M+n-1 ticks")
    # the planner's pp objective knobs this report's numbers anchor
    for name, want in (("FLAGS_spmd_plan_pp_micro", 8),
                       ("FLAGS_spmd_plan_pp_beam", 8),
                       ("FLAGS_spmd_plan_pp_flops_weight", 1.0),
                       ("FLAGS_spmd_plan_pp_wire_weight", 1.0),
                       ("FLAGS_spmd_plan_pp_hbm_weight", 1.0),
                       ("FLAGS_spmd_plan_pp_bubble_weight", 1.0)):
        try:
            got = flag(name)
        except Exception as e:  # noqa: BLE001
            problems.append(
                f"pp_schedule_report: planner knob {name} missing ({e})")
            continue
        if got != want:
            problems.append(
                f"pp_schedule_report: planner knob {name} default "
                f"changed to {got!r} (docs/spmd_planner.md flag table "
                f"says {want!r}) — update the doc and this pin together")
    if N_MICRO % N_STAGES != 0:
        problems.append(
            "pp_schedule_report: N_MICRO must stay divisible by "
            "N_STAGES (the interleaved schedule's injection-group "
            "contract)")
    return problems


def main():
    rows = []
    ref_cache = None
    for schedule in ("gpipe", "1f1b", "interleaved"):
        fn, arg, ws, x, y = build(schedule)
        if ref_cache is None:
            ref_cache = reference(ws, x, y)
        ref_loss, ref_g = ref_cache
        lowered = fn.lower(arg)
        compiled = lowered.compile()
        try:
            ma = compiled.memory_analysis()
            temp_mb = ma.temp_size_in_bytes / 1e6
        except Exception:
            temp_mb = float("nan")
        loss, g = fn(arg)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            loss, g = fn(arg)
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / reps * 1000
        gg = grads_to_global(schedule, np.asarray(g))
        err = float(np.max(np.abs(gg - ref_g)))
        match = err < 1e-4 and abs(float(loss) - ref_loss) < 1e-5
        ticks = schedule_ticks(N_MICRO, N_STAGES, schedule, N_VIRTUAL)
        rows.append((schedule, ticks, temp_mb, dt, float(loss), match))
        print(f"{schedule:12s} ticks={ticks:3d} tempMB={temp_mb:8.1f} "
              f"step={dt:7.2f}ms loss={float(loss):.6f} "
              f"grads_match={match}")

    est = stage_program_estimate()
    est_mb = est["peak_bytes"] / 1e6
    print(f"stage-program liveness estimate: peak {est_mb:.2f} MB "
          f"(activations {est['activation_peak_bytes'] / 1e6:.2f} MB)")

    doc = [
        "# Pipeline schedule comparison",
        "",
        f"Measured on the virtual 8-CPU mesh (pp={N_STAGES}, "
        f"v={N_VIRTUAL} chunks/rank, M={N_MICRO} microbatches of {MB}, "
        f"hidden={D}); fwd+bwd step via `tools/pp_schedule_report.py`. "
        "Chunk-time ticks are the schedule-intrinsic cost "
        "(`schedule_ticks`); XLA temp memory is the compiled buffer "
        "footprint (activation stash shows up here); every schedule's "
        "grads are verified against the non-pipelined 8-layer reference.",
        "",
        "| schedule | chunk-ticks | bubble | XLA temp MB | step ms "
        "(8-CPU) | grads match |",
        "|---|---|---|---|---|---|",
    ]
    for schedule, ticks, temp_mb, dt, _loss, match in rows:
        bub = (bubble_fraction(N_MICRO, N_STAGES)
               if schedule != "interleaved"
               else (N_STAGES - 1) / (N_VIRTUAL * N_MICRO + N_STAGES - 1))
        doc.append(f"| {schedule} | {ticks} | {bub:.3f} | {temp_mb:.1f} | "
                   f"{dt:.2f} | {'yes' if match else 'NO'} |")
    doc += [
        "",
        f"Per-chunk build-time estimate (liveness over the stage's "
        f"static Program, `paddle_tpu.static.analyze_memory`): peak "
        f"{est_mb:.2f} MB, activations "
        f"{est['activation_peak_bytes'] / 1e6:.2f} MB — the pre-XLA "
        "upper bound one microbatch stashes per chunk; multiply by the "
        "schedule's in-flight microbatch count to anticipate the stash "
        "before compiling.",
        "",
        "Reading: `1f1b` = gpipe tick order + per-tick rematerialization "
        "(bounds the activation stash to tick-boundary hiddens; on this "
        "small CPU config XLA's own scheduling already bounds gpipe's "
        "stash, so the two measure alike — the bound matters at model "
        "scale, where the stash would otherwise grow with M); "
        "`interleaved` "
        "= virtual-stage schedule — bubble (n-1)/(vM+n-1) vs "
        "(n-1)/(M+n-1) and the finer chunk granularity is what actually "
        "cuts the compiled temp footprint here — at one extra ppermute "
        "per chunk. CPU step-ms is indicative only (no real ICI); the "
        "tick/bubble/memory columns are the architecture-true comparison.",
    ]
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "pp_schedules.md")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        f.write("\n".join(doc) + "\n")
    print(f"wrote {out}")
    if not all(r[5] for r in rows):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
