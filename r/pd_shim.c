/* R shim over the paddle_tpu C-ABI predictor (see r/predictor.R).
 *
 * Build: R CMD SHLIB r/pd_shim.c \
 *          -I paddle_tpu/_native/include \
 *          -L paddle_tpu/_native/lib -lpaddle_tpu_capi
 *
 * Exposes three .Call entry points: R_PD_NewPredictor, R_PD_Run,
 * R_PD_Delete. Inputs arrive as R single-precision vectors plus integer
 * shape vectors; outputs return as a list of R numeric arrays with dim
 * attributes. Mirrors the reference r/ client's role over the C API.
 */
#include <R.h>
#include <Rinternals.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#include "paddle_tpu_capi.h"

static void pd_finalizer(SEXP ptr) {
  PD_Predictor* h = (PD_Predictor*)R_ExternalPtrAddr(ptr);
  if (h) {
    PD_DeletePredictor(h);
    R_ClearExternalPtr(ptr);
  }
}

SEXP R_PD_NewPredictor(SEXP prefix, SEXP key) {
  const char* p = CHAR(STRING_ELT(prefix, 0));
  const char* k = CHAR(STRING_ELT(key, 0));
  PD_Predictor* h = PD_NewPredictor(p, k);
  if (!h) error("PD_NewPredictor: %s", PD_GetLastError());
  SEXP ptr = PROTECT(R_MakeExternalPtr(h, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ptr, pd_finalizer, TRUE);
  UNPROTECT(1);
  return ptr;
}

SEXP R_PD_Run(SEXP ptr, SEXP bufs, SEXP shapes) {
  PD_Predictor* h = (PD_Predictor*)R_ExternalPtrAddr(ptr);
  if (!h) error("predictor deleted");
  int n = LENGTH(bufs);
  const void** in_bufs = (const void**)calloc(n, sizeof(void*));
  int* dtypes = (int*)calloc(n, sizeof(int));
  const int64_t** in_shapes = (const int64_t**)calloc(n, sizeof(void*));
  int* ndims = (int*)calloc(n, sizeof(int));
  int64_t** owned = (int64_t**)calloc(n, sizeof(void*));
  /* R numeric vectors are double; the C ABI wants float32 — repack */
  float** packed = (float**)calloc(n, sizeof(void*));
  for (int i = 0; i < n; i++) {
    SEXP b = VECTOR_ELT(bufs, i);
    SEXP s = VECTOR_ELT(shapes, i);
    int len = LENGTH(b);
    packed[i] = (float*)calloc(len, sizeof(float));
    for (int j = 0; j < len; j++) packed[i][j] = (float)REAL(b)[j];
    in_bufs[i] = packed[i];
    dtypes[i] = PD_DTYPE_FLOAT32;
    int nd = LENGTH(s);
    owned[i] = (int64_t*)calloc(nd, sizeof(int64_t));
    for (int j = 0; j < nd; j++) owned[i][j] = (int64_t)INTEGER(s)[j];
    in_shapes[i] = owned[i];
    ndims[i] = nd;
  }
  int rc = PD_PredictorRun(h, in_bufs, dtypes, in_shapes, ndims, n);
  for (int i = 0; i < n; i++) {
    free(owned[i]);
    free(packed[i]);
  }
  free(owned);
  free(packed);
  free(in_bufs);
  free(dtypes);
  free(in_shapes);
  free(ndims);
  if (rc != 0) error("PD_PredictorRun: %s", PD_GetLastError());

  int n_out = PD_PredictorNumOutputs(h);
  SEXP out = PROTECT(allocVector(VECSXP, n_out));
  for (int i = 0; i < n_out; i++) {
    const float* data;
    const int64_t* shape;
    int ndim;
    if (PD_PredictorOutput(h, i, &data, &shape, &ndim) != 0)
      error("PD_PredictorOutput: %s", PD_GetLastError());
    R_xlen_t count = 1;
    for (int j = 0; j < ndim; j++) count *= (R_xlen_t)shape[j];
    SEXP arr = PROTECT(allocVector(REALSXP, count));
    for (R_xlen_t j = 0; j < count; j++) REAL(arr)[j] = (double)data[j];
    SEXP dim = PROTECT(allocVector(INTSXP, ndim));
    for (int j = 0; j < ndim; j++) INTEGER(dim)[j] = (int)shape[j];
    setAttrib(arr, R_DimSymbol, dim);
    SET_VECTOR_ELT(out, i, arr);
    UNPROTECT(2);
  }
  UNPROTECT(1);
  return out;
}

SEXP R_PD_Delete(SEXP ptr) {
  pd_finalizer(ptr);
  return R_NilValue;
}
