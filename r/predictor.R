# paddle_tpu R inference client (analog of the reference r/ client over
# its C++ predictor API). The C ABI returns pointers, so the binding is a
# small compiled shim (pd_shim.c) exposing .Call entry points; this file
# is the user-facing R surface over it.
#
# Usage (direct C route; requires the built libpaddle_tpu_capi):
#   source("predictor.R")
#   p <- pd_new_predictor("/path/model", "")
#   out <- pd_run(p, list(matrix(runif(8), nrow = 2)))   # list of arrays
#   pd_delete_predictor(p)
#
# The wrapper .so exports R-callable shims (R_PD_*) over the C ABI; build
# it once with:
#   R CMD SHLIB r/pd_shim.c -L paddle_tpu/_native/lib -lpaddle_tpu_capi

pd_lib_loaded <- FALSE

pd_load <- function(shim_path = "pd_shim.so") {
  dyn.load(shim_path)
  pd_lib_loaded <<- TRUE
  invisible(TRUE)
}

pd_new_predictor <- function(model_prefix, cipher_key_hex = "") {
  stopifnot(pd_lib_loaded)
  .Call("R_PD_NewPredictor", as.character(model_prefix),
        as.character(cipher_key_hex))
}

pd_run <- function(predictor, inputs) {
  stopifnot(pd_lib_loaded)
  # inputs: list of numeric arrays; shapes are taken from dim()
  bufs <- lapply(inputs, function(x) as.single(as.vector(x)))
  shapes <- lapply(inputs, function(x) {
    d <- dim(x)
    if (is.null(d)) length(x) else d
  })
  .Call("R_PD_Run", predictor, bufs, shapes)
}

pd_delete_predictor <- function(predictor) {
  stopifnot(pd_lib_loaded)
  .Call("R_PD_Delete", predictor)
  invisible(NULL)
}
