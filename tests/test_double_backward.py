"""create_graph double backward on the tape (reference imperative
partial_grad_engine create_graph; previously NotImplementedError). The
recorded engine re-derives each node's vjp from its stored primal closure
inside record_op, so gradients are tape-linked and differentiate again."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tape import grad


def T(x, sg=False):
    return paddle.to_tensor(np.asarray(x, "float32"), stop_gradient=sg)


def test_second_and_third_derivative():
    x = T([2.0, 3.0])
    y = x * x * x
    (g1,) = grad(y, [x], create_graph=True)
    np.testing.assert_allclose(np.asarray(g1._value), [12.0, 27.0])
    (g2,) = grad(g1.sum(), [x], create_graph=True)
    np.testing.assert_allclose(np.asarray(g2._value), [12.0, 18.0])
    (g3,) = grad(g2.sum(), [x])
    np.testing.assert_allclose(np.asarray(g3._value), [6.0, 6.0])


def test_gradient_penalty_through_backward():
    x = T([1.5])
    y = (x * x * x).sum()
    (g,) = grad(y, [x], create_graph=True)
    ((g * g).sum()).backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), [36 * 1.5 ** 3],
                               rtol=1e-5)


def test_mixed_partials_matmul():
    a = T([[1.0, 2.0], [3.0, 4.0]])
    b = T([[0.5, 1.0], [2.0, 0.1]])
    out = paddle.matmul(a, b).sum()
    (ga,) = grad(out, [a], create_graph=True)
    # d(sum(dout/da))/db: sum(ga) = sum_j b_kj summed rows -> d/db = ones
    (gb,) = grad(ga.sum(), [b])
    np.testing.assert_allclose(np.asarray(gb._value),
                               np.full((2, 2), 2.0), rtol=1e-6)


def test_grad_outputs_seed_and_allow_unused():
    x = T([1.0, 2.0])
    z = T([3.0])
    y = x * 2.0
    seed = T([10.0, 20.0], sg=True)
    (g,) = grad([y], [x], grad_outputs=[seed], create_graph=True)
    np.testing.assert_allclose(np.asarray(g._value), [20.0, 40.0])
    gx, gz = grad([y.sum()], [x, z], create_graph=True, allow_unused=True)
    assert gz is None
    np.testing.assert_allclose(np.asarray(gx._value), [2.0, 2.0])


def test_double_backward_through_network():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(3, 8), nn.Tanh(), nn.Linear(8, 1))
    x = T(np.random.RandomState(0).randn(4, 3))
    y = net(x).sum()
    (gx,) = grad(y, [x], create_graph=True)
    penalty = (gx * gx).sum()
    params = [p for p in net.parameters() if not p.stop_gradient]
    gps = grad(penalty, params, allow_unused=True)
    found = [g for g in gps if g is not None
             and np.abs(np.asarray(g._value)).sum() > 0]
    assert found, "gradient penalty produced no parameter gradients"


def test_first_order_unaffected():
    x = T([4.0])
    y = (x * x).sum()
    (g,) = grad(y, [x])
    assert g.stop_gradient
    np.testing.assert_allclose(np.asarray(g._value), [8.0])
