"""The cluster telemetry plane's tier-1 proof: tools/cluster_obs_drill.py
runs a 3-shard-server (+1 backup each) PS fleet, a serve+online-train
client, and a TelemetryHub under seeded RESET/DROP chaos plus a scripted
decode-beat STALL, then permanently kills a shard primary mid-run.

The drill itself asserts the hard invariants (one coalesced incident,
>=3 processes in the merged dump, a trace id crossing client->primary->
backup, hub counter totals bitwise-equal to per-process sums, exactly
the scripted SLO breach); this test runs it end-to-end the way CI does
and cross-checks the printed report.
"""
import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.chaos

DRILL = os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     "cluster_obs_drill.py")


def test_cluster_obs_drill_end_to_end(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               CLUSTER_OBS_DIR=str(tmp_path))
    proc = subprocess.run(
        [sys.executable, DRILL], env=env, cwd="/root/repo",
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-2000:],
                                  proc.stderr[-4000:])
    report = json.loads(proc.stdout)
    assert report["violations"] == 0
    assert report["incidents"] == 1
    assert set(report["alerts"]) == {"serve_ttft"}   # scripted breach ONLY
    assert report["stall_fired"] >= 1
    assert len(report["incident_members"]) >= 4      # client + 3 servers
    assert report["cross_process_chains"] >= 1
    # the merged incident dump landed where we pointed it
    assert any(f.startswith("incident_") and f.endswith(".json")
               for f in os.listdir(str(tmp_path)))


def test_cluster_obs_drill_self_check():
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [sys.executable, DRILL, "--self-check"], env=env,
        cwd="/root/repo", capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    assert "clean" in proc.stdout
