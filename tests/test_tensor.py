"""Tensor basics: creation, dtype rules, operators, indexing, numpy interop.

Models the reference's tensor unittests
(python/paddle/fluid/tests/unittests/test_var_base.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_basic():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == (2, 2)
    assert t.dtype == paddle.float32
    np.testing.assert_allclose(t.numpy(), [[1, 2], [3, 4]])


def test_default_dtypes():
    assert paddle.to_tensor(1.5).dtype == paddle.float32
    assert paddle.to_tensor(3).dtype == paddle.int64
    assert paddle.to_tensor(True).dtype == np.bool_
    assert paddle.to_tensor(np.float64(2.0)).dtype == paddle.float32
    assert paddle.to_tensor(np.array([1], dtype="int32")).dtype == paddle.int32


def test_creation_ops():
    assert paddle.zeros([2, 3]).shape == (2, 3)
    assert paddle.ones([4], dtype="int32").dtype == paddle.int32
    np.testing.assert_allclose(paddle.full([2], 7.0).numpy(), [7, 7])
    np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
    e = paddle.eye(3)
    np.testing.assert_allclose(e.numpy(), np.eye(3))
    z = paddle.zeros_like(paddle.ones([2, 2]))
    np.testing.assert_allclose(z.numpy(), np.zeros((2, 2)))


def test_arithmetic_operators():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((2.0 + a).numpy(), [3, 4, 5])
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])
    np.testing.assert_allclose(abs(paddle.to_tensor([-1.0, 2.0])).numpy(), [1, 2])


def test_comparison_and_logic():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([2.0, 2.0, 2.0])
    np.testing.assert_array_equal((a > b).numpy(), [False, False, True])
    np.testing.assert_array_equal((a == b).numpy(), [False, True, False])
    assert bool(paddle.ops.allclose(a, a))


def test_matmul():
    a = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
    b = paddle.to_tensor(np.arange(12, dtype="float32").reshape(3, 4))
    c = a @ b
    np.testing.assert_allclose(c.numpy(), a.numpy() @ b.numpy())
    assert paddle.matmul(a, b).shape == (2, 4)


def test_indexing():
    x = paddle.to_tensor(np.arange(24, dtype="float32").reshape(2, 3, 4))
    np.testing.assert_allclose(x[0].numpy(), x.numpy()[0])
    np.testing.assert_allclose(x[:, 1].numpy(), x.numpy()[:, 1])
    np.testing.assert_allclose(x[0, 1, 2].item(), 6.0)
    np.testing.assert_allclose(x[..., -1].numpy(), x.numpy()[..., -1])
    idx = paddle.to_tensor([0, 1])
    np.testing.assert_allclose(x[idx].numpy(), x.numpy()[[0, 1]])


def test_setitem():
    x = paddle.zeros([3, 3])
    x[1] = 5.0
    np.testing.assert_allclose(x.numpy()[1], [5, 5, 5])
    x[0, 0] = 1.0
    assert x[0, 0].item() == 1.0


def test_reshape_and_friends():
    x = paddle.to_tensor(np.arange(12, dtype="float32"))
    assert x.reshape([3, 4]).shape == (3, 4)
    assert x.reshape([3, -1]).shape == (3, 4)
    assert x.reshape([3, 4]).transpose([1, 0]).shape == (4, 3)
    assert x.reshape([1, 12, 1]).squeeze().shape == (12,)
    assert x.unsqueeze(0).shape == (1, 12)
    assert x.reshape([3, 4]).flatten().shape == (12,)
    assert paddle.concat([x, x]).shape == (24,)
    assert paddle.stack([x, x]).shape == (2, 12)
    parts = paddle.split(x.reshape([3, 4]), 2, axis=1)
    assert len(parts) == 2 and parts[0].shape == (3, 2)
    parts = paddle.split(x.reshape([3, 4]), [1, 3], axis=1)
    assert parts[1].shape == (3, 3)


def test_reductions():
    x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
    assert x.sum().item() == 15.0
    np.testing.assert_allclose(x.sum(axis=0).numpy(), [3, 5, 7])
    assert x.mean().item() == 2.5
    assert x.max().item() == 5.0
    assert x.argmax().item() == 5
    np.testing.assert_allclose(x.min(axis=1).numpy(), [0, 3])
    assert x.prod(axis=1).shape == (2,)


def test_cast():
    x = paddle.to_tensor([1.5, 2.5])
    y = x.astype("int32")
    assert y.dtype == paddle.int32
    assert y.stop_gradient
    z = x.astype(paddle.bfloat16)
    assert z.dtype == paddle.bfloat16


def test_topk_sort():
    x = paddle.to_tensor([3.0, 1.0, 4.0, 1.0, 5.0])
    vals, idx = paddle.topk(x, 2)
    np.testing.assert_allclose(vals.numpy(), [5, 4])
    np.testing.assert_array_equal(idx.numpy(), [4, 2])
    np.testing.assert_allclose(paddle.sort(x).numpy(), [1, 1, 3, 4, 5])


def test_where_gather_scatter():
    x = paddle.to_tensor([1.0, 2.0, 3.0, 4.0])
    cond = paddle.to_tensor([True, False, True, False])
    np.testing.assert_allclose(paddle.where(cond, x, -x).numpy(), [1, -2, 3, -4])
    np.testing.assert_allclose(paddle.gather(x, paddle.to_tensor([2, 0])).numpy(), [3, 1])
    out = paddle.scatter(x, paddle.to_tensor([0, 1]), paddle.to_tensor([10.0, 20.0]))
    np.testing.assert_allclose(out.numpy(), [10, 20, 3, 4])


def test_random_reproducible():
    paddle.seed(42)
    a = paddle.randn([4])
    paddle.seed(42)
    b = paddle.randn([4])
    np.testing.assert_allclose(a.numpy(), b.numpy())
    u = paddle.uniform([1000], min=0.0, max=1.0)
    assert 0.0 <= float(u.min()) and float(u.max()) <= 1.0


def test_einsum():
    a = np.random.rand(2, 3).astype("float32")
    b = np.random.rand(3, 4).astype("float32")
    out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)


def test_detach_and_clone():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    d = x.detach()
    assert d.stop_gradient
    c = x.clone()
    assert not c.stop_gradient
