"""Recompute / activation checkpointing (VERDICT r02 item 4; reference
RecomputeOptimizer fluid/optimizer.py:4526, backward.py:701).

Correctness contract: gradients with recompute on must equal gradients
with it off — rematerialization changes memory, never math.
"""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.core import rng as _rng
from paddle_tpu.core import tape as _tape
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import fleet, recompute


def _mlp():
    paddle.seed(3)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 16),
                         nn.GELU(), nn.Linear(16, 4))


def test_manual_recompute_grads_match():
    net = _mlp()
    params, buffers = net.functional_state()
    x = jnp.asarray(np.random.RandomState(0).randn(4, 8), jnp.float32)

    def loss_fn(p, use_rc):
        with _tape.no_grad(), _rng.rng_state(jax.random.PRNGKey(0)):
            net.load_functional_state(p, buffers)
            xt = Tensor(x, _internal=True)
            if use_rc:
                h = recompute(net[0], xt)        # single layer
                h = recompute(lambda t: net[3](net[2](net[1](t))), h,
                              policy="dots")     # a segment, dots policy
                out = net[4](h)
            else:
                out = net(xt)
            return (out._value ** 2).mean()

    l0, g0 = jax.value_and_grad(lambda p: loss_fn(p, False))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss_fn(p, True))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for k in g0:
        np.testing.assert_allclose(np.asarray(g0[k]), np.asarray(g1[k]),
                                   atol=1e-6, err_msg=k)


def test_manual_recompute_eager_passthrough():
    net = _mlp()
    x = paddle.randn([4, 8])
    x.stop_gradient = False
    out = recompute(net, x)  # eager: passthrough, still differentiable
    out.sum().backward()
    assert net[0].weight.grad is not None


def test_layer_enable_recompute_in_hapi_fit():
    """strategy.recompute through Model.prepare: transformer blocks get
    wrapped, loss/grads stay identical to the plain run."""
    from paddle_tpu.io import TensorDataset

    def build(with_rc):
        paddle.seed(11)
        net = nn.Sequential(
            nn.Embedding(64, 16),
            nn.TransformerEncoder(
                nn.TransformerEncoderLayer(16, 2, 32, dropout=0.0), 2),
            nn.Linear(16, 8))
        opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        if with_rc:
            strategy = fleet.DistributedStrategy()
            strategy.recompute = True
            opt = fleet.distributed_optimizer(opt, strategy)
        model = paddle.Model(net)
        model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss())
        return net, model

    rng = np.random.RandomState(5)
    X = rng.randint(0, 64, (32, 12)).astype("int64")
    Y = rng.randint(0, 8, (32, 12)).astype("int64")
    hist = []
    for with_rc in (False, True):
        net, model = build(with_rc)
        if with_rc:
            enc_layers = [s for _, s in net.named_sublayers()
                          if isinstance(s, nn.TransformerEncoderLayer)]
            assert enc_layers and all(s._recompute for s in enc_layers)
        from paddle_tpu.hapi.callbacks import History
        h = History()
        paddle.seed(42)  # identical step keys / batch order for both runs
        model.fit(TensorDataset([X, Y]), batch_size=16, epochs=2, verbose=0,
                  shuffle=False, callbacks=[h])
        hist.append(h.history["loss"])
    np.testing.assert_allclose(hist[0], hist[1], rtol=1e-5)


def test_static_recompute_segments():
    """Static Program: checkpoints split the op list; fetches and loss
    match the unsegmented lowering."""
    def run(with_rc):
        paddle.enable_static()
        try:
            import paddle_tpu.static as static
            paddle.seed(7)
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [4, 8], "float32")
                h1 = nn.Linear(8, 16)(x)
                h1 = paddle.tanh(h1) if hasattr(paddle, "tanh") else h1
                h2 = nn.Linear(16, 16)(h1)
                out = nn.Linear(16, 1)(h2)
                loss = paddle.mean(out) if hasattr(paddle, "mean") else out
                opt = optimizer.SGD(learning_rate=0.1)
                if with_rc:
                    strategy = fleet.DistributedStrategy()
                    strategy.recompute = True
                    strategy.recompute_configs = {
                        "checkpoints": [h1.name, h2.name]}
                    opt = fleet.distributed_optimizer(opt, strategy)
                opt.minimize(loss)
            exe = static.Executor()
            exe.run(startup)
            xs = np.random.RandomState(1).randn(4, 8).astype("float32")
            vals = [exe.run(main, feed={"x": xs},
                            fetch_list=[loss])[0] for _ in range(3)]
            return [float(np.asarray(v)) for v in vals]
        finally:
            paddle.disable_static()

    base = run(False)
    rc = run(True)
    np.testing.assert_allclose(base, rc, rtol=1e-5)
    assert base[0] != base[-1]  # training actually moved


def test_tp_plus_recompute_dryrun_mesh():
    """BASELINE config 5 shape: model-parallel + recompute on the 8-device
    mesh — a full fwd+bwd step compiles and yields a finite loss."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.sharding import param_spec_for
    from paddle_tpu.text.models.bert import Bert, BertConfig, \
        BertPretrainingCriterion

    mesh = mesh_mod.init_mesh({"dp": 4, "tp": 2})
    cfg = BertConfig.tiny()
    paddle.seed(0)
    net = Bert(cfg)
    net.train()
    for _, sub in net.named_sublayers():
        if isinstance(sub, nn.TransformerEncoderLayer):
            sub.enable_recompute(policy="dots")
    criterion = BertPretrainingCriterion(cfg.vocab_size)
    params, buffers = net.functional_state()
    shardings = {k: NamedSharding(mesh, param_spec_for(k, v.ndim))
                 for k, v in params.items()}
    data_sh = NamedSharding(mesh, P("dp"))

    def step(p, ids, labels, key):
        with _rng.rng_state(key), _tape.no_grad():
            def loss_of(pp):
                net.load_functional_state(pp, buffers)
                logits = net(Tensor(ids, _internal=True))
                return criterion(logits,
                                 Tensor(labels, _internal=True))._value
            return jax.value_and_grad(loss_of)(p)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(4, cfg.vocab_size, (8, 16)), jnp.int64)
    labels = jnp.asarray(np.where(rng.rand(8, 16) < 0.15,
                                  rng.randint(4, cfg.vocab_size, (8, 16)),
                                  -100), jnp.int64)
    jstep = jax.jit(step, in_shardings=(shardings, data_sh, data_sh, None))
    with mesh:
        loss, grads = jstep(params, ids, labels, jax.random.PRNGKey(0))
    assert np.isfinite(float(loss))
    assert all(np.isfinite(np.asarray(g)).all() for g in grads.values())
    mesh_mod.init_mesh({"dp": 8})
