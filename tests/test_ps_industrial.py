"""Industrial PS remainder (VERDICT r03 item 6): Downpour-style sparse
pull/push inside train_from_dataset, mid-train table snapshot/restore,
and a kill-the-server recovery run. References:
framework/downpour_worker.cc, operators/distributed/large_scale_kv.h."""
import os
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.distributed.ps import PSClient, PSServer


VOCAB, DIM = 32, 4


@pytest.fixture()
def server():
    srv = PSServer(tables={
        "emb": {"type": "sparse", "dim": DIM, "optimizer": "sgd", "lr": 0.5,
                "init": "zeros"}})
    srv.start()
    yield srv
    srv.shutdown()


class _IdsDataset:
    """Minimal dataset yielding {'ids': [b], 'label': [b,1]} batches."""

    def __init__(self, n_batches=12, b=8, seed=0):
        rng = np.random.RandomState(seed)
        self._batches = []
        for _ in range(n_batches):
            ids = rng.randint(0, VOCAB, (b,)).astype("int64")
            lab = (ids % 2).astype("float32").reshape(b, 1)
            self._batches.append({"ids": ids, "label": lab})

    def batches(self):
        yield from self._batches


def _build_program():
    from paddle_tpu import nn, optimizer
    paddle.enable_static()
    main = static.Program("downpour")
    with static.program_guard(main):
        ids = static.data("ids", [-1], "int64")
        label = static.data("label", [-1, 1], "float32")
        emb = nn.Embedding(VOCAB, DIM)
        head = nn.Linear(DIM, 1, bias_attr=False)
        rows = emb(ids)
        logits = head(rows)
        loss = paddle.ops.mean(
            paddle.nn.functional.binary_cross_entropy_with_logits(
                logits, label))
        opt = optimizer.SGD(learning_rate=0.5)
        opt.minimize(loss)
    return main, loss, emb.weight.scope_name, head.weight.scope_name


@pytest.fixture(autouse=True)
def _dynamic_after():
    yield
    paddle.disable_static()


def test_downpour_train_from_dataset(server):
    client = PSClient([server.endpoint])
    main, loss, emb_name, head_name = _build_program()
    exe = static.Executor()
    scope = static.global_scope()

    head_before = np.asarray(scope.get(head_name)).copy()
    ds = _IdsDataset(n_batches=30, b=16)
    it_losses = []
    orig_run = exe.run

    def run_and_record(*a, **k):
        outs = orig_run(*a, **k)
        if k.get("fetch_list"):
            it_losses.append(float(np.asarray(outs[0]).mean()))
        return outs

    exe.run = run_and_record
    exe.train_from_dataset(
        program=main, dataset=ds, fetch_list=[loss],
        ps_config={"client": client,
                   "sparse": [{"param": emb_name, "slot": "ids",
                               "table": "emb"}]})
    exe.run = orig_run

    # the authoritative embedding rows live on the server and must have
    # trained (server-side sgd accessor applied the pushed grads)
    ids = np.arange(VOCAB, dtype=np.int64)
    server_rows = client.pull_sparse("emb", ids)
    assert np.abs(server_rows).sum() > 0, "server table never updated"
    # the local optimizer section excluded the PS param but trained head
    opt_params = [p.name for p, _ in main.optimizer_section[1]]
    assert emb_name not in opt_params
    assert not np.allclose(np.asarray(scope.get(head_name)), head_before)
    # loss goes down over the downpour loop
    first, last = np.mean(it_losses[:5]), np.mean(it_losses[-5:])
    assert last < first - 0.02, (first, last)
    client.close()


def test_snapshot_restore_midtrain(server, tmp_path):
    client = PSClient([server.endpoint])
    rng = np.random.RandomState(0)
    ids = np.arange(8, dtype=np.int64)
    # train the table a bit
    client.pull_sparse("emb", ids)
    client.push_sparse_grad("emb", ids, rng.randn(8, DIM).astype("float32"))
    trained = client.pull_sparse("emb", ids)

    snap = str(tmp_path / "ps_snap")
    client.save_snapshot(snap)
    assert os.path.exists(snap + ".s0")

    # keep training past the snapshot, then "fail" and restore
    client.push_sparse_grad("emb", ids, rng.randn(8, DIM).astype("float32"))
    after = client.pull_sparse("emb", ids)
    assert not np.allclose(after, trained)
    client.load_snapshot(snap)
    restored = client.pull_sparse("emb", ids)
    np.testing.assert_allclose(restored, trained, rtol=1e-6)
    client.close()


def test_server_death_and_restart_with_snapshot(tmp_path):
    """Kill-the-server recovery: state survives via the snapshot file and
    a fresh server process (the elastic-restart contract; reference
    heart_beat_monitor.cc + large_scale_kv checkpointing)."""
    spec = {"emb": {"type": "sparse", "dim": DIM, "optimizer": "sgd",
                    "lr": 0.5, "init": "zeros"}}
    srv = PSServer(tables=spec)
    srv.start()
    client = PSClient([srv.endpoint])
    ids = np.arange(6, dtype=np.int64)
    client.pull_sparse("emb", ids)
    client.push_sparse_grad("emb", ids,
                            np.ones((6, DIM), "float32"))
    trained = client.pull_sparse("emb", ids)
    snap = str(tmp_path / "snap")
    client.save_snapshot(snap)
    client.close()
    srv.shutdown()          # hard stop — the "failure"

    srv2 = PSServer(tables=spec)
    srv2.start()
    c2 = PSClient([srv2.endpoint])
    assert np.abs(c2.pull_sparse("emb", ids)).sum() == 0  # fresh tables
    c2.load_snapshot(snap)
    np.testing.assert_allclose(c2.pull_sparse("emb", ids), trained,
                               rtol=1e-6)
    c2.close()
    srv2.shutdown()
