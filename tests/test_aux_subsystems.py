"""Aux subsystems: enforce errors (N25), Program passes + DOT dumps (N10),
LogWriter/VisualDL (5.5), SIGTERM preemption guard (5.3)."""
import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer
from paddle_tpu.core import enforce


def test_enforce_taxonomy():
    with pytest.raises(enforce.InvalidArgumentError):
        enforce.enforce(False, "nope")
    with pytest.raises(ValueError):  # typed errors are also builtins
        enforce.enforce_eq(1, 2)
    with pytest.raises(enforce.EnforceNotMet):
        enforce.check_type(3, "x", str)
    enforce.check_shape([2, -1, 3])
    with pytest.raises(enforce.InvalidArgumentError):
        enforce.check_shape([0, 2])
    enforce.enforce_ge(2, 2)


def test_program_passes_and_dot(tmp_path):
    import paddle_tpu.static as static
    from paddle_tpu.static.passes import apply_pass, graph_viz
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4], "float32")
            y = ops.sum(x * 2.0)
            _dead = ops.exp(x) + 5.0  # feeds nothing
            main._jit_fetch_vars = [y]
        n_before = len(main.ops)
        pruned = apply_pass(main, "eliminate_dead_ops")
        assert len(pruned.ops) < n_before
        exe = static.Executor()
        out = exe.run(pruned, feed={"x": np.ones(4, "float32")},
                      fetch_list=[y])[0]
        assert float(out) == 8.0

        dot = graph_viz(main, path=os.path.join(tmp_path, "g.dot"))
        assert dot.startswith("digraph") and "sum" in dot
        assert os.path.exists(os.path.join(tmp_path, "g.dot"))
    finally:
        paddle.disable_static()


def test_log_writer_and_visualdl_callback(tmp_path):
    from paddle_tpu.hapi.callbacks import VisualDL
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.utils import read_scalars

    paddle.seed(0)
    X = np.random.rand(32, 4).astype("float32")
    Y = X @ np.random.rand(4, 1).astype("float32")
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(optimizer=optimizer.SGD(learning_rate=0.1,
                                          parameters=net.parameters()),
                  loss=nn.MSELoss())
    logdir = os.path.join(tmp_path, "vdl")
    model.fit(TensorDataset([X, Y]), batch_size=8, epochs=2, verbose=0,
              callbacks=[VisualDL(logdir)])
    recs = read_scalars(logdir, tag="train/loss")
    assert len(recs) == 8
    assert recs[-1]["value"] < recs[0]["value"]
    assert read_scalars(logdir, tag="epoch/loss")


CHILD = textwrap.dedent("""
    import os, signal, threading, time
    import jax; jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.hapi.callbacks import Callback

    class SigtermAt(Callback):
        def __init__(self): self.n = 0
        def on_train_batch_end(self, step, logs=None):
            self.n += 1
            if self.n == 3:   # mid-epoch, NOT on a save interval
                os.kill(os.getpid(), signal.SIGTERM)

    paddle.seed(5)
    X = np.random.rand(32, 4).astype("float32")
    Y = (X @ np.random.rand(4, 1).astype("float32"))
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(optimizer=optimizer.SGD(learning_rate=0.1,
                                          parameters=net.parameters()),
                  loss=nn.MSELoss())
    model.fit(TensorDataset([X, Y]), batch_size=8, epochs=4, verbose=0,
              shuffle=False, callbacks=[SigtermAt()],
              auto_checkpoint_dir={ckpt_dir!r},
              auto_checkpoint_freq=100)   # periodic saves never fire
""")


def test_sigterm_grace_checkpoint(tmp_path):
    """SIGTERM mid-epoch forces one synchronous checkpoint at the exact
    step, even though the periodic interval never fired."""
    ckpt_dir = os.path.join(str(tmp_path), "ckpt")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [sys.executable, "-c", CHILD.format(ckpt_dir=ckpt_dir)],
        env=env, cwd="/root/repo", capture_output=True, text=True,
        timeout=300)
    assert proc.returncode == -signal.SIGTERM, (proc.returncode,
                                                proc.stderr[-2000:])
    from paddle_tpu.incubate.checkpoint import TrainingCheckpoint
    ck = TrainingCheckpoint(ckpt_dir)
    assert ck.latest_step() == 3
    st = ck.restore()
    assert st["counters"]["global_step"] == 3


# ------------------------- elastic / heartbeats ----------------------------

def test_heartbeat_update_and_check(tmp_path):
    from paddle_tpu.distributed.elastic import Heartbeat
    hb = Heartbeat(str(tmp_path), rank=0, interval_s=0.05).start()
    hb.update(step=7)
    import json
    with open(hb.path) as f:
        rec = json.load(f)
    assert rec["rank"] == 0 and rec["step"] == 7
    assert Heartbeat.check(str(tmp_path), timeout_s=60) == []
    hb.stop()
    import time
    time.sleep(0.15)
    assert Heartbeat.check(str(tmp_path), timeout_s=0.05) == [0]


def test_stall_monitor_fires():
    import time
    from paddle_tpu.distributed.elastic import StallMonitor
    fired = []
    with StallMonitor(timeout_s=0.2, on_stall=fired.append) as m:
        m.step_done()
        time.sleep(0.5)
    assert fired and fired[0] >= 0.2
    assert m.stalled


def test_launch_elastic_restart(tmp_path):
    """A trainer that crashes on its first attempt and succeeds after a
    restart (state via a marker file, standing in for auto-checkpoint
    resume)."""
    import textwrap
    from paddle_tpu.distributed.launch import launch
    script = os.path.join(str(tmp_path), "train.py")
    marker = os.path.join(str(tmp_path), "attempted")
    with open(script, "w") as f:
        f.write(textwrap.dedent(f"""
            import os, sys
            if not os.path.exists({marker!r}):
                open({marker!r}, "w").close()
                sys.exit(1)       # first attempt: crash
            sys.exit(0)           # resumed attempt: success
        """))
    assert launch(script, nproc_per_node=1, elastic_retries=2) == 0
    with pytest.raises(SystemExit):
        os.remove(marker)
        launch(script, nproc_per_node=1, elastic_retries=0)
