"""AMP tests.

Models the reference's amp op tests (test_amp_check_finite_and_scale_op.py,
test_update_loss_scaling_op.py) and API tests (test_amp_api / hapi amp)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import amp, nn, optimizer


def test_autocast_white_op_bf16():
    x = paddle.ones([4, 8])
    w = paddle.ones([8, 4])
    with amp.auto_cast(level="O1"):
        y = paddle.ops.matmul(x, w)
    assert y._value.dtype == jnp.bfloat16


def test_autocast_black_op_f32():
    x = paddle.ones([4, 8]).astype("bfloat16")
    with amp.auto_cast(level="O1"):
        y = paddle.ops.softmax(x)
    assert y._value.dtype == jnp.float32


def test_autocast_gray_op_keeps_dtype():
    x = paddle.ones([4])
    with amp.auto_cast(level="O1"):
        y = x + x
    assert y._value.dtype == jnp.float32


def test_autocast_o2_casts_gray():
    x = paddle.ones([4])
    with amp.auto_cast(level="O2"):
        y = x + x
    assert y._value.dtype == jnp.bfloat16


def test_autocast_off_outside_context():
    x = paddle.ones([4, 8])
    w = paddle.ones([8, 4])
    y = paddle.ops.matmul(x, w)
    assert y._value.dtype == jnp.float32


def test_autocast_custom_lists():
    x = paddle.ones([4, 8])
    w = paddle.ones([8, 4])
    with amp.auto_cast(level="O1", custom_black_list={"matmul"}):
        y = paddle.ops.matmul(x, w)
    assert y._value.dtype == jnp.float32


def test_autocast_grad_dtype_matches_param():
    # the cast sits inside the differentiated region: f32 leaves get f32
    # grads even when compute ran in bf16
    w = paddle.ones([8, 4])
    w.stop_gradient = False
    x = paddle.ones([2, 8])
    with amp.auto_cast(level="O1"):
        y = paddle.ops.matmul(x, w)
    y.sum().backward()
    assert w.grad._value.dtype == jnp.float32


def test_check_finite_and_unscale():
    grads = {"a": jnp.asarray([2.0, 4.0]), "b": jnp.asarray([8.0])}
    out, found = amp.check_finite_and_unscale(grads, jnp.asarray(2.0))
    assert not bool(found)
    np.testing.assert_allclose(np.asarray(out["a"]), [1.0, 2.0])
    grads["b"] = jnp.asarray([jnp.inf])
    out, found = amp.check_finite_and_unscale(grads, jnp.asarray(2.0))
    assert bool(found)


def test_update_loss_scaling_dynamics():
    s = jnp.asarray(1024.0, jnp.float32)
    good = jnp.asarray(0, jnp.int32)
    bad = jnp.asarray(0, jnp.int32)
    # two consecutive nan steps at decr_every_n_nan_or_inf=2 halve the scale
    s1, good, bad = amp.update_loss_scaling(
        s, good, bad, jnp.asarray(True), incr_ratio=2.0, decr_ratio=0.5,
        incr_every_n_steps=3, decr_every_n_nan_or_inf=2)
    assert float(s1) == 1024.0 and int(bad) == 1
    s2, good, bad = amp.update_loss_scaling(
        s1, good, bad, jnp.asarray(True), incr_ratio=2.0, decr_ratio=0.5,
        incr_every_n_steps=3, decr_every_n_nan_or_inf=2)
    assert float(s2) == 512.0 and int(bad) == 0
    # three good steps double it
    for _ in range(3):
        s2, good, bad = amp.update_loss_scaling(
            s2, good, bad, jnp.asarray(False), incr_ratio=2.0,
            decr_ratio=0.5, incr_every_n_steps=3, decr_every_n_nan_or_inf=2)
    assert float(s2) == 1024.0


def test_grad_scaler_eager_skip_on_inf():
    lin = nn.Linear(4, 4)
    opt = optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    scaler = amp.GradScaler(init_loss_scaling=4.0,
                            use_dynamic_loss_scaling=True,
                            decr_every_n_nan_or_inf=1)
    w_before = np.asarray(lin.weight._value).copy()
    x = paddle.to_tensor(np.full((2, 4), np.inf, np.float32))
    loss = scaler.scale(lin(x).sum())
    loss.backward()
    scaler.step(opt)   # found_inf -> update skipped
    scaler.update()    # scale halves
    np.testing.assert_array_equal(np.asarray(lin.weight._value), w_before)
    assert scaler.get_loss_scaling() == 2.0
    opt.clear_grad()
    # finite step updates params and resets
    x = paddle.ones([2, 4])
    loss = scaler.scale(lin(x).sum())
    loss.backward()
    scaler.step(opt)
    scaler.update()
    assert not np.array_equal(np.asarray(lin.weight._value), w_before)


def test_master_weight_optimizer():
    # bf16 param + multi_precision: master slot carries f32 precision, so
    # many tiny updates that vanish in bf16 accumulate correctly
    w = paddle.ones([64]).astype("bfloat16")
    w.stop_gradient = False
    w.name = "w"
    opt = optimizer.SGD(learning_rate=1.0, parameters=[w],
                        multi_precision=True)
    params = {"w": w._value}
    opt._ensure_slots(params)
    assert opt._slots["w"]["master"].dtype == jnp.float32
    slots = dict(opt._slots)
    g = jnp.full((64,), 1e-4, jnp.bfloat16)  # 1 - 1e-4 rounds to 1 in bf16
    p, s = params, slots
    for t in range(100):
        p, s = opt.apply_gradients_pure(
            p, {"w": g}, s, jnp.asarray(1.0), jnp.asarray(t + 1))
    master = np.asarray(s["w"]["master"])
    np.testing.assert_allclose(master, 1.0 - 1e-2, rtol=1e-3)
    # without master weights the bf16 param would still be exactly 1.0;
    # the cast-back is only bf16-accurate (eps ~ 0.004 at 1.0)
    assert abs(float(np.asarray(p["w"])[0]) - (1.0 - 1e-2)) < 4e-3


def test_hapi_fit_with_amp_o2():
    from paddle_tpu.hapi import Model
    from paddle_tpu.static import InputSpec

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))
    model = Model(net, inputs=[InputSpec([None, 8], "float32", "x")],
                  labels=[InputSpec([None], "int64", "y")])
    opt = optimizer.AdamW(learning_rate=0.01, parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(),
                  amp_configs={"level": "O2", "dtype": "bfloat16"})
    # O2 decorate: params cast to bf16, optimizer has master weights
    assert net[0].weight._value.dtype == jnp.bfloat16
    assert opt._multi_precision

    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    import paddle_tpu.io as io
    ds = [(x[i], y[i]) for i in range(64)]
    losses = []
    for ep in range(4):
        out = model.fit(ds, batch_size=16, epochs=1, verbose=0)
        l0 = model.evaluate(ds, batch_size=32, verbose=0)["loss"]
        losses.append(l0)
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
    # master slots exist for every trainable param
    for k, s in opt._slots.items():
        assert "master" in s and s["master"].dtype == jnp.float32


def test_fp16_amp_with_scaler_in_fit():
    from paddle_tpu.hapi import Model
    from paddle_tpu.static import InputSpec

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = Model(net, inputs=[InputSpec([None, 8], "float32", "x")],
                  labels=[InputSpec([None], "int64", "y")])
    opt = optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(),
                  amp_configs={"level": "O1", "dtype": "float16",
                               "init_loss_scaling": 128.0})
    scaler = model._amp_configs["scaler"]
    assert scaler is not None
    rng = np.random.RandomState(1)
    x = rng.randn(32, 8).astype(np.float32)
    y = (x[:, 1] > 0).astype(np.int64)
    ds = [(x[i], y[i]) for i in range(32)]
    model.fit(ds, batch_size=8, epochs=2, verbose=0)
    assert scaler.get_loss_scaling() > 0


def test_static_amp_program_level():
    import paddle_tpu.static as static

    paddle.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [4, 8], "float32")
            w = static.nn.fc(x, 16)
            loss = paddle.ops.mean(w)
            opt = optimizer.SGD(learning_rate=0.1)
            opt = static.amp.decorate(opt, level="O1")
            opt.minimize(loss)
        assert prog.amp_level == "O1"
        exe = static.Executor()
        rng = np.random.RandomState(0)
        out1 = exe.run(prog, feed={"x": rng.randn(4, 8).astype(np.float32)},
                       fetch_list=[loss])
        out2 = exe.run(prog, feed={"x": rng.randn(4, 8).astype(np.float32)},
                       fetch_list=[loss])
        assert np.isfinite(out1[0]).all() and np.isfinite(out2[0]).all()
    finally:
        paddle.disable_static()


def test_adamw_master_weight_decay_accumulates():
    # decoupled decay must land on the f32 master, not only the bf16 copy
    w = (paddle.ones([32]) * 2.0).astype("bfloat16")
    w.stop_gradient = False
    w.name = "w"
    opt = optimizer.AdamW(learning_rate=0.1, weight_decay=0.1,
                          parameters=[w], multi_precision=True)
    params = {"w": w._value}
    opt._ensure_slots(params)
    s = dict(opt._slots)
    p = params
    zero_g = {"w": jnp.zeros([32], jnp.bfloat16)}
    masters = []
    for t in range(3):
        p, s = opt.apply_gradients_pure(p, zero_g, s, jnp.asarray(0.1),
                                        jnp.asarray(t + 1))
        masters.append(float(np.asarray(s["w"]["master"])[0]))
    # with zero grads, each step multiplies the master by (1 - lr*wd)=0.99
    np.testing.assert_allclose(masters, [2 * 0.99, 2 * 0.99 ** 2,
                                         2 * 0.99 ** 3], rtol=1e-5)


def test_fp16_scaler_with_grad_accumulation():
    from paddle_tpu.hapi import Model
    from paddle_tpu.static import InputSpec

    paddle.seed(3)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    model = Model(net, inputs=[InputSpec([None, 8], "float32", "x")],
                  labels=[InputSpec([None], "int64", "y")])
    opt = optimizer.SGD(learning_rate=0.05, parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(),
                  amp_configs={"level": "O1", "dtype": "float16",
                               "init_loss_scaling": 64.0})
    rng = np.random.RandomState(1)
    x = rng.randn(32, 8).astype(np.float32)
    y = (x[:, 1] > 0).astype(np.int64)
    ds = [(x[i], y[i]) for i in range(32)]
    l0 = model.evaluate(ds, batch_size=32, verbose=0)["loss"]
    model.fit(ds, batch_size=8, epochs=3, verbose=0,
              accumulate_grad_batches=2)
    l1 = model.evaluate(ds, batch_size=32, verbose=0)["loss"]
    assert l1 < l0, f"accumulated fp16 training did not learn: {l0} -> {l1}"


def _amp_key(prefix, prog_name):
    import paddle_tpu.static as static
    return next(k for k in static.global_scope().var_names()
                if k.startswith(f"{prefix}@{prog_name}#"))


def test_static_fp16_dynamic_loss_scaling_trains():
    """VERDICT r04 item 5: static MNIST-style training in fp16 with the
    scale adapting in-program, matching the bf16 loss curve."""
    import paddle_tpu.static as static

    def run_training(dtype):
        paddle.enable_static()
        try:
            paddle.seed(0)
            prog = static.Program(f"fp16_{dtype}")
            with static.program_guard(prog):
                x = static.data("x", [-1, 8], "float32")
                y = static.data("y", [-1, 1], "float32")
                net = paddle.nn.Linear(8, 1, bias_attr=False)
                loss = paddle.ops.mse_loss(net(x), y)
                opt = optimizer.SGD(learning_rate=0.05)
                opt = static.amp.decorate(
                    opt, level="O1", dtype=dtype,
                    init_loss_scaling=2.0 ** 10,
                    incr_every_n_steps=5)
                opt.minimize(loss)
            exe = static.Executor()
            exe.run(static.default_startup_program())
            rng = np.random.RandomState(0)
            X = rng.rand(64, 8).astype("float32")
            W = rng.rand(8, 1).astype("float32")
            Y = X @ W
            losses = []
            for _ in range(60):
                (lv,) = exe.run(prog, feed={"x": X, "y": Y},
                                fetch_list=[loss])
                losses.append(float(lv))
            return losses
        finally:
            paddle.disable_static()

    fp16 = run_training("float16")
    bf16 = run_training("bfloat16")
    assert fp16[-1] < fp16[0] * 0.1, fp16[-1]
    # curves agree to mixed-precision tolerance
    assert abs(fp16[-1] - bf16[-1]) < 0.05, (fp16[-1], bf16[-1])
    # the scale grew (incr_every_n_steps=5 over 60 clean steps)
    scale = float(np.asarray(static.global_scope().get(
        _amp_key("_amp_loss_scale_", "fp16_float16"))))
    assert scale > 2.0 ** 10, scale
    good = int(np.asarray(static.global_scope().get(
        _amp_key("_amp_good_steps_", "fp16_float16"))))
    assert 0 <= good < 5


def test_static_fp16_overflow_skips_update_and_halves_scale():
    import paddle_tpu.static as static

    paddle.enable_static()
    try:
        paddle.seed(0)
        prog = static.Program("fp16_overflow")
        with static.program_guard(prog):
            x = static.data("x", [-1, 4], "float32")
            net = paddle.nn.Linear(4, 1, bias_attr=False)
            # square the pre-activation: feeding 1e4 inputs overflows the
            # fp16 forward -> inf grads -> found_inf path
            h = net(x)
            loss = paddle.ops.mean(h * h)
            opt = optimizer.SGD(learning_rate=0.01)
            opt = static.amp.decorate(
                opt, level="O1", dtype="float16",
                init_loss_scaling=2.0 ** 8,
                decr_every_n_nan_or_inf=1)
            opt.minimize(loss)
        exe = static.Executor()
        exe.run(static.default_startup_program())
        w_before = np.asarray(
            static.global_scope().get(net.weight.scope_name)).copy()
        X = np.full((8, 4), 1e4, "float32")  # overflows fp16 matmul
        exe.run(prog, feed={"x": X}, fetch_list=[loss])
        w_after = np.asarray(
            static.global_scope().get(net.weight.scope_name))
        np.testing.assert_allclose(w_after, w_before)  # update skipped
        scale = float(np.asarray(static.global_scope().get(
            _amp_key("_amp_loss_scale_", prog.name))))
        assert scale == 2.0 ** 7, scale  # halved once
    finally:
        paddle.disable_static()
