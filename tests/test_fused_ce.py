"""Fused linear+cross-entropy kernel: parity with the unfused loss head."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.ops.pallas.fused_ce import fused_linear_cross_entropy as raw_k


@pytest.fixture
def interpret():
    paddle.set_flags({"FLAGS_pallas_interpret": True})
    yield
    paddle.set_flags({"FLAGS_pallas_interpret": False})


def _mk(rng, *shape):
    return paddle.to_tensor(rng.randn(*shape).astype("float32"),
                            stop_gradient=False)


def test_functional_fused_vs_fallback(interpret):
    rng = np.random.RandomState(0)
    n, hd, v = 64, 32, 517
    h1, w1 = _mk(rng, n, hd), _mk(rng, v, hd)
    b1 = _mk(rng, v)
    y = paddle.to_tensor(
        np.where(rng.rand(n) < 0.3, -100, rng.randint(0, v, n)).astype(
            "int64"))

    loss_k = F.fused_linear_cross_entropy(h1, w1, b1, y)
    loss_k.backward()
    gk = [np.asarray(t.grad._value) for t in (h1, w1, b1)]

    paddle.set_flags({"FLAGS_use_fused_ce": False})
    try:
        h2, w2, b2 = (paddle.to_tensor(np.asarray(t._value),
                                       stop_gradient=False)
                      for t in (h1, w1, b1))
        loss_f = F.fused_linear_cross_entropy(h2, w2, b2, y)
        loss_f.backward()
        gf = [np.asarray(t.grad._value) for t in (h2, w2, b2)]
    finally:
        paddle.set_flags({"FLAGS_use_fused_ce": True})

    np.testing.assert_allclose(float(loss_k._value), float(loss_f._value),
                               rtol=1e-6)
    for a, b in zip(gk, gf):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_reduction_modes(interpret):
    rng = np.random.RandomState(1)
    h = _mk(rng, 16, 8)
    w = _mk(rng, 50, 8)
    y = paddle.to_tensor(rng.randint(0, 50, 16).astype("int64"))
    per_tok = F.fused_linear_cross_entropy(h, w, None, y, reduction="none")
    assert tuple(per_tok.shape) == (16,)
    s = F.fused_linear_cross_entropy(h, w, None, y, reduction="sum")
    m = F.fused_linear_cross_entropy(h, w, None, y, reduction="mean")
    np.testing.assert_allclose(float(s._value) / 16, float(m._value),
                               rtol=1e-6)


def test_bert_fused_head_matches_criterion(interpret):
    from paddle_tpu.text.models.bert import (Bert, BertConfig,
                                             BertPretrainingCriterion)
    cfg = BertConfig.tiny()
    paddle.seed(0)
    net = Bert(cfg)
    net.eval()
    rng = np.random.RandomState(2)
    b, s = 2, 16
    ids = paddle.to_tensor(rng.randint(4, cfg.vocab_size, (b, s)).astype(
        "int64"))
    labels = paddle.to_tensor(
        np.where(rng.rand(b, s) < 0.15,
                 rng.randint(4, cfg.vocab_size, (b, s)), -100).astype(
                     "int64"))

    loss_fused = net(ids, masked_lm_labels=labels)
    logits = net(ids)
    loss_ref = BertPretrainingCriterion(cfg.vocab_size)(logits, labels)
    np.testing.assert_allclose(float(loss_fused._value),
                               float(loss_ref._value), rtol=1e-5)

    loss_fused.backward()
    g = net.embeddings.word_embeddings.weight.grad
    assert g is not None and np.isfinite(np.asarray(g._value)).all()


def test_gpt_causal_flag_and_fused_loss(interpret):
    """GPT's is_causal path (flash-eligible) matches explicit-mask
    attention; the fused LM loss matches manual CE."""
    from paddle_tpu import nn as pnn
    from paddle_tpu.text.models.gpt import GPT, GPTConfig

    cfg = GPTConfig.tiny()
    paddle.seed(4)
    net = GPT(cfg)
    net.eval()
    rng = np.random.RandomState(0)
    b, s = 2, 16
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (b, s)).astype(
        "int64"))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (b, s)).astype(
        "int64"))

    logits = net(ids)
    # causal correctness: position t must not see positions > t — perturb
    # a late token and check early logits unchanged
    ids2 = np.asarray(ids._value).copy()
    ids2[:, -1] = (ids2[:, -1] + 1) % cfg.vocab_size
    logits2 = net(paddle.to_tensor(ids2))
    np.testing.assert_allclose(np.asarray(logits._value)[:, : s - 1],
                               np.asarray(logits2._value)[:, : s - 1],
                               atol=1e-5)

    loss_fused = net(ids, labels=labels)
    ce = pnn.CrossEntropyLoss(ignore_index=-100)
    v = cfg.vocab_size
    import paddle_tpu.ops as ops
    loss_ref = ce(ops.reshape(logits, [b * s, v]),
                  ops.reshape(labels, [b * s]))
    np.testing.assert_allclose(float(loss_fused._value),
                               float(loss_ref._value), rtol=1e-5)
    loss_fused.backward()
    assert net.wte.weight.grad is not None


def test_gpt_generate(interpret):
    from paddle_tpu.text.models.gpt import GPT, GPTConfig
    cfg = GPTConfig.tiny()
    paddle.seed(7)
    net = GPT(cfg)
    net.eval()
    prompt = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 4)).astype(
            "int64"))
    # greedy: deterministic
    out1 = net.generate(prompt, max_new_tokens=6, temperature=0)
    out2 = net.generate(prompt, max_new_tokens=6, temperature=0)
    a, b = np.asarray(out1._value), np.asarray(out2._value)
    assert a.shape == (2, 10)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a[:, :4], np.asarray(prompt._value))
    # sampling with top_k produces valid ids
    out3 = net.generate(prompt, max_new_tokens=3, temperature=1.0, top_k=5)
    v = np.asarray(out3._value)
    assert v.shape == (2, 7) and (v >= 0).all() and (v < cfg.vocab_size).all()
    # eos early stop
    eos = int(a[0, 4])  # force an eos that will occur greedily
    out4 = net.generate(prompt, max_new_tokens=6, temperature=0,
                        eos_token_id=eos)
    assert np.asarray(out4._value).shape[1] <= 10
