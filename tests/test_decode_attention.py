"""Pallas decode-attention kernel: parity with the jnp StaticKVCache path.

Interpret-mode (FLAGS_pallas_interpret) parity tests vs
_static_cache_attention / _sdpa — cache-length masking at several index
values, ragged per-batch lengths, bf16/f32 tolerances, and the vjp-free
eval contract (training-time cache attention stays on the jnp path).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.nn.layer.transformer import _static_cache_attention
from paddle_tpu.ops.pallas.decode_attention import decode_attention, supported


@pytest.fixture
def interpret():
    paddle.set_flags({"FLAGS_pallas_interpret": True})
    yield
    paddle.set_flags({"FLAGS_pallas_interpret": False})


def _ref_ragged(q, kc, vc, lengths, scale):
    """Dense numpy oracle with per-batch live lengths (row r of batch i
    attends to cache cols <= lengths[i] - s + r)."""
    b, h, s, d = q.shape
    L = kc.shape[2]
    out = []
    for i in range(b):
        index = int(lengths[i]) - s
        live = np.arange(L)[None, :] <= index + np.arange(s)[:, None]
        sc = np.einsum("hsd,hld->hsl", np.asarray(q[i], np.float32),
                       np.asarray(kc[i], np.float32)) * scale
        sc = np.where(live[None], sc, -1e9)
        p = np.exp(sc - sc.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        out.append(np.einsum("hsl,hld->hsd", p,
                             np.asarray(vc[i], np.float32)))
    return np.stack(out)


@pytest.mark.parametrize("index,s", [(0, 8), (0, 1), (17, 1), (31, 1),
                                     (96, 32), (127, 1)])
def test_matches_static_cache_attention(interpret, index, s):
    """Scalar cache index at several fill levels, incl. empty-cache
    prefill (index=0) and a full cache (index + s == L)."""
    rng = np.random.RandomState(0)
    b, h, d, L = 2, 3, 16, 128
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    kc = jnp.asarray(rng.randn(b, h, L, d), jnp.float32)
    vc = jnp.asarray(rng.randn(b, h, L, d), jnp.float32)
    idx = jnp.int32(index)

    out = decode_attention(q, kc, vc, idx)
    ref = _static_cache_attention(q, kc, vc, idx, d ** -0.5, 0.0, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ragged_per_batch_lengths(interpret):
    """A [b] index vector: each batch row attends its own prefix — the
    jnp path can't express this without a materialized mask."""
    rng = np.random.RandomState(1)
    b, h, s, d, L = 4, 2, 1, 32, 256
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    kc = jnp.asarray(rng.randn(b, h, L, d), jnp.float32)
    vc = jnp.asarray(rng.randn(b, h, L, d), jnp.float32)
    index = jnp.asarray([0, 17, 130, 255], jnp.int32)

    out = decode_attention(q, kc, vc, index)
    ref = _ref_ragged(q, kc, vc, np.asarray(index) + s, d ** -0.5)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)


def test_bf16_tolerance(interpret):
    rng = np.random.RandomState(2)
    b, h, s, d, L = 2, 2, 1, 32, 128
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
    kc = jnp.asarray(rng.randn(b, h, L, d), jnp.bfloat16)
    vc = jnp.asarray(rng.randn(b, h, L, d), jnp.bfloat16)
    idx = jnp.int32(40)
    out = decode_attention(q, kc, vc, idx)
    assert out.dtype == jnp.bfloat16
    ref = _static_cache_attention(q.astype(jnp.float32),
                                  kc.astype(jnp.float32),
                                  vc.astype(jnp.float32), idx, d ** -0.5,
                                  0.0, False)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               atol=2e-2)


def test_under_jit_traced_index(interpret):
    """The generate() scan passes a traced index; the scalar-prefetch grid
    must handle it (this is the whole point of the design)."""
    rng = np.random.RandomState(3)
    b, h, s, d, L = 2, 2, 1, 16, 64
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    kc = jnp.asarray(rng.randn(b, h, L, d), jnp.float32)
    vc = jnp.asarray(rng.randn(b, h, L, d), jnp.float32)

    fn = jax.jit(lambda q, kc, vc, i: decode_attention(q, kc, vc, i))
    for index in (0, 13, 63):
        out = fn(q, kc, vc, jnp.int32(index))
        ref = _static_cache_attention(q, kc, vc, jnp.int32(index),
                                      d ** -0.5, 0.0, False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)


def test_block_k_override_and_flag(interpret):
    rng = np.random.RandomState(4)
    b, h, s, d, L = 1, 1, 1, 16, 256
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    kc = jnp.asarray(rng.randn(b, h, L, d), jnp.float32)
    vc = jnp.asarray(rng.randn(b, h, L, d), jnp.float32)
    ref = _static_cache_attention(q, kc, vc, jnp.int32(100), d ** -0.5,
                                  0.0, False)
    for bk in (64, 128, 256):
        out = decode_attention(q, kc, vc, jnp.int32(100), block_k=bk)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
    paddle.set_flags({"FLAGS_decode_block_k": 64})
    try:
        out = decode_attention(q, kc, vc, jnp.int32(100))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
    finally:
        paddle.set_flags({"FLAGS_decode_block_k": 0})


def test_supported_gate():
    assert supported((2, 4, 1, 64), (2, 4, 1024, 64))
    assert supported((2, 4, 32, 64), (2, 4, 1024, 64))     # chunked prefill
    assert not supported((2, 4, 1, 512), (2, 4, 1024, 512))  # head too wide
    assert not supported((2, 4, 512, 64), (2, 4, 1024, 64))  # prefill, not
    assert not supported((2, 4, 1, 64), (2, 2, 1024, 64))    # heads differ


def test_mha_cache_path_uses_kernel_in_eval(interpret):
    """MultiHeadAttention + StaticKVCache routes through the decode kernel
    in eval mode (hit counter) and matches the jnp path bit-for-bit-ish;
    training with dropout stays on jnp (gate counter)."""
    from paddle_tpu import nn
    paddle.seed(0)
    mha = nn.MultiHeadAttention(32, 2, dropout=0.5)
    mha.eval()
    x = paddle.randn([2, 4, 32])
    cache = mha.gen_static_cache(2, 16, "float32")

    for name in list(monitor.stats("pallas.")):
        monitor.reset(name)
    out_k, _ = mha(x, cache=cache)
    assert monitor.stat_get("pallas.hit.decode_attention") == 1

    paddle.set_flags({"FLAGS_use_decode_attention": False})
    try:
        out_j, _ = mha(x, cache=cache)
    finally:
        paddle.set_flags({"FLAGS_use_decode_attention": True})
    np.testing.assert_allclose(np.asarray(out_k._value),
                               np.asarray(out_j._value), atol=2e-5)
    assert monitor.stat_get(
        "pallas.gate_reject.decode_attention.flag_off") == 1

    # training mode: gate keeps the kernel out (vjp-free contract — even
    # at dropout=0 the kernel must not end up in a differentiated graph)
    mha.train()
    _ = mha(x, cache=mha.gen_static_cache(2, 16, "float32"))
    assert monitor.stat_get(
        "pallas.gate_reject.decode_attention.training") == 1


def test_gpt_generate_cached_kernel_matches_oracle(interpret):
    """End to end: tiny-GPT generate(use_cache=True) with the decode
    kernel equals the no-cache host-loop oracle (greedy)."""
    from paddle_tpu.text.models.gpt import GPT, GPTConfig
    paddle.seed(0)
    net = GPT(GPTConfig.tiny())
    net.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 1024, (2, 7)).astype("int64"))

    for name in list(monitor.stats("pallas.")):
        monitor.reset(name)
    out_cached = net.generate(ids, max_new_tokens=9, temperature=0,
                              use_cache=True)
    assert monitor.stat_get("pallas.hit.decode_attention") > 0
    out_oracle = net.generate(ids, max_new_tokens=9, temperature=0,
                              use_cache=False)
    np.testing.assert_array_equal(np.asarray(out_cached._value),
                                  np.asarray(out_oracle._value))
