"""tools/spmd_lint.py in-process (ISSUE 3 satellite): the golden GPT TP
config must lint clean (this test IS the tier-1 invocation, as
test_framework_lint is for the framework gate), every --inject seam must
produce its named diagnostic and a failing exit code, and the tool must
be wired into framework_lint's cross-check registry."""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import framework_lint  # noqa: E402
import spmd_lint  # noqa: E402


def test_golden_config_is_clean(capsys):
    assert spmd_lint.self_check() == []
    assert spmd_lint.main([]) == 0
    out = capsys.readouterr().out
    assert "all_reduce" in out and "diagnostics: none" in out
    assert "per-device HBM estimate" in out


def test_report_contents():
    report, program, logits = spmd_lint.build_report(tp=2, layers=2)
    assert report.mesh_axes == {"tp": 2}
    ar = [c for c in report.collectives if c.kind == "all_reduce"]
    assert len(ar) == 5 and all(c.bytes > 0 for c in ar)
    assert report.hbm["peak_bytes"] < report.hbm_replicated["peak_bytes"]


def test_injections_fail_with_named_diagnostic(capsys):
    for inject in spmd_lint.INJECTIONS:
        assert spmd_lint.main(["--inject", inject]) == 1
        out = capsys.readouterr().out
        assert inject in out, f"--inject {inject} did not surface {inject}"


def test_pp_wire_cost_reported(capsys):
    assert spmd_lint.main(["--pp", "4", "--micro", "8"]) == 0
    out = capsys.readouterr().out
    assert "ppermute" in out and "11" in out  # 8 + 4 - 1 ticks


def test_registered_in_framework_lint_cross_checks():
    assert "spmd_lint" in framework_lint.TOOL_CROSS_CHECKS
    # and the registry check actually ran it (clean repo -> no findings)
    assert framework_lint.check_registered_tools() == []


def test_inject_nondivisible_does_not_corrupt_program():
    """The --inject non-divisible seam (ISSUE 10 satellite): repeated
    build_report calls in one process must not see the corrupted aval —
    the seam now swaps an aval VIEW into a cloned Program instead of
    mutating the real persistable."""
    report, program, _ = spmd_lint.build_report(inject="non-divisible")
    assert any(d.code == "non-divisible" for d in report.diagnostics)
    # the injected program carries the odd vocab...
    wte = next(v for v in program.persistable_vars.values()
               if v.aval.shape[1] == 64 and v.aval.shape[0] % 2 == 1)
    assert wte.aval.shape[0] == 1025
    # ...but a fresh build in the same process is pristine
    report2, program2, _ = spmd_lint.build_report()
    assert report2.diagnostics == []
    assert all(v.aval.shape[0] % 2 == 0
               for v in program2.persistable_vars.values()
               if len(v.aval.shape) == 2 and v.aval.shape[1] == 64)
    assert spmd_lint.self_check() == []
