"""Composition test: dp x tp x sp mesh + recompute + Pallas flash
attention + fused CE + bf16 params in ONE jitted training step. Features
that pass alone but fight when composed are the classic framework failure
mode; this pins the full stack."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu import optimizer as opt_mod
from paddle_tpu.core import rng as _rng
from paddle_tpu.core import tape as _tape
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.sharding import param_spec_for
from paddle_tpu.text.models.bert import Bert, BertConfig


def test_everything_composes_one_step():
    paddle.set_flags({"FLAGS_pallas_interpret": True})
    try:
        mesh = mesh_mod.init_mesh({"dp": 2, "tp": 2, "sp": 2})
        cfg = BertConfig.tiny()
        paddle.seed(0)
        net = Bert(cfg)
        net.train()
        for _, sub in net.named_sublayers():
            if isinstance(sub, nn.TransformerEncoderLayer):
                sub.enable_recompute(policy="dots")

        optimizer = opt_mod.AdamW(learning_rate=1e-3,
                                  parameters=net.parameters(),
                                  multi_precision=True)
        params, buffers = net.functional_state()
        params = {k: v.astype(jnp.bfloat16)
                  if v.dtype == jnp.float32 else v
                  for k, v in params.items()}
        named = dict(net.named_parameters())
        optimizer._ensure_slots(params)
        slots = dict(optimizer._slots)
        meta = optimizer._param_meta(named)

        shardings = {k: NamedSharding(mesh, param_spec_for(k, v.ndim))
                     for k, v in params.items()}
        slot_sh = {k: {s: shardings[k] for s in slots[k]} for k in slots}
        data_sh = NamedSharding(mesh, P("dp", "sp"))
        repl = NamedSharding(mesh, P())

        def train_step(params, slots, ids, labels, lr, t, key):
            with _rng.rng_state(key), _tape.no_grad():
                def loss_of(p):
                    net.load_functional_state(p, buffers)
                    # fused CE head (pallas, interpret on CPU)
                    loss = net(Tensor(ids, _internal=True),
                               masked_lm_labels=Tensor(labels,
                                                       _internal=True))
                    return loss._value.astype(jnp.float32)

                loss, grads = jax.value_and_grad(loss_of)(params)
                new_p, new_s = optimizer.apply_gradients_pure(
                    params, grads, slots, lr, t, param_meta=meta)
            return loss, new_p, new_s

        step = jax.jit(
            train_step,
            in_shardings=(shardings, slot_sh, data_sh, data_sh, repl,
                          repl, repl),
            out_shardings=(repl, shardings, slot_sh),
            donate_argnums=(0, 1))

        rng = np.random.RandomState(0)
        b, s = 4, 32
        ids = jnp.asarray(rng.randint(4, cfg.vocab_size, (b, s)), jnp.int64)
        labels = jnp.asarray(
            np.where(rng.rand(b, s) < 0.15,
                     rng.randint(4, cfg.vocab_size, (b, s)), -100),
            jnp.int64)
        with mesh:
            losses = []
            for t in range(2):
                loss, params, slots = step(
                    params, slots, ids, labels,
                    jnp.asarray(1e-3, jnp.float32),
                    jnp.asarray(t + 1, jnp.int32),
                    jax.random.PRNGKey(t))
                losses.append(float(np.asarray(loss)))
        assert all(np.isfinite(losses)), losses
        assert losses[1] < losses[0], losses  # learning on the same batch
        # bf16 params kept bf16; master slots stayed f32
        anyp = next(iter(params.values()))
        assert any(v.dtype == jnp.bfloat16 for v in params.values())
        assert any("master" in s for s in slots.values())
    finally:
        paddle.set_flags({"FLAGS_pallas_interpret": False})
        mesh_mod.init_mesh({"dp": 8})
