"""Mosaic bitwidth guard: no 64-bit value may appear inside a Pallas kernel.

paddle_tpu enables jax_enable_x64 globally (int64 labels are first-class
Paddle semantics), but the TPU Mosaic compiler aborts the whole process on
any 64-bit kernel value (layout.h `has_single_bit(bitwidth_) && bitwidth_
<= 32`). CPU interpret-mode tests can't catch that — the kernels run fine
interpreted with f64 tiles — so this test traces every kernel entry point
and walks the captured kernel jaxprs asserting every intermediate is
<= 32-bit. This is the regression guard for the round-3 failure where
`jnp.where(col == y, 1.0, 0.0)` (scalar-scalar where => f64 under x64)
silently made BENCH fall back to the jnp paths.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

import paddle_tpu  # noqa: F401  — turns on jax_enable_x64
# the package __init__ shadows the submodule names with the functions, so
# fetch the modules from sys.modules via importlib
import importlib

fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")
fc = importlib.import_module("paddle_tpu.ops.pallas.fused_ce")


def _walk_jaxprs(jaxpr, found):
    for eqn in jaxpr.eqns:
        if "pallas_call" in eqn.primitive.name:
            found.append(eqn.params["jaxpr"])
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if inner is not None:
                _walk_jaxprs(inner, found)
            if isinstance(v, (list, tuple)):
                for vv in v:
                    inner = getattr(vv, "jaxpr", None)
                    if inner is not None:
                        _walk_jaxprs(inner, found)
    return found


def _assert_no_64bit(fn, *args):
    jaxpr = jax.make_jaxpr(fn)(*args)
    kernels = _walk_jaxprs(jaxpr.jaxpr, [])
    assert kernels, "no pallas_call found — test is vacuous"

    def check(kj):
        for eqn in kj.eqns:
            for v in list(eqn.invars) + list(eqn.outvars):
                if isinstance(v, jax.extend.core.Literal):
                    # 64-bit scalar literals (e.g. the constant 0 in
                    # `ref[0]`) lower to in-range index constants and are
                    # fine; only *computed* 64-bit values trip Mosaic
                    continue
                aval = getattr(v, "aval", None)
                if aval is None or not hasattr(aval, "dtype"):
                    continue
                if getattr(aval, "shape", ()) == ():
                    # scalar weak-f64 constants (NEG_INF etc.) are folded
                    # into their f32 consumers before Mosaic sees them; the
                    # crash class is 64-bit *tiles* (r03: a [bn,bv] f64 from
                    # a scalar-scalar jnp.where)
                    continue
                itemsize = jnp.dtype(aval.dtype).itemsize
                assert itemsize <= 4, (
                    f"64-bit value in pallas kernel: {eqn.primitive.name} "
                    f"-> {aval.dtype}{getattr(aval, 'shape', ())} — Mosaic "
                    "will SIGABRT on TPU (layout.h bitwidth check)")
            for p in eqn.params.values():
                inner = getattr(p, "jaxpr", None)
                if inner is not None:
                    check(inner)

    for kj in kernels:
        check(getattr(kj, "jaxpr", kj))


@pytest.mark.parametrize("causal,with_bias", [(False, False), (True, False),
                                              (False, True), (True, True)])
def test_flash_attention_kernels_32bit(causal, with_bias):
    b, h, s, d = 2, 2, 64, 32
    q = jnp.zeros((b, h, s, d), jnp.bfloat16)
    bias = jnp.zeros((b, s), jnp.float32) if with_bias else None

    def fwd(q, k, v):
        return fa.flash_attention(q, k, v, bias=bias, causal=causal)

    _assert_no_64bit(fwd, q, q, q)

    def bwd(q, k, v):
        return jax.grad(lambda q, k, v: fwd(q, k, v).astype(
            jnp.float32).sum(), argnums=(0, 1, 2))(q, k, v)

    _assert_no_64bit(bwd, q, q, q)


@pytest.mark.parametrize("with_bias,ragged_vocab", [(True, True),
                                                    (False, False)])
def test_fused_ce_kernels_32bit(with_bias, ragged_vocab):
    n, hd, v = 64, 32, (300 if ragged_vocab else 256)
    h = jnp.zeros((n, hd), jnp.bfloat16)
    w = jnp.zeros((v, hd), jnp.bfloat16)
    b = jnp.zeros((v,), jnp.float32) if with_bias else None
    y = jnp.zeros((n,), jnp.int32)

    def fwd(h, w):
        return fc.fused_linear_cross_entropy(h, w, b, y).sum()

    _assert_no_64bit(fwd, h, w)

    def bwd(h, w):
        return jax.grad(fwd, argnums=(0, 1))(h, w)

    _assert_no_64bit(bwd, h, w)
