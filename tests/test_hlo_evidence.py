"""tools/hlo_evidence.py tier-1 self-check: the tunnel-independent kernel
evidence harness must run on CPU, produce the documented schema, and its
canonical configs must keep passing every kernel eligibility gate (the
framework_lint TOOL_CROSS_CHECKS registration runs the same self_check)."""
import json
import os
import sys

import pytest

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

import hlo_evidence  # noqa: E402


def test_self_check_clean():
    assert hlo_evidence.self_check() == []


def test_registered_in_framework_lint():
    import framework_lint
    assert "hlo_evidence" in framework_lint.TOOL_CROSS_CHECKS


def test_gates_pass_for_all_bench_shapes():
    """Every bench shape must be kernel-eligible — otherwise the bench
    would silently measure fallback paths again (BENCH_r03)."""
    import importlib
    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")
    fc = importlib.import_module("paddle_tpu.ops.pallas.fused_ce")
    da = importlib.import_module("paddle_tpu.ops.pallas.decode_attention")

    bert, dec, ls = (hlo_evidence.BERT_CFG, hlo_evidence.DECODE_CFG,
                     hlo_evidence.LONGSEQ_CFG)
    assert fc.supported(bert["batch"] * bert["seq"], 768, 30522)
    s = ls["seq"]
    assert fa.supported((ls["batch"], 12, s, 64), (ls["batch"], 12, s, 64),
                        (ls["batch"], 12, s, 64))
    assert da.supported((dec["batch"], 12, 1, 64),
                        (dec["batch"], 12, dec["max_seq_len"], 64))


def test_tiny_run_schema_and_assertions(tmp_path):
    """Run the tool end to end on CPU with toy configs: TPU-target
    lowering must succeed, all three kernels must appear as custom calls,
    and the default-config decode reduction must clear 2x."""
    out = tmp_path / "HLO_EVIDENCE.json"
    report = hlo_evidence.run(str(out), tiny=True)

    data = json.loads(out.read_text())
    assert data == json.loads(json.dumps(report))  # round-trips
    assert data["platform"] == "tpu" and data["tiny"] is True
    for name in ("bert_train_step", "gpt_longseq_train_step",
                 "gpt_decode_step"):
        g = data["graphs"][name]
        assert "custom_calls" in g and "cost_analysis" in g
        assert "config" in g and "pallas_counters" in g

    assert data["graphs"]["bert_train_step"]["custom_calls"].get(
        "_ce_fwd_kernel", 0) > 0
    assert data["graphs"]["gpt_longseq_train_step"]["custom_calls"].get(
        "_flash_fwd_kernel", 0) > 0
    dec = data["graphs"]["gpt_decode_step"]
    assert dec["custom_calls"].get("_decode_attn_kernel", 0) > 0
    assert dec["sdpa_custom_calls"].get("_decode_attn_kernel", 0) == 0
    # cost analysis is computable on CPU for the TPU-lowered module
    assert dec["cost_analysis"].get("flops", -1) > 0
    full = dec["attention_per_step_full_config"]
    assert full["flops_reduction_x"] >= 2.0
    assert full["bytes_reduction_x"] >= 2.0
    assert data["ok"], [a for a in data["assertions"] if not a["ok"]]


def test_decode_attention_model_math():
    m = hlo_evidence.decode_attention_model(
        {"max_seq_len": 1024, "prompt": 32, "new": 128, "batch": 8},
        heads=12, head_dim=64, layers=12, bk=128)
    # live cols never exceed the cache and never shrink below one block
    assert 128 <= m["avg_live_cols_kernel"] <= 1024
    assert m["sdpa_full_cache"]["flops"] > m["decode_kernel"]["flops"]
    assert m["flops_reduction_x"] == pytest.approx(
        1024 / m["avg_live_cols_kernel"], rel=1e-2)
