"""Numeric-vs-analytic gradient checks across the op library.

The reference runs this contract for all 700+ ops via OpTest.check_grad
(op_test.py:1329); here a representative slab of every op family is swept.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad, check_output

rng = np.random.RandomState(7)


def u(*shape):
    return rng.uniform(0.5, 1.5, shape).astype("float64")


def s(*shape):
    return rng.uniform(-1.0, 1.0, shape).astype("float64")


ELEMENTWISE_UNARY = [
    (paddle.ops.exp, u(3, 4)),
    (paddle.ops.log, u(3, 4)),
    (paddle.ops.sqrt, u(3, 4)),
    (paddle.ops.rsqrt, u(3, 4)),
    (paddle.ops.square, s(3, 4)),
    (paddle.ops.tanh, s(3, 4)),
    (paddle.ops.sin, s(3, 4)),
    (paddle.ops.cos, s(3, 4)),
    (paddle.ops.sigmoid, s(3, 4)),
    (paddle.ops.erf, s(3, 4)),
    (paddle.ops.log1p, u(3, 4)),
    (paddle.ops.reciprocal, u(3, 4)),
    (paddle.ops.softplus, s(3, 4)),
    (paddle.ops.silu, s(3, 4)),
    (paddle.ops.mish, s(3, 4)),
]


@pytest.mark.parametrize("op,x", ELEMENTWISE_UNARY,
                         ids=[op.op_name for op, _ in ELEMENTWISE_UNARY])
def test_unary_grad(op, x):
    check_grad(op, [x])


BINARY = [
    (paddle.ops.add, s(3, 4), s(3, 4)),
    (paddle.ops.subtract, s(3, 4), s(3, 4)),
    (paddle.ops.multiply, s(3, 4), s(3, 4)),
    (paddle.ops.divide, s(3, 4), u(3, 4)),
    (paddle.ops.maximum, s(3, 4), s(3, 4)),
    (paddle.ops.minimum, s(3, 4), s(3, 4)),
    (paddle.ops.matmul, s(3, 4), s(4, 5)),
    (paddle.ops.atan2, u(3, 3), u(3, 3)),
]


@pytest.mark.parametrize("op,x,y", BINARY, ids=[op.op_name for op, _, _ in BINARY])
def test_binary_grad(op, x, y):
    check_grad(op, [x, y])


def test_broadcast_binary_grad():
    check_grad(paddle.ops.add, [s(3, 4), s(4)])
    check_grad(paddle.ops.multiply, [s(2, 1, 4), s(3, 1)])


REDUCTIONS = [
    (paddle.ops.sum, dict()),
    (paddle.ops.mean, dict()),
    (paddle.ops.sum, dict(axis=1)),
    (paddle.ops.mean, dict(axis=0, keepdim=True)),
    (paddle.ops.logsumexp, dict()),
    (paddle.ops.prod, dict(axis=1)),
]


@pytest.mark.parametrize("op,attrs", REDUCTIONS)
def test_reduction_grad(op, attrs):
    check_grad(op, [u(3, 4)], **attrs)


def test_max_min_grad():
    x = s(3, 4)
    check_grad(paddle.ops.max, [x])
    check_grad(paddle.ops.min, [x], rtol=5e-3)


MANIP = [
    (paddle.ops.reshape, dict(shape=(4, 3))),
    (paddle.ops.transpose, dict(perm=(1, 0))),
    (paddle.ops.flatten, dict()),
    (paddle.ops.squeeze, dict()),
]


@pytest.mark.parametrize("op,attrs", MANIP)
def test_manip_grad(op, attrs):
    check_grad(op, [s(3, 4)], **attrs)


def test_concat_grad():
    check_grad(lambda a, b: paddle.concat([a, b], axis=1), [s(2, 3), s(2, 4)])


def test_activation_outputs():
    x = s(4, 5)
    check_output(paddle.ops.relu, lambda v: np.maximum(v, 0), [x])
    check_output(paddle.ops.softmax,
                 lambda v: np.exp(v) / np.exp(v).sum(-1, keepdims=True), [x],
                 rtol=1e-4)
    check_output(paddle.ops.sigmoid, lambda v: 1 / (1 + np.exp(-v)), [x])


def test_layer_norm_grad():
    check_grad(lambda x, w, b: paddle.ops.layer_norm(x, w, b),
               [s(4, 8), u(8), s(8)], rtol=5e-3, atol=5e-4)


def test_softmax_grad():
    check_grad(paddle.ops.softmax, [s(3, 5)])


def test_cross_entropy_grad():
    logits = s(4, 5)
    label = np.array([0, 2, 4, 1])

    def op(x):
        return paddle.ops.cross_entropy(x, paddle.to_tensor(label))

    check_grad(op, [logits])


def test_conv2d_forward_matches_naive():
    x = s(1, 2, 5, 5).astype("float32")
    w = s(3, 2, 3, 3).astype("float32")
    out = paddle.ops.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                            stride=1, padding=1)
    # naive correlation
    xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)])
    ref = np.zeros((1, 3, 5, 5), dtype="float64")
    for o in range(3):
        for i in range(2):
            for r in range(5):
                for c in range(5):
                    ref[0, o, r, c] += (xp[0, i, r:r + 3, c:c + 3] * w[o, i]).sum()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)


def test_conv2d_grad():
    check_grad(lambda x, w: paddle.ops.conv2d(x, w, stride=1, padding=1),
               [s(1, 2, 4, 4), s(2, 2, 3, 3)], rtol=5e-3, atol=5e-4)


def test_pool_grads():
    check_grad(lambda x: paddle.ops.avg_pool2d(x, 2), [s(1, 2, 4, 4)])
    check_grad(lambda x: paddle.ops.max_pool2d(x, 2), [u(1, 2, 4, 4) + np.arange(16).reshape(1, 1, 4, 4)])


def test_batch_norm_train_output():
    x = s(4, 3, 2, 2).astype("float32")
    rm = np.zeros(3, "float32")
    rv = np.ones(3, "float32")
    out, nrm, nrv = paddle.ops.batch_norm(
        paddle.to_tensor(x), paddle.to_tensor(rm), paddle.to_tensor(rv),
        training=True)
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    ref = (x - mean[None, :, None, None]) / np.sqrt(var[None, :, None, None] + 1e-5)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(nrm.numpy(), 0.1 * mean, rtol=1e-4, atol=1e-5)


def test_embedding_grad():
    w = s(10, 4)
    ids = np.array([1, 3, 3, 7])

    def op(weight):
        return paddle.ops.embedding(weight, paddle.to_tensor(ids))

    check_grad(op, [w])


def test_gather_grad():
    idx = np.array([2, 0, 1])

    def op(x):
        return paddle.ops.gather(x, paddle.to_tensor(idx))

    check_grad(op, [s(4, 3)])


def test_losses_forward():
    x = u(4, 3)
    y = u(4, 3)
    check_output(paddle.ops.mse_loss, lambda a, b: ((a - b) ** 2).mean(), [x, y])
    check_output(paddle.ops.l1_loss, lambda a, b: np.abs(a - b).mean(), [x, y])
    check_grad(paddle.ops.mse_loss, [x, y])
    check_grad(paddle.ops.binary_cross_entropy_with_logits, [s(4, 3), (u(4, 3) > 1.0).astype("float64")], wrt=[0])
