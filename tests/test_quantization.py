"""Quantization: fake-quant STE, QAT wrapping, PTQ calibration, int8
export (reference slim/quantization + fake_quantize_op.cc)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.quantization import (PTQ, QAT, QuantConfig, fake_quant,
                                     weight_quantize)

T = paddle.to_tensor


def test_fake_quant_values_and_ste():
    x = T(np.array([-2.0, -0.5, 0.0, 0.4, 2.0], "float32"))
    x.stop_gradient = False
    y = fake_quant(x, 1.0, bits=8)
    v = y.numpy()
    assert abs(v[2]) < 1e-7
    assert v[0] == -1.0 and v[-1] == 1.0        # clipped to scale
    assert abs(v[3] - 0.4) < 1.0 / 127          # quantization step
    y.sum().backward()
    g = np.asarray(x.grad._value)
    np.testing.assert_allclose(g, [0, 1, 1, 1, 0])  # STE inside the range


def test_qat_trains_and_converges():
    paddle.seed(0)
    np.random.seed(0)
    X = np.random.rand(64, 8).astype("float32")
    Y = X @ np.random.rand(8, 1).astype("float32")
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    qat = QAT(QuantConfig())
    net = qat.quantize(net)
    from paddle_tpu.quantization import QuantedLinear
    assert sum(isinstance(s, QuantedLinear)
               for _, s in net.named_sublayers()) == 2
    opt = optimizer.Adam(learning_rate=0.02, parameters=net.parameters())
    losses = []
    for _ in range(60):
        loss = ((net(T(X)) - T(Y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])
    qat.convert(net)
    out = net(T(X))
    assert np.isfinite(out.numpy()).all()


def test_ptq_calibration_sets_scales():
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 4))
    ptq = PTQ()
    net = ptq.quantize(net)
    big = T((np.random.rand(16, 4) * 5).astype("float32"))
    for _ in range(3):
        net(big)  # calibration passes
    from paddle_tpu.quantization import AbsmaxObserver
    obs = [s for _, s in net.named_sublayers()
           if isinstance(s, AbsmaxObserver)]
    assert obs and float(obs[0].scale.numpy()) > 2.0  # saw the range
    ptq.convert(net)
    scale_frozen = float(obs[0].scale.numpy())
    net(T(np.full((4, 4), 100.0, "float32")))
    assert float(obs[0].scale.numpy()) == scale_frozen


def test_weight_quantize_export():
    paddle.seed(2)
    net = nn.Sequential(nn.Linear(4, 8))
    net = QAT().quantize(net)
    packs = weight_quantize(net)
    assert len(packs) == 1
    (pack,) = packs.values()
    assert pack["int8"].dtype == np.int8
    # dequantized int8 approximates the float weight
    deq = pack["int8"].astype(np.float32) / 127.0 * pack["scale"]
    target = np.asarray([s for _, s in net.named_sublayers()
                         if type(s).__name__ == "QuantedLinear"
                         ][0].inner.weight._value)
    np.testing.assert_allclose(deq, target, atol=np.abs(target).max() / 100)
