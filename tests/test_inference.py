"""Inference export: jit.save -> StableHLO artifact -> Predictor round-trip
(reference CreatePaddlePredictor analysis_predictor.cc:1056,
save_inference_model fluid/io.py:1198)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.hapi.model import InputSpec


def _save_model(tmp_path):
    paddle.seed(9)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    net.eval()
    prefix = os.path.join(str(tmp_path), "m")
    paddle.jit.save(net, prefix,
                    input_spec=[InputSpec([None, 8], "float32", name="x")])
    return net, prefix


def test_save_writes_artifacts(tmp_path):
    _, prefix = _save_model(tmp_path)
    for suffix in (".pdmodel", ".pdiparams", ".stablehlo", ".pdinfer.json"):
        assert os.path.exists(prefix + suffix), suffix


def test_predictor_round_trip_in_process(tmp_path):
    net, prefix = _save_model(tmp_path)
    x = np.random.RandomState(0).randn(2, 8).astype("float32")
    want = np.asarray(net(paddle.to_tensor(x))._value)

    from paddle_tpu.inference import Config, create_predictor
    config = Config(prefix)
    pred = create_predictor(config)
    assert pred.get_input_names() == ["x"]
    h = pred.get_input_handle("x")
    h.copy_from_cpu(x)
    pred.run()
    got = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_predictor_symbolic_batch(tmp_path):
    """The exported artifact accepts batch sizes other than the example's."""
    net, prefix = _save_model(tmp_path)
    for b in (1, 3, 7):
        x = np.random.RandomState(b).randn(b, 8).astype("float32")
        want = np.asarray(net(paddle.to_tensor(x))._value)
        from paddle_tpu.inference import Predictor
        got = Predictor(prefix).run([x])[0]
        np.testing.assert_allclose(got, want, atol=1e-5)


def test_predictor_fresh_process_without_model_class(tmp_path):
    """The deployment check: a fresh interpreter that never sees the model's
    Python class (only paddle_tpu.inference) reproduces the outputs."""
    net, prefix = _save_model(tmp_path)
    x = np.random.RandomState(1).randn(4, 8).astype("float32")
    want = np.asarray(net(paddle.to_tensor(x))._value)
    xpath = os.path.join(str(tmp_path), "x.npy")
    opath = os.path.join(str(tmp_path), "out.npy")
    np.save(xpath, x)

    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "from paddle_tpu.inference import Config, create_predictor\n"
        f"pred = create_predictor(Config({prefix!r}))\n"
        f"out = pred.run([np.load({xpath!r})])[0]\n"
        f"np.save({opath!r}, out)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          cwd="/root/repo", capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    got = np.load(opath)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_predictor_fallback_without_stablehlo(tmp_path):
    net, prefix = _save_model(tmp_path)
    os.remove(prefix + ".stablehlo")
    x = np.random.RandomState(2).randn(2, 8).astype("float32")
    want = np.asarray(net(paddle.to_tensor(x))._value)
    from paddle_tpu.inference import Predictor
    got = Predictor(prefix).run([x])[0]
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_no_phantom_submodules():
    """Every name in paddle_tpu._SUBMODULES must import (VERDICT r02 weak
    item 3: incubate/profiler/sysconfig/callbacks/inference were phantom)."""
    import paddle_tpu
    for name in paddle_tpu._SUBMODULES:
        mod = getattr(paddle_tpu, name)
        assert mod is not None, name


def test_incubate_functional_double_backward():
    from paddle_tpu.incubate import functional as IF
    f = lambda x: (x ** 3).sum()  # noqa: E731
    x = paddle.to_tensor(np.array([1.0, 2.0], dtype="float32"))
    g = IF.grad(f)(x)
    np.testing.assert_allclose(np.asarray(g._value), [3.0, 12.0], rtol=1e-6)
    h = IF.hessian(f)(x)
    np.testing.assert_allclose(np.asarray(h._value),
                               [[6.0, 0.0], [0.0, 12.0]], rtol=1e-6)


def test_static_save_inference_model_round_trip(tmp_path):
    """static.save_inference_model -> Predictor in a fresh process
    (reference fluid/io.py:1198 + CreatePaddlePredictor)."""
    import paddle_tpu.static as static
    from paddle_tpu import ops, optimizer

    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [2, 6], "float32")
            h = nn.Linear(6, 3)(x)
            y = ops.softmax(h, axis=-1)
        exe = static.Executor()
        exe.run(startup)
        xs = np.random.RandomState(0).randn(2, 6).astype("float32")
        want = exe.run(main, feed={"x": xs}, fetch_list=[y])[0]
        prefix = os.path.join(str(tmp_path), "static_m")
        static.save_inference_model(prefix, [x], [y], exe)

        # round trip through load_inference_model
        prog2, feeds, fetches = static.load_inference_model(prefix)
        got = exe.run(prog2, feed={"x": xs}, fetch_list=fetches)[0]
        np.testing.assert_allclose(got, want, atol=1e-6)
    finally:
        paddle.disable_static()

    # fresh process via the Predictor over the StableHLO artifact
    opath = os.path.join(str(tmp_path), "o.npy")
    xpath = os.path.join(str(tmp_path), "x.npy")
    np.save(xpath, xs)
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "from paddle_tpu.inference import Predictor\n"
        f"out = Predictor({prefix!r}).run([np.load({xpath!r})])[0]\n"
        f"np.save({opath!r}, out)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          cwd="/root/repo", capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    np.testing.assert_allclose(np.load(opath), want, atol=1e-5)


def test_encrypted_model_round_trip(tmp_path):
    """N35 analog: AES-256-GCM model encryption at rest; wrong/missing key
    fails loudly, right key reproduces outputs."""
    from paddle_tpu.framework.crypto import Cipher, CipherUtils
    from paddle_tpu.inference import Config, Predictor, encrypt_model

    net, prefix = _save_model(tmp_path)
    x = np.random.RandomState(3).randn(2, 8).astype("float32")
    want = np.asarray(net(paddle.to_tensor(x))._value)

    key = CipherUtils.gen_key_to_file(os.path.join(str(tmp_path), "k"))
    encrypt_model(prefix, key)
    assert not os.path.exists(prefix + ".stablehlo")
    assert os.path.exists(prefix + ".stablehlo.enc")

    with pytest.raises(PermissionError, match="encrypted"):
        Predictor(prefix)  # no key -> loud

    cfg = Config(prefix)
    cfg.set_cipher_key(key)
    got = Predictor(cfg).run([x])[0]
    np.testing.assert_allclose(got, want, atol=1e-5)

    bad = Config(prefix)
    bad.set_cipher_key(CipherUtils.gen_key())
    with pytest.raises(Exception):  # authentication failure
        Predictor(bad)

    # raw cipher surface
    c = Cipher(key)
    blob = c.encrypt(b"secret weights")
    assert c.decrypt(blob) == b"secret weights"
    with pytest.raises(Exception):
        c.decrypt(blob[:-1] + bytes([blob[-1] ^ 1]))  # tamper detected


def test_resnet18_trains_tiny():
    """BASELINE config 2 representative: ResNet forward/backward/step."""
    from paddle_tpu.vision.models import resnet18
    from paddle_tpu import optimizer
    paddle.seed(0)
    net = resnet18(num_classes=4)
    opt = optimizer.Momentum(learning_rate=0.01,
                             parameters=net.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).rand(
        2, 3, 32, 32).astype("float32"))
    y = paddle.to_tensor(np.array([0, 3], "int64"))
    ce = nn.CrossEntropyLoss()
    losses = []
    for _ in range(3):
        loss = ce(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
