"""Chaos suite for the fault-tolerant PS transport (ISSUE 2).

Every fault here is INJECTED — seeded and scripted through
paddle_tpu.testing.faults, no real network partitions, no flaky sleeps —
so the suite is deterministic and fast enough for tier-1. The contract
under test mirrors the reference's brpc channel guarantees
(connect_timeout + retry policy + idempotent service handlers):

- transient resets / lost replies / stalls are retried under a deadline,
  and mutating calls apply EXACTLY ONCE via the server replay cache;
- a stall past PADDLE_PS_CALL_TIMEOUT raises DeadlineExceeded naming the
  method and endpoint once the retry budget is spent;
- oversized / garbled frames are rejected cleanly on both ends;
- a full 2-server training run threaded with faults plus a mid-run
  server kill + snapshot restore ends bitwise-equal to a fault-free run;
- the ps.rpc.* monitor counters tick so supervisors can see flakiness.
"""
import socket
import threading

import numpy as np
import pytest

from paddle_tpu.core import monitor
from paddle_tpu.distributed.ps import PSClient, PSServer
from paddle_tpu.distributed.ps import rpc
from paddle_tpu.testing import faults

pytestmark = pytest.mark.chaos

DIM = 4

# tight-but-safe chaos timings: per-attempt deadline far above an
# in-process RPC (~1ms) yet small enough that deadline tests stay fast
FAST = dict(timeout=5.0, max_retries=3, backoff_base=0.01,
            backoff_max=0.05, connect_retry_s=5.0)


def _sparse_spec(optimizer="sgd", lr=1.0):
    return {"type": "sparse", "dim": DIM, "optimizer": optimizer,
            "lr": lr, "init": "zeros"}


def _dense_spec():
    return {"type": "dense", "shape": (3, DIM), "optimizer": "sgd",
            "lr": 0.1, "init": "zeros"}


@pytest.fixture()
def server():
    srv = PSServer(tables={"emb": _sparse_spec(),
                           "dense0": _dense_spec()})
    srv.start()
    yield srv
    srv.shutdown()


@pytest.fixture(autouse=True)
def _no_leftover_injector():
    yield
    faults.uninstall()


def _delta(before, name):
    return monitor.stat_get(name) - before.get(name, 0)


# ---------------------------------------------------------------- retry

def test_retry_survives_connection_reset(server):
    client = PSClient([server.endpoint], **FAST)
    before = monitor.stats("ps.rpc.")
    with faults.inject(faults.Fault("client", "send", faults.RESET,
                                    method="pull_sparse", times=2)) as inj:
        rows = client.pull_sparse("emb", [1, 2, 3])
    assert rows.shape == (3, DIM)
    assert inj.fired(faults.RESET) == 2
    assert _delta(before, "ps.rpc.retries") >= 2
    assert _delta(before, "ps.rpc.reconnects") >= 2
    # counters are part of the public stats() surface
    assert "ps.rpc.retries" in monitor.stats()
    client.close()


def test_reconnect_reruns_auth_handshake(server, monkeypatch):
    # token read at serve() time is already set? serve() captured env at
    # start — spin a dedicated server AFTER setting the token
    monkeypatch.setenv("PADDLE_PS_TOKEN", "sekrit-chaos")
    srv = PSServer(tables={"emb": _sparse_spec()})
    srv.start()
    try:
        client = PSClient([srv.endpoint], **FAST)
        with faults.inject(faults.Fault("client", "recv", faults.RESET,
                                        method="pull_sparse")) as inj:
            rows = client.pull_sparse("emb", [7])
        assert rows.shape == (1, DIM)
        assert inj.fired() == 1  # the re-dial re-ran __auth__ and served
        client.close()
    finally:
        srv.shutdown()


# ------------------------------------------------------- exactly-once

def test_dropped_reply_applies_push_exactly_once(server):
    """THE keystone: the reply to push_sparse_grad is lost after the
    server applied it; the client's retry must hit the replay cache, not
    the optimizer."""
    client = PSClient([server.endpoint], **FAST)
    client.pull_sparse("emb", [1, 2, 3])          # materialize rows at 0
    table = server.table("emb")
    applied0 = table.applied
    before = monitor.stats("ps.rpc.")
    with faults.inject(faults.Fault("server", "reply", faults.DROP,
                                    method="push_sparse_grad")) as inj:
        client.push_sparse_grad("emb", [1, 2, 3],
                                np.ones((3, DIM), np.float32))
    assert inj.fired(faults.DROP) == 1
    # applied once, replayed (not re-applied) on the retry
    assert table.applied == applied0 + 1
    assert client.table_applied("emb") == applied0 + 1
    assert _delta(before, "ps.rpc.replays") >= 1
    # sgd lr=1.0 from zeros: exactly one application == exactly -1.0
    np.testing.assert_array_equal(
        client.pull_sparse("emb", [1, 2, 3]),
        -np.ones((3, DIM), np.float32))
    client.close()


def test_dropped_reply_dense_and_barrier_replay(server):
    client = PSClient([server.endpoint], **FAST)
    srv_table = server.table("dense0")
    with faults.inject(
            faults.Fault("server", "reply", faults.DROP,
                         method="push_dense_grad"),
            faults.Fault("server", "reply", faults.DROP,
                         method="set_dense")) as inj:
        client.set_dense("dense0", np.full((3, DIM), 5.0, np.float32))
        client.push_dense_grad("dense0", np.ones((3, DIM), np.float32))
    assert inj.fired(faults.DROP) == 2
    # one set + one sgd step (lr=0.1): 5.0 - 0.1, not 5.0 - 0.2
    np.testing.assert_allclose(client.pull_dense("dense0"),
                               np.full((3, DIM), 4.9, np.float32))
    assert srv_table.applied == 2
    client.close()


# --------------------------------------------------------- deadlines

def test_stall_past_deadline_names_method_and_endpoint(server):
    client = PSClient([server.endpoint], timeout=0.3, max_retries=1,
                      backoff_base=0.01, backoff_max=0.02,
                      connect_retry_s=2.0)
    before = monitor.stats("ps.rpc.")
    with faults.inject(faults.Fault("server", "reply", faults.STALL,
                                    method="pull_dense", times=10,
                                    delay=1.0)):
        with pytest.raises(rpc.DeadlineExceeded) as ei:
            client.pull_dense("dense0")
    msg = str(ei.value)
    assert "pull_dense" in msg and server.endpoint in msg
    assert _delta(before, "ps.rpc.deadline_exceeded") >= 1
    assert _delta(before, "ps.rpc.retries") >= 1
    client.close()


def test_stalled_mutation_is_rescued_by_replay(server):
    """A stall on the REPLY of a mutating call: the first attempt times
    out client-side after the server applied+committed, and the retry
    replays the cached reply — the call SUCCEEDS and applies once."""
    client = PSClient([server.endpoint], timeout=0.4, max_retries=2,
                      backoff_base=0.01, backoff_max=0.02,
                      connect_retry_s=2.0)
    client.pull_sparse("emb", [9])
    table = server.table("emb")
    applied0 = table.applied
    with faults.inject(faults.Fault("server", "reply", faults.STALL,
                                    method="push_sparse_grad", times=1,
                                    delay=1.0)):
        client.push_sparse_grad("emb", [9], np.ones((1, DIM), np.float32))
    assert table.applied == applied0 + 1
    np.testing.assert_array_equal(client.pull_sparse("emb", [9]),
                                  -np.ones((1, DIM), np.float32))
    client.close()


# ------------------------------------------------------------- frames

def test_oversized_frame_rejected_without_allocation():
    a, b = socket.socketpair()
    try:
        b.sendall(rpc._HDR.pack(1 << 45))   # 32 TiB claim
        with pytest.raises(rpc.FrameError, match="PADDLE_PS_MAX_FRAME"):
            rpc.recv_msg(a)
    finally:
        a.close()
        b.close()


def test_oversized_send_refused():
    a, b = socket.socketpair()
    try:
        with pytest.raises(rpc.FrameError, match="refusing to send"):
            rpc.send_msg(a, {"x": np.zeros(1 << 12, np.uint8)},
                         max_frame=1 << 10)
    finally:
        a.close()
        b.close()


def test_garbled_frame_rejected_cleanly():
    a, b = socket.socketpair()
    try:
        b.sendall(rpc._HDR.pack(10) + b"\x00" * 10)
        with pytest.raises((rpc.FrameError, Exception)) as ei:
            rpc.recv_msg(a)
        # specifically a clean frame/pickle rejection, not an OOM/crash
        import pickle
        assert isinstance(ei.value, (rpc.FrameError,
                                     pickle.UnpicklingError))
    finally:
        a.close()
        b.close()


def test_server_survives_bad_frames_from_one_peer(server):
    """A hostile/garbled connection is dropped per-connection; the server
    keeps serving everyone else and counts the event."""
    before = monitor.stats("ps.rpc.")
    host, port = server.endpoint.rsplit(":", 1)
    evil = socket.create_connection((host, int(port)), timeout=5.0)
    evil.sendall(rpc._HDR.pack(1 << 45))
    evil.settimeout(5.0)
    # server answers with a best-effort error frame and/or closes; either
    # way the stream ends rather than allocating 32 TiB
    try:
        data = evil.recv(1 << 16)
        if data:
            assert b"bad frame" in data
    except OSError:
        pass
    evil.close()
    assert _delta(before, "ps.rpc.bad_frames") >= 1
    # a well-behaved client is unaffected
    client = PSClient([server.endpoint], **FAST)
    assert client.pull_sparse("emb", [4]).shape == (1, DIM)
    assert client.ping()[0] < 5.0
    client.close()


def test_garbled_reply_triggers_retry(server):
    client = PSClient([server.endpoint], **FAST)
    with faults.inject(faults.Fault("server", "reply", faults.GARBLE,
                                    method="pull_sparse")) as inj:
        rows = client.pull_sparse("emb", [11])
    assert inj.fired(faults.GARBLE) == 1
    assert rows.shape == (1, DIM)
    client.close()


def test_ping_served_before_auth(monkeypatch):
    monkeypatch.setenv("PADDLE_PS_TOKEN", "sekrit-ping")
    stop = threading.Event()
    port, _ = rpc.serve("127.0.0.1:0", lambda m, kw: None, stop)
    try:
        # a tokenless probe: no __auth__ frame, just __ping__
        monkeypatch.delenv("PADDLE_PS_TOKEN")
        sock = socket.create_connection(("127.0.0.1", port), timeout=5.0)
        rpc.send_msg(sock, {"method": "__ping__"})
        assert rpc.recv_msg(sock) == {"result": "pong"}
        # ...but real methods still require the handshake
        rpc.send_msg(sock, {"method": "pull_dense", "table": "x"})
        reply = rpc.recv_msg(sock)
        assert reply and "auth required" in reply.get("error", "")
        sock.close()
    finally:
        stop.set()


# ------------------------------------------------- chaos training run

N_STEPS = 24
SNAP_STEP = 11          # snapshot lands after this step's pushes
KILL_STEP = 17          # server 0 dies after this step completes
VOCAB = 64


def _train_steps(client, start, stop_, snap_path=None):
    """Deterministic 2-table loop; grads depend on PULLED state, so any
    lost or double-applied update poisons every later step."""
    for step in range(start, stop_):
        rng = np.random.RandomState(1000 + step)
        ids = rng.randint(0, VOCAB, size=10).astype(np.int64)
        rows = client.pull_sparse("emb", ids)
        grads = rows * 0.05 + rng.randn(len(ids), DIM).astype(np.float32)
        client.push_sparse_grad("emb", ids, grads)
        dense = client.pull_dense("dense0")
        client.push_dense_grad(
            "dense0", dense * 0.05 + rng.randn(3, DIM).astype(np.float32))
        if step == SNAP_STEP and snap_path:
            client.save_snapshot(snap_path)


def _final_state(client):
    all_ids = np.arange(VOCAB, dtype=np.int64)
    return (client.pull_sparse("emb", all_ids).copy(),
            client.pull_dense("dense0").copy())


def _spawn_servers(ports):
    servers = []
    for p in ports:
        srv = PSServer(endpoint=f"127.0.0.1:{p}",
                       tables={"emb": _sparse_spec("adagrad", lr=0.1),
                               "dense0": _dense_spec()})
        srv.start()
        servers.append(srv)
    return servers


def test_chaos_training_bitwise_equals_fault_free(tmp_path):
    """2-server PS training with seeded resets + dropped replies AND a
    mid-run server kill + snapshot-restore: the final dense and sparse
    tables must be BITWISE equal to a fault-free run — no lost, no
    double-applied gradients."""
    # ---- fault-free reference run
    ref_servers = _spawn_servers((0, 0))
    ref_client = PSClient([s.endpoint for s in ref_servers], **FAST)
    _train_steps(ref_client, 0, N_STEPS,
                 snap_path=str(tmp_path / "ref_snap"))
    ref_sparse, ref_dense = _final_state(ref_client)
    ref_client.close()
    for s in ref_servers:
        s.shutdown()

    # ---- chaos run: seeded resets + lost replies through every step
    servers = _spawn_servers((0, 0))
    endpoints = [s.endpoint for s in servers]
    client = PSClient(endpoints, **FAST)
    before = monitor.stats("ps.rpc.")
    snap = str(tmp_path / "chaos_snap")
    with faults.inject(seed=7, p={faults.RESET: 0.04,
                                  faults.DROP: 0.04}) as inj:
        _train_steps(client, 0, KILL_STEP + 1, snap_path=snap)

        # ---- mid-run crash of server 0, restart on the SAME endpoint
        servers[0].shutdown()
        fresh = _spawn_servers((int(endpoints[0].rsplit(":", 1)[1]),))[0]
        servers[0] = fresh
        # global rollback to the snapshot, replay the suffix — the
        # standard PS recovery the reference's HeartBeatMonitor +
        # large_scale_kv checkpointing enable
        client.load_snapshot(snap)
        _train_steps(client, SNAP_STEP + 1, N_STEPS)

    got_sparse, got_dense = _final_state(client)
    # the chaos actually happened...
    assert inj.fired(faults.DROP) >= 1, "seed injected no drops"
    assert inj.fired(faults.RESET) >= 1, "seed injected no resets"
    # ...the transport reported it through the monitor...
    assert _delta(before, "ps.rpc.retries") >= 1
    assert _delta(before, "ps.rpc.reconnects") >= 1
    assert _delta(before, "ps.rpc.replays") >= 1
    # ...and not one gradient was lost or double-counted
    np.testing.assert_array_equal(got_sparse, ref_sparse)
    np.testing.assert_array_equal(got_dense, ref_dense)
    client.close()
    for s in servers:
        s.shutdown()


def test_chaos_run_is_seed_deterministic():
    """Same seed -> same injected fault sequence per stream (the
    scripted-chaos determinism the harness promises downstream tests)."""
    a = faults.FaultInjector(seed=42, p={faults.DROP: 0.5})
    b = faults.FaultInjector(seed=42, p={faults.DROP: 0.5})
    seq_a = [a.on_event("server", "reply", "push_sparse_grad")
             for _ in range(64)]
    seq_b = [b.on_event("server", "reply", "push_sparse_grad")
             for _ in range(64)]
    assert seq_a == seq_b
    assert seq_a.count("drop") > 0
    c = faults.FaultInjector(seed=43, p={faults.DROP: 0.5})
    seq_c = [c.on_event("server", "reply", "push_sparse_grad")
             for _ in range(64)]
    assert seq_a != seq_c


def test_two_communicators_share_client_without_replay_collision(server):
    """Replay keys are namespaced per Communicator: a second instance
    over the SAME PSClient restarts its batch numbering, and its pushes
    must apply — not be mistaken for replays of the first one's."""
    from paddle_tpu.distributed.ps import Communicator
    client = PSClient([server.endpoint], **FAST)
    client.pull_sparse("emb", [5])
    table = server.table("emb")
    applied0 = table.applied
    for _ in range(2):
        comm = Communicator(client, send_every=1, max_queue=8,
                            max_delay_s=0.01)
        comm.push_sparse("emb", [5], np.ones((1, DIM), np.float32))
        comm.flush(timeout=30.0)
        comm.stop()
    assert table.applied == applied0 + 2
    np.testing.assert_array_equal(client.pull_sparse("emb", [5]),
                                  -2.0 * np.ones((1, DIM), np.float32))
    client.close()


def test_oversized_request_fails_fast_without_retry(server):
    """A request over the frame bound is a deterministic LOCAL error:
    FrameError immediately, no retries, no reconnect churn."""
    client = PSClient([server.endpoint], **FAST)
    client.pull_sparse("emb", [1])          # connection warm and healthy
    before = monitor.stats("ps.rpc.")
    from paddle_tpu.core.flags import set_flags
    set_flags({"PADDLE_PS_MAX_FRAME": 4096})
    try:
        with pytest.raises(rpc.FrameError, match="PADDLE_PS_MAX_FRAME"):
            client.push_sparse_grad(
                "emb", np.arange(4096, dtype=np.int64),
                np.ones((4096, DIM), np.float32))
    finally:
        set_flags({"PADDLE_PS_MAX_FRAME": 1 << 30})
    assert _delta(before, "ps.rpc.retries") == 0
    assert _delta(before, "ps.rpc.reconnects") == 0
    # the connection is still usable afterwards
    assert client.pull_sparse("emb", [1]).shape == (1, DIM)
    client.close()


def test_communicator_retries_through_faults(server):
    """The async send thread rides the retrying transport: a reset +
    dropped reply under its merged batch neither kills the thread nor
    double-applies."""
    from paddle_tpu.distributed.ps import Communicator
    client = PSClient([server.endpoint], **FAST)
    client.pull_sparse("emb", [1, 2])
    table = server.table("emb")
    applied0 = table.applied
    comm = Communicator(client, send_every=2, max_queue=16,
                        max_delay_s=0.01)
    with faults.inject(
            faults.Fault("client", "send", faults.RESET,
                         method="push_sparse_grad"),
            faults.Fault("server", "reply", faults.DROP,
                         method="push_sparse_grad")):
        comm.push_sparse("emb", [1], np.ones((1, DIM), np.float32))
        comm.push_sparse("emb", [2], np.ones((1, DIM), np.float32))
        comm.flush(timeout=30.0)
    comm.stop()
    # one merged batch, applied exactly once despite both faults
    assert table.applied == applied0 + 1
    np.testing.assert_array_equal(client.pull_sparse("emb", [1, 2]),
                                  -np.ones((2, DIM), np.float32))
    client.close()
