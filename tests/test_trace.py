"""Span tracer (core/trace.py): ids/parenting, cross-thread attach,
always-on ring, capture buffer, Chrome export with flow events, and the
profiler.RecordEvent absorption. See docs/observability.md."""
import json
import threading

import pytest

import paddle_tpu as paddle  # noqa: F401 — flags registered
from paddle_tpu.core import trace


@pytest.fixture(autouse=True)
def _clean_tracer():
    trace.reset()
    yield
    if trace.enabled():
        trace.stop()
    trace.reset()


def test_span_nesting_and_ids():
    with trace.span("outer", kind="test") as outer:
        assert trace.current() == (outer.trace_id, outer.span_id)
        with trace.span("inner") as inner:
            assert inner.parent_id == outer.span_id
            assert inner.trace_id == outer.trace_id
        with trace.span("inner2") as inner2:
            assert inner2.parent_id == outer.span_id
    assert trace.current() is None
    assert outer.t1 is not None and outer.t1 >= outer.t0
    assert outer.attrs["kind"] == "test"
    # separate roots get separate traces
    with trace.span("other") as other:
        assert other.trace_id != outer.trace_id
        assert other.parent_id is None


def test_span_exception_records_error_and_reraises():
    with pytest.raises(ValueError):
        with trace.span("boom") as sp:
            raise ValueError("x")
    assert sp.attrs["error"] == "ValueError"
    assert sp.t1 is not None  # finished despite the exception


def test_ring_is_bounded_and_always_on():
    trace.set_ring_size(8)
    try:
        assert not trace.enabled()  # ring records even without capture
        for i in range(20):
            trace.instant(f"e{i}")
        recent = trace.recent()
        assert len(recent) == 8
        assert recent[-1].name == "e19"  # newest last
        assert trace.recent(3)[0].name == "e17"
    finally:
        trace.set_ring_size(4096)


def test_capture_buffer_only_between_start_stop():
    trace.instant("before")
    trace.start()
    trace.instant("during")
    spans = trace.stop()
    trace.instant("after")
    assert [s.name for s in spans] == ["during"]
    assert {s.name for s in trace.recent()} >= {"before", "during",
                                                "after"}


def test_attach_joins_worker_thread_to_trace():
    out = {}
    with trace.span("driver") as sp:
        ctx = trace.current()

        def worker():
            with trace.attach(ctx):
                with trace.span("work") as w:
                    out["w"] = w
            out["after"] = trace.current()

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert out["w"].trace_id == sp.trace_id
    assert out["w"].parent_id == sp.span_id
    assert out["after"] is None          # attach scope fully popped
    assert out["w"].tid != sp.tid        # genuinely another thread


def test_remote_parent_tuple_propagates_trace_id():
    # the PS server resolves the client-shipped (trace_id, span_id)
    with trace.span("handler", parent=("cafe-1", "cafe-2")) as sp:
        assert sp.trace_id == "cafe-1"
        assert sp.parent_id == "cafe-2"


def test_chrome_export_slices_flows_and_thread_names(tmp_path):
    trace.start()
    with trace.span("dispatch", step=0) as d:
        d.flow(41, "s")
    with trace.span("retire") as r:
        r.flow(41, "t")
    with trace.span("materialize") as m:
        m.flow(41, "f")
    trace.stop()
    path = str(tmp_path / "trace.json")
    trace.export_chrome_trace(path, spans=[d, r, m])
    data = json.load(open(path))
    ev = data["traceEvents"]
    slices = [e for e in ev if e["ph"] == "X"]
    flows = [e for e in ev if e.get("cat") == "flow"]
    metas = [e for e in ev if e["ph"] == "M"]
    assert {e["name"] for e in slices} == {"dispatch", "retire",
                                           "materialize"}
    assert [e["ph"] for e in flows] == ["s", "t", "f"]
    assert all(e["id"] == 41 for e in flows)
    assert flows[-1]["bp"] == "e"
    assert metas and metas[0]["args"]["name"]
    # slice args carry span identity + attrs
    disp = next(e for e in slices if e["name"] == "dispatch")
    assert disp["args"]["step"] == 0
    assert disp["args"]["trace_id"] == d.trace_id
    # flow ts binds inside its slice
    assert disp["ts"] <= flows[0]["ts"] <= disp["ts"] + disp["dur"]


def test_record_event_missed_end_cannot_corrupt_parentage():
    """Legacy begin()/end() callers (tape.py per-op annotations) skip
    end() when the op raises; the RecordEvent span is detached, so the
    leak costs one sample — NOT a dead ancestor for every later span."""
    from paddle_tpu import profiler as prof
    prof.start_profiler()
    try:
        prof.RecordEvent("op/leaky").begin()   # end() never called
        assert trace.current() is None          # ambient stack untouched
        with trace.span("after") as sp:
            assert sp.parent_id is None         # fresh root, not 'leaky'
    finally:
        prof.stop_profiler()
    prof.reset_profiler()


def test_record_event_absorbed_into_tracer():
    from paddle_tpu import profiler as prof
    prof.reset_profiler()
    ring_before = len(trace.recent())
    rec = prof.RecordEvent("cheap")
    rec.begin()
    rec.end()
    # disabled profiler: RecordEvent stays a no-op (hot per-op sites)
    assert len(trace.recent()) == ring_before
    assert prof.events() == []
    prof.start_profiler()
    try:
        with trace.span("outer") as outer:
            with prof.RecordEvent("annotated"):
                pass
        names = [e[0] for e in prof.events()]
        # RecordEvent became a span nested under the ambient one...
        sp = next(s for s in trace.recent() if s.name == "annotated")
        assert sp.parent_id == outer.span_id
        # ...and first-class trace spans reach the profiler table too
        assert "annotated" in names and "outer" in names
        assert "annotated" in prof.summary()
    finally:
        prof.stop_profiler()
    prof.reset_profiler()
