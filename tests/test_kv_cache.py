"""KV-cache incremental decoding (VERDICT r03 item 2).

The reference's incremental decoding lives in its C++ predictor stack
(inference/api/analysis_predictor.cc:306 zero-copy run loop); the TPU
redesign is a static-shape StaticKVCache (nn/layer/transformer.py) driven
by one jitted prefill+lax.scan program (text/models/gpt.py _decode_fn) —
no per-token retrace, O(1) work per token.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.text.models.gpt import GPT, GPTConfig


@pytest.fixture(scope="module")
def net():
    paddle.seed(0)
    net = GPT(GPTConfig.tiny())
    net.eval()
    return net


def _ids(b=2, s=12, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randint(0, 1000, (b, s)).astype("int64"))


def test_static_cache_attention_matches_full(net):
    """Feeding a sequence through MHA in chunks against a StaticKVCache
    must equal one full causal forward."""
    paddle.seed(1)
    mha = nn.MultiHeadAttention(32, 4)
    mha.eval()
    x = paddle.to_tensor(
        np.random.RandomState(1).randn(2, 10, 32).astype("float32"))
    full = mha(x, is_causal=True)

    cache = mha.gen_static_cache(2, 10, "float32")
    outs = []
    for lo, hi in ((0, 4), (4, 5), (5, 10)):   # prefill + 1-token + chunk
        o, cache = mha(x[:, lo:hi], cache=cache)
        outs.append(np.asarray(o._value))
    inc = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full._value), inc,
                               rtol=1e-4, atol=1e-5)
    assert int(cache.index) == 10


def test_greedy_cached_equals_reforward(net):
    ids = _ids()
    # 5 tokens: each host-loop step is a fresh compile at a new length,
    # the dominant cost in the suite profile; 5 steps still cross a
    # cache-refill boundary and the scan path
    host = net.generate(ids, max_new_tokens=5, temperature=0,
                        use_cache=False)
    cached = net.generate(ids, max_new_tokens=5, temperature=0,
                          use_cache=True)
    np.testing.assert_array_equal(np.asarray(host._value),
                                  np.asarray(cached._value))


def test_no_retrace_on_repeat_calls(net):
    ids = _ids(seed=3)
    a = net.generate(ids, max_new_tokens=4, temperature=0, use_cache=True)
    fns_mid = list(net._decode_cache.values())
    b = net.generate(_ids(seed=4), max_new_tokens=4, temperature=0,
                     use_cache=True)
    fns_after = list(net._decode_cache.values())
    # same (shape, config) → the jitted program is reused, not rebuilt
    assert fns_after == fns_mid
    np.testing.assert_array_equal(np.asarray(a._value)[:, :12],
                                  np.asarray(_ids(seed=3)._value))
    assert a.shape == b.shape == (2, 16)


def test_eos_stops_and_pads(net):
    ids = _ids(seed=5)
    free = net.generate(ids, max_new_tokens=6, temperature=0, use_cache=True)
    eos = int(np.asarray(free._value)[0, 13])   # token emitted at step 2
    out = np.asarray(net.generate(ids, max_new_tokens=6, temperature=0,
                                  use_cache=True,
                                  eos_token_id=eos)._value)
    row = out[0, 12:]
    hit = np.where(row == eos)[0]
    assert hit.size > 0
    # everything after the first eos is eos (finished rows are pinned)
    np.testing.assert_array_equal(row[hit[0]:],
                                  np.full(row.size - hit[0], eos))


def test_sampling_reproducible_by_seed(net):
    ids = _ids(seed=6)
    a = net.generate(ids, max_new_tokens=6, temperature=0.7, top_k=8,
                     use_cache=True, seed=11)
    b = net.generate(ids, max_new_tokens=6, temperature=0.7, top_k=8,
                     use_cache=True, seed=11)
    c = net.generate(ids, max_new_tokens=6, temperature=0.7, top_k=8,
                     use_cache=True, seed=12)
    np.testing.assert_array_equal(np.asarray(a._value), np.asarray(b._value))
    assert not np.array_equal(np.asarray(a._value), np.asarray(c._value))


def test_generate_rejects_overflow(net):
    with pytest.raises(ValueError):
        net.generate(_ids(s=120), max_new_tokens=20, use_cache=True)


def test_transformer_decoder_static_cache_matches_full():
    """Incremental decoding through TransformerDecoder (per-layer
    StaticKVCache) equals the full causal forward."""
    paddle.seed(3)
    from paddle_tpu.nn import TransformerDecoder, TransformerDecoderLayer
    d, heads, L = 16, 2, 2
    layer = TransformerDecoderLayer(d, heads, 32, dropout=0.0)
    dec = TransformerDecoder(layer, L)
    dec.eval()
    rng = np.random.RandomState(3)
    s = 6
    tgt = paddle.to_tensor(rng.randn(2, s, d).astype("float32"))
    memory = paddle.to_tensor(rng.randn(2, 4, d).astype("float32"))
    # full forward with causal mask
    causal = np.triu(np.full((s, s), -1e9, "float32"), 1)
    full = dec(tgt, memory,
               tgt_mask=paddle.to_tensor(causal)).numpy()

    caches = dec.gen_static_cache(2, s)
    outs = []
    for lo, hi in ((0, 3), (3, 4), (4, 6)):   # prefill + steps
        o, caches = dec(tgt[:, lo:hi], memory, cache=caches)
        outs.append(o.numpy())
    inc = np.concatenate(outs, axis=1)
    np.testing.assert_allclose(full, inc, rtol=2e-4, atol=2e-5)
    assert int(caches[0].index) == s


def test_export_decode_predictor_matches_generate(net, tmp_path):
    """The exported StableHLO decode artifact (prefill + scan), run
    through the inference Predictor, reproduces GPT.generate exactly —
    incremental decoding wired through the deployment path (VERDICT r03
    item 2, Predictor clause)."""
    from paddle_tpu import inference
    from paddle_tpu.text.models.gpt import export_decode

    ids = _ids(b=2, s=12, seed=9)
    ref = np.asarray(net.generate(ids, max_new_tokens=5, temperature=0,
                                  use_cache=True)._value)
    prefix = str(tmp_path / "decode")
    export_decode(net, prefix, batch_size=2, prompt_len=12,
                  max_new_tokens=5)
    pred = inference.create_predictor(inference.Config(prefix))
    (toks,) = pred.run([np.asarray(ids._value, np.int32), np.int32(0)])
    np.testing.assert_array_equal(toks.astype(np.int64), ref[:, 12:])


def test_beam_search_with_kv_cache_beam1_matches_greedy(net):
    """BeamSearchDecoder driving GPT through StaticKVCache states: cache
    buffers reorder by parent beam each step; beam_size=1 must reproduce
    greedy generate (VERDICT r03 item 2, BeamSearchDecoder clause)."""
    import jax.numpy as jnp

    from paddle_tpu.nn import BeamSearchDecoder, dynamic_decode

    ids = _ids(b=2, s=8, seed=21)
    new = 6
    ref = np.asarray(net.generate(ids, max_new_tokens=new, temperature=0,
                                  use_cache=True)._value)[:, 8:]

    total = 8 + new + 1
    caches = [blk.attn.gen_static_cache(2, total, jnp.float32)
              for blk in net.blocks]
    # prefill the caches with the prompt; feed its last logits' argmax as
    # the decoder's start token is handled by the cell below
    logits, caches = net._forward_cached(ids._value, caches, jnp.int32(0))

    class _GPTCell:
        """Cell over [n] token ids with StaticKVCache list states."""

        def __call__(self, inputs, states):
            toks = np.asarray(inputs._value
                              if hasattr(inputs, "_value") else inputs)
            lg, new_states = net._forward_cached(
                jnp.asarray(toks)[:, None], states, states[0].index)
            return paddle.to_tensor(np.asarray(lg)), new_states

    # start each (single) beam from the prompt's greedy first token is
    # produced by the decoder itself: give it the prefix logits via a
    # start token equal to the greedy continuation
    start = int(np.asarray(ref[0, 0]))
    dec = BeamSearchDecoder(_GPTCell(), start_token=start,
                            end_token=-1, beam_size=1)
    (paths, scores), _ = dynamic_decode(dec, caches,
                                        max_step_num=new - 1)
    out = np.asarray(paths._value)          # [b, 1, T]
    # decoder consumed ref[:,0] as start; its outputs are steps 1..new-1
    np.testing.assert_array_equal(out[0, 0], ref[0, 1:])
