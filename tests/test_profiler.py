"""Profiler subsystem (analog of reference platform/profiler.h +
fluid/profiler.py tests)."""
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import profiler as prof


def test_record_event_and_summary():
    prof.reset_profiler()
    prof.start_profiler()
    try:
        with prof.RecordEvent("phase_a"):
            _ = sum(range(1000))
        with prof.RecordEvent("phase_a"):
            pass
        with prof.RecordEvent("phase_b"):
            pass
    finally:
        prof.stop_profiler()
    evs = prof.events()
    assert len(evs) == 3
    table = prof.summary(sorted_key="calls")
    assert "phase_a" in table and "phase_b" in table
    # disabled: RecordEvent must be a no-op
    with prof.RecordEvent("after_stop"):
        pass
    assert len(prof.events()) == 3


def test_profiler_context_captures_op_events(capsys):
    x = paddle.to_tensor(np.ones((8, 8), "float32"))
    with prof.profiler(sorted_key="total"):
        y = x @ x
        _ = y.sum()
    out = capsys.readouterr().out
    assert "op/" in out  # per-op host annotations made it into the table


def test_chrome_trace_export(tmp_path):
    prof.reset_profiler()
    prof.start_profiler()
    with prof.RecordEvent("traced"):
        pass
    prof.stop_profiler()
    path = os.path.join(tmp_path, "trace.json")
    prof.export_chrome_trace(path)
    import json
    with open(path) as f:
        data = json.load(f)
    assert data["traceEvents"] and data["traceEvents"][0]["name"] == "traced"


def test_cost_analysis_reports_flops():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(a, b):
        return a @ b

    a = jnp.ones((128, 128), jnp.float32)
    ca = prof.cost_analysis(f, a, a)
    # 2*M*N*K flops for a 128^3 matmul
    assert float(ca.get("flops", 0)) >= 2 * 128 ** 3 * 0.9


def test_profiler_callback_in_fit(capsys):
    from paddle_tpu import nn, optimizer
    from paddle_tpu.hapi.callbacks import ProfilerCallback
    from paddle_tpu.io import TensorDataset

    paddle.seed(0)
    X = np.random.rand(32, 4).astype("float32")
    Y = (X @ np.random.rand(4, 1).astype("float32"))
    net = nn.Linear(4, 1)
    model = paddle.Model(net)
    model.prepare(optimizer=optimizer.SGD(learning_rate=0.1,
                                          parameters=net.parameters()),
                  loss=nn.MSELoss())
    cb = ProfilerCallback(start_step=1, stop_step=2)
    model.fit(TensorDataset([X, Y]), batch_size=16, epochs=1, verbose=0,
              callbacks=[cb])
    out = capsys.readouterr().out
    assert "hapi/train_step" in out
