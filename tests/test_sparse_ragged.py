"""SelectedRows sparse gradients + RaggedTensor/sequence ops
(SURVEY hard part 1; reference framework/selected_rows.h,
framework/lod_tensor.h, operators/sequence_ops/)."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer
from paddle_tpu.core.ragged import RaggedTensor
from paddle_tpu.core.selected_rows import SelectedRows


# --------------------------- SelectedRows ---------------------------------

def test_sparse_embedding_grad_is_selected_rows():
    paddle.seed(0)
    emb = nn.Embedding(100, 8, sparse=True)
    ids = paddle.to_tensor(np.array([[1, 3], [3, 7]], "int64"))
    out = emb(ids)
    out.sum().backward()
    g = emb.weight.grad._value
    assert isinstance(g, SelectedRows)
    assert g.dense_shape == (100, 8)
    assert sorted(np.asarray(g.rows).tolist()) == [1, 3, 3, 7]
    # densified grad must equal the dense-path grad
    emb2 = nn.Embedding(100, 8, sparse=False)
    emb2.weight.set_value(np.asarray(emb.weight._value))
    out2 = emb2(ids)
    out2.sum().backward()
    np.testing.assert_allclose(np.asarray(g.to_dense()),
                               np.asarray(emb2.weight.grad._value),
                               atol=1e-6)


def test_selected_rows_coalesce_and_add():
    sr = SelectedRows([1, 3, 1], np.ones((3, 2), "float32"), (5, 2))
    c = sr.coalesce()
    assert np.asarray(c.rows).tolist() == [1, 3]
    np.testing.assert_allclose(np.asarray(c.values),
                               [[2, 2], [1, 1]])
    both = sr + SelectedRows([0], np.ones((1, 2), "float32"), (5, 2))
    assert both.rows.shape[0] == 4
    dense = both + jnp.zeros((5, 2))
    assert dense.shape == (5, 2)


def test_sgd_sparse_update_matches_dense():
    def run(sparse):
        paddle.seed(1)
        emb = nn.Embedding(50, 4, sparse=sparse)
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=emb.parameters())
        ids = paddle.to_tensor(np.array([2, 2, 9], "int64"))
        loss = (emb(ids) ** 2).sum()
        loss.backward()
        opt.step()
        return np.asarray(emb.weight._value)

    np.testing.assert_allclose(run(True), run(False), atol=1e-6)


def test_adam_lazy_sparse_update():
    paddle.seed(2)
    emb = nn.Embedding(50, 4, sparse=True)
    w0 = np.asarray(emb.weight._value).copy()
    opt = optimizer.Adam(learning_rate=0.1, lazy_mode=True,
                         parameters=emb.parameters())
    ids = paddle.to_tensor(np.array([5, 11], "int64"))
    (emb(ids) ** 2).sum().backward()
    opt.step()
    w1 = np.asarray(emb.weight._value)
    changed = np.abs(w1 - w0).sum(axis=1) > 0
    assert changed[5] and changed[11]
    assert changed.sum() == 2  # lazy: ONLY the touched rows moved
    # non-lazy adam densifies (all-rows moment decay semantics preserved)
    opt2 = optimizer.Adam(learning_rate=0.1, lazy_mode=False,
                          parameters=emb.parameters())
    (emb(ids) ** 2).sum().backward()
    opt2.step()  # must not raise


# --------------------------- Ragged / sequence ----------------------------

def _ragged():
    return RaggedTensor.from_rows([
        jnp.asarray([[1., 1.], [2., 2.], [3., 3.]]),
        jnp.asarray([[4., 4.]]),
        jnp.asarray([[5., 5.], [6., 6.]]),
    ])


def test_ragged_round_trip_and_lod():
    r = _ragged()
    assert r.nrows == 3
    assert r.recursive_sequence_lengths() == [[3, 1, 2]]
    assert r.lod == [[0, 3, 4, 6]]
    padded = r.to_padded()
    assert padded.shape == (3, 3, 2)
    assert float(padded[1, 2, 0]) == 0.0  # padding
    back = RaggedTensor.from_padded(padded, np.asarray(r.lengths))
    np.testing.assert_allclose(np.asarray(back.values),
                               np.asarray(r.values))


def test_sequence_pool_modes():
    r = _ragged()
    np.testing.assert_allclose(np.asarray(ops.sequence_pool(r, "sum")),
                               [[6, 6], [4, 4], [11, 11]])
    np.testing.assert_allclose(np.asarray(ops.sequence_pool(r, "average")),
                               [[2, 2], [4, 4], [5.5, 5.5]])
    np.testing.assert_allclose(np.asarray(ops.sequence_pool(r, "max")),
                               [[3, 3], [4, 4], [6, 6]])
    np.testing.assert_allclose(np.asarray(ops.sequence_first_step(r)),
                               [[1, 1], [4, 4], [5, 5]])
    np.testing.assert_allclose(np.asarray(ops.sequence_last_step(r)),
                               [[3, 3], [4, 4], [6, 6]])


def test_sequence_softmax_and_reverse():
    r = RaggedTensor.from_rows([jnp.asarray([1., 2.]), jnp.asarray([3.])])
    sm = ops.sequence_softmax(r)
    e = np.exp([1., 2.])
    np.testing.assert_allclose(np.asarray(sm.values)[:2], e / e.sum(),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sm.values)[2], 1.0)
    rev = ops.sequence_reverse(_ragged())
    np.testing.assert_allclose(np.asarray(rev.values)[:3, 0], [3, 2, 1])


def test_sequence_expand_concat_slice_pad():
    ref = _ragged()
    x = jnp.asarray([[10.], [20.], [30.]])
    ex = ops.sequence_expand(x, ref)
    np.testing.assert_allclose(np.asarray(ex.values)[:, 0],
                               [10, 10, 10, 20, 30, 30])
    cc = ops.sequence_concat([ref, ref])
    assert cc.recursive_sequence_lengths() == [[6, 2, 4]]
    sl = ops.sequence_slice(ref, [0, 0, 1], [2, 1, 1])
    assert sl.recursive_sequence_lengths() == [[2, 1, 1]]
    padded, lens = ops.sequence_pad(ref)
    assert padded.shape == (3, 3, 2)
    r2 = ops.sequence_unpad(padded, np.asarray(lens))
    np.testing.assert_allclose(np.asarray(r2.values),
                               np.asarray(ref.values))
