"""Regression tests for static-graph review findings."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, static


@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_static_dropout_mask_differs_per_run(static_mode):
    main = static.Program("drop")
    with static.program_guard(main):
        x = static.data("x", [4, 64], "float32")
        out = nn.functional.dropout(x, p=0.5, training=True)
    exe = static.Executor()
    xv = np.ones((4, 64), "float32")
    (a,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    (b,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    assert not np.allclose(a, b), "dropout mask must differ across runs"


def test_clone_for_test_freezes_bn_and_drops_dropout(static_mode):
    main = static.Program("cft")
    with static.program_guard(main):
        x = static.data("x", [8, 4], "float32")
        bn = nn.BatchNorm1D(4)
        out = nn.functional.dropout(bn(x), p=0.9, training=True)
    test_prog = main.clone(for_test=True)
    assert test_prog.state_writes == {}
    exe = static.Executor()
    xv = np.random.RandomState(0).rand(8, 4).astype("float32") + 3.0
    m_before = np.asarray(static.global_scope().get(bn._mean.scope_name))
    (o1,) = exe.run(test_prog, feed={"x": xv}, fetch_list=[out])
    (o2,) = exe.run(test_prog, feed={"x": xv}, fetch_list=[out])
    m_after = np.asarray(static.global_scope().get(bn._mean.scope_name))
    np.testing.assert_allclose(m_before, m_after)  # stats frozen
    np.testing.assert_allclose(o1, o2)  # dropout removed -> deterministic


def test_nontrained_persistable_survives_donation(static_mode):
    # frozen param is donated but must flow back to the scope untouched
    main = static.Program("frozen")
    with static.program_guard(main):
        x = static.data("x", [4, 4], "float32")
        frozen = nn.Linear(4, 4)
        for p in frozen.parameters():
            p.trainable = False
            p.stop_gradient = True
        head = nn.Linear(4, 2)
        loss = paddle.ops.mean(head(frozen(x)))
        optimizer.SGD(learning_rate=0.1).minimize(
            loss, parameters=head.parameters())
    exe = static.Executor()
    xv = np.random.rand(4, 4).astype("float32")
    w0 = np.asarray(static.global_scope().get(frozen.weight.scope_name)).copy()
    for _ in range(3):
        exe.run(main, feed={"x": xv}, fetch_list=[loss])
    w1 = np.asarray(static.global_scope().get(frozen.weight.scope_name))
    np.testing.assert_allclose(w0, w1)


def test_static_vars_in_dynamic_mode_raise(static_mode):
    main = static.Program("err")
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        net = nn.Linear(2, 2)
        net(x)
    paddle.disable_static()
    try:
        with pytest.raises(RuntimeError, match="static-graph Variables"):
            net(paddle.randn([2, 2]))
    finally:
        paddle.enable_static()


def test_to_static_updates_bn_buffers():
    from paddle_tpu import jit
    net = nn.Sequential(nn.BatchNorm1D(4))
    snet = jit.to_static(net)
    x = paddle.to_tensor(np.random.rand(16, 4).astype("float32") + 5.0)
    snet(x)
    assert not np.allclose(net[0]._mean.numpy(), 0.0)


def test_to_static_kwargs_in_cache_key():
    from paddle_tpu import jit

    @jit.to_static
    def f(a, scale=1.0):
        return a * scale

    x = paddle.ones([2])
    np.testing.assert_allclose(f(x, scale=2.0).numpy(), [2, 2])
    np.testing.assert_allclose(f(x, scale=3.0).numpy(), [3, 3])


def test_jit_save_plain_function_raises():
    from paddle_tpu import jit
    from paddle_tpu.hapi.model import InputSpec

    sf = jit.to_static(lambda x: x * 2)
    with pytest.raises(TypeError, match="Layer"):
        jit.save(sf, "/tmp/nope", input_spec=[InputSpec([1], "float32")])


def test_static_gradients_rejects_data_vars(static_mode):
    main = static.Program("g")
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        net = nn.Linear(2, 1)
        loss = paddle.ops.mean(net(x))
        with pytest.raises(NotImplementedError):
            static.gradients(loss, [x])
