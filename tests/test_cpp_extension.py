"""Custom-op story (reference utils/cpp_extension + PD_BUILD_OP):
host-side C++ JIT load and device-side Python custom op registration."""
import ctypes
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension


def test_load_compiles_and_calls_cpp(tmp_path):
    src = tmp_path / "mysum.cc"
    src.write_text(textwrap.dedent("""
        extern "C" double pt_sum(const double* xs, long long n) {
            double acc = 0;
            for (long long i = 0; i < n; i++) acc += xs[i];
            return acc;
        }
    """))
    lib = cpp_extension.load("mysum", [str(src)],
                             build_directory=str(tmp_path))
    lib.pt_sum.restype = ctypes.c_double
    lib.pt_sum.argtypes = [ctypes.POINTER(ctypes.c_double),
                           ctypes.c_longlong]
    xs = np.arange(10, dtype=np.float64)
    out = lib.pt_sum(xs.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                     len(xs))
    assert out == 45.0
    # rebuild is skipped when sources are unchanged (same mtime check)
    lib2 = cpp_extension.load("mysum", [str(src)],
                              build_directory=str(tmp_path))
    assert lib2 is not None


def test_register_custom_op_dispatch_and_grad():
    import jax.numpy as jnp

    def swish_fwd(x):
        s = 1.0 / (1.0 + jnp.exp(-x))
        return x * s, (x, s)

    def swish_bwd(res, g):
        x, s = res
        return (g * (s + x * s * (1 - s)),)

    @cpp_extension.register_custom_op(name="my_swish",
                                      vjp=(swish_fwd, swish_bwd))
    def my_swish(x):
        return x * (1.0 / (1.0 + jnp.exp(-x)))

    from paddle_tpu.ops._dispatch import OP_REGISTRY
    assert "my_swish" in OP_REGISTRY

    x = paddle.to_tensor(np.array([-1.0, 0.0, 2.0], "float32"),
                         stop_gradient=False)
    out = my_swish(x)
    ref = np.asarray(x._value) / (1 + np.exp(-np.asarray(x._value)))
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)
    out.sum().backward()
    g = np.asarray(x.grad._value)
    # numeric check of the custom vjp
    eps = 1e-3
    xv = np.asarray(x._value, np.float64)
    num = ((xv + eps) / (1 + np.exp(-(xv + eps)))
           - (xv - eps) / (1 + np.exp(-(xv - eps)))) / (2 * eps)
    np.testing.assert_allclose(g, num, rtol=1e-3, atol=1e-4)
