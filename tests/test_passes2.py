"""Perf-relevant Program passes (VERDICT r03 N10 'partial' note):
constant folding and CSE measurably shrink the lowered op list while
preserving results."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.static.passes import apply_pass


def _run(program, feed, fetch):
    exe = static.Executor()
    return exe.run(program, feed=feed, fetch_list=fetch)


def test_constant_folding_happens_at_trace_time():
    """Design property (static/passes.py NOTE): literal-only chains run
    eagerly during tracing and enter the Program as baked constants — the
    4-op chain below records exactly ONE op (the add that touches the
    data Variable), i.e. constant folding needs no pass here."""
    paddle.enable_static()
    try:
        main = static.Program("fold")
        with static.program_guard(main):
            x = static.data("x", [2, 3], "float32")
            c = paddle.ops.arange(0, 6, dtype="float32")
            c = paddle.ops.reshape(c, [2, 3])
            c = paddle.ops.scale(c, 2.0)
            out = paddle.ops.add(x, c)
        assert len(main.ops) == 1, [op.name for op in main.ops]
        xv = np.ones((2, 3), "float32")
        (a,) = _run(main, {"x": xv}, [out])
        expect = xv + np.arange(6, dtype="float32").reshape(2, 3) * 2
        np.testing.assert_allclose(a, expect, rtol=1e-6)
    finally:
        paddle.disable_static()


def test_cse_merges_duplicates():
    paddle.enable_static()
    try:
        main = static.Program("cse")
        with static.program_guard(main):
            x = static.data("x", [2, 2], "float32")
            a = paddle.ops.exp(x)
            b = paddle.ops.exp(x)        # duplicate
            out = paddle.ops.add(a, b)
        n_before = len(main.ops)
        deduped = apply_pass(main, "cse")
        assert len(deduped.ops) == n_before - 1
        xv = np.random.RandomState(0).rand(2, 2).astype("float32")
        (r1,) = _run(main, {"x": xv}, [out])
        (r2,) = _run(deduped, {"x": xv}, [out])
        np.testing.assert_allclose(r1, r2, rtol=1e-6)
    finally:
        paddle.disable_static()


def test_fold_constants_on_deserialized_program():
    """VERDICT r04 weak #8. In this design, record-time eager evaluation
    already folds const-only subexpressions (constants execute eagerly
    during tracing), so freshly-traced programs have nothing to fold; the
    pass covers DESERIALIZED/hand-built programs, where const chains can
    exist as recorded ops. Build one directly and fold it."""
    import jax.tree_util as jtu
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import static
    from paddle_tpu.ops import OP_REGISTRY
    from paddle_tpu.static.passes import fold_constants
    from paddle_tpu.static.program import OpNode, Program, Variable, _Ref

    prog = Program("fold")
    x = Variable([2, 4], "float32", name="x", is_data=True, program=prog)
    prog.add_data_var(x)
    w = np.full((4, 4), 2.0, "float32")

    def mk(opname, flat, n_args, kwargs, out_shapes, out_dtypes):
        leaves, tree = jtu.tree_flatten(kwargs)
        outs = [Variable(s, d, program=prog)
                for s, d in zip(out_shapes, out_dtypes)]
        node = OpNode(OP_REGISTRY[opname].raw, opname, list(flat) + leaves,
                      n_args, tree, outs)
        prog.ops.append(node)
        return outs

    (wt,) = mk("transpose", [w, [1, 0]], 2, {}, [(4, 4)], ["float32"])
    (ws,) = mk("scale", [_Ref(wt), 3.0], 2, {}, [(4, 4)], ["float32"])
    (out,) = mk("matmul", [_Ref(x), _Ref(ws)], 2, {}, [(2, 4)], ["float32"])
    prog._jit_fetch_vars = [out]

    folded = fold_constants(prog)
    assert len(folded.ops) == 1, len(folded.ops)  # only the matmul remains
    exe = static.Executor()
    xv = np.random.RandomState(0).rand(2, 4).astype("float32")
    (a,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
    (b,) = exe.run(folded, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(a, b, rtol=1e-6)
    np.testing.assert_allclose(a, xv @ (w.T * 3.0), rtol=1e-5)


def test_onnx_export_compat_surface():
    import os
    import tempfile

    import numpy as np
    import paddle_tpu as paddle
    import pytest as _pytest
    from paddle_tpu import jit, nn

    net = nn.Linear(4, 2)
    net.eval()
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "m.onnx")
        with _pytest.warns(UserWarning, match="StableHLO"):
            prefix = paddle.onnx.export(
                net, path,
                input_spec=[jit.InputSpec([1, 4], "float32", "x")])
        assert os.path.exists(prefix + ".stablehlo")
        from paddle_tpu.inference import Predictor
        x = np.ones((1, 4), "float32")
        got = Predictor(prefix).run([x])[0]
        want = np.asarray(net(paddle.to_tensor(x)).numpy())
        np.testing.assert_allclose(got, want, rtol=1e-5)
