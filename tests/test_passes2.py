"""Perf-relevant Program passes (VERDICT r03 N10 'partial' note):
constant folding and CSE measurably shrink the lowered op list while
preserving results."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.static.passes import apply_pass


def _run(program, feed, fetch):
    exe = static.Executor()
    return exe.run(program, feed=feed, fetch_list=fetch)


def test_constant_folding_happens_at_trace_time():
    """Design property (static/passes.py NOTE): literal-only chains run
    eagerly during tracing and enter the Program as baked constants — the
    4-op chain below records exactly ONE op (the add that touches the
    data Variable), i.e. constant folding needs no pass here."""
    paddle.enable_static()
    try:
        main = static.Program("fold")
        with static.program_guard(main):
            x = static.data("x", [2, 3], "float32")
            c = paddle.ops.arange(0, 6, dtype="float32")
            c = paddle.ops.reshape(c, [2, 3])
            c = paddle.ops.scale(c, 2.0)
            out = paddle.ops.add(x, c)
        assert len(main.ops) == 1, [op.name for op in main.ops]
        xv = np.ones((2, 3), "float32")
        (a,) = _run(main, {"x": xv}, [out])
        expect = xv + np.arange(6, dtype="float32").reshape(2, 3) * 2
        np.testing.assert_allclose(a, expect, rtol=1e-6)
    finally:
        paddle.disable_static()


def test_cse_merges_duplicates():
    paddle.enable_static()
    try:
        main = static.Program("cse")
        with static.program_guard(main):
            x = static.data("x", [2, 2], "float32")
            a = paddle.ops.exp(x)
            b = paddle.ops.exp(x)        # duplicate
            out = paddle.ops.add(a, b)
        n_before = len(main.ops)
        deduped = apply_pass(main, "cse")
        assert len(deduped.ops) == n_before - 1
        xv = np.random.RandomState(0).rand(2, 2).astype("float32")
        (r1,) = _run(main, {"x": xv}, [out])
        (r2,) = _run(deduped, {"x": xv}, [out])
        np.testing.assert_allclose(r1, r2, rtol=1e-6)
    finally:
        paddle.disable_static()
