"""launch.py-driven PS job with a worker dying mid-epoch (VERDICT r03
item 6 'Done' clause): elastic whole-job restart recovers with table
state intact via the snapshot file."""
import os
import subprocess
import sys
import textwrap

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = textwrap.dedent("""
    import os, sys
    import numpy as np

    role = os.environ["TRAINING_ROLE"]
    attempt = int(os.environ.get("PADDLE_LAUNCH_ATTEMPT", "0"))
    workdir = sys.argv[1]
    snap = os.path.join(workdir, "snap")

    if role == "PSERVER":
        from paddle_tpu.distributed.ps import PSServer
        port = os.environ["PADDLE_PORT"]
        srv = PSServer(endpoint=f"127.0.0.1:{port}", tables={
            "emb": {"type": "sparse", "dim": 4, "optimizer": "sgd",
                    "lr": 1.0, "init": "zeros"}})
        srv.start()
        srv.run()                       # until stop_servers
        sys.exit(0)

    # ---- worker --------------------------------------------------------
    from paddle_tpu.distributed.ps import PSClient
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    eps = os.environ["PADDLE_PSERVERS_IP_PORT_LIST"].split(",")
    client = PSClient(eps)
    ids = np.arange(4, dtype=np.int64)

    if attempt > 0 and rank == 0 and os.path.exists(snap + ".s0"):
        # restart path: restore table state before continuing
        client.load_snapshot(snap)

    client.pull_sparse("emb", ids)
    client.push_sparse_grad("emb", ids, np.ones((4, 4), np.float32))
    if rank == 0:
        client.save_snapshot(snap)

    if attempt == 0 and rank == 0:
        # die mid-epoch on the first attempt (the "kill")
        os._exit(7)

    client.push_sparse_grad("emb", ids, np.ones((4, 4), np.float32))
    rows = client.pull_sparse("emb", ids)
    if rank == 0 and attempt > 0:
        # restored snapshot (-1s and lower from attempt 0) + this run's
        # two pushes: monotone descent proves state carried over rather
        # than restarting from zeros
        assert (np.asarray(rows) <= -2.999).all(), np.asarray(rows)
    with open(os.path.join(workdir, f"ok_{rank}_{attempt}"), "w") as f:
        f.write("done")
    if rank == 0:
        # wait for the peer before shutting servers down — stopping while
        # rank 1 is mid-push would fail its RPC and flap the job
        import time
        peer = os.path.join(workdir, f"ok_1_{attempt}")
        deadline = time.time() + 60
        while not os.path.exists(peer) and time.time() < deadline:
            time.sleep(0.1)
        client.stop_servers()
    client.close()
    sys.exit(0)
""")


def test_launch_ps_kill_worker_recovers(tmp_path):
    script = tmp_path / "ps_job.py"
    script.write_text(_SCRIPT)
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    code = (
        "from paddle_tpu.distributed.launch import launch_ps; "
        f"launch_ps({str(script)!r}, ({str(tmp_path)!r},), server_num=1, "
        f"worker_num=2, start_port={port}, elastic_retries=2)")
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": "",
           "PYTHONPATH": f"{os.environ.get('PYTHONPATH', '')}:{REPO}"}
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"launch failed:\n{r.stdout}\n{r.stderr}"
    assert "elastic restart 1/2" in r.stdout
    import glob
    oks = sorted(os.path.basename(f)
                 for f in glob.glob(str(tmp_path / "ok_*")))
    # rank 0 must have completed on a RESTARTED attempt (it dies on #0)
    assert any(f.startswith("ok_0_") and not f.endswith("_0")
               for f in oks), oks
    assert any(f.startswith("ok_1_") for f in oks), oks
