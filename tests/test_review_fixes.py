"""Regression tests for review findings (kwarg grads, pad order, PyLayer
alignment, ignore_index, softplus overflow, ceil_mode, bf16 flag)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_kwarg_tensor_gets_grad():
    x = paddle.randn([4, 8])
    w = paddle.ones([8]); w.stop_gradient = False
    b = paddle.zeros([8]); b.stop_gradient = False
    out = paddle.ops.layer_norm(x, weight=w, bias=b)
    out.sum().backward()
    assert w.grad is not None and b.grad is not None
    np.testing.assert_allclose(b.grad.numpy(), np.full(8, 4.0), rtol=1e-5)


def test_pad_pair_order_matches_paddle():
    x = paddle.ones([1, 1, 3, 3])
    out = paddle.ops.pad(x, [1, 2, 0, 0])  # pads W by (1,2), H untouched
    assert out.shape == (1, 1, 3, 6)
    out2 = paddle.ops.pad(x, [0, 0, 3, 4])  # pads H by (3,4)
    assert out2.shape == (1, 1, 10, 3)


def test_pylayer_mixed_stop_gradient_alignment():
    class Mix(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, a, b):
            return a + 2 * b

        @staticmethod
        def backward(ctx, g):
            return g * 100, g * 2  # (grad_a, grad_b)

    a = paddle.ones([3])  # stop_gradient=True
    b = paddle.ones([3]); b.stop_gradient = False
    out = Mix.apply(a, b)
    out.sum().backward()
    assert a.grad is None
    np.testing.assert_allclose(b.grad.numpy(), [2.0, 2.0, 2.0])


def test_cross_entropy_negative_ignore_index():
    logits = paddle.to_tensor(np.random.randn(4, 5).astype("float32"),
                              stop_gradient=False)
    labels = paddle.to_tensor(np.array([1, -100, 2, -100]))
    loss = paddle.ops.cross_entropy(logits, labels, ignore_index=-100)
    # only 2 valid rows contribute; finite and grads zero on ignored rows
    assert np.isfinite(loss.item())
    loss.backward()
    g = logits.grad.numpy()
    np.testing.assert_allclose(g[1], 0.0, atol=1e-7)
    np.testing.assert_allclose(g[3], 0.0, atol=1e-7)
    assert np.abs(g[0]).sum() > 0


def test_softplus_large_input_grad():
    x = paddle.to_tensor([100.0], stop_gradient=False)
    y = paddle.ops.softplus(x)
    y.backward()
    np.testing.assert_allclose(y.numpy(), [100.0])
    np.testing.assert_allclose(x.grad.numpy(), [1.0])


def test_max_pool_ceil_mode():
    x = paddle.randn([1, 1, 5, 5])
    out = paddle.ops.max_pool2d(x, 2, stride=2, ceil_mode=True)
    assert out.shape == (1, 1, 3, 3)
    out = paddle.ops.max_pool2d(x, 2, stride=2, ceil_mode=False)
    assert out.shape == (1, 1, 2, 2)
    a = paddle.ops.avg_pool2d(x, 2, stride=2, ceil_mode=True)
    assert a.shape == (1, 1, 3, 3)
    # exclusive counting: corner cell averages only the 1 real element
    np.testing.assert_allclose(a.numpy()[0, 0, 2, 2], x.numpy()[0, 0, 4, 4],
                               rtol=1e-6)


def test_bf16_matmul_flag():
    a = paddle.ones([8, 8]).astype(paddle.bfloat16)
    b = paddle.ones([8, 8]).astype(paddle.bfloat16)
    paddle.set_flags({"FLAGS_use_bf16_matmul": False})
    try:
        out = paddle.matmul(a, b)
        assert out.dtype == paddle.bfloat16
    finally:
        paddle.set_flags({"FLAGS_use_bf16_matmul": True})
    out = paddle.matmul(a, b)
    assert out.dtype == paddle.bfloat16
    np.testing.assert_allclose(out.numpy().astype("float32"), np.full((8, 8), 8.0))


def test_in_dynamic_mode_importable():
    assert paddle.in_dynamic_mode() in (True, False)
