"""Regression tests for review findings (kwarg grads, pad order, PyLayer
alignment, ignore_index, softplus overflow, ceil_mode, bf16 flag)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_kwarg_tensor_gets_grad():
    x = paddle.randn([4, 8])
    w = paddle.ones([8]); w.stop_gradient = False
    b = paddle.zeros([8]); b.stop_gradient = False
    out = paddle.ops.layer_norm(x, weight=w, bias=b)
    out.sum().backward()
    assert w.grad is not None and b.grad is not None
    np.testing.assert_allclose(b.grad.numpy(), np.full(8, 4.0), rtol=1e-5)


def test_pad_pair_order_matches_paddle():
    x = paddle.ones([1, 1, 3, 3])
    out = paddle.ops.pad(x, [1, 2, 0, 0])  # pads W by (1,2), H untouched
    assert out.shape == (1, 1, 3, 6)
    out2 = paddle.ops.pad(x, [0, 0, 3, 4])  # pads H by (3,4)
    assert out2.shape == (1, 1, 10, 3)


def test_pylayer_mixed_stop_gradient_alignment():
    class Mix(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, a, b):
            return a + 2 * b

        @staticmethod
        def backward(ctx, g):
            return g * 100, g * 2  # (grad_a, grad_b)

    a = paddle.ones([3])  # stop_gradient=True
    b = paddle.ones([3]); b.stop_gradient = False
    out = Mix.apply(a, b)
    out.sum().backward()
    assert a.grad is None
    np.testing.assert_allclose(b.grad.numpy(), [2.0, 2.0, 2.0])


def test_cross_entropy_negative_ignore_index():
    logits = paddle.to_tensor(np.random.randn(4, 5).astype("float32"),
                              stop_gradient=False)
    labels = paddle.to_tensor(np.array([1, -100, 2, -100]))
    loss = paddle.ops.cross_entropy(logits, labels, ignore_index=-100)
    # only 2 valid rows contribute; finite and grads zero on ignored rows
    assert np.isfinite(loss.item())
    loss.backward()
    g = logits.grad.numpy()
    np.testing.assert_allclose(g[1], 0.0, atol=1e-7)
    np.testing.assert_allclose(g[3], 0.0, atol=1e-7)
    assert np.abs(g[0]).sum() > 0


def test_softplus_large_input_grad():
    x = paddle.to_tensor([100.0], stop_gradient=False)
    y = paddle.ops.softplus(x)
    y.backward()
    np.testing.assert_allclose(y.numpy(), [100.0])
    np.testing.assert_allclose(x.grad.numpy(), [1.0])


def test_max_pool_ceil_mode():
    x = paddle.randn([1, 1, 5, 5])
    out = paddle.ops.max_pool2d(x, 2, stride=2, ceil_mode=True)
    assert out.shape == (1, 1, 3, 3)
    out = paddle.ops.max_pool2d(x, 2, stride=2, ceil_mode=False)
    assert out.shape == (1, 1, 2, 2)
    a = paddle.ops.avg_pool2d(x, 2, stride=2, ceil_mode=True)
    assert a.shape == (1, 1, 3, 3)
    # exclusive counting: corner cell averages only the 1 real element
    np.testing.assert_allclose(a.numpy()[0, 0, 2, 2], x.numpy()[0, 0, 4, 4],
                               rtol=1e-6)


def test_bf16_matmul_flag():
    a = paddle.ones([8, 8]).astype(paddle.bfloat16)
    b = paddle.ones([8, 8]).astype(paddle.bfloat16)
    paddle.set_flags({"FLAGS_use_bf16_matmul": False})
    try:
        out = paddle.matmul(a, b)
        assert out.dtype == paddle.bfloat16
    finally:
        paddle.set_flags({"FLAGS_use_bf16_matmul": True})
    out = paddle.matmul(a, b)
    assert out.dtype == paddle.bfloat16
    np.testing.assert_allclose(out.numpy().astype("float32"), np.full((8, 8), 8.0))


def test_in_dynamic_mode_importable():
    assert paddle.in_dynamic_mode() in (True, False)


def test_gradient_accumulation_matches_big_batch():
    from paddle_tpu import Model, nn, optimizer
    paddle.seed(5)
    X = np.random.randn(8, 4).astype("float32")
    y = np.random.randn(8, 1).astype("float32")

    def make():
        paddle.seed(7)
        net = nn.Linear(4, 1)
        m = Model(net)
        m.prepare(optimizer.SGD(learning_rate=0.1,
                                parameters=net.parameters()),
                  loss=nn.MSELoss())
        return m

    m_big = make()
    m_big.train_batch([X], [y])
    w_big = m_big.network.weight.numpy()

    m_acc = make()
    # two half-batches with accumulation; MSE of halves averages to full MSE
    m_acc.train_batch([X[:4]], [y[:4]], update=False)
    m_acc.train_batch([X[4:]], [y[4:]], update=True)
    w_acc = m_acc.network.weight.numpy()
    np.testing.assert_allclose(w_acc, w_big, rtol=1e-5, atol=1e-6)


def test_nll_loss_ignore_index():
    logp = paddle.to_tensor(np.log(np.full((3, 4), 0.25, "float32")))
    lab = paddle.to_tensor(np.array([0, -100, 2]))
    loss = paddle.ops.nll_loss(logp, lab, ignore_index=-100)
    np.testing.assert_allclose(loss.item(), np.log(4.0), rtol=1e-6)


def test_weighted_cross_entropy_normalization():
    logits = paddle.to_tensor(np.zeros((2, 2), "float32"))
    labels = paddle.to_tensor(np.array([0, 1]))
    w = paddle.to_tensor(np.array([1.0, 3.0], "float32"))
    loss = paddle.ops.cross_entropy(logits, labels, weight=w)
    # both losses = ln2; weighted mean = (1*ln2 + 3*ln2)/(1+3) = ln2
    np.testing.assert_allclose(loss.item(), np.log(2.0), rtol=1e-6)


def test_instance_norm_independent_attrs():
    from paddle_tpu import nn
    layer = nn.InstanceNorm2D(4, bias_attr=False)
    assert layer.weight is not None and layer.bias is None


def test_embedding_negative_padding_idx():
    from paddle_tpu import nn
    emb = nn.Embedding(10, 4, padding_idx=-1)
    out = emb(paddle.to_tensor(np.array([9, 1])))
    np.testing.assert_allclose(out.numpy()[0], np.zeros(4))
    assert np.abs(out.numpy()[1]).sum() > 0


def test_rnn_wrapper_sequence_mask():
    from paddle_tpu import nn
    cell = nn.GRUCell(3, 5)
    rnn = nn.RNN(cell)
    x = paddle.randn([2, 6, 3])
    out, state = rnn(x, initial_states=paddle.zeros([2, 5]),
                     sequence_length=paddle.to_tensor([6, 3]))
    assert np.allclose(out.numpy()[1, 3:], 0.0)  # masked outputs
    np.testing.assert_allclose(state.numpy()[1], out.numpy()[1, 2], rtol=1e-5)
