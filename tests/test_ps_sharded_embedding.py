"""Chaos suite for the pod-scale sharded embedding engine (ISSUE 12).

PR 7 proved the replicated STORAGE exactly-once through a permanent
primary kill; this suite proves the layers the recsys workload actually
trains through — client-side batched deduped cross-shard lookups
(distributed/ps/client.py), the tiered HeterPS LRU cache (ps/heter.py)
and the async embedding-prefetch stage (ps/embedding.py riding
static/pipeline_runner.InflightDriver) — add ZERO new failure surface:

- a cross-shard batch costs one row per shard regardless of duplication
  and routing, order-preserving, exactly-once, through empty batches and
  mid-batch ShardMapStale epoch bumps;
- a latency-skewed (slow, not dead) shard server is absorbed by the
  prefetch stage WITHOUT changing results (testing/faults.py endpoint-
  targetable STALL);
- THE acceptance proof: 3-shard-server/1-backup training where every
  pull rides prefetch + LRU cache, under seeded RESET+DROP chaos plus
  scripted PARTITION dials plus a PERMANENT mid-run shard-primary kill,
  ends bitwise-equal to the synchronous fault-free run, with >=1
  promotion, >=1 cache invalidation, and per-server `table.applied`
  matching the deterministic push schedule replayed against the
  membership timeline EXACTLY.
"""
import time

import numpy as np
import pytest

from paddle_tpu.core import monitor
from paddle_tpu.core.flags import set_flags
from paddle_tpu.distributed.ps import (EmbeddingPrefetcher, HeterPSCache,
                                       PSClient, PSServer, ShardMap)
from paddle_tpu.static.pipeline_runner import PipelineStepError
from paddle_tpu.testing import faults

pytestmark = pytest.mark.chaos

DIM = 4
VOCAB = 60

FAST = dict(timeout=5.0, max_retries=2, backoff_base=0.01,
            backoff_max=0.05, connect_retry_s=5.0)
HB = dict(heartbeat_s=0.1, heartbeat_timeout_s=0.7)


def _specs(optimizer="adagrad", lr=0.1):
    return {"emb": {"type": "sparse", "dim": DIM, "optimizer": optimizer,
                    "lr": lr, "init": "uniform", "seed": 9}}


def _cluster(n=3, k=1, specs=None):
    servers = [PSServer("127.0.0.1:0", specs or _specs())
               for _ in range(n)]
    eps = [s.start() for s in servers]
    smap = ShardMap.create(eps, n_backups=k)
    for s in servers:
        s.enable_replication(shard_map=smap, peers=eps, n_backups=k,
                             rpc_opts=dict(FAST), **HB)
    return servers, eps


def _teardown(servers, *closers):
    for c in closers:
        try:
            c.close()
        except Exception:
            pass
    for s in servers:
        s.shutdown()


@pytest.fixture(autouse=True)
def _no_leftover_injector():
    yield
    faults.uninstall()


def _delta(before, name):
    return monitor.stat_get(name) - before.get(name, 0)


# ------------------------------------------- cross-shard batched lookups

@pytest.mark.parametrize("fanout", [1, 4])
def test_pull_dedupes_across_shards_order_preserving(fanout):
    """[5, 9, 5, ...] spanning all shards with duplicates within AND
    across shard slices: one row per unique id on the wire, result in
    input order, duplicate positions identical."""
    servers, eps = _cluster()
    client = PSClient(eps, **FAST)
    set_flags({"PADDLE_PS_FANOUT_THREADS": fanout})
    try:
        ids = np.array([5, 9, 5, 1, 3, 2, 2, 59, 9], np.int64)
        before = monitor.stats("ps.client.")
        rows = client.pull_sparse("emb", ids)
        assert rows.shape == (9, DIM)
        # one row per unique id crossed the wire, one RPC per shard
        assert _delta(before, "ps.client.pull_ids") == 9
        assert _delta(before, "ps.client.pull_unique_rows") == 6
        assert _delta(before, "ps.client.pull_rpcs") == 3
        # order-preserving: each position equals its single-id pull
        for pos, i in enumerate(ids):
            np.testing.assert_array_equal(
                rows[pos], client.pull_sparse("emb", np.array([i]))[0])
        # duplicate positions are bitwise the same row
        np.testing.assert_array_equal(rows[0], rows[2])
        np.testing.assert_array_equal(rows[1], rows[8])
        # servers materialized only their own unique ids
        sizes = [len(s.table("emb")) for s in servers]
        assert sizes[0] == 2   # shard 0: {3, 9}  (pulls only touch the
        assert sizes[1] == 1   # shard 1: {1}      primary — no backup
        assert sizes[2] == 3   # shard 2: {2, 5, 59}       materializes)
    finally:
        set_flags({"PADDLE_PS_FANOUT_THREADS": 4})
        _teardown(servers, client)


def test_pull_empty_batch_and_empty_push():
    servers, eps = _cluster()
    client = PSClient(eps, **FAST)
    try:
        rows = client.pull_sparse("emb", np.zeros((0,), np.int64))
        assert rows.shape == (0, DIM)
        # empty pushes are a no-op, not a wire error
        client.push_sparse_grad("emb", np.zeros((0,), np.int64),
                                np.zeros((0, DIM), np.float32))
        assert all(s.table("emb").applied == 0 for s in servers)
    finally:
        _teardown(servers, client)


def test_batch_during_epoch_bump_order_preserving_exactly_once():
    """A batch arriving with a stale map epoch: the first shard call
    gets a ShardMapStale redirect, the client adopts mid-batch and
    re-routes — rows stay order-preserving, pushes stay exactly-once."""
    servers, eps = _cluster()
    client = PSClient(eps, **FAST)
    try:
        ids = np.array([0, 1, 2, 4, 0], np.int64)   # all shards + dup
        expect = client.pull_sparse("emb", ids)
        # bump the cluster's epoch behind the client's back (no routing
        # change needed — the epoch check alone trips the redirect)
        old = servers[0].replica.shard_map
        d = old.to_dict()
        d["epoch"] = old.epoch + 1
        for s in servers:
            s.replica.install(d)
        before = monitor.stats("ps.replica.")
        rows = client.pull_sparse("emb", ids)
        np.testing.assert_array_equal(rows, expect)
        assert _delta(before, "ps.replica.stale_maps") >= 1
        assert client.shard_map.epoch == old.epoch + 1
        # a push with dup ids through the bumped map: merged client-side,
        # applied exactly once per member of each touched shard
        applied0 = [s.table("emb").applied for s in servers]
        client.push_sparse_grad("emb", ids, np.ones((5, DIM), np.float32))
        for idx, s in enumerate(servers):
            # chained map: server i is primary of shard i, backup of
            # shard i-1; ids touch shards {0,1,2} -> 2 applies each
            assert s.table("emb").applied == applied0[idx] + 2
    finally:
        _teardown(servers, client)


def test_push_batch_exactly_once_under_dropped_replies():
    """DROP every shard's first push reply: the client retries, the
    replay cache dedupes — applied counters exact, values exact."""
    servers, eps = _cluster()
    client = PSClient(eps, **FAST)
    try:
        ids = np.arange(6, dtype=np.int64)           # shards {0,1,2}
        client.pull_sparse("emb", ids)
        base = client.pull_sparse("emb", ids)
        # times=2 < the 3-attempt transport budget: both drops can land
        # on ONE forward's replies without exhausting it (3 would evict
        # the backup — a different, also-correct story)
        with faults.inject(faults.Fault("server", "reply", faults.DROP,
                                        method="push_sparse_grad",
                                        times=2)) as inj:
            client.push_sparse_grad("emb", ids,
                                    np.ones((6, DIM), np.float32))
        assert inj.fired(faults.DROP) >= 1
        for s in servers:
            assert s.table("emb").applied == 2   # primary + backup roles
        got = client.pull_sparse("emb", ids)
        # adagrad lr=0.1 single unit push: row -= 0.1/sqrt(1)+eps-ish;
        # exactness vs a clean reference cluster is the real check
        ref_servers, ref_eps = _cluster()
        ref = PSClient(ref_eps, **FAST)
        ref.pull_sparse("emb", ids)
        ref.push_sparse_grad("emb", ids, np.ones((6, DIM), np.float32))
        np.testing.assert_array_equal(got, ref.pull_sparse("emb", ids))
        assert not np.array_equal(base, got)
        _teardown(ref_servers, ref)
    finally:
        _teardown(servers, client)


# ------------------------------------------------- prefetch + slow shard

def _run_workload(eps, n_steps, use_prefetch, compute_s=0.0,
                  cache_rows=None):
    """The shared deterministic loop; returns (final rows, stats)."""
    client = PSClient(eps, **FAST)
    pf = cache = None
    if use_prefetch:
        cache = HeterPSCache(client, "emb", DIM,
                             capacity=cache_rows or 32, host_rows=64)
        pf = EmbeddingPrefetcher(cache)
    try:
        for step in range(n_steps):
            ids = _batch_ids(step)
            if pf is not None:
                rows = pf.get(ids)
                if step + 1 < n_steps:
                    pf.prefetch(_batch_ids(step + 1))
            else:
                rows = client.pull_sparse("emb", ids)
            if compute_s:
                time.sleep(compute_s)      # the "dense step"
            grads = rows * 0.05 + np.random.RandomState(
                5000 + step).randn(len(ids), DIM).astype(np.float32)
            if pf is not None:
                pf.push_grad(ids, grads)
            else:
                client.push_sparse_grad("emb", ids, grads)
        final = client.pull_sparse("emb", np.arange(VOCAB, dtype=np.int64))
        stats = pf.stats() if pf is not None else {}
        return final, stats
    finally:
        if pf is not None:
            pf.close()
        client.close()


def _batch_ids(step):
    return np.random.RandomState(1000 + step).randint(
        0, VOCAB, size=10).astype(np.int64)


def test_slow_shard_latency_skew_absorbed_by_prefetch():
    """testing/faults.py endpoint-targetable STALL: ONE shard server is
    slow (never dead — nothing retries or fails over). The prefetch
    stage hides its latency behind the dense step without changing a
    single bit of the result."""
    n_steps = 10
    ref_servers, ref_eps = _cluster()
    ref, _ = _run_workload(ref_eps, n_steps, use_prefetch=False)
    _teardown(ref_servers)

    servers, eps = _cluster()
    try:
        skew = faults.Fault("client", "send", faults.STALL,
                            endpoint=eps[1], times=10 ** 9, delay=0.05)
        with faults.inject(skew) as inj:
            got, stats = _run_workload(eps, n_steps, use_prefetch=True,
                                       compute_s=0.03)
        assert inj.fired(faults.STALL) >= n_steps  # the skew was real
        np.testing.assert_array_equal(got, ref)    # ...and invisible
        # the dense step absorbed most of the background pull time
        assert stats["prefetched"] == n_steps - 1
        assert stats["wait_s"] < stats["pull_s"], stats
    finally:
        _teardown(servers)


def test_prefetch_failure_surfaces_then_recovers():
    """A dead prefetch surfaces as PipelineStepError naming its step —
    and having surfaced, the prefetcher starts a clean window: one
    transient outage must not poison every later prefetch."""
    srv = PSServer(tables=_specs())
    ep = srv.start()
    client = PSClient([ep], **FAST)
    pf = EmbeddingPrefetcher(client, table="emb")
    ids = np.array([1, 2], np.int64)
    try:
        # kill the first prefetch's pull: more RESETs than the
        # transport's 3-attempt budget
        with faults.inject(faults.Fault("client", "send", faults.RESET,
                                        method="pull_sparse", times=5)):
            pf.prefetch(ids)
            with pytest.raises(PipelineStepError) as ei:
                pf.get(ids)
        assert ei.value.step_index == 0
        # recovery: a fresh prefetch on the rebuilt window works, and
        # matches the synchronous path
        pf.prefetch(ids)
        np.testing.assert_array_equal(pf.get(ids),
                                      client.pull_sparse("emb", ids))
        assert pf.stats()["prefetched"] == 2
    finally:
        pf.close()
        client.close()
        srv.shutdown()


def test_prefetch_abandons_skipped_batches_and_bounds_versions():
    """FIFO contract: queued batches the trainer skipped past are
    dropped (not left pinning the window head), and the conflict
    version table resets whenever no snapshot is in flight — bounded by
    the prefetch window, never by the vocab."""
    servers, eps = _cluster()
    client = PSClient(eps, **FAST)
    pf = EmbeddingPrefetcher(client, table="emb", depth=2)
    try:
        pf.prefetch(np.array([0, 1], np.int64))
        pf.prefetch(np.array([2, 3], np.int64))
        before = monitor.stats("ps.embed.")
        rows = pf.get(np.array([4, 5], np.int64))   # matches neither
        assert _delta(before, "ps.embed.abandoned") == 2
        assert _delta(before, "ps.embed.sync_pulls") == 1
        np.testing.assert_array_equal(rows,
                                      client.pull_sparse("emb", [4, 5]))
        # the window restarts cleanly after the drain
        pf.prefetch(np.array([6], np.int64))
        np.testing.assert_array_equal(pf.get(np.array([6], np.int64)),
                                      client.pull_sparse("emb", [6]))
        # no snapshot in flight -> pushes don't accrete version entries
        pf.push_grad(np.array([6], np.int64), np.ones((1, DIM),
                                                      np.float32))
        assert len(pf._versions) == 0
    finally:
        pf.close()
        _teardown(servers, client)


def test_prefetch_conflict_ids_repulled_bitwise():
    """Overlapping consecutive batches: the prefetched copy of a row
    that the current step then pushes is STALE — get() must re-pull
    exactly those ids and match the synchronous path bitwise."""
    servers, eps = _cluster()
    client = PSClient(eps, **FAST)
    pf = EmbeddingPrefetcher(client, table="emb")
    try:
        a = np.array([0, 1, 2, 3], np.int64)
        b = np.array([2, 3, 4, 5], np.int64)        # overlaps {2, 3}
        pf.get(a)                                   # sync (cold)
        pf.prefetch(b)                              # snapshot pre-push
        pf.sync()                                   # rows of b fetched
        g = np.ones((4, DIM), np.float32)
        pf.push_grad(a, g)                          # {2,3} now stale
        before = monitor.stats("ps.embed.")
        rows_b = pf.get(b)
        assert _delta(before, "ps.embed.conflict_repulls") == 2
        np.testing.assert_array_equal(
            rows_b, client.pull_sparse("emb", b))   # post-push values
    finally:
        pf.close()
        _teardown(servers, client)


# ---------------------------------------- THE acceptance chaos training

N_STEPS = 24
KILL_STEP = 11


def _expected_applied(eps, dead_idx=None):
    """EXACT per-server `emb.applied` expectation: the deterministic
    push schedule replayed against the membership timeline (chained
    map: shard s -> primary eps[s], backup eps[s+1]; after KILL_STEP
    the dead server leaves every chain). One lost OR double-applied
    mutation anywhere breaks the equality."""
    n = len(eps)
    emb = {ep: 0 for ep in eps}
    for step in range(N_STEPS):
        shards = {int(i) % n for i in _batch_ids(step)}
        killed = dead_idx is not None and step >= KILL_STEP
        for s in shards:
            members = [eps[s], eps[(s + 1) % n]]
            if killed:
                members = [m for m in members if m != eps[dead_idx]]
            for m in members:
                emb[m] += 1
    return emb


def test_chaos_sharded_embedding_kill_primary_bitwise_equals_sync():
    """THE proof. Three runs on identical 3-server/1-backup clusters:

    1. synchronous pulls, fault-free            -> reference bits
    2. prefetch + tiered LRU cache, fault-free  -> must equal (1)
    3. prefetch + cache under seeded RESET+DROP chaos + scripted
       PARTITION dials + a PERMANENT mid-run kill of shard 0's
       primary                                  -> must equal (1)

    with >=1 promotion, >=1 cache invalidation, the prefetch/cache path
    live through the outage, and per-server table.applied matching the
    deterministic schedule against the membership timeline exactly."""
    # ---- run 1: synchronous, fault-free
    s1, eps1 = _cluster()
    ref, _ = _run_workload(eps1, N_STEPS, use_prefetch=False)
    exp = _expected_applied(eps1)
    for s in s1:
        assert s.table("emb").applied == exp[s.endpoint]
    _teardown(s1)

    # ---- run 2: the async engine, fault-free — prefetch parity
    s2, eps2 = _cluster()
    got2, stats2 = _run_workload(eps2, N_STEPS, use_prefetch=True)
    np.testing.assert_array_equal(got2, ref)
    assert stats2["prefetched"] == N_STEPS - 1
    exp = _expected_applied(eps2)
    for s in s2:
        assert s.table("emb").applied == exp[s.endpoint]
    _teardown(s2)

    # ---- run 3: chaos + permanent shard-primary kill
    servers, eps = _cluster()
    before = monitor.stats("ps.replica.")
    rpc_before = monitor.stats("ps.rpc.")
    heter_before = monitor.stats("ps.heter.")
    client = PSClient(eps, **FAST)
    try:
        with faults.inject(
                faults.Fault("client", "dial", faults.PARTITION,
                             endpoint=eps[2], times=2),
                seed=11, p={faults.RESET: 0.02, faults.DROP: 0.02}) as inj:
            # the chaos client is BORN inside the injector: its very
            # first dial of eps[2] is refused (scripted PARTITION), so
            # construction-time dead-endpoint tolerance + the failover
            # re-dial path are both on the proof's critical path
            chaos_client = PSClient(eps, **FAST)
            cache = HeterPSCache(chaos_client, "emb", DIM, capacity=32,
                                 host_rows=64)
            pf = EmbeddingPrefetcher(cache)
            try:
                for step in range(N_STEPS):
                    ids = _batch_ids(step)
                    if step == KILL_STEP:
                        servers[0].shutdown()   # permanent: NEVER back
                    rows = pf.get(ids)
                    if step + 1 < N_STEPS:
                        pf.prefetch(_batch_ids(step + 1))
                    grads = rows * 0.05 + np.random.RandomState(
                        5000 + step).randn(len(ids),
                                           DIM).astype(np.float32)
                    pf.push_grad(ids, grads)
            finally:
                pf.close()
        got3 = client.pull_sparse("emb", np.arange(VOCAB, dtype=np.int64))

        # the chaos actually happened, in every scripted+seeded flavor
        assert inj.fired(faults.RESET) >= 1, "seed injected no resets"
        assert inj.fired(faults.DROP) >= 1, "seed injected no drops"
        assert inj.fired(faults.PARTITION) == 2
        assert _delta(rpc_before, "ps.rpc.retries") >= 1
        assert _delta(before, "ps.replica.promotions") >= 1
        assert chaos_client.shard_map.epoch > 1
        assert eps[0] not in chaos_client.shard_map.servers
        # the cache tier lived through it: hits served, eviction + the
        # membership change invalidated it at least once
        assert _delta(heter_before, "ps.heter.hits") >= 1
        assert _delta(heter_before, "ps.heter.evictions") >= 1
        assert _delta(heter_before, "ps.heter.invalidations") >= 1

        # ...and not one gradient was lost, duplicated or served stale
        np.testing.assert_array_equal(got3, ref)

        # exactly-once, replayed against the membership timeline
        exp = _expected_applied(eps, dead_idx=0)
        for s in servers[1:]:
            assert s.table("emb").applied == exp[s.endpoint]
    finally:
        try:
            chaos_client.close()
        except Exception:
            pass
        _teardown(servers, client)
