"""Beam-search dynamic decoding (reference fluid/layers/rnn.py
BeamSearchDecoder + dynamic_decode; SURVEY hard part 2)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, ops
from paddle_tpu.nn import BeamSearchDecoder, dynamic_decode


class _ToyCell(nn.Layer):
    """Deterministic 'language model': state is the last token's one-hot;
    logits force token (prev + 1) % V until V-1 (= end)."""

    def __init__(self, V):
        super().__init__()
        self.V = V

    def forward(self, inputs, states):
        # inputs: [n] int64 token ids; states: [n, V] dummy hidden
        onehot = ops.one_hot(inputs, self.V).astype("float32")
        nxt = ops.one_hot((inputs + 1) % self.V, self.V).astype("float32")
        logits = nxt * 10.0  # strongly prefer prev+1
        return logits, states


def test_greedy_path_via_beam1():
    V = 6
    cell = _ToyCell(V)
    dec = BeamSearchDecoder(cell, start_token=0, end_token=V - 1,
                            beam_size=1)
    inits = paddle.to_tensor(np.zeros((2, V), "float32"))  # batch 2
    (paths, scores), _ = dynamic_decode(dec, inits, max_step_num=10)
    p = np.asarray(paths._value)
    assert p.shape[:2] == (2, 1)
    # from start 0: 1, 2, 3, 4, 5(end) — decode stops at end token
    np.testing.assert_array_equal(p[0, 0], [1, 2, 3, 4, 5])
    np.testing.assert_array_equal(p[1, 0], p[0, 0])


def test_beam_search_orders_hypotheses():
    """A cell with two strong continuations: the beam must keep both and
    rank the higher-probability path first."""
    V = 5

    class TwoWay(nn.Layer):
        def forward(self, inputs, states):
            n = inputs.shape[0]
            base = np.full((1, V), -10.0, np.float32)
            logits = np.repeat(base, n, 0)
            prev = np.asarray(inputs._value)
            # from 0: token 1 (p~0.6) or 2 (p~0.4); everything then ends (4)
            logits[prev == 0, 1] = np.log(0.6) + 10
            logits[prev == 0, 2] = np.log(0.4) + 10
            logits[prev == 1, 4] = 10.0
            logits[prev == 2, 4] = 10.0
            logits[prev == 4, 4] = 10.0
            return paddle.to_tensor(logits), states

    dec = BeamSearchDecoder(TwoWay(), start_token=0, end_token=4,
                            beam_size=2)
    inits = paddle.to_tensor(np.zeros((1, 3), "float32"))
    (paths, scores), _ = dynamic_decode(dec, inits, max_step_num=6)
    p = np.asarray(paths._value)[0]          # [beam, T]
    s = np.asarray(scores._value)[0]
    assert p[0, 0] == 1 and p[1, 0] == 2     # both continuations kept
    assert s[0] > s[1]                       # ranked by joint score
    assert (p[:, 1] == 4).all()              # both reached end


def test_beam_with_lstm_cell_runs():
    paddle.seed(0)
    V, H = 12, 8
    cell = nn.LSTMCell(H, H)
    emb = nn.Embedding(V, H)
    proj = nn.Linear(H, V)
    dec = BeamSearchDecoder(cell, start_token=1, end_token=2, beam_size=3,
                            embedding_fn=emb, output_fn=proj)
    b = 2
    inits = (paddle.to_tensor(np.zeros((b, H), "float32")),
             paddle.to_tensor(np.zeros((b, H), "float32")))
    (paths, scores), _ = dynamic_decode(dec, inits, max_step_num=5)
    assert np.asarray(paths._value).shape[:2] == (b, 3)
    assert np.isfinite(np.asarray(scores._value)).all()
