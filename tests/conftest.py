"""Test config: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of running distributed tests as multiple
local processes on one host (reference:
python/paddle/fluid/tests/unittests/test_dist_base.py:642) — here XLA's
host-platform device-count spoofing gives us 8 "chips" in-process instead.
"""
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# pass-safety harness (static/passes.py): every Program pass runs
# verify-before/verify-after in tests, so a pass bug fails at the rewrite
os.environ.setdefault("PADDLE_TPU_VERIFY_PASSES", "1")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    # newer jax spells the host-device spoof as a config option; older
    # builds only understand the XLA_FLAGS form set above
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running test, excluded from tier-1 "
        "(-m 'not slow')")
    config.addinivalue_line(
        "markers", "chaos: seeded deterministic fault-injection suite "
        "(paddle_tpu.testing.faults); fast enough to stay in tier-1")


@pytest.fixture(autouse=True)
def _fresh_seed():
    import numpy as np
    import paddle_tpu
    paddle_tpu.seed(1234)
    np.random.seed(1234)  # tests draw synthetic data from the global RNG;
    yield                 # per-test seeding keeps them order-independent
