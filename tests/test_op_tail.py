"""Registry-audit residue ops (tools/op_coverage.py; VERDICT r04 item 3):
spectral_norm, the beam_search pair, segment reductions, spp,
generate_proposals, quantize variants, tdm ops, DetectionMAP.
References cited per-op in the implementations."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import metric, ops


def T(x, dtype="float32"):
    return paddle.to_tensor(np.asarray(x, dtype))


def test_spectral_norm_unit_sigma_and_grad():
    rng = np.random.RandomState(0)
    w = rng.randn(8, 6).astype("float32")
    u = rng.randn(8).astype("float32")
    v = rng.randn(6).astype("float32")
    out = ops.spectral_norm(T(w), T(u), T(v), power_iters=30).numpy()
    np.testing.assert_allclose(np.linalg.svd(out)[1][0], 1.0, rtol=1e-4)
    # dim=1 normalizes along the other axis, same sigma property
    out2 = ops.spectral_norm(T(w), T(v), T(u), dim=1,
                             power_iters=30).numpy()
    np.testing.assert_allclose(np.linalg.svd(out2)[1][0], 1.0, rtol=1e-4)
    # differentiable
    wt = T(w)
    wt.stop_gradient = False
    loss = ops.spectral_norm(wt, T(u), T(v), power_iters=3).sum()
    loss.backward()
    assert np.isfinite(wt.grad.numpy()).all()


def test_beam_search_step_and_decode_roundtrip():
    # greedy trellis: beam search with K=2 over 3 steps must recover the
    # highest-probability path
    b, k, vocab = 1, 2, 4
    pre_ids = T([[1, 1]], "int64")
    pre_sc = T([[0.0, 0.0]], "float32")
    probs = np.array([[[0.1, 0.5, 0.3, 0.1],
                       [0.25, 0.25, 0.25, 0.25]]], "float32")
    ids, sc, par = ops.beam_search(pre_ids, pre_sc, T(np.log(probs)),
                                   beam_size=k, end_id=0)
    assert ids.numpy().tolist() == [[1, 2]]      # top-2 from lane 0
    assert par.numpy().tolist() == [[0, 0]]
    np.testing.assert_allclose(sc.numpy()[0, 0], np.log(0.5), rtol=1e-5)

    # finished lane freezes: pre_id == end_id emits end_id at its score
    pre_ids2 = T([[0, 3]], "int64")
    pre_sc2 = T([[-0.1, -5.0]], "float32")
    ids2, sc2, _ = ops.beam_search(pre_ids2, pre_sc2, T(np.log(probs)),
                                   beam_size=k, end_id=0)
    assert ids2.numpy()[0, 0] == 0
    np.testing.assert_allclose(sc2.numpy()[0, 0], -0.1, rtol=1e-5)

    step_ids = T([[[3, 4]], [[5, 6]]], "int64")
    step_par = T([[[0, 0]], [[1, 0]]], "int64")
    seqs = ops.beam_search_decode(step_ids, step_par, end_id=0)
    assert seqs.numpy().tolist() == [[[4, 5], [3, 6]]]


def test_segment_reductions():
    d = T(np.arange(8).reshape(4, 2))
    seg = T([0, 0, 1, 1], "int32")
    np.testing.assert_allclose(ops.segment_sum(d, seg).numpy(),
                               [[2, 4], [10, 12]])
    np.testing.assert_allclose(ops.segment_mean(d, seg).numpy(),
                               [[1, 2], [5, 6]])
    np.testing.assert_allclose(ops.segment_max(d, seg).numpy(),
                               [[2, 3], [6, 7]])
    np.testing.assert_allclose(ops.segment_min(d, seg).numpy(),
                               [[0, 1], [4, 5]])


def test_truncated_normal_bounds():
    x = ops.truncated_normal([5000], mean=1.0, std=0.5).numpy()
    assert (x <= 1.0 + 2 * 0.5 + 1e-5).all()
    assert (x >= 1.0 - 2 * 0.5 - 1e-5).all()
    assert abs(float(x.mean()) - 1.0) < 0.05


def test_spp_shapes_and_values():
    x = T(np.arange(2 * 3 * 4 * 4).reshape(2, 3, 4, 4))
    out = ops.spp(x, pyramid_height=2, pool_type="max").numpy()
    assert out.shape == (2, 3 * (1 + 4))
    # level 0 equals global max pool per channel
    np.testing.assert_allclose(out[:, :3],
                               np.asarray(x.numpy()).max((2, 3)))


def test_sampling_id_distribution():
    p = T(np.tile(np.array([[0.0, 0.0, 1.0]], "float32"), (16, 1)))
    ids = ops.sampling_id(p, seed=7).numpy()
    assert (ids == 2).all()


def test_fake_quantize_variants_roundtrip():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 5).astype("float32")
    qd, scale = ops.fake_quantize_dequantize_abs_max(T(x))
    np.testing.assert_allclose(float(scale.numpy()),
                               np.abs(x).max(), rtol=1e-6)
    assert np.abs(qd.numpy() - x).max() <= np.abs(x).max() / 127 + 1e-6
    qd2, st = ops.fake_quantize_dequantize_moving_average_abs_max(
        T(x), T(1.0))
    assert np.isfinite(qd2.numpy()).all()
    qd3, sc3 = ops.fake_channel_wise_quantize_dequantize_abs_max(T(x))
    np.testing.assert_allclose(sc3.numpy(), np.abs(x).max(1), rtol=1e-6)
    qd4, sc4 = ops.fake_quantize_range_abs_max(T(x), T(0.5))
    np.testing.assert_allclose(float(sc4.numpy()),
                               max(0.5, np.abs(x).max()), rtol=1e-6)
    codes = np.round(x / np.abs(x).max() * 127)
    deq = ops.fake_dequantize_max_abs(T(codes), T(np.abs(x).max()),
                                      127.0).numpy()
    assert np.abs(deq - x).max() <= np.abs(x).max() / 127 + 1e-6
    ch_codes = np.round(x / np.abs(x).max(1, keepdims=True) * 127)
    deq_ch = ops.fake_channel_wise_dequantize_max_abs(
        T(ch_codes), T(np.abs(x).max(1)), quant_axis=0).numpy()
    assert np.abs(deq_ch - x).max() <= np.abs(x).max() / 127 + 1e-6


def test_dequantize_log_sign_mirror():
    tab = T(np.linspace(0.1, 12.8, 128))
    out = ops.dequantize_log(T([-1, 1, 0], "int8"), tab).numpy()
    np.testing.assert_allclose(out[0], -12.8, rtol=1e-6)
    np.testing.assert_allclose(out[1], tab.numpy()[1], rtol=1e-6)
    np.testing.assert_allclose(out[2], 0.1, rtol=1e-6)


def test_positive_negative_pair():
    score = T([0.9, 0.2, 0.5, 0.8])
    label = T([1.0, 0.0, 1.0, 0.0])
    qid = T([0, 0, 0, 1], "int64")
    p, n, u = ops.positive_negative_pair(score, label, qid)
    # query 0: pairs (0,1): 0.9>0.2 & 1>0 pos; (1,2): 0.2<0.5 & 0<1 pos
    assert (float(p.numpy()), float(n.numpy()),
            float(u.numpy())) == (2.0, 0.0, 0.0)


def test_generate_proposals_basic():
    rng = np.random.RandomState(0)
    sc = rng.rand(1, 3, 4, 4).astype("float32")
    bd = (rng.randn(1, 12, 4, 4) * 0.05).astype("float32")
    anc = rng.rand(4, 4, 3, 4).astype("float32") * 10
    anc[..., 2:] += 15
    var = np.ones((4, 4, 3, 4), "float32")
    rois, probs, num = ops.generate_proposals(
        T(sc), T(bd), T([[32.0, 32.0]]), T(anc), T(var),
        pre_nms_top_n=30, post_nms_top_n=8, return_rois_num=True)
    r = rois.numpy()
    assert r.shape[1] == 4 and r.shape[0] <= 8
    assert int(num.numpy()[0]) == r.shape[0]
    assert (r >= 0).all() and (r <= 32).all()
    # scores sorted descending
    p = probs.numpy().reshape(-1)
    assert (np.diff(p) <= 1e-6).all()


def test_tdm_child_and_sampler():
    # tree: 0 pad; 1 root (children 2,3); 2 -> (4,5); 3 -> (6,7);
    # 4..7 leaves
    info = np.zeros((8, 5), "int32")
    info[1] = [1, 0, 0, 2, 3]
    info[2] = [2, 1, 1, 4, 5]
    info[3] = [3, 1, 1, 6, 7]
    for n in (4, 5, 6, 7):
        info[n] = [n, 2, n // 2, 0, 0]
    ch, leaf = ops.tdm_child(T([[1]], "int64"), T(info, "int32"), 2)
    assert ch.numpy().tolist() == [[[2, 3]]]
    assert leaf.numpy().tolist() == [[[0, 0]]]
    ch2, leaf2 = ops.tdm_child(T([[2]], "int64"), T(info, "int32"), 2)
    assert ch2.numpy().tolist() == [[[4, 5]]]
    assert leaf2.numpy().tolist() == [[[1, 1]]]

    travel = np.array([[0, 0], [0, 0], [0, 0], [0, 0],
                       [2, 4], [2, 5], [3, 6], [3, 7]], "int64")
    layers = [np.array([2, 3], "int64"), np.array([4, 5, 6, 7], "int64")]
    out, lab, mask = ops.tdm_sampler(T([4, 7], "int64"), travel, layers,
                                     [1, 2], [2, 4], 4, seed=3)
    o, l = out.numpy(), lab.numpy()
    assert o.shape == (2, 2 + 3)  # (pos+1neg) + (pos+2neg)
    assert l.tolist() == [[1, 0, 1, 0, 0]] * 2
    assert o[0, 0] == 2 and o[0, 2] == 4      # positives on the path
    assert o[1, 0] == 3 and o[1, 2] == 7


def test_print_and_assert_ops(capsys):
    x = T([1.0, 2.0])
    ops.print_op(x, message="dbg")
    assert "dbg" in capsys.readouterr().out
    ops.assert_op(T([True, True], "bool"))
    with pytest.raises(AssertionError):
        ops.assert_op(T([True, False], "bool"), data=[x])


def test_detection_map_metric():
    m = metric.DetectionMAP(overlap_threshold=0.5)
    # image 0: one gt, one perfect det + one far fp with lower score
    m.update(np.array([[0, 0.9, 0, 0, 10, 10],
                       [0, 0.3, 50, 50, 60, 60]], "float32"),
             np.array([[0, 0, 9, 9]], "float32"), np.array([0]))
    ap = m.accumulate()
    assert ap == pytest.approx(1.0)
    # a missed gt halves recall
    m.update(np.zeros((0, 6), "float32"),
             np.array([[0, 0, 9, 9]], "float32"), np.array([0]))
    assert 0.4 < m.accumulate() < 0.75
    m.reset()
    assert m.accumulate() == 0.0


def test_beam_search_unaccumulated_probabilities():
    """ADVICE r05: is_accumulated=False takes NORMALIZED probabilities
    (reference beam_search_op.cc applies std::log, not log_softmax).
    Hand-computed: total[b,k,v] = pre_scores[b,k] + log(probs[b,k,v])."""
    probs = np.array([[[0.7, 0.2, 0.1],
                       [0.1, 0.6, 0.3]]], "float32")      # [1, 2, 3]
    pre_ids = T([[1, 2]], "int64")
    pre_sc = T([[-1.0, -2.0]], "float32")
    ids, sc, par = ops.beam_search(pre_ids, pre_sc, T(probs),
                                   beam_size=2, end_id=0,
                                   is_accumulated=False)
    total = np.array([[-1.0 + np.log(0.7), -1.0 + np.log(0.2),
                       -1.0 + np.log(0.1)],
                      [-2.0 + np.log(0.1), -2.0 + np.log(0.6),
                       -2.0 + np.log(0.3)]], "float32").reshape(-1)
    order = np.argsort(-total)
    np.testing.assert_allclose(sc.numpy()[0], total[order[:2]], rtol=1e-5)
    assert ids.numpy()[0].tolist() == [int(o % 3) for o in order[:2]]
    assert par.numpy()[0].tolist() == [int(o // 3) for o in order[:2]]
    # the old code ran log_softmax over the probabilities — i.e. treated
    # them as LOGITS (log_softmax(0.7) != log(0.7)); the absolute-score
    # assertion above fails under that treatment
    np.testing.assert_allclose(sc.numpy()[0, 0],
                               -1.0 + np.log(0.7), rtol=1e-5)
