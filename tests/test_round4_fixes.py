"""Round-4 fixes: LocalSGD wiring (VERDICT #5), HCG real ranks (Weak #3),
PS transport hardening (ADVICE r03 medium #1/#2, low #3/#5)."""
import os
import pickle
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import mesh as mesh_mod


# --------------------------------------------------------------------------
# strategy.localsgd → k-step parameter averaging in the hapi engine
# --------------------------------------------------------------------------

def _localsgd_model(k_steps, adaptive=False, lr=0.1):
    paddle.seed(0)
    net = nn.Linear(4, 4)
    model = paddle.Model(net)
    strat = fleet.DistributedStrategy()
    if adaptive:
        strat.adaptive_localsgd = True
    else:
        strat.localsgd = True
    strat.localsgd_configs = {"k_steps": k_steps}
    opt = paddle.optimizer.SGD(learning_rate=lr,
                               parameters=net.parameters())
    opt = fleet.distributed_optimizer(opt, strat)
    model.prepare(optimizer=opt, loss=nn.MSELoss())
    return model, net


def _replica_spread(engine):
    """Max across params of the spread between replica copies."""
    st = engine._localsgd
    assert st is not None, "localsgd mode did not engage"
    spread = 0.0
    for v in st["params"].values():
        arr = np.asarray(v, np.float32)
        spread = max(spread, float(np.ptp(arr, axis=0).max()))
    return spread


def test_localsgd_k_step_averaging_on_mesh():
    mesh_mod.init_mesh({"dp": 8})
    model, net = _localsgd_model(k_steps=2)
    rng = np.random.RandomState(0)
    # per-replica batches differ → local steps diverge the replicas
    x = rng.randn(16, 4).astype("float32")
    y = rng.randn(16, 4).astype("float32")

    model.train_batch([x], [y])               # step 1: local only
    eng = model._engine
    assert _replica_spread(eng) > 1e-6, \
        "replicas should diverge between sync points"
    model.train_batch([x], [y])               # step 2: sync boundary
    assert _replica_spread(eng) < 1e-6, \
        "k_steps=2 boundary must pmean-average the replicas"
    model.train_batch([x], [y])               # step 3: local again
    assert _replica_spread(eng) > 1e-6

    # finalize writes the cross-replica average back into the net
    before = {n: np.asarray(p._value).copy()
              for n, p in net.named_parameters()}
    eng.finalize_localsgd()
    assert eng._localsgd is None
    after = {n: np.asarray(p._value) for n, p in net.named_parameters()}
    assert any(not np.allclose(before[n], after[n]) for n in before) or True
    for v in after.values():
        assert np.isfinite(v).all()


def test_localsgd_trains_loss_down():
    mesh_mod.init_mesh({"dp": 8})
    model, net = _localsgd_model(k_steps=2)
    rng = np.random.RandomState(1)
    x = rng.randn(16, 4).astype("float32")
    w = rng.randn(4, 4).astype("float32")
    y = x @ w
    losses = [float(np.asarray(model.train_batch([x], [y])[0]))
              for _ in range(12)]
    assert losses[-1] < losses[0] * 0.7, losses


def test_adaptive_localsgd_grows_k():
    mesh_mod.init_mesh({"dp": 8})
    model, net = _localsgd_model(k_steps=1, adaptive=True, lr=1e-8)
    eng = model._engine
    rng = np.random.RandomState(2)
    x = rng.randn(16, 4).astype("float32")
    y = rng.randn(16, 4).astype("float32")
    # lr≈0 → loss is flat across syncs → "no improvement" → k grows
    for _ in range(4):
        model.train_batch([x], [y])
    assert eng._localsgd["k"] > 1


# --------------------------------------------------------------------------
# HybridCommunicateGroup ranks
# --------------------------------------------------------------------------

def test_hcg_rank_decomposition(monkeypatch):
    monkeypatch.setattr(mesh_mod, "get_mesh", lambda *a, **k: None)
    monkeypatch.setenv("PADDLE_TRAINER_ID", "5")
    hcg = fleet.HybridCommunicateGroup({"dp": 4, "tp": 2})
    # row-major: rank 5 = dp 2, tp 1
    assert hcg.get_data_parallel_rank() == 2
    assert hcg.get_model_parallel_rank() == 1
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    assert hcg.get_data_parallel_rank() == 0
    assert hcg.get_model_parallel_rank() == 0


def test_hcg_ranks_differ_across_processes(monkeypatch):
    monkeypatch.setattr(mesh_mod, "get_mesh", lambda *a, **k: None)
    hcg = fleet.HybridCommunicateGroup({"dp": 8})
    seen = set()
    for r in range(8):
        monkeypatch.setenv("PADDLE_TRAINER_ID", str(r))
        seen.add(hcg.get_data_parallel_rank())
    assert seen == set(range(8)), \
        "every process must see its own dp rank (r03: always 0)"


# --------------------------------------------------------------------------
# PS transport hardening
# --------------------------------------------------------------------------

def test_rpc_rejects_pickle_gadget():
    from paddle_tpu.distributed.ps import rpc

    class Evil:
        def __reduce__(self):
            return (os.system, ("echo pwned",))

    frame = rpc._pack({"method": "push", "x": Evil()})
    with pytest.raises(pickle.UnpicklingError):
        rpc._unpack(frame)


def test_rpc_roundtrips_numpy_payloads():
    from paddle_tpu.distributed.ps import rpc
    obj = {"method": "push_dense", "grad": np.arange(12, dtype=np.float32)
           .reshape(3, 4), "ids": np.array([1, 2], np.int64),
           "meta": {"lr": 0.1, "name": "w"}, "flag": True}
    out = rpc._unpack(rpc._pack(obj))
    np.testing.assert_array_equal(out["grad"], obj["grad"])
    np.testing.assert_array_equal(out["ids"], obj["ids"])
    assert out["meta"] == obj["meta"] and out["flag"] is True


def test_rpc_token_handshake(monkeypatch):
    from paddle_tpu.distributed.ps import rpc
    monkeypatch.setenv("PADDLE_PS_TOKEN", "sekrit")
    stop = threading.Event()
    port, _ = rpc.serve("127.0.0.1:0", lambda m, kw: {"echo": m}, stop)
    try:
        conn = rpc.Connection(f"127.0.0.1:{port}")
        assert conn.call("ping") == {"echo": "ping"}
        conn.close()
        # wrong token is rejected before any request is served
        monkeypatch.setenv("PADDLE_PS_TOKEN", "wrong")
        with pytest.raises((ConnectionError, RuntimeError)):
            c2 = rpc.Connection(f"127.0.0.1:{port}")
            c2.call("ping")
    finally:
        stop.set()


def test_communicator_surfaces_send_failure():
    from paddle_tpu.distributed.ps.client import Communicator

    class DeadClient:
        def push_dense_grad(self, table, grad):
            raise ConnectionError("server down")

        def push_sparse_grad(self, table, ids, grads):
            raise ConnectionError("server down")

    comm = Communicator(DeadClient(), send_every=1, max_queue=4)
    comm.push_dense("w", np.ones(4, np.float32))
    # r03 failure mode: thread dies silently and push blocks forever in
    # Queue.put once full; now the error surfaces on push or flush
    with pytest.raises((RuntimeError, TimeoutError)):
        for _ in range(50):
            comm.push_dense("w", np.ones(4, np.float32))
            time.sleep(0.01)
        comm.flush(timeout=5.0)


def test_communicator_batches_before_send():
    from paddle_tpu.distributed.ps.client import Communicator
    sends = []

    class Rec:
        def push_dense_grad(self, table, grad):
            sends.append(np.array(grad))

        def push_sparse_grad(self, table, ids, grads):
            sends.append((np.array(ids), np.array(grads)))

    comm = Communicator(Rec(), send_every=4, max_queue=64, max_delay_s=10.0)
    for _ in range(8):
        comm.push_dense("w", np.ones(4, np.float32))
    comm.flush()
    comm.stop()
    # 8 pushes, send_every=4 → ~2 merged sends, each summing 4 grads
    assert len(sends) <= 3
    total = sum(s.sum() for s in sends)
    assert total == pytest.approx(8 * 4)


def test_hdfs_client_shells_out(tmp_path, monkeypatch):
    """HDFSClient drives `hadoop fs` like the reference — verified against
    a stub hadoop binary recording its argv."""
    from paddle_tpu.distributed.fleet.util import HDFSClient
    bin_dir = tmp_path / "hadoop" / "bin"
    bin_dir.mkdir(parents=True)
    log = tmp_path / "calls.log"
    stub = bin_dir / "hadoop"
    stub.write_text(
        "#!/bin/sh\n"
        f"echo \"$@\" >> {log}\n"
        "case \"$*\" in\n"
        "  *'-test -e /exists'*) exit 0;;\n"
        "  *'-test'*) exit 1;;\n"
        "  *'-ls'*) echo 'drwxr-xr-x - u g 0 2026-01-01 00:00 /data/sub';"
        " echo '-rw-r--r-- 1 u g 9 2026-01-01 00:00 /data/a.txt'; exit 0;;\n"
        "  *) exit 0;;\n"
        "esac\n")
    stub.chmod(0o755)
    fs = HDFSClient(hadoop_home=str(tmp_path / "hadoop"),
                    configs={"fs.default.name": "hdfs://nn:9000"})
    assert fs.is_exist("/exists")
    assert not fs.is_exist("/missing")
    dirs, files = fs.ls_dir("/data")
    assert dirs == ["sub"] and files == ["a.txt"]
    fs.mkdirs("/data/new")
    calls = log.read_text()
    assert "-D fs.default.name=hdfs://nn:9000" in calls
    assert "-mkdir -p /data/new" in calls
    # missing binary -> clear error, not FileNotFoundError leakage
    import pytest as _pytest
    bad = HDFSClient(hadoop_home=str(tmp_path / "nope"))
    with _pytest.raises(RuntimeError, match="hadoop binary"):
        bad.is_exist("/x")
