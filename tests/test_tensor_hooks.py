"""Tensor.register_hook (reference imperative/hooks.h +
varbase_patch_methods.py register_hook) — grad observation and
replacement on intermediate and leaf tensors."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_hook_observes_and_replaces_grad():
    x = paddle.to_tensor(np.array([1.0, 2.0], "float32"),
                         stop_gradient=False)
    y = x * 2.0
    seen = []
    y.register_hook(lambda g: seen.append(np.asarray(g._value))
                    or (g * 10.0))
    y.sum().backward()
    np.testing.assert_allclose(seen[0], [1.0, 1.0])
    np.testing.assert_allclose(np.asarray(x.grad._value), [20.0, 20.0])


def test_leaf_hook_and_remove():
    x = paddle.to_tensor(np.ones(3, "float32"), stop_gradient=False)
    seen = []
    h = x.register_hook(lambda g: seen.append(1))
    (x * 3.0).sum().backward()
    assert seen == [1]
    h.remove()
    x.clear_gradient()
    (x * 3.0).sum().backward()
    assert seen == [1]          # removed hook does not fire again


def test_observer_hook_keeps_grad():
    x = paddle.to_tensor(np.ones(2, "float32"), stop_gradient=False)
    y = x * 5.0
    y.register_hook(lambda g: None)     # pure observer
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), [5.0, 5.0])


def test_hook_on_stop_gradient_raises():
    x = paddle.to_tensor(np.ones(2, "float32"))
    with pytest.raises(RuntimeError):
        x.register_hook(lambda g: g)


def test_multiple_hooks_chain_in_order():
    x = paddle.to_tensor(np.ones(2, "float32"), stop_gradient=False)
    y = x * 1.0
    y.register_hook(lambda g: g + 1.0)
    y.register_hook(lambda g: g * 2.0)
    y.sum().backward()
    # (1 + 1) * 2 = 4
    np.testing.assert_allclose(np.asarray(x.grad._value), [4.0, 4.0])
