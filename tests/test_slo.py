"""core/slo.py: the shared estimators, histogram merge, burn-rate
engine, and anomaly detectors behind the cluster telemetry plane."""
import numpy as np
import pytest

from paddle_tpu.core import slo
from paddle_tpu.core.monitor import _Hist


# --------------------------------------------------------------------------
# percentile: the ONE estimator every tool's p50/p99 goes through
# --------------------------------------------------------------------------

def test_percentile_matches_numpy():
    rng = np.random.RandomState(3)
    xs = list(rng.uniform(0, 100, 257))
    for p in (0, 25, 50, 90, 99, 100):
        assert slo.percentile(xs, p) == pytest.approx(
            float(np.percentile(np.asarray(xs), p)))


def test_percentile_edge_cases():
    assert slo.percentile([], 99) is None
    assert slo.percentile([7.0], 50) == 7.0
    assert slo.percentile([1, 2, 3, 4], 50, ndigits=3) == 2.5
    # ndigits pins tool output bytes
    assert slo.percentile([1.23456, 2.34567], 99, ndigits=3) == round(
        float(np.percentile([1.23456, 2.34567], 99)), 3)


# --------------------------------------------------------------------------
# bucketed estimators
# --------------------------------------------------------------------------

def _hist(values, bounds=(1.0, 10.0, 100.0)):
    h = _Hist(bounds)
    for v in values:
        h.observe(v)
    return h.summary()


def test_good_count_aligns_threshold_down():
    s = _hist([0.5, 5.0, 50.0, 500.0])
    # threshold exactly on a bound: everything in <=10 buckets is good
    assert slo.good_count(s, 10.0) == (2, 4)
    # threshold INSIDE the (10, 100] bucket aligns DOWN: the straddling
    # bucket's observations count as bad (conservative, never optimistic)
    assert slo.good_count(s, 60.0) == (2, 4)
    assert slo.good_count(s, 100.0) == (3, 4)


def test_good_count_without_buckets_uses_max():
    assert slo.good_count({"count": 3, "max": 8.0}, 10.0) == (3, 3)
    assert slo.good_count({"count": 3, "max": 80.0}, 10.0) == (0, 3)
    assert slo.good_count({}, 10.0) == (0, 0)


def test_hist_quantile_interpolates_and_clamps():
    s = _hist([0.5] * 50 + [5.0] * 50)
    q50 = slo.hist_quantile(s, 50)
    assert 0.5 <= q50 <= 1.0
    # p100 clamps to the exact max, not a bucket bound
    assert slo.hist_quantile(s, 100) == 5.0
    assert slo.hist_quantile({"count": 0}, 50) is None
    # a degraded merge (no buckets) is honest: no quantiles
    assert slo.hist_quantile({"count": 5, "sum": 1.0, "min": 0.1,
                              "max": 0.5, "bounds": None,
                              "buckets": None}, 50) is None


# --------------------------------------------------------------------------
# merge: per-process histograms fold into the union stream's histogram
# --------------------------------------------------------------------------

def test_merge_hists_equals_union_stream():
    rng = np.random.RandomState(7)
    a = list(rng.uniform(0, 120, 100))
    b = list(rng.uniform(0, 120, 57))
    merged = slo.merge_hists([_hist(a), _hist(b)])
    union = _hist(a + b)
    assert merged["buckets"] == union["buckets"]
    assert merged["bounds"] == union["bounds"]
    assert merged["count"] == union["count"] == 157
    assert merged["sum"] == pytest.approx(union["sum"])
    assert merged["min"] == union["min"]
    assert merged["max"] == union["max"]


def test_merge_hists_mixed_bounds_degrades_honestly():
    a = _hist([1.0, 20.0], bounds=(1.0, 10.0, 100.0))
    b = _hist([2.0, 30.0], bounds=(5.0, 50.0))
    m = slo.merge_hists([a, b])
    assert m["bounds"] is None and m["buckets"] is None
    assert m["count"] == 4
    assert m["min"] == 1.0 and m["max"] == 30.0
    assert m["avg"] == pytest.approx((1 + 20 + 2 + 30) / 4)
    # empty input
    z = slo.merge_hists([])
    assert z["count"] == 0 and z["bounds"] is None


# --------------------------------------------------------------------------
# burn-rate engine
# --------------------------------------------------------------------------

def _lat_summary(good, bad, threshold=100.0):
    return {"count": good + bad, "sum": 0.0, "min": 0.0, "max": 1.0,
            "bounds": [threshold], "buckets": [good, bad]}


def test_latency_slo_breach_and_hysteretic_clear():
    spec = slo.SLOSpec("lat", "latency", "m", objective=0.05,
                       threshold_ms=100.0)
    eng = slo.SLOEngine([spec], fast_s=10.0, slow_s=60.0)
    t0 = 1000.0
    assert eng.observe({}, {"m": _lat_summary(0, 0)}, now=t0) == []
    # sustained 50% bad vs a 5% objective: burn 10x in every window
    alerts = eng.observe({}, {"m": _lat_summary(50, 50)}, now=t0 + 5)
    assert [a["slo"] for a in alerts] == ["lat"]
    assert alerts[0]["type"] == "slo_breach"
    assert alerts[0]["burn"]["fast"] >= 1.0
    assert alerts[0]["burn"]["slow"] >= 1.0
    assert eng.active() == ["lat"]
    # still burning: active, but NOT a duplicate alert
    assert eng.observe({}, {"m": _lat_summary(50, 60)}, now=t0 + 6) == []
    assert eng.active() == ["lat"]
    # recovery: a flood of good observations drops the fast burn under
    # threshold -> hysteretic clear
    assert eng.observe({}, {"m": _lat_summary(2000, 60)},
                       now=t0 + 20) == []
    assert eng.active() == []


def test_single_spike_cannot_page():
    # one bad request in a sea of good traffic never crosses a 5% budget
    spec = slo.SLOSpec("lat", "latency", "m", objective=0.05,
                       threshold_ms=100.0)
    eng = slo.SLOEngine([spec], fast_s=10.0, slow_s=60.0)
    eng.observe({}, {"m": _lat_summary(0, 0)}, now=0.0)
    assert eng.observe({}, {"m": _lat_summary(99, 1)}, now=5.0) == []
    assert eng.active() == []


def test_rate_slo_per_second_budget():
    spec = slo.SLOSpec("errs", "rate", "err_count", objective=2.0)
    eng = slo.SLOEngine([spec], fast_s=10.0, slow_s=60.0)
    eng.observe({"err_count": 0.0}, {}, now=0.0)
    # 100 errors in 10s = 10/s against a 2/s budget: burn 5x
    alerts = eng.observe({"err_count": 100.0}, {}, now=10.0)
    assert [a["slo"] for a in alerts] == ["errs"]
    # quiet period: clears
    eng.observe({"err_count": 100.0}, {}, now=25.0)
    assert eng.active() == []


def test_rate_slo_with_denominator():
    spec = slo.SLOSpec("bad_frac", "rate", "bad", objective=0.01,
                       denominator="total")
    eng = slo.SLOEngine([spec], fast_s=10.0, slow_s=60.0)
    eng.observe({"bad": 0.0, "total": 0.0}, {}, now=0.0)
    alerts = eng.observe({"bad": 5.0, "total": 100.0}, {}, now=5.0)
    assert [a["slo"] for a in alerts] == ["bad_frac"]    # 5% vs 1%
    # no new bad events -> no breach even while the ratio history stands
    eng2 = slo.SLOEngine([spec], fast_s=10.0, slow_s=60.0)
    eng2.observe({"bad": 0.0, "total": 0.0}, {}, now=0.0)
    assert eng2.observe({"bad": 0.0, "total": 100.0}, {}, now=5.0) == []


def test_slospec_validation():
    with pytest.raises(ValueError):
        slo.SLOSpec("x", "latency", "m", objective=0.1)  # no threshold
    with pytest.raises(ValueError):
        slo.SLOSpec("x", "weird", "m", objective=0.1)
    d = slo.SLOSpec("x", "rate", "m", objective=0.1).to_dict()
    assert d["name"] == "x" and d["kind"] == "rate"


# --------------------------------------------------------------------------
# anomaly detectors
# --------------------------------------------------------------------------

def test_rolling_median_detector_warmup_spike_and_level_change():
    det = slo.RollingMedianDetector(window=16, k=3.0, min_samples=8)
    # warm-up: even huge values train the baseline without paging
    for v in (50.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0):
        assert det.observe(v) is False
    assert det.anomalies == 0
    # in-family observation
    assert det.observe(1.1) is False
    # a straggler 10x the median pages
    assert det.observe(10.0) is True
    assert det.anomalies == 1
    # a sustained shift stops being anomalous once the median catches up
    flags = [det.observe(10.0) for _ in range(20)]
    assert flags[-1] is False
    assert det.median() == pytest.approx(10.0)


def test_latency_skew():
    skew, worst = slo.latency_skew({"s0": 1.0, "s1": 1.0, "s2": 3.0})
    assert worst == "s2" and skew == pytest.approx(3.0)
    assert slo.latency_skew({"s0": 2.0}) is None
    assert slo.latency_skew({"s0": None, "s1": 2.0}) is None
    assert slo.latency_skew({"s0": 0.0, "s1": 0.0}) is None
