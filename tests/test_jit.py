"""paddle.jit tests: to_static compilation + save/load export roundtrip
(reference test_jit_save_load.py territory)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn
from paddle_tpu.hapi.model import InputSpec


def test_to_static_layer_matches_eager():
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net.eval()
    x = paddle.randn([3, 4])
    eager = net(x).numpy()
    snet = jit.to_static(net)
    out = snet(x)
    np.testing.assert_allclose(out.numpy(), eager, rtol=1e-5)
    # second call hits the jit cache
    out2 = snet(x)
    np.testing.assert_allclose(out2.numpy(), eager, rtol=1e-5)


def test_to_static_function_decorator():
    @jit.to_static
    def f(a, b):
        return paddle.ops.exp(a) + b

    a = paddle.randn([4])
    b = paddle.randn([4])
    np.testing.assert_allclose(f(a, b).numpy(),
                               np.exp(a.numpy()) + b.numpy(), rtol=1e-5)


def test_jit_save_load_roundtrip(tmp_path):
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net.eval()
    path = str(tmp_path / "exported" / "model")
    jit.save(net, path, input_spec=[InputSpec([None, 4], "float32", "x")])

    loaded = jit.load(path)
    x = np.random.rand(1, 4).astype("float32")
    want = net(paddle.to_tensor(x)).numpy()
    got = loaded(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_jit_save_load_conv_model(tmp_path):
    from paddle_tpu.vision.models import LeNet
    net = LeNet()
    net.eval()
    path = str(tmp_path / "lenet")
    jit.save(net, path, input_spec=[InputSpec([1, 1, 28, 28], "float32", "img")])
    loaded = jit.load(path)
    x = np.random.rand(1, 1, 28, 28).astype("float32")
    np.testing.assert_allclose(loaded(paddle.to_tensor(x)).numpy(),
                               net(paddle.to_tensor(x)).numpy(),
                               rtol=1e-4, atol=1e-5)
