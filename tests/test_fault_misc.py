"""Fault-tolerance satellites around the PS chaos suite (ISSUE 2):
supervisor-side heartbeat robustness, checkpoint-manager lifecycle, and
the SIGTERM PreemptionGuard grace-save contract."""
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------- heartbeat

def _write(path, payload):
    with open(path, "w") as f:
        f.write(payload)


def test_heartbeat_check_tolerates_corruption(tmp_path):
    """The supervisor must outlive everything else: corrupt, partial,
    schema-less, or mid-delete beat files mark that rank STALE instead of
    raising out of the watch loop."""
    from paddle_tpu.distributed.elastic import Heartbeat
    d = str(tmp_path)
    now = time.time()
    _write(os.path.join(d, "heartbeat_0.json"),
           json.dumps({"rank": 0, "step": 5, "time": now}))        # fresh
    _write(os.path.join(d, "heartbeat_1.json"),
           json.dumps({"rank": 1, "step": 5, "time": now - 999}))  # stale
    _write(os.path.join(d, "heartbeat_2.json"), "{corrupt json!!")  # bad
    _write(os.path.join(d, "heartbeat_3.json.tmp"), "{partial")    # tmp
    _write(os.path.join(d, "heartbeat_4.json"),
           json.dumps({"rank": 4, "step": 5}))               # no "time"
    _write(os.path.join(d, "heartbeat_5.json"),
           json.dumps({"rank": 5, "time": "not-a-number"}))  # bad type
    stale = Heartbeat.check(d, timeout_s=60.0)
    # 0 alive; 3 is an uncommitted atomic-write twin, not a rank
    assert stale == [1, 2, 4, 5]


def test_heartbeat_check_survives_missing_directory(tmp_path):
    from paddle_tpu.distributed.elastic import Heartbeat
    assert Heartbeat.check(str(tmp_path / "never_made")) == []


def test_heartbeat_update_then_check_roundtrip(tmp_path):
    from paddle_tpu.distributed.elastic import Heartbeat
    hb = Heartbeat(str(tmp_path), rank=7, interval_s=60.0)
    hb.update(step=3)
    assert Heartbeat.check(str(tmp_path), timeout_s=60.0) == []


# ------------------------------------------- checkpoint manager leak

def test_train_epoch_range_closes_manager(tmp_path, monkeypatch):
    from paddle_tpu.incubate import checkpoint as ck
    closed = []
    orig_close = ck.TrainingCheckpoint.close
    monkeypatch.setattr(
        ck.TrainingCheckpoint, "close",
        lambda self: (closed.append(1), orig_close(self))[1])

    d1 = str(tmp_path / "full")
    assert list(ck.train_epoch_range(2, directory=d1)) == [0, 1]
    assert len(closed) == 1, "exhausted generator must close its manager"

    # abandoned mid-loop (break → GeneratorExit) closes too
    gen = ck.train_epoch_range(5, directory=str(tmp_path / "part"))
    next(gen)
    gen.close()
    assert len(closed) == 2, "abandoned generator must close its manager"


# -------------------------------------------------- preemption guard

GUARD_CHILD = textwrap.dedent("""
    import os, sys, time
    import jax; jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from paddle_tpu.incubate.checkpoint import (TrainingCheckpoint,
                                                PreemptionGuard)
    d = sys.argv[1]
    # save_interval huge: the ONLY way a checkpoint lands is the guard's
    # grace save at SIGTERM time
    ck = TrainingCheckpoint(d, keep=2, save_interval_steps=10**9,
                            async_save=False)
    state = {"step": 0}

    def capture():
        s = state["step"]
        return s, {"w": np.full((4,), s, np.float32),
                   "counters": {"epoch": 0, "step": s, "global_step": s}}

    with PreemptionGuard(ck, capture):
        print("ready", flush=True)
        for step in range(1, 10 ** 6):
            state["step"] = step
            time.sleep(0.02)
    raise SystemExit("unreachable: child must die by SIGTERM")
""")


def test_preemption_guard_grace_checkpoint(tmp_path):
    """SIGTERM a training loop: the grace checkpoint lands, the process
    dies BY SIGTERM as its wait status (so launchers see the truth), and
    a restore resumes from the exact captured step."""
    d = os.path.join(str(tmp_path), "guard_ckpt")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, "-c", GUARD_CHILD, d], env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "ready"
        time.sleep(0.5)                       # let a few steps tick
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    # died BY SIGTERM (grace handler re-raises the default disposition)
    assert proc.returncode == -signal.SIGTERM, (
        proc.returncode, proc.stderr.read()[-2000:])

    from paddle_tpu.incubate.checkpoint import TrainingCheckpoint
    ck = TrainingCheckpoint(d, save_interval_steps=10 ** 9,
                            async_save=False)
    try:
        latest = ck.latest_step()
        assert latest is not None and latest >= 1, \
            "grace checkpoint never landed"
        st = ck.restore()
        # checkpoint is internally consistent with ITS step label — the
        # exact step the signal interrupted, not a stale periodic save
        assert int(st["counters"]["global_step"]) == latest
        np.testing.assert_array_equal(
            st["w"], np.full((4,), latest, np.float32))
    finally:
        ck.close()


def test_preemption_guard_restore_into_resumes_exact_step(tmp_path):
    """restore_into() on a model picks the training loop back up at the
    grace-saved step (counters round-trip through capture/restore)."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.incubate.checkpoint import (PreemptionGuard,
                                                TrainingCheckpoint)

    def build():
        paddle.seed(7)
        net = nn.Sequential(nn.Linear(4, 1))
        model = paddle.Model(net)
        model.prepare(
            optimizer=optimizer.SGD(learning_rate=0.1,
                                    parameters=net.parameters()),
            loss=nn.MSELoss())
        return model

    d = os.path.join(str(tmp_path), "resume_ckpt")
    model = build()
    ck = TrainingCheckpoint(d, async_save=False)
    step_at_signal = 17

    def capture():
        return step_at_signal, ck.capture(model, epoch=2,
                                          step=step_at_signal,
                                          global_step=step_at_signal)

    # in-process SIGTERM with a chained no-op handler: the guard must
    # grace-save, then defer to the previous (callable) handler instead
    # of killing the test process
    fired = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: fired.append(s))
    try:
        with PreemptionGuard(ck, capture) as guard:
            os.kill(os.getpid(), signal.SIGTERM)
        assert guard.fired and fired == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)

    model2 = build()
    counters = ck.restore_into(model2)
    assert {k: int(v) for k, v in counters.items()} == {
        "epoch": 2, "step": step_at_signal,
        "global_step": step_at_signal}
    ck.close()
