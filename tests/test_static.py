"""Static graph tests (reference: static-mode halves of test_layers.py and
book tests like test_recognize_digits.py static path)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, static


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_static_forward_linear():
    main = static.Program("main")
    with static.program_guard(main):
        x = static.data("x", [-1, 4], "float32")
        net = nn.Linear(4, 3)
        y = net(x)
        assert isinstance(y, static.Variable)
    exe = static.Executor()
    exe.run(static.default_startup_program())
    xv = np.random.rand(5, 4).astype("float32")
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    assert out.shape == (5, 3)
    w = static.global_scope().get(net.weight.scope_name)
    np.testing.assert_allclose(out, xv @ np.asarray(w)
                               + np.asarray(static.global_scope().get(net.bias.scope_name)),
                               rtol=1e-5)


def test_program_to_string_lists_ops():
    main = static.Program("m")
    with static.program_guard(main):
        x = static.data("x", [2, 2])
        y = paddle.ops.exp(x) + 1.0
    s = str(main)
    assert "exp" in s and "data" in s


def test_static_training_converges():
    main = static.Program("train")
    with static.program_guard(main):
        x = static.data("x", [-1, 3], "float32")
        label = static.data("y", [-1, 1], "float32")
        net = nn.Linear(3, 1, bias_attr=False)
        pred = net(x)
        loss = paddle.ops.mse_loss(pred, label)
        opt = optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)

    exe = static.Executor()
    exe.run(static.default_startup_program())
    rng = np.random.RandomState(0)
    X = rng.rand(64, 3).astype("float32")
    W = np.array([[1.0], [2.0], [3.0]], dtype="float32")
    Y = X @ W
    losses = []
    for _ in range(200):
        (lv,) = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.01, losses[-1]
    w = np.asarray(static.global_scope().get(net.weight.scope_name))
    np.testing.assert_allclose(w, W, atol=0.2)


def test_append_backward_grads_fetchable():
    main = static.Program("bwd")
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        net = nn.Linear(2, 1, bias_attr=False)
        loss = paddle.ops.mean(net(x))
        pairs = static.append_backward(loss)
        assert len(pairs) == 1
    exe = static.Executor()
    xv = np.ones((2, 2), "float32")
    (g,) = exe.run(main, feed={"x": xv}, fetch_list=[pairs[0][1]])
    # d mean(x@w) / dw = mean over batch of x = ones * batch avg
    np.testing.assert_allclose(g, np.full((2, 1), 1.0), rtol=1e-5)


def test_static_batchnorm_state_persists():
    main = static.Program("bn")
    with static.program_guard(main):
        x = static.data("x", [8, 4], "float32")
        bn = nn.BatchNorm1D(4, momentum=0.5)
        out = bn(x)
    exe = static.Executor()
    xv = np.random.RandomState(0).rand(8, 4).astype("float32") + 5.0
    exe.run(main, feed={"x": xv}, fetch_list=[out])
    m1 = np.asarray(static.global_scope().get(bn._mean.scope_name))
    exe.run(main, feed={"x": xv}, fetch_list=[out])
    m2 = np.asarray(static.global_scope().get(bn._mean.scope_name))
    assert not np.allclose(m1, 0.0)
    assert not np.allclose(m1, m2)  # running stats advanced across runs


def test_executor_program_cache():
    main = static.Program("cache")
    with static.program_guard(main):
        x = static.data("x", [4, 4], "float32")
        y = paddle.ops.exp(x)
    exe = static.Executor()
    xv = np.zeros((4, 4), "float32")
    exe.run(main, feed={"x": xv}, fetch_list=[y])
    n = len(exe._cache)
    exe.run(main, feed={"x": xv}, fetch_list=[y])
    assert len(exe._cache) == n  # second run hits the compiled cache


def test_static_save_load(tmp_path):
    main = static.Program("sv")
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        net = nn.Linear(2, 2)
        y = net(x)
    path = str(tmp_path / "model")
    static.save(main, path)
    old = np.asarray(static.global_scope().get(net.weight.scope_name))
    static.global_scope().set(net.weight.scope_name, np.zeros((2, 2), "float32"))
    static.load(main, path)
    now = np.asarray(static.global_scope().get(net.weight.scope_name))
    np.testing.assert_allclose(now, old)


def test_static_nn_fc():
    main = static.Program("fc")
    with static.program_guard(main):
        x = static.data("x", [3, 5], "float32")
        y = static.nn.fc(x, size=7, activation="relu")
    exe = static.Executor()
    (out,) = exe.run(main, feed={"x": np.random.rand(3, 5).astype("float32")},
                     fetch_list=[y])
    assert out.shape == (3, 7)
    assert (out >= 0).all()


def test_data_parallel_compiled_program():
    # CompiledProgram.with_data_parallel shards the batch over the dp mesh
    from paddle_tpu.distributed import mesh as mesh_mod
    import jax
    mesh_mod.init_mesh({"dp": len(jax.devices())})
    try:
        main = static.Program("dp")
        with static.program_guard(main):
            x = static.data("x", [-1, 4], "float32")
            net = nn.Linear(4, 2)
            loss = paddle.ops.mean(net(x))
            optimizer.SGD(learning_rate=0.01).minimize(loss)
        cp = static.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        exe = static.Executor()
        xv = np.random.rand(16, 4).astype("float32")
        (l1,) = exe.run(cp, feed={"x": xv}, fetch_list=[loss])
        (l2,) = exe.run(cp, feed={"x": xv}, fetch_list=[loss])
        assert l2 < l1
    finally:
        mesh_mod.reset_mesh()  # don't leak the dp mesh into other tests
