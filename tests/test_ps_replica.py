"""Chaos suite for the replicated PS storage tier (ISSUE 7).

PR 2 proved the TRANSPORT exactly-once under injected faults; this suite
proves the STORAGE survives a permanent server death. Contract under
test (distributed/ps/{shard_map,replica}.py):

- the default shard map reproduces legacy modulo routing bit-for-bit;
- a primary forwards every mutation to its backups under the client's
  replay id, so promotion + client retry keeps exactly-once;
- a stale-epoch client gets a clean ShardMapStale redirect (one round
  trip, never cached in the replay cache) and re-routes;
- heartbeat loss promotes the first live backup, bumps the epoch, and
  clients transparently re-route (ConnectRefused fails over, not dies);
- a restarted server rejoins via snapshot + replay-keyed delta log;
- THE acceptance proof: training on a 3-server/1-backup cluster with
  one primary killed PERMANENTLY mid-run under seeded RESET/DROP chaos
  ends bitwise-equal to the fault-free run, with >=1 recorded promotion
  and zero double-applies (table.applied exact).
"""
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core import monitor
from paddle_tpu.distributed.ps import (ConnectRefused, PSClient, PSServer,
                                       ShardMap, rpc)
from paddle_tpu.testing import faults

pytestmark = pytest.mark.chaos

DIM = 4

# tight-but-safe chaos timings (see test_ps_faults.FAST) + a failover
# window that outlasts the test heartbeat deadline below
FAST = dict(timeout=5.0, max_retries=2, backoff_base=0.01,
            backoff_max=0.05, connect_retry_s=5.0)
HB = dict(heartbeat_s=0.1, heartbeat_timeout_s=0.7)


def _specs(optimizer="sgd", lr=1.0):
    return {"emb": {"type": "sparse", "dim": DIM, "optimizer": optimizer,
                    "lr": lr, "init": "zeros"},
            "dense0": {"type": "dense", "shape": (3, DIM),
                       "optimizer": "sgd", "lr": 0.1, "init": "zeros"}}


def _cluster(n=3, k=1, specs=None, **hb):
    """n replicated in-process servers on ephemeral ports sharing one
    chained shard map (shard i: primary i, backups the next k)."""
    servers = [PSServer("127.0.0.1:0", specs or _specs())
               for _ in range(n)]
    eps = [s.start() for s in servers]
    smap = ShardMap.create(eps, n_backups=k)
    opts = {**HB, **hb}
    for s in servers:
        s.enable_replication(shard_map=smap, peers=eps, n_backups=k,
                             rpc_opts=dict(FAST), **opts)
    return servers, eps


def _teardown(servers, *clients):
    for c in clients:
        try:
            c.close()
        except Exception:
            pass
    for s in servers:
        s.shutdown()


@pytest.fixture(autouse=True)
def _no_leftover_injector():
    yield
    faults.uninstall()


def _delta(before, name):
    return monitor.stat_get(name) - before.get(name, 0)


# ------------------------------------------------------------- shard map

def test_default_map_matches_legacy_modulo_routing():
    eps = ["h:1", "h:2", "h:3"]
    m = ShardMap.default(eps)
    assert m.epoch == 0 and m.n_shards == 3
    import zlib
    for i in range(12):
        assert m.primary(m.shard_of_id(i)) == eps[i % 3]
        assert m.backups(m.shard_of_id(i)) == []
    assert m.shard_of_name("w") == zlib.crc32(b"w") % 3


def test_map_promote_evict_attach_epochs():
    eps = ["h:1", "h:2", "h:3"]
    m = ShardMap.create(eps, n_backups=1)
    assert m.backups(0) == ["h:2"] and m.backups(2) == ["h:1"]
    m2 = m.without("h:1")
    assert m2.epoch == m.epoch + 1
    assert m2.primary(0) == "h:2" and m2.backups(0) == []
    assert m2.backups(2) == []          # h:1 dropped as backup too
    assert "h:1" not in m2.servers
    assert sorted(m2.under_replicated(1)) == [0, 2]
    m3 = m2.with_backup(0, "h:4")
    assert m3.epoch == m2.epoch + 1
    assert m3.backups(0) == ["h:4"] and "h:4" in m3.servers
    # round-trips through the plain-dict wire form
    assert ShardMap.from_dict(m3.to_dict()) == m3


# ----------------------------------------------------------- replication

def test_push_forwards_to_backup_exactly_once():
    servers, eps = _cluster()
    client = PSClient(eps, **FAST)
    try:
        ids = np.array([0, 3], np.int64)          # shard 0 -> primary 0
        client.pull_sparse("emb", ids)
        before = monitor.stats("ps.replica.")
        client.push_sparse_grad("emb", ids, np.ones((2, DIM), np.float32))
        # applied on the primary AND on its backup (server 1), once each
        assert servers[0].table("emb").applied == 1
        assert servers[1].table("emb").applied == 1
        assert _delta(before, "ps.replica.forwards") >= 1
        np.testing.assert_array_equal(
            servers[1].table("emb").pull(ids),
            -np.ones((2, DIM), np.float32))
    finally:
        _teardown(servers, client)


def test_forward_rides_transport_faults_exactly_once():
    """DROP on the forward's reply: the backup applied, the primary's
    forward retry must replay — not double-apply on the backup."""
    servers, eps = _cluster()
    client = PSClient(eps, **FAST)
    try:
        ids = np.array([0], np.int64)
        client.pull_sparse("emb", ids)
        with faults.inject(faults.Fault("server", "reply", faults.DROP,
                                        method="push_sparse_grad")) as inj:
            # the FIRST push_sparse_grad reply in the stream is the
            # backup's reply to the primary's forward (the forward runs
            # inside the primary's handler, before its own reply)
            client.push_sparse_grad("emb", ids,
                                    np.ones((1, DIM), np.float32))
        assert inj.fired(faults.DROP) == 1
        assert servers[0].table("emb").applied == 1
        assert servers[1].table("emb").applied == 1
        np.testing.assert_array_equal(
            servers[1].table("emb").pull(ids),
            -np.ones((1, DIM), np.float32))
    finally:
        _teardown(servers, client)


def test_stale_epoch_client_redirect_roundtrip():
    servers, eps = _cluster()
    client = PSClient(eps, **FAST)
    try:
        ids = np.array([0], np.int64)
        client.pull_sparse("emb", ids)
        # bump the cluster's map behind the client's back: swap shard
        # 0's primary and backup, epoch+1
        old = servers[0].replica.shard_map
        d = old.to_dict()
        s0 = d["shards"][0]
        s0["primary"], s0["backups"] = s0["backups"][0], [s0["primary"]]
        d["epoch"] = old.epoch + 1
        for s in servers:
            s.replica.install(d)
        before = monitor.stats("ps.replica.")
        applied0 = [s.table("emb").applied for s in servers]
        client.push_sparse_grad("emb", ids, np.ones((1, DIM), np.float32))
        # the client was redirected once, adopted the new map, and the
        # push applied exactly once on the NEW primary (old backup)
        assert _delta(before, "ps.replica.stale_maps") >= 1
        assert client.shard_map.epoch == old.epoch + 1
        assert servers[1].table("emb").applied == applied0[1] + 1
        # forwarded back to the demoted server (now the backup)
        assert servers[0].table("emb").applied == applied0[0] + 1
    finally:
        _teardown(servers, client)


# -------------------------------------------------------------- failover

def test_promotion_under_concurrent_pushes_keeps_exactly_once():
    """Kill a primary while 4 threads push to its shard: every acked
    push applies exactly once (table.applied exact, values exact)."""
    servers, eps = _cluster()
    client = PSClient(eps, **FAST)
    n_threads, n_pushes = 4, 30
    ids = np.array([0], np.int64)                 # shard 0
    client.pull_sparse("emb", ids)
    errors = []
    acked = [0] * n_threads

    def pusher(w):
        c = PSClient(eps, **FAST)
        try:
            for _ in range(n_pushes):
                c.push_sparse_grad("emb", ids,
                                   np.ones((1, DIM), np.float32))
                acked[w] += 1
                time.sleep(0.02)
        except Exception as e:  # noqa: BLE001 — asserted below
            errors.append(e)
        finally:
            c.close()

    threads = [threading.Thread(target=pusher, args=(w,))
               for w in range(n_threads)]
    try:
        before = monitor.stats("ps.replica.")
        for t in threads:
            t.start()
        time.sleep(0.15)
        servers[0].shutdown()                     # permanent kill
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert all(a == n_pushes for a in acked)
        assert _delta(before, "ps.replica.promotions") >= 1
        # the promoted backup holds EXACTLY sum(acked) applications
        total = n_threads * n_pushes
        assert servers[1].table("emb").applied == total
        np.testing.assert_array_equal(
            servers[1].table("emb").pull(ids),
            -float(total) * np.ones((1, DIM), np.float32))
    finally:
        _teardown(servers, client)


def test_ping_reports_per_server_health_with_dead_endpoint():
    servers, eps = _cluster(n=2)
    client = PSClient(eps, **FAST)
    try:
        assert all(isinstance(x, float) for x in client.ping())
        servers[1].shutdown()
        health = client.ping()                    # must NOT raise
        assert isinstance(health[0], float)
        assert health[1] is None
    finally:
        _teardown(servers, client)


def test_partition_fault_refuses_dial():
    """PARTITION: connect-refused at dial time, distinct from RESET
    mid-call — dead servers are scriptable without killing processes."""
    srv = PSServer(tables=_specs())
    ep = srv.start()
    try:
        with faults.inject(faults.Fault("client", "dial", faults.PARTITION,
                                        method=ep, times=99)) as inj:
            with pytest.raises(ConnectRefused):
                rpc.Connection(ep, connect_retry_s=1.0)
        assert inj.fired(faults.PARTITION) == 1
        # rule spent/uninstalled: the endpoint dials fine again
        c = rpc.Connection(ep, connect_retry_s=2.0)
        c.close()
    finally:
        srv.shutdown()


def test_double_failure_promotes_live_backup_not_corpse():
    """k=2: shard 0's primary AND first backup die together; the
    surviving second backup must converge on a map whose shard-0
    primary is ALIVE (itself) — never a corpse — and keep taking
    writes."""
    servers, eps = _cluster(n=3, k=2)
    client = PSClient(eps, **FAST)
    try:
        ids = np.array([0], np.int64)             # shard 0
        client.pull_sparse("emb", ids)
        client.push_sparse_grad("emb", ids, np.ones((1, DIM), np.float32))
        assert servers[2].table("emb").applied == 1   # k=2: everyone got it
        servers[0].shutdown()
        servers[1].shutdown()
        deadline = time.monotonic() + 10
        m = servers[2].replica.shard_map
        while time.monotonic() < deadline and (
                eps[0] in m.servers or eps[1] in m.servers):
            time.sleep(0.05)
            m = servers[2].replica.shard_map
        assert eps[0] not in m.servers and eps[1] not in m.servers
        assert m.primary(0) == eps[2]
        client.push_sparse_grad("emb", ids, np.ones((1, DIM), np.float32))
        assert servers[2].table("emb").applied == 2
        np.testing.assert_array_equal(
            servers[2].table("emb").pull(ids),
            -2.0 * np.ones((1, DIM), np.float32))
    finally:
        _teardown(servers, client)


def test_quorum_failure_keeps_rid_retryable_exactly_once():
    """PADDLE_PS_REPLICA_QUORUM=2 with a dead backup: the push fails
    WITHOUT poisoning its replay id (the error is never cached). After
    a replacement backup catches up, the retry under the SAME
    request_key succeeds forward-only: the primary never re-applies,
    and the backup — whose snapshot already covers the mutation —
    replays the forward instead of applying it twice."""
    from paddle_tpu.core.flags import set_flags
    servers, eps = _cluster(n=2, k=1)
    client = PSClient(eps, **FAST)
    set_flags({"PADDLE_PS_REPLICA_QUORUM": 2})
    restarted = None
    try:
        ids = np.array([0], np.int64)       # shard 0: primary 0, backup 1
        client.pull_sparse("emb", ids)
        servers[1].shutdown()               # backup dies -> quorum 1/2
        with pytest.raises(RuntimeError, match="quorum not met"):
            client.push_sparse_grad("emb", ids,
                                    np.ones((1, DIM), np.float32),
                                    request_key="push-q")
        assert servers[0].table("emb").applied == 1   # applied locally once
        # an empty replacement joins and catches up (snapshot includes
        # the half-durable push + its rid)
        restarted = PSServer("127.0.0.1:0", _specs())
        restarted.start()
        restarted.enable_replication(peers=[servers[0].endpoint],
                                     n_backups=1, rpc_opts=dict(FAST),
                                     **HB)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and restarted.endpoint \
                not in servers[0].replica.shard_map.servers:
            time.sleep(0.05)
        assert restarted.endpoint in servers[0].replica.shard_map.servers
        # retry of the SAME logical call: quorum now met, exactly-once
        client.push_sparse_grad("emb", ids,
                                np.ones((1, DIM), np.float32),
                                request_key="push-q")
        assert servers[0].table("emb").applied == 1   # no second apply
        assert restarted.table("emb").applied == 0    # forward replayed
        np.testing.assert_array_equal(
            restarted.table("emb").pull(ids),
            servers[0].table("emb").pull(ids))
    finally:
        set_flags({"PADDLE_PS_REPLICA_QUORUM": 0})
        if restarted is not None:
            restarted.shutdown()
        _teardown(servers, client)


# ------------------------------------------------------ rejoin/catch-up

def test_rejoin_catches_up_snapshot_plus_deltas():
    servers, eps = _cluster()
    client = PSClient(eps, **FAST)
    fresh = None
    try:
        ids = np.array([0, 3, 6], np.int64)       # shard 0
        client.pull_sparse("emb", ids)
        client.push_sparse_grad("emb", ids, np.ones((3, DIM), np.float32))
        # kill shard 0's primary; its backup (server 1) promotes
        servers[0].shutdown()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                eps[0] in servers[1].replica.shard_map.servers:
            time.sleep(0.05)
        assert eps[0] not in servers[1].replica.shard_map.servers
        # keep training against the promoted primary
        client.push_sparse_grad("emb", ids, np.ones((3, DIM), np.float32))
        before = monitor.stats("ps.replica.")
        # a REPLACEMENT server joins with empty tables + just peer
        # endpoints: bootstrap -> fetch snapshot -> attach -> deltas
        fresh = PSServer("127.0.0.1:0", _specs())
        fresh.start()
        live = [s.endpoint for s in servers[1:]]
        fresh.enable_replication(peers=live, n_backups=1,
                                 rpc_opts=dict(FAST), **HB)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and \
                fresh.endpoint not in servers[1].replica.shard_map.servers:
            time.sleep(0.05)
        m = servers[1].replica.shard_map
        assert fresh.endpoint in m.servers
        assert _delta(before, "ps.replica.catchups") >= 1
        # the rejoined backup's shard-0 rows are bitwise the primary's
        np.testing.assert_array_equal(
            fresh.table("emb").pull(ids),
            servers[1].table("emb").pull(ids))
        # and a NEW push forwards to it
        client.push_sparse_grad("emb", ids, np.ones((3, DIM), np.float32))
        np.testing.assert_array_equal(
            fresh.table("emb").pull(ids),
            servers[1].table("emb").pull(ids))
    finally:
        if fresh is not None:
            fresh.shutdown()
        _teardown(servers, client)


# ---------------------------------------- THE acceptance chaos training

N_STEPS = 24
KILL_STEP = 11
VOCAB = 60


def _train_steps(client, start, stop):
    """Deterministic 2-table loop; grads depend on PULLED state, so any
    lost or double-applied update poisons every later step."""
    for step in range(start, stop):
        rng = np.random.RandomState(1000 + step)
        ids = rng.randint(0, VOCAB, size=10).astype(np.int64)
        rows = client.pull_sparse("emb", ids)
        grads = rows * 0.05 + rng.randn(len(ids), DIM).astype(np.float32)
        client.push_sparse_grad("emb", ids, grads)
        dense = client.pull_dense("dense0")
        client.push_dense_grad(
            "dense0", dense * 0.05 + rng.randn(3, DIM).astype(np.float32))


def _final_state(client):
    all_ids = np.arange(VOCAB, dtype=np.int64)
    return (client.pull_sparse("emb", all_ids).copy(),
            client.pull_dense("dense0").copy())


def _expected_applied(eps, dead_idx=None):
    """EXACT per-server table.applied expectation: replay the
    deterministic push schedule against the replica-membership timeline
    (chained map: shard s -> primary eps[s], backup eps[s+1]; after
    KILL_STEP the dead server leaves every chain). A single lost OR
    double-applied mutation anywhere breaks the equality."""
    import zlib
    n = len(eps)
    d = zlib.crc32(b"dense0") % n
    emb = {ep: 0 for ep in eps}
    dense = {ep: 0 for ep in eps}
    for step in range(N_STEPS):
        rng = np.random.RandomState(1000 + step)
        ids = rng.randint(0, VOCAB, size=10).astype(np.int64)
        shards = {int(i) % n for i in ids}
        killed = dead_idx is not None and step >= KILL_STEP
        for s in range(n):
            members = [eps[s], eps[(s + 1) % n]]
            if killed:
                members = [m for m in members if m != eps[dead_idx]]
            for m in members:
                if s in shards:
                    emb[m] += 1
                if s == d:
                    dense[m] += 1
    return emb, dense


def test_chaos_storage_kill_primary_bitwise_equals_fault_free():
    """THE proof: 3-server/1-backup training where shard 0's primary is
    killed PERMANENTLY mid-run (never restarted) under seeded RESET+DROP
    chaos must end bitwise-equal to the fault-free run, with >=1
    promotion and zero double-applies."""
    specs = _specs("adagrad", lr=0.1)

    # ---- fault-free reference run on an identical replicated cluster
    ref_servers, ref_eps = _cluster(specs=specs)
    ref_client = PSClient(ref_eps, **FAST)
    _train_steps(ref_client, 0, N_STEPS)
    ref_sparse, ref_dense = _final_state(ref_client)
    # counter-exact sanity on the fault-free cluster first
    exp_emb, exp_dense = _expected_applied(ref_eps)
    for s in ref_servers:
        assert s.table("emb").applied == exp_emb[s.endpoint]
        assert s.table("dense0").applied == exp_dense[s.endpoint]
    _teardown(ref_servers, ref_client)

    # ---- chaos run: seeded resets + lost replies + a permanent kill
    servers, eps = _cluster(specs=specs)
    client = PSClient(eps, **FAST)
    before = monitor.stats("ps.replica.")
    rpc_before = monitor.stats("ps.rpc.")
    try:
        with faults.inject(seed=11, p={faults.RESET: 0.02,
                                       faults.DROP: 0.02}) as inj:
            _train_steps(client, 0, KILL_STEP)
            servers[0].shutdown()        # permanent: NEVER restarted
            _train_steps(client, KILL_STEP, N_STEPS)
        got_sparse, got_dense = _final_state(client)

        # the chaos actually happened and the tier reported it
        assert inj.fired(faults.RESET) >= 1, "seed injected no resets"
        assert inj.fired(faults.DROP) >= 1, "seed injected no drops"
        assert _delta(rpc_before, "ps.rpc.retries") >= 1
        assert _delta(before, "ps.replica.promotions") >= 1
        assert _delta(before, "ps.replica.forwards") >= 1
        assert client.shard_map.epoch > 0
        assert eps[0] not in client.shard_map.servers

        # ...and not one gradient was lost or double-counted
        np.testing.assert_array_equal(got_sparse, ref_sparse)
        np.testing.assert_array_equal(got_dense, ref_dense)

        # zero double-applies: every LIVE server's counters match the
        # deterministic schedule replayed against the membership
        # timeline, exactly
        exp_emb, exp_dense = _expected_applied(eps, dead_idx=0)
        for s in servers[1:]:
            assert s.table("emb").applied == exp_emb[s.endpoint]
            assert s.table("dense0").applied == exp_dense[s.endpoint]
    finally:
        _teardown(servers, client)
