"""End-to-end Model.fit tests — the 'book tests' analog
(reference python/paddle/fluid/tests/book/test_recognize_digits.py:
small model trained a few iterations, loss must drop, save/load roundtrip).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import Model, nn, optimizer
from paddle_tpu.hapi.callbacks import EarlyStopping, History
from paddle_tpu.io import DataLoader, TensorDataset
from paddle_tpu.metric import Accuracy
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def small_mnist(n=512, mode="train"):
    ds = MNIST(mode=mode)
    from paddle_tpu.io import Subset
    return Subset(ds, range(n))


def test_model_fit_mnist_lenet():
    paddle.seed(1)
    model = Model(LeNet())
    model.prepare(
        optimizer=optimizer.Adam(learning_rate=0.001,
                                 parameters=model.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=Accuracy())
    hist = History()
    train = small_mnist(512)
    model.fit(train, batch_size=64, epochs=2, verbose=0, callbacks=[hist],
              shuffle=True, drop_last=True)
    losses = hist.history["loss"]
    assert losses[-1] < losses[0], f"loss did not drop: {losses}"
    logs = model.evaluate(small_mnist(256, "test"), batch_size=64, verbose=0)
    assert logs["acc"] > 0.3  # synthetic digits are very separable
    assert logs["loss"] < 2.5


def test_model_save_load_roundtrip(tmp_path):
    paddle.seed(2)
    model = Model(LeNet())
    model.prepare(optimizer=optimizer.Adam(parameters=model.parameters()),
                  loss=nn.CrossEntropyLoss(), metrics=Accuracy())
    train = small_mnist(128)
    model.fit(train, batch_size=64, epochs=1, verbose=0)
    path = str(tmp_path / "ckpt" / "model")
    model.save(path)

    model2 = Model(LeNet())
    model2.prepare(optimizer=optimizer.Adam(parameters=model2.parameters()),
                   loss=nn.CrossEntropyLoss(), metrics=Accuracy())
    model2.load(path)
    x = paddle.randn([4, 1, 28, 28])
    np.testing.assert_allclose(model.predict_batch([x])[0],
                               model2.predict_batch([x])[0], rtol=1e-5,
                               atol=1e-6)
    assert model2._optimizer._step_count == model._optimizer._step_count


def test_model_predict_stack():
    model = Model(LeNet())
    model.prepare(loss=None)
    ds = small_mnist(32, "test")
    outs = model.predict(ds, batch_size=16, stack_outputs=True)
    assert outs[0].shape == (32, 10)


def test_early_stopping_stops():
    paddle.seed(3)
    model = Model(nn.Sequential(nn.Flatten(), nn.Linear(784, 10)))
    model.prepare(optimizer=optimizer.SGD(learning_rate=0.0,
                                          parameters=model.parameters()),
                  loss=nn.CrossEntropyLoss())
    es = EarlyStopping(monitor="loss", patience=1, verbose=0)
    model.fit(small_mnist(64), batch_size=32, epochs=10, verbose=0,
              callbacks=[es])
    assert model.stop_training  # lr=0 -> no improvement -> stops early


def test_dataloader_shapes_and_order():
    X = np.arange(20, dtype="float32").reshape(10, 2)
    y = np.arange(10, dtype="int64")
    ds = TensorDataset([X, y])
    dl = DataLoader(ds, batch_size=4, shuffle=False)
    batches = list(dl)
    assert len(batches) == 3
    xb, yb = batches[0]
    assert xb.shape == (4, 2)
    np.testing.assert_array_equal(yb, [0, 1, 2, 3])
    dl = DataLoader(ds, batch_size=4, drop_last=True)
    assert len(list(dl)) == 2


def test_dataloader_num_workers():
    X = np.random.rand(64, 3).astype("float32")
    ds = TensorDataset([X])
    dl = DataLoader(ds, batch_size=8, num_workers=2, shuffle=False)
    got = np.concatenate([b[0] for b in dl])
    np.testing.assert_allclose(got, X)


def test_metrics_accuracy():
    from paddle_tpu.metric import Accuracy
    m = Accuracy(topk=(1, 2))
    pred = paddle.to_tensor(np.array([[0.9, 0.05, 0.05],
                                      [0.1, 0.8, 0.1],
                                      [0.3, 0.4, 0.3]], dtype="float32"))
    label = paddle.to_tensor(np.array([[0], [0], [2]]))
    correct = m.compute(pred, label)
    m.update(correct)
    top1, top2 = m.accumulate()
    assert abs(top1 - 1 / 3) < 1e-6
    assert abs(top2 - 2 / 3) < 1e-6


def test_model_summary(capsys):
    model = Model(LeNet())
    info = model.summary()
    assert info["total_params"] == 61610


def test_summary_and_flops():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    info = paddle.summary(net, (1, 8))
    assert info["total_params"] == 8 * 16 + 16 + 16 * 4 + 4
    assert info["trainable_params"] == info["total_params"]
    f = paddle.flops(net, (1, 8))
    # two matmuls dominate: 2*(8*16) + 2*(16*4) flops per sample
    assert f >= 2 * 8 * 16 + 2 * 16 * 4
    assert f < 10000


def test_flops_leaves_net_usable_and_modes_intact():
    """Regression: flops() traces through the layer — afterwards the real
    params must be reseated (no leaked tracers) and per-sublayer
    train/eval flags preserved."""
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8), nn.Linear(8, 2))
    net.train()
    net[1].eval()  # deliberately frozen BN
    paddle.flops(net, (2, 4))
    assert net.training and not net[1].training  # modes preserved
    out = net(paddle.to_tensor(np.ones((2, 4), "float32")))  # no tracers
    assert np.isfinite(np.asarray(out._value)).all()
    # multi-input and InputSpec forms
    from paddle_tpu.hapi.model import InputSpec
    info = paddle.summary(net, InputSpec([None, 4], "float32"))
    assert info["total_params"] > 0
    m = Model(net)
    info2 = m.summary((2, 4))
    assert info2["total_params"] == info["total_params"]


def test_model_engine_mode_independent():
    """The one-engine design delta (reference dual adapters): Model works
    identically with enable_static() flipped on around the training loop
    (fit/evaluate included — the guard lives in the engine), records NO
    ops into the default Program, and a net BUILT under static mode gets
    a clear error."""
    import paddle_tpu as paddle
    from paddle_tpu import io, nn, optimizer, static
    from paddle_tpu.distributed import mesh as mesh_mod

    prev_mesh = mesh_mod.get_mesh()
    mesh_mod.reset_mesh()  # isolate from suites that leave a dp mesh
    net = nn.Linear(4, 2)
    m = paddle.Model(net)
    m.prepare(optimizer.SGD(learning_rate=0.1,
                            parameters=net.parameters()),
              nn.CrossEntropyLoss())
    x = np.random.RandomState(0).rand(8, 4).astype("float32")
    y = np.random.RandomState(1).randint(0, 2, (8,)).astype("int64")
    base = m.train_batch([x], [y])
    assert np.isfinite(base[0])
    paddle.enable_static()
    try:
        n_ops_before = len(static.default_main_program().ops)
        again = m.train_batch([x], [y])
        assert np.isfinite(again[0])

        class _DS(io.Dataset):
            def __getitem__(self, i):
                return x[i % 8], y[i % 8]

            def __len__(self):
                return 8

        m.fit(_DS(), batch_size=4, epochs=1, verbose=0)   # engine path
        m.evaluate(_DS(), batch_size=4, verbose=0)
        # the engine must not have appended ops to the static Program
        assert len(static.default_main_program().ops) == n_ops_before
        with pytest.raises(TypeError, match="enable_static"):
            paddle.Model(nn.Linear(4, 2))
    finally:
        paddle.disable_static()
        if prev_mesh is not None:
            mesh_mod.set_mesh(prev_mesh)
