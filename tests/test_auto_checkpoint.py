"""Preemption-safe checkpointing (VERDICT r02 item 7; reference
fluid/incubate/checkpoint/auto_checkpoint.py:71).

The contract under test: SIGKILL mid-training, resume from the latest
committed checkpoint, and the continued loss trajectory is bit-identical
to an uninterrupted run — params, optimizer slots, LR state, rng chain and
data position all restored.
"""
import os
import signal
import subprocess
import sys
import textwrap

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.hapi.callbacks import Callback

STEPS_PER_EPOCH = 4
EPOCHS = 3


class LossTrace(Callback):
    def __init__(self):
        self.losses = []

    def on_train_batch_end(self, step, logs=None):
        self.losses.append(float(logs["loss"]))


def _build():
    paddle.seed(123)
    np.random.seed(123)
    X = np.random.rand(32, 8).astype("float32")
    Y = (X @ np.random.rand(8, 1).astype("float32"))
    net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    model = paddle.Model(net)
    model.prepare(optimizer=optimizer.Adam(learning_rate=0.05,
                                           parameters=net.parameters()),
                  loss=nn.MSELoss())
    from paddle_tpu.io import TensorDataset
    return model, TensorDataset([X, Y])


def _fit(model, ds, ckpt_dir, callbacks, epochs=EPOCHS):
    model.fit(ds, batch_size=8, epochs=epochs, verbose=0, shuffle=False,
              callbacks=callbacks, auto_checkpoint_dir=ckpt_dir,
              auto_checkpoint_freq=2, keep_checkpoint_max=2)


CHILD = textwrap.dedent("""
    import os, signal
    import jax; jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import test_auto_checkpoint as T
    import paddle_tpu as paddle

    class Killer(T.LossTrace):
        def on_train_batch_end(self, step, logs=None):
            super().on_train_batch_end(step, logs)
            if len(self.losses) == 6:      # mid-epoch-2 (global step 6)
                os.kill(os.getpid(), signal.SIGKILL)

    model, ds = T._build()
    T._fit(model, ds, {ckpt_dir!r}, [Killer()])
    raise SystemExit("unreachable: child must have been SIGKILLed")
""")


def test_kill_and_resume_bit_identical(tmp_path):
    ckpt_dir = os.path.join(str(tmp_path), "ckpt")

    # uninterrupted reference trajectory (no checkpointing side effects)
    model, ds = _build()
    ref = LossTrace()
    model.fit(ds, batch_size=8, epochs=EPOCHS, verbose=0, shuffle=False,
              callbacks=[ref])
    assert len(ref.losses) == STEPS_PER_EPOCH * EPOCHS

    # child trains with auto-checkpoint and SIGKILLs itself mid-epoch 2
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH="/root/repo/tests:/root/repo")
    proc = subprocess.run(
        [sys.executable, "-c", CHILD.format(ckpt_dir=ckpt_dir)],
        env=env, cwd="/root/repo", capture_output=True, text=True,
        timeout=600)
    assert proc.returncode == -signal.SIGKILL, (proc.returncode,
                                                proc.stderr[-2000:])

    # a committed checkpoint exists despite the hard kill
    from paddle_tpu.incubate.checkpoint import TrainingCheckpoint
    latest = TrainingCheckpoint(ckpt_dir).latest_step()
    assert latest is not None and 1 <= latest <= 6

    # resume: must continue the reference trajectory exactly
    model2, ds2 = _build()
    tr = LossTrace()
    _fit(model2, ds2, ckpt_dir, [tr])
    want = ref.losses[latest:]
    assert len(tr.losses) == len(want), (latest, len(tr.losses), len(want))
    np.testing.assert_allclose(tr.losses, want, rtol=1e-6)


def test_training_checkpoint_roundtrip(tmp_path):
    from paddle_tpu.incubate.checkpoint import TrainingCheckpoint
    ck = TrainingCheckpoint(os.path.join(str(tmp_path), "c"), keep=2,
                            async_save=False)
    for s in (1, 2, 3):
        ck.save(s, {"w": np.full((4,), s, "float32"), "step": s})
    ck.wait()
    assert ck.latest_step() == 3
    st = ck.restore()
    assert int(st["step"]) == 3
    np.testing.assert_array_equal(st["w"], np.full((4,), 3, "float32"))
    assert ck.restore(1) is None  # GC'd by keep-latest-k


def test_train_epoch_range_resumes(tmp_path):
    from paddle_tpu.incubate.checkpoint import train_epoch_range
    d = os.path.join(str(tmp_path), "er")
    seen = []
    for e in train_epoch_range(5, directory=d):
        seen.append(e)
        if e == 2:
            break  # crash DURING epoch 2: it never commits, so it re-runs
    seen2 = list(train_epoch_range(5, directory=d))
    assert seen == [0, 1, 2] and seen2 == [2, 3, 4]
