"""Shape/dtype inference + memory estimator (ISSUE 1 tentpole).

The rule library must agree with the record-time jax.eval_shape ground
truth on representative programs (matmul/conv/reduce/concat/elementwise/
control-flow), flag a deliberately mis-shaped matmul and an AMP
fp16/fp32 boundary mismatch at build time, and feed a sane liveness
peak-memory estimate for a small MLP."""
import copy

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops
from paddle_tpu.ops._dispatch import SHAPE_INFER_REGISTRY
from paddle_tpu.static.program import _Ref
from paddle_tpu.static.shape_infer import (ShapeInferError, analyze_memory,
                                           infer_program)


def _static():
    import paddle_tpu.static as static
    paddle.enable_static()
    return static


def test_rule_library_covers_at_least_25_ops():
    assert len(SHAPE_INFER_REGISTRY) >= 25, sorted(SHAPE_INFER_REGISTRY)
    for must in ("matmul", "conv2d", "concat", "sum", "mean", "add",
                 "reshape", "transpose", "softmax", "embedding"):
        assert must in SHAPE_INFER_REGISTRY


def test_rules_agree_with_recorded_avals_on_representative_program():
    """check=True cross-validates every rule against the record-time
    eval_shape ground truth — any rule/kernel disagreement raises."""
    static = _static()
    try:
        main = static.Program("rep")
        with static.program_guard(main):
            x = static.data("x", [4, 8], "float32")
            w = static.data("w", [8, 3], "float32")
            ids = static.data("ids", [4], "int64")
            emb = static.data("emb", [16, 8], "float32")
            h = ops.matmul(x, w)                       # [4, 3]
            h = ops.add(h, ops.full([3], 1.0))         # broadcast
            s = ops.softmax(h, axis=-1)
            r = ops.sum(s, axis=1, keepdim=True)       # [4, 1]
            m = ops.mean(h)                            # []
            c = ops.concat([h, h], axis=1)             # [4, 6]
            t = ops.transpose(c, [1, 0])               # [6, 4]
            f = ops.reshape(t, [-1])                   # [24]
            e = ops.embedding(emb, ids)                # [4, 8]
            oh = ops.one_hot(ids, 5)                   # [4, 5]
            cast = ops.cast(r, "int32")
            img = static.data("img", [2, 3, 8, 8], "float32")
            ker = static.data("ker", [4, 3, 3, 3], "float32")
            conv = ops.conv2d(img, ker, stride=1, padding=1)  # [2,4,8,8]
            relu = ops.relu(conv)
        env = infer_program(main, check=True)
        by = {v.var_id: v for op in main.ops for v in op.out_vars}
        assert tuple(env[h.var_id].shape) == (4, 3)
        assert tuple(env[c.var_id].shape) == (4, 6)
        assert tuple(env[f.var_id].shape) == (24,)
        assert tuple(env[e.var_id].shape) == (4, 8)
        assert tuple(env[oh.var_id].shape) == (4, 5)
        assert tuple(env[conv.var_id].shape) == (2, 4, 8, 8)
        assert env[cast.var_id].dtype == np.dtype("int32")
    finally:
        paddle.disable_static()


def test_control_flow_and_fallback_ops_infer_via_eval_shape():
    static = _static()
    try:
        main = static.Program("cf")
        with static.program_guard(main):
            x = static.data("x", [4], "float32")
            i = ops.zeros([], "int32")
            n = ops.full([], 3, "int32")
            _, acc = static.nn.while_loop(
                lambda i, a: ops.less_than(i, n),
                lambda i, a: (i + 1, a * 2.0), [i, x])
            y = ops.roll(acc, 1)   # no explicit rule -> eval_shape path
        env = infer_program(main, check=True)
        assert tuple(env[acc.var_id].shape) == (4,)
        assert tuple(env[y.var_id].shape) == (4,)
    finally:
        paddle.disable_static()


def test_misshaped_matmul_flagged_at_build_time():
    """A transpiler-style rewrite that rewires matmul's rhs to a
    wrong-shaped var must fail inference with a named contraction
    diagnostic — not an XLA trace error at Executor.run."""
    static = _static()
    try:
        main = static.Program("bad_mm")
        with static.program_guard(main):
            x = static.data("x", [4, 8], "float32")
            w = static.data("w", [8, 3], "float32")
            out = ops.matmul(x, w)
        broken = copy.copy(main)
        mm = copy.copy(main.ops[0])
        x_ref = mm.flat[0]
        assert isinstance(x_ref, _Ref)
        mm.flat = [x_ref, copy.copy(x_ref)] + list(mm.flat[2:])  # x @ x
        broken.ops = [mm]
        with pytest.raises(ShapeInferError, match="contraction") as e:
            infer_program(broken)
        assert e.value.op_name == "matmul"
    finally:
        paddle.disable_static()


def test_recorded_aval_drift_detected():
    static = _static()
    try:
        main = static.Program("drift")
        with static.program_guard(main):
            x = static.data("x", [2, 3], "float32")
            y = ops.exp(x)
        broken = copy.copy(main)
        op = copy.copy(main.ops[0])
        import jax
        op.out_vars = [copy.copy(op.out_vars[0])]
        op.out_vars[0].aval = jax.ShapeDtypeStruct((7, 7), jnp.float32)
        broken.ops = [op]
        with pytest.raises(ShapeInferError, match="records shape"):
            infer_program(broken)
    finally:
        paddle.disable_static()


def test_amp_boundary_mismatch_flagged():
    """AMP O1/fp16: a gray-list op mixing fp16 and fp32 floats promotes
    silently — infer_program reports it at build time."""
    static = _static()
    try:
        main = static.Program("ampb")
        with static.program_guard(main):
            a = static.data("a", [4, 4], "float16")
            b = static.data("b", [4, 4], "float32")
            out = ops.add(a, b)   # gray zone: runs "in whatever arrives"
        main.amp_level = "O1"
        main.amp_dtype = jnp.float16
        with pytest.raises(ShapeInferError, match="AMP boundary") as e:
            infer_program(main)
        assert "add" in str(e.value)
        # amp_check=False: shapes still validate, boundary scan skipped
        env = infer_program(main, amp_check=False)
        assert tuple(env[out.var_id].shape) == (4, 4)
    finally:
        paddle.disable_static()


def test_amp_white_op_casts_cleanly():
    """White-list ops are cast wholesale by the executor's AMP policy —
    the same cast simulated in inference, so no violation and fp16
    output dtypes."""
    static = _static()
    try:
        main = static.Program("ampw")
        with static.program_guard(main):
            x = static.data("x", [4, 8], "float32")
            w = static.data("w", [8, 3], "float32")
            out = ops.matmul(x, w)
        main.amp_level = "O1"
        main.amp_dtype = jnp.float16
        env = infer_program(main)   # no boundary violation
        assert env[out.var_id].dtype == np.dtype("float16")
    finally:
        paddle.disable_static()


def test_memory_estimator_on_mlp():
    static = _static()
    try:
        main = static.Program("mlp")
        with static.program_guard(main):
            x = static.data("x", [32, 64], "float32")
            w1 = static.data("w1", [64, 128], "float32")
            w2 = static.data("w2", [128, 10], "float32")
            h = ops.relu(ops.matmul(x, w1))
            out = ops.softmax(ops.matmul(h, w2))
        main._jit_fetch_vars = [out]
        est = analyze_memory(main)
        feed = (32 * 64 + 64 * 128 + 128 * 10) * 4
        assert est["feed_bytes"] == feed
        assert est["param_bytes"] == 0
        assert len(est["timeline"]) == len(main.ops)
        # peak: feeds + the largest live activation set; h ([32,128]) and
        # its matmul predecessor coexist, out is pinned to the end
        assert est["activation_peak_bytes"] >= 32 * 128 * 4
        assert est["peak_bytes"] <= feed + 4 * (
            32 * 128 * 2 + 32 * 10 * 2)
        assert est["peak_bytes"] == feed + est["activation_peak_bytes"]
    finally:
        paddle.disable_static()


def test_executor_publishes_memory_estimate_under_flag():
    from paddle_tpu.core import flags as flags_mod
    from paddle_tpu.core import monitor
    static = _static()
    try:
        main = static.Program("est")
        with static.program_guard(main):
            x = static.data("x", [4, 4], "float32")
            out = ops.relu(x)
        exe = static.Executor()
        monitor.reset("executor/estimated_peak_bytes")
        flags_mod.set_flags({"FLAGS_log_memory_estimate": True})
        try:
            got = exe.run(main, feed={"x": np.ones((4, 4), "float32")},
                          fetch_list=[out])[0]
        finally:
            flags_mod.set_flags({"FLAGS_log_memory_estimate": False})
        np.testing.assert_allclose(got, np.ones((4, 4)))
        assert monitor.stat_get("executor/estimated_peak_bytes") > 0
    finally:
        paddle.disable_static()
