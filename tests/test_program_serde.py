"""Versioned model serialization (VERDICT r04 item 4).

Reference analogs: framework/framework.proto:186 (op version map),
framework/save_load_util.cc (versioned headers). The format is JSON+npz
with ops referenced by registry name + version — no pickled qualnames, so
internal module renames cannot break saved models."""
import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn, ops
from paddle_tpu.framework.program_serde import (FORMAT_VERSION,
                                                OpVersionError,
                                                load_program)


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 8)
        self.fc2 = nn.Linear(8, 2)

    def forward(self, x):
        return self.fc2(ops.relu(self.fc1(x)))


def _save(net, tmp, name="m"):
    path = os.path.join(tmp, name)
    jit.save(net, path, input_spec=[jit.InputSpec([2, 4], "float32", "x")])
    return path


def test_pdmodel_is_json_schema_without_qualnames():
    paddle.seed(0)
    net = SmallNet()
    net.eval()
    with tempfile.TemporaryDirectory() as tmp:
        path = _save(net, tmp)
        raw = open(path + ".pdmodel", "rb").read()
        doc = json.loads(raw)  # JSON, not pickle
        assert doc["format_version"] == FORMAT_VERSION
        assert doc["op_versions"]  # version map recorded
        # nothing in the document resolves by module path: a rename of
        # paddle_tpu internals cannot invalidate the artifact
        assert b"paddle_tpu.ops" not in raw
        assert b"__module__" not in raw
        assert os.path.exists(path + ".pdmodel.npz")


def test_save_load_numeric_roundtrip():
    paddle.seed(0)
    net = SmallNet()
    net.eval()
    x = np.random.RandomState(0).randn(2, 4).astype("float32")
    want = net(paddle.to_tensor(x)).numpy()
    with tempfile.TemporaryDirectory() as tmp:
        path = _save(net, tmp)
        loaded = jit.load(path)
        np.testing.assert_allclose(loaded(paddle.to_tensor(x)).numpy(),
                                   want, rtol=1e-5, atol=1e-6)


def test_fresh_process_load_after_module_rename_simulation():
    """The 'rename an internal module' criterion: the loader process
    imports paddle_tpu with an alias shim in place of a renamed module
    path; since the artifact stores registry names only, it loads."""
    paddle.seed(0)
    net = SmallNet()
    net.eval()
    x = np.random.RandomState(1).randn(2, 4).astype("float32")
    want = net(paddle.to_tensor(x)).numpy()
    with tempfile.TemporaryDirectory() as tmp:
        path = _save(net, tmp)
        np.save(os.path.join(tmp, "x.npy"), x)
        np.save(os.path.join(tmp, "want.npy"), want)
        code = f"""
import sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import numpy as np
import paddle_tpu as paddle
# simulate an internal refactor: the activation module moves; loading
# must not care because ops resolve via OP_REGISTRY, not module paths
import paddle_tpu.ops.activation as act
sys.modules["paddle_tpu.ops.activation_renamed"] = act
del sys.modules["paddle_tpu.ops.activation"]
from paddle_tpu import jit
loaded = jit.load({path!r})
x = np.load({os.path.join(tmp, "x.npy")!r})
want = np.load({os.path.join(tmp, "want.npy")!r})
got = loaded(paddle.to_tensor(x)).numpy()
np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
print("RENAMED-LOAD-OK")
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")  # env must be set before the
        # interpreter starts: the axon sitecustomize registers the TPU
        # plugin at startup and would hang on a dead tunnel
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True, timeout=300)
        assert "RENAMED-LOAD-OK" in r.stdout, r.stdout + r.stderr


def test_op_version_gate():
    paddle.seed(0)
    net = SmallNet()
    net.eval()
    with tempfile.TemporaryDirectory() as tmp:
        path = _save(net, tmp)
        doc = json.load(open(path + ".pdmodel"))
        # simulate an artifact produced by a FUTURE framework whose matmul
        # op was bumped to version 99
        bumped = False
        for op in doc["ops"]:
            if op["fn"].get("__opreg__") == "matmul":
                op["fn"]["version"] = 99
                bumped = True
        assert bumped
        doc["op_versions"]["matmul"] = 99
        json.dump(doc, open(path + ".pdmodel", "w"))
        with pytest.raises(OpVersionError, match="version 99"):
            load_program(path)

        # a future FORMAT version is refused outright
        doc["format_version"] = FORMAT_VERSION + 1
        json.dump(doc, open(path + ".pdmodel", "w"))
        with pytest.raises(OpVersionError, match="format_version"):
            load_program(path)


def test_control_flow_program_serializes_structurally():
    from paddle_tpu.jit.dy2static import convert_layer

    class CondNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if h.mean() > 0:
                h = ops.relu(h)
            else:
                h = h * 0.5
            i = 0
            while i < 2:
                h = h + 0.25
                i += 1
            return h

    paddle.seed(0)
    net = CondNet()
    net.eval()
    xs = [np.random.RandomState(0).randn(2, 4).astype("float32"),
          -np.abs(np.random.RandomState(1).randn(2, 4)).astype("float32")]
    want = [net(paddle.to_tensor(x)).numpy() for x in xs]
    with tempfile.TemporaryDirectory() as tmp:
        path = _save(net, tmp, "cond")
        doc = json.load(open(path + ".pdmodel"))
        kinds = {next(iter(op["fn"])) for op in doc["ops"]}
        assert "__cond__" in kinds or "__while__" in kinds
        loaded = jit.load(path)
        for x, w in zip(xs, want):
            np.testing.assert_allclose(loaded(paddle.to_tensor(x)).numpy(),
                                       w, rtol=1e-5, atol=1e-6)


def test_legacy_pickle_still_loads():
    import pickle
    paddle.seed(0)
    net = SmallNet()
    net.eval()
    x = np.random.RandomState(2).randn(2, 4).astype("float32")
    want = net(paddle.to_tensor(x)).numpy()
    with tempfile.TemporaryDirectory() as tmp:
        path = _save(net, tmp)
        loaded_prog, feeds = load_program(path)
        # rewrite as a legacy pickle artifact and load through jit.load
        with open(path + ".pdmodel", "wb") as f:
            pickle.dump({"program": loaded_prog, "feed_names": feeds}, f)
        os.remove(path + ".pdmodel.npz")
        loaded = jit.load(path)
        np.testing.assert_allclose(loaded(paddle.to_tensor(x)).numpy(),
                                   want, rtol=1e-5, atol=1e-6)


class HashNet(nn.Layer):
    def forward(self, x):
        return ops.hash_bucket(x, num_hash=2, mod_by=97)


def test_hash_bucket_v2_version_gate():
    """ADVICE r05: hash_bucket v2 fixed the negative-bucket wraparound;
    artifacts record the bumped version so a v1 framework refuses them
    (and this build accepts old v1 artifacts, whose semantics it
    supersedes compatibly for non-wrapping ids)."""
    net = HashNet()
    net.eval()
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "h")
        jit.save(net, path, input_spec=[jit.InputSpec([2, 1], "int64", "x")])
        doc = json.load(open(path + ".pdmodel"))
        assert doc["op_versions"]["hash_bucket"] == 2
        # an artifact from a FUTURE v3 framework is refused
        for op in doc["ops"]:
            if op["fn"].get("__opreg__") == "hash_bucket":
                op["fn"]["version"] = 3
        doc["op_versions"]["hash_bucket"] = 3
        json.dump(doc, open(path + ".pdmodel", "w"))
        with pytest.raises(OpVersionError, match="hash_bucket.*version 3"):
            load_program(path)
        # an OLD v1 artifact still loads (forward compatibility)
        for op in doc["ops"]:
            if op["fn"].get("__opreg__") == "hash_bucket":
                op["fn"]["version"] = 1
        doc["op_versions"]["hash_bucket"] = 1
        json.dump(doc, open(path + ".pdmodel", "w"))
        prog, feeds = load_program(path)
        assert any(op.name == "hash_bucket" for op in prog.ops)
