"""End-to-end 'book test' workloads (reference
python/paddle/fluid/tests/book/: small models trained a few iterations,
loss must drop): word2vec with SPARSE embedding grads, and a huge-vocab
sharded embedding over the mesh — the TPU-native foundation for the
deferred PS stack (SURVEY hard part 5)."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer
from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.distributed import mesh as mesh_mod


def test_word2vec_book_sparse_grads():
    """Skip-gram word2vec (reference book/test_word2vec_book.py) trained
    eagerly with embedding(sparse=True): the table's grads stay
    SelectedRows end-to-end and the loss drops."""
    paddle.seed(0)
    rng = np.random.RandomState(0)
    V, D, N = 500, 16, 256
    # synthetic corpus: word i co-occurs with (i +/- 1) mod V
    centers = rng.randint(0, V, N)
    contexts = (centers + rng.choice([-1, 1], N)) % V

    emb_in = nn.Embedding(V, D, sparse=True)
    emb_out = nn.Embedding(V, D, sparse=True)
    opt = optimizer.Adam(learning_rate=0.05, lazy_mode=True,
                         parameters=list(emb_in.parameters())
                         + list(emb_out.parameters()))
    losses = []
    saw_sparse = False
    for lo in range(0, N, 64):
        c = paddle.to_tensor(centers[lo:lo + 64].astype("int64"))
        t = paddle.to_tensor(contexts[lo:lo + 64].astype("int64"))
        neg = paddle.to_tensor(
            rng.randint(0, V, 64 * 4).reshape(64, 4).astype("int64"))
        vc = emb_in(c)                                   # [b, D]
        vt = emb_out(t)                                  # [b, D]
        vn = emb_out(neg)                                # [b, 4, D]
        pos = ops.sum(vc * vt, axis=-1)
        negs = ops.sum(vn * ops.unsqueeze(vc, [1]), axis=-1)
        loss = (ops.mean(ops.softplus(-pos))
                + ops.mean(ops.softplus(negs)))
        loss.backward()
        if emb_in.weight.grad is not None:
            saw_sparse = saw_sparse or isinstance(
                emb_in.weight.grad._value, SelectedRows)
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert saw_sparse, "sparse embedding grads never materialized"

    # like-for-like convergence: the pos-only objective before vs after
    # several more epochs of training (same loss form on both sides)
    def pos_loss():
        from paddle_tpu.core import tape as _tape
        vals = []
        with _tape.no_grad():
            for lo in range(0, N, 64):
                c = paddle.to_tensor(centers[lo:lo + 64].astype("int64"))
                t = paddle.to_tensor(contexts[lo:lo + 64].astype("int64"))
                vals.append(float(ops.mean(ops.softplus(
                    -ops.sum(emb_in(c) * emb_out(t), axis=-1))).numpy()))
        return float(np.mean(vals))

    before = pos_loss()
    for _ in range(4):
        for lo in range(0, N, 64):
            c = paddle.to_tensor(centers[lo:lo + 64].astype("int64"))
            t = paddle.to_tensor(contexts[lo:lo + 64].astype("int64"))
            vc, vt = emb_in(c), emb_out(t)
            loss = ops.mean(ops.softplus(-ops.sum(vc * vt, axis=-1)))
            loss.backward()
            opt.step()
            opt.clear_grad()
    after = pos_loss()
    assert after < before * 0.5, (before, after)


def test_huge_vocab_sharded_embedding_mesh8():
    """1M-row embedding sharded over 8 devices (128 MB table, 16 MB per
    shard): lookups psum across the axis and match a replicated gather —
    the vocab-sharded design standing in for the reference's PS-side
    embedding tables (SURVEY hard part 5)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_mod.init_mesh({"tp": 8})
    V, D, B = 1_048_576, 32, 16
    rng = np.random.RandomState(0)
    # build host-side once (f32), shard to devices; the host copy doubles
    # as the gather reference so the sharded table never pulls back whole
    host = (rng.randn(V, D) * 0.01).astype(np.float32)
    table = jax.device_put(host, NamedSharding(mesh, P("tp", None)))
    ids = jnp.asarray(rng.randint(0, V, (B,)), jnp.int32)

    per_shard = V // 8

    def spmd(tbl, ids_all):
        import jax.numpy as jnp
        from jax import lax
        rank = lax.axis_index("tp")
        lo = rank * per_shard
        local = ids_all - lo
        valid = (local >= 0) & (local < per_shard)
        emb = jnp.take(tbl, jnp.where(valid, local, 0), axis=0)
        return lax.psum(jnp.where(valid[:, None], emb, 0.0), "tp")

    out = jax.jit(jax.shard_map(
        spmd, mesh=mesh, in_specs=(P("tp", None), P()),
        out_specs=P(), check_vma=False))(table, ids)
    want = host[np.asarray(ids)]
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-6)
    mesh_mod.init_mesh({"dp": 8})
