"""End-to-end 'book test' workloads (reference
python/paddle/fluid/tests/book/: small models trained a few iterations,
loss must drop): word2vec with SPARSE embedding grads, and a huge-vocab
sharded embedding over the mesh — the TPU-native foundation for the
deferred PS stack (SURVEY hard part 5)."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer
from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.distributed import mesh as mesh_mod


def test_word2vec_book_sparse_grads():
    """Skip-gram word2vec (reference book/test_word2vec_book.py) trained
    eagerly with embedding(sparse=True): the table's grads stay
    SelectedRows end-to-end and the loss drops."""
    paddle.seed(0)
    rng = np.random.RandomState(0)
    V, D, N = 500, 16, 256
    # synthetic corpus: word i co-occurs with (i +/- 1) mod V
    centers = rng.randint(0, V, N)
    contexts = (centers + rng.choice([-1, 1], N)) % V

    emb_in = nn.Embedding(V, D, sparse=True)
    emb_out = nn.Embedding(V, D, sparse=True)
    opt = optimizer.Adam(learning_rate=0.05, lazy_mode=True,
                         parameters=list(emb_in.parameters())
                         + list(emb_out.parameters()))
    losses = []
    saw_sparse = False
    for lo in range(0, N, 64):
        c = paddle.to_tensor(centers[lo:lo + 64].astype("int64"))
        t = paddle.to_tensor(contexts[lo:lo + 64].astype("int64"))
        neg = paddle.to_tensor(
            rng.randint(0, V, 64 * 4).reshape(64, 4).astype("int64"))
        vc = emb_in(c)                                   # [b, D]
        vt = emb_out(t)                                  # [b, D]
        vn = emb_out(neg)                                # [b, 4, D]
        pos = ops.sum(vc * vt, axis=-1)
        negs = ops.sum(vn * ops.unsqueeze(vc, [1]), axis=-1)
        loss = (ops.mean(ops.softplus(-pos))
                + ops.mean(ops.softplus(negs)))
        loss.backward()
        if emb_in.weight.grad is not None:
            saw_sparse = saw_sparse or isinstance(
                emb_in.weight.grad._value, SelectedRows)
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert saw_sparse, "sparse embedding grads never materialized"

    # like-for-like convergence: the pos-only objective before vs after
    # several more epochs of training (same loss form on both sides)
    def pos_loss():
        from paddle_tpu.core import tape as _tape
        vals = []
        with _tape.no_grad():
            for lo in range(0, N, 64):
                c = paddle.to_tensor(centers[lo:lo + 64].astype("int64"))
                t = paddle.to_tensor(contexts[lo:lo + 64].astype("int64"))
                vals.append(float(ops.mean(ops.softplus(
                    -ops.sum(emb_in(c) * emb_out(t), axis=-1))).numpy()))
        return float(np.mean(vals))

    before = pos_loss()
    for _ in range(4):
        for lo in range(0, N, 64):
            c = paddle.to_tensor(centers[lo:lo + 64].astype("int64"))
            t = paddle.to_tensor(contexts[lo:lo + 64].astype("int64"))
            vc, vt = emb_in(c), emb_out(t)
            loss = ops.mean(ops.softplus(-ops.sum(vc * vt, axis=-1)))
            loss.backward()
            opt.step()
            opt.clear_grad()
    after = pos_loss()
    assert after < before * 0.5, (before, after)


def test_huge_vocab_sharded_embedding_mesh8():
    """1M-row embedding sharded over 8 devices (128 MB table, 16 MB per
    shard): lookups psum across the axis and match a replicated gather —
    the vocab-sharded design standing in for the reference's PS-side
    embedding tables (SURVEY hard part 5)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_mod.init_mesh({"tp": 8})
    V, D, B = 1_048_576, 32, 16
    rng = np.random.RandomState(0)
    # build host-side once (f32), shard to devices; the host copy doubles
    # as the gather reference so the sharded table never pulls back whole
    host = (rng.randn(V, D) * 0.01).astype(np.float32)
    table = jax.device_put(host, NamedSharding(mesh, P("tp", None)))
    ids = jnp.asarray(rng.randint(0, V, (B,)), jnp.int32)

    per_shard = V // 8

    def spmd(tbl, ids_all):
        import jax.numpy as jnp
        from jax import lax
        rank = lax.axis_index("tp")
        lo = rank * per_shard
        local = ids_all - lo
        valid = (local >= 0) & (local < per_shard)
        emb = jnp.take(tbl, jnp.where(valid, local, 0), axis=0)
        return lax.psum(jnp.where(valid[:, None], emb, 0.0), "tp")

    out = jax.jit(mesh_mod.shard_map(
        spmd, mesh=mesh, in_specs=(P("tp", None), P()),
        out_specs=P()))(table, ids)
    want = host[np.asarray(ids)]
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-6)
    mesh_mod.init_mesh({"dp": 8})


def test_recommender_system_book(tmp_path):
    """fluid 'book' recommender_system (reference
    python/paddle/fluid/tests/book/test_recommender_system.py): user/movie
    embeddings + fc towers + cosine ranking over MovieLens — here over the
    zero-egress Movielens dataset and the 2.0 API."""
    import zipfile

    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.text.datasets import Movielens

    users = "".join(f"{u}::M::25::4::1\n" for u in range(1, 5))
    movies = "".join(f"{m}::T{m} (1995)::Comedy\n" for m in range(1, 6))
    rng = np.random.RandomState(0)
    ratings = "".join(
        f"{rng.randint(1, 5)}::{rng.randint(1, 6)}::{rng.randint(1, 6)}::0\n"
        for _ in range(64))
    z = str(tmp_path / "ml.zip")
    with zipfile.ZipFile(z, "w") as zf:
        zf.writestr("ml-1m/users.dat", users)
        zf.writestr("ml-1m/movies.dat", movies)
        zf.writestr("ml-1m/ratings.dat", ratings)
    ds = Movielens(data_file=z, mode="train", test_ratio=0.0)

    paddle.seed(0)

    class Tower(nn.Layer):
        def __init__(self, n_ids):
            super().__init__()
            self.emb = nn.Embedding(n_ids, 8)
            self.fc = nn.Linear(8, 8)

        def forward(self, ids):
            return self.fc(self.emb(ids))

    user_t, movie_t = Tower(8), Tower(8)
    params = list(user_t.parameters()) + list(movie_t.parameters())
    opt = optimizer.Adam(learning_rate=0.05, parameters=params)

    uid = paddle.to_tensor(np.array([r[0] for r in ds], "int64"))
    mid = paddle.to_tensor(np.array([r[4] for r in ds], "int64"))
    rating = paddle.to_tensor(
        np.array([r[7] for r in ds], "float32") / 5.0)

    losses = []
    for _ in range(30):
        uu, mm = user_t(uid), movie_t(mid)
        sim = paddle.ops.cos_sim(uu, mm)
        loss = ((sim - rating) ** 2.0).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_label_semantic_roles_book(tmp_path):
    """fluid 'book' label_semantic_roles (reference
    book/test_label_semantic_roles.py): embeddings -> BiGRU-ish encoder ->
    linear_chain_crf over Conll05 — viterbi decode recovers training
    labels on a tiny corpus."""
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer, ops
    from paddle_tpu.text.datasets import Conll05st

    words = "The\ncat\nsat\n\nA\ndog\nbarked\n"
    props = "- B-A0\n- I-A0\n- B-V\n\n- B-A0\n- I-A0\n- B-V\n"
    wf, pf = tmp_path / "w.txt", tmp_path / "p.txt"
    wf.write_text(words)
    pf.write_text(props)
    ds = Conll05st(words_file=str(wf), props_file=str(pf))
    V, L = len(ds.word_dict), len(ds.label_dict)

    paddle.seed(0)
    emb = nn.Embedding(V, 16)
    fc = nn.Linear(16, L)
    # CRF transition params
    import jax.numpy as jnp
    trans = paddle.to_tensor(
        np.zeros((L + 2, L), "float32"), stop_gradient=False)
    params = list(emb.parameters()) + list(fc.parameters()) + [trans]
    opt = optimizer.Adam(learning_rate=0.1, parameters=params)

    seqs = [ds[i] for i in range(len(ds))]
    for _ in range(40):
        total = None
        for w, lab in seqs:
            feats = ops.unsqueeze(fc(emb(paddle.to_tensor(w))), [0])
            nll = ops.linear_chain_crf(
                feats, trans, paddle.to_tensor(lab[None], "int64"))
            nll = nll.sum() if hasattr(nll, "sum") else nll
            total = nll if total is None else total + nll
        total.backward()
        opt.step()
        opt.clear_grad()
    # decode recovers gold labels
    for w, lab in seqs:
        feats = ops.unsqueeze(fc(emb(paddle.to_tensor(w))), [0])
        _, path = ops.viterbi_decode(feats, trans)
        np.testing.assert_array_equal(
            np.asarray(path._value).reshape(-1), lab)
