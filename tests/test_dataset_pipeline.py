"""Industrial data path: native MultiSlot parser, InMemoryDataset with
global shuffle, QueueDataset streaming, train_from_dataset
(reference framework/data_set.h:157, data_feed.h:663, executor.cc:165)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer
from paddle_tpu.io import DatasetFactory, InMemoryDataset, QueueDataset


def _write_files(tmp_path, n_files=2, rows_per_file=8):
    """MultiSlot format: per line: '1 <label> 4 <x0..x3>'."""
    rng = np.random.RandomState(0)
    files, all_rows = [], []
    for fi in range(n_files):
        path = os.path.join(str(tmp_path), f"part-{fi:03d}.txt")
        with open(path, "w") as f:
            for _ in range(rows_per_file):
                x = rng.rand(4)
                y = float(x.sum() > 2.0)
                f.write("1 %d 4 %s\n" % (
                    int(y), " ".join(f"{v:.6f}" for v in x)))
                all_rows.append((y, x))
        files.append(path)
    return files, all_rows


class _Var:
    def __init__(self, name, shape, dtype):
        self.name, self.shape, self.dtype = name, shape, dtype


def test_native_parser_used():
    from paddle_tpu._native import native_lib
    assert native_lib() is not None, "C++ parser must build on this machine"


def test_in_memory_dataset_load_and_batches(tmp_path):
    files, all_rows = _write_files(tmp_path)
    ds = InMemoryDataset()
    ds.init(batch_size=4, thread_num=2,
            use_var=[_Var("y", [-1, 1], "int64"),
                     _Var("x", [-1, 4], "float32")])
    ds.set_filelist(files)
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 16
    batches = list(ds.batches())
    assert len(batches) == 4
    assert batches[0]["x"].shape == (4, 4)
    assert batches[0]["y"].shape == (4, 1)
    # order preserved without shuffle: first batch = first 4 rows
    np.testing.assert_allclose(batches[0]["x"][0],
                               all_rows[0][1], rtol=1e-5)
    ds.local_shuffle()
    shuffled = list(ds.batches())
    assert not np.allclose(shuffled[0]["x"], batches[0]["x"])
    ds.global_shuffle()  # single-process: full permutation
    assert sum(b["x"].shape[0] for b in ds.batches()) == 16
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_queue_dataset_streams(tmp_path):
    files, _ = _write_files(tmp_path)
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.init(batch_size=4, thread_num=1,
            use_var=[_Var("y", [-1, 1], "int64"),
                     _Var("x", [-1, 4], "float32")])
    ds.set_filelist(files)
    batches = list(ds.batches())
    assert len(batches) == 4 and batches[0]["x"].shape == (4, 4)


def test_train_from_dataset(tmp_path):
    files, _ = _write_files(tmp_path, n_files=2, rows_per_file=16)
    import paddle_tpu.static as static
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [-1, 4], "float32")
            y = static.data("y", [-1, 1], "float32")
            pred = nn.Linear(4, 1)(x)
            loss = ops.mean((pred - y) ** 2)
            optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = static.Executor()
        exe.run(startup)

        ds = InMemoryDataset()
        ds.init(batch_size=8, thread_num=2, use_var=[x, y])
        ds.set_filelist(files)
        ds.load_into_memory()

        first, last = [], []
        for epoch in range(6):
            ds.local_shuffle()
            losses = []
            for feed in ds.batches():
                losses.append(float(exe.run(main, feed=feed,
                                            fetch_list=[loss])[0]))
            (first if epoch == 0 else last)[:] = losses
        assert np.mean(last) < np.mean(first) * 0.7, (first, last)

        # the one-call loop API
        exe.train_from_dataset(main, ds, fetch_list=[loss],
                               print_period=1000)
        from paddle_tpu.core import monitor
        assert monitor.stat_get("executor/dataset_batches") >= 4
    finally:
        paddle.disable_static()


def test_ragged_slot_pads_to_declared_width(tmp_path):
    path = os.path.join(str(tmp_path), "ragged.txt")
    with open(path, "w") as f:
        f.write("2 5 6\n")      # 2 ids
        f.write("3 7 8 9\n")    # 3 ids
    ds = InMemoryDataset()
    ds.init(batch_size=2, use_var=[_Var("ids", [-1, 4], "int64")])
    ds.set_filelist([path])
    ds.load_into_memory()
    (batch,) = list(ds.batches())
    np.testing.assert_array_equal(batch["ids"],
                                  [[5, 6, 0, 0], [7, 8, 9, 0]])


# --------------------------- DataLoader workers ----------------------------

class _SlowSquares(paddle.io.Dataset):
    """Python-heavy __getitem__: the GIL-bound case process workers fix."""

    def __len__(self):
        return 32

    def __getitem__(self, idx):
        total = sum(i * i for i in range(2000))  # pure-Python work
        return (np.full((4,), idx, "float32"),
                np.asarray([idx % 2], "int64"))


def test_dataloader_process_workers_order_and_values():
    from paddle_tpu.io import DataLoader
    dl = DataLoader(_SlowSquares(), batch_size=8, num_workers=2,
                    shuffle=False, use_shared_memory=True)
    batches = list(dl)
    assert len(batches) == 4
    xs = np.concatenate([b[0] for b in batches])
    np.testing.assert_allclose(xs[:, 0], np.arange(32))  # sampler order kept


def test_dataloader_worker_init_fn_and_error_propagation(tmp_path):
    from paddle_tpu.io import DataLoader

    seen = []

    class Boom(paddle.io.Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, idx):
            if idx == 5:
                raise ValueError("boom at 5")
            return np.zeros(2, "float32")

    dl = DataLoader(Boom(), batch_size=4, num_workers=2,
                    use_shared_memory=True,
                    worker_init_fn=lambda wid: seen.append(wid))
    with pytest.raises(RuntimeError, match="boom at 5"):
        list(dl)


def test_dataloader_thread_fallback_still_works():
    from paddle_tpu.io import DataLoader
    dl = DataLoader(_SlowSquares(), batch_size=8, num_workers=2,
                    use_shared_memory=False)
    assert len(list(dl)) == 4


def test_dataloader_abandoned_iterator_shuts_down_threads():
    """A consumer that bails mid-epoch (GeneratorExit) must not leak the
    ThreadPoolExecutor workers / producer threads — before the fix they
    lived until process exit."""
    import threading
    import time

    from paddle_tpu.io import DataLoader

    before = set(threading.enumerate())
    dl = DataLoader(_SlowSquares(), batch_size=4, num_workers=2,
                    use_shared_memory=False)
    it = iter(dl)
    next(it)  # pools + producer threads are now live
    spawned = [t for t in threading.enumerate() if t not in before]
    assert spawned, "expected loader worker threads while iterating"
    it.close()  # GeneratorExit through both generator layers
    deadline = time.time() + 10
    while time.time() < deadline:
        if not any(t.is_alive() for t in spawned):
            break
        time.sleep(0.05)
    leaked = [t.name for t in spawned if t.is_alive()]
    assert not leaked, f"loader threads leaked after close: {leaked}"
