"""Regression tests for round-3 fixes (VERDICT r02 "what's weak")."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import mesh as mesh_mod


def test_fleet_init_rejects_non_factoring_degrees():
    """VERDICT weak #6: silent DP fallback was a correctness trap."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 3}  # 3 !| 8
    with pytest.raises(ValueError, match="factor the device count"):
        fleet.init(is_collective=True, strategy=strategy)
    mesh_mod.init_mesh({"dp": 8})


def test_sequence_mask_eager_and_jit():
    lengths = paddle.to_tensor(np.array([1, 3, 2]))
    m = F.sequence_mask(lengths)  # eager: maxlen inferred
    assert tuple(m.shape) == (3, 3)
    assert np.asarray(m._value).tolist() == [[1, 0, 0], [1, 1, 1], [1, 1, 0]]

    import jax
    import jax.numpy as jnp

    def f(lv):
        return F.sequence_mask(paddle.Tensor(lv, _internal=True),
                               maxlen=4)._value

    out = jax.jit(f)(jnp.asarray([2, 4]))  # static maxlen under jit works
    assert np.asarray(out).tolist() == [[1, 1, 0, 0], [1, 1, 1, 1]]

    def g(lv):
        return F.sequence_mask(paddle.Tensor(lv, _internal=True))._value

    with pytest.raises(ValueError, match="concrete mask width"):
        jax.jit(g)(jnp.asarray([2, 4]))  # dynamic width: loud error


def test_to_static_data_dependent_branch_converts():
    """Round 3 asserted this RAISED (tracing must not silently bake one
    branch); round 5's dy2static converter (jit/dy2static.py) now lowers
    the branch to lax.cond, so both sides must evaluate correctly."""
    import paddle_tpu.jit as jit

    @jit.to_static
    def f(x):
        if (x.mean() > 0):  # data-dependent Python branch
            return x + 1
        return x - 1

    pos = paddle.to_tensor(np.ones((4,), "float32"))
    neg = paddle.to_tensor(-np.ones((4,), "float32"))
    np.testing.assert_allclose(np.asarray(f(pos).numpy()), 2.0)
    np.testing.assert_allclose(np.asarray(f(neg).numpy()), -2.0)


def test_static_variable_bool_errors():
    import paddle_tpu.static as static
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4], "float32")
            with pytest.raises(TypeError, match="while_loop"):
                if x.sum() > 0:  # noqa: F634 — the point is it must raise
                    pass
    finally:
        paddle.disable_static()
