"""Regression tests for round-3 fixes (VERDICT r02 "what's weak")."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import mesh as mesh_mod


def test_fleet_init_rejects_non_factoring_degrees():
    """VERDICT weak #6: silent DP fallback was a correctness trap."""
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 3}  # 3 !| 8
    with pytest.raises(ValueError, match="factor the device count"):
        fleet.init(is_collective=True, strategy=strategy)
    mesh_mod.init_mesh({"dp": 8})


def test_sequence_mask_eager_and_jit():
    lengths = paddle.to_tensor(np.array([1, 3, 2]))
    m = F.sequence_mask(lengths)  # eager: maxlen inferred
    assert tuple(m.shape) == (3, 3)
    assert np.asarray(m._value).tolist() == [[1, 0, 0], [1, 1, 1], [1, 1, 0]]

    import jax
    import jax.numpy as jnp

    def f(lv):
        return F.sequence_mask(paddle.Tensor(lv, _internal=True),
                               maxlen=4)._value

    out = jax.jit(f)(jnp.asarray([2, 4]))  # static maxlen under jit works
    assert np.asarray(out).tolist() == [[1, 1, 0, 0], [1, 1, 1, 1]]

    def g(lv):
        return F.sequence_mask(paddle.Tensor(lv, _internal=True))._value

    with pytest.raises(ValueError, match="concrete mask width"):
        jax.jit(g)(jnp.asarray([2, 4]))  # dynamic width: loud error
