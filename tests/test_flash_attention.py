"""Pallas flash-attention kernel: numeric parity with the jnp path.

Runs the kernel in Pallas interpreter mode on the CPU mesh (same code path
as compiled TPU modulo Mosaic lowering), mirroring the reference's
golden-op discipline (reference unittests/op_test.py:232 — kernel output
vs numpy reference, analytic grads vs finite differences elsewhere in
tests/op_test.py).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.ops.pallas import flash_attention


def _ref(q, k, v, bias=None, causal=False, scale=None):
    d = q.shape[-1]
    sc = scale or d ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sc
    if bias is not None:
        s = s + bias[:, None, None, :]
    if causal:
        s = jnp.where(jnp.tril(jnp.ones(s.shape[-2:], bool)), s, -1e9)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("b,h,sq,sk,d,causal,with_bias", [
    (2, 3, 32, 32, 16, False, False),
    (1, 2, 64, 64, 32, True, False),
    (2, 2, 32, 64, 8, False, True),
])
def test_flash_matches_reference(b, h, sq, sk, d, causal, with_bias):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, sq, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, sk, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, sk, d), jnp.float32)
    bias = jnp.asarray(np.where(rng.rand(b, sk) < 0.3, -1e9, 0.0),
                       jnp.float32) if with_bias else None

    out = flash_attention(q, k, v, bias=bias, causal=causal)
    ref = _ref(q, k, v, bias, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    g1 = jax.grad(lambda *a: flash_attention(
        *a, bias=bias, causal=causal).sum(), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: _ref(*a, bias, causal).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=5e-5)


def test_sdpa_routes_through_flash(monkeypatch):
    """The functional API picks the kernel when the flag forces interpret
    mode, and its output matches the jnp path — through the autograd tape."""
    calls = []
    real = F._flash_sdpa

    def counted(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(F, "_flash_sdpa", counted)
    paddle.set_flags({"FLAGS_flash_attention_interpret": True,
                          "FLAGS_flash_min_seq": 0})
    try:
        rng = np.random.RandomState(1)
        mk = lambda *s: paddle.to_tensor(  # noqa: E731
            rng.randn(*s).astype("float32"), stop_gradient=False)
        q, k, v = mk(2, 2, 32, 16), mk(2, 2, 32, 16), mk(2, 2, 32, 16)
        out_flash = F.scaled_dot_product_attention(q, k, v)
        assert calls, "flash kernel was not routed to"
        paddle.set_flags({"FLAGS_flash_attention_interpret": False})
        out_ref = F.scaled_dot_product_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out_flash._value),
                                   np.asarray(out_ref._value), atol=2e-5)

        paddle.set_flags({"FLAGS_flash_attention_interpret": True,
                          "FLAGS_flash_min_seq": 0})
        out_flash.sum().backward()
        gq = np.asarray(q.grad._value)
        assert np.isfinite(gq).all() and np.abs(gq).max() > 0
    finally:
        paddle.set_flags({"FLAGS_flash_attention_interpret": False})


def test_mha_layer_uses_flash_and_trains():
    """MultiHeadAttention forward/backward through the kernel, bf16-safe."""
    from paddle_tpu import nn
    paddle.set_flags({"FLAGS_flash_attention_interpret": True,
                          "FLAGS_flash_min_seq": 0})
    try:
        paddle.seed(0)
        mha = nn.MultiHeadAttention(32, 2, dropout=0.0)
        x = paddle.randn([2, 16, 32])
        x.stop_gradient = False
        out = mha(x)
        assert tuple(out.shape) == (2, 16, 32)
        out.mean().backward()
        assert mha.qkv_proj.weight.grad is not None
        g = np.asarray(mha.qkv_proj.weight.grad._value)
        assert np.isfinite(g).all()
    finally:
        paddle.set_flags({"FLAGS_flash_attention_interpret": False})


def test_flash_causal_rectangular_matches_sdpa():
    """Bottom-right-aligned causal mask for sq != sk (KV-cache decode):
    the kernel must agree with the jnp path's tril(k=sk-sq) convention."""
    from paddle_tpu.nn.functional import _sdpa
    rng = np.random.RandomState(3)
    for sq, sk in [(8, 64), (32, 64)]:
        q = jnp.asarray(rng.randn(1, 2, sq, 16), jnp.float32)
        k = jnp.asarray(rng.randn(1, 2, sk, 16), jnp.float32)
        v = jnp.asarray(rng.randn(1, 2, sk, 16), jnp.float32)
        out = flash_attention(q, k, v, causal=True)
        ref = _sdpa.raw(q, k, v, None, 16 ** -0.5, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)


def test_flash_fallbacks():
    """Shapes the kernel can't handle must route to the jnp path, not crash."""
    from paddle_tpu.ops.pallas.flash_attention import supported
    # broadcastable-but-not-exact mask shapes
    assert not supported((2, 2, 32, 16), (2, 2, 32, 16), (2, 2, 32, 16),
                         (1, 1, 1, 32))
    assert not supported((2, 2, 32, 16), (2, 2, 32, 16), (2, 2, 32, 16),
                         (2, 1, 1, 1))
    # v head_dim differs from q/k
    assert not supported((1, 2, 32, 16), (1, 2, 32, 16), (1, 2, 32, 32))
    # odd sequence lengths ARE supported now: the wrapper pads to a
    # multiple of 8 (masking padded key columns) and slices back
    assert supported((1, 2, 33, 16), (1, 2, 33, 16), (1, 2, 33, 16))
    # the functional API works on odd shapes through the kernel
    paddle.set_flags({"FLAGS_flash_attention_interpret": True,
                          "FLAGS_flash_min_seq": 0})
    try:
        rng = np.random.RandomState(4)
        mk = lambda *s: paddle.to_tensor(  # noqa: E731
            rng.randn(*s).astype("float32"))
        out = F.scaled_dot_product_attention(mk(1, 2, 33, 16),
                                             mk(1, 2, 33, 16),
                                             mk(1, 2, 33, 16))
        assert tuple(out.shape) == (1, 2, 33, 16)
    finally:
        paddle.set_flags({"FLAGS_flash_attention_interpret": False})


@pytest.mark.parametrize("sq,sk,causal,with_bias", [
    (33, 33, False, False),   # odd square
    (33, 33, True, False),    # odd causal: original diagonal preserved
    (7, 65, False, True),     # both dims ragged + bias path
    (1, 40, True, False),     # single-row decode-like query
])
def test_flash_padded_odd_shapes_match_reference(sq, sk, causal, with_bias):
    """Pad-to-8 + bias masking + slice-back must be exact vs the dense
    reference, forward and backward."""
    from paddle_tpu.nn.functional import _sdpa
    rng = np.random.RandomState(7)
    b, h, d = 2, 2, 16
    q = jnp.asarray(rng.randn(b, h, sq, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, sk, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, sk, d), jnp.float32)
    bias = jnp.asarray(np.where(rng.rand(b, sk) < 0.3, -1e9, 0.0),
                       jnp.float32) if with_bias else None

    out = flash_attention(q, k, v, bias=bias, causal=causal)
    assert out.shape == (b, h, sq, d)
    if causal:
        from paddle_tpu.nn.functional import _sdpa
        ref = _sdpa.raw(q, k, v, None if bias is None
                        else bias[:, None, None, :], d ** -0.5, True)
    else:
        ref = _ref(q, k, v, bias, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)

    g1 = jax.grad(lambda *a: flash_attention(
        *a, bias=bias, causal=causal).sum(), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: (_sdpa.raw(
        a[0], a[1], a[2], None if bias is None else bias[:, None, None, :],
        d ** -0.5, causal)).sum(), argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4)


def test_ring_attention_flash_path_matches():
    """Ring attention over the sp axis with the flash kernel per block
    must equal full single-device attention (causal and not)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.ring_attention import _ring_attention_raw

    mesh = mesh_mod.init_mesh({"sp": 8})
    rng = np.random.RandomState(0)
    b, h, s, d = 1, 2, 64, 16   # s_local = 8 per device
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    spec = P(None, None, "sp", None)

    for causal in (False, True):
        ref = _ref(q, k, v, causal=causal)
        paddle.set_flags({"FLAGS_pallas_interpret": True})
        try:
            out = mesh_mod.shard_map(
                lambda ql, kl, vl: _ring_attention_raw(
                    ql, kl, vl, "sp", causal, None),
                mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)
        finally:
            paddle.set_flags({"FLAGS_pallas_interpret": False})
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5)
    mesh_mod.init_mesh({"dp": 8})


def test_flash_return_lse():
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(1, 1, 32, 8), jnp.float32)
    k = jnp.asarray(rng.randn(1, 1, 32, 8), jnp.float32)
    v = jnp.asarray(rng.randn(1, 1, 32, 8), jnp.float32)
    out, lse = flash_attention(q, k, v, return_lse=True)
    # lse must equal logsumexp of the scaled logits
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * (8 ** -0.5)
    want = jax.scipy.special.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want),
                               atol=2e-5)


def test_flash_bf16():
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 2, 64, 32), jnp.bfloat16)
    k = jnp.asarray(rng.randn(1, 2, 64, 32), jnp.bfloat16)
    v = jnp.asarray(rng.randn(1, 2, 64, 32), jnp.bfloat16)
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = _ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)
