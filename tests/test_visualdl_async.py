"""VisualDL under the async fit loop: the default sample_freq drains at
the log_freq window boundary — where fit() has ALREADY materialized the
window — so streaming per-batch losses costs ZERO extra device syncs;
sample_freq=1 restores (and demonstrates) the per-batch-sync behavior.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import Model, nn, optimizer
from paddle_tpu.hapi import model as model_mod
from paddle_tpu.hapi.callbacks import VisualDL
from paddle_tpu.io import TensorDataset
from paddle_tpu.utils.log_writer import read_scalars

N_BATCHES = 20
LOG_FREQ = 10


def _fit_with_spy(monkeypatch, tmp_path, sample_freq, epochs=1,
                  n_batches=N_BATCHES):
    """Run a small async fit with VisualDL attached; count 'forced'
    loss reads — value() calls that hit a not-yet-drained window entry
    (each one is an extra device sync the pipeline paid for)."""
    forced = []
    orig_value = model_mod._LazyLoss.value

    def spy(self):
        if self._val is None:
            forced.append(self.step)
        return orig_value(self)

    monkeypatch.setattr(model_mod._LazyLoss, "value", spy)
    paddle.seed(7)
    x = np.random.RandomState(0).rand(n_batches * 2, 4).astype("float32")
    y = (x.sum(axis=1, keepdims=True)).astype("float32")
    ds = TensorDataset([x, y])
    model = Model(nn.Linear(4, 1))
    model.prepare(optimizer=optimizer.SGD(
        learning_rate=0.01, parameters=model.parameters()),
        loss=nn.MSELoss())
    logdir = str(tmp_path / f"vdl_{sample_freq}_{epochs}")
    cb = VisualDL(logdir, sample_freq=sample_freq)
    model.fit(ds, batch_size=2, epochs=epochs, verbose=0,
              log_freq=LOG_FREQ, callbacks=[cb], shuffle=False)
    recs = read_scalars(logdir, tag="train/loss")
    return forced, recs


def test_default_sample_freq_adds_no_syncs(monkeypatch, tmp_path):
    # sanity: the async loop is actually on
    assert paddle.get_flags(["FLAGS_executor_max_inflight"])[
        "FLAGS_executor_max_inflight"] > 0
    forced, recs = _fit_with_spy(monkeypatch, tmp_path,
                                 sample_freq=LOG_FREQ)
    # window-boundary drain: every loss VisualDL read was already
    # materialized by fit's own log_freq drain — zero extra syncs
    assert forced == [], f"VisualDL forced early syncs at {forced}"
    # ...and per-batch records are all there, exact, in order
    assert [r["step"] for r in recs] == list(range(1, N_BATCHES + 1))
    assert all(np.isfinite(r["value"]) for r in recs)


def test_multi_epoch_odd_length_stays_aligned(monkeypatch, tmp_path):
    """Regression: the flush cadence keys on fit's PER-EPOCH step, not a
    global counter — with 15 batches/epoch (not a multiple of 10) the
    second epoch's flushes must still land on drained boundaries."""
    forced, recs = _fit_with_spy(monkeypatch, tmp_path,
                                 sample_freq=LOG_FREQ, epochs=2,
                                 n_batches=15)
    assert forced == [], f"epoch-2 flush forced early syncs at {forced}"
    assert len(recs) == 30  # every batch of both epochs recorded


def test_sample_freq_1_forces_per_batch_syncs(monkeypatch, tmp_path):
    forced, recs = _fit_with_spy(monkeypatch, tmp_path, sample_freq=1)
    # the old write-every-batch behavior: most batches force a drain
    # of their own not-yet-retired step (the window keeps 2 in flight)
    assert len(forced) > N_BATCHES // 2, forced
    assert [r["step"] for r in recs] == list(range(1, N_BATCHES + 1))


def test_values_identical_across_sample_freqs(monkeypatch, tmp_path):
    _, eager = _fit_with_spy(monkeypatch, tmp_path, sample_freq=1)
    _, lazy = _fit_with_spy(monkeypatch, tmp_path,
                            sample_freq=LOG_FREQ)
    # buffering only defers the WRITE; the recorded losses are the
    # exact per-step values either way
    np.testing.assert_allclose([r["value"] for r in eager],
                               [r["value"] for r in lazy], rtol=0, atol=0)


def test_sync_loop_unaffected(monkeypatch, tmp_path):
    # inflight=0 restores the fully synchronous loop: losses are plain
    # floats and VisualDL still records every batch
    saved = paddle.get_flags(["FLAGS_executor_max_inflight"])
    paddle.set_flags({"FLAGS_executor_max_inflight": 0})
    try:
        forced, recs = _fit_with_spy(monkeypatch, tmp_path,
                                     sample_freq=LOG_FREQ)
    finally:
        paddle.set_flags(saved)
    assert forced == []
    assert len(recs) == N_BATCHES
