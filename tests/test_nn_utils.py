"""nn.utils reparameterizations (reference nn/utils/weight_norm_hook.py
weight_norm :155 / remove_weight_norm :202; spectral_norm_op.cc)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def test_weight_norm_roundtrip_and_training():
    paddle.seed(0)
    lin = nn.Linear(4, 3, bias_attr=False)
    w_before = np.asarray(lin.weight.numpy()).copy()
    nn.utils.weight_norm(lin, dim=0)
    names = dict(lin.named_parameters())
    assert "weight_g" in names and "weight_v" in names
    assert "weight" not in names
    x = paddle.to_tensor(np.random.RandomState(0).rand(2, 4)
                         .astype("float32"))
    out = lin(x)
    # reparameterized forward matches the original weight initially
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               np.asarray(x.numpy()) @ w_before, rtol=1e-5)
    # gradients flow to g and v
    loss = out.sum()
    loss.backward()
    assert names["weight_g"].grad is not None
    assert names["weight_v"].grad is not None
    # a training step changes the effective weight
    opt = optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    opt.step()
    opt.clear_grad()
    out2 = lin(x)
    assert not np.allclose(np.asarray(out2.numpy()),
                           np.asarray(out.numpy()))

    nn.utils.remove_weight_norm(lin)
    names = dict(lin.named_parameters())
    assert "weight" in names and "weight_g" not in names
    out3 = lin(x)
    np.testing.assert_allclose(np.asarray(out3.numpy()),
                               np.asarray(out2.numpy()), rtol=1e-5)
    with pytest.raises(ValueError, match="no weight_norm"):
        nn.utils.remove_weight_norm(lin)


def test_spectral_norm_bounds_sigma():
    paddle.seed(1)
    lin = nn.Linear(6, 8, bias_attr=False)
    lin.weight.set_value(np.asarray(lin.weight.numpy()) * 10.0)
    nn.utils.spectral_norm(lin, n_power_iterations=10)
    x = paddle.to_tensor(np.eye(6, dtype="float32"))
    lin(x)  # runs the hook (power iteration + normalize)
    w_eff = np.asarray(lin.weight.numpy())
    assert np.linalg.svd(w_eff)[1][0] == pytest.approx(1.0, rel=1e-2)


def test_parameters_vector_roundtrip():
    paddle.seed(2)
    net = nn.Linear(3, 2)
    params = list(net.parameters())
    vec = nn.utils.parameters_to_vector(params)
    assert vec.shape == (3 * 2 + 2,)
    flat = np.asarray(vec.numpy())
    nn.utils.vector_to_parameters(paddle.to_tensor(flat * 2.0), params)
    np.testing.assert_allclose(
        np.asarray(nn.utils.parameters_to_vector(params).numpy()),
        flat * 2.0, rtol=1e-6)
    with pytest.raises(ValueError, match="elements"):
        nn.utils.vector_to_parameters(
            paddle.to_tensor(np.zeros(3, "float32")), params)


def test_spectral_norm_buffers_persist_and_grads_flow():
    paddle.seed(3)
    lin = nn.Linear(6, 8, bias_attr=False)
    nn.utils.spectral_norm(lin, n_power_iterations=1)
    u0 = np.asarray(lin._buffers["weight_u"].numpy()).copy()
    x = paddle.to_tensor(np.random.RandomState(0).rand(2, 6)
                         .astype("float32"))
    lin(x)
    u1 = np.asarray(lin._buffers["weight_u"].numpy())
    assert not np.allclose(u0, u1)  # the iteration advanced the buffer
    # grads flow through sigma to the original weight
    loss = lin(x).sum()
    loss.backward()
    worig = dict(lin.named_parameters())["weight_orig"]
    assert worig.grad is not None
    assert np.isfinite(np.asarray(worig.grad.numpy())).all()


def test_clip_grad_norm_and_value():
    net = nn.Linear(4, 2, bias_attr=False)
    x = paddle.to_tensor(np.ones((2, 4), "float32") * 100.0)
    net(x).sum().backward()
    g0 = np.asarray(net.weight.grad.numpy()).copy()
    total = nn.utils.clip_grad_norm_(net.parameters(), max_norm=1.0)
    assert float(np.asarray(total.numpy())) == pytest.approx(
        np.linalg.norm(g0), rel=1e-5)
    g1 = np.asarray(net.weight.grad.numpy())
    assert np.linalg.norm(g1) == pytest.approx(1.0, rel=1e-4)

    net.weight.grad = paddle.to_tensor(
        np.array([[5.0, -7.0, 0.1, 2.0]] * 2, "float32").T)
    nn.utils.clip_grad_value_(net.parameters(), 2.5)
    g2 = np.asarray(net.weight.grad.numpy())
    assert g2.max() <= 2.5 and g2.min() >= -2.5
