"""Typed metrics (core/monitor.py): legacy stat_* back-compat, time
series, histograms, exports, and the atomic prefix reset the bench modes
depend on. See docs/observability.md."""
import json
import threading

import pytest

import paddle_tpu as paddle  # noqa: F401
from paddle_tpu.core import monitor


@pytest.fixture(autouse=True)
def _clean():
    monitor.reset(prefix="tm.")
    yield
    monitor.reset(prefix="tm.")


def test_legacy_surface_unchanged():
    monitor.stat_add("tm.c")
    monitor.stat_add("tm.c", 2)
    monitor.stat_set("tm.g", 7.5)
    monitor.stat_set_many({"tm.a": 1, "tm.b": 2})
    assert monitor.stat_get("tm.c") == 3
    assert monitor.stat_get("tm.missing") == 0
    s = monitor.stats("tm.")
    assert s["tm.c"] == 3 and s["tm.g"] == 7.5 and s["tm.a"] == 1
    monitor.reset(name="tm.c")
    assert monitor.stat_get("tm.c") == 0
    assert "tm.c" not in monitor.stats("tm.")


def test_time_series_bounded_and_ordered():
    saved = paddle.get_flags(["FLAGS_monitor_series_len"])
    paddle.set_flags({"FLAGS_monitor_series_len": 5})
    try:
        for _ in range(12):
            monitor.stat_add("tm.ser")
        ser = monitor.series("tm.ser")
        assert len(ser) == 5
        values = [v for _, v in ser]
        assert values == [8.0, 9.0, 10.0, 11.0, 12.0]  # newest last
        ts = [t for t, _ in ser]
        assert ts == sorted(ts)
    finally:
        paddle.set_flags(saved)


def test_histogram_observe_and_summary():
    h = monitor.histogram("tm.lat", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    s = monitor.histogram_summary("tm.lat")
    assert s["count"] == 4
    assert s["sum"] == pytest.approx(555.5)
    assert s["min"] == 0.5 and s["max"] == 500.0
    assert s["buckets"] == [1, 1, 1, 1]  # one per bucket incl. +Inf
    # histograms surface through the legacy stats() snapshot
    flat = monitor.stats("tm.lat")
    assert flat["tm.lat.count"] == 4
    assert flat["tm.lat.avg"] == pytest.approx(555.5 / 4)


def test_typed_handles():
    c = monitor.counter("tm.h.c")
    g = monitor.gauge("tm.h.g")
    c.add()
    c.add(4)
    g.set(2.5)
    assert c.value() == 5 and g.value() == 2.5
    snap = monitor.snapshot()
    assert snap["types"]["tm.h.c"] == "counter"
    assert snap["types"]["tm.h.g"] == "gauge"


def test_export_jsonl_and_prometheus(tmp_path):
    monitor.stat_add("tm.exp.count", 3)
    monitor.stat_set("tm.exp.gauge", 1.5)
    monitor.observe("tm.exp.hist", 2.0, buckets=(1.0, 10.0))
    path = str(tmp_path / "metrics.jsonl")
    monitor.export_jsonl(path)
    recs = {r["name"]: r for r in map(json.loads, open(path))}
    assert recs["tm.exp.count"]["value"] == 3
    assert recs["tm.exp.count"]["type"] == "counter"
    assert recs["tm.exp.count"]["series"]  # trajectory rides along
    assert recs["tm.exp.hist"]["histogram"]["count"] == 1
    text = monitor.prometheus_text()
    assert "# TYPE tm_exp_count counter" in text
    assert "tm_exp_gauge 1.5" in text
    assert 'tm_exp_hist_bucket{le="10.0"} 1' in text
    assert 'tm_exp_hist_bucket{le="+Inf"} 1' in text
    assert "tm_exp_hist_count 1" in text


def test_snapshot_consistent_under_lock():
    monitor.stat_add("tm.snap", 2)
    snap = monitor.snapshot()
    assert snap["values"]["tm.snap"] == 2
    assert snap["series"]["tm.snap"][-1][1] == 2.0


def test_prefix_reset_atomic_with_racing_writers():
    """Regression: reset(prefix=...) must clear value + series +
    histogram in ONE critical section. A writer may re-create the
    counter right after, but a snapshot must NEVER show a fresh value
    carrying a stale (pre-reset) series — which is exactly what a
    per-structure-lock reset produced mid-bench."""
    stop = threading.Event()
    errors = []

    def writer():
        while not stop.is_set():
            monitor.stat_add("tm.race.c")
            monitor.observe("tm.race.h", 1.0, buckets=(10.0,))

    threads = [threading.Thread(target=writer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            monitor.reset(prefix="tm.race.")
            snap = monitor.snapshot()
            val = snap["values"].get("tm.race.c")
            ser = snap.get("series", {}).get("tm.race.c", [])
            if val is not None and ser:
                # counter restarted at 1,2,3,... after the reset; its
                # newest series sample IS the current value, and no
                # sample can exceed it (a stale pre-reset series would)
                if ser[-1][1] != val or max(v for _, v in ser) > val:
                    errors.append((val, ser[-3:]))
            hist = snap["histograms"].get("tm.race.h")
            hser = snap.get("series", {}).get("tm.race.h", [])
            if hist is not None and len(hser) > hist["count"]:
                errors.append(("hist", hist["count"], len(hser)))
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors, f"non-atomic prefix reset observed: {errors[:3]}"
    monitor.reset(prefix="tm.race.")
    assert monitor.stats("tm.race.") == {}
    assert monitor.series("tm.race.c") == []
    assert monitor.histogram_summary("tm.race.h") is None
