"""Crash-to-fallback hardening: an injected Pallas kernel failure must
demote to the jnp path with the pallas.fallback counter incremented and a
correct result — never an abort (the BENCH_r03 failure mode, where a
Mosaic crash silently pushed the whole bench onto fallback paths with a
single opaque boolean as the only evidence)."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core import monitor


@pytest.fixture
def interpret():
    paddle.set_flags({"FLAGS_pallas_interpret": True,
                      "FLAGS_flash_min_seq": 0})
    yield
    paddle.set_flags({"FLAGS_pallas_interpret": False,
                      "FLAGS_flash_min_seq": 1024})


def _reset():
    for name in list(monitor.stats("pallas.")):
        monitor.reset(name)


def test_flash_crash_demotes_and_counts(interpret, monkeypatch):
    def boom(*a, **k):
        raise RuntimeError("injected Mosaic crash")

    monkeypatch.setattr(F, "_flash_sdpa", boom)
    _reset()
    rng = np.random.RandomState(0)
    mk = lambda *s: paddle.to_tensor(  # noqa: E731
        rng.randn(*s).astype("float32"))
    q, k, v = mk(2, 2, 32, 16), mk(2, 2, 32, 16), mk(2, 2, 32, 16)
    with pytest.warns(RuntimeWarning, match="demoted to the jnp fallback"):
        out = F.scaled_dot_product_attention(q, k, v)
    assert monitor.stat_get(
        "pallas.fallback.flash_attention.RuntimeError") == 1
    assert monitor.stat_get("pallas.hit.flash_attention") == 0
    ref = F._sdpa(q, k, v, None, 16 ** -0.5, False)
    np.testing.assert_allclose(np.asarray(out._value),
                               np.asarray(ref._value), atol=1e-6)


def test_fused_ce_crash_demotes_and_counts(interpret, monkeypatch):
    def boom(*a, **k):
        raise ValueError("injected kernel failure")

    monkeypatch.setattr(F, "_fused_ce_op", boom)
    _reset()
    rng = np.random.RandomState(1)
    h = paddle.to_tensor(rng.randn(16, 8).astype("float32"),
                         stop_gradient=False)
    w = paddle.to_tensor(rng.randn(50, 8).astype("float32"),
                         stop_gradient=False)
    y = paddle.to_tensor(rng.randint(0, 50, 16).astype("int64"))
    with pytest.warns(RuntimeWarning, match="fused_ce"):
        loss = F.fused_linear_cross_entropy(h, w, None, y)
    assert monitor.stat_get("pallas.fallback.fused_ce.ValueError") == 1
    # the demoted path must still train: grads flow through the fallback
    loss.backward()
    assert np.isfinite(np.asarray(h.grad._value)).all()


def test_decode_crash_demotes_and_counts(interpret, monkeypatch):
    import paddle_tpu.ops.pallas as pallas_pkg
    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import _static_cache_attention

    def boom(*a, **k):
        raise RuntimeError("injected decode crash")

    monkeypatch.setattr(pallas_pkg, "decode_attention", boom)
    _reset()
    paddle.seed(0)
    mha = nn.MultiHeadAttention(32, 2, dropout=0.0)
    mha.eval()
    x = paddle.randn([2, 1, 32])
    cache = mha.gen_static_cache(2, 16, "float32")
    with pytest.warns(RuntimeWarning, match="decode_attention"):
        out, new_cache = mha(x, cache=cache)
    assert monitor.stat_get(
        "pallas.fallback.decode_attention.RuntimeError") == 1
    # and the fallback output is the jnp cache-attention result
    paddle.set_flags({"FLAGS_use_decode_attention": False})
    try:
        out_ref, _ = mha(x, cache=mha.gen_static_cache(2, 16, "float32"))
    finally:
        paddle.set_flags({"FLAGS_use_decode_attention": True})
    np.testing.assert_allclose(np.asarray(out._value),
                               np.asarray(out_ref._value), atol=1e-6)


def test_strict_mode_reraises(interpret, monkeypatch):
    def boom(*a, **k):
        raise RuntimeError("injected")

    monkeypatch.setattr(F, "_flash_sdpa", boom)
    paddle.set_flags({"FLAGS_pallas_strict": True})
    try:
        rng = np.random.RandomState(2)
        mk = lambda *s: paddle.to_tensor(  # noqa: E731
            rng.randn(*s).astype("float32"))
        with pytest.raises(RuntimeError, match="injected"):
            F.scaled_dot_product_attention(mk(1, 2, 32, 16),
                                           mk(1, 2, 32, 16),
                                           mk(1, 2, 32, 16))
    finally:
        paddle.set_flags({"FLAGS_pallas_strict": False})


def test_generate_completes_under_decode_crash(interpret, monkeypatch):
    """The bench decode scenario end to end: a dead decode kernel must
    still produce a correct full generation (scan included), only slower."""
    import paddle_tpu.ops.pallas as pallas_pkg
    from paddle_tpu.text.models.gpt import GPT, GPTConfig

    paddle.seed(0)
    net = GPT(GPTConfig.tiny())
    net.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 1024, (2, 5)).astype("int64"))
    want = np.asarray(net.generate(ids, max_new_tokens=6, temperature=0,
                                   use_cache=True)._value)

    def boom(*a, **k):
        raise RuntimeError("injected decode crash")

    monkeypatch.setattr(pallas_pkg, "decode_attention", boom)
    _reset()
    net.__dict__.pop("_decode_cache", None)  # force a fresh trace
    with pytest.warns(RuntimeWarning):
        got = np.asarray(net.generate(ids, max_new_tokens=6, temperature=0,
                                      use_cache=True)._value)
    assert monitor.stat_get(
        "pallas.fallback.decode_attention.RuntimeError") > 0
    np.testing.assert_array_equal(got, want)
