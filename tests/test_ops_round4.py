"""Round-4 op-library widening (VERDICT r03 item 4): the named stubs —
mode, 3-D pooling, Conv1D/3DTranspose, SpectralNorm — with the op_test
numeric-grad treatment. References: operators/mode_op, pool_op.cc (pool3d),
conv_transpose_op.cc, spectral_norm_op.cc."""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
from paddle_tpu import nn, ops
from paddle_tpu.nn import functional as F

from op_test import check_grad


# ---------------------------------------------------------------- mode ----

def test_mode_matches_torch():
    rng = np.random.RandomState(0)
    x = rng.randint(0, 5, (3, 17)).astype("float32")
    v, i = ops.mode(paddle.to_tensor(x))
    tv, _ = torch.mode(torch.tensor(x), dim=-1)
    np.testing.assert_array_equal(v.numpy(), tv.numpy())
    # returned index points at an occurrence of the mode
    picked = np.take_along_axis(x, i.numpy()[:, None].astype(int), 1)[:, 0]
    np.testing.assert_array_equal(picked, v.numpy())


def test_mode_axis_keepdim():
    rng = np.random.RandomState(1)
    x = rng.randint(0, 3, (4, 6, 5)).astype("int64")
    v, i = ops.mode(paddle.to_tensor(x), axis=1, keepdim=True)
    assert v.shape == (4, 1, 5) and i.shape == (4, 1, 5)
    tv, _ = torch.mode(torch.tensor(x), dim=1, keepdim=True)
    np.testing.assert_array_equal(v.numpy(), tv.numpy())


# ---------------------------------------------------------- 3-D pooling ----

@pytest.mark.parametrize("k,s,p", [(2, 2, 0), (3, 2, 1), ((2, 3, 2), 1, 0)])
def test_max_pool3d_matches_torch(k, s, p):
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 8, 9, 8).astype("float32")
    out = F.max_pool3d(paddle.to_tensor(x), k, stride=s, padding=p)
    ref = tF.max_pool3d(torch.tensor(x), k, stride=s, padding=p)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)


@pytest.mark.parametrize("k,s,p", [(2, 2, 0), (3, 2, 1)])
def test_avg_pool3d_matches_torch(k, s, p):
    rng = np.random.RandomState(3)
    x = rng.randn(2, 3, 8, 8, 8).astype("float32")
    out = F.avg_pool3d(paddle.to_tensor(x), k, stride=s, padding=p)
    # paddle exclusive=True == torch count_include_pad=False
    ref = tF.avg_pool3d(torch.tensor(x), k, stride=s, padding=p,
                        count_include_pad=False)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5,
                               atol=1e-6)


def test_pool3d_grads():
    rng = np.random.RandomState(4)
    x = rng.randn(1, 2, 4, 4, 4)
    check_grad(lambda t: F.avg_pool3d(t, 2), [x])
    check_grad(lambda t: F.max_pool3d(t, 2), [x])


def test_pool3d_layers_and_adaptive():
    rng = np.random.RandomState(5)
    x = paddle.to_tensor(rng.randn(2, 3, 4, 8, 8).astype("float32"))
    assert nn.MaxPool3D(2)(x).shape == (2, 3, 2, 4, 4)
    assert nn.AvgPool3D(2)(x).shape == (2, 3, 2, 4, 4)
    out = F.adaptive_avg_pool3d(x, (2, 4, 2))
    ref = tF.adaptive_avg_pool3d(torch.tensor(np.asarray(x._value)),
                                 (2, 4, 2))
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5,
                               atol=1e-6)
    assert F.adaptive_max_pool3d(x, 2).shape == (2, 3, 2, 2, 2)


# ------------------------------------------------------- conv transpose ----

@pytest.mark.parametrize("stride,pad,opad,dil,groups",
                         [(2, 1, 0, 1, 1), (3, 0, 1, 1, 1), (1, 2, 0, 2, 1),
                          (2, 1, 1, 1, 2)])
def test_conv1d_transpose_matches_torch(stride, pad, opad, dil, groups):
    rng = np.random.RandomState(6)
    x = rng.randn(2, 4, 9).astype("float32")
    w = rng.randn(4, 6 // groups, 5).astype("float32")
    b = rng.randn(6).astype("float32")
    out = F.conv1d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                             paddle.to_tensor(b), stride=stride, padding=pad,
                             output_padding=opad, dilation=dil,
                             groups=groups)
    ref = tF.conv_transpose1d(torch.tensor(x), torch.tensor(w),
                              torch.tensor(b), stride=stride, padding=pad,
                              output_padding=opad, dilation=dil,
                              groups=groups)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_conv3d_transpose_matches_torch():
    rng = np.random.RandomState(7)
    x = rng.randn(1, 3, 4, 5, 4).astype("float32")
    w = rng.randn(3, 2, 3, 3, 3).astype("float32")
    out = F.conv3d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                             stride=2, padding=1)
    ref = tF.conv_transpose3d(torch.tensor(x), torch.tensor(w), stride=2,
                              padding=1)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_conv_transpose_layers_and_grad():
    paddle.seed(0)
    rng = np.random.RandomState(8)
    layer = nn.Conv1DTranspose(3, 5, 4, stride=2, padding=1)
    x = paddle.to_tensor(rng.randn(2, 3, 6).astype("float32"))
    assert layer(x).shape == (2, 5, 12)
    layer3 = nn.Conv3DTranspose(2, 3, 3, stride=2)
    x3 = paddle.to_tensor(rng.randn(1, 2, 3, 3, 3).astype("float32"))
    assert layer3(x3).shape == (1, 3, 7, 7, 7)
    # numeric grad through x and w
    xg = rng.randn(1, 2, 5)
    wg = rng.randn(2, 3, 3)
    check_grad(lambda a, b: F.conv1d_transpose(a, b, stride=2), [xg, wg])


# --------------------------------------------------------- spectral norm ----

def test_spectral_norm_unit_sigma():
    paddle.seed(0)
    rng = np.random.RandomState(9)
    w = rng.randn(6, 4, 3, 3).astype("float32")
    sn = nn.SpectralNorm(w.shape, dim=0, power_iters=30)
    out = np.asarray(sn(paddle.to_tensor(w))._value)
    # after enough power iterations the top singular value is ~1
    mat = out.reshape(6, -1)
    assert abs(np.linalg.svd(mat, compute_uv=False)[0] - 1.0) < 1e-3
    # direction preserved: out is w / sigma
    sigma = np.linalg.svd(w.reshape(6, -1), compute_uv=False)[0]
    np.testing.assert_allclose(out, w / sigma, rtol=1e-3, atol=1e-4)


def test_spectral_norm_buffers_update_and_jit():
    import jax
    paddle.seed(0)
    rng = np.random.RandomState(10)
    w = rng.randn(5, 8).astype("float32")
    sn = nn.SpectralNorm(w.shape, dim=0, power_iters=2)
    u0 = np.asarray(sn.weight_u._value).copy()
    sn(paddle.to_tensor(w))
    assert not np.allclose(u0, np.asarray(sn.weight_u._value))

    # composes under jit via the functional engine contract
    params, buffers = sn.functional_state()

    def f(buffers, wv):
        sn.load_functional_state({}, buffers)
        out = sn(paddle.to_tensor(wv))
        return out._value, {n: b._value for n, b in sn.named_buffers()}

    out, new_bufs = jax.jit(f)(buffers, w)
    assert np.isfinite(np.asarray(out)).all()
    assert set(new_bufs) == set(buffers)
