"""Round-4 vision dataset breadth (reference vision/datasets: folder.py,
flowers.py, voc2012.py) — built against synthetic archives so the tests
run zero-egress."""
import os
import tarfile

import numpy as np
import pytest
from PIL import Image

from paddle_tpu.vision.datasets import (DatasetFolder, Flowers, ImageFolder,
                                        VOC2012)


def _png(path, color, size=(8, 6)):
    Image.new("RGB", size, color).save(path)


def test_dataset_folder(tmp_path):
    for cls, color in (("cat", (255, 0, 0)), ("dog", (0, 255, 0))):
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            _png(str(d / f"{i}.png"), color)
    ds = DatasetFolder(str(tmp_path))
    assert ds.classes == ["cat", "dog"]
    assert len(ds) == 6
    img, label = ds[0]
    assert img.shape == (6, 8, 3) and label == 0
    img, label = ds[5]
    assert label == 1 and img[0, 0, 1] == 255

    flat = ImageFolder(str(tmp_path))
    assert len(flat) == 6
    (img,) = flat[0]
    assert img.shape == (6, 8, 3)

    # transform hook
    ds2 = DatasetFolder(str(tmp_path), transform=lambda a: a.mean())
    v, _ = ds2[0]
    assert np.isscalar(v) or np.ndim(v) == 0


def test_flowers(tmp_path):
    import scipy.io
    tgz = str(tmp_path / "102flowers.tgz")
    with tarfile.open(tgz, "w:gz") as tf:
        for i in range(1, 5):
            p = str(tmp_path / f"image_{i:05d}.jpg")
            Image.new("RGB", (10, 10), (i * 20, 0, 0)).save(p)
            tf.add(p, arcname=f"jpg/image_{i:05d}.jpg")
    labels = str(tmp_path / "imagelabels.mat")
    scipy.io.savemat(labels, {"labels": np.array([[1, 2, 1, 2]])})
    setid = str(tmp_path / "setid.mat")
    scipy.io.savemat(setid, {"trnid": np.array([[1, 3]]),
                             "valid": np.array([[2]]),
                             "tstid": np.array([[4]])})
    ds = Flowers(data_file=tgz, label_file=labels, setid_file=setid,
                 mode="train")
    assert len(ds) == 2
    img, lab = ds[0]
    assert img.shape == (10, 10, 3) and int(lab[0]) == 1
    assert len(Flowers(data_file=tgz, label_file=labels,
                       setid_file=setid, mode="valid")) == 1
    with pytest.raises(RuntimeError):
        Flowers(download=True)


def test_voc2012(tmp_path):
    tar_path = str(tmp_path / "voc.tar")
    keys = ["2007_000001", "2007_000002"]
    with tarfile.open(tar_path, "w") as tf:
        lst = str(tmp_path / "train.txt")
        with open(lst, "w") as f:
            f.write("\n".join(keys) + "\n")
        tf.add(lst, arcname="VOCdevkit/VOC2012/ImageSets/Segmentation/"
               "train.txt")
        for k in keys:
            jp = str(tmp_path / f"{k}.jpg")
            Image.new("RGB", (12, 9), (1, 2, 3)).save(jp)
            tf.add(jp, arcname=f"VOCdevkit/VOC2012/JPEGImages/{k}.jpg")
            pp = str(tmp_path / f"{k}.png")
            Image.new("P", (12, 9), 0).save(pp)
            tf.add(pp, arcname="VOCdevkit/VOC2012/SegmentationClass/"
                   f"{k}.png")
    ds = VOC2012(data_file=tar_path, mode="train")
    assert len(ds) == 2
    img, lab = ds[0]
    assert img.shape == (9, 12, 3)
    assert lab.shape == (9, 12)


def test_pretrained_loads_from_cache_or_raises(tmp_path, monkeypatch):
    """pretrained=True resolves weights from the zero-egress cache and
    raises with the drop-in path when absent (was silently ignored)."""
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet18

    monkeypatch.setenv("PADDLE_TPU_WEIGHTS_DIR", str(tmp_path))
    with pytest.raises(FileNotFoundError, match="resnet18"):
        resnet18(pretrained=True)

    paddle.seed(0)
    donor = resnet18()
    from paddle_tpu.framework.io import save as fsave
    fsave(donor.state_dict(), str(tmp_path / "resnet18.pdparams"))
    loaded = resnet18(pretrained=True)
    a = dict(donor.named_parameters())
    b = dict(loaded.named_parameters())
    k = next(iter(a))
    np.testing.assert_allclose(np.asarray(a[k]._value),
                               np.asarray(b[k]._value))
