"""Round-4 transform breadth (reference vision/transforms/transforms.py:
ColorJitter, Saturation/Contrast/Hue, RandomRotation, Grayscale)."""
import numpy as np

from paddle_tpu.vision import transforms as T


def _img():
    rng = np.random.RandomState(0)
    return rng.rand(3, 8, 8).astype("float32")


def test_grayscale_matches_luma():
    img = _img()
    g = T.Grayscale()(img)
    ref = 0.299 * img[0] + 0.587 * img[1] + 0.114 * img[2]
    np.testing.assert_allclose(g[0], ref, rtol=1e-5)
    g3 = T.Grayscale(3)(img)
    assert g3.shape == (3, 8, 8)
    np.testing.assert_allclose(g3[0], g3[2])


def test_saturation_contrast_zero_value_identity():
    img = _img()
    np.testing.assert_allclose(T.SaturationTransform(0.0)(img), img,
                               rtol=1e-5)
    np.testing.assert_allclose(T.ContrastTransform(0.0)(img), img,
                               rtol=1e-5)
    np.testing.assert_allclose(T.HueTransform(0.0)(img), img, atol=1e-5)


def test_saturation_one_collapses_to_gray_at_f0():
    img = _img()
    np.random.seed(3)
    out = T.SaturationTransform(0.9)(img)
    assert out.shape == img.shape and np.isfinite(out).all()


def test_hue_preserves_luma_roughly():
    img = _img()
    np.random.seed(1)
    out = T.HueTransform(0.4)(img)
    luma_in = 0.299 * img[0] + 0.587 * img[1] + 0.114 * img[2]
    luma_out = 0.299 * out[0] + 0.587 * out[1] + 0.114 * out[2]
    np.testing.assert_allclose(luma_out, luma_in, atol=1e-4)


def test_color_jitter_runs_and_varies():
    img = _img()
    np.random.seed(2)
    jit = T.ColorJitter(brightness=0.4, contrast=0.4, saturation=0.4,
                        hue=0.2)
    out = jit(img)
    assert out.shape == img.shape
    assert not np.allclose(out, img)


def test_random_rotation():
    img = np.zeros((1, 9, 9), "float32")
    img[0, 4, :] = 1.0                       # horizontal line
    np.random.seed(0)
    rot = T.RandomRotation((90, 90))(img)    # exact 90 degrees
    # line becomes vertical
    assert rot[0, :, 4].sum() > 7
    ident = T.RandomRotation((0, 0))(img)
    np.testing.assert_allclose(ident, img, atol=1e-6)
