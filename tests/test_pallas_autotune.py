"""Shape-keyed Pallas block autotuning: measure-on-first-use, in-process +
disk caching, flag overrides winning over the table."""
import json

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.ops.pallas import autotune, flash_attention


@pytest.fixture
def tuning(tmp_path, monkeypatch):
    """Interpret-mode measuring (FLAGS_pallas_autotune_force) with a fresh
    disk cache file."""
    cache = tmp_path / "autotune.json"
    monkeypatch.setenv("PADDLE_TPU_PALLAS_AUTOTUNE_CACHE", str(cache))
    autotune.clear()
    paddle.set_flags({"FLAGS_pallas_interpret": True,
                      "FLAGS_pallas_autotune_force": True})
    yield cache
    paddle.set_flags({"FLAGS_pallas_interpret": False,
                      "FLAGS_pallas_autotune_force": False})
    autotune.clear()


def test_bucketing():
    assert autotune.bucket(1) == 1
    assert autotune.bucket(8) == 8
    assert autotune.bucket(33) == 64
    assert autotune.bucket(1000) == 1024
    assert autotune.bucket(1024) == 1024


def test_lookup_measures_once_and_round_trips_disk(tuning):
    calls = []

    def measure(params):
        calls.append(params)
        return 0.001 if params == (64, 64) else 0.5

    cands = [(128, 128), (64, 64)]
    got = autotune.lookup("test_kernel", (128, 128), "float32", cands,
                          measure, (128, 128))
    assert got == (64, 64)
    assert sorted(calls) == sorted(cands)

    # second lookup: in-process hit, no re-measure
    calls.clear()
    got = autotune.lookup("test_kernel", (128, 128), "float32", cands,
                          measure, (128, 128))
    assert got == (64, 64) and not calls

    # disk round-trip: a fresh process (cleared table) reloads the entry
    data = json.loads(tuning.read_text())
    assert any(k.startswith("test_kernel|128,128|float32|")
               for k in data["entries"])
    autotune.clear()
    got = autotune.lookup("test_kernel", (128, 128), "float32", cands,
                          measure, (128, 128))
    assert got == (64, 64) and not calls


def test_flash_attention_autotunes_and_caches(tuning):
    """flash_attention at a multi-candidate shape measures once, writes
    the disk cache, and the winner produces correct output."""
    monitor.reset("pallas.autotune.measured.flash_fwd")
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 1, 128, 16), jnp.float32)
    out1 = flash_attention(q, q, q)
    assert monitor.stat_get("pallas.autotune.measured.flash_fwd") == 1
    data = json.loads(tuning.read_text())
    assert any(k.startswith("flash_fwd|") for k in data["entries"])

    # same shape family again: table hit, no second measurement
    out2 = flash_attention(q, q, q)
    assert monitor.stat_get("pallas.autotune.measured.flash_fwd") == 1
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               atol=1e-6)


def test_flag_override_wins_over_table(tuning):
    """FLAGS_flash_block_* beats a table entry recorded for the shape."""
    seen = []
    import importlib
    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")
    real = fa._flash

    def spy(q, k, v, bias, scale, causal, heads, bq, bk, off):
        seen.append((bq, bk))
        return real(q, k, v, bias, scale, causal, heads, bq, bk, off)

    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 1, 128, 16), jnp.float32)
    flash_attention(q, q, q)  # seeds the table for this bucket
    fa._flash, orig = spy, real
    try:
        paddle.set_flags({"FLAGS_flash_block_q": 32,
                          "FLAGS_flash_block_k": 32})
        flash_attention(q, q, q)
        assert seen[-1] == (32, 32)
    finally:
        fa._flash = orig
        paddle.set_flags({"FLAGS_flash_block_q": 0,
                          "FLAGS_flash_block_k": 0})


def test_corrupt_disk_cache_is_ignored(tuning):
    tuning.write_text("{not json")
    autotune.clear()
    got = autotune.lookup("k", (8,), "float32", [(8,)], lambda p: 0.1, (8,))
    assert got == (8,)


def test_no_measure_off_tpu_without_force(tuning):
    """Without the force flag, CPU lookups return the heuristic default
    (interpret timings are meaningless)."""
    paddle.set_flags({"FLAGS_pallas_autotune_force": False})
    autotune.clear()
    calls = []
    got = autotune.lookup("k2", (64, 64), "float32", [(64, 64), (32, 32)],
                          lambda p: calls.append(p) or 0.1, (64, 64))
    assert got == (64, 64) and not calls
