"""Golden-op test harness.

TPU-native analog of the reference's OpTest
(reference: python/paddle/fluid/tests/unittests/op_test.py:232 —
check_output_with_place at :1027, check_grad numeric-vs-analytic at :1329,
get_numeric_gradient at :101). Each op is checked two ways:
  1. forward against a numpy reference callable,
  2. tape-analytic gradient against central finite differences.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle


def check_output(op_fn, np_fn, inputs, rtol=1e-5, atol=1e-6, **attrs):
    tensors = [paddle.to_tensor(v) for v in inputs]
    out = op_fn(*tensors, **attrs)
    ref = np_fn(*inputs, **attrs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    refs = ref if isinstance(ref, (tuple, list)) else [ref]
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(o.numpy(), r, rtol=rtol, atol=atol)


def numeric_grad(op_fn, inputs, wrt, delta=1e-3, **attrs):
    """Central finite differences of sum(op(x)) w.r.t. inputs[wrt]."""
    base = [np.array(v, dtype="float64") for v in inputs]

    def f(vals):
        ts = [paddle.to_tensor(v.astype("float64")) for v in vals]
        out = op_fn(*ts, **attrs)
        outs = out if isinstance(out, (tuple, list)) else [out]
        return float(sum(o.numpy().astype("float64").sum() for o in outs
                         if np.issubdtype(o.numpy().dtype, np.floating)))

    x = base[wrt]
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        orig = x[idx]
        x[idx] = orig + delta
        fp = f(base)
        x[idx] = orig - delta
        fm = f(base)
        x[idx] = orig
        g[idx] = (fp - fm) / (2 * delta)
        it.iternext()
    return g


def check_grad(op_fn, inputs, wrt=None, rtol=2e-3, atol=2e-4, delta=1e-3,
               **attrs):
    """Compare tape-analytic grads against finite differences (float64)."""
    wrt = wrt if wrt is not None else list(range(len(inputs)))
    tensors = [paddle.to_tensor(np.array(v, dtype="float64"),
                                stop_gradient=False) for v in inputs]
    out = op_fn(*tensors, **attrs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    loss = None
    for o in outs:
        if np.issubdtype(o.numpy().dtype, np.floating):
            s = o.sum()
            loss = s if loss is None else loss + s
    loss.backward()
    for i in wrt:
        analytic = tensors[i].grad.numpy()
        numeric = numeric_grad(op_fn, inputs, i, delta=delta, **attrs)
        np.testing.assert_allclose(
            analytic, numeric, rtol=rtol, atol=atol,
            err_msg=f"grad mismatch for input {i} of "
                    f"{getattr(op_fn, 'op_name', op_fn)}")
