"""Control-flow-capable to_static (VERDICT r04 item 2).

Reference analog: python/paddle/fluid/dygraph/dygraph_to_static/
(ifelse_transformer.py, loop_transformer.py, logical_transformer.py,
program_translator.py). The 'Done' criterion: a model with a
data-dependent branch and loop converts, saves, reloads, and matches
eager numerically.
"""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn, ops
from paddle_tpu.jit.dy2static import Dy2StaticError, convert_function


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


# ---------------------------------------------------------------------------
# function-level conversion, eager + traced
# ---------------------------------------------------------------------------

def branchy(x):
    if x.mean() > 0:
        y = x * 2.0
    else:
        y = x - 1.0
    return y


def test_if_data_dependent_eager_and_traced():
    pos = paddle.to_tensor(np.full((2, 3), 2.0, "float32"))
    neg = paddle.to_tensor(np.full((2, 3), -2.0, "float32"))
    st = jit.to_static(branchy)
    for x, want in ((pos, _np(pos) * 2), (neg, _np(neg) - 1)):
        np.testing.assert_allclose(_np(branchy(x)), want)       # eager
        np.testing.assert_allclose(_np(st(x)), want)            # jax.jit

    conv = convert_function(branchy)
    for x, want in ((pos, _np(pos) * 2), (neg, _np(neg) - 1)):
        np.testing.assert_allclose(_np(conv(x)), want)          # converted,
        # eager values: plain python branch


def loopy(x):
    s = paddle.to_tensor(np.zeros((), "float32"))
    i = 0
    while i < x.shape[0]:        # static bound: python loop under trace
        s = s + x[i].sum()
        i += 1
    return s


def test_while_static_bound_unchanged():
    x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(3, 2))
    conv = convert_function(loopy)
    np.testing.assert_allclose(_np(conv(x)), _np(loopy(x)))


def data_dep_loop(x):
    # keep doubling until the sum exceeds 100: a genuinely data-dependent
    # trip count
    n = paddle.to_tensor(np.zeros((), "int32"))
    while x.sum() < 100.0:
        x = x * 2.0
        n = n + 1
    return x, n


def test_while_data_dependent_traced():
    x0 = np.full((4,), 1.0, "float32")
    st = jit.to_static(data_dep_loop)
    out, n = st(paddle.to_tensor(x0))
    # eager reference
    eo, en = data_dep_loop(paddle.to_tensor(x0))
    np.testing.assert_allclose(_np(out), _np(eo))
    assert int(_np(n)) == int(_np(en)) == 5   # sum 4*2^5 = 128 >= 100


def test_for_range_semantics_preserved():
    def f(x):
        acc = x * 0.0
        for i in range(3):
            acc = acc + x * float(i + 1)
        return acc, i

    x = paddle.to_tensor(np.ones((2,), "float32"))
    conv = convert_function(f)
    out, i = conv(x)
    np.testing.assert_allclose(_np(out), np.full((2,), 6.0, "float32"))
    assert i == 2  # python for leaves the target at the last iterate


def test_bool_ops_on_tensors():
    def f(x):
        if (x.mean() > 0) and (x.max() < 10):
            return x + 1.0
        else:
            return x - 1.0

    x = paddle.to_tensor(np.full((3,), 2.0, "float32"))
    big = paddle.to_tensor(np.full((3,), 50.0, "float32"))
    st = jit.to_static(f)
    np.testing.assert_allclose(_np(st(x)), _np(x) + 1)
    np.testing.assert_allclose(_np(st(big)), _np(big) - 1)


def test_early_return_no_else():
    def f(x):
        if x.mean() > 0:
            return x + 1.0
        return x - 1.0

    st = jit.to_static(f)
    pos = paddle.to_tensor(np.full((3,), 2.0, "float32"))
    neg = paddle.to_tensor(np.full((3,), -2.0, "float32"))
    np.testing.assert_allclose(_np(st(pos)), 3.0)
    np.testing.assert_allclose(_np(st(neg)), -3.0)


def test_early_return_with_trailing_code():
    def f(x):
        if x.mean() > 0:
            y = x * 2.0
            return y
        z = x * 3.0
        z = z + 1.0
        return z

    st = jit.to_static(f)
    pos = paddle.to_tensor(np.full((3,), 2.0, "float32"))
    neg = paddle.to_tensor(np.full((3,), -2.0, "float32"))
    np.testing.assert_allclose(_np(st(pos)), 4.0)
    np.testing.assert_allclose(_np(st(neg)), -5.0)


def test_static_python_branch_still_works():
    def f(x, flag=True):
        if flag:                 # plain python predicate: untouched path
            return x * 3.0
        return x

    conv = convert_function(f)
    x = paddle.to_tensor(np.ones((2,), "float32"))
    np.testing.assert_allclose(_np(conv(x)), 3 * _np(x))
    np.testing.assert_allclose(_np(conv(x, flag=False)), _np(x))


def test_nested_if_in_while():
    def f(x):
        i = 0
        s = x * 0.0
        while i < 4:
            if x.mean() > 0:
                s = s + x
            else:
                s = s - x
            i += 1
        return s

    st = jit.to_static(f)
    pos = paddle.to_tensor(np.full((2,), 1.0, "float32"))
    neg = paddle.to_tensor(np.full((2,), -1.0, "float32"))
    np.testing.assert_allclose(_np(st(pos)), np.full((2,), 4.0))
    np.testing.assert_allclose(_np(st(neg)), np.full((2,), 4.0))


def test_branch_mismatch_raises():
    def f(x):
        if x.mean() > 0:
            tag = "pos"
        else:
            tag = "neg"
        return x, tag

    st = jit.to_static(f)
    with pytest.raises(Exception, match="non-tensor|structure|branch"):
        st(paddle.to_tensor(np.ones((2,), "float32")))


# ---------------------------------------------------------------------------
# the VERDICT 'Done' criterion: Layer with branch + loop -> save -> load
# ---------------------------------------------------------------------------

class DynamicNet(nn.Layer):
    """Data-dependent branch AND loop in forward."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 4)

    def forward(self, x):
        h = self.fc(x)
        if h.mean() > 0:          # data-dependent branch
            h = ops.relu(h)
        else:
            h = h * 0.5
        i = 0
        while i < 3:              # loop (static trip count, still converted)
            h = h + 0.1
            i += 1
        return h


def test_layer_save_load_numeric_match():
    paddle.seed(0)
    net = DynamicNet()
    net.eval()
    xs = [np.random.RandomState(s).randn(2, 4).astype("float32") * sign
          for s, sign in ((0, 1.0), (1, -1.0))]

    eager = [_np(net(paddle.to_tensor(x))) for x in xs]

    d = tempfile.mkdtemp()
    path = os.path.join(d, "dyn")
    jit.save(net, path, input_spec=[jit.InputSpec([2, 4], "float32", "x")])
    loaded = jit.load(path)
    for x, want in zip(xs, eager):
        got = _np(loaded(paddle.to_tensor(x)))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class LoopNet(nn.Layer):
    """Data-dependent trip count through save/load."""

    def __init__(self):
        super().__init__()
        self.scale = self.create_parameter(
            [1], default_initializer=nn.initializer.Constant(2.0))

    def forward(self, x):
        s = x
        while s.sum() < 50.0:
            s = s * self.scale
        return s


def test_layer_data_dependent_loop_save_load():
    net = LoopNet()
    net.eval()
    x = np.full((2, 2), 1.0, "float32")
    want = _np(net(paddle.to_tensor(x)))
    assert float(want.sum()) >= 50.0

    d = tempfile.mkdtemp()
    path = os.path.join(d, "loopnet")
    jit.save(net, path, input_spec=[jit.InputSpec([2, 2], "float32", "x")])
    loaded = jit.load(path)
    got = _np(loaded(paddle.to_tensor(x)))
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # different magnitude input takes a different trip count
    x2 = np.full((2, 2), 4.0, "float32")
    np.testing.assert_allclose(_np(loaded(paddle.to_tensor(x2))),
                               _np(net(paddle.to_tensor(x2))), rtol=1e-5)


def test_elif_chain_tensor_conds():
    def f(x):
        if x.mean() > 1.0:
            return x * 10.0
        elif x.mean() > 0.0:
            return x + 100.0
        return x - 1000.0

    st = jit.to_static(f)
    big = paddle.to_tensor(np.full((2,), 2.0, "float32"))
    mid = paddle.to_tensor(np.full((2,), 0.5, "float32"))
    low = paddle.to_tensor(np.full((2,), -1.0, "float32"))
    np.testing.assert_allclose(_np(st(big)), 20.0)
    np.testing.assert_allclose(_np(st(mid)), 100.5)
    np.testing.assert_allclose(_np(st(low)), -1001.0)


def test_tuple_valued_local_through_branch():
    def f(x):
        if x.mean() > 0:
            pair = (x * 2.0, x + 1.0)
        else:
            pair = (x * 3.0, x - 1.0)
        return pair[0] + pair[1]

    st = jit.to_static(f)
    pos = paddle.to_tensor(np.full((2,), 1.0, "float32"))
    neg = paddle.to_tensor(np.full((2,), -1.0, "float32"))
    np.testing.assert_allclose(_np(st(pos)), 4.0)   # 2 + 0... 2x+x+1 = 4
    np.testing.assert_allclose(_np(st(neg)), -5.0)  # -3 + -2


def test_closure_capture_preserved():
    scale = 3.0
    offset = paddle.to_tensor(np.full((2,), 10.0, "float32"))

    def f(x):
        if x.mean() > 0:
            y = x * scale + offset
        else:
            y = x * scale - offset
        return y

    st = jit.to_static(f)
    pos = paddle.to_tensor(np.full((2,), 2.0, "float32"))
    np.testing.assert_allclose(_np(st(pos)), 16.0)


def test_super_call_survives_conversion():
    class Base(nn.Layer):
        def forward(self, x):
            return x * 2.0

    class Child(Base):
        def forward(self, x):
            h = super().forward(x)   # zero-arg super needs __class__ cell
            if h.mean() > 0:
                h = h + 1.0
            else:
                h = h - 1.0
            return h

    net = Child()
    st = jit.to_static(net)
    pos = paddle.to_tensor(np.full((2,), 1.0, "float32"))
    neg = paddle.to_tensor(np.full((2,), -1.0, "float32"))
    np.testing.assert_allclose(_np(st(pos)), 3.0)
    np.testing.assert_allclose(_np(st(neg)), -3.0)


def test_while_tensor_accumulator_with_aux_string():
    def f(x):
        tag = "iter"          # loop-invariant aux value: allowed
        i = 0
        while i < 3:
            x = x + 1.0
            i += 1
        assert tag == "iter"
        return x

    st = jit.to_static(f)
    np.testing.assert_allclose(
        _np(st(paddle.to_tensor(np.zeros((2,), "float32")))), 3.0)


def test_for_else_clause():
    def f(x):
        for i in range(2):
            x = x + 1.0
        else:
            x = x * 10.0
        return x

    conv = convert_function(f)
    np.testing.assert_allclose(
        _np(conv(paddle.to_tensor(np.zeros((2,), "float32")))), 20.0)


class ElifNet(nn.Layer):
    """elif chain + early returns through the STATIC (jit.save) path."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 4)

    def forward(self, x):
        h = self.fc(x)
        if h.mean() > 1.0:
            return h * 10.0
        elif h.mean() > 0.0:
            return h + 100.0
        return h - 1000.0


def test_elif_chain_save_load():
    paddle.seed(0)
    net = ElifNet()
    net.eval()
    xs = [np.full((2, 4), v, "float32") for v in (5.0, 0.05, -5.0)]
    want = [_np(net(paddle.to_tensor(x))) for x in xs]
    d = tempfile.mkdtemp()
    path = os.path.join(d, "elif")
    jit.save(net, path, input_spec=[jit.InputSpec([2, 4], "float32", "x")])
    loaded = jit.load(path)
    for x, w in zip(xs, want):
        np.testing.assert_allclose(_np(loaded(paddle.to_tensor(x))), w,
                                   rtol=1e-5, atol=1e-5)


class GatedBlock(nn.Layer):
    """Control flow lives in a SUBLAYER's forward."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 4)

    def forward(self, x):
        h = self.fc(x)
        if h.mean() > 0:
            return h * 2.0
        return h * 0.5


class OuterNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.block = GatedBlock()
        self.head = nn.Linear(4, 2)

    def forward(self, x):
        return self.head(self.block(x))


def test_sublayer_control_flow_converts_and_saves():
    """convert_layer recurses (reference convert_call): tensor branches in
    sublayers convert for both to_static and jit.save; export leaves no
    instance-forward overrides behind."""
    paddle.seed(0)
    net = OuterNet()
    net.eval()
    xs = [np.random.RandomState(0).randn(2, 4).astype("float32"),
          -np.abs(np.random.RandomState(1).randn(2, 4)).astype("float32")
          * 3.0]
    want = [_np(net(paddle.to_tensor(x))) for x in xs]

    st = jit.to_static(net)
    for x, w in zip(xs, want):
        np.testing.assert_allclose(_np(st(paddle.to_tensor(x))), w,
                                   rtol=1e-5, atol=1e-6)

    paddle.seed(0)
    net2 = OuterNet()
    net2.eval()
    d = tempfile.mkdtemp()
    path = os.path.join(d, "sub")
    jit.save(net2, path, input_spec=[jit.InputSpec([2, 4], "float32", "x")])
    # save undid every instance-level forward it installed
    assert "forward" not in net2.__dict__
    assert "forward" not in net2.block.__dict__
    loaded = jit.load(path)
    for x, w in zip(xs, want):
        np.testing.assert_allclose(_np(loaded(paddle.to_tensor(x))), w,
                                   rtol=1e-5, atol=1e-6)


# module global used by test_monkeypatch_after_convert
_GLOBAL_SCALE = 2.0


def _scaled_branch(x):
    if x.mean() > 0:
        y = x * _GLOBAL_SCALE
    else:
        y = x - _GLOBAL_SCALE
    return y


def test_monkeypatch_after_convert():
    """Pins the chosen globals semantics (docs/dy2static.md): _convert
    execs against the LIVE fn.__globals__, so monkeypatching a module
    global after conversion is observed by the converted function — and
    the __jst__ helper never leaks into this module's namespace."""
    global _GLOBAL_SCALE
    conv = convert_function(_scaled_branch)
    assert conv is not _scaled_branch
    x = paddle.to_tensor(np.ones((2, 2), "float32"))
    np.testing.assert_allclose(_np(conv(x)), np.full((2, 2), 2.0))
    old = _GLOBAL_SCALE
    try:
        _GLOBAL_SCALE = 5.0
        np.testing.assert_allclose(_np(conv(x)), np.full((2, 2), 5.0))
    finally:
        _GLOBAL_SCALE = old
    # collision safety: conversion must not plant helpers in user globals
    assert "__jst__" not in globals()
    assert "__jst_factory__" not in globals()
