"""Legacy paddle.dataset + paddle.reader compat (VERDICT r04 item 10;
reference python/paddle/dataset/mnist.py, python/paddle/reader/
decorator.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import reader


def test_reader_decorators_compose():
    def r():
        yield from range(10)

    def r2():
        yield from range(10, 20)

    assert list(reader.firstn(r, 3)()) == [0, 1, 2]
    assert list(reader.chain(r, r2)()) == list(range(20))
    assert sorted(reader.shuffle(r, 4)()) == list(range(10))
    assert list(reader.map_readers(lambda a, b: a + b, r, r2)()) == \
        [i + j for i, j in zip(range(10), range(10, 20))]
    assert list(reader.compose(r, r2)()) == list(zip(range(10),
                                                     range(10, 20)))
    assert sorted(reader.buffered(r, 2)()) == list(range(10))
    c = reader.cache(r)
    assert list(c()) == list(range(10)) and list(c()) == list(range(10))
    got = sorted(reader.xmap_readers(lambda x: x * 2, r, 2, 4)())
    assert got == [2 * i for i in range(10)]
    ordered = list(reader.xmap_readers(lambda x: x * 2, r, 3, 4,
                                       order=True)())
    assert ordered == [2 * i for i in range(10)]
    assert sorted(reader.multiprocess_reader([r, r2])()) == list(range(20))


def test_compose_not_aligned():
    def short():
        yield from range(3)

    def long():
        yield from range(5)

    with pytest.raises(reader.ComposeNotAligned):
        list(reader.compose(short, long)())
    # unchecked mode just truncates
    assert len(list(reader.compose(short, long,
                                   check_alignment=False)())) == 3


def test_dataset_mnist_reader():
    from paddle_tpu import dataset
    it = dataset.mnist.train()()
    img, lab = next(it)
    assert img.shape == (784,) and img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert 0 <= lab <= 9


def test_dataset_uci_and_imdb():
    from paddle_tpu import dataset
    x, y = next(dataset.uci_housing.train()())
    assert x.shape == (13,) and y.shape == (1,)
    ids, lab = next(dataset.imdb.train()())
    assert isinstance(ids, list) and lab in (0, 1)


def test_dataset_with_reader_pipeline():
    """The fluid-era idiom end-to-end: shuffled, batched reader feeding
    a train loop."""
    from paddle_tpu import dataset
    r = reader.buffered(reader.shuffle(
        reader.firstn(dataset.uci_housing.train(), 32), 16), 4)
    xs = [x for x, _ in r()]
    assert len(xs) == 32


def test_tensor_namespace_layout():
    """paddle.tensor module layout parity (reference python/paddle/tensor/:
    creation/manipulation/math/linalg/logic/random/search/stat)."""
    import paddle_tpu.tensor as T
    from paddle_tpu.tensor.creation import full
    import paddle_tpu.tensor.math  # noqa: F401

    out = full([2, 2], 3.0)
    assert np.asarray(out.numpy()).tolist() == [[3.0, 3.0], [3.0, 3.0]]
    assert T.random.rand([3]).shape == (3,)
    assert hasattr(T.search, "topk") and hasattr(T.stat, "mean")
    assert hasattr(T, "manipulation") and hasattr(T, "linalg")
    # functions also live flat on the namespace, as in the reference
    assert hasattr(T, "concat") and hasattr(T, "matmul")


def test_paddle_batch_root_api():
    """paddle.batch parity (reference python/paddle/batch.py:18)."""
    def r():
        yield from range(7)

    batches = list(paddle.batch(r, 3)())
    assert batches == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(paddle.batch(r, 3, drop_last=True)()) == \
        [[0, 1, 2], [3, 4, 5]]
