"""Static control flow: while_loop / cond (VERDICT r02 item 8; reference
operators/controlflow/ + fluid/layers/control_flow.py)."""
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, ops


def _static():
    import paddle_tpu.static as static
    paddle.enable_static()
    return static


def test_while_loop_executor_run():
    static = _static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4], "float32")
            i = ops.zeros([], "int32")
            n = ops.full([], 5, "int32")

            def cond_fn(i, acc):
                return ops.less_than(i, n)

            def body_fn(i, acc):
                return i + 1, acc * 2.0

            _, acc = static.nn.while_loop(cond_fn, body_fn, [i, x])
        exe = static.Executor()
        xs = np.array([1, 2, 3, 4], "float32")
        out = exe.run(main, feed={"x": xs}, fetch_list=[acc])[0]
        np.testing.assert_allclose(out, xs * 32.0)  # doubled 5 times
    finally:
        paddle.disable_static()


def test_while_loop_shape_invariant_error():
    static = _static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4], "float32")
            i = ops.zeros([], "int32")
            with pytest.raises(ValueError, match="shape invariant"):
                static.nn.while_loop(
                    lambda i, a: ops.less_than(i, ops.full([], 3, "int32")),
                    lambda i, a: (i + 1, ops.concat([a, a])),  # grows!
                    [i, x])
    finally:
        paddle.disable_static()


def test_cond_executor_run_and_grad():
    """cond through Executor.run with a backward section: grads flow
    through the taken branch."""
    static = _static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [4], "float32")
            lin = nn.Linear(4, 4)
            h = lin(x)
            flag = ops.sum(h) > 0.0

            def t():
                return ops.sum(h * 2.0)

            def f():
                return ops.sum(h * -3.0)

            loss = static.nn.cond(flag, t, f)
            opt = optimizer.SGD(learning_rate=0.1)
            opt.minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        xs = np.ones(4, "float32")
        w0 = np.array(static.global_scope().get(lin.weight.scope_name))
        l0 = exe.run(main, feed={"x": xs}, fetch_list=[loss])[0]
        w1 = np.array(static.global_scope().get(lin.weight.scope_name))
        assert not np.allclose(w0, w1)  # gradient actually applied
        l1 = exe.run(main, feed={"x": xs}, fetch_list=[loss])[0]
        assert float(l1) != float(l0)
    finally:
        paddle.disable_static()


def test_cond_branch_mismatch_error():
    static = _static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4], "float32")
            p = ops.sum(x) > 0.0
            with pytest.raises(ValueError, match="mismatch"):
                static.nn.cond(p, lambda: ops.sum(x),
                               lambda: ops.reshape(x, [2, 2]))
    finally:
        paddle.disable_static()


def test_while_loop_bounded_differentiable():
    """maximum_trip_count lowers to a masked scan, so the loop
    differentiates: minimize f(w) = (w * 2^k - 8)^2 over scalar w."""
    static = _static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [1], "float32")
            lin = nn.Linear(1, 1, bias_attr=False)
            w = lin(x)  # scalar-ish [1,1]
            i = ops.zeros([], "int32")
            three = ops.full([], 3, "int32")

            def cond_fn(i, v):
                return ops.less_than(i, three)

            def body_fn(i, v):
                return i + 1, v * 2.0

            _, out = static.nn.while_loop(cond_fn, body_fn, [i, w],
                                          maximum_trip_count=4)
            loss = ops.mean((out - 8.0) ** 2)
            opt = optimizer.SGD(learning_rate=0.005)  # stability: lr < 2/128
            opt.minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        xs = np.ones((1, 1), "float32")
        losses = [float(exe.run(main, feed={"x": xs},
                                fetch_list=[loss])[0])
                  for _ in range(40)]
        assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])
    finally:
        paddle.disable_static()


def test_beam_search_style_decode():
    """Greedy iterative decode: repeatedly pick argmax score, accumulate
    one-hot history — the control-flow shape of beam search (reference
    dynamic decode, fluid/layers/rnn.py)."""
    static = _static()
    try:
        main = static.Program()
        with static.program_guard(main):
            logits = static.data("logits", [6, 8], "float32")  # [steps, V]
            i = ops.zeros([], "int32")
            steps = ops.full([], 6, "int32")
            chosen = ops.zeros([6], "int64")
            score = ops.zeros([], "float32")

            def cond_fn(i, chosen, score):
                return ops.less_than(i, steps)

            def body_fn(i, chosen, score):
                row = ops.gather(logits, ops.reshape(i, [1]))  # [1, 8]
                tok = ops.reshape(ops.argmax(row, axis=-1), [])
                s = ops.reshape(ops.max(row), [])
                onehot = (ops.arange(6, dtype="int32") ==
                          ops.reshape(i, [1])).astype("int64")
                return (i + 1,
                        chosen + onehot * tok.astype("int64"),
                        score + s)

            _, chosen_f, score_f = static.nn.while_loop(
                cond_fn, body_fn, [i, chosen, score])
        exe = static.Executor()
        L = np.random.RandomState(0).randn(6, 8).astype("float32")
        toks, sc = exe.run(main, feed={"logits": L},
                           fetch_list=[chosen_f, score_f])
        np.testing.assert_array_equal(toks, L.argmax(-1))
        np.testing.assert_allclose(sc, L.max(-1).sum(), rtol=1e-5)
    finally:
        paddle.disable_static()


def test_while_program_pickles():
    """Control-flow ops serialize structurally with the Program (the
    reference pickles sub-blocks inside the ProgramDesc)."""
    static = _static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2], "float32")
            i = ops.zeros([], "int32")
            lim = ops.full([], 4, "int32")
            _, y = static.nn.while_loop(
                lambda i, v: ops.less_than(i, lim),
                lambda i, v: (i + 1, v + 1.0), [i, x])
        blob = pickle.dumps(main)
        main2 = pickle.loads(blob)
        exe = static.Executor()
        out = exe.run(main2, feed={"x": np.zeros(2, "float32")},
                      fetch_list=[y.name])[0]
        np.testing.assert_allclose(out, [4.0, 4.0])
    finally:
        paddle.disable_static()


def test_dygraph_fallback():
    i = paddle.to_tensor(np.int32(0))
    x = paddle.to_tensor(np.float32(1.0))
    import paddle_tpu.static as static
    i_f, x_f = static.nn.while_loop(
        lambda i, v: i < 3, lambda i, v: (i + 1, v * 2.0), [i, x])
    assert float(x_f.numpy()) == 8.0
    out = static.nn.cond(paddle.to_tensor(True), lambda: 1, lambda: 2)
    assert out == 1
