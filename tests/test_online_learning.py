"""Online-learning loop (ISSUE 14): serve -> train -> publish -> serve.

The closed loop (docs/online_learning.md) is three seams proven here:

1. ServeLoop emits a structured completion record at retire
   (ServeRequest.completion_record via on_complete) and a
   dataset/streaming.StreamingDataset turns the at-least-once record
   feed into exactly-once training batches relative to its checkpoint
   cut — dedupe window, bounded queue backpressure, scripted backlog
   bursts that pause WITHOUT dropping.
2. The continuous Downpour trainer (static/executor.py ps_config
   mode="online") accumulates local deltas and pushes them through
   PSClient.push_sparse_delta under replay-stable request keys — a
   flush whose ack was lost resends the FROZEN payload under the same
   key and dedupes server-side, including across a failover re-route to
   a promoted backup and across a trainer restart that restored the
   replay identity.
3. EmbeddingSnapshotPublisher cuts versioned snapshots out of the
   replica tier's consistent fetch and ServeLoop.publish_weights
   hot-swaps them between decode beats: in-flight streams finish on the
   version pinned at first admission, the pool never drops a request.

THE acceptance proof (`test_online_learning_chaos_drill`): live serve
traffic from a tiny GPT measurably shifts the served model — a
versioned eval metric strictly decreases across >=3 hot-swapped
snapshot versions — under seeded RESET+DROP chaos, a PERMANENT mid-run
shard-primary kill, and a mid-run trainer restart onto a fresh PSClient
with restored replay identity; per-server `table.applied` matches the
deterministic flush schedule replayed against the membership timeline
EXACTLY, and zero serve requests are dropped.
"""
import itertools
import json
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.core import monitor
from paddle_tpu.dataset import StreamingDataset
from paddle_tpu.distributed.ps import (EmbeddingPrefetcher,
                                       EmbeddingSnapshotPublisher,
                                       HeterPSCache, PSClient, PSServer,
                                       ShardMap)
from paddle_tpu.inference import ServeConfig, ServeLoop
from paddle_tpu.testing import faults
from paddle_tpu.text.models.gpt import GPT, GPTConfig

HID = 64          # GPTConfig.tiny() hidden size == PS table dim
VOCAB = 1024      # GPTConfig.tiny() vocab == embedding rows

FAST = dict(timeout=2.0, max_retries=2, backoff_base=0.01,
            backoff_max=0.05, connect_retry_s=5.0)
HB = dict(heartbeat_s=0.1, heartbeat_timeout_s=0.7)

# the direction serve traffic should pull the embedding: a fixed,
# deterministic per-id target row (the drill's eval metric is distance
# to it)
TARGET = np.random.RandomState(77).uniform(
    -0.5, 0.5, (VOCAB, HID)).astype(np.float32)


def _geo_specs(dim):
    return {"wte": {"type": "geo_sparse", "dim": dim, "init": "zeros"}}


def _cluster(n=3, k=1, dim=HID):
    servers = [PSServer("127.0.0.1:0", _geo_specs(dim)) for _ in range(n)]
    eps = [s.start() for s in servers]
    smap = ShardMap.create(eps, n_backups=k)
    for s in servers:
        s.enable_replication(shard_map=smap, peers=eps, n_backups=k,
                             rpc_opts=dict(FAST), **HB)
    return servers, eps


def _teardown(servers, *closers):
    for c in closers:
        try:
            c.close()
        except Exception:
            pass
    for s in servers:
        s.shutdown()


def _await_promotion(client, dead_ep, deadline=15.0):
    """Poll until the client's shard map adopts the epoch without
    `dead_ep` (heartbeat suspicion -> backup promotion)."""
    t0 = time.perf_counter()
    last = None
    while time.perf_counter() - t0 < deadline:
        try:
            client.refresh_shard_map()
        except Exception as e:  # a dead peer mid-refresh; keep polling
            last = e
        if dead_ep not in client.shard_map.servers:
            return
        time.sleep(0.1)
    raise AssertionError(f"no promotion after {dead_ep} died ({last!r})")


def _delta(before, name):
    return monitor.stat_get(name) - before.get(name, 0)


@pytest.fixture(autouse=True)
def _no_leftover_injector():
    yield
    faults.uninstall()


@pytest.fixture(autouse=True)
def _dynamic_after():
    yield
    paddle.disable_static()


@pytest.fixture(scope="module")
def net():
    paddle.seed(0)
    m = GPT(GPTConfig.tiny())
    m.eval()
    yield m
    # This module is the first heavy GPT/jit user in the suite's
    # alphabetical order; drop its compiled graphs so the heartbeat-timed
    # chaos drills in test_ps_sharded_embedding.py don't inherit the
    # memory/GC pressure.
    del m
    import gc
    import jax
    jax.clear_caches()
    gc.collect()


# ---------------------------------------------------------------------------
# satellite 1: retire-time completion-record seam
# ---------------------------------------------------------------------------

def test_completion_record_seam(net):
    recs = []
    loop = ServeLoop(net, ServeConfig(max_active=2, kv_blocks=16,
                                      block_size=16, max_seq_len=64),
                     on_complete=recs.append)
    prompts = [np.array([1, 2, 3], np.int64),
               np.array([7, 8, 9, 10], np.int64)]
    reqs = [loop.submit(p, max_new_tokens=4) for p in prompts]
    loop.run_until_idle()

    assert len(recs) == 2
    for p, req in zip(prompts, reqs):
        rec = next(r for r in recs if r["rid"] == req.rid)
        assert rec["prompt"] == [int(t) for t in p]
        assert rec["tokens"] == req.result(timeout=0).tolist()
        assert rec["version"] == 0          # pinned at first admission
        assert rec["preemptions"] == 0
        assert rec["t_submit"] <= rec["t_first"] <= rec["t_done"]
        assert rec["ttft_s"] > 0 and rec["per_token_s"] > 0
        json.dumps(rec)   # host ints/floats only — queueable as-is


def test_completion_hook_errors_are_contained(net):
    def bad_hook(rec):
        raise RuntimeError("log sink down")

    loop = ServeLoop(net, ServeConfig(max_active=2, kv_blocks=16,
                                      block_size=16, max_seq_len=64),
                     on_complete=bad_hook)
    before = monitor.stats("serve.")
    outs = loop.serve([[1, 2], [3, 4, 5]], max_new_tokens=3)
    # a broken completion sink must never fail serving
    assert all(len(o) == 3 for o in outs)
    assert _delta(before, "serve.completion_log_errors") == 2
    assert _delta(before, "serve.requests_errored") == 0


# ---------------------------------------------------------------------------
# StreamingDataset: dedupe window, checkpoint cut, backpressure
# ---------------------------------------------------------------------------

def _rec(rid):
    return {"rid": rid, "prompt": [rid], "tokens": [rid + 1]}


def test_streaming_dedupe_and_checkpoint_cut():
    ds = StreamingDataset(batch_size=4, name="s-cut")
    for rid in range(10):
        assert ds.offer(_rec(rid))        # accepted
        assert not ds.offer(_rec(rid))    # at-least-once duplicate
    st = ds.stats()
    assert (st["accepted"], st["duplicates"], st["watermark"]) == (10, 10, 9)

    gen = ds.batches()
    got = [r["rid"] for r in next(gen)] + [r["rid"] for r in next(gen)]
    assert got == list(range(8))

    # checkpoint cut: buffer, window and cursor move to a fresh instance
    snap = ds.state_dict()
    ds2 = StreamingDataset(batch_size=4, name="s-cut2")
    ds2.load_state_dict(snap)
    with pytest.raises(ValueError):
        next(ds2.batches(start_batch=0))  # out-of-sync resume is loud
    assert not ds2.offer(_rec(3))         # window survives the cut
    ds2.close()
    tail = [[r["rid"] for r in b] for b in ds2.batches(start_batch=2)]
    assert tail == [[8, 9]]               # final partial batch, no loss
    assert ds2.stats()["delivered_records"] == 10


def test_streaming_backpressure_bounds_the_queue():
    ds = StreamingDataset(batch_size=1, capacity=2, name="s-cap")
    assert ds.offer(_rec(0)) and ds.offer(_rec(1))
    t0 = time.perf_counter()
    assert not ds.offer(_rec(2), timeout=0.05)   # blocks, then rejects
    assert time.perf_counter() - t0 >= 0.04
    assert ds.stats()["rejected_full"] == 1
    next(ds.batches())                            # free one slot
    assert ds.offer(_rec(2), timeout=0.05)


# satellite 2: scripted backlog burst — pause/resume, never drop
def test_backlog_burst_pauses_without_drop():
    ds = StreamingDataset(batch_size=1, name="s-burst")
    for rid in range(6):
        ds.offer(_rec(rid))
    ds.close()
    with faults.inject(faults.backlog_burst(name="s-burst", after=1,
                                            times=2, delay=0.15)) as inj:
        t0 = time.perf_counter()
        got = [b[0]["rid"] for b in ds.batches()]
        burst_s = time.perf_counter() - t0
    assert got == list(range(6))          # every record, in order
    assert inj.fired(faults.STALL) == 2
    assert burst_s >= 0.3                 # delivery actually paused
    assert ds.stats()["delivery_faults"] == 0

    # chaos RESET at the deliver boundary is absorbed, not a drop
    ds2 = StreamingDataset(batch_size=2, name="s-reset")
    for rid in range(4):
        ds2.offer(_rec(rid))
    ds2.close()
    with faults.inject(faults.Fault("stream", "deliver", faults.RESET,
                                    method="s-reset", times=3)):
        got = [[r["rid"] for r in b] for b in ds2.batches()]
    assert got == [[0, 1], [2, 3]]
    assert ds2.stats()["delivery_faults"] == 3


# ---------------------------------------------------------------------------
# zero-downtime hot-swap: drain barrier + version pinning
# ---------------------------------------------------------------------------

def test_hot_swap_drains_pins_and_redirects():
    paddle.seed(0)
    m = GPT(GPTConfig.tiny())
    m.eval()
    recs = []
    loop = ServeLoop(m, ServeConfig(max_active=2, kv_blocks=24,
                                    block_size=16, max_seq_len=64),
                     on_complete=recs.append)
    wte_key = next(k for k, v in loop._params.items()
                   if tuple(v.shape) == (VOCAB, HID))
    prompt = np.array([3, 1, 4, 1], np.int64)
    before = monitor.stats("serve.")

    r0 = loop.submit(prompt, max_new_tokens=8)
    loop.run_until_idle()

    with pytest.raises(KeyError):
        loop.publish_weights(1, {"nope": np.zeros((2, 2))})
    with pytest.raises(ValueError):
        loop.publish_weights(1, {wte_key: np.zeros((3, 3))})

    rolled = np.roll(np.asarray(loop._params[wte_key]), 7, axis=0)
    loop.publish_weights(5, {wte_key: rolled})
    assert loop.stats()["swap_staged"] and loop.model_version == 0
    r1 = loop.submit(prompt, max_new_tokens=8)
    loop.run_until_idle()
    assert loop.model_version == 5 and not loop.stats()["swap_staged"]

    rec0 = next(r for r in recs if r["rid"] == r0.rid)
    rec1 = next(r for r in recs if r["rid"] == r1.rid)
    assert (rec0["version"], rec1["version"]) == (0, 5)
    # the swap is live: same prompt, different model, different stream
    assert rec0["tokens"] != rec1["tokens"]

    # started-loop mode: a stream in flight when the swap stages runs to
    # retirement on its pinned version; the next admit gets the new one
    loop.start()
    try:
        rA = loop.submit(prompt, max_new_tokens=40)
        while rA.t_first is None:
            time.sleep(0.005)
        loop.publish_weights(6, {wte_key: np.asarray(rolled)[::-1].copy()})
        rB = loop.submit(prompt, max_new_tokens=4)
        assert len(rA.result(timeout=30)) == 40
        assert len(rB.result(timeout=30)) == 4
    finally:
        loop.stop()
    recA = next(r for r in recs if r["rid"] == rA.rid)
    recB = next(r for r in recs if r["rid"] == rB.rid)
    assert recA["version"] == 5           # pinned across the staged swap
    assert recB["version"] == 6           # admitted only after it applied
    assert _delta(before, "serve.hot_swaps") == 2
    assert _delta(before, "serve.requests_errored") == 0


# ---------------------------------------------------------------------------
# satellite 3: push_sparse_delta dedupes server-side across failover
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_push_delta_dedupes_across_failover_reroute():
    servers, eps = _cluster(3, 1, dim=4)
    client = PSClient(eps, **FAST)
    try:
        ids = np.array([0], np.int64)          # shard 0: eps[0] -> eps[1]
        one = np.ones((1, 4), np.float32)
        applied = lambda j: servers[j].table("wte").applied  # noqa: E731

        client.push_sparse_delta("wte", ids, one, request_key=("t", 0))
        assert (applied(0), applied(1), applied(2)) == (1, 1, 0)

        # lost ack: the reply frame drops AFTER primary applied+forwarded;
        # the transport retry replays out of the rid cache on both members
        with faults.inject(faults.Fault("server", "reply", faults.DROP,
                                        method="push_sparse_delta")) as inj:
            client.push_sparse_delta("wte", ids, one, request_key=("t", 1))
        assert inj.fired(faults.DROP) == 1
        assert (applied(0), applied(1), applied(2)) == (2, 2, 0)

        # primary dies; the SAME unacked payload resent under the SAME
        # key re-routes to the promoted backup, whose replay cache holds
        # the rid from the forward — replayed, never re-applied
        servers[0].shutdown()
        _await_promotion(client, eps[0])
        client.push_sparse_delta("wte", ids, one, request_key=("t", 1))
        assert applied(1) == 2
        assert np.allclose(client.pull_sparse("wte", ids), 2.0)

        # fresh traffic still lands exactly once on the new primary
        client.push_sparse_delta("wte", ids, one, request_key=("t", 2))
        assert applied(1) == 3 and applied(2) == 0
        assert np.allclose(client.pull_sparse("wte", ids), 3.0)
    finally:
        _teardown(servers[1:], client)


# ---------------------------------------------------------------------------
# continuous Downpour trainer: frozen-payload retry + staleness bound
# ---------------------------------------------------------------------------

T_VOCAB, T_DIM = 32, 4
T_TARGET = np.random.RandomState(5).uniform(
    -1.0, 1.0, (T_VOCAB, T_DIM)).astype(np.float32)


def _build_online_program(vocab, dim, lr=0.25, name="online"):
    from paddle_tpu import nn, optimizer
    paddle.enable_static()
    main = static.Program(name)
    with static.program_guard(main):
        ids = static.data("ids", [-1], "int64")
        target = static.data("target", [-1, dim], "float32")
        emb = nn.Embedding(vocab, dim)
        rows = emb(ids)
        diff = rows - target
        # mean over tokens, sum over dim: per-occurrence row movement is
        # 2*lr*n/N <= 2*lr — a contraction toward the target for lr<0.5
        # no matter how duplicated an id is within the batch
        loss = paddle.ops.mean(paddle.ops.sum(diff * diff, axis=-1))
        opt = optimizer.SGD(learning_rate=lr)
        opt.minimize(loss)
    return main, loss, emb.weight.scope_name


class _FeedDataset:
    def __init__(self, feeds):
        self._feeds = feeds

    def batches(self, start_batch=0):
        yield from self._feeds[start_batch:]


def test_online_trainer_frozen_payload_retries_exactly_once():
    srv = PSServer("127.0.0.1:0", _geo_specs(T_DIM))
    ep = srv.start()
    client = PSClient([ep], **FAST)
    main, loss, emb_name = _build_online_program(T_VOCAB, T_DIM)
    exe = static.Executor()
    scope = static.global_scope()
    uniq = np.array([0, 1, 2, 3], np.int64)
    feeds = [{"ids": uniq, "target": T_TARGET[uniq]} for _ in range(4)]
    holder = {}
    before = monitor.stats("ps.online.")
    try:
        # the first flush's transport attempts ALL reset (1 try + 2
        # retries): the payload freezes, defers inside the staleness
        # bound, and resends NEXT batch under its original request key
        with faults.inject(faults.Fault("client", "send", faults.RESET,
                                        method="push_sparse_delta",
                                        times=3)) as inj:
            exe.train_from_dataset(
                program=main, dataset=_FeedDataset(feeds),
                ps_config={"client": client, "mode": "online",
                           "sync_every": 1, "staleness_batches": 3,
                           "sparse": [{"param": emb_name, "slot": "ids",
                                       "table": "wte"}],
                           "on_batch": lambda d: holder.update(drv=d)})
        assert inj.fired(faults.RESET) == 3
        drv = holder["drv"]
        assert [seq for _, seq, _ in drv.flush_log] == [0, 1, 2, 3]
        assert _delta(before, "ps.online.deferred_flushes") == 1
        # every cut payload applied EXACTLY once despite the dead flush
        assert srv.table("wte").applied == 4
        # single-trainer invariant: server rows == local trained rows
        local = np.asarray(scope.get(emb_name), np.float32)[uniq]
        assert np.allclose(client.pull_sparse("wte", uniq), local,
                           atol=1e-5)
        # and the traffic moved the table toward the target
        assert np.square(local - T_TARGET[uniq]).mean() \
            < np.square(T_TARGET[uniq]).mean()
    finally:
        _teardown([srv], client)


def test_online_trainer_staleness_bound_fails_stop():
    srv = PSServer("127.0.0.1:0", _geo_specs(T_DIM))
    ep = srv.start()
    client = PSClient([ep], **FAST)
    main, _, emb_name = _build_online_program(T_VOCAB, T_DIM,
                                              name="online-stale")
    exe = static.Executor()
    uniq = np.array([4, 5], np.int64)
    feeds = [{"ids": uniq, "target": T_TARGET[uniq]} for _ in range(4)]
    try:
        with faults.inject(faults.Fault("client", "send", faults.RESET,
                                        method="push_sparse_delta",
                                        times=10 ** 9)):
            with pytest.raises((ConnectionError, OSError, RuntimeError)):
                # flush 1 defers; flush 2 trips the bound and fail-stops
                exe.train_from_dataset(
                    program=main, dataset=_FeedDataset(feeds),
                    ps_config={"client": client, "mode": "online",
                               "sync_every": 1, "staleness_batches": 2,
                               "sparse": [{"param": emb_name,
                                           "slot": "ids",
                                           "table": "wte"}]})
    finally:
        _teardown([srv], client)


# ---------------------------------------------------------------------------
# versioned snapshot publisher
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_snapshot_publisher_cursor_failover_and_cache():
    servers, eps = _cluster(3, 1, dim=4)
    client = PSClient(eps, **FAST)
    cache = HeterPSCache(client, "wte", 4, capacity=8, host_rows=0)
    try:
        ids = np.arange(6, dtype=np.int64)
        rows = np.tile(np.arange(1, 7, dtype=np.float32)[:, None], (1, 4))
        client.push_sparse_delta("wte", ids, rows, request_key=("p", 0))
        cache.pull(np.array([4], np.int64))       # warm the cache

        pub = EmbeddingSnapshotPublisher(client, "wte", cache=cache)
        before = monitor.stats("ps.")
        v1, snap1 = pub.publish()
        assert v1 == 1 and len(snap1) == 6
        assert all(np.allclose(snap1[int(i)], rows[i]) for i in ids)

        # untouched cluster: cursors unchanged, nothing refetched
        v2, snap2 = pub.publish()
        assert v2 == 2
        assert all(np.allclose(snap2[int(i)], rows[i]) for i in ids)
        assert _delta(before, "ps.publish.shards_refetched") == 3  # v1 only

        # one id trains -> only the servers that saw the mutation
        # refetch: shard 1's primary applied it and shard 2's primary
        # holds the forwarded backup copy (the seq cursor is per-server,
        # so backup traffic moves it too); shard 0 stays cached. The
        # attached cache invalidates so the published row is SERVED.
        client.push_sparse_delta("wte", np.array([4], np.int64),
                                 np.ones((1, 4), np.float32),
                                 request_key=("p", 1))
        v3, snap3 = pub.publish()
        assert np.allclose(snap3[4], rows[4] + 1.0)
        assert _delta(before, "ps.publish.shards_refetched") == 5
        assert _delta(before, "ps.heter.invalidations") >= 3
        assert np.allclose(cache.pull(np.array([4], np.int64))[0],
                           rows[4] + 1.0)

        # a publish mid-failover rides the re-route to the promoted
        # backup — consistent snapshot, no half-published version
        servers[0].shutdown()
        _await_promotion(client, eps[0])
        client.push_sparse_delta("wte", np.array([0], np.int64),
                                 np.ones((1, 4), np.float32),
                                 request_key=("p", 2))
        v4, snap4 = pub.publish()
        assert v4 == 4 and np.allclose(snap4[0], rows[0] + 1.0)

        # materialize overlays published rows on the served base
        base = np.zeros((8, 4), np.float32)
        dense = pub.materialize(base)
        assert np.allclose(dense[0], rows[0] + 1.0)
        assert np.allclose(dense[6:], 0.0)
    finally:
        _teardown(servers[1:], client)


def test_snapshot_publisher_unreplicated_fallback():
    srv = PSServer("127.0.0.1:0", _geo_specs(4))
    ep = srv.start()
    client = PSClient([ep], **FAST)
    try:
        ids = np.array([2, 9], np.int64)
        client.push_sparse_delta("wte", ids,
                                 np.full((2, 4), 3.0, np.float32),
                                 request_key=("u", 0))
        pub = EmbeddingSnapshotPublisher(client, "wte")
        before = monitor.stats("ps.publish.")
        _, snap = pub.publish()
        assert np.allclose(snap[2], 3.0) and np.allclose(snap[9], 3.0)
        pub.publish()
        # no replication gate -> no cutoff cursor: every publish refetches
        assert _delta(before, "ps.publish.shards_refetched") == 2
    finally:
        _teardown([srv], client)


# ---------------------------------------------------------------------------
# THE drill: the closed loop under chaos
# ---------------------------------------------------------------------------

class _Window:
    """Expose the shared streaming generator to train_from_dataset a
    fixed number of batches at a time — each call is one trainer
    "session" over the same exactly-once stream."""

    def __init__(self, ds):
        self.ds = ds
        self._gen = None
        self.n = 0

    def take(self, n):
        self.n = int(n)
        return self

    def batches(self, start_batch=0):
        if self._gen is None:
            self._gen = self.ds.batches(start_batch=start_batch)
        else:
            assert int(start_batch) == \
                self.ds.stats()["delivered_batches"]
        return itertools.islice(self._gen, self.n)


@pytest.mark.chaos
def test_online_learning_chaos_drill():
    servers, eps = _cluster(3, 1, dim=HID)
    paddle.seed(0)
    gpt = GPT(GPTConfig.tiny())
    gpt.eval()

    trained_ids = set()

    def _collate(recs):
        ids = np.concatenate([np.asarray(r["prompt"] + r["tokens"],
                                         np.int64) for r in recs])
        trained_ids.update(int(t) for t in ids)
        return {"ids": ids, "target": TARGET[ids]}

    ds = StreamingDataset(batch_size=3, collate=_collate, name="drill")

    def _on_complete(rec):   # at-least-once transport: every record twice
        ds.offer(rec)
        ds.offer(rec)

    loop = ServeLoop(gpt, ServeConfig(max_active=4, kv_blocks=16,
                                      block_size=16, max_seq_len=64),
                     on_complete=_on_complete)
    wte_key = next(k for k, v in loop._params.items()
                   if tuple(v.shape) == (VOCAB, HID))
    wte0 = np.asarray(loop._params[wte_key]).copy()

    main, loss, emb_name = _build_online_program(VOCAB, HID, lr=0.25,
                                                 name="drill")
    exe = static.Executor()
    window = _Window(ds)
    holder = {}
    all_reqs = []
    snaps = []

    clients = [PSClient(eps, **FAST),      # trainer, first life
               PSClient(eps, **FAST)]      # publisher + serving cache
    client_t, client_p = clients
    cache = HeterPSCache(client_p, "wte", HID, capacity=256, host_rows=0)
    pub = EmbeddingSnapshotPublisher(client_p, "wte", cache=cache)
    prefetchers = []

    def serve_phase(k):
        rng = np.random.RandomState(1000 + k)
        reqs = [loop.submit(rng.randint(0, 48, 4).astype(np.int64),
                            max_new_tokens=6) for _ in range(6)]
        loop.run_until_idle()
        all_reqs.extend(reqs)

    def train_phase(client, n_batches, state):
        pf = EmbeddingPrefetcher(client, table="wte")
        prefetchers.append(pf)
        cfg = {"client": client, "mode": "online", "sync_every": 1,
               "trainer_id": 7,
               "sparse": [{"param": emb_name, "slot": "ids",
                           "table": "wte", "prefetcher": pf}],
               "on_batch": lambda d: holder.update(drv=d)}
        if state is not None:
            cfg["state"] = state
        start = ds.stats()["delivered_batches"]
        exe.train_from_dataset(program=main,
                               dataset=window.take(n_batches),
                               ps_config=cfg, start_batch=start)
        drv = holder["drv"]
        assert all(f is None for f in drv._frozen)  # phase fully acked
        return {"online": drv.online_state(), "ds": ds.state_dict()}

    def publish_and_swap():
        version, _ = pub.publish()
        snap = pub.materialize(np.asarray(loop._params[wte_key]))
        loop.publish_weights(version, {wte_key: snap})
        loop.run_until_idle()               # applies between beats
        assert loop.model_version == version
        snaps.append(snap)

    before = monitor.stats("serve.")
    try:
        with faults.inject(seed=11, p={faults.RESET: 0.02,
                                       faults.DROP: 0.02}) as inj:
            serve_phase(0)
            ckpt = train_phase(client_t, 2, None)       # flush seq 0,1
            publish_and_swap()                          # v1

            serve_phase(1)
            ckpt = train_phase(client_t, 1, ckpt["online"])  # seq 2
            k_kill = len(holder["drv"].flush_log)

            # trainer "dies" at the checkpoint; a shard primary dies for
            # real. The restarted trainer is a FRESH process image: new
            # PSClient whose replay identity comes from the checkpoint.
            servers[0].shutdown()
            client_t2 = PSClient(eps, **FAST)
            clients.append(client_t2)
            _await_promotion(client_t2, eps[0])
            ckpt = train_phase(client_t2, 1, ckpt["online"])  # seq 3
            publish_and_swap()                          # v2 (rides failover)

            serve_phase(2)
            train_phase(client_t2, 2, ckpt["online"])   # seq 4,5
            publish_and_swap()                          # v3

            # chaos actually ran
            assert inj.fired(faults.RESET) >= 1
            assert inj.fired(faults.DROP) >= 1

        # ---- zero dropped serve requests across >=3 hot-swaps ----
        assert len(all_reqs) == 18
        assert all(len(r.result(timeout=0)) == 6 for r in all_reqs)
        assert _delta(before, "serve.requests_completed") == 18
        assert _delta(before, "serve.requests_errored") == 0
        assert _delta(before, "serve.hot_swaps") == 3
        assert loop.model_version == 3

        # ---- exactly-once stream accounting ----
        st = ds.stats()
        assert st["accepted"] == 18 and st["duplicates"] == 18
        assert st["delivered_records"] == 18
        assert st["delivered_batches"] == 6 and st["backlog"] == 0

        # ---- exactly-once delta accounting: replay the flush schedule
        # against the membership timeline (shard s lives on eps[s] with
        # backup eps[s+1]; the killed server leaves every chain) ----
        log = holder["drv"].flush_log
        assert [seq for _, seq, _ in log] == [0, 1, 2, 3, 4, 5]
        expected = {ep: 0 for ep in eps}
        for _, seq, ids in log:
            for s in sorted({int(i) % 3 for i in ids}):
                for ep in (eps[s], eps[(s + 1) % 3]):
                    if seq >= k_kill and ep == eps[0]:
                        continue
                    expected[ep] += 1
        for j in (1, 2):
            assert servers[j].table("wte").applied == expected[eps[j]], \
                f"server {j}: {servers[j].table('wte').applied} != " \
                f"{expected[eps[j]]}"

        # ---- the served model measurably shifted toward the traffic:
        # versioned eval metric strictly decreases across snapshots ----
        ev = np.fromiter(sorted(trained_ids), np.int64)
        m = [float(np.square(w[ev] - TARGET[ev]).mean())
             for w in [wte0] + snaps]
        assert m[1] < m[0] and m[2] < m[1] and m[3] < m[2], m
        assert m[3] < 0.9 * m[0], m
        # the swap protocol also invalidated the serving-side cache
        assert monitor.stat_get("ps.heter.invalidations") >= 3
    finally:
        _teardown(servers[1:], *clients, *prefetchers)
