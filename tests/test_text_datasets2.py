"""Round-4 text dataset breadth (reference text/datasets: imikolov,
movielens, conll05, wmt14/16) — synthetic local archives, zero-egress."""
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.text.datasets import (Conll05st, Imikolov, Movielens,
                                      WMT14, WMT16)


def test_imikolov_ngram_and_seq(tmp_path):
    train = "the cat sat\nthe dog sat\nthe cat ran\n" * 20
    valid = "the cat sat\n"
    for name, content in (("ptb.train.txt", train),
                          ("ptb.valid.txt", valid)):
        (tmp_path / name).write_text(content)
    tar = str(tmp_path / "simple-examples.tgz")
    with tarfile.open(tar, "w:gz") as tf:
        for name in ("ptb.train.txt", "ptb.valid.txt"):
            tf.add(str(tmp_path / name),
                   arcname=f"simple-examples/data/{name}")
    ds = Imikolov(data_file=tar, data_type="NGRAM", window_size=3,
                  min_word_freq=10, mode="train")
    assert len(ds) > 0
    assert all(g.shape == (3,) for g in ds)
    seq = Imikolov(data_file=tar, data_type="SEQ", window_size=10,
                   min_word_freq=10, mode="test")
    src, trg = seq[0]
    np.testing.assert_array_equal(src[1:], trg[:-1])
    with pytest.raises(RuntimeError):
        Imikolov(download=True)


def test_movielens(tmp_path):
    users = "1::M::25::4::10001\n2::F::35::7::10002\n"
    movies = "10::Toy Story (1995)::Animation|Comedy\n" \
             "20::Heat (1995)::Action\n"
    ratings = "1::10::5::100\n1::20::3::200\n2::10::4::300\n"
    z = str(tmp_path / "ml-1m.zip")
    with zipfile.ZipFile(z, "w") as zf:
        zf.writestr("ml-1m/users.dat", users)
        zf.writestr("ml-1m/movies.dat", movies)
        zf.writestr("ml-1m/ratings.dat", ratings)
    ds = Movielens(data_file=z, mode="train", test_ratio=0.0)
    assert len(ds) == 3
    uid, g, age, job, mid, cats, title, rating = ds[0]
    assert (uid, g, age, job, mid) == (1, 0, 25, 4, 10)
    assert rating == 5.0 and len(cats) == 2
    # same title word "(1995)" shared across movies
    m2 = [r for r in ds if r[4] == 20][0]
    assert set(m2[6].tolist()) & set(title.tolist())


def test_conll05(tmp_path):
    words = "The\ncat\nsat\n\nDogs\nbark\n"
    props = "- B-A0\n- I-A0\n- B-V\n\n- B-A0\n- B-V\n"
    wf = tmp_path / "words.txt"
    pf = tmp_path / "props.txt"
    wf.write_text(words)
    pf.write_text(props)
    ds = Conll05st(words_file=str(wf), props_file=str(pf))
    assert len(ds) == 2
    w0, l0 = ds[0]
    assert w0.shape == (3,) and l0.shape == (3,)
    assert len(ds.word_dict) == 5 and len(ds.label_dict) == 3


def _wmt_tar(tmp_path, names):
    src = "ein haus\nzwei katzen\n"
    trg = "a house\ntwo cats\n"
    tar = str(tmp_path / "wmt.tgz")
    with tarfile.open(tar, "w:gz") as tf:
        for n, content in names.items():
            p = tmp_path / n
            p.write_text(content)
            tf.add(str(p), arcname=f"data/{n}")
    return tar


def test_wmt14_and_16(tmp_path):
    tar = _wmt_tar(tmp_path, {"train.src": "ein haus\nzwei katzen\n",
                              "train.trg": "a house\ntwo cats\n"})
    ds = WMT14(data_file=tar, mode="train")
    src, tin, tout = ds[0]
    assert tin[0] == ds.trg_dict["<s>"]
    assert tout[-1] == ds.trg_dict["<e>"]
    np.testing.assert_array_equal(tin[1:], tout[:-1])

    tar16 = _wmt_tar(tmp_path, {"train.en": "a house\n",
                                "train.de": "ein haus\n"})
    ds16 = WMT16(data_file=tar16, mode="train")
    assert len(ds16) == 1
