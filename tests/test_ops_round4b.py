"""Round-4 op widening batch 2: math/manipulation/loss/vision families
(reference operators/ — addmm, multiplex, strided_slice, temporal_shift,
gather_tree, unique, pool_with_index/unpool, row_conv, nce, hsigmoid,
center_loss, edit_distance, mean_iou, ...)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
from paddle_tpu import ops

from op_test import check_grad


def T(x, dtype="float32"):
    return paddle.to_tensor(np.asarray(x, dtype))


# ----------------------------------------------------------------- math ----

def test_addmm_trace_mv():
    rng = np.random.RandomState(0)
    a, x, y = rng.randn(3, 4), rng.randn(3, 5), rng.randn(5, 4)
    out = ops.addmm(T(a), T(x), T(y), beta=0.5, alpha=2.0)
    np.testing.assert_allclose(out.numpy(), 0.5 * a + 2.0 * (x @ y),
                               rtol=1e-5)
    m = rng.randn(4, 4)
    np.testing.assert_allclose(ops.trace(T(m)).numpy(), np.trace(m),
                               rtol=1e-5)
    v = rng.randn(4)
    np.testing.assert_allclose(ops.mv(T(m), T(v)).numpy(), m @ v, rtol=1e-5)
    check_grad(lambda p, q: ops.addmm(T(np.zeros((2, 2))), p, q),
               [rng.randn(2, 3), rng.randn(3, 2)])


def test_diag_embed_matches_torch():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3).astype("float32")
    for off in (-1, 0, 2):
        out = ops.diag_embed(T(x), offset=off)
        ref = torch.diag_embed(torch.tensor(x), offset=off)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)


def test_multiplex():
    a = np.array([[1.0, 2], [3, 4]], "float32")
    b = np.array([[10.0, 20], [30, 40]], "float32")
    out = ops.multiplex([T(a), T(b)], T([1, 0], "int32"))
    np.testing.assert_array_equal(out.numpy(), [[10, 20], [3, 4]])


def test_cos_sim_bilinear_norms():
    rng = np.random.RandomState(2)
    x, y = rng.randn(4, 6).astype("float32"), rng.randn(4, 6).astype("float32")
    out = ops.cos_sim(T(x), T(y))
    ref = tF.cosine_similarity(torch.tensor(x), torch.tensor(y), dim=-1)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)
    w = rng.randn(3, 6, 6).astype("float32")
    out = ops.bilinear_tensor_product(T(x), T(y), T(w))
    ref = np.einsum("bm,kmn,bn->bk", x, w, y)
    np.testing.assert_allclose(out.numpy(), ref, rtol=2e-4)
    np.testing.assert_allclose(ops.squared_l2_norm(T(x)).numpy(),
                               (x ** 2).sum(), rtol=1e-5)
    np.testing.assert_allclose(ops.l1_norm(T(x)).numpy(),
                               np.abs(x).sum(), rtol=1e-5)
    np.testing.assert_allclose(
        ops.squared_l2_distance(T(x), T(y)).numpy(),
        ((x - y) ** 2).sum(axis=1), rtol=1e-5)


def test_clip_by_norm_and_allclose():
    x = np.array([3.0, 4.0], "float32")
    out = ops.clip_by_norm(T(x), 1.0)
    np.testing.assert_allclose(out.numpy(), x / 5.0, rtol=1e-5)
    np.testing.assert_allclose(ops.clip_by_norm(T(x), 10.0).numpy(), x,
                               rtol=1e-6)
    assert bool(ops.allclose(T(x), T(x + 1e-9)).numpy())
    assert not bool(ops.allclose(T(x), T(x + 1.0)).numpy())


# --------------------------------------------------------- manipulation ----

def test_unbind_unstack_reverse():
    x = np.arange(24).reshape(2, 3, 4).astype("float32")
    parts = ops.unbind(T(x), axis=1)
    assert len(parts) == 3 and parts[1].shape == (2, 4)
    np.testing.assert_array_equal(parts[2].numpy(), x[:, 2])
    np.testing.assert_array_equal(
        ops.reverse(T(x), axis=0).numpy(), x[::-1])


def test_strided_slice():
    x = np.arange(40).reshape(5, 8).astype("float32")
    out = ops.strided_slice(T(x), axes=[0, 1], starts=[1, 0], ends=[4, 8],
                            strides=[2, 3])
    np.testing.assert_array_equal(out.numpy(), x[1:4:2, 0:8:3])


def test_space_to_depth_shuffle_channel():
    x = np.arange(32).reshape(1, 2, 4, 4).astype("float32")
    out = ops.space_to_depth(T(x), 2)
    assert out.shape == (1, 8, 2, 2)
    ref = tF.pixel_unshuffle(torch.tensor(x), 2)
    # channel ordering differs between conventions; compare as sets per
    # spatial location
    assert sorted(out.numpy().ravel()) == sorted(ref.numpy().ravel())
    y = np.arange(16).reshape(1, 4, 2, 2).astype("float32")
    sc = ops.shuffle_channel(T(y), 2)
    ref = torch.channel_shuffle(torch.tensor(y), 2)
    np.testing.assert_array_equal(sc.numpy(), ref.numpy())


def test_temporal_shift():
    nt, c, h, w = 4, 8, 2, 2
    x = np.random.RandomState(3).randn(nt, c, h, w).astype("float32")
    out = ops.temporal_shift(T(x), seg_num=2, shift_ratio=0.25).numpy()
    x5 = x.reshape(2, 2, c, h, w)
    # first quarter shifted backward in time
    np.testing.assert_array_equal(out.reshape(2, 2, c, h, w)[:, 0, :2],
                                  x5[:, 1, :2])
    np.testing.assert_array_equal(out.reshape(2, 2, c, h, w)[:, 1, :2], 0)
    # second quarter shifted forward
    np.testing.assert_array_equal(out.reshape(2, 2, c, h, w)[:, 1, 2:4],
                                  x5[:, 0, 2:4])
    # rest untouched
    np.testing.assert_array_equal(out.reshape(2, 2, c, h, w)[:, :, 4:],
                                  x5[:, :, 4:])


def test_shard_index():
    x = np.array([1, 6, 11, 15], "int64")
    out = ops.shard_index(T(x, "int64"), index_num=16, nshards=2, shard_id=0)
    np.testing.assert_array_equal(out.numpy(), [1, 6, -1, -1])
    out = ops.shard_index(T(x, "int64"), index_num=16, nshards=2, shard_id=1)
    np.testing.assert_array_equal(out.numpy(), [-1, -1, 3, 7])


def test_unique_and_nonzero():
    x = np.array([3, 1, 3, 2, 1], "int64")
    vals, inv, cnt = ops.unique(T(x, "int64"), return_inverse=True,
                                return_counts=True)
    np.testing.assert_array_equal(vals.numpy(), [1, 2, 3])
    np.testing.assert_array_equal(cnt.numpy(), [2, 1, 2])
    np.testing.assert_array_equal(vals.numpy()[inv.numpy()], x)
    uc, cc = ops.unique_consecutive(T(np.array([1, 1, 2, 2, 2, 1]), "int64"),
                                    return_counts=True)
    np.testing.assert_array_equal(uc.numpy(), [1, 2, 1])
    np.testing.assert_array_equal(cc.numpy(), [2, 3, 1])
    nz = ops.nonzero(T(np.array([[1, 0], [0, 2]], "float32")))
    np.testing.assert_array_equal(nz.numpy(), [[0, 0], [1, 1]])


def test_gather_tree():
    # [max_time=3, batch=1, beam=2]
    ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], "int64")
    parents = np.array([[[0, 0]], [[0, 0]], [[1, 0]]], "int64")
    out = ops.gather_tree(T(ids, "int64"), T(parents, "int64")).numpy()
    ref = torch.ops  # placeholder: compute by hand
    # beam 0 final token 5 has parent 1 -> time1 beam1 token 4 -> parent 0
    np.testing.assert_array_equal(out[:, 0, 0], [1, 4, 5])
    np.testing.assert_array_equal(out[:, 0, 1], [1, 3, 6])


def test_partial_concat_sum_pad_like():
    a = np.arange(12).reshape(2, 6).astype("float32")
    b = 10 * np.ones((2, 6), "float32")
    out = ops.partial_concat([T(a), T(b)], start_index=1, length=2)
    np.testing.assert_array_equal(out.numpy(),
                                  np.concatenate([a[:, 1:3], b[:, 1:3]], 1))
    out = ops.partial_sum([T(a), T(b)], start_index=0, length=3)
    np.testing.assert_array_equal(out.numpy(), a[:, :3] + b[:, :3])
    big = np.zeros((3, 4), "float32")
    small = np.ones((2, 3), "float32")
    out = ops.pad_constant_like(T(big), T(small), pad_value=7.0)
    assert out.shape == (3, 4)
    assert (out.numpy()[2] == 7).all() and (out.numpy()[:2, :3] == 1).all()


# ---------------------------------------------------------------- losses ----

def test_hinge_rank_modified_huber():
    logits = np.array([0.5, -0.3], "float32")
    label = np.array([1.0, 0.0], "float32")
    np.testing.assert_allclose(
        ops.hinge_loss(T(logits), T(label)).numpy(),
        [max(0, 1 - 0.5), max(0, 1 - 0.3)], rtol=1e-5)
    left, right, lab = np.array([1.0]), np.array([0.2]), np.array([1.0])
    out = ops.rank_loss(T(lab), T(left), T(right)).numpy()
    ref = np.log1p(np.exp(-(left - right))) + (1 - lab) * (left - right)
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    x = np.array([-2.0, 0.5, 2.0], "float32")
    y = np.array([1.0, 1.0, 1.0], "float32")
    out = ops.modified_huber_loss(T(x), T(y)).numpy()
    np.testing.assert_allclose(out, [8.0, 0.25, 0.0], rtol=1e-5)


def test_bpr_npair_center():
    rng = np.random.RandomState(4)
    logits = rng.randn(3, 5).astype("float32")
    lab = np.array([0, 3, 2])
    out = ops.bpr_loss(T(logits), T(lab, "int64")).numpy()
    assert out.shape == (3, 1) and (out > 0).all()
    anchor = rng.randn(4, 8).astype("float32")
    pos = rng.randn(4, 8).astype("float32")
    nl = ops.npair_loss(T(anchor), T(pos), T([0, 1, 0, 2], "int64"))
    assert np.isfinite(float(nl.numpy()))
    feats = rng.randn(4, 3).astype("float32")
    centers = np.zeros((5, 3), "float32")
    loss, newc = ops.center_loss(T(feats), T([1, 1, 2, 0], "int64"),
                                 T(centers), alpha=0.5)
    np.testing.assert_allclose(loss.numpy()[:, 0],
                               0.5 * (feats ** 2).sum(1), rtol=1e-5)
    # centers moved toward their members' mean
    assert not np.allclose(newc.numpy()[1], 0)
    assert np.allclose(newc.numpy()[3], 0)      # class 3 unseen


def test_nce_and_hsigmoid():
    rng = np.random.RandomState(5)
    x = rng.randn(3, 6).astype("float32")
    w = rng.randn(20, 6).astype("float32")
    b = rng.randn(20).astype("float32")
    lab = np.array([4, 7, 19])
    samples = np.array([1, 2, 3, 5, 8])
    out = ops.nce(T(x), T(lab, "int64"), T(w), T(b),
                  sample_ids=T(samples, "int64")).numpy()
    assert out.shape == (3, 1) and (out > 0).all()
    hw = rng.randn(19, 6).astype("float32")
    out = ops.hsigmoid_loss(T(x), T(lab, "int64"), T(hw),
                            num_classes=20).numpy()
    assert out.shape == (3, 1) and (out > 0).all()
    # directional finite-difference check of the analytic gradient
    import jax, jax.numpy as jnp
    f = lambda xx: jnp.sum(ops.hsigmoid_loss.raw(
        xx, jnp.asarray(lab), jnp.asarray(hw, jnp.float64),
        num_classes=20))
    x64 = np.asarray(x, "float64")
    g = jax.grad(f)(jnp.asarray(x64))
    d = rng.randn(*x.shape)
    eps = 1e-6
    fd = (f(jnp.asarray(x64 + eps * d)) - f(jnp.asarray(x64 - eps * d))) \
        / (2 * eps)
    np.testing.assert_allclose(float(jnp.vdot(g, d)), float(fd), rtol=1e-5)


def test_sigmoid_focal_loss_reduces_easy_examples():
    logit = np.array([[5.0], [-5.0]], "float32")   # confident
    label = np.array([[1.0], [0.0]], "float32")    # and correct
    out = ops.sigmoid_focal_loss(T(logit), T(label)).numpy()
    assert (out < 1e-3).all()


# ---------------------------------------------------------------- vision ----

def test_pool_with_index_roundtrips_unpool():
    rng = np.random.RandomState(6)
    x = rng.randn(2, 3, 6, 6).astype("float32")
    out, idx = ops.max_pool2d_with_index(T(x), 2, stride=2)
    ref, ref_idx = tF.max_pool2d(torch.tensor(x), 2, stride=2,
                                 return_indices=True)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-6)
    np.testing.assert_array_equal(idx.numpy(), ref_idx.numpy())
    restored = ops.max_unpool2d(out, idx, 2, stride=2)
    ref_restored = tF.max_unpool2d(ref, ref_idx, 2, stride=2)
    np.testing.assert_allclose(restored.numpy(), ref_restored.numpy(),
                               rtol=1e-6)


def test_affine_channel_row_conv_im2sequence():
    rng = np.random.RandomState(7)
    x = rng.randn(2, 3, 4, 4).astype("float32")
    s, b = rng.randn(3).astype("float32"), rng.randn(3).astype("float32")
    out = ops.affine_channel(T(x), T(s), T(b))
    np.testing.assert_allclose(
        out.numpy(), x * s[None, :, None, None] + b[None, :, None, None],
        rtol=1e-5)
    seq = rng.randn(1, 5, 3).astype("float32")
    w = rng.randn(2, 3).astype("float32")
    out = ops.row_conv(T(seq), T(w)).numpy()
    ref = seq * w[0]
    ref[:, :-1] += seq[:, 1:] * w[1]
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    out = ops.im2sequence(T(x), 2, stride=2)
    assert out.shape == (2 * 2 * 2, 3 * 4)


def test_data_norm_l2_normalize():
    rng = np.random.RandomState(8)
    x = rng.randn(6, 4).astype("float32")
    bs = np.full((4,), 10.0, "float32")
    bsum = rng.randn(4).astype("float32") * 10
    bsq = np.abs(rng.randn(4)).astype("float32") * 10 + 10
    out = ops.data_norm(T(x), T(bs), T(bsum), T(bsq)).numpy()
    means = bsum / bs
    scales = 1 / np.sqrt(bsq / bs - means ** 2 + 1e-4)
    np.testing.assert_allclose(out, (x - means) * scales, rtol=1e-4)
    out = ops.l2_normalize(T(x)).numpy()
    np.testing.assert_allclose(np.linalg.norm(out, axis=-1),
                               np.ones(6), rtol=1e-5)


def test_edit_distance_and_mean_iou():
    d, n = ops.edit_distance([[1, 2, 3], [4, 5]], [[1, 3], [4, 5]],
                             normalized=False)
    np.testing.assert_array_equal(d.numpy()[:, 0], [1, 0])
    assert n == 2
    pred = np.array([0, 1, 1, 2], "int64")
    lab = np.array([0, 1, 2, 2], "int64")
    miou, wrong, correct = ops.mean_iou(T(pred, "int64"), T(lab, "int64"), 3)
    # class0: 1/1, class1: 1/2, class2: 1/2 -> mean 2/3
    np.testing.assert_allclose(float(miou.numpy()), 2 / 3, rtol=1e-5)
    np.testing.assert_array_equal(correct.numpy(), [1, 1, 1])
