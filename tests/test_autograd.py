"""Dygraph autograd engine tests.

Models the reference's imperative tests
(python/paddle/fluid/tests/unittests/test_imperative_basic.py and
test_imperative_double_grad.py's first-order parts); gradients are checked
against hand-derived closed forms (the OpTest numeric-gradient discipline
lives in tests/test_op_grads.py).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = paddle.exp(x)
    z = (y * 2.0).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2.0 * np.exp([1.0, 2.0]), rtol=1e-6)


def test_grad_accumulation_multiple_uses():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x * x + x  # dy/dx = 2x + 1 = 7
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


def test_grad_accumulates_across_backwards():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    x.clear_grad()
    assert x.grad is None


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = paddle.to_tensor([2.0], stop_gradient=True)
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
    assert y.grad is None


def test_detach_cuts_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = (x * x).detach()
    z = y * x
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])  # only through z = y*x


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._node is None


def test_no_grad_decorator():
    x = paddle.to_tensor([1.0], stop_gradient=False)

    @paddle.no_grad()
    def f(v):
        return v * 3

    assert f(x).stop_gradient


def test_matmul_grad():
    a = np.random.rand(3, 4).astype("float32")
    b = np.random.rand(4, 5).astype("float32")
    ta = paddle.to_tensor(a, stop_gradient=False)
    tb = paddle.to_tensor(b, stop_gradient=False)
    out = paddle.matmul(ta, tb).sum()
    out.backward()
    ones = np.ones((3, 5), dtype="float32")
    np.testing.assert_allclose(ta.grad.numpy(), ones @ b.T, rtol=1e-5)
    np.testing.assert_allclose(tb.grad.numpy(), a.T @ ones, rtol=1e-5)


def test_broadcast_grad():
    x = paddle.to_tensor(np.ones((3, 4), "float32"), stop_gradient=False)
    b = paddle.to_tensor(np.ones((4,), "float32"), stop_gradient=False)
    ((x + b) * 2).sum().backward()
    np.testing.assert_allclose(b.grad.numpy(), [6, 6, 6, 6])


def test_softmax_ce_grad_matches_softmax_minus_onehot():
    logits = np.array([[1.0, 2.0, 3.0]], dtype="float32")
    t = paddle.to_tensor(logits, stop_gradient=False)
    label = paddle.to_tensor(np.array([2], dtype="int64"))
    loss = paddle.ops.cross_entropy(t, label)
    loss.backward()
    sm = np.exp(logits) / np.exp(logits).sum()
    expected = sm - np.eye(3, dtype="float32")[2]
    np.testing.assert_allclose(t.grad.numpy(), expected, rtol=1e-5, atol=1e-6)


def test_paddle_grad_api():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x * x
    (gx,) = paddle.grad(y, x)
    np.testing.assert_allclose(gx.numpy(), [12.0])
    assert x.grad is None  # paddle.grad must not touch .grad


def test_paddle_grad_unused():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    z = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        paddle.grad(y, [x, z])
    y2 = x * 2
    gx, gz = paddle.grad(y2, [x, z], allow_unused=True)
    assert gz is None and np.allclose(gx.numpy(), [2.0])


def test_retain_graph():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0])


def test_freed_graph_raises():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_setitem_grad():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = x * 2
    y[0] = 0.0
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 2.0, 2.0])


def test_getitem_grad():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = x[1:]
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0.0, 1.0, 1.0])


def test_multi_output_split_grad():
    x = paddle.to_tensor(np.arange(4, dtype="float32"), stop_gradient=False)
    a, b = paddle.split(x, 2)
    (a.sum() * 2 + b.sum() * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2, 3, 3])


def test_backward_non_scalar_with_grad_tensor():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3
    y.backward(paddle.to_tensor([1.0, 10.0]))
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 30.0])


def test_pylayer():
    class Double(paddle.autograd.PyLayer):
        @staticmethod
        def forward(ctx, x):
            return x * 2

        @staticmethod
        def backward(ctx, g):
            return g * 2

    x = paddle.to_tensor([1.5], stop_gradient=False)
    y = Double.apply(x)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])
