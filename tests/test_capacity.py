"""Analytic capacity model (ISSUE 18): the deterministic beat
simulation, the queueing closed forms, and the profile round-trip. The
full closed-loop `--validate` (CPU calibration + live harness replay)
is @slow — tier-1 asserts the model's math, the committed
HLO_EVIDENCE.json record, and determinism."""
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from paddle_tpu.static import capacity as C  # noqa: E402
from paddle_tpu.traffic import workload as W  # noqa: E402


def _profile(**kw):
    d = dict(source="test", beat_ms_base=2.0, beat_ms_per_active=0.5,
             prefill_ms={8: 4.0, 16: 7.0}, admit_ms=1.0,
             admit_serial_ms=0.5, ttft_tail_ms=3.0, token_tail_ms=0.2)
    d.update(kw)
    return C.DeviceProfile(**d)


def _spec(rate=40.0, duration_s=2.0, new=6, prompt=6):
    return W.WorkloadSpec(
        name="cap", duration_s=duration_s,
        arrival={"kind": "poisson", "rate": rate},
        tenants=({"name": "t", "weight": 1.0, "kind": "llm",
                  "prompt": {"kind": "fixed", "value": prompt},
                  "new": {"kind": "fixed", "value": new}},),
        max_seq_len=48)


def test_bucket_mirrors_the_serve_pad_ladder():
    assert [C._bucket(n) for n in (1, 8, 9, 16, 17, 33)] == \
        [8, 8, 16, 16, 32, 64]


def test_device_profile_round_trips_and_extrapolates():
    p = _profile()
    q = C.DeviceProfile.from_dict(json.loads(json.dumps(p.as_dict())))
    assert q.as_dict() == p.as_dict()
    # affine beat model
    assert p.beat_ms(4) == pytest.approx(2.0 + 0.5 * 4)
    assert p.beat_ms(0) == pytest.approx(2.0)
    # known bucket exact, unknown bucket extrapolated linearly in width
    assert p.prefill_cost_ms(7) == pytest.approx(4.0)
    assert p.prefill_cost_ms(30) == pytest.approx(7.0 * 32 / 16)


def test_queueing_closed_forms():
    # Erlang C: certain wait at/over saturation, monotone in load
    assert C._erlang_c(10.0, 1.0, 10) == 1.0
    lo = C._erlang_c(2.0, 1.0, 8)
    hi = C._erlang_c(6.0, 1.0, 8)
    assert 0.0 < lo < hi < 1.0
    # Allen-Cunneen wait: zero without load, inf past saturation,
    # monotone in offered rate and in service-time variability
    assert C.queue_wait_ms(0.0, 0.1, 1.0, 4) == 0.0
    assert C.queue_wait_ms(50.0, 0.1, 1.0, 4) == float("inf")
    w1 = C.queue_wait_ms(20.0, 0.1, 1.0, 4)
    w2 = C.queue_wait_ms(30.0, 0.1, 1.0, 4)
    assert 0.0 < w1 < w2
    assert C.queue_wait_ms(20.0, 0.1, 3.0, 4) > w1


def test_knee_shrinks_with_longer_generations():
    p = _profile()
    k_short = C.knee_rps(p, slots=8, mean_new=4.0, mean_prompt=8.0)
    k_long = C.knee_rps(p, slots=8, mean_new=16.0, mean_prompt=8.0)
    assert k_long < k_short
    # more slots buy capacity while prefill stays off the beat
    assert C.knee_rps(_profile(beat_ms_per_active=0.0, prefill_ms={8: 0.1},
                               admit_serial_ms=0.0),
                      slots=16, mean_new=4.0, mean_prompt=8.0) > \
        C.knee_rps(_profile(beat_ms_per_active=0.0, prefill_ms={8: 0.1},
                            admit_serial_ms=0.0),
                   slots=8, mean_new=4.0, mean_prompt=8.0)


def test_simulate_is_deterministic_and_complete():
    events = W.schedule(_spec(), seed=11)
    assert events
    kw = dict(slots=4, kv_blocks=24, block_size=8)
    a = C.simulate(events, _profile(), **kw)
    b = C.simulate(events, _profile(), **kw)
    assert a == b
    assert a["completed"] == len(events)
    assert len(a["ttfts_ms"]) == len(events)
    # every TTFT carries the admission latency floor
    assert min(a["ttfts_ms"]) >= 1.0


def test_simulate_backpressure_and_preemption_paths():
    # a pool of 2 blocks against 2-block worst cases: admissions stall
    events = W.schedule(_spec(rate=80.0, duration_s=1.0), seed=3)
    tight = C.simulate(events, _profile(), slots=8, kv_blocks=2,
                       block_size=8)
    assert tight["completed"] == len(events)      # stalls, never drops
    assert tight["backpressure_ticks"] > 0
    # growth into an exhausted pool preempts and still completes
    grow = C.simulate(W.schedule(_spec(rate=60.0, duration_s=1.0,
                                       new=14, prompt=6), seed=3),
                      _profile(), slots=6, kv_blocks=6, block_size=8)
    assert grow["completed"] > 0
    assert grow["preempted"] > 0


def test_predict_is_deterministic_and_internally_consistent():
    spec = _spec(rate=30.0)
    p = _profile()
    kw = dict(slots=8, kv_blocks=48, block_size=8)
    a = C.predict(spec, 7, p, **kw)
    assert a == C.predict(spec, 7, p, **kw)
    assert a["completed"] == a["events"] > 0
    assert a["ttft_ms"]["p99"] >= a["ttft_ms"]["p50"]
    assert a["token_ms"]["p99"] >= a["token_ms"]["p50"]
    assert a["rho"] == pytest.approx(a["offered_rps"] / a["knee_rps"],
                                     rel=1e-3)
    # the p99s carry the fitted host-jitter tails
    assert a["ttft_ms"]["p99"] >= a["ttft_ms"]["p50"] + p.ttft_tail_ms


def test_committed_capacity_evidence_is_in_band():
    """The committed HLO_EVIDENCE.json capacity_validation record must
    hold: ok, headroom >= 1 (the perf floor), and all three builtin
    specs scored by the hub."""
    with open(os.path.join(REPO, "HLO_EVIDENCE.json")) as f:
        section = json.load(f)["graphs"]["capacity_validation"]
    assert section["ok"] is True
    assert section["band_headroom_x"] >= 1.0
    assert set(section["specs"]) == {"steady", "diurnal", "flash"}
    for name, s in section["specs"].items():
        assert s["ok"], name
        assert s["observed"]["scored_by"] == "hub"
        assert s["observed"]["errors"] == 0


@pytest.mark.slow
def test_validate_closed_loop_end_to_end(tmp_path):
    """The real thing: calibrate a CPU profile, predict the builtin
    trio, replay each through the harness with a live hub, and hold
    every metric to its band. Serial-only (CPU timing)."""
    import shutil

    import capacity_plan

    out = tmp_path / "evidence.json"
    shutil.copy(os.path.join(REPO, "HLO_EVIDENCE.json"), out)
    section = capacity_plan.validate(evidence_path=str(out))
    assert section["ok"], json.dumps(section, indent=1)
    with open(out) as f:
        assert json.load(f)["graphs"]["capacity_validation"]["ok"]
