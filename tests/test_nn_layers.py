"""Layer zoo tests (reference test_layers.py territory)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_linear():
    layer = nn.Linear(4, 3)
    x = paddle.randn([2, 4])
    out = layer(x)
    assert out.shape == (2, 3)
    np.testing.assert_allclose(
        out.numpy(), x.numpy() @ layer.weight.numpy() + layer.bias.numpy(),
        rtol=1e-5)
    assert len(layer.parameters()) == 2
    assert not layer.weight.stop_gradient


def test_layer_train_eval_dropout():
    layer = nn.Dropout(0.5)
    x = paddle.ones([100])
    layer.eval()
    np.testing.assert_allclose(layer(x).numpy(), np.ones(100))
    layer.train()
    out = layer(x).numpy()
    assert (out == 0).any() and (out > 1.0).any()  # upscale_in_train


def test_sequential_and_state_dict():
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.randn([3, 4])
    out = model(x)
    assert out.shape == (3, 2)
    sd = model.state_dict()
    assert len(sd) == 4
    model2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model2.set_state_dict(sd)
    np.testing.assert_allclose(model2(x).numpy(), out.numpy(), rtol=1e-6)


def test_named_parameters_nested():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(2, 3)
            self.sub = nn.Sequential(nn.Linear(3, 3))

        def forward(self, x):
            return self.sub(self.fc1(x))

    net = Net()
    names = dict(net.named_parameters())
    assert "fc1.weight" in names and "sub.0.bias" in names
    assert len(net.parameters()) == 4


def test_conv_bn_pool_stack():
    net = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1),
        nn.BatchNorm2D(8),
        nn.ReLU(),
        nn.MaxPool2D(2),
    )
    x = paddle.randn([2, 3, 8, 8])
    out = net(x)
    assert out.shape == (2, 8, 4, 4)
    # BN buffers updated in train mode
    assert not np.allclose(net[1]._mean.numpy(), 0.0)
    net.eval()
    out2 = net(x)
    assert out2.shape == (2, 8, 4, 4)


def test_batchnorm_running_stats_converge():
    bn = nn.BatchNorm1D(4, momentum=0.0)  # new stats replace old entirely
    x = paddle.to_tensor(np.random.randn(32, 4).astype("float32") * 2 + 3)
    bn(x)
    np.testing.assert_allclose(bn._mean.numpy(), x.numpy().mean(0), rtol=1e-3)


def test_embedding_layer():
    emb = nn.Embedding(10, 6, padding_idx=0)
    ids = paddle.to_tensor(np.array([[1, 2, 0]]))
    out = emb(ids)
    assert out.shape == (1, 3, 6)
    np.testing.assert_allclose(out.numpy()[0, 2], np.zeros(6))


def test_layernorm_layer():
    ln = nn.LayerNorm(8)
    x = paddle.randn([4, 8])
    out = ln(x).numpy()
    np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(out.std(-1), 1.0, atol=1e-2)


def test_lstm_shapes_and_grad():
    lstm = nn.LSTM(input_size=5, hidden_size=7, num_layers=2)
    x = paddle.randn([3, 11, 5])
    out, (h, c) = lstm(x)
    assert out.shape == (3, 11, 7)
    assert h.shape == (2, 3, 7) and c.shape == (2, 3, 7)
    out.mean().backward()
    assert lstm.weight_ih_l0.grad is not None


def test_bidirectional_gru():
    gru = nn.GRU(4, 6, direction="bidirect")
    x = paddle.randn([2, 5, 4])
    out, h = gru(x)
    assert out.shape == (2, 5, 12)
    assert h.shape == (2, 2, 6)


def test_lstm_sequence_length_mask():
    lstm = nn.LSTM(3, 4)
    x = paddle.randn([2, 6, 3])
    out, (h, _) = lstm(x, sequence_length=paddle.to_tensor([6, 3]))
    # final state of batch 1 equals hidden at t=3
    np.testing.assert_allclose(h.numpy()[0, 1], out.numpy()[1, 2], rtol=1e-5)


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    q = paddle.randn([2, 5, 16])
    out = mha(q)
    assert out.shape == (2, 5, 16)
    # cross attention
    kv = paddle.randn([2, 7, 16])
    out = mha(q, kv, kv)
    assert out.shape == (2, 5, 16)


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=2,
                                       dim_feedforward=32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 6, 16])
    out = enc(x)
    assert out.shape == (2, 6, 16)
    out.mean().backward()
    grads = [p.grad for p in enc.parameters()]
    assert all(g is not None for g in grads)


def test_full_transformer():
    model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=1,
                           num_decoder_layers=1, dim_feedforward=32,
                           dropout=0.0)
    src = paddle.randn([2, 4, 16])
    tgt = paddle.randn([2, 3, 16])
    out = model(src, tgt)
    assert out.shape == (2, 3, 16)


def test_loss_layers():
    ce = nn.CrossEntropyLoss()
    logits = paddle.randn([4, 10]); logits.stop_gradient = False
    labels = paddle.to_tensor(np.array([1, 2, 3, 4]))
    loss = ce(logits, labels)
    assert loss.shape == ()
    loss.backward()
    assert logits.grad is not None


def test_forward_hooks():
    layer = nn.Linear(2, 2)
    calls = []
    h = layer.register_forward_post_hook(
        lambda lyr, inp, out: calls.append(out.shape))
    layer(paddle.randn([3, 2]))
    assert calls == [(3, 2)]
    h.remove()
    layer(paddle.randn([3, 2]))
    assert len(calls) == 1


def test_sublayer_replacement_and_apply():
    net = nn.Sequential(nn.Linear(2, 2), nn.ReLU())
    count = [0]
    net.apply(lambda l: count.__setitem__(0, count[0] + 1))
    assert count[0] == 3  # self + 2 children


def test_round4_layer_classes():
    """The 11 layer classes closing the nn.* class surface vs the
    reference (adaptive pools 1D/3D, Pool2D, BilinearTensorProduct,
    PairwiseDistance, RowConv, HSigmoidLoss, NCELoss, RNNCellBase
    export)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(0)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 3, 8).astype("float32"))
    assert nn.AdaptiveAvgPool1D(4)(x).shape == (2, 3, 4)
    assert nn.AdaptiveMaxPool1D(2)(x).shape == (2, 3, 2)
    b = nn.BilinearTensorProduct(4, 5, 3)
    assert b(paddle.to_tensor(np.ones((2, 4), "float32")),
             paddle.to_tensor(np.ones((2, 5), "float32"))).shape == (2, 3)
    pd = nn.PairwiseDistance()(
        paddle.to_tensor(np.zeros((2, 4), "float32")),
        paddle.to_tensor(np.ones((2, 4), "float32")))
    np.testing.assert_allclose(np.asarray(pd._value), [2.0, 2.0],
                               rtol=1e-4)
    assert nn.RowConv(3, 2)(x.transpose([0, 2, 1])).shape == (2, 8, 3)
    hs = nn.HSigmoidLoss(6, 10)(
        paddle.to_tensor(np.ones((3, 6), "float32")),
        paddle.to_tensor(np.array([1, 2, 3]), "int64"))
    assert hs.shape == (3, 1) and (hs.numpy() > 0).all()
    img = paddle.to_tensor(np.ones((1, 2, 4, 4), "float32"))
    assert nn.Pool2D(2, "avg", 2)(img).shape == (1, 2, 2, 2)
    nce = nn.NCELoss(20, 6)(
        paddle.to_tensor(np.ones((3, 6), "float32")),
        paddle.to_tensor(np.array([1, 2, 3]), "int64"))
    assert nce.shape == (3, 1)
    assert nn.AdaptiveMaxPool3D(2)(
        paddle.to_tensor(np.ones((1, 2, 4, 4, 4), "float32"))
    ).shape == (1, 2, 2, 2, 2)
    assert issubclass(nn.LSTMCell, nn.RNNCellBase)


def test_tree_conv_tbcnn():
    """ops.tree_conv / nn.TreeConv (reference tree_conv_op.cc TBCNN):
    hand-computed continuous-binary-tree window on a 3-node tree."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn, ops

    x = np.zeros((1, 3, 2), "float32")
    x[0, 0] = [1.0, 0.0]
    x[0, 1] = [0.0, 1.0]
    x[0, 2] = [0.0, 2.0]
    edges = np.array([[[1, 2], [1, 3], [0, 0]]], "int64")  # 0-padded
    f = np.zeros((2, 3, 1, 1), "float32")
    f[0, 0, 0, 0] = 1.0   # top: feature 0
    f[1, 1, 0, 0] = 1.0   # left: feature 1
    f[1, 2, 0, 0] = 1.0   # right: feature 1
    out = ops.tree_conv(paddle.to_tensor(x),
                        paddle.to_tensor(edges, "int64"),
                        paddle.to_tensor(f))
    o = np.asarray(out._value)
    # root window: top(1) + child A at eta_l=1 (1) + child B at eta_r=1 (2)
    np.testing.assert_allclose(o[0, 0, 0, 0], np.tanh(4.0), rtol=1e-5)
    np.testing.assert_allclose(o[0, 1, 0, 0], 0.0, atol=1e-6)
    paddle.seed(0)
    layer = nn.TreeConv(2, 4, num_filters=2)
    assert layer(paddle.to_tensor(x),
                 paddle.to_tensor(edges, "int64")).shape == (1, 3, 4, 2)
