"""Round-4 detection-zoo widening (reference operators/detection/):
anchor_generator, density_prior_box, matrix_nms, target_assign,
polygon_box_transform, FPN distribute/collect, box_decoder_and_assign,
mine_hard_examples, yolov3_loss."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import ops


def T(x, dtype="float32"):
    return paddle.to_tensor(np.asarray(x, dtype))


def test_anchor_generator_shapes_and_centers():
    feat = T(np.zeros((1, 8, 4, 5)))
    anchors, var = ops.anchor_generator(feat, anchor_sizes=[32, 64],
                                        aspect_ratios=[1.0, 2.0],
                                        stride=(16.0, 16.0))
    assert anchors.shape == (4, 5, 4, 4) and var.shape == anchors.shape
    a = anchors.numpy()
    # cell (0,0) anchors center at offset*stride = 8
    np.testing.assert_allclose((a[0, 0, 0, 0] + a[0, 0, 0, 2]) / 2, 8.0,
                               atol=1e-4)
    # square size-32 anchor has area 32^2
    w = a[0, 0, 0, 2] - a[0, 0, 0, 0]
    h = a[0, 0, 0, 3] - a[0, 0, 0, 1]
    np.testing.assert_allclose(w * h, 1024.0, rtol=1e-4)


def test_density_prior_box():
    feat = T(np.zeros((1, 3, 2, 2)))
    img = T(np.zeros((1, 3, 32, 32)))
    boxes, var = ops.density_prior_box(feat, img, densities=[2],
                                       fixed_sizes=[8.0],
                                       fixed_ratios=[1.0], clip=True)
    assert boxes.shape == (2, 2, 4, 4)
    b = boxes.numpy()
    assert (b >= 0).all() and (b <= 1).all()
    # all 4 shifted centers distinct
    centers = (b[0, 0, :, :2] + b[0, 0, :, 2:]) / 2
    assert len({tuple(c) for c in centers.round(4).tolist()}) == 4


def test_matrix_nms_decays_overlaps():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                     "float32")
    scores = np.array([[0.9, 0.8, 0.7]], "float32")
    out, idx = ops.matrix_nms(T(boxes), T(scores), score_threshold=0.1,
                              post_threshold=0.0)
    o = np.asarray(out._value)
    assert o.shape[1] == 6 and o.shape[0] == 3
    # top box keeps its score; overlapping second decays; far third ~keeps
    srt = o[np.argsort(-o[:, 1])]
    assert abs(srt[0, 1] - 0.9) < 1e-5
    decayed = o[np.asarray(idx._value) == 1][0, 1]
    assert decayed < 0.8 * 0.7


def test_target_assign():
    x = T(np.arange(2 * 3 * 2).reshape(2, 3, 2))
    mi = T(np.array([[0, 2, -1], [1, -1, 0]]), "int64").astype("int32")
    out, w = ops.target_assign(x, mi, mismatch_value=-9)
    o = np.asarray(out._value)
    np.testing.assert_allclose(o[0, 0], [0, 1])
    np.testing.assert_allclose(o[0, 1], [4, 5])
    np.testing.assert_allclose(o[0, 2], [-9, -9])
    np.testing.assert_allclose(np.asarray(w._value)[..., 0],
                               [[1, 1, 0], [1, 0, 1]])


def test_polygon_box_transform():
    x = np.zeros((1, 4, 2, 3), "float32")
    out = ops.polygon_box_transform(T(x)).numpy()
    # with zero offsets, even channels = 4*x coord, odd = 4*y coord
    np.testing.assert_allclose(out[0, 0, 0], [0, 4, 8])
    np.testing.assert_allclose(out[0, 1, 1], [4, 4, 4])


def test_fpn_distribute_and_collect():
    rois = np.array([[0, 0, 10, 10],       # small -> low level
                     [0, 0, 300, 300],     # big  -> high level
                     [0, 0, 60, 60]], "float32")
    outs, restore = ops.distribute_fpn_proposals(T(rois), 2, 5, 4, 224)
    sizes = [int(np.asarray(o._value).shape[0]) for o in outs]
    assert sum(sizes) == 3 and sizes[0] >= 1
    # restore index maps original row -> its position in the concat
    cat = np.concatenate([np.asarray(o._value) for o in outs])
    np.testing.assert_allclose(cat[np.asarray(restore._value)], rois)
    col = ops.collect_fpn_proposals(
        [T(rois[:2]), T(rois[2:])],
        [T(np.array([0.3, 0.9])), T(np.array([0.5]))], post_nms_top_n=2)
    c = np.asarray(col._value)
    np.testing.assert_allclose(c[0], rois[1])   # highest score first
    assert c.shape == (2, 4)


def test_box_decoder_and_assign():
    priors = np.array([[0, 0, 10, 10]], "float32")
    pvar = np.array([[0.1, 0.1, 0.2, 0.2]], "float32")
    tb = np.zeros((1, 8), "float32")            # 2 classes, zero deltas
    score = np.array([[0.2, 0.8]], "float32")
    decoded, assigned = ops.box_decoder_and_assign(T(priors), T(pvar),
                                                   T(tb), T(score))
    np.testing.assert_allclose(np.asarray(assigned._value)[0],
                               priors[0], rtol=1e-5)
    assert decoded.shape == (1, 8)


def test_mine_hard_examples():
    loss = np.array([[0.9, 0.1, 0.8, 0.2, 0.5]], "float32")
    mi = np.array([[3, -1, -1, -1, -1]], "int64")   # 1 positive, 4 negs
    mask = ops.mine_hard_examples(T(loss), T(mi, "int64"),
                                  neg_pos_ratio=2.0).numpy()
    # top-2 loss negatives are slots 2 (0.8) and 4 (0.5)
    np.testing.assert_array_equal(mask[0], [0, 0, 1, 0, 1])


def test_yolov3_loss_trains_signal():
    import jax
    rng = np.random.RandomState(0)
    n, a, c, h, w = 1, 3, 4, 4, 4
    x = rng.randn(n, a * (5 + c), h, w).astype("float32") * 0.1
    gt_box = np.array([[[0.5, 0.5, 0.4, 0.4]]], "float32")
    gt_label = np.array([[2]], "int64")
    loss = ops.yolov3_loss(T(x), T(gt_box), T(gt_label, "int64"),
                           anchors=[10, 13, 16, 30, 33, 23],
                           anchor_mask=[0, 1, 2], class_num=c,
                           downsample_ratio=8)
    v = float(np.asarray(loss._value)[0])
    assert np.isfinite(v) and v > 0
    # differentiable
    xt = T(x)
    xt.stop_gradient = False
    out = ops.yolov3_loss(xt, T(gt_box), T(gt_label, "int64"),
                          anchors=[10, 13, 16, 30, 33, 23],
                          anchor_mask=[0, 1, 2], class_num=c,
                          downsample_ratio=8)
    out.sum().backward()
    assert np.abs(np.asarray(xt.grad._value)).sum() > 0
