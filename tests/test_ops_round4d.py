"""Round-4 op widening batch 4: CTR/industrial families, fake quant ops,
chunk_eval, gru/lstm units, accuracy/auc (references cited per-op)."""
import numpy as np
import torch

import paddle_tpu as paddle
from paddle_tpu import ops


def T(x, dtype="float32"):
    return paddle.to_tensor(np.asarray(x, dtype))


def test_cvm_and_hash():
    x = np.array([[3.0, 1.0, 5.0, 6.0]], "float32")
    out = ops.cvm(T(x)).numpy()
    np.testing.assert_allclose(out[0, 0], np.log(4.0), rtol=1e-6)
    np.testing.assert_allclose(out[0, 1], np.log(2.0) - np.log(4.0),
                               rtol=1e-5)
    np.testing.assert_allclose(out[0, 2:], [5, 6])
    assert ops.cvm(T(x), use_cvm=False).shape == (1, 2)
    h = ops.hash_bucket(T([[1], [2]], "int64"), num_hash=3,
                        mod_by=1000).numpy()
    assert h.shape == (2, 1, 3)
    assert (h >= 0).all() and (h < 1000).all()
    assert len(np.unique(h)) > 1


def test_batch_fc_rank_attention_match_fsp():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 4).astype("float32")
    w = rng.randn(2, 4, 5).astype("float32")
    out = ops.batch_fc(T(x), T(w)).numpy()
    np.testing.assert_allclose(out, np.einsum("sbi,sio->sbo", x, w),
                               rtol=1e-5)
    xr = rng.randn(4, 6).astype("float32")
    ro = np.array([[1], [2], [1], [3]], "int32")
    rp = rng.randn(3, 6, 2).astype("float32")
    out = ops.rank_attention(T(xr), T(ro, "int32"), T(rp)).numpy()
    np.testing.assert_allclose(out[1], xr[1] @ rp[1], rtol=1e-5)
    a = rng.randn(2, 3, 4).astype("float32")
    b = rng.randn(2, 5, 4).astype("float32")
    wt = rng.randn(4, 2, 4).astype("float32")
    mm = ops.match_matrix_tensor(T(a), T(b), T(wt)).numpy()
    assert mm.shape == (2, 2, 3, 5)
    np.testing.assert_allclose(
        mm[0, 0, 0, 0], a[0, 0] @ wt[:, 0] @ b[0, 0], rtol=1e-4)
    f1 = rng.randn(1, 3, 4, 4).astype("float32")
    f2 = rng.randn(1, 5, 4, 4).astype("float32")
    fsp = ops.fsp_matrix(T(f1), T(f2)).numpy()
    np.testing.assert_allclose(
        fsp, np.einsum("nahw,nbhw->nab", f1, f2) / 16, rtol=1e-5)


def test_conv_shift():
    x = np.array([[1.0, 2, 3, 4, 5]], "float32")
    y = np.array([[0.0, 1.0, 0.0]], "float32")   # identity kernel
    np.testing.assert_allclose(ops.conv_shift(T(x), T(y)).numpy(), x,
                               rtol=1e-6)
    y2 = np.array([[1.0, 0.0, 0.0]], "float32")  # shift by -1 tap
    out = ops.conv_shift(T(x), T(y2)).numpy()
    np.testing.assert_allclose(out, np.roll(x, 1, axis=1), rtol=1e-6)


def test_filter_by_instag():
    x = np.arange(12).reshape(4, 3).astype("float32")
    tags = [[1], [2, 3], [4], [3]]
    out, idx = ops.filter_by_instag(T(x), tags, [3])
    np.testing.assert_array_equal(np.asarray(idx._value), [1, 3])
    np.testing.assert_allclose(np.asarray(out._value), x[[1, 3]])


def test_fake_quant_family():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 6).astype("float32")
    q, scale = ops.fake_quantize_abs_max(T(x))
    assert abs(float(scale.numpy()) - np.abs(x).max()) < 1e-6
    np.testing.assert_allclose(q.numpy(), x, atol=np.abs(x).max() / 127)
    qc, sc = ops.fake_channel_wise_quantize_abs_max(T(x), quant_axis=0)
    assert sc.shape == (4,)
    np.testing.assert_allclose(qc.numpy(), x,
                               atol=np.abs(x).max() / 127 + 1e-6)
    q2, state = ops.fake_quantize_moving_average_abs_max(
        T(x), T(np.asarray(1.0)))
    assert np.isfinite(q2.numpy()).all()
    deq = ops.dequantize_abs_max(T(np.array([127.0])), T(np.asarray(2.0)),
                                 127.0)
    np.testing.assert_allclose(deq.numpy(), [2.0], rtol=1e-6)


def test_chunk_eval_iob():
    # tags: B-0=0, I-0=1, Outside=2
    label = np.array([[0, 1, 2, 0, 1]])
    infer = np.array([[0, 1, 2, 0, 2]])  # second chunk truncated -> wrong
    p, r, f1, ni, nl, nc = ops.chunk_eval(infer, label,
                                          num_chunk_types=1)
    assert (ni, nl, nc) == (2, 2, 1)
    assert abs(p - 0.5) < 1e-9 and abs(r - 0.5) < 1e-9


def test_gru_lstm_units_match_torch_cells():
    rng = np.random.RandomState(2)
    b, d = 3, 4
    # lstm_unit vs torch.lstm_cell math (pre-projected gates)
    gates = rng.randn(b, 4 * d).astype("float32")
    c_prev = rng.randn(b, d).astype("float32")
    h, c = ops.lstm_unit(T(gates), T(c_prev))
    i, f, g, o = (gates[:, k * d:(k + 1) * d] for k in range(4))
    sig = lambda z: 1 / (1 + np.exp(-z))  # noqa: E731
    c_ref = sig(f) * c_prev + sig(i) * np.tanh(g)
    np.testing.assert_allclose(c.numpy(), c_ref, rtol=1e-5)
    np.testing.assert_allclose(h.numpy(), sig(o) * np.tanh(c_ref),
                               rtol=1e-5)
    # gru_unit: update gate u=1 keeps the previous hidden state
    x = np.zeros((b, 3 * d), "float32")
    x[:, :d] = 50.0                       # huge update gate logit
    hp = rng.randn(b, d).astype("float32")
    w = rng.randn(d, 3 * d).astype("float32") * 0.0
    h, _, _ = ops.gru_unit(T(x), T(hp), T(w))
    np.testing.assert_allclose(h.numpy(), hp, rtol=1e-4)


def test_accuracy_and_auc():
    logits = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], "float32")
    label = np.array([1, 0, 0], "int64")
    acc = float(ops.accuracy(T(logits), T(label, "int64")).numpy())
    assert abs(acc - 2 / 3) < 1e-6
    # perfectly separable scores -> auc 1
    pred = np.array([0.1, 0.2, 0.8, 0.9], "float32")
    lab = np.array([0, 0, 1, 1], "int64")
    a = float(ops.auc(T(pred), T(lab, "int64")).numpy())
    assert a > 0.99
    a2 = float(ops.auc(T(pred[::-1].copy()), T(lab, "int64")).numpy())
    assert a2 < 0.05


def test_metric_chunk_evaluator_and_edit_distance():
    from paddle_tpu.metric import ChunkEvaluator, EditDistance
    ce = ChunkEvaluator(num_chunk_types=1)
    ce.update(np.array([[0, 1, 2, 0, 2]]), np.array([[0, 1, 2, 0, 1]]))
    p, r, f1 = ce.accumulate()
    assert 0 < p <= 1 and 0 < r <= 1 and 0 < f1 <= 1
    ce.update(np.array([[0, 1]]), np.array([[0, 1]]))  # perfect batch
    p2, _, _ = ce.accumulate()
    assert p2 >= p

    ed = EditDistance(normalized=False)
    ed.update([[1, 2, 3]], [[1, 3]])
    ed.update([[4]], [[4]])
    assert ed.accumulate() == 0.5          # (1 + 0) / 2


def test_dlpack_interop_with_torch():
    """utils.dlpack (reference paddle/utils/dlpack.py): zero-copy exchange
    with torch over the DLPack protocol."""
    import torch as _torch

    import paddle_tpu as paddle
    from paddle_tpu.utils import dlpack

    t = _torch.arange(6, dtype=_torch.float32).reshape(2, 3)
    pt = dlpack.from_dlpack(t)
    np.testing.assert_allclose(pt.numpy(), t.numpy())

    x = paddle.to_tensor(np.ones((3, 2), "float32") * 7)
    back = _torch.utils.dlpack.from_dlpack(dlpack.to_dlpack(x))
    np.testing.assert_allclose(back.numpy(), 7.0)


def test_py_func_host_callback_in_jit_and_grad():
    """ops.py_func (reference py_func_op.cc): host numpy code inside the
    compiled step via pure_callback, with a custom backward."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import ops

    def host_fn(a):
        return np.sin(a) * 2.0

    def host_bwd(a, g):
        return (np.cos(a) * 2.0 * g,)

    x = paddle.to_tensor(np.array([0.0, 1.0, 2.0], "float32"),
                         stop_gradient=False)
    out = ops.py_func(host_fn, x, backward_func=host_bwd)
    np.testing.assert_allclose(out.numpy(), np.sin(x.numpy()) * 2.0,
                               rtol=1e-6)
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._value),
                               np.cos(x.numpy()) * 2.0, rtol=1e-5)

    # composes under jit (XLA inserts the host round-trip)
    from paddle_tpu.core.tensor import Tensor

    @jax.jit
    def f(v):
        return ops.py_func(host_fn, Tensor(v, _internal=True))._value

    np.testing.assert_allclose(
        np.asarray(f(jnp.arange(3, dtype=jnp.float32))),
        np.sin([0, 1, 2]) * 2, rtol=1e-5)


def test_new_functional_smalls():
    """The round-4 nn.functional additions (dice_loss, alpha_dropout,
    dropout2d/3d, 1-D pools, soft_relu, add_position_encoding,
    image_resize aliases)."""
    import paddle_tpu as paddle
    from paddle_tpu.nn import functional as F

    rng = np.random.RandomState(0)
    # dice loss: perfect prediction -> ~0
    lab = np.array([[0], [1]], "int64")
    perfect = np.eye(2, dtype="float32")[lab[:, 0]]
    dl = float(F.dice_loss(T(perfect), paddle.to_tensor(lab, "int64"))
               .numpy())
    assert dl < 1e-3
    # soft_relu = softplus with clipping
    x = T([-1.0, 0.0, 3.0])
    np.testing.assert_allclose(F.soft_relu(x).numpy(),
                               np.log1p(np.exp([-1.0, 0.0, 3.0])),
                               rtol=1e-5)
    # 1-D pools
    seq = T(rng.randn(2, 3, 8))
    assert F.avg_pool1d(seq, 2, stride=2).shape == (2, 3, 4)
    assert F.adaptive_avg_pool1d(seq, 2).shape == (2, 3, 2)
    assert F.adaptive_max_pool1d(seq, 4).shape == (2, 3, 4)
    # dropout2d zeroes whole channels; eval mode is identity
    img = T(np.ones((2, 4, 5, 5)))
    paddle.seed(7)
    out = F.dropout2d(img, p=0.5, training=True).numpy()
    per_chan = out.reshape(2, 4, -1)
    assert set(np.unique((per_chan > 0).mean(axis=2))) <= {0.0, 1.0}
    np.testing.assert_allclose(
        F.dropout2d(img, p=0.5, training=False).numpy(), 1.0)
    paddle.seed(8)
    out3 = F.dropout3d(T(np.ones((1, 3, 2, 2, 2))), p=0.5).numpy()
    assert out3.shape == (1, 3, 2, 2, 2)
    # alpha_dropout preserves mean/std approximately on SELU-scale data
    paddle.seed(9)
    big = T(rng.randn(20000).astype("float32"))
    ad = F.alpha_dropout(big, p=0.3).numpy()
    assert abs(ad.mean()) < 0.1 and abs(ad.std() - 1.0) < 0.15
    # positional encoding: beta=0 is identity; known sin at pos 1
    xb = T(rng.randn(1, 4, 6))
    np.testing.assert_allclose(
        F.add_position_encoding(xb, beta=0.0).numpy(), xb.numpy(),
        rtol=1e-6)
    pe_only = F.add_position_encoding(T(np.zeros((1, 4, 6))),
                                      alpha=0.0).numpy()
    np.testing.assert_allclose(pe_only[0, 0, :3], 0.0, atol=1e-6)
    np.testing.assert_allclose(pe_only[0, 1, 0], np.sin(1.0), rtol=1e-5)
    # resize aliases
    img2 = T(np.ones((1, 1, 4, 4)))
    assert F.resize_nearest(img2, out_shape=(8, 8)).shape == (1, 1, 8, 8)
    assert F.image_resize(img2, out_shape=(2, 2)).shape == (1, 1, 2, 2)
