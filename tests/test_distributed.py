"""Distributed tests on the virtual 8-device CPU mesh.

Models the reference's strategy of numerically checking collectives with
local multi-process ranks (test_collective_base.py) — here ranks are mesh
shards in one process.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import collective, fleet, mesh as mesh_mod
from paddle_tpu.distributed.moe import MoELayer
from paddle_tpu.distributed.pipeline import gpipe, micro_batch, pipeline_loss
from paddle_tpu.distributed.ring_attention import (ring_attention,
                                                   sequence_parallel_attention,
                                                   ulysses_attention)


@pytest.fixture
def mesh8():
    m = mesh_mod.init_mesh({"dp": 8})
    yield m


@pytest.fixture
def mesh_sp():
    # sp=4: the ring/ulysses math is degree-independent and the 8-way
    # form is exercised by the dryrun gate; 4 halves the scan-of-permutes
    # compile time that dominated the suite profile
    m = mesh_mod.init_mesh({"sp": 4}, name="default")
    yield m
    mesh_mod.init_mesh({"dp": 8})


def test_collectives_inside_shard_map(mesh8):
    x = jnp.arange(8.0)

    def body(xl):
        s = collective.all_reduce(xl, op=collective.ReduceOp.SUM)
        mx = collective.all_reduce(xl * 1.0, op=collective.ReduceOp.MAX)
        return s, mx

    s, mx = mesh_mod.shard_map(body, mesh=mesh8, in_specs=P("dp"),
                          out_specs=P("dp"))(x)
    np.testing.assert_allclose(np.asarray(s), np.full(8, 28.0))
    np.testing.assert_allclose(np.asarray(mx), np.full(8, 7.0))


def test_reduce_scatter_and_alltoall(mesh8):
    x = jnp.arange(64.0).reshape(8, 8)

    def body(xl):
        rs = collective._reduce_scatter_raw(xl[0], axis="dp",
                                            op=collective.ReduceOp.SUM)
        a2a = collective._alltoall_raw(xl[0], axis="dp")
        return rs[None], a2a[None]

    rs, a2a = mesh_mod.shard_map(body, mesh=mesh8, in_specs=P("dp"),
                            out_specs=P("dp"))(x)
    # reduce_scatter of rows 0..7: rank r gets sum over ranks of element r
    np.testing.assert_allclose(np.asarray(rs).reshape(-1),
                               x.sum(axis=0))
    # alltoall transposes the (rank, slot) grid
    np.testing.assert_allclose(np.asarray(a2a).reshape(8, 8), np.asarray(x).T)


def test_broadcast_and_ppermute(mesh8):
    x = jnp.arange(8.0)

    def body(xl):
        b = collective._broadcast_raw(xl, axis="dp", src=3)
        ring = collective._ppermute_raw(xl, axis="dp",
                                        perm=tuple((i, (i + 1) % 8)
                                                   for i in range(8)))
        return b, ring

    b, ring = mesh_mod.shard_map(body, mesh=mesh8, in_specs=P("dp"),
                            out_specs=P("dp"))(x)
    np.testing.assert_allclose(np.asarray(b), np.full(8, 3.0))
    np.testing.assert_allclose(np.asarray(ring), np.roll(np.arange(8.0), 1))


def test_eager_single_rank_noop():
    t = paddle.to_tensor([1.0, 2.0])
    mesh_mod.init_mesh({"dp": 1}, name="single")
    g = collective.Group("zz")  # axis absent => size 1 => identity
    out = collective.all_reduce(t, group=g)
    np.testing.assert_allclose(out.numpy(), [1.0, 2.0])


def test_ring_attention_matches_dense(mesh_sp):
    rng = np.random.RandomState(0)
    b, h, s, d = 2, 2, 32, 8
    q = rng.randn(b, h, s, d).astype("float32")
    k = rng.randn(b, h, s, d).astype("float32")
    v = rng.randn(b, h, s, d).astype("float32")

    from paddle_tpu.nn.functional import scaled_dot_product_attention
    dense = scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        training=False).numpy()
    ring = sequence_parallel_attention(paddle.to_tensor(q),
                                       paddle.to_tensor(k),
                                       paddle.to_tensor(v), mode="ring")
    np.testing.assert_allclose(ring.numpy(), dense, rtol=2e-4, atol=2e-5)


def test_ring_attention_causal(mesh_sp):
    rng = np.random.RandomState(1)
    b, h, s, d = 1, 2, 16, 4
    q, k, v = (rng.randn(b, h, s, d).astype("float32") for _ in range(3))
    from paddle_tpu.nn.functional import scaled_dot_product_attention
    dense = scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        is_causal=True, training=False).numpy()
    ring = sequence_parallel_attention(paddle.to_tensor(q),
                                       paddle.to_tensor(k),
                                       paddle.to_tensor(v), causal=True,
                                       mode="ring")
    np.testing.assert_allclose(ring.numpy(), dense, rtol=2e-4, atol=2e-5)


def test_ulysses_matches_dense(mesh_sp):
    rng = np.random.RandomState(2)
    b, h, s, d = 1, 8, 16, 4  # heads divisible by sp=8
    q, k, v = (rng.randn(b, h, s, d).astype("float32") for _ in range(3))
    from paddle_tpu.nn.functional import scaled_dot_product_attention
    dense = scaled_dot_product_attention(
        paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
        training=False).numpy()
    uly = sequence_parallel_attention(paddle.to_tensor(q),
                                      paddle.to_tensor(k),
                                      paddle.to_tensor(v), mode="ulysses")
    np.testing.assert_allclose(uly.numpy(), dense, rtol=2e-4, atol=2e-5)


def test_tensor_parallel_linears():
    mesh = mesh_mod.init_mesh({"tp": 8})
    from paddle_tpu.distributed.fleet.meta_parallel import (
        ColumnParallelLinear, RowParallelLinear)
    paddle.seed(3)
    col = ColumnParallelLinear(8, 16, gather_output=True)
    row = RowParallelLinear(16, 8, input_is_parallel=False)
    # dense reference from the local shards (tp=8 -> per-shard out 2)
    x = np.random.RandomState(3).randn(4, 8).astype("float32")

    def spmd(xl):
        h = col(paddle.Tensor(xl, _internal=True))
        out = row(h._value if hasattr(h, "_value") else h)
        return out._value if hasattr(out, "_value") else out

    out = mesh_mod.shard_map(spmd, mesh=mesh, in_specs=P(), out_specs=P())(jnp.asarray(x))
    assert np.asarray(out).shape == (4, 8)
    mesh_mod.init_mesh({"dp": 8})


def test_pipeline_matches_sequential():
    mesh = mesh_mod.init_mesh({"pp": 8}, name="default")
    rng = np.random.RandomState(0)
    d = 4
    # 8 homogeneous stages: h -> tanh(h @ w_r), rank r holds w_r
    ws = rng.randn(8, d, d).astype("float32") * 0.5
    x = rng.randn(16, d).astype("float32")
    xm = micro_batch(jnp.asarray(x), 4)  # [4, 4, d]

    def run(ws_l, xm_l):
        from jax import lax
        def stage(h):
            return jnp.tanh(h @ ws_l[0])
        outs = gpipe(stage, xm_l, axis="pp")
        # only the last stage holds real outputs; psum replicates them
        mask = (lax.axis_index("pp") == 7).astype(outs.dtype)
        return lax.psum(outs * mask, "pp")

    outs = mesh_mod.shard_map(run, mesh=mesh,
                         in_specs=(P("pp"), P()), out_specs=P())(
        jnp.asarray(ws), xm)
    # sequential reference
    ref = x.copy()
    for r in range(8):
        ref = np.tanh(ref @ ws[r])
    got = np.asarray(outs).reshape(16, d)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    mesh_mod.init_mesh({"dp": 8})


def test_pipeline_loss_and_grads_match():
    mesh = mesh_mod.init_mesh({"pp": 8}, name="default")
    rng = np.random.RandomState(1)
    d = 4
    ws = rng.randn(8, d, d).astype("float32") * 0.5
    x = rng.randn(8, d).astype("float32")
    y = rng.randn(8, d).astype("float32")
    xm = micro_batch(jnp.asarray(x), 2)
    ym = micro_batch(jnp.asarray(y), 2)

    def loss_fn_ref(ws_all):
        h = jnp.asarray(x)
        for r in range(8):
            h = jnp.tanh(h @ ws_all[r])
        return jnp.mean((h - jnp.asarray(y)) ** 2)

    ref_loss, ref_grads = jax.value_and_grad(loss_fn_ref)(jnp.asarray(ws))

    def spmd_loss(ws_l, xm_l, ym_l):
        def stage(h):
            return jnp.tanh(h @ ws_l[0])

        def mb_loss(h, lbl):
            return jnp.mean((h - lbl) ** 2)

        return pipeline_loss(stage, mb_loss, xm_l, ym_l, axis="pp")

    def outer(ws_full):
        return mesh_mod.shard_map(spmd_loss, mesh=mesh,
                             in_specs=(P("pp"), P(), P()),
                             out_specs=P())(ws_full, xm, ym).mean()

    loss, grads = jax.value_and_grad(outer)(jnp.asarray(ws))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grads), np.asarray(ref_grads),
                               rtol=1e-3, atol=1e-5)
    mesh_mod.init_mesh({"dp": 8})


def test_moe_layer_dense_fallback():
    mesh_mod.init_mesh({"dp": 8})
    paddle.seed(4)
    moe = MoELayer(d_model=8, d_hidden=16, num_experts=4, axis="ep")
    x = paddle.randn([2, 6, 8])
    out = moe(x)
    assert out.shape == (2, 6, 8)
    out.mean().backward()
    assert moe.w_up.grad is not None


def test_moe_expert_parallel():
    mesh = mesh_mod.init_mesh({"ep": 8}, name="default")
    paddle.seed(5)
    moe = MoELayer(d_model=8, d_hidden=16, num_experts=8, axis="ep")
    x = np.random.RandomState(5).randn(2, 4, 8).astype("float32")
    params, _ = moe.functional_state()
    rng = np.random.RandomState(6)
    # global expert stacks: [E_total, ...] sharded to this rank's [E/ep, ...]
    globals_ = {}
    specs = {}
    for k, v in params.items():
        if any(s in k for s in ("w_up", "b_up", "w_down", "b_down")):
            shape = (8,) + tuple(v.shape[1:])
            globals_[k] = jnp.asarray(rng.randn(*shape).astype("float32") * 0.1)
            specs[k] = P("ep")
        else:
            globals_[k] = v
            specs[k] = P()

    def spmd(p, xv):
        moe.load_functional_state(p)
        out = moe(paddle.Tensor(xv, _internal=True))
        return out._value

    out = mesh_mod.shard_map(spmd, mesh=mesh, in_specs=(specs, P()),
                        out_specs=P())(globals_,
                                                        jnp.asarray(x))
    assert np.asarray(out).shape == (2, 4, 8)
    assert np.isfinite(np.asarray(out)).all()
    mesh_mod.init_mesh({"dp": 8})


def test_fleet_init_and_strategy():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_pipe_parallel_world_size() == 2
    m = mesh_mod.get_mesh()
    assert set(m.axis_names) == {"dp", "tp", "pp"}
    assert fleet.worker_num() >= 1 and fleet.is_first_worker()
    mesh_mod.init_mesh({"dp": 8})


def test_model_fit_data_parallel(mesh8):
    from paddle_tpu import Model
    from paddle_tpu.io import TensorDataset
    paddle.seed(6)
    X = np.random.rand(128, 8).astype("float32")
    W = np.random.rand(8, 1).astype("float32")
    Y = X @ W
    net = nn.Linear(8, 1)
    model = Model(net)
    opt = fleet.distributed_optimizer(
        optimizer.Adam(learning_rate=0.05, parameters=net.parameters()))
    model.prepare(optimizer=opt, loss=nn.MSELoss())
    from paddle_tpu.hapi.callbacks import History
    h = History()
    model.fit(TensorDataset([X, Y]), batch_size=64, epochs=8, verbose=0,
              callbacks=[h], drop_last=True)
    losses = h.history["loss"]
    assert losses[-1] < losses[0] * 0.1, losses


def test_data_parallel_wrapper(mesh8):
    from paddle_tpu.distributed import DataParallel
    net = nn.Linear(4, 2)
    dp = DataParallel(net)
    x = paddle.randn([8, 4])
    out = dp(x)
    assert out.shape == (8, 2)
    out.mean().backward()
    assert net.weight.grad is not None


def test_zero_sharded_dp(mesh8):
    from paddle_tpu import Model
    from paddle_tpu.io import TensorDataset
    paddle.seed(7)
    X = np.random.rand(64, 8).astype("float32")
    Y = (X @ np.random.rand(8, 1).astype("float32"))
    net = nn.Linear(8, 1)
    strategy = fleet.DistributedStrategy()
    strategy.sharding = True
    opt = fleet.distributed_optimizer(
        optimizer.Adam(learning_rate=0.05, parameters=net.parameters()),
        strategy)
    model = Model(net)
    model.prepare(optimizer=opt, loss=nn.MSELoss())
    from paddle_tpu.hapi.callbacks import History
    h = History()
    model.fit(TensorDataset([X, Y]), batch_size=64, epochs=6, verbose=0,
              callbacks=[h], drop_last=True)
    assert h.history["loss"][-1] < h.history["loss"][0]


def test_pipeline_1f1b_schedule_matches_gpipe():
    """schedule='1f1b' (per-tick remat, bounded activation stash) must be
    numerically identical to gpipe — rematerialization changes memory,
    never math. Swept over microbatch counts."""
    from paddle_tpu.distributed.pipeline import bubble_fraction
    # 4 stages, 2 microbatch counts: full 8-stage coverage lives in the
    # dryrun_multichip gate; this test's job is ONLY gpipe==1f1b math,
    # and 6 shard_map compilations at 8 stages cost minutes of suite time
    mesh = mesh_mod.init_mesh({"pp": 4}, name="default")
    rng = np.random.RandomState(3)
    d = 4
    ws = rng.randn(4, d, d).astype("float32") * 0.5
    x = rng.randn(16, d).astype("float32")
    y = rng.randn(16, d).astype("float32")

    def run(schedule, n_micro):
        xm = micro_batch(jnp.asarray(x), n_micro)
        ym = micro_batch(jnp.asarray(y), n_micro)

        def spmd_loss(ws_l, xm_l, ym_l):
            def stage(h):
                return jnp.tanh(h @ ws_l[0])

            def mb_loss(h, lbl):
                return jnp.mean((h - lbl) ** 2)

            return pipeline_loss(stage, mb_loss, xm_l, ym_l, axis="pp",
                                 schedule=schedule)

        def outer(ws_full):
            return mesh_mod.shard_map(spmd_loss, mesh=mesh,
                                 in_specs=(P("pp"), P(), P()),
                                 out_specs=P())(ws_full, xm, ym).mean()

        return jax.value_and_grad(outer)(jnp.asarray(ws))

    for n_micro in (2, 8):  # bubble high -> low ends of the sweep
        l0, g0 = run("gpipe", n_micro)
        l1, g1 = run("1f1b", n_micro)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g0), np.asarray(g1),
                                   rtol=1e-5, atol=1e-7)
    assert bubble_fraction(8, 8) < bubble_fraction(2, 8)
    mesh_mod.init_mesh({"dp": 8})


def test_pipeline_interleaved_matches_sequential_and_grads():
    """Interleaved virtual-stage schedule (VERDICT r04 item 7): 4 ranks x
    2 chunks = 8 global stages; forward and grads must match the
    non-pipelined 8-layer reference."""
    from paddle_tpu.distributed.pipeline import interleaved

    mesh = mesh_mod.init_mesh({"pp": 4}, name="default")
    rng = np.random.RandomState(2)
    d = 4
    # chunk c on rank r is global stage c*4 + r: ws[global_stage]
    ws = rng.randn(8, d, d).astype("float32") * 0.5
    # per-rank param layout: [rank][chunk] -> ws[c*4 + r]
    ws_by_rank = np.stack([np.stack([ws[c * 4 + r] for c in range(2)])
                           for r in range(4)])  # [4, 2, d, d]
    x = rng.randn(8, d).astype("float32")
    y = rng.randn(8, d).astype("float32")
    xm = micro_batch(jnp.asarray(x), 4)   # M=4 (divisible by n=4)
    ym = micro_batch(jnp.asarray(y), 4)

    def loss_fn_ref(ws_all):
        h = jnp.asarray(x)
        for s in range(8):
            h = jnp.tanh(h @ ws_all[s])
        return jnp.mean((h - jnp.asarray(y)) ** 2)

    ref_loss, ref_grads = jax.value_and_grad(loss_fn_ref)(jnp.asarray(ws))

    def spmd_loss(wr, xm_l, ym_l):
        chunks = [lambda h, c=c: jnp.tanh(h @ wr[0, c]) for c in range(2)]

        def mb_loss(h, lbl):
            return jnp.mean((h - lbl) ** 2)

        return pipeline_loss(chunks, mb_loss, xm_l, ym_l, axis="pp",
                             schedule="interleaved")

    def outer(wr_full):
        return mesh_mod.shard_map(spmd_loss, mesh=mesh,
                             in_specs=(P("pp"), P(), P()),
                             out_specs=P())(wr_full, xm, ym).mean()

    loss, grads = jax.value_and_grad(outer)(jnp.asarray(ws_by_rank))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    # map per-rank grads back to global-stage layout and compare
    g = np.asarray(grads)
    for r in range(4):
        for c in range(2):
            np.testing.assert_allclose(g[r, c], np.asarray(ref_grads)[c * 4 + r],
                                       rtol=1e-3, atol=1e-5)
    mesh_mod.init_mesh({"dp": 8})


def test_schedule_ticks_accounting():
    from paddle_tpu.distributed.pipeline import (bubble_fraction,
                                                 schedule_ticks)
    # 8 microbatches, 4 stages, 2 virtual chunks
    assert schedule_ticks(8, 4, "gpipe", num_virtual=2) == 2 * 11
    assert schedule_ticks(8, 4, "1f1b", num_virtual=2) == 2 * 11
    assert schedule_ticks(8, 4, "interleaved", num_virtual=2) == 19
    assert bubble_fraction(8, 4) == 3 / 11
