"""framework_lint in-process (ISSUE 1): the repo itself must be clean
(this test IS the tier-1 invocation of the lint), and seeded fixtures
with a registry/API.spec drift and a tracer-concretization hazard must
each produce violations."""
import json
import os
import sys
import tempfile
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import framework_lint  # noqa: E402


def test_repo_is_clean():
    problems = framework_lint.run_lint()
    assert problems == [], "\n".join(problems)
    assert framework_lint.main([]) == 0


def test_registry_spec_drift_detected():
    with tempfile.TemporaryDirectory() as tmp:
        # a spec that lost hash_bucket and carries a dead MISSING entry
        spec = os.path.join(tmp, "API.spec")
        with open(os.path.join(REPO, "API.spec")) as f:
            lines = [ln for ln in f
                     if not ln.split(" ", 1)[0].endswith(".hash_bucket")]
        lines.append("paddle_tpu.gone_op MISSING\n")
        with open(spec, "w") as f:
            f.writelines(lines)
        problems = framework_lint.check_registry_spec(
            spec, framework_lint.VERSIONS_PATH)
        assert any("hash_bucket" in p and "absent from API.spec" in p
                   for p in problems)
        assert any("MISSING" in p for p in problems)


def test_version_drift_detected():
    with tempfile.TemporaryDirectory() as tmp:
        with open(framework_lint.VERSIONS_PATH) as f:
            snap = json.load(f)
        # signature changed without a version bump
        snap["matmul"] = {"version": snap["matmul"]["version"],
                         "sig": "(x, y, old_flag=False)"}
        # and a version regression: snapshot is ahead of the live @defop
        snap["relu"] = {"version": 99, "sig": snap["relu"]["sig"]}
        # and a stale snapshot: live beam_search is v2, snapshot says v1
        snap["beam_search"] = {"version": 1, "sig": snap["beam_search"]["sig"]}
        # and a stale entry for a removed op
        snap["op_that_was_deleted"] = {"version": 1, "sig": "(x)"}
        vpath = os.path.join(tmp, "OP_VERSIONS.json")
        with open(vpath, "w") as f:
            json.dump(snap, f)
        problems = framework_lint.check_registry_spec(
            framework_lint.SPEC_PATH, vpath)
        assert any("matmul" in p and "without a version bump" in p
                   for p in problems)
        assert any("relu" in p and "regressed" in p for p in problems)
        assert any("beam_search" in p and "still records v1" in p
                   for p in problems)
        assert any("op_that_was_deleted" in p and "no longer registered"
                   in p for p in problems)


def test_concretization_hazards_detected_and_pragma_suppresses():
    src = textwrap.dedent("""
        import jax.numpy as jnp
        from paddle_tpu.ops._dispatch import defop

        @defop
        def bad_branch(x, axis=0):
            y = jnp.exp(x)
            if x > 0:                      # hazard: if on traced value
                y = y * 2
            return y

        @defop
        def bad_concretize(x):
            s = jnp.sum(x)
            n = float(x)                   # hazard: float() of traced
            return s.item() + n            # hazard: .item()

        @defop
        def fine_op(x, mode="a"):
            if mode == "a":                # static attr: fine
                return jnp.exp(x)
            if x.ndim == 2:                # metadata: fine
                return jnp.log(x)
            return jnp.sqrt(x)

        @defop
        def waived(x):
            if x > 0:  # lint: concretization-ok
                return jnp.exp(x)
            return x
    """)
    with tempfile.TemporaryDirectory() as tmp:
        with open(os.path.join(tmp, "fixture_ops.py"), "w") as f:
            f.write(src)
        hits = framework_lint.check_concretization(tmp)
    joined = "\n".join(hits)
    assert "bad_branch" in joined and "`if` on traced" in joined
    assert "bad_concretize" in joined and "`float()`" in joined
    assert ".item()" in joined
    assert "fine_op" not in joined
    assert "waived" not in joined
