"""framework_lint in-process (ISSUE 1): the repo itself must be clean
(this test IS the tier-1 invocation of the lint), and seeded fixtures
with a registry/API.spec drift and a tracer-concretization hazard must
each produce violations."""
import json
import os
import sys
import tempfile
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import framework_lint  # noqa: E402


def test_repo_is_clean():
    problems = framework_lint.run_lint()
    assert problems == [], "\n".join(problems)
    assert framework_lint.main([]) == 0


def test_registry_spec_drift_detected():
    with tempfile.TemporaryDirectory() as tmp:
        # a spec that lost hash_bucket and carries a dead MISSING entry
        spec = os.path.join(tmp, "API.spec")
        with open(os.path.join(REPO, "API.spec")) as f:
            lines = [ln for ln in f
                     if not ln.split(" ", 1)[0].endswith(".hash_bucket")]
        lines.append("paddle_tpu.gone_op MISSING\n")
        with open(spec, "w") as f:
            f.writelines(lines)
        problems = framework_lint.check_registry_spec(
            spec, framework_lint.VERSIONS_PATH)
        assert any("hash_bucket" in p and "absent from API.spec" in p
                   for p in problems)
        assert any("MISSING" in p for p in problems)


def test_version_drift_detected():
    with tempfile.TemporaryDirectory() as tmp:
        with open(framework_lint.VERSIONS_PATH) as f:
            snap = json.load(f)
        # signature changed without a version bump
        snap["matmul"] = {"version": snap["matmul"]["version"],
                         "sig": "(x, y, old_flag=False)"}
        # and a version regression: snapshot is ahead of the live @defop
        snap["relu"] = {"version": 99, "sig": snap["relu"]["sig"]}
        # and a stale snapshot: live beam_search is v2, snapshot says v1
        snap["beam_search"] = {"version": 1, "sig": snap["beam_search"]["sig"]}
        # and a stale entry for a removed op
        snap["op_that_was_deleted"] = {"version": 1, "sig": "(x)"}
        vpath = os.path.join(tmp, "OP_VERSIONS.json")
        with open(vpath, "w") as f:
            json.dump(snap, f)
        problems = framework_lint.check_registry_spec(
            framework_lint.SPEC_PATH, vpath)
        assert any("matmul" in p and "without a version bump" in p
                   for p in problems)
        assert any("relu" in p and "regressed" in p for p in problems)
        assert any("beam_search" in p and "still records v1" in p
                   for p in problems)
        assert any("op_that_was_deleted" in p and "no longer registered"
                   in p for p in problems)


def test_concretization_hazards_detected_and_pragma_suppresses():
    src = textwrap.dedent("""
        import jax.numpy as jnp
        from paddle_tpu.ops._dispatch import defop

        @defop
        def bad_branch(x, axis=0):
            y = jnp.exp(x)
            if x > 0:                      # hazard: if on traced value
                y = y * 2
            return y

        @defop
        def bad_concretize(x):
            s = jnp.sum(x)
            n = float(x)                   # hazard: float() of traced
            return s.item() + n            # hazard: .item()

        @defop
        def fine_op(x, mode="a"):
            if mode == "a":                # static attr: fine
                return jnp.exp(x)
            if x.ndim == 2:                # metadata: fine
                return jnp.log(x)
            return jnp.sqrt(x)

        @defop
        def waived(x):
            if x > 0:  # lint: concretization-ok
                return jnp.exp(x)
            return x
    """)
    with tempfile.TemporaryDirectory() as tmp:
        with open(os.path.join(tmp, "fixture_ops.py"), "w") as f:
            f.write(src)
        hits = framework_lint.check_concretization(tmp)
    joined = "\n".join(hits)
    assert "bad_branch" in joined and "`if` on traced" in joined
    assert "bad_concretize" in joined and "`float()`" in joined
    assert ".item()" in joined
    assert "fine_op" not in joined
    assert "waived" not in joined


def test_perf_floors_clean_on_committed_evidence():
    """The committed HLO_EVIDENCE.json must clear every floor — this is
    the tier-1 perf-regression gate (ROADMAP) while the TPU bench
    tunnel is down."""
    assert framework_lint.check_perf_floors() == []


def test_perf_floor_regression_detected():
    with open(framework_lint.EVIDENCE_PATH) as f:
        evidence = json.load(f)
    evidence["graphs"]["gpt_decode_step"]["attention_per_step"][
        "flops_reduction_x"] = 1.3  # below the 2x floor
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "HLO_EVIDENCE.json")
        with open(path, "w") as f:
            json.dump(evidence, f)
        problems = framework_lint.check_perf_floors(path)
    assert len(problems) == 1
    assert "decode-attention FLOPs reduction" in problems[0]
    assert "1.3" in problems[0] and "2.0" in problems[0]


def test_perf_floor_missing_metric_detected():
    with open(framework_lint.EVIDENCE_PATH) as f:
        evidence = json.load(f)
    del evidence["graphs"]["serve_decode"]["kv_bytes_per_step"][
        "bytes_reduction_x_at_typical_fill"]
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "HLO_EVIDENCE.json")
        with open(path, "w") as f:
            json.dump(evidence, f)
        problems = framework_lint.check_perf_floors(path)
    assert len(problems) == 1
    assert "serve_decode KV-bytes reduction" in problems[0]
    assert "missing" in problems[0]


def test_perf_floor_missing_or_corrupt_file_detected():
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "HLO_EVIDENCE.json")
        problems = framework_lint.check_perf_floors(path)
        assert len(problems) == 1 and "not found" in problems[0]
        with open(path, "w") as f:
            f.write("{broken")
        problems = framework_lint.check_perf_floors(path)
        assert len(problems) == 1 and "not valid JSON" in problems[0]


def test_perf_floor_null_metric_detected():
    """Review fix: a legitimately-null JSON leaf must NOT slip through
    the missing-key guard — it is a non-numeric violation."""
    with open(framework_lint.EVIDENCE_PATH) as f:
        evidence = json.load(f)
    evidence["graphs"]["pipeline_scan_megastep"]["dispatch_model"][
        "dispatch_reduction_x"] = None
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "HLO_EVIDENCE.json")
        with open(path, "w") as f:
            json.dump(evidence, f)
        problems = framework_lint.check_perf_floors(path)
    assert len(problems) == 1
    assert "scan-fused dispatch reduction" in problems[0]
    assert "non-numeric" in problems[0]


def test_pp_schedule_report_registered_and_green():
    """ISSUE 11 satellite: the pipeline-schedule report was the only
    pipeline tool outside the lint net — its self_check now pins the
    report's mesh/microbatch constants against pipeline.py's schedule
    accounting and the stage-cut planner's objective knobs."""
    import pp_schedule_report
    assert "pp_schedule_report" in framework_lint.TOOL_CROSS_CHECKS
    assert pp_schedule_report.self_check() == []


def test_spmd_plan_pipeline_json_schema(capsys):
    """The `spmd_plan --pipeline --json` schema is CI surface: key
    drift here breaks tier-1, same pin as the Megatron rediscovery."""
    import spmd_plan
    assert spmd_plan.main(["--pipeline", "--json", "--tp", "1"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is True
    assert set(payload) >= {
        "axis", "bubble", "cuts", "diagnostics", "evaluations",
        "expert", "frontier_bytes_per_tick", "hand", "inner", "mesh",
        "num_micro", "num_stages", "num_virtual", "objective", "ok",
        "schedule", "stages", "wire"}
    assert payload["axis"] == "pp"
    assert payload["num_stages"] == 4
    assert payload["schedule"] == "1f1b"
    assert len(payload["stages"]) == 4
    for stage in payload["stages"]:
        assert set(stage) == {"stage", "op_range", "flops", "hbm_peak",
                              "param_bytes", "diagnostics"}
        assert stage["diagnostics"] == 0
    assert set(payload["wire"]) == {"kind", "axis", "count",
                                    "bytes_per_tick", "total_bytes"}
    assert payload["wire"]["kind"] == "ppermute"
    assert payload["hand"]["objective"] >= payload["objective"]
    # a second run serializes identically (stability contract)
    assert spmd_plan.main(["--pipeline", "--json", "--tp", "1"]) == 0
    assert json.loads(capsys.readouterr().out) == payload


def test_spmd_plan_pipeline_ep_prices_all_to_all(capsys):
    """An ep-mesh MoE plan must place experts and price the all-to-all
    dispatch/combine wire in the report (golden acceptance)."""
    import spmd_plan
    assert spmd_plan.main(["--pipeline", "--json", "--tp", "1",
                           "--pp", "2", "--ep", "2"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is True
    assert payload["expert"]["axis"] == "ep"
    assert payload["expert"]["all_to_all_count"] > 0
    assert payload["expert"]["all_to_all_bytes"] > 0
    assert any("w_up" in t for t in payload["expert"]["rules"])


def test_traffic_determinism_lint_detects_and_pragma_suppresses():
    src = textwrap.dedent("""
        import random
        import time

        import numpy as np


        def bad_clock():
            return time.time()


        def bad_stdlib():
            return random.uniform(0, 1)


        def bad_global_numpy():
            return np.random.rand(3)


        def bad_unseeded_ctor():
            return np.random.RandomState()


        def allowed():
            t = time.perf_counter()
            time.sleep(0)
            rng = np.random.RandomState(7)
            waived = np.random.rand()  # lint: traffic-determinism-ok
            return t, rng, waived
    """)
    with tempfile.TemporaryDirectory() as tmp:
        with open(os.path.join(tmp, "mod.py"), "w") as f:
            f.write(src)
        problems = framework_lint.check_traffic_determinism(tmp)
    assert any("time.time()" in p for p in problems), problems
    assert any("random.uniform" in p for p in problems), problems
    assert any("np.random.rand" in p for p in problems), problems
    assert any("np.random.RandomState" in p and "seed" in p
               for p in problems), problems
    # exactly the four violations: perf_counter/sleep/seeded-ctor are
    # allowed and the pragma'd global draw is waived
    assert len(problems) == 4, problems


def test_traffic_lab_itself_is_deterministic():
    assert framework_lint.check_traffic_determinism() == []


def test_tool_registry_completeness_detected():
    with tempfile.TemporaryDirectory() as tmp:
        with open(os.path.join(tmp, "rogue_tool.py"), "w") as f:
            f.write("def self_check():\n    return []\n")
        with open(os.path.join(tmp, "no_check_tool.py"), "w") as f:
            f.write("def main():\n    return 0\n")
        problems = framework_lint.check_tool_registry(tmp)
    assert any("rogue_tool" in p and "TOOL_CROSS_CHECKS" in p
               for p in problems), problems
    assert not any("no_check_tool" in p for p in problems), problems


def test_tool_registry_repo_is_complete():
    assert framework_lint.check_tool_registry() == []
