"""PTQ calibration path (VERDICT r04 item 8): absmax + histogram
observers over sample data -> quantized artifact loadable by the
predictor; accuracy within 1% of fp32.

Reference: inference/api/mkldnn_quantizer.cc, fluid/contrib/slim."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn, optimizer
from paddle_tpu.quantization import (HistogramObserver, PTQ, QuantConfig,
                                     QuantedConv2D, QuantedLinear)


def _make_data(n=512, seed=0):
    """4-class synthetic 'digits': class k lights up quadrant k."""
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 1, 8, 8).astype("float32") * 0.3
    y = rng.randint(0, 4, n)
    for i, k in enumerate(y):
        r, c = divmod(int(k), 2)
        X[i, 0, r * 4:(r + 1) * 4, c * 4:(c + 1) * 4] += 0.9
    return X, y.astype("int64")


class TinyLeNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2D(1, 6, 3, padding=1)
        self.conv2 = nn.Conv2D(6, 8, 3, padding=1)
        self.fc1 = nn.Linear(8 * 4 * 4, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        x = F.max_pool2d(F.relu(self.conv1(x)), 2)
        x = F.relu(self.conv2(x))
        x = x.reshape([x.shape[0], -1])
        return self.fc2(F.relu(self.fc1(x)))


def _train(net, X, y, epochs=3):
    opt = optimizer.Adam(learning_rate=3e-3, parameters=net.parameters())
    loss_fn = nn.CrossEntropyLoss()
    net.train()
    for _ in range(epochs):
        for i in range(0, len(X), 64):
            xb = paddle.to_tensor(X[i:i + 64])
            yb = paddle.to_tensor(y[i:i + 64])
            loss = loss_fn(net(xb), yb)
            loss.backward()
            opt.step()
            opt.clear_grad()
    net.eval()


def _acc(net, X, y):
    net.eval()
    preds = []
    for i in range(0, len(X), 128):
        logits = net(paddle.to_tensor(X[i:i + 128]))
        preds.append(np.asarray(logits.numpy()).argmax(1))
    return float((np.concatenate(preds) == y).mean())


@pytest.fixture(scope="module")
def trained():
    paddle.seed(0)
    X, y = _make_data(512, seed=0)
    Xt, yt = _make_data(256, seed=1)
    net = TinyLeNet()
    _train(net, X, y)
    acc = _acc(net, Xt, yt)
    assert acc > 0.95, acc
    return net, (X, y), (Xt, yt), acc


@pytest.mark.parametrize("observer", ["absmax", "histogram"])
def test_ptq_within_one_percent(trained, observer):
    net, (X, _y), (Xt, yt), fp32_acc = trained
    q = PTQ(QuantConfig(act_observer=observer))
    qnet = q.quantize(net, inplace=False)
    # quantized wrappers actually installed
    kinds = {type(s) for _, s in qnet.named_sublayers()}
    assert QuantedConv2D in kinds and QuantedLinear in kinds
    q.calibrate(qnet, (X[i:i + 64] for i in range(0, 256, 64)))
    q.convert(qnet)
    q_acc = _acc(qnet, Xt, yt)
    assert q_acc >= fp32_acc - 0.01, (fp32_acc, q_acc)


def test_histogram_observer_rejects_outliers():
    obs = HistogramObserver(bins=512, percentile=0.999)
    rng = np.random.RandomState(0)
    bulk = rng.randn(4096).astype("float32")
    spiked = np.concatenate([bulk, np.array([1000.0], "float32")])
    obs.observe(paddle.to_tensor(spiked))
    scale = float(np.asarray(obs.scale.numpy()))
    # absmax would say 1000; the percentile scale stays near the bulk
    assert scale < 10.0, scale

    amax = HistogramObserver(bins=512, percentile=1.0)
    amax.observe(paddle.to_tensor(spiked))
    assert float(np.asarray(amax.scale.numpy())) > 900.0


def test_ptq_artifact_loads_in_predictor(trained):
    from paddle_tpu.inference import Predictor
    net, (X, _y), (Xt, yt), _ = trained
    q = PTQ(QuantConfig(act_observer="histogram"))
    qnet = q.quantize(net, inplace=False)
    q.calibrate(qnet, [X[:64], X[64:128]])
    q.convert(qnet)
    with tempfile.TemporaryDirectory() as tmp:
        prefix = os.path.join(tmp, "qlenet")
        jit.save(qnet, prefix,
                 input_spec=[jit.InputSpec([8, 1, 8, 8], "float32", "x")])
        want = np.asarray(qnet(paddle.to_tensor(Xt[:8])).numpy())
        got = Predictor(prefix).run([Xt[:8]])[0]
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
        # the quantized artifact classifies like the eager quantized net
        assert (got.argmax(1) == want.argmax(1)).all()
