"""Round-2 fix coverage: collective semantics, nan/inf sweep, grad seeding,
jit kwargs, dropout fast path.

Models the reference's numeric collective checks (test_collective_base.py)
and nan/inf debugging tests (details/nan_inf_utils_detail.*)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import collective, mesh as mesh_mod


@pytest.fixture
def mesh8():
    yield mesh_mod.init_mesh({"dp": 8})


def test_allreduce_prod_with_zeros(mesh8):
    # the log/exp trick yields a tiny nonzero for zero products; the
    # gather-based PROD must return exactly 0
    x = jnp.asarray([0.0, 2.0, 3.0, 1.0, -1.0, 1.0, 1.0, 2.0])

    def body(xl):
        return collective._allreduce_raw(xl, axis="dp",
                                         op=collective.ReduceOp.PROD)

    out = mesh_mod.shard_map(body, mesh=mesh8, in_specs=P("dp"),
                        out_specs=P("dp"))(x)
    expect = np.prod(np.asarray(x))
    np.testing.assert_allclose(np.asarray(out), np.full(8, expect))
    assert float(np.asarray(out)[0]) == 0.0


def test_allreduce_prod_negative(mesh8):
    x = jnp.asarray([-2.0, 2.0, 1.0, 1.0, -1.0, 1.0, 1.0, -3.0])

    def body(xl):
        return collective._allreduce_raw(xl, axis="dp",
                                         op=collective.ReduceOp.PROD)

    out = mesh_mod.shard_map(body, mesh=mesh8, in_specs=P("dp"),
                        out_specs=P("dp"))(x)
    np.testing.assert_allclose(np.asarray(out),
                               np.full(8, np.prod(np.asarray(x))))


def test_reduce_scatter_max(mesh8):
    # op must be honored, not silently SUM-reduced
    x = jnp.arange(64.0).reshape(8, 8)

    def body(xl):
        return collective._reduce_scatter_raw(
            xl[0], axis="dp", op=collective.ReduceOp.MAX)[None]

    out = mesh_mod.shard_map(body, mesh=mesh8, in_specs=P("dp"),
                        out_specs=P("dp"))(x)
    np.testing.assert_allclose(np.asarray(out).reshape(-1),
                               np.asarray(x).max(axis=0))


def test_broadcast_bool_dtype(mesh8):
    # psum-mask broadcast broke on bool; ppermute multicast must not
    x = jnp.asarray([True, False, True, False, True, False, True, False])

    def body(xl):
        return collective._broadcast_raw(xl, axis="dp", src=2)

    out = mesh_mod.shard_map(body, mesh=mesh8, in_specs=P("dp"),
                        out_specs=P("dp"))(x)
    assert np.asarray(out).dtype == np.bool_
    np.testing.assert_array_equal(np.asarray(out), np.full(8, True))


def test_subgroup_allreduce(mesh8):
    # new_group over a rank subset: members reduce among themselves,
    # non-members keep their value (singleton groups)
    x = jnp.arange(8.0)
    g = collective.new_group(ranks=[0, 1, 2, 3])
    assert g.nranks == 4
    assert g.get_group_rank(2) == 2 and g.get_group_rank(7) == -1

    def body(xl):
        return collective._allreduce_raw(
            xl, axis="dp", op=collective.ReduceOp.SUM,
            groups=collective._hashable(g.index_groups()))

    out = mesh_mod.shard_map(body, mesh=mesh8, in_specs=P("dp"),
                        out_specs=P("dp"))(x)
    expect = np.asarray([6.0, 6.0, 6.0, 6.0, 4.0, 5.0, 6.0, 7.0])
    np.testing.assert_allclose(np.asarray(out), expect)


def test_subgroup_broadcast(mesh8):
    x = jnp.arange(8.0)

    def body(xl):
        return collective._broadcast_raw(xl, axis="dp", src=1,
                                         members=(1, 5, 6))

    out = mesh_mod.shard_map(body, mesh=mesh8, in_specs=P("dp"),
                        out_specs=P("dp"))(x)
    expect = np.asarray([0.0, 1.0, 2.0, 3.0, 4.0, 1.0, 1.0, 7.0])
    np.testing.assert_allclose(np.asarray(out), expect)


def test_check_nan_inf_eager_op():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor([1.0, 0.0])
        with pytest.raises(RuntimeError, match="nan"):
            _ = paddle.ops.log(x - 1.0)  # log(0), log(-1) -> -inf, nan
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_check_nan_inf_off_by_default():
    x = paddle.to_tensor([0.0])
    out = paddle.ops.log(x)  # -inf, no raise
    assert np.isneginf(out.numpy()).all()


def test_grad_output_is_input_sums_seed():
    # grad(outputs=[x, y], inputs=[x]) with y = f(x): dx must be
    # seed(identity) + df/dx, not just the path gradient
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = x * x  # dy/dx = 2x
    gx, = paddle.grad([x, y], [x], retain_graph=True)
    np.testing.assert_allclose(gx.numpy(), 1.0 + 2.0 * np.asarray([2.0, 3.0]))


def test_grad_nonleaf_output_is_input():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    h = x * 3.0          # non-leaf
    y = h * h            # dy/dh = 2h
    gh, = paddle.grad([h, y], [h], retain_graph=True)
    np.testing.assert_allclose(gh.numpy(), 1.0 + 2.0 * 3.0 * np.asarray([1.0, 2.0]))


def test_jit_tensor_kwarg_not_baked():
    from paddle_tpu import jit

    def f(x, bias=None):
        return x + bias

    sf = jit.to_static(f)
    x = paddle.to_tensor([1.0, 1.0])
    b1 = paddle.to_tensor([10.0, 10.0])
    b2 = paddle.to_tensor([20.0, 20.0])  # same shape/dtype, different value
    out1 = sf(x, bias=b1)
    out2 = sf(x, bias=b2)
    np.testing.assert_allclose(out1.numpy(), [11.0, 11.0])
    np.testing.assert_allclose(out2.numpy(), [21.0, 21.0])


def test_dropout_p1_zeroes():
    x = paddle.ones([8, 8])
    out = paddle.ops.dropout(x, p=1.0, training=True)
    assert float(out.sum()) == 0.0


def test_dropout_statistics_and_scaling():
    x = paddle.ones([256, 256])
    out = paddle.ops.dropout(x, p=0.25, training=True)
    arr = out.numpy()
    keep_frac = (arr != 0).mean()
    assert abs(keep_frac - 0.75) < 0.02
    # upscale_in_train: kept values are x / keep
    np.testing.assert_allclose(arr[arr != 0], 1.0 / 0.75, rtol=1e-6)


def test_predict_empty_loader():
    from paddle_tpu import nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.static import InputSpec

    net = nn.Linear(4, 2)
    m = Model(net, inputs=[InputSpec([None, 4], "float32", "x")])
    m.prepare()
    assert m.predict([], batch_size=2) == []
