"""fluid v1 compatibility namespace (reference python/paddle/fluid/
layers/nn.py fc :181, embedding :389 等): v1-style programs run on the
2.0 implementations, eager and static."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import fluid


def test_fluid_layer_functions_eager():
    fluid.layers._param_layers.clear()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(4, 3, 2).astype("float32"))
    out = fluid.layers.fc(x, size=5, act="relu", name="fc1")
    assert out.shape == (4, 5) and (out.numpy() >= 0).all()
    # same name reuses the same parameters
    out2 = fluid.layers.fc(x, size=5, act="relu", name="fc1")
    np.testing.assert_allclose(out.numpy(), out2.numpy())

    ids = paddle.to_tensor(np.array([1, 2, 3], "int64"))
    emb = fluid.layers.embedding(ids, size=[10, 4], name="emb1")
    assert emb.shape == (3, 4)

    img = paddle.to_tensor(rng.rand(2, 3, 8, 8).astype("float32"))
    conv = fluid.layers.conv2d(img, num_filters=4, filter_size=3,
                               padding=1, act="relu", name="c1")
    assert conv.shape == (2, 4, 8, 8)
    pooled = fluid.layers.pool2d(conv, pool_size=2, pool_stride=2)
    assert pooled.shape == (2, 4, 4, 4)
    bn = fluid.layers.batch_norm(conv, name="bn1")
    assert bn.shape == conv.shape


def test_fluid_op_aliases():
    a = paddle.to_tensor(np.array([[1.0, 2], [3, 4]], "float32"))
    b = paddle.to_tensor(np.array([[5.0, 6], [7, 8]], "float32"))
    np.testing.assert_allclose(
        fluid.layers.elementwise_add(a, b).numpy(), [[6, 8], [10, 12]])
    np.testing.assert_allclose(fluid.layers.mul(a, b).numpy(),
                               a.numpy() @ b.numpy())
    # v1 axis semantics: y[C] broadcast against x[N,C,H,W] from dim 1
    x4 = paddle.to_tensor(np.arange(24, dtype="float32").reshape(2, 3, 2, 2))
    yc = paddle.to_tensor(np.array([10.0, 20, 30], "float32"))
    got = fluid.layers.elementwise_add(x4, yc, axis=1).numpy()
    want = x4.numpy() + yc.numpy().reshape(1, 3, 1, 1)
    np.testing.assert_allclose(got, want)
    # act kwarg applies the named activation
    neg = paddle.to_tensor(np.array([[-5.0, 2]], "float32"))
    z = paddle.to_tensor(np.array([[0.0, 0]], "float32"))
    np.testing.assert_allclose(
        fluid.layers.elementwise_add(neg, z, act="relu").numpy(), [[0, 2]])
    np.testing.assert_allclose(
        fluid.layers.reduce_mean(a, dim=1).numpy(), [1.5, 3.5])
    fc = fluid.layers.fill_constant([2, 2], "float32", 3.0)
    assert (fc.numpy() == 3).all()
    s = fluid.layers.shape(a)
    np.testing.assert_array_equal(s.numpy(), [2, 2])
    logits = paddle.to_tensor(np.array([[2.0, 0.1]], "float32"))
    lab = paddle.to_tensor(np.array([0], "int64"))
    ce = fluid.layers.cross_entropy(fluid.layers.softmax(logits), lab)
    assert float(ce.numpy()) > 0


def test_fluid_static_program():
    fluid.layers._param_layers.clear()
    paddle.enable_static()
    try:
        main = fluid.Program("fluid_v1")
        with fluid.program_guard(main):
            x = fluid.data("x", [-1, 4], "float32")
            h = fluid.layers.fc(x, size=8, act="relu", name="h")
            out = fluid.layers.fc(h, size=1, name="out")
        exe = fluid.Executor()
        res = exe.run(main, feed={"x": np.ones((3, 4), "float32")},
                      fetch_list=[out])
        assert res[0].shape == (3, 1)
    finally:
        paddle.disable_static()


def test_fluid_io_roundtrip(tmp_path):
    fluid.layers._param_layers.clear()
    paddle.enable_static()
    try:
        main = fluid.Program("fluid_io")
        with fluid.program_guard(main):
            x = fluid.data("x", [-1, 2], "float32")
            out = fluid.layers.fc(x, size=2, name="io_fc")
        exe = fluid.Executor()
        (before,) = exe.run(main, feed={"x": np.ones((1, 2), "float32")},
                            fetch_list=[out])
        fluid.io.save_persistables(exe, str(tmp_path), main)
        fluid.io.load_persistables(exe, str(tmp_path), main)
        (after,) = exe.run(main, feed={"x": np.ones((1, 2), "float32")},
                           fetch_list=[out])
        np.testing.assert_allclose(before, after)
    finally:
        paddle.disable_static()


def test_unnamed_layers_do_not_share_params():
    """Two anonymous fc() calls create distinct parameters (reference
    LayerHelper auto-names fc_0/fc_1); explicit names pin reuse."""
    fluid.layers._param_layers.clear()
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.rand(2, 4).astype("float32"))
    a = fluid.layers.fc(x, size=3)
    b = fluid.layers.fc(x, size=3)
    assert not np.allclose(a.numpy(), b.numpy()), \
        "anonymous fc calls shared parameters"
    c1 = fluid.layers.fc(x, size=3, name="pinned")
    c2 = fluid.layers.fc(x, size=3, name="pinned")
    np.testing.assert_allclose(c1.numpy(), c2.numpy())
