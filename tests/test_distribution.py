"""paddle.distribution vs scipy references."""
import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
from paddle_tpu.distribution import (Bernoulli, Beta, Categorical,
                                     Dirichlet, Normal, Uniform,
                                     kl_divergence)


def test_normal():
    d = Normal(1.0, 2.0)
    x = np.array([0.0, 1.0, 3.0], "float32")
    np.testing.assert_allclose(d.log_prob(x).numpy(),
                               st.norm(1.0, 2.0).logpdf(x), rtol=1e-5)
    np.testing.assert_allclose(float(d.entropy().numpy()),
                               st.norm(1.0, 2.0).entropy(), rtol=1e-6)
    s = d.sample([20000]).numpy()
    assert abs(s.mean() - 1.0) < 0.08 and abs(s.std() - 2.0) < 0.1


def test_uniform_categorical_bernoulli():
    u = Uniform(0.0, 4.0)
    assert abs(float(u.entropy().numpy()) - np.log(4.0)) < 1e-6
    assert float(u.log_prob(np.float32(5.0)).numpy()) == -np.inf

    c = Categorical(probs=np.array([0.2, 0.3, 0.5], "float32"))
    np.testing.assert_allclose(c.entropy().numpy(),
                               st.entropy([0.2, 0.3, 0.5]), rtol=1e-5)
    s = c.sample([20000]).numpy()
    np.testing.assert_allclose(np.bincount(s) / 20000, [0.2, 0.3, 0.5],
                               atol=0.03)

    b = Bernoulli(np.float32(0.3))
    np.testing.assert_allclose(float(b.log_prob(np.float32(1.0)).numpy()),
                               np.log(0.3), rtol=1e-5)


def test_beta_dirichlet():
    d = Beta(2.0, 5.0)
    x = np.array([0.1, 0.4], "float32")
    np.testing.assert_allclose(d.log_prob(x).numpy(),
                               st.beta(2, 5).logpdf(x), rtol=1e-4)
    np.testing.assert_allclose(float(d.entropy().numpy()),
                               st.beta(2, 5).entropy(), rtol=1e-4)
    dd = Dirichlet(np.array([2.0, 3.0, 4.0], "float32"))
    v = np.array([0.2, 0.3, 0.5], "float32")
    np.testing.assert_allclose(float(dd.log_prob(v).numpy()),
                               st.dirichlet([2, 3, 4]).logpdf(v), rtol=1e-4)


def test_kl_closed_forms():
    p, q = Normal(0.0, 1.0), Normal(1.0, 2.0)
    mc = p.sample([200000]).numpy()
    kl_mc = (st.norm(0, 1).logpdf(mc) - st.norm(1, 2).logpdf(mc)).mean()
    np.testing.assert_allclose(float(kl_divergence(p, q).numpy()), kl_mc,
                               atol=0.02)
    c1 = Categorical(probs=np.array([0.5, 0.5], "float32"))
    c2 = Categorical(probs=np.array([0.9, 0.1], "float32"))
    want = 0.5 * np.log(0.5 / 0.9) + 0.5 * np.log(0.5 / 0.1)
    np.testing.assert_allclose(float(kl_divergence(c1, c2).numpy()), want,
                               rtol=1e-5)
    b1, b2 = Beta(2.0, 3.0), Beta(4.0, 1.5)
    s = b1.sample([200000]).numpy()
    kl_mc = (st.beta(2, 3).logpdf(s) - st.beta(4, 1.5).logpdf(s)).mean()
    np.testing.assert_allclose(float(kl_divergence(b1, b2).numpy()), kl_mc,
                               atol=0.03)
    with pytest.raises(NotImplementedError):
        kl_divergence(b1, c1)
