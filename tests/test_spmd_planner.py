"""Auto-sharding planner (ISSUE 10 tentpole).

Golden plans: on the spmd_lint GPT workload with a tp-only mesh the
planner must REDISCOVER the hand-written Megatron layout (qkv/fc1
column-parallel, out-proj/fc2 row-parallel, wte vocab-parallel, 2L+1
all-reduces, zero diagnostics) at preset-or-better predicted cost; a
dp×tp mesh must shard `input_ids` on dp; a deliberately non-divisible
vocab must force a legal fallback (replicated wte, zero diagnostics)
rather than a diagnosed plan.

End-to-end: the planned layout jit-compiles over the 8-device
MULTICHIP-style dp/tp/sp mesh and one train step lands on the SAME loss
and parameters as the hand-tuned `param_spec_for` layout.
"""
import json

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn, ops, static
from paddle_tpu.core import monitor
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed import sharding
from paddle_tpu.static import spmd_analyzer as spmd
from paddle_tpu.static import spmd_planner
from paddle_tpu.static.spmd_planner import (ShardingPlan, name_template,
                                            plan_program)
from paddle_tpu.text.models.gpt import GPT, GPTConfig


@pytest.fixture()
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _gpt_program(layers=2, hidden=64, heads=2, vocab=1024, batch=2,
                 seq=16, inter=None):
    main = static.Program("plan_gpt")
    with static.program_guard(main):
        ids = static.data("input_ids", [batch, seq], "int64")
        net = GPT(GPTConfig(vocab_size=vocab, hidden_size=hidden,
                            num_layers=layers, num_heads=heads,
                            intermediate_size=inter or 4 * hidden,
                            max_seq_len=max(seq, 8)))
        logits = net(ids)
    main._jit_fetch_vars = [logits]
    return main, net, logits


# ---------------------------------------------------------------------------
# golden plans
# ---------------------------------------------------------------------------

def test_tp_only_rediscovers_megatron_layout(static_mode):
    layers = 2
    main, net, logits = _gpt_program(layers)
    plan = plan_program(main, {"tp": 2}, layer=net)

    assert plan.predicted["diagnostics"] == 0
    assert plan.report.diagnostics == []
    # the hand-written preset layout, re-derived from cost search alone
    for name, want in {
            "blocks.0.attn.qkv_proj.weight": P(None, "tp"),
            "blocks.1.attn.qkv_proj.weight": P(None, "tp"),
            "blocks.0.attn.out_proj.weight": P("tp", None),
            "blocks.0.fc1.weight": P(None, "tp"),
            "blocks.1.fc2.weight": P("tp", None),
            "wte.weight": P("tp", None)}.items():
        assert plan.spec_for(name, 2) == want, name
    # 2L+1 all-reduces, all on tp, nothing else on the wire
    ar = [c for c in plan.report.collectives if c.kind == "all_reduce"]
    assert len(ar) == 2 * layers + 1
    assert all(c.axis == "tp" for c in ar)
    assert [c for c in plan.report.collectives
            if c.kind != "all_reduce"] == []
    # logits stay vocab (column-parallel) sharded
    assert plan.report.spec_of(logits) == ((), (), ("tp",))
    # predicted cost no worse than the hand-written preset on BOTH axes
    preset = spmd.analyze_program(
        main, mesh={"tp": 2},
        param_specs=sharding.named_param_specs(net, {"tp": 2}))
    assert plan.predicted["collective_bytes"] <= preset.collective_bytes()
    assert plan.predicted["hbm_peak"] <= preset.hbm["peak_bytes"]
    # and strictly below full replication on HBM
    assert plan.predicted["hbm_peak"] < plan.baseline["hbm_peak"]


def test_dp_tp_mesh_shards_input_ids_on_dp(static_mode):
    main, net, _ = _gpt_program(batch=4)
    plan = plan_program(main, {"dp": 2, "tp": 2}, layer=net)
    assert plan.predicted["diagnostics"] == 0
    ids_spec = tuple(plan.data_specs["input_ids"])
    assert ids_spec and ids_spec[0] == "dp"
    # weights still go tp, not dp (the batch axis is data's)
    assert plan.spec_for("blocks.0.attn.qkv_proj.weight", 2) \
        == P(None, "tp")
    preset = spmd.analyze_program(
        main, mesh={"dp": 2, "tp": 2},
        param_specs=sharding.named_param_specs(net, {"dp": 2, "tp": 2}),
        data_specs={"input_ids": P("dp")})
    assert plan.predicted["collective_bytes"] <= preset.collective_bytes()
    assert plan.predicted["hbm_peak"] <= preset.hbm["peak_bytes"]


def test_non_divisible_vocab_forces_legal_fallback(static_mode):
    """vocab=1023 cannot shard over tp=2: the planner must fall back to
    a replicated embedding (zero diagnostics), NOT emit a diagnosed
    plan — while the hand-written preset DOES diagnose here."""
    main, net, _ = _gpt_program(vocab=1023)
    plan = plan_program(main, {"tp": 2}, layer=net)
    assert plan.predicted["diagnostics"] == 0
    assert plan.report.diagnostics == []
    assert plan.spec_for("wte.weight", 2) == P()
    # the block chains still shard
    assert plan.spec_for("blocks.0.attn.qkv_proj.weight", 2) \
        == P(None, "tp")
    preset = spmd.analyze_program(
        main, mesh={"tp": 2},
        param_specs=sharding.named_param_specs(net, {"tp": 2}))
    assert any(d.code == "non-divisible" for d in preset.diagnostics)


def test_no_mesh_trivial_plan(static_mode):
    main, net, _ = _gpt_program()
    plan = plan_program(main, {}, layer=net)
    assert plan.rules == [] and plan.data_specs == {}
    assert plan.predicted["diagnostics"] == 0


def test_plan_monitor_gauges(static_mode):
    main, net, _ = _gpt_program()
    before = monitor.stat_get("spmd.plans_resolved")
    plan = plan_program(main, {"tp": 2}, layer=net)
    assert monitor.stat_get("spmd.plans_resolved") == before + 1
    assert monitor.stat_get("spmd.plan_collective_bytes") \
        == plan.predicted["collective_bytes"]
    assert monitor.stat_get("spmd.plan_evaluations") == plan.evaluations > 0


# ---------------------------------------------------------------------------
# emission: rules / add_tp_rule / strategy
# ---------------------------------------------------------------------------

def test_name_template_groups_indices_not_identifiers():
    t = name_template("blocks.11.fc2.weight")
    assert t == r"^blocks\.\d+\.fc2\.weight$"
    import re
    assert re.search(t, "blocks.3.fc2.weight")
    assert not re.search(t, "blocks.3.fc1.weight")  # fc1 != fc2
    assert not re.search(t, "blocks.3.fc2.weight.extra")


def test_rules_install_via_add_tp_rule(static_mode):
    main, net, _ = _gpt_program()
    plan = plan_program(main, {"tp": 2}, layer=net)
    patterns = plan.install_rules()
    try:
        got = sharding.param_spec_for("blocks.7.attn.qkv_proj.weight", 2,
                                      sharding.mesh_like({"tp": 2}))
        assert got == P(None, "tp")
        # rank mismatch: the rule's builder declines, presets take over
        got1 = sharding.param_spec_for("blocks.7.attn.qkv_proj.weight", 3,
                                       sharding.mesh_like({"tp": 2}))
        assert got1 == P()
    finally:
        for pat in patterns:
            sharding.remove_tp_rule(pat)


def test_plan_specs_feed_analyze_program(static_mode):
    """The emitted {scope: spec} dict round-trips through the analyzer
    (the Program.spmd_param_specs form) to the same costs the planner
    predicted."""
    main, net, _ = _gpt_program()
    plan = plan_program(main, {"tp": 2}, layer=net)
    rep = spmd.analyze_program(main, mesh={"tp": 2},
                               param_specs=plan.param_specs,
                               data_specs=plan.data_specs)
    assert rep.diagnostics == []
    assert rep.collective_bytes() == plan.predicted["collective_bytes"]
    assert rep.hbm["peak_bytes"] == plan.predicted["hbm_peak"]


def test_auto_shard_strategy_resolves_at_compile(static_mode):
    """strategy.auto_shard=True via fleet.distributed_optimizer: the
    Executor must resolve the plan at compile (specs pinned on the
    program) and still run the step."""
    from paddle_tpu import optimizer as opt_mod
    from paddle_tpu.distributed import fleet

    m = mesh_mod.init_mesh({"tp": 2}, name="_planner_strategy_test")
    mesh_mod.set_mesh(m, "_planner_strategy_test")
    try:
        main = static.Program("auto_shard")
        with static.program_guard(main):
            x = static.data("x", [4, 8], "float32")
            net = nn.Linear(8, 4)
            loss = ops.mean(net(x))
            strategy = fleet.DistributedStrategy()
            strategy.auto_shard = True
            opt = fleet.distributed_optimizer(
                opt_mod.SGD(learning_rate=0.1), strategy)
            opt.minimize(loss)
        assert getattr(main, "_auto_shard", None) is not None
        exe = static.Executor()
        (out,) = exe.run(main, feed={"x": np.ones((4, 8), "float32")},
                         fetch_list=[loss])
        assert np.isfinite(out)
        specs = getattr(main, "spmd_param_specs", None)
        assert specs is not None  # the compile resolved the plan
        assert set(specs) == set(main.persistable_vars)
        plan = main._auto_shard["plan"]
        assert isinstance(plan, ShardingPlan)
        assert plan.predicted["diagnostics"] == 0
    finally:
        mesh_mod.reset_mesh("_planner_strategy_test")


def test_as_strategy_carries_plan(static_mode):
    main, net, _ = _gpt_program()
    plan = plan_program(main, {"tp": 2}, layer=net)
    strategy = plan.as_strategy()
    assert strategy.auto_shard is True
    assert strategy.auto_shard_configs["plan"] is plan
    main._auto_shard = dict(strategy.auto_shard_configs)
    got = spmd_planner.resolve_auto_shard(main)
    assert got is plan
    assert main.spmd_param_specs == plan.param_specs


# ---------------------------------------------------------------------------
# the CLI (tools/spmd_plan.py): --json is stable and consumed here
# ---------------------------------------------------------------------------

def _tools():
    import os
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))


def test_cli_json_output_stable(capsys):
    _tools()
    import spmd_plan
    assert spmd_plan.main(["--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is True
    assert payload["mesh"] == {"tp": 2}
    assert payload["predicted"]["diagnostics"] == 0
    assert payload["predicted"]["collective_bytes"] \
        <= payload["preset"]["collective_bytes"]
    assert payload["predicted"]["hbm_peak"] <= payload["preset"]["hbm_peak"]
    templates = {r["template"]: r["spec"] for r in payload["rules"]}
    assert templates[r"^blocks\.\d+\.attn\.qkv_proj\.weight$"] \
        == [None, "tp"]
    assert templates[r"^wte\.weight$"] == ["tp", None]
    # a second run serializes identically (stability contract)
    assert spmd_plan.main(["--json"]) == 0
    assert json.loads(capsys.readouterr().out) == payload


def test_cli_human_output(capsys):
    _tools()
    import spmd_plan
    assert spmd_plan.main([]) == 0
    out = capsys.readouterr().out
    assert "rules:" in out and "preset" in out and "replicated" in out


def test_self_check_registered_and_green():
    _tools()
    import framework_lint
    import spmd_plan
    assert "spmd_plan" in framework_lint.TOOL_CROSS_CHECKS
    assert spmd_plan.self_check() == []


# ---------------------------------------------------------------------------
# e2e: planned layout == hand-tuned layout on the 8-device dryrun mesh
# ---------------------------------------------------------------------------

def test_multichip_dp_tp_sp_plan_matches_hand_tuned_loss(static_mode):
    """The MULTICHIP acceptance: one GPT train step jitted over the
    dp/tp/sp mesh on 8 (virtual) devices, once with the PLANNED
    shardings and once with the hand-tuned `param_spec_for` layout —
    same loss, same updated params."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")

    cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                    num_heads=2, intermediate_size=64, max_seq_len=16)
    # plan against the statically traced forward
    main = static.Program("e2e_gpt")
    with static.program_guard(main):
        ids_v = static.data("input_ids", [4, 16], "int64")
        net = GPT(cfg)
        net.eval()
        _ = net(ids_v)
    mesh_shape = {"dp": 2, "tp": 2, "sp": 2}
    plan = plan_program(main, mesh_shape, layer=net)
    assert plan.predicted["diagnostics"] == 0
    paddle.disable_static()

    from paddle_tpu.core import rng as _rng
    from paddle_tpu.core import tape as _tape
    from paddle_tpu.core.tensor import Tensor

    paddle.seed(0)
    net2 = GPT(cfg)
    net2.eval()
    params, buffers = net2.functional_state()
    mesh = mesh_mod.init_mesh(mesh_shape, name="_planner_e2e",
                              devices=jax.devices()[:8])

    def loss_and_update(p, ids, labels):
        with _rng.rng_state(jax.random.PRNGKey(0)), _tape.no_grad():
            def loss_of(pp):
                net2.load_functional_state(pp, buffers)
                loss = net2(Tensor(ids, _internal=True),
                            labels=Tensor(labels, _internal=True))
                return loss._value
            loss, grads = jax.value_and_grad(loss_of)(p)
            new_p = jax.tree_util.tree_map(
                lambda w, g: w - 0.1 * g, p, grads)
        return loss, new_p

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(4, cfg.vocab_size, (4, 16)), jnp.int64)
    labels = jnp.asarray(rng.randint(4, cfg.vocab_size, (4, 16)),
                         jnp.int64)
    repl = NamedSharding(mesh, P())
    data_spec = plan.data_specs.get("input_ids", P("dp"))
    data_sh = NamedSharding(mesh, data_spec)
    assert tuple(data_spec)[0] == "dp"  # the dryrun batch convention

    def run(shardings):
        step = jax.jit(loss_and_update,
                       in_shardings=(shardings, data_sh, data_sh),
                       out_shardings=(repl, shardings))
        with mesh:
            loss, new_p = step(params, ids, labels)
        return float(np.asarray(loss)), new_p

    try:
        planned = plan.build_param_shardings(params, mesh)
        hand = {k: NamedSharding(
            mesh, sharding.param_spec_for(k, v.ndim, mesh))
            for k, v in params.items()}
        # the plans genuinely shard (not all replicated)
        assert any(tuple(s.spec) and any(tuple(s.spec))
                   for s in planned.values())
        loss_plan, p_plan = run(planned)
        loss_hand, p_hand = run(hand)
        assert np.isfinite(loss_plan)
        np.testing.assert_allclose(loss_plan, loss_hand, rtol=1e-5)
        for k in ("wte.weight", "blocks.0.attn.qkv_proj.weight",
                  "blocks.1.fc2.weight"):
            np.testing.assert_allclose(np.asarray(p_plan[k]),
                                       np.asarray(p_hand[k]), rtol=1e-5,
                                       atol=1e-6)
    finally:
        mesh_mod.reset_mesh("_planner_e2e")


def test_template_collision_with_replicated_group_keeps_exact_rules(
        static_mode):
    """Review fix: a replicated group must veto its template. Two params
    share the template `^blocks\\.\\d+\\.fc\\.weight$` but only one can
    shard (the other's dim is non-divisible): the rules must NOT contain
    the bare template (it would claim the replicated member through
    spec_for/install_rules), only an exact-name rule for the shardable
    one."""
    main = static.Program("collide")
    with static.program_guard(main):
        x = static.data("x", [4, 64], "float32")
        a = nn.Linear(64, 30, bias_attr=False)   # 30 % 4 != 0
        b = nn.Linear(64, 64, bias_attr=False)   # 64 % 4 == 0
        y = ops.matmul(a(x), ops.transpose(b.weight, [0, 1])[:30, :])
    main._jit_fetch_vars = [y]
    names = {a.weight.scope_name: "blocks.0.fc.weight",
             b.weight.scope_name: "blocks.1.fc.weight"}
    plan = plan_program(main, {"tp": 4}, names=names)
    assert plan.predicted["diagnostics"] == 0
    templates = [r.template for r in plan.rules]
    assert r"^blocks\.\d+\.fc\.weight$" not in templates
    # the non-divisible member resolves replicated through the RULES
    assert plan.spec_for("blocks.0.fc.weight", 2) == P()
    assert tuple(plan.param_specs[a.weight.scope_name]) == (None, None)
    # any sharded sibling uses an exact-name rule only
    for r in plan.rules:
        assert r"\d+" not in r.template


# ---------------------------------------------------------------------------
# two-tier topology: the planner keeps tp intra-pod from cost alone
# ---------------------------------------------------------------------------

TIERED_MESH = {"pod": {"size": 2, "tier": "dcn"}, "dp": 2, "tp": 2}


def test_topology_plan_pins_tp_intra_pod(static_mode):
    """On the {pod(dcn), dp, tp} mesh the beam must land the Megatron
    layout with every model-parallel collective on the fast tier and
    the batch DCN-major — zero diagnostics, zero cross-tier — and carry
    the hierarchical grad-sync selection into the fleet strategy."""
    cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                    num_heads=2, intermediate_size=64, max_seq_len=16)
    main = static.Program("topo_gpt")
    with static.program_guard(main):
        ids = static.data("input_ids", [8, 16], "int64")
        net = GPT(cfg)
        net.eval()
        _ = net(ids)
    plan = plan_program(main, TIERED_MESH, layer=net)
    assert plan.predicted["diagnostics"] == 0
    assert not [d for d in plan.report.diagnostics
                if d.code == "cross-tier"]
    # nothing but the (exempt) data feed may touch the slow axis
    for c in plan.report.collectives:
        assert "pod" not in str(c.axis).split(",")
    spec = plan.data_specs["input_ids"]
    assert tuple(spec)[0] == ("pod", "dp")  # DCN-major batch
    assert plan.mesh_tiers["pod"]["tier"] == "dcn"
    gs = plan.grad_sync
    assert gs["recommendation"] == "hierarchical"
    assert gs["inter_pod_reduction_x"] >= 2.0
    strat = plan.as_strategy()
    assert strat.hierarchical_allreduce is True
    assert strat.hierarchical_allreduce_configs == {
        "inner_axes": ["dp"], "outer_axes": ["pod"]}
    # the topology block serializes; flat plans stay byte-identical
    assert "topology" in plan.to_json()
    flat = plan_program(main, {"tp": 2}, layer=net)
    assert "topology" not in flat.to_json()
    assert flat.as_strategy().hierarchical_allreduce is False


def test_cli_topology_json_stable(capsys):
    _tools()
    import spmd_plan
    assert spmd_plan.main(["--topology", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is True and payload["cross_tier"] == 0
    gs = payload["topology"]["grad_sync"]
    assert gs["recommendation"] == "hierarchical"
    sch = gs["schemes"]
    assert sch["hierarchical"]["wire_bytes"]["dcn"] * 2 \
        == sch["flat"]["wire_bytes"]["dcn"]
    assert sch["hierarchical"]["wire_bytes"]["ici"] \
        == sch["flat"]["wire_bytes"]["ici"]
    assert spmd_plan.main(["--topology", "--json"]) == 0
    assert json.loads(capsys.readouterr().out) == payload


def test_topology_plan_matches_flat_plan_loss(static_mode):
    """The nested-mesh acceptance: one GPT train step jitted over the
    8-device {pod: 2, dp: 2, tp: 2} mesh with the topology plan's
    shardings lands on the same loss and updated params as the flat
    {dp: 4, tp: 2} plan — the pod split of the batch is a relabeling
    of dp, so the two-tier layout costs nothing in arithmetic."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")

    cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                    num_heads=2, intermediate_size=64, max_seq_len=16)
    main = static.Program("topo_e2e")
    with static.program_guard(main):
        ids_v = static.data("input_ids", [4, 16], "int64")
        net = GPT(cfg)
        net.eval()
        _ = net(ids_v)
    plan_topo = plan_program(main, TIERED_MESH, layer=net)
    plan_flat = plan_program(main, {"dp": 4, "tp": 2}, layer=net)
    assert plan_topo.predicted["diagnostics"] == 0
    assert plan_flat.predicted["diagnostics"] == 0
    paddle.disable_static()

    from paddle_tpu.core import rng as _rng
    from paddle_tpu.core import tape as _tape
    from paddle_tpu.core.tensor import Tensor

    paddle.seed(0)
    net2 = GPT(cfg)
    net2.eval()
    params, buffers = net2.functional_state()

    def loss_and_update(p, ids, labels):
        with _rng.rng_state(jax.random.PRNGKey(0)), _tape.no_grad():
            def loss_of(pp):
                net2.load_functional_state(pp, buffers)
                loss = net2(Tensor(ids, _internal=True),
                            labels=Tensor(labels, _internal=True))
                return loss._value
            loss, grads = jax.value_and_grad(loss_of)(p)
            new_p = jax.tree_util.tree_map(
                lambda w, g: w - 0.1 * g, p, grads)
        return loss, new_p

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(4, cfg.vocab_size, (4, 16)), jnp.int64)
    labels = jnp.asarray(rng.randint(4, cfg.vocab_size, (4, 16)),
                         jnp.int64)

    def run(plan, mesh_shape, name):
        mesh = mesh_mod.init_mesh(mesh_shape, name=name,
                                  devices=jax.devices()[:8])
        try:
            repl = NamedSharding(mesh, P())
            data_sh = NamedSharding(mesh,
                                    plan.data_specs["input_ids"])
            shardings = plan.build_param_shardings(params, mesh)
            assert any(tuple(s.spec) and any(tuple(s.spec))
                       for s in shardings.values())
            step = jax.jit(loss_and_update,
                           in_shardings=(shardings, data_sh, data_sh),
                           out_shardings=(repl, shardings))
            with mesh:
                loss, new_p = step(params, ids, labels)
            return float(np.asarray(loss)), new_p
        finally:
            mesh_mod.reset_mesh(name)

    assert tuple(plan_topo.data_specs["input_ids"])[0] == ("pod", "dp")
    loss_t, p_t = run(plan_topo, TIERED_MESH, "_topo_e2e")
    loss_f, p_f = run(plan_flat, {"dp": 4, "tp": 2}, "_flat_e2e")
    assert np.isfinite(loss_t)
    np.testing.assert_allclose(loss_t, loss_f, rtol=1e-5)
    for k in ("wte.weight", "blocks.0.attn.qkv_proj.weight",
              "blocks.1.fc2.weight"):
        np.testing.assert_allclose(np.asarray(p_t[k]),
                                   np.asarray(p_f[k]), rtol=1e-5,
                                   atol=1e-6)
