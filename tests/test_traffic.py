"""Traffic lab: deterministic workload schedules + the shared harness.

Replay byte-identity is the contract everything else rides on
(docs/traffic_lab.md): the same (spec, seed) yields the same schedule
bytes and — through the real ServeLoop — the same generated tokens.
"""
import json

import numpy as np
import pytest

from paddle_tpu.traffic import harness
from paddle_tpu.traffic import workload as W


def _mixed_spec(duration_s=2.0, rate=40.0):
    """A two-tenant (llm + hybrid) mix small enough for the tiny loop."""
    return W.WorkloadSpec(
        name="mixed", duration_s=duration_s,
        arrival={"kind": "poisson", "rate": rate},
        tenants=(
            {"name": "chat", "weight": 0.6, "kind": "llm",
             "prompt": {"kind": "lognormal", "median": 6, "sigma": 0.5,
                        "lo": 2},
             "new": {"kind": "uniform", "lo": 2, "hi": 6}},
            {"name": "rec", "weight": 0.4, "kind": "hybrid",
             "prompt": {"kind": "uniform", "lo": 2, "hi": 8},
             "new": {"kind": "fixed", "value": 3}, "lookups": 4}),
        vocab=512, max_seq_len=48)


# ---------------------------------------------------------------------------
# generator edge cases
# ---------------------------------------------------------------------------

def test_zero_rate_window_emits_nothing():
    spec = W.WorkloadSpec(
        name="win", duration_s=3.0,
        arrival={"kind": "windows",
                 "windows": [[1.0, 30.0], [1.0, 0.0], [1.0, 30.0]]},
        max_seq_len=32)
    events = W.schedule(spec, seed=3)
    assert any(e.t < 1.0 for e in events)
    assert any(e.t >= 2.0 for e in events)
    assert [e for e in events if 1.0 <= e.t < 2.0] == []
    # indices stay dense across the dead window (the schedule is one
    # stream, not per-window streams)
    assert [e.index for e in events] == list(range(len(events)))


def test_all_zero_windows_is_an_empty_schedule():
    spec = W.WorkloadSpec(
        name="dead", duration_s=2.0,
        arrival={"kind": "windows", "windows": [[2.0, 0.0]]})
    assert W.schedule(spec, seed=0) == []


def test_pareto_heavy_tail_truncates_at_the_cap():
    spec = W.WorkloadSpec(
        name="tail", duration_s=2.0,
        arrival={"kind": "poisson", "rate": 50.0},
        tenants=({"name": "t", "weight": 1.0, "kind": "llm",
                  "prompt": {"kind": "pareto", "alpha": 1.1, "scale": 6,
                             "lo": 2, "hi": 4096},
                  "new": {"kind": "fixed", "value": 4}},),
        max_seq_len=32)
    gen = W.WorkloadGenerator(spec, seed=1)
    events = list(gen)
    assert len(events) > 20
    # the tail really was drawn past the cap, and every event still fits
    assert gen.stats["truncated"] > 0
    for e in events:
        assert 2 <= e.prompt.size <= spec.max_seq_len - 1
        assert e.tokens_total() <= spec.max_seq_len


def test_state_dict_resume_is_byte_identical():
    spec = _mixed_spec()
    ref = W.schedule(spec, seed=9)
    assert len(ref) > 10
    gen = W.WorkloadGenerator(spec, 9)
    head = [gen.next_event() for _ in range(7)]
    # snapshot mid-wave, round-trip through JSON like a checkpoint would
    state = json.loads(json.dumps(gen.state_dict()))
    resumed = W.WorkloadGenerator(spec, 9).load_state_dict(state)
    tail = list(resumed)
    assert W.schedule_digest(head + tail) == W.schedule_digest(ref)
    assert resumed.stats["events"] == len(ref)
    # snapshots are bound to (spec, seed)
    with pytest.raises(ValueError):
        W.WorkloadGenerator(spec, 8).load_state_dict(state)
    other = W.WorkloadSpec(name="other", duration_s=1.0,
                           arrival={"kind": "poisson", "rate": 1.0})
    with pytest.raises(ValueError):
        W.WorkloadGenerator(other, 9).load_state_dict(state)


def test_hybrid_tenant_events_carry_lookups():
    events = W.schedule(_mixed_spec(duration_s=1.0), seed=4)
    rec = [e for e in events if e.tenant == "rec"]
    assert rec
    for e in rec:
        assert e.kind == "hybrid"
        assert e.lookup_ids is not None and e.lookup_ids.size == 4
    for e in events:
        if e.tenant == "chat":
            assert e.lookup_ids is None


# ---------------------------------------------------------------------------
# the harness closed loop
# ---------------------------------------------------------------------------

def test_same_seed_replay_is_byte_identical_through_harness():
    spec = _mixed_spec(duration_s=1.0, rate=30.0)
    a = harness.run_spec(spec, seed=5, time_scale=0.05, clients=2)
    b = harness.run_spec(spec, seed=5, time_scale=0.05, clients=2)
    # same seed: same schedule bytes AND same generated tokens, even
    # though the two runs batched/interleaved differently on the wall
    # clock (per-stream sampling keys are position-folded)
    assert a.events > 0
    assert a.schedule_digest == b.schedule_digest
    assert a.outputs_digest == b.outputs_digest
    assert a.completed == a.events and a.errors == 0
    assert b.completed == b.events and b.errors == 0
    # and a different seed is a different schedule
    assert W.schedule_digest(W.schedule(spec, 6)) != a.schedule_digest


def test_flash_crowd_backpressure_drops_nothing():
    spec = W.WorkloadSpec(
        name="flashlet", duration_s=0.8,
        arrival={"kind": "flash", "base": 5.0, "burst_rate": 150.0,
                 "burst_at_s": 0.1, "burst_len_s": 0.3},
        tenants=({"name": "chat", "weight": 1.0, "kind": "llm",
                  "prompt": {"kind": "fixed", "value": 6},
                  "new": {"kind": "fixed", "value": 6}},),
        vocab=256, max_seq_len=32)
    events = W.schedule(spec, seed=2)
    burst = [e for e in events if 0.1 <= e.t < 0.4]
    assert len(burst) > 20           # the flash window dominates
    rep = harness.run_spec(
        spec, seed=2, time_scale=0.25, clients=4,
        serve_cfg={"max_active": 2, "kv_blocks": 8, "block_size": 8,
                   "max_seq_len": 32})
    # the burst outran 2 slots: admissions waited (counted), but FCFS
    # backpressure queues rather than drops — everything completed
    assert rep.backpressure_waits > 0
    assert rep.completed == rep.events == len(events)
    assert rep.errors == 0


def test_run_spec_rejects_specs_that_overflow_the_serve_cap():
    spec = W.WorkloadSpec(
        name="toolong", duration_s=0.5,
        arrival={"kind": "poisson", "rate": 20.0},
        tenants=({"name": "t", "weight": 1.0, "kind": "llm",
                  "prompt": {"kind": "fixed", "value": 40},
                  "new": {"kind": "fixed", "value": 40}},),
        max_seq_len=96)
    with pytest.raises(ValueError, match="serve cap"):
        harness.run_spec(spec, seed=0, time_scale=0.0)


def test_drive_serve_collects_submit_errors_instead_of_raising():
    class Boom:
        def submit(self, *a, **k):
            raise RuntimeError("full")

        def run_until_idle(self):
            pass

    subs = harness.submissions_from_prompts(
        [np.arange(1, 5, dtype=np.int64)] * 3, 2)
    stats = harness.drive_serve(Boom(), subs, clients=2, wait="idle")
    assert len(stats.errors) == 3
    assert all(e.startswith("submit[") for e in stats.errors)
