"""Parameter-server stack (distributed/ps; reference N20-N22:
operators/distributed/, paddle/fluid/distributed/, framework/fleet/).

Tiers mirror the reference's PS test strategy (test_dist_fleet_ps*.py:
tables unit-tested in-proc, then real server processes driven by the env
contract):
1. table accessors vs hand-computed update rules;
2. client<->server over real sockets (in-proc server threads), row
   sharding across 2 servers, barrier, save/load;
3. async Communicator merge semantics;
4. end-to-end: 1 server + 2 worker PROCESSES via the fleet env contract
   training a PS-backed embedding model — loss must drop.
"""
import os
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- 1: tables

def test_dense_table_sgd():
    from paddle_tpu.distributed.ps.table import DenseTable
    t = DenseTable((3, 2), optimizer="sgd", lr=0.1)
    g = np.ones((3, 2), np.float32)
    t.push_grad(g)
    np.testing.assert_allclose(t.pull(), -0.1 * g, atol=1e-6)


def test_dense_table_adam_matches_formula():
    from paddle_tpu.distributed.ps.table import DenseTable
    t = DenseTable((4,), optimizer="adam", lr=0.01)
    rng = np.random.RandomState(0)
    p = np.zeros(4, np.float64)
    m = np.zeros(4)
    v = np.zeros(4)
    for step in range(1, 6):
        g = rng.randn(4)
        t.push_grad(g.astype(np.float32))
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mh = m / (1 - 0.9 ** step)
        vh = v / (1 - 0.999 ** step)
        p -= 0.01 * mh / (np.sqrt(vh) + 1e-8)
    np.testing.assert_allclose(t.pull(), p, atol=1e-5)


def test_sparse_table_lazy_rows_and_merge():
    from paddle_tpu.distributed.ps.table import SparseTable
    t = SparseTable(dim=3, optimizer="sgd", lr=1.0, init="zeros")
    assert len(t) == 0
    rows = t.pull([5, 9, 5])
    assert rows.shape == (3, 3) and len(t) == 2  # lazy creation, 2 unique
    # duplicate ids in one push must accumulate (MergeAdd) before the rule
    t.push_grad([5, 5, 9], np.ones((3, 3), np.float32))
    got = t.pull([5, 9])
    np.testing.assert_allclose(got[0], -2 * np.ones(3), atol=1e-6)
    np.testing.assert_allclose(got[1], -1 * np.ones(3), atol=1e-6)


def test_sparse_table_adagrad_rule():
    from paddle_tpu.distributed.ps.table import SparseTable
    t = SparseTable(dim=2, optimizer="adagrad", lr=0.1, init="zeros")
    g = np.array([[1.0, 2.0]], np.float32)
    t.push_grad([7], g)
    expect = -0.1 * g / (np.sqrt(g * g) + 1e-6)
    np.testing.assert_allclose(t.pull([7]), expect, atol=1e-5)


def test_geo_table_folds_deltas():
    from paddle_tpu.distributed.ps.table import GeoSparseTable
    t = GeoSparseTable(dim=2, init="zeros")
    t.push_delta([3, 3], np.array([[1, 1], [2, 2]], np.float32))
    np.testing.assert_allclose(t.pull([3]), [[3, 3]], atol=1e-6)


def test_table_state_roundtrip():
    from paddle_tpu.distributed.ps.table import SparseTable
    a = SparseTable(dim=4, optimizer="adagrad", lr=0.05)
    a.push_grad([1, 2, 3], np.random.RandomState(0).randn(3, 4)
                .astype(np.float32))
    b = SparseTable(dim=4, optimizer="adagrad", lr=0.05)
    b.load_state(a.state())
    np.testing.assert_allclose(a.pull([1, 2, 3]), b.pull([1, 2, 3]))
    # slots carried over: identical next update
    g = np.ones((1, 4), np.float32)
    a.push_grad([2], g)
    b.push_grad([2], g)
    np.testing.assert_allclose(a.pull([2]), b.pull([2]), atol=1e-6)


# --------------------------------------------- 2: client/server sharding

@pytest.fixture()
def two_servers():
    from paddle_tpu.distributed.ps import PSClient, PSServer
    specs = {
        "emb": {"type": "sparse", "dim": 4, "optimizer": "sgd", "lr": 1.0,
                "init": "zeros"},
        "w": {"type": "dense", "shape": (2, 2), "optimizer": "sgd",
              "lr": 0.5},
        "bar": {"type": "barrier", "trainer_num": 2},
    }
    servers = [PSServer("127.0.0.1:0", specs) for _ in range(2)]
    eps = [s.start() for s in servers]
    client = PSClient(eps)
    yield client, servers
    client.stop_servers()
    client.close()


def test_pull_push_sparse_sharded(two_servers):
    client, servers = two_servers
    ids = np.array([0, 1, 2, 3, 10, 11], np.int64)  # both parities -> both servers
    rows = client.pull_sparse("emb", ids)
    assert rows.shape == (6, 4)
    client.push_sparse_grad("emb", ids, np.ones((6, 4), np.float32))
    got = client.pull_sparse("emb", ids)
    np.testing.assert_allclose(got, -np.ones((6, 4)), atol=1e-6)
    # rows actually sharded: each server holds only its parity
    even = servers[0].table("emb") if 0 % 2 == 0 else servers[1].table("emb")
    assert len(even) == 3  # ids 0, 2, 10
    # order preservation with duplicates and interleaved owners
    mixed = np.array([3, 0, 3, 2], np.int64)
    got = client.pull_sparse("emb", mixed)
    np.testing.assert_allclose(got[0], got[2], atol=1e-6)


def test_dense_roundtrip_and_update(two_servers):
    client, _ = two_servers
    w0 = client.pull_dense("w")
    np.testing.assert_allclose(w0, np.zeros((2, 2)))
    client.push_dense_grad("w", np.ones((2, 2), np.float32))
    np.testing.assert_allclose(client.pull_dense("w"),
                               -0.5 * np.ones((2, 2)), atol=1e-6)
    client.set_dense("w", np.full((2, 2), 7.0, np.float32))
    np.testing.assert_allclose(client.pull_dense("w"), 7.0)


def test_barrier_across_threads(two_servers):
    client, _ = two_servers
    from paddle_tpu.distributed.ps import PSClient
    results = []

    def other():
        c2 = PSClient(client.endpoints)
        results.append(c2.barrier("bar", 1))
        c2.close()

    t = threading.Thread(target=other)
    t.start()
    assert client.barrier("bar", 0)
    t.join(30)
    assert results == [True]


def test_server_error_propagates(two_servers):
    client, _ = two_servers
    with pytest.raises(RuntimeError, match="ps server error"):
        client.pull_dense("nonexistent_table")


# ------------------------------------------------------- 3: communicator

def test_communicator_merges_and_flushes(two_servers):
    client, _ = two_servers
    from paddle_tpu.distributed.ps import Communicator
    comm = Communicator(client, send_every=100)  # force merge-at-flush
    for _ in range(5):
        comm.push_sparse("emb", [42, 43], np.ones((2, 4), np.float32))
    comm.push_dense("w", np.ones((2, 2), np.float32))
    comm.flush()
    comm.stop()
    got = client.pull_sparse("emb", [42, 43])
    np.testing.assert_allclose(got, -5 * np.ones((2, 4)), atol=1e-6)
    np.testing.assert_allclose(client.pull_dense("w"),
                               -0.5 * np.ones((2, 2)), atol=1e-6)


def test_dense_routing_is_process_stable():
    # hash() is PYTHONHASHSEED-randomized across worker processes; routing
    # must not be (review finding): verify the crc32 rule in a fresh
    # interpreter with a different hash seed
    import zlib
    expect = zlib.crc32(b"w") % 2
    out = subprocess.run(
        [sys.executable, "-c",
         "import zlib; print(zlib.crc32(b'w') % 2)"],
        env={**os.environ, "PYTHONHASHSEED": "12345"},
        capture_output=True, text=True, cwd=REPO)
    assert int(out.stdout) == expect


def test_user_defined_role_maker_endpoints(two_servers):
    client, _ = two_servers
    import paddle_tpu.distributed.fleet as fleet
    from paddle_tpu.distributed.fleet import UserDefinedRoleMaker, Role
    rm = UserDefinedRoleMaker(current_id=1, role=Role.WORKER, worker_num=3,
                              server_endpoints=client.endpoints)
    saved = dict(fleet._fleet_state)
    try:
        fleet.init(role_maker=rm, is_collective=False)
        assert fleet.worker_index() == 1
        assert fleet.worker_num() == 3
        assert not fleet.is_first_worker()
        fleet.init_worker()  # endpoints come from the role maker, no env
        assert fleet.ps_client().n_servers == 2
        fleet._fleet_state.pop("ps_client").close()
    finally:
        fleet._fleet_state.clear()
        fleet._fleet_state.update(saved)


# ------------------------------------------- 4: end-to-end fleet PS mode

_SERVER = textwrap.dedent("""
    import paddle_tpu.distributed.fleet as fleet
    fleet.init(is_collective=False)
    assert fleet.is_server()
    fleet.init_server(tables={
        "emb": {"type": "sparse", "dim": 8, "optimizer": "adagrad",
                "lr": 0.2, "init": "uniform", "seed": 3},
        "bar": {"type": "barrier", "trainer_num": 2},
    })
    fleet.run_server()
""")

_WORKER = textwrap.dedent("""
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed.fleet as fleet
    from paddle_tpu.distributed import ps

    strategy = fleet.DistributedStrategy()
    strategy.a_sync = True
    fleet.init(is_collective=False, strategy=strategy)
    assert fleet.is_worker() and not fleet.is_server()
    fleet.init_worker()
    client = fleet.ps_client()
    comm = fleet.ps_communicator()
    assert comm is not None  # a_sync selected the async path

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    emb = ps.SparseEmbedding(client, "emb", dim=8, communicator=comm)

    # toy skip-gram-ish objective: pull rows for a batch of ids, dot with
    # a local dense head, logistic loss on labels derivable per-row. The
    # vocab is small (64) so rows are revisited and actually train.
    rng = np.random.RandomState(100 + rank)
    head = paddle.to_tensor(
        (rng.randn(8).astype(np.float32) * 0.1), stop_gradient=False)
    losses = []
    for step in range(40):
        ids = rng.randint(0, 64, size=(16,))
        labels = (ids % 2).astype(np.float32)  # learnable from the row
        rows, index = emb.pull(ids)
        feats = paddle.gather(rows, index)          # [16, 8] on device
        logits = paddle.matmul(feats, head)
        y = paddle.to_tensor(labels)
        loss = paddle.nn.functional.binary_cross_entropy_with_logits(
            logits, y)
        loss.backward()
        emb.push_grad(rows)
        head = paddle.to_tensor(
            head.numpy() - 0.1 * head.grad.numpy(), stop_gradient=False)
        losses.append(float(loss.numpy()))
    comm.flush()
    client.barrier("bar", rank)
    first, last = np.mean(losses[:5]), np.mean(losses[-5:])
    print(f"worker {rank}: loss {first:.4f} -> {last:.4f}")
    assert last < first - 0.05, (first, last)
    fleet.stop_worker()
""")


def test_fleet_ps_end_to_end(tmp_path):
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env_base = {**os.environ,
                "PADDLE_PSERVERS_IP_PORT_LIST": f"127.0.0.1:{port}",
                "PADDLE_TRAINERS_NUM": "2",
                "PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"}
    server = subprocess.Popen(
        [sys.executable, "-c", _SERVER],
        env={**env_base, "TRAINING_ROLE": "PSERVER",
             "PADDLE_PSERVER_ID": "0"},
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    workers = [subprocess.Popen(
        [sys.executable, "-c", _WORKER],
        env={**env_base, "TRAINING_ROLE": "TRAINER",
             "PADDLE_TRAINER_ID": str(i)},
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(2)]
    outs = []
    try:
        for w in workers:
            out, _ = w.communicate(timeout=300)
            outs.append(out)
        for w, out in zip(workers, outs):
            assert w.returncode == 0, f"worker failed:\n{out}"
        server_out, _ = server.communicate(timeout=60)
        assert server.returncode == 0, f"server failed:\n{server_out}"
    finally:
        for p in workers + [server]:
            if p.poll() is None:
                p.kill()


def test_sparse_table_pull_with_duplicate_ids():
    """Regression (ADVICE r05): _ensure must dedupe unseen ids while
    preserving order — pull([5, 9, 5]) once claimed two rows for id 5,
    aliasing id 9's row and corrupting _index for every later id."""
    from paddle_tpu.distributed.ps.table import SparseTable
    t = SparseTable(4, optimizer="sgd", lr=0.1, init="uniform", seed=0)
    rows = t.pull([5, 9, 5])
    assert rows.shape == (3, 4)
    assert len(t) == 2                      # two distinct ids materialized
    np.testing.assert_array_equal(rows[0], rows[2])   # same id, same row
    assert not np.array_equal(rows[0], rows[1])       # 9 got its OWN row
    # indices are dense and order-preserving: 5 first-seen before 9
    assert t._index[5] == 0 and t._index[9] == 1
    # later ids keep extending densely
    t.pull([7])
    assert t._index[7] == 2
    # pushes against duplicate-id pulls update exactly the two rows
    before = t.pull([5, 9])
    t.push_grad([5, 9, 5], np.ones((3, 4), "float32"))
    after = t.pull([5, 9])
    assert not np.allclose(before, after)
