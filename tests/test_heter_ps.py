"""HeterPS accelerator-resident cache (reference
framework/fleet/heter_ps/hashtable.h; N22)."""
import numpy as np
import pytest

from paddle_tpu.distributed.ps import (DeviceHashTable, HeterPSCache,
                                       PSClient, PSServer)


def test_device_hashtable_roundtrip():
    t = DeviceHashTable(capacity=64, dim=3)
    ids = np.array([5, 900, 12345678901234, 7], np.int64)
    rows = np.arange(12, dtype=np.float32).reshape(4, 3)
    t.insert(ids, rows)
    got, found = t.lookup(np.array([7, 5, 999], np.int64))
    assert list(np.asarray(found)) == [True, True, False]
    np.testing.assert_allclose(np.asarray(got)[0], rows[3])
    np.testing.assert_allclose(np.asarray(got)[1], rows[0])
    np.testing.assert_allclose(np.asarray(got)[2], 0.0)
    # overwrite existing key
    t.insert(np.array([5], np.int64), np.full((1, 3), 9.0, np.float32))
    got, _ = t.lookup(np.array([5], np.int64))
    np.testing.assert_allclose(np.asarray(got)[0], 9.0)
    assert len(t) == 4


def test_device_hashtable_collisions_and_capacity():
    # tiny table forces probing; all 8 inserts must still land
    t = DeviceHashTable(capacity=16, dim=1, max_probes=16)
    ids = np.arange(8, dtype=np.int64) * 16    # adversarial-ish stride
    t.insert(ids, np.arange(8, dtype=np.float32).reshape(8, 1))
    got, found = t.lookup(ids)
    assert np.asarray(found).all()
    np.testing.assert_allclose(np.asarray(got)[:, 0], np.arange(8))
    with pytest.raises(RuntimeError):
        big = DeviceHashTable(capacity=4, dim=1, max_probes=2)
        big.insert(np.arange(16, dtype=np.int64),
                   np.zeros((16, 1), np.float32))


@pytest.fixture()
def ps():
    srv = PSServer(tables={"emb": {"type": "sparse", "dim": 4,
                                   "optimizer": "sgd", "lr": 1.0,
                                   "init": "uniform", "seed": 3}})
    srv.start()
    client = PSClient([srv.endpoint])
    yield client
    client.close()
    srv.shutdown()


def test_heter_cache_read_through_and_hit_tracking(ps):
    cache = HeterPSCache(ps, "emb", dim=4, capacity=256)
    ids = np.array([[1, 2], [2, 3]], np.int64)
    rows, index = cache.pull(ids)
    assert rows.shape == (3, 4) and index.shape == (2, 2)
    assert cache.misses == 3 and cache.hits == 0
    server_rows = np.asarray(ps.pull_sparse("emb", np.array([1, 2, 3])))
    np.testing.assert_allclose(np.asarray(rows), server_rows, rtol=1e-6)
    # second pull: all hits, no RPC needed for those rows
    rows2, _ = cache.pull(ids)
    assert cache.hits == 3 and cache.misses == 3
    np.testing.assert_allclose(np.asarray(rows2), server_rows, rtol=1e-6)


def test_heter_cache_push_refreshes(ps):
    cache = HeterPSCache(ps, "emb", dim=4, capacity=256)
    ids = np.array([10, 11], np.int64)
    before, _ = cache.pull(ids)
    g = np.ones((2, 4), np.float32)
    cache.push_grad(ids, g)
    # server applied sgd lr=1.0: row -= g; cache must match the server
    after, _ = cache.pull(ids)
    np.testing.assert_allclose(np.asarray(after),
                               np.asarray(before) - 1.0, rtol=1e-5)
    srv_rows = np.asarray(ps.pull_sparse("emb", ids))
    np.testing.assert_allclose(np.asarray(after), srv_rows, rtol=1e-6)


def test_heter_cache_duplicate_grad_merge(ps):
    cache = HeterPSCache(ps, "emb", dim=4, capacity=64)
    ids = np.array([20, 20, 21], np.int64)
    cache.pull(ids)
    grads = np.stack([np.full(4, 1.0), np.full(4, 2.0),
                      np.full(4, 5.0)]).astype(np.float32)
    before = np.asarray(ps.pull_sparse("emb", np.array([20, 21])))
    cache.push_grad(ids, grads)
    after = np.asarray(ps.pull_sparse("emb", np.array([20, 21])))
    np.testing.assert_allclose(after[0], before[0] - 3.0, rtol=1e-5)
    np.testing.assert_allclose(after[1], before[1] - 5.0, rtol=1e-5)


def _stat(name):
    from paddle_tpu.core import monitor
    return monitor.stat_get(name)


def test_device_hashtable_remove_then_reinsert():
    t = DeviceHashTable(capacity=32, dim=2)
    ids = np.arange(6, dtype=np.int64) * 32      # force probe collisions
    t.insert(ids, np.arange(12, dtype=np.float32).reshape(6, 2))
    t.remove(ids[:2])
    got, found = t.lookup(ids)
    assert list(np.asarray(found)) == [False, False, True, True, True, True]
    assert len(t) == 4
    # re-inserting a key that still sits PAST a removed hole must update
    # the existing slot, not create a duplicate in the hole
    t.insert(ids[2:3], np.full((1, 2), 42.0, np.float32))
    got, found = t.lookup(ids[2:3])
    np.testing.assert_allclose(np.asarray(got)[0], 42.0)
    t.remove(ids[2:3])
    got, found = t.lookup(ids[2:3])
    assert not bool(np.asarray(found)[0])        # no stale duplicate


def test_heter_cache_lru_evicts_to_host_tier(ps):
    cache = HeterPSCache(ps, "emb", dim=4, capacity=4, host_rows=8)
    first = np.arange(4, dtype=np.int64)
    rows_first, _ = cache.pull(first)
    ev0, hh0 = _stat("ps.heter.evictions"), _stat("ps.heter.host_hits")
    cache.pull(np.arange(4, 8, dtype=np.int64))  # evicts the first 4
    assert _stat("ps.heter.evictions") - ev0 == 4
    assert len(cache) == 4 and cache.host_len == 4
    # evicted ids come back from the HOST tier: correct values, no PS RPC
    rpcs0 = _stat("ps.client.pull_rpcs")
    rows_again, _ = cache.pull(first)
    assert _stat("ps.client.pull_rpcs") == rpcs0
    assert _stat("ps.heter.host_hits") - hh0 == 4
    np.testing.assert_array_equal(np.asarray(rows_again),
                                  np.asarray(rows_first))
    np.testing.assert_array_equal(
        np.asarray(rows_again), np.asarray(ps.pull_sparse("emb", first)))


def test_heter_cache_host_tier_disabled_goes_to_ps(ps):
    cache = HeterPSCache(ps, "emb", dim=4, capacity=2, host_rows=0)
    cache.pull(np.array([1, 2], np.int64))
    cache.pull(np.array([3, 4], np.int64))       # 1, 2 evicted, dropped
    assert cache.host_len == 0
    m0 = _stat("ps.heter.misses")
    rows, _ = cache.pull(np.array([1], np.int64))
    assert _stat("ps.heter.misses") - m0 == 1    # re-read through the PS
    np.testing.assert_array_equal(
        np.asarray(rows), np.asarray(ps.pull_sparse("emb", [1])))


def test_heter_cache_push_keeps_tiers_coherent(ps):
    """A pushed id must never be served from a pre-push host-tier copy:
    push refreshes the device tier and drops the host copy."""
    cache = HeterPSCache(ps, "emb", dim=4, capacity=2, host_rows=8)
    cache.pull(np.array([30, 31], np.int64))
    cache.pull(np.array([32, 33], np.int64))     # 30, 31 -> host tier
    assert cache.host_len == 2
    cache.push_grad(np.array([30], np.int64),
                    np.ones((1, 4), np.float32))
    rows, _ = cache.pull(np.array([30], np.int64))
    np.testing.assert_array_equal(
        np.asarray(rows), np.asarray(ps.pull_sparse("emb", [30])))


def test_heter_cache_empty_push_is_noop(ps):
    cache = HeterPSCache(ps, "emb", dim=4, capacity=16)
    cache.push_grad(np.zeros((0,), np.int64), np.zeros((0, 4), np.float32))
    assert len(cache) == 0          # same no-op contract as the client


def test_promoted_backup_rows_repulled_never_stale():
    """ISSUE 12 satellite: rows cached before a failover promotion are
    INVALIDATED by the shard-map adoption — the next pull re-reads from
    the promoted backup instead of serving the stale cached copy."""
    import time

    from paddle_tpu.core import monitor
    from paddle_tpu.distributed.ps import ShardMap

    spec = {"emb": {"type": "sparse", "dim": 4, "optimizer": "sgd",
                    "lr": 1.0, "init": "uniform", "seed": 3}}
    fast = dict(timeout=5.0, max_retries=2, backoff_base=0.01,
                backoff_max=0.05)
    servers = [PSServer("127.0.0.1:0", dict(spec)) for _ in range(2)]
    eps = [s.start() for s in servers]
    smap = ShardMap.create(eps, n_backups=1)
    for s in servers:
        s.enable_replication(shard_map=smap, peers=eps, n_backups=1,
                             heartbeat_s=0.1, heartbeat_timeout_s=0.7,
                             rpc_opts=dict(fast))
    client_a = PSClient(eps, **fast)
    client_b = PSClient(eps, **fast)
    cache = HeterPSCache(client_a, "emb", dim=4, capacity=64)
    try:
        ids = np.array([0], np.int64)            # shard 0: primary 0
        cached, _ = cache.pull(ids)
        # an INVISIBLE writer updates the row (cache can't see it)...
        client_b.push_sparse_grad("emb", ids, np.ones((1, 4), np.float32))
        fresh_value = np.asarray(client_b.pull_sparse("emb", ids))
        assert not np.array_equal(np.asarray(cached), fresh_value)
        # ...then the primary dies permanently and the backup promotes
        servers[0].shutdown()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                eps[0] in servers[1].replica.shard_map.servers:
            time.sleep(0.05)
        assert eps[0] not in servers[1].replica.shard_map.servers
        inv0 = monitor.stat_get("ps.heter.invalidations")
        # ANY traffic that re-routes adopts the new map; the adoption
        # pends an invalidation that applies before the next row is read
        cache.pull(np.array([7], np.int64))      # miss -> RPC -> adopt
        rows, _ = cache.pull(ids)                # must NOT be the hit
        assert monitor.stat_get("ps.heter.invalidations") - inv0 >= 1
        np.testing.assert_array_equal(np.asarray(rows), fresh_value)
    finally:
        cache_closers = (client_a, client_b)
        for c in cache_closers:
            try:
                c.close()
            except Exception:
                pass
        for s in servers:
            s.shutdown()
