"""HeterPS accelerator-resident cache (reference
framework/fleet/heter_ps/hashtable.h; N22)."""
import numpy as np
import pytest

from paddle_tpu.distributed.ps import (DeviceHashTable, HeterPSCache,
                                       PSClient, PSServer)


def test_device_hashtable_roundtrip():
    t = DeviceHashTable(capacity=64, dim=3)
    ids = np.array([5, 900, 12345678901234, 7], np.int64)
    rows = np.arange(12, dtype=np.float32).reshape(4, 3)
    t.insert(ids, rows)
    got, found = t.lookup(np.array([7, 5, 999], np.int64))
    assert list(np.asarray(found)) == [True, True, False]
    np.testing.assert_allclose(np.asarray(got)[0], rows[3])
    np.testing.assert_allclose(np.asarray(got)[1], rows[0])
    np.testing.assert_allclose(np.asarray(got)[2], 0.0)
    # overwrite existing key
    t.insert(np.array([5], np.int64), np.full((1, 3), 9.0, np.float32))
    got, _ = t.lookup(np.array([5], np.int64))
    np.testing.assert_allclose(np.asarray(got)[0], 9.0)
    assert len(t) == 4


def test_device_hashtable_collisions_and_capacity():
    # tiny table forces probing; all 8 inserts must still land
    t = DeviceHashTable(capacity=16, dim=1, max_probes=16)
    ids = np.arange(8, dtype=np.int64) * 16    # adversarial-ish stride
    t.insert(ids, np.arange(8, dtype=np.float32).reshape(8, 1))
    got, found = t.lookup(ids)
    assert np.asarray(found).all()
    np.testing.assert_allclose(np.asarray(got)[:, 0], np.arange(8))
    with pytest.raises(RuntimeError):
        big = DeviceHashTable(capacity=4, dim=1, max_probes=2)
        big.insert(np.arange(16, dtype=np.int64),
                   np.zeros((16, 1), np.float32))


@pytest.fixture()
def ps():
    srv = PSServer(tables={"emb": {"type": "sparse", "dim": 4,
                                   "optimizer": "sgd", "lr": 1.0,
                                   "init": "uniform", "seed": 3}})
    srv.start()
    client = PSClient([srv.endpoint])
    yield client
    client.close()
    srv.shutdown()


def test_heter_cache_read_through_and_hit_tracking(ps):
    cache = HeterPSCache(ps, "emb", dim=4, capacity=256)
    ids = np.array([[1, 2], [2, 3]], np.int64)
    rows, index = cache.pull(ids)
    assert rows.shape == (3, 4) and index.shape == (2, 2)
    assert cache.misses == 3 and cache.hits == 0
    server_rows = np.asarray(ps.pull_sparse("emb", np.array([1, 2, 3])))
    np.testing.assert_allclose(np.asarray(rows), server_rows, rtol=1e-6)
    # second pull: all hits, no RPC needed for those rows
    rows2, _ = cache.pull(ids)
    assert cache.hits == 3 and cache.misses == 3
    np.testing.assert_allclose(np.asarray(rows2), server_rows, rtol=1e-6)


def test_heter_cache_push_refreshes(ps):
    cache = HeterPSCache(ps, "emb", dim=4, capacity=256)
    ids = np.array([10, 11], np.int64)
    before, _ = cache.pull(ids)
    g = np.ones((2, 4), np.float32)
    cache.push_grad(ids, g)
    # server applied sgd lr=1.0: row -= g; cache must match the server
    after, _ = cache.pull(ids)
    np.testing.assert_allclose(np.asarray(after),
                               np.asarray(before) - 1.0, rtol=1e-5)
    srv_rows = np.asarray(ps.pull_sparse("emb", ids))
    np.testing.assert_allclose(np.asarray(after), srv_rows, rtol=1e-6)


def test_heter_cache_duplicate_grad_merge(ps):
    cache = HeterPSCache(ps, "emb", dim=4, capacity=64)
    ids = np.array([20, 20, 21], np.int64)
    cache.pull(ids)
    grads = np.stack([np.full(4, 1.0), np.full(4, 2.0),
                      np.full(4, 5.0)]).astype(np.float32)
    before = np.asarray(ps.pull_sparse("emb", np.array([20, 21])))
    cache.push_grad(ids, grads)
    after = np.asarray(ps.pull_sparse("emb", np.array([20, 21])))
    np.testing.assert_allclose(after[0], before[0] - 3.0, rtol=1e-5)
    np.testing.assert_allclose(after[1], before[1] - 5.0, rtol=1e-5)
