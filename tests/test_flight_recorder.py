"""Flight recorder (core/flight_recorder.py) + obs_report rendering +
the Chrome-trace acceptance path: a pipelined train_from_dataset run
exports dispatch/retire/materialize spans linked by flow events across
threads; a forced PipelineStepError and a PS chaos run each produce a
dump that tools/obs_report.py renders. See docs/observability.md."""
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer, static
from paddle_tpu.core import flight_recorder, trace
from paddle_tpu.static import PipelineRunner, PipelineStepError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import obs_report  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_recorder():
    flight_recorder._dumped.clear()
    trace.reset()
    yield
    flight_recorder._dumped.clear()


@pytest.fixture()
def dump_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "dumps")
    monkeypatch.setenv("PADDLE_TPU_DUMP_DIR", d)
    return d


def _dumps(d, reason=None):
    if not os.path.isdir(d):
        return []
    names = sorted(os.listdir(d))
    if reason is not None:
        names = [n for n in names if f"_{reason}_" in n]
    return [os.path.join(d, n) for n in names]


def _build(name):
    paddle.seed(0)
    prog = static.Program(name)
    with static.program_guard(prog):
        x = static.data("x", [-1, 4], "float32")
        y = static.data("y", [-1, 1], "float32")
        h = ops.relu(nn.Linear(4, 8)(x))
        loss = ops.mse_loss(nn.Linear(8, 1)(h), y)
        optimizer.Adam(learning_rate=0.05).minimize(loss)
    return prog, loss


def _feeds(n, batch=8):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(batch, 4).astype("float32"),
             "y": rng.rand(batch, 1).astype("float32")}
            for _ in range(n)]


# ------------------------------------------------------------- unit level

def test_dump_noop_without_env(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_DUMP_DIR", raising=False)
    assert not flight_recorder.enabled()
    assert flight_recorder.dump("whatever", ValueError("x")) is None


def test_dump_schema_and_rate_limit(dump_dir):
    trace.instant("marker", step=7)
    from paddle_tpu.core import monitor
    monitor.stat_add("tm.fr.counter", 3)
    paths = [flight_recorder.dump("unit", ValueError("boom"),
                                  extra={"k": 1})
             for _ in range(flight_recorder.MAX_DUMPS_PER_REASON + 2)]
    written = [p for p in paths if p]
    assert len(written) == flight_recorder.MAX_DUMPS_PER_REASON
    rec = json.load(open(written[0]))
    assert tuple(rec.keys()) == flight_recorder.SCHEMA_KEYS
    assert rec["schema"] == flight_recorder.SCHEMA_VERSION
    assert rec["reason"] == "unit"
    assert rec["exception"]["type"] == "ValueError"
    assert rec["extra"] == {"k": 1}
    assert any(s["name"] == "marker" and s["attrs"].get("step") == 7
               for s in rec["spans"])
    assert rec["metrics"]["values"]["tm.fr.counter"] == 3
    assert "FLAGS_executor_max_inflight" in rec["flags"]
    monitor.reset(prefix="tm.fr.")


def test_suppressed_scope_blocks_reason_on_this_thread(dump_dir):
    # the Communicator's outer retry layer suppresses premature
    # "transport death" dumps from inner per-call exhaustion
    with flight_recorder.suppressed("ps_transport_death"):
        assert flight_recorder.dump("ps_transport_death") is None
        assert flight_recorder.dump("other_reason") is not None
    assert flight_recorder.dump("ps_transport_death") is not None


def test_schema_v2_identity_fields(dump_dir):
    # schema 2 adds cluster identity: incident_id + role + peer_members
    flight_recorder.set_identity(role="server", peers=["a", "b"])
    try:
        p = flight_recorder.dump("unit_v2", incident_id="inc_test01")
        rec = json.load(open(p))
        assert rec["schema"] == 2 == flight_recorder.SCHEMA_VERSION
        assert tuple(rec.keys()) == flight_recorder.SCHEMA_KEYS
        assert rec["incident_id"] == "inc_test01"
        assert rec["role"] == "server"
        assert rec["peer_members"] == ["a", "b"]
        text = obs_report.render(rec)
        assert "role: server" in text
        assert "incident: inc_test01" in text
    finally:
        flight_recorder.set_identity(role=None, peers=None)


def test_v1_fixture_renders_unchanged():
    # regression: committed schema-1 dumps must keep rendering
    # byte-identically — v2 fields are additive and only printed when
    # present, so old dumps never grow new lines
    fix = os.path.join(os.path.dirname(__file__), "fixtures")
    rec = obs_report.load(os.path.join(fix, "obsdump_v1.json"))
    assert rec["schema"] == 1
    want = open(os.path.join(fix, "obsdump_v1.expected.txt")).read()
    assert obs_report.render(rec) + "\n" == want
    assert "role:" not in want and "incident:" not in want


def test_dump_listener_fires_once_per_trigger(dump_dir):
    seen = []
    flight_recorder.register_dump_listener(
        lambda reason, exc, iid: seen.append((reason, iid)))
    try:
        flight_recorder.dump("listener_probe")
        flight_recorder.dump("listener_probe2", incident_id="inc_x")
    finally:
        flight_recorder.unregister_dump_listener(
            flight_recorder._dump_listeners[-1]
            if flight_recorder._dump_listeners else None)
        flight_recorder._dump_listeners.clear()
    assert ("listener_probe", None) in seen
    assert ("listener_probe2", "inc_x") in seen


# ------------------------------------------ PipelineStepError -> dump

def test_pipeline_step_error_dumps_and_report_renders(dump_dir):
    paddle.enable_static()
    try:
        prog, loss = _build("fr_chaos")
        exe = static.Executor()
        runner = PipelineRunner(exe, prog, fetch_list=[loss],
                                max_inflight=4)
        feeds = _feeds(4)
        runner.submit(feeds[0])
        entry = runner._entry
        orig = entry.jitted
        calls = {"n": 0}

        def bomb(*a, **k):
            calls["n"] += 1
            if calls["n"] == 2:  # overall step index 2
                raise RuntimeError("injected chaos")
            return orig(*a, **k)

        entry.jitted = bomb
        try:
            runner.submit(feeds[1])
            runner.submit(feeds[2])
            with pytest.raises(PipelineStepError, match="step 2"):
                runner.sync()
        finally:
            entry.jitted = orig
    finally:
        paddle.disable_static()
    dumps = _dumps(dump_dir, "pipeline_step_error")
    assert dumps, "PipelineStepError did not produce a dump"
    rec = obs_report.load(dumps[0])
    assert rec["extra"]["step_index"] == 2
    text = obs_report.render(rec)
    assert "== step timeline" in text
    assert "== ps health" in text
    assert "== pallas kernels" in text
    assert "pipeline/dispatch" in text      # host-overhead table rows
    # the failing run's dispatch spans made it into the timeline
    assert "injected chaos" in rec["exception"]["message"]
    # dump -> chrome trace conversion round-trips
    out = str(os.path.join(dump_dir, "from_dump.json"))
    obs_report.dump_to_chrome_trace(rec, out)
    ev = json.load(open(out))["traceEvents"]
    assert any(e.get("cat") == "flow" for e in ev)


# ------------------------------------------------- PS chaos -> dump

def test_ps_transport_death_dumps(dump_dir):
    from paddle_tpu.distributed.ps import PSClient, PSServer
    from paddle_tpu.testing import faults
    srv = PSServer(tables={"emb": {"type": "sparse", "dim": 4,
                                   "optimizer": "sgd", "lr": 1.0,
                                   "init": "zeros"}})
    srv.start()
    try:
        client = PSClient([srv.endpoint], timeout=2.0, max_retries=1,
                          backoff_base=0.01, backoff_max=0.02,
                          connect_retry_s=5.0)
        with faults.inject(faults.Fault("client", "send", faults.RESET,
                                        method="pull_sparse", times=10)):
            with pytest.raises(ConnectionError):
                client.pull_sparse("emb", [1, 2])
        faults.uninstall()
        client.close()
    finally:
        srv.shutdown()
    dumps = _dumps(dump_dir, "ps_transport_death")
    assert dumps, "transport death did not produce a dump"
    rec = obs_report.load(dumps[0])
    assert rec["extra"]["method"] == "pull_sparse"
    assert rec["extra"]["attempts"] == 2
    text = obs_report.render(rec)
    assert "ps.rpc.retries" in text
    # the dying call's span is in the dump, error-tagged
    assert any(s["name"] == "ps.rpc/pull_sparse"
               and s["attrs"].get("error") for s in rec["spans"])


# ------------------------------------------------ fatal-signal hook

@pytest.mark.slow
def test_signal_dump_in_subprocess(tmp_path):
    d = str(tmp_path / "sigdumps")
    code = (
        "import os, signal, sys\n"
        "import paddle_tpu\n"           # maybe_install() arms the hook
        "os.kill(os.getpid(), signal.SIGUSR1)\n"   # on-demand dump
        "print('alive')\n"              # SIGUSR1 must not kill us
    )
    env = dict(os.environ, PADDLE_TPU_DUMP_DIR=d, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stderr
    assert "alive" in out.stdout
    dumps = _dumps(d, "signal_SIGUSR1")
    assert dumps, f"no signal dump in {d}: {os.listdir(tmp_path)}"
    rec = json.load(open(dumps[0]))
    assert rec["reason"] == "signal_SIGUSR1"


# ------------------------------- acceptance: chrome trace with flows

def test_pipelined_train_from_dataset_exports_linked_chrome_trace(
        tmp_path):
    class _DS:  # minimal train_from_dataset dataset: batches() of feeds
        def __init__(self, feeds):
            self._feeds = feeds

        def batches(self):
            return iter(self._feeds)

    paddle.enable_static()
    trace.reset()
    trace.start()
    try:
        prog, loss = _build("fr_accept")
        exe = static.Executor()
        exe.train_from_dataset(program=prog, dataset=_DS(_feeds(5)),
                               fetch_list=[loss], print_period=1)
    finally:
        spans = trace.stop()
        paddle.disable_static()
    dispatch = {s.attrs["step"]: s for s in spans
                if s.name == "pipeline/dispatch"}
    assert set(dispatch) == {0, 1, 2, 3, 4}
    retire_flows = {fid for s in spans if s.name == "pipeline/retire"
                    for fid, ph in (s.flows or []) if ph == "t"}
    mat_flows = {fid for s in spans if s.name == "pipeline/materialize"
                 for fid, ph in (s.flows or []) if ph == "f"}
    prefetch = [s for s in spans if s.name == "pipeline/prefetch"]
    assert len(prefetch) == 5
    for step, d in dispatch.items():
        step_fid = next(fid for fid, ph in d.flows if ph == "s")
        # dispatch -> retire -> materialize all linked by one flow id
        assert step_fid in retire_flows, f"step {step} never retired"
        assert step_fid in mat_flows, f"step {step} never materialized"
        # ...and the prefetch handoff terminates on the dispatch span
        pf_fid = next(fid for fid, ph in d.flows if ph == "f")
        assert any(pf_fid in [fid for fid, ph in (p.flows or [])
                              if ph == "s"] for p in prefetch)
    # the work genuinely crossed threads: prefetch ran off the driver
    driver_tid = dispatch[0].tid
    assert any(p.tid != driver_tid for p in prefetch)
    # every span shares ONE trace id (attach() joined the prefetcher)
    assert len({s.trace_id for s in [*dispatch.values(), *prefetch]}) == 1
    # exported chrome trace carries matching s/t/f flow triples
    path = str(tmp_path / "pipeline_trace.json")
    trace.export_chrome_trace(path, spans=spans)
    ev = json.load(open(path))["traceEvents"]
    flows = [e for e in ev if e.get("cat") == "flow"]
    by_id = {}
    for e in flows:
        by_id.setdefault(e["id"], set()).add(e["ph"])
    full_chains = [fid for fid, phases in by_id.items()
                   if {"s", "t", "f"} <= phases]
    assert len(full_chains) >= 5  # one complete arrow chain per step
    tids = {e["tid"] for e in ev if e["ph"] == "X"}
    assert len(tids) >= 2
