"""core/telemetry.py in-process: exactly-once counter shipping through
scripted transport chaos, last-wins gauges, union-exact histogram
merge, never-block-the-hot-path span backpressure, and the coordinated
incident protocol (trigger -> join -> merged dump)."""
import copy
import json
import os
import time

import pytest

from paddle_tpu.core import flight_recorder, monitor, telemetry, trace
from paddle_tpu.core.monitor import _Hist
from paddle_tpu.testing import faults

FAST_RPC = dict(timeout=0.5, max_retries=3, backoff_base=0.01,
                backoff_max=0.05, connect_retry_s=1.0)


class _Registry:
    """A fake per-process monitor registry the shipper snapshots."""

    def __init__(self):
        self.values = {}
        self.types = {}
        self.hists = {}

    def counter(self, name, v):
        self.values[name] = self.values.get(name, 0.0) + v
        self.types[name] = "counter"

    def gauge(self, name, v):
        self.values[name] = v
        self.types[name] = "gauge"

    def hist(self, name, summary):
        self.hists[name] = summary
        self.types[name] = "histogram"

    def snapshot(self):
        return copy.deepcopy({"values": self.values, "types": self.types,
                              "histograms": self.hists})


@pytest.fixture
def hub(tmp_path):
    h = telemetry.TelemetryHub(dump_dir=str(tmp_path),
                               incident_window_s=10.0)
    yield h
    h.stop()


def _shipper(hub, member, reg, **kw):
    kw.setdefault("rpc_opts", FAST_RPC)
    kw.setdefault("capture_spans", False)
    kw.setdefault("report_incidents", False)
    return telemetry.TelemetryShipper(
        hub.endpoint, member_id=member, snapshot_fn=reg.snapshot, **kw)


def test_counters_exactly_once_through_drop_and_reset(hub):
    reg = _Registry()
    s = _shipper(hub, "m1", reg, role="worker")
    try:
        reg.counter("c", 5.0)
        # the applied-but-reply-lost case replay keys exist for: the hub
        # applies the delta, the reply is DROPPED, the retried shipment
        # must be a replay (NOT a re-add)
        with faults.inject(faults.Fault("server", "reply", faults.DROP,
                                        method="telemetry_ship",
                                        times=1)) as inj:
            s.flush()
            assert inj.fired(faults.DROP) == 1
        assert hub.member_counters("m1") == {"c": 5.0}
        # connection torn down mid-exchange: the reconnect retry carries
        # the same replay key
        reg.counter("c", 4.0)
        with faults.inject(faults.Fault("server", "reply", faults.RESET,
                                        method="telemetry_ship",
                                        times=1)) as inj:
            s.flush()
            assert inj.fired(faults.RESET) == 1
        assert hub.member_counters("m1") == {"c": 9.0}
        assert hub.snapshot()["counters"] == {"c": 9.0}
        # nothing new: a flush ships nothing and totals stand
        s.flush()
        assert hub.snapshot()["counters"] == {"c": 9.0}
        assert s.shipped_totals()["c"] == 9.0
    finally:
        s.close(drain_timeout=2.0)


def test_gauges_last_wins_and_multi_member_counter_sum(hub):
    ra, rb = _Registry(), _Registry()
    sa = _shipper(hub, "a", ra)
    sb = _shipper(hub, "b", rb)
    try:
        ra.gauge("depth", 3.0)
        ra.counter("n", 2.0)
        sa.flush()
        ra.gauge("depth", 7.0)
        ra.counter("n", 1.0)
        sa.flush()
        rb.counter("n", 10.0)
        sb.flush()
        snap = hub.snapshot()
        assert snap["gauges"]["depth"] == 7.0         # last wins
        assert snap["counters"]["n"] == 13.0          # sum of members
        assert hub.member_counters("a") == {"n": 3.0}
        assert hub.member_counters("b") == {"n": 10.0}
    finally:
        sa.close(drain_timeout=2.0)
        sb.close(drain_timeout=2.0)


def test_hist_merge_across_members_equals_union_stream(hub):
    import numpy as np
    rng = np.random.RandomState(5)
    xs_a = list(rng.uniform(0, 50, 80))
    xs_b = list(rng.uniform(0, 50, 33))
    bounds = (1.0, 5.0, 25.0)

    def _summary(xs):
        h = _Hist(bounds)
        for v in xs:
            h.observe(v)
        return h.summary()

    ra, rb = _Registry(), _Registry()
    ra.hist("lat_ms", _summary(xs_a))
    rb.hist("lat_ms", _summary(xs_b))
    sa = _shipper(hub, "a", ra)
    sb = _shipper(hub, "b", rb)
    try:
        sa.flush()
        sb.flush()
        merged = hub.snapshot()["hists"]["lat_ms"]
        union = _summary(xs_a + xs_b)
        assert merged["buckets"] == union["buckets"]
        assert merged["bounds"] == union["bounds"]
        assert merged["count"] == union["count"]
        assert merged["sum"] == pytest.approx(union["sum"])
    finally:
        sa.close(drain_timeout=2.0)
        sb.close(drain_timeout=2.0)


def test_span_backpressure_never_blocks_and_counts_drops():
    # a DEAD hub: nothing listens on the endpoint. The span sink (the
    # hot-path side) must stay O(1) append/shed; the flush side fails
    # without the sink ever waiting on it.
    reg = _Registry()
    before = monitor.stats("telemetry.")
    s = telemetry.TelemetryShipper(
        "127.0.0.1:9", member_id="dead", snapshot_fn=reg.snapshot,
        span_buffer=8, rpc_opts=dict(timeout=0.2, max_retries=0,
                                     backoff_base=0.01, backoff_max=0.02,
                                     connect_retry_s=0.2,
                                     fail_fast_refused=True),
        report_incidents=False)
    try:
        t0 = time.perf_counter()
        for i in range(500):
            with trace.span("unit/backpressure", i=i):
                pass
        sink_wall = time.perf_counter() - t0
        # 500 spans through a full buffer against a dead hub: the beat
        # thread never blocked on telemetry
        assert sink_wall < 1.0
        reg.counter("c", 1.0)
        # the flush side reports unreachable (the lazy dial fails) —
        # never raises out of a member's beat thread
        assert s.flush() is False
        after = monitor.stats("telemetry.")
        dropped = (after.get("telemetry.dropped_spans", 0)
                   - before.get("telemetry.dropped_spans", 0))
        batches = (after.get("telemetry.dropped_batches", 0)
                   - before.get("telemetry.dropped_batches", 0))
        assert dropped >= 490          # cap 8, the rest shed
        assert batches >= 1            # the affected flush is counted
    finally:
        try:
            s.close(drain_timeout=0.5)
        except Exception:
            pass                       # the hub is dead by design


def test_incident_trigger_joins_and_merges(hub, tmp_path, monkeypatch):
    monkeypatch.setattr(flight_recorder, "dump_dir", lambda: None)
    reg = _Registry()
    s = telemetry.TelemetryShipper(
        hub.endpoint, member_id="w1", role="trainer", peers=["w1"],
        snapshot_fn=reg.snapshot, flush_s=0.05, rpc_opts=FAST_RPC,
        capture_spans=True, report_incidents=True).start()
    try:
        with trace.span("unit/incident_span"):
            pass
        flight_recorder.dump("unit_incident_trigger")
        deadline = time.time() + 10.0
        while time.time() < deadline and not hub.incidents():
            time.sleep(0.05)
        incs = hub.incidents()
        assert len(incs) == 1
        iid = next(iter(incs))
        assert incs[iid]["reason"] == "unit_incident_trigger"
        # a second trigger inside the window JOINS instead of opening
        flight_recorder.dump("unit_incident_second")
        time.sleep(0.3)
        assert len(hub.incidents()) == 1
        # the member's schema-v2 record lands in the merged dump
        path = os.path.join(str(tmp_path), f"incident_{iid}.json")
        deadline = time.time() + 10.0
        rec = None
        while time.time() < deadline:
            with open(path) as f:
                inc = json.load(f)
            rec = inc["members"].get("w1")
            if rec:
                break
            time.sleep(0.05)
        assert rec, f"member record never attached: {inc['members']}"
        assert inc["schema"] == telemetry.INCIDENT_SCHEMA
        assert rec["schema"] == flight_recorder.SCHEMA_VERSION
        assert rec["incident_id"] == iid
        assert rec["role"] == "trainer"
        assert any(sp["name"] == "unit/incident_span"
                   for sp in rec["spans"])
        assert "w1" in incs[iid]["triggers"]
    finally:
        s.close(drain_timeout=2.0)
        flight_recorder.set_identity(role="", peers=[])


def test_fetch_snapshot(hub):
    reg = _Registry()
    reg.counter("k", 3.0)
    s = _shipper(hub, "f1", reg)
    try:
        s.flush()
        snap = telemetry.fetch_snapshot(hub.endpoint)
        assert snap["counters"] == {"k": 3.0}
        assert "f1" in snap["members"]
    finally:
        s.close(drain_timeout=2.0)
    with pytest.raises(Exception):
        telemetry.fetch_snapshot("127.0.0.1:9", timeout=0.3)
