"""Multi-host bootstrap: 2 local processes x 4 CPU devices form ONE
8-device mesh via the PADDLE_* env contract -> jax.distributed
(VERDICT r02 item 6; reference gen_nccl_id_op_helper.cc TCP rendezvous and
test strategy test_dist_base.py:642 — multi-node jobs tested as local
processes)."""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

WORKER = textwrap.dedent("""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import distributed as dist

    dist.init_parallel_env({"dp": 8})   # joins the coordination service
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = dist.get_mesh()
    repl = NamedSharding(mesh, P())
    row = NamedSharding(mesh, P("dp"))

    # dp-sharded least-squares descent: every host must end with the same w
    rng = np.random.RandomState(0)
    X = rng.randn(16, 4).astype(np.float32)
    y = (X @ np.array([1.0, 2.0, 3.0, 4.0], np.float32))
    rank = dist.get_rank()
    Xl, yl = X[rank * 8:(rank + 1) * 8], y[rank * 8:(rank + 1) * 8]
    Xg = jax.make_array_from_process_local_data(row, Xl)
    yg = jax.make_array_from_process_local_data(row, yl)
    w = jax.device_put(jnp.zeros(4, jnp.float32), repl)

    def loss(w, X, y):
        return ((X @ w - y) ** 2).mean()

    step = jax.jit(lambda w, X, y: w - 0.1 * jax.grad(loss)(w, X, y),
                   in_shardings=(repl, row, row), out_shardings=repl)
    for _ in range(20):
        w = step(w, Xg, yg)
    out = np.asarray(w)
    np.save(OUT_PATH, out)
    print("worker", rank, "w=", out)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_eight_device_mesh(tmp_path):
    ports = [_free_port(), _free_port()]
    endpoints = ",".join(f"127.0.0.1:{p}" for p in ports)
    procs, outs = [], []
    for rank in range(2):
        out_path = os.path.join(str(tmp_path), f"w{rank}.npy")
        outs.append(out_path)
        code = f"OUT_PATH = {out_path!r}\n" + WORKER
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PADDLE_TRAINER_ID=str(rank),
            PADDLE_TRAINERS_NUM="2",
            PADDLE_TRAINER_ENDPOINTS=endpoints,
            PADDLE_CURRENT_ENDPOINT=f"127.0.0.1:{ports[rank]}",
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code], env=env, cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    logs = []
    for p in procs:
        out, _ = p.communicate(timeout=480)
        logs.append(out)
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-3000:]

    w0, w1 = np.load(outs[0]), np.load(outs[1])
    np.testing.assert_array_equal(w0, w1)  # identical params on both hosts

    # and both match the single-process reference descent
    rng = np.random.RandomState(0)
    X = rng.randn(16, 4).astype(np.float32)
    y = X @ np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    w = np.zeros(4, np.float32)
    for _ in range(20):
        w = w - 0.1 * (2.0 / 16) * X.T @ (X @ w - y)
    np.testing.assert_allclose(w0, w, rtol=1e-4)


HYBRID_WORKER = textwrap.dedent("""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import distributed as dist
    from jax.sharding import NamedSharding, PartitionSpec as P

    dist.init_parallel_env()           # join the coordination service
    assert jax.process_count() == 2
    # hybrid: dp across processes (DCN analog), tp within (ICI analog)
    mesh = dist.init_hybrid_mesh({"tp": 4}, {"dp": 2})
    assert mesh.shape == {"dp": 2, "tp": 4}, mesh.shape
    # every dp group must hold devices of ONE process (DCN axis outermost)
    devs = np.asarray(mesh.devices)
    for slice_row in devs:
        assert len({d.process_index for d in slice_row.ravel()}) == 1

    repl = NamedSharding(mesh, P())
    batch = NamedSharding(mesh, P("dp"))
    col = NamedSharding(mesh, P(None, "tp"))   # W1 column-parallel
    row_ = NamedSharding(mesh, P("tp", None))  # W2 row-parallel

    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype(np.float32)
    Y = rng.randn(16, 2).astype(np.float32)
    W1 = (rng.randn(8, 8) * 0.3).astype(np.float32)
    W2 = (rng.randn(8, 2) * 0.3).astype(np.float32)

    rank = dist.get_rank()
    Xl, Yl = X[rank * 8:(rank + 1) * 8], Y[rank * 8:(rank + 1) * 8]
    Xg = jax.make_array_from_process_local_data(batch, Xl)
    Yg = jax.make_array_from_process_local_data(batch, Yl)
    w1 = jax.device_put(jnp.asarray(W1), col)
    w2 = jax.device_put(jnp.asarray(W2), row_)

    def loss_fn(w1, w2, X, Y):
        h = jax.nn.relu(X @ w1)
        return ((h @ w2 - Y) ** 2).mean()

    @jax.jit
    def step(w1, w2, X, Y):
        l, g = jax.value_and_grad(loss_fn, argnums=(0, 1))(w1, w2, X, Y)
        return l, w1 - 0.05 * g[0], w2 - 0.05 * g[1]

    losses = []
    for _ in range(10):
        l, w1, w2 = step(w1, w2, Xg, Yg)
        losses.append(float(l))
    np.save(OUT_PATH, np.asarray(losses, np.float64))
    print("hybrid worker", rank, "loss", losses[0], "->", losses[-1])
""")


def test_hybrid_dcn_ici_train_step_matches_single_process(tmp_path):
    """VERDICT r04 item 6: dp-across-processes x tp-within-process train
    step; both processes see the same loss curve as a single-process
    reference."""
    ports = [_free_port(), _free_port()]
    endpoints = ",".join(f"127.0.0.1:{p}" for p in ports)
    procs, outs = [], []
    for rank in range(2):
        out_path = os.path.join(str(tmp_path), f"l{rank}.npy")
        outs.append(out_path)
        code = f"OUT_PATH = {out_path!r}\n" + HYBRID_WORKER
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            PADDLE_TRAINER_ID=str(rank),
            PADDLE_TRAINERS_NUM="2",
            PADDLE_TRAINER_ENDPOINTS=endpoints,
            PADDLE_CURRENT_ENDPOINT=f"127.0.0.1:{ports[rank]}",
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code], env=env, cwd="/root/repo",
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    logs = []
    for p in procs:
        out, _ = p.communicate(timeout=480)
        logs.append(out)
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-3000:]

    l0, l1 = np.load(outs[0]), np.load(outs[1])
    np.testing.assert_array_equal(l0, l1)

    # single-process reference: identical math in plain numpy
    rng = np.random.RandomState(0)
    X = rng.randn(16, 8).astype(np.float32)
    Y = rng.randn(16, 2).astype(np.float32)
    W1 = (rng.randn(8, 8) * 0.3).astype(np.float32)
    W2 = (rng.randn(8, 2) * 0.3).astype(np.float32)
    ref = []
    for _ in range(10):
        H = np.maximum(X @ W1, 0)
        P_ = H @ W2
        ref.append(float(((P_ - Y) ** 2).mean()))
        dP = 2.0 / P_.size * (P_ - Y)
        dW2 = H.T @ dP
        dH = dP @ W2.T
        dH[H <= 0] = 0
        dW1 = X.T @ dH
        W1 -= 0.05 * dW1
        W2 -= 0.05 * dW2
    np.testing.assert_allclose(l0, ref, rtol=1e-4)


def test_init_hybrid_mesh_single_process_grouping():
    """Single-process form: 8 CPU devices = 1 slice; a pure-ICI hybrid
    mesh still works and validation catches bad shapes."""
    import jax
    import pytest
    from paddle_tpu.distributed import mesh as mesh_mod
    try:
        m = mesh_mod.init_hybrid_mesh({"tp": 4, "sp": 2}, {"dp": 1})
        assert m.shape == {"dp": 1, "tp": 4, "sp": 2}
        with pytest.raises(ValueError, match="needs 2 slices"):
            mesh_mod.init_hybrid_mesh({"tp": 4}, {"dp": 2})
        with pytest.raises(ValueError, match="appear in both"):
            mesh_mod.init_hybrid_mesh({"dp": 8}, {"dp": 1})
    finally:
        mesh_mod.reset_mesh()
