"""Optimizer suite tests (reference test_adam_op.py / test_sgd_op.py /
test_momentum_op.py family + lr scheduler tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def quadratic_problem():
    w = nn.Parameter(np.array([5.0, -3.0], dtype="float32"))
    return w


def run_steps(opt, w, n=50):
    for _ in range(n):
        loss = (w * w).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return w


@pytest.mark.parametrize("cls,kw", [
    (optimizer.SGD, dict(learning_rate=0.1)),
    (optimizer.Momentum, dict(learning_rate=0.05, momentum=0.9)),
    (optimizer.Adam, dict(learning_rate=0.2)),
    (optimizer.AdamW, dict(learning_rate=0.2)),
    (optimizer.Adamax, dict(learning_rate=0.3)),
    (optimizer.Adagrad, dict(learning_rate=1.0)),
    (optimizer.Adadelta, dict(learning_rate=10.0)),
    (optimizer.RMSProp, dict(learning_rate=0.1)),
    (optimizer.Lamb, dict(learning_rate=0.1)),
    (optimizer.Lars, dict(learning_rate=10.0)),
])
def test_optimizers_converge(cls, kw):
    w = quadratic_problem()
    opt = cls(parameters=[w], **kw)
    run_steps(opt, w, 60)
    # Adadelta's unit-correction makes early steps tiny by design; require
    # solid progress rather than full convergence for it.
    bound = 3.0 if cls is optimizer.Adadelta else 0.5
    assert np.abs(w.numpy()).max() < bound, f"{cls.__name__}: {w.numpy()}"


def test_adam_matches_reference_formula():
    w = nn.Parameter(np.array([1.0], dtype="float32"))
    opt = optimizer.Adam(learning_rate=0.1, parameters=[w],
                         beta1=0.9, beta2=0.99, epsilon=1e-8)
    g = np.array([0.5], dtype="float32")
    loss = (w * paddle.to_tensor(g)).sum()
    loss.backward()
    opt.step()
    m = 0.1 * g
    v = 0.01 * g * g
    step = 0.1 * np.sqrt(1 - 0.99) / (1 - 0.9)
    expected = 1.0 - step * m / (np.sqrt(v) + 1e-8)
    np.testing.assert_allclose(w.numpy(), expected, rtol=1e-5)


def test_weight_decay_coupled():
    w = nn.Parameter(np.array([1.0], dtype="float32"))
    opt = optimizer.SGD(learning_rate=0.1, parameters=[w], weight_decay=0.1)
    (w * 0.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.1 * 0.1], rtol=1e-6)


def test_adamw_decoupled_decay():
    w = nn.Parameter(np.array([1.0], dtype="float32"))
    opt = optimizer.AdamW(learning_rate=0.1, parameters=[w], weight_decay=0.5)
    (w * 0.0).sum().backward()
    opt.step()
    # grad==0: adam update is 0, only decoupled decay applies
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.1 * 0.5], rtol=1e-5)


def test_grad_clip_global_norm():
    w1 = nn.Parameter(np.array([3.0], dtype="float32"))
    w2 = nn.Parameter(np.array([4.0], dtype="float32"))
    clip = optimizer.ClipGradByGlobalNorm(1.0)
    opt = optimizer.SGD(learning_rate=1.0, parameters=[w1, w2],
                        grad_clip=clip)
    (w1 * 3.0 + w2 * 4.0).backward()  # grads (3, 4), global norm 5
    opt.step()
    np.testing.assert_allclose(w1.numpy(), [3.0 - 3.0 / 5.0], rtol=1e-5)
    np.testing.assert_allclose(w2.numpy(), [4.0 - 4.0 / 5.0], rtol=1e-5)


def test_lr_scheduler_step_decay():
    sched = optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
    w = quadratic_problem()
    opt = optimizer.SGD(learning_rate=sched, parameters=[w])
    assert opt.get_lr() == 0.1
    sched.step(); sched.step()
    assert abs(opt.get_lr() - 0.05) < 1e-9


def test_lr_schedules_values():
    lr = optimizer.lr.PiecewiseDecay([3, 6], [0.1, 0.01, 0.001])
    vals = []
    for _ in range(8):
        vals.append(lr())
        lr.step()
    assert vals[0] == 0.1 and vals[4] == 0.01 and vals[7] == 0.001

    warm = optimizer.lr.LinearWarmup(0.1, warmup_steps=5, start_lr=0.0,
                                     end_lr=0.1)
    v0 = warm()
    for _ in range(6):
        warm.step()
    assert v0 == 0.0 and abs(warm() - 0.1) < 1e-9

    cos = optimizer.lr.CosineAnnealingDecay(0.1, T_max=10)
    assert abs(cos() - 0.1) < 1e-9
    for _ in range(10):
        cos.step()
    assert cos() < 1e-9


def test_optimizer_state_roundtrip(tmp_path):
    w = quadratic_problem()
    opt = optimizer.Adam(learning_rate=0.1, parameters=[w])
    run_steps(opt, w, 3)
    sd = opt.state_dict()
    path = str(tmp_path / "opt.pdopt")
    paddle.save(sd, path)

    w2 = quadratic_problem()
    opt2 = optimizer.Adam(learning_rate=0.1, parameters=[w2])
    opt2.set_state_dict(paddle.load(path))
    assert opt2._step_count == 3
    key = [k for k in opt2._slots][0]
    np.testing.assert_allclose(
        np.asarray(opt2._slots[key]["moment1"]),
        np.asarray(opt._slots[key]["moment1"]), rtol=1e-6)


def test_minimize_api():
    w = quadratic_problem()
    opt = optimizer.SGD(learning_rate=0.1, parameters=[w])
    loss = (w * w).sum()
    opt.minimize(loss)
    assert np.abs(w.numpy()).max() < 5.0


def test_training_loop_linear_model():
    paddle.seed(0)
    net = nn.Linear(3, 1)
    opt = optimizer.Adam(learning_rate=0.05, parameters=net.parameters())
    X = paddle.randn([64, 3])
    true_w = np.array([[1.0], [2.0], [-1.0]], dtype="float32")
    y = paddle.to_tensor(X.numpy() @ true_w + 0.5)
    loss_fn = nn.MSELoss()
    first = None
    for i in range(150):
        loss = loss_fn(net(X), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if first is None:
            first = loss.item()
    assert loss.item() < first * 0.01
    np.testing.assert_allclose(net.weight.numpy(), true_w, atol=0.1)


def test_ftrl_converges_and_sparsifies():
    paddle.seed(0)
    np.random.seed(0)
    X = np.random.rand(64, 8).astype("float32")
    w_true = np.zeros((8, 1), "float32")
    w_true[:3] = [[1.0], [-2.0], [0.5]]  # sparse ground truth
    Y = X @ w_true
    lin = nn.Linear(8, 1)
    opt = optimizer.Ftrl(learning_rate=0.5, l1=0.01,
                         parameters=lin.parameters())
    losses = []
    for _ in range(150):
        loss = ((lin(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])


def test_dpsgd_noisy_but_trains():
    paddle.seed(0)
    np.random.seed(1)
    X = np.random.rand(64, 4).astype("float32")
    Y = X @ np.ones((4, 1), "float32")
    lin = nn.Linear(4, 1)
    opt = optimizer.Dpsgd(learning_rate=0.05, clip=5.0, batch_size=64.0,
                          sigma=0.5, parameters=lin.parameters())
    losses = []
    for _ in range(80):
        loss = ((lin(paddle.to_tensor(X)) - paddle.to_tensor(Y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5  # noisy, but descending
