"""Round-4 op widening batch 3: deformable conv, SyncBatchNorm convert,
set_value, reference-v1 alias names."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, ops


def T(x, dtype="float32"):
    return paddle.to_tensor(np.asarray(x, dtype))


def test_deform_conv2d_zero_offsets_equals_conv2d():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 6, 6).astype("float32")
    w = rng.randn(5, 4, 3, 3).astype("float32")
    off = np.zeros((2, 2 * 9, 6, 6), "float32")
    out = ops.deform_conv2d(T(x), T(off), T(w), padding=1)
    ref = ops.conv2d(T(x), T(w), padding=1)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)


def test_deform_conv2d_integer_offset_shifts_sampling():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 6, 6).astype("float32")
    w = rng.randn(3, 2, 3, 3).astype("float32")
    off = np.zeros((1, 18, 6, 6), "float32")
    off[:, 1::2] = 1.0                       # +1 in x for every tap
    out = ops.deform_conv2d(T(x), T(off), T(w), padding=1)
    xs = np.zeros_like(x)
    xs[..., :-1] = x[..., 1:]
    ref = ops.conv2d(T(xs), T(w), padding=1)
    np.testing.assert_allclose(out.numpy()[..., 1:-1, 1:-1],
                               ref.numpy()[..., 1:-1, 1:-1], atol=1e-5)


def test_deform_conv2d_mask_modulates():
    rng = np.random.RandomState(2)
    x = rng.randn(1, 2, 4, 4).astype("float32")
    w = rng.randn(2, 2, 3, 3).astype("float32")
    off = np.zeros((1, 18, 4, 4), "float32")
    m0 = np.zeros((1, 9, 4, 4), "float32")   # all taps masked -> zeros
    out = ops.deform_conv2d(T(x), T(off), T(w), padding=1, mask=T(m0))
    np.testing.assert_allclose(out.numpy(), 0.0, atol=1e-6)
    m1 = np.ones((1, 9, 4, 4), "float32")
    out1 = ops.deform_conv2d(T(x), T(off), T(w), padding=1, mask=T(m1))
    ref = ops.conv2d(T(x), T(w), padding=1)
    np.testing.assert_allclose(out1.numpy(), ref.numpy(), atol=1e-5)


def test_deform_conv2d_differentiable():
    rng = np.random.RandomState(3)
    x = T(rng.randn(1, 2, 4, 4).astype("float32"))
    x.stop_gradient = False
    off = T(rng.randn(1, 18, 4, 4).astype("float32") * 0.3)
    off.stop_gradient = False
    w = T(rng.randn(2, 2, 3, 3).astype("float32"))
    out = ops.deform_conv2d(x, off, w, padding=1)
    out.sum().backward()
    assert np.isfinite(np.asarray(x.grad._value)).all()
    assert np.abs(np.asarray(off.grad._value)).sum() > 0


def test_sync_batchnorm_convert_and_global_stats():
    net = nn.Sequential(nn.Conv2D(3, 4, 3), nn.BatchNorm2D(4))
    net2 = nn.SyncBatchNorm.convert_sync_batchnorm(net)
    bns = [m for _, m in net2.named_sublayers()
           if isinstance(m, nn.SyncBatchNorm)]
    assert len(bns) == 1
    # under plain eager (no mesh region) it behaves like BatchNorm
    x = T(np.random.RandomState(4).randn(2, 3, 8, 8))
    y = net2(x)
    assert np.isfinite(y.numpy()).all()


def test_set_value_and_alias_names():
    x = T(np.zeros((3, 4)))
    out = ops.set_value(x, 7.0)
    assert (out.numpy() == 7).all()
    out = ops.set_value(x, 5.0, item=(slice(0, 2), slice(1, 3)))
    assert out.numpy()[0, 1] == 5 and out.numpy()[2, 3] == 0
    from paddle_tpu.ops._dispatch import OP_REGISTRY
    for name in ("kldiv_loss", "bce_loss", "warpctc", "lrn", "pad2d",
                 "pad3d", "set_value", "deform_conv2d", "deformable_conv"):
        assert name in OP_REGISTRY, name
    # alias correctness spot-check
    a = T(np.random.RandomState(5).rand(2, 3) + 0.1)
    b = T(np.random.RandomState(6).rand(2, 3) + 0.1)
    np.testing.assert_allclose(ops.lrn(T(np.ones((1, 2, 3, 3)))).numpy(),
                               ops.local_response_norm(
                                   T(np.ones((1, 2, 3, 3))), 5).numpy())


def test_pad2d_pad3d():
    x = T(np.ones((1, 1, 2, 2)))
    out = ops.pad2d(x, [1, 0, 2, 0])         # top=1 left=2
    assert out.shape == (1, 1, 3, 4)
    assert out.numpy()[0, 0, 0, 2] == 0 and out.numpy()[0, 0, 1, 2] == 1
    x3 = T(np.ones((1, 1, 2, 2, 2)))
    out = ops.pad3d(x3, [1, 1, 0, 0, 0, 0])
    assert out.shape == (1, 1, 4, 2, 2)
