"""Async pipelined executor hot loop (static/pipeline_runner.py):
serial vs pipelined vs scan-fused bitwise parity (params, optimizer
slots, AMP loss-scale state, fetches), in-flight failure surfacing with
the step index named, the uid-keyed LRU program cache, and the feed
fast path. See docs/async_executor.md."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer, static
from paddle_tpu.core import monitor
from paddle_tpu.static import (FetchHandle, PipelineRunner,
                               PipelineStepError)


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _build(name, amp=False):
    """Small train program: 2-layer MLP + mse, Adam (momentum slots);
    optionally fp16 dynamic-loss-scaling AMP (scale/good/bad state rides
    the compiled step)."""
    paddle.seed(0)
    prog = static.Program(name)
    with static.program_guard(prog):
        x = static.data("x", [-1, 4], "float32")
        y = static.data("y", [-1, 1], "float32")
        h = ops.relu(nn.Linear(4, 8)(x))
        loss = ops.mse_loss(nn.Linear(8, 1)(h), y)
        opt = optimizer.Adam(learning_rate=0.05)
        if amp:
            opt = static.amp.decorate(opt, level="O1", dtype="float16",
                                      init_loss_scaling=2.0 ** 8,
                                      incr_every_n_steps=3)
        opt.minimize(loss)
    return prog, loss, opt


def _feeds(n, batch=8):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(batch, 4).astype("float32"),
             "y": rng.rand(batch, 1).astype("float32")}
            for _ in range(n)]


def _amp_state(prog):
    scope = static.global_scope()
    return {k.split("@")[0]: np.asarray(scope.get(k))
            for k in scope.var_names()
            if "@" in k and k.rsplit("#", 1)[-1] == str(prog.uid)}


def _slot_arrays(opt):
    # amp.decorate's wrapper __getattr__-delegates _slots to the inner
    # opt; insertion order == param creation order, stable across builds
    # (the NAMES differ per build: each program mints fresh params)
    return [np.asarray(v) for _, s in opt._slots.items()
            for _, v in sorted(s.items())]


def _run_serial(n, amp=False):
    prog, loss, opt = _build(f"serial_amp{amp}", amp=amp)
    exe = static.Executor()
    paddle.seed(123)
    vals = [np.asarray(exe.run(prog, feed=f, fetch_list=[loss])[0])
            for f in _feeds(n)]
    params = [np.asarray(static.global_scope().get(n_))
              for n_ in prog.persist_ids]  # creation order, build-stable
    return vals, params, _slot_arrays(opt), _amp_state(prog)


def _run_pipelined(n, inflight, scan, amp=False):
    prog, loss, opt = _build(f"pipe{inflight}_{scan}_amp{amp}", amp=amp)
    exe = static.Executor()
    paddle.seed(123)
    with PipelineRunner(exe, prog, fetch_list=[loss],
                        max_inflight=inflight, scan_steps=scan) as r:
        handles = [h[0] for h in r.run(iter(_feeds(n)))]
        vals = [h.numpy() for h in handles]
    params = [np.asarray(static.global_scope().get(n_))
              for n_ in prog.persist_ids]  # creation order, build-stable
    return vals, params, _slot_arrays(opt), _amp_state(prog)


def _assert_bitwise(a, b, what):
    vals_a, params_a, slots_a, amp_a = a
    vals_b, params_b, slots_b, amp_b = b
    for i, (va, vb) in enumerate(zip(vals_a, vals_b)):
        np.testing.assert_array_equal(va, vb,
                                      err_msg=f"{what}: fetch step {i}")
    assert len(params_a) == len(params_b)
    for i, (pa, pb) in enumerate(zip(params_a, params_b)):
        np.testing.assert_array_equal(pa, pb, err_msg=f"{what}: param {i}")
    assert len(slots_a) == len(slots_b) and len(slots_a) > 0
    for sa, sb in zip(slots_a, slots_b):
        np.testing.assert_array_equal(sa, sb, err_msg=f"{what}: slots")
    assert sorted(amp_a) == sorted(amp_b)
    for k in amp_a:
        np.testing.assert_array_equal(amp_a[k], amp_b[k],
                                      err_msg=f"{what}: amp {k}")


@pytest.mark.parametrize("inflight", [1, 2, 4])
def test_pipelined_bitwise_equals_serial(inflight):
    serial = _run_serial(7)
    pipe = _run_pipelined(7, inflight, 0)
    _assert_bitwise(serial, pipe, f"inflight={inflight}")


@pytest.mark.parametrize("scan_k", [2, 3])
def test_scan_fused_bitwise_equals_serial(scan_k):
    # 7 steps at K=3 -> 2 megasteps + 1 unfused remainder
    serial = _run_serial(7)
    pipe = _run_pipelined(7, 2, scan_k)
    _assert_bitwise(serial, pipe, f"scan_k={scan_k}")
    assert monitor.stat_get("executor/scan_megasteps") > 0


def test_pipelined_amp_loss_scale_state_bitwise():
    # fp16 dynamic loss scaling: _amp_{loss_scale,good,bad} state rides
    # the carry; incr_every_n_steps=3 over 7 clean steps moves it
    serial = _run_serial(7, amp=True)
    assert serial[3], "amp state must exist for this test to mean anything"
    _assert_bitwise(serial, _run_pipelined(7, 2, 0, amp=True),
                    "amp inflight=2")
    _assert_bitwise(serial, _run_pipelined(7, 2, 3, amp=True),
                    "amp scan_k=3")


def test_scan_handles_shape_change_unfused():
    # feed shapes break mid-stream: the prefetcher must run the odd
    # batches unfused and stay bitwise-correct
    feeds = _feeds(4) + _feeds(3, batch=5) + _feeds(2)
    prog, loss, _ = _build("shape_serial")
    exe = static.Executor()
    paddle.seed(123)
    serial = [np.asarray(exe.run(prog, feed=f, fetch_list=[loss])[0])
              for f in feeds]
    prog2, loss2, _ = _build("shape_scan")
    exe2 = static.Executor()
    paddle.seed(123)
    with PipelineRunner(exe2, prog2, fetch_list=[loss2], max_inflight=2,
                        scan_steps=2) as r:
        vals = [h[0].numpy() for h in r.run(iter(feeds))]
    for a, b in zip(serial, vals):
        np.testing.assert_array_equal(a, b)


def test_inflight_failure_surfaces_at_next_materialization():
    prog, loss, _ = _build("chaos")
    exe = static.Executor()
    runner = PipelineRunner(exe, prog, fetch_list=[loss], max_inflight=4)
    feeds = _feeds(6)
    h0 = runner.submit(feeds[0])[0]  # compiles the entry
    entry = runner._entry
    orig = entry.jitted
    calls = {"n": 0}

    def bomb(*a, **k):
        calls["n"] += 1
        if calls["n"] == 2:  # step index 2 overall (0 ran unpatched)
            raise RuntimeError("injected chaos")
        return orig(*a, **k)

    entry.jitted = bomb
    try:
        h1 = runner.submit(feeds[1])[0]
        h2 = runner.submit(feeds[2])[0]   # fails in-flight, NOT raised here
        h3 = runner.submit(feeds[3])[0]   # pipeline broken: skipped
        # earlier steps still materialize fine
        assert float(h0.numpy()) > 0 and float(h1.numpy()) > 0
        # the failure surfaces at the next materialization, naming step 2
        with pytest.raises(PipelineStepError, match="step 2"):
            h2.numpy()
        # ... and a LATER handle still names the FIRST failing step
        with pytest.raises(PipelineStepError, match="step 2"):
            h3.numpy()
        with pytest.raises(PipelineStepError, match="step 2") as ei:
            runner.sync()
        assert ei.value.step_index == 2
    finally:
        entry.jitted = orig


def test_async_xla_failure_names_step():
    """Chaos-adjacent: the failure happens INSIDE the computation (host
    callback raising for one specific step's `t`), not in dispatch
    bookkeeping — it must still surface as PipelineStepError naming the
    failing step at a materialization boundary."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback

    prog, loss, _ = _build("chaos_xla")
    exe = static.Executor()
    runner = PipelineRunner(exe, prog, fetch_list=[loss], max_inflight=8)
    feeds = _feeds(5)
    runner.submit(feeds[0])
    entry = runner._entry
    orig_step, orig_jit = entry.step_fn, entry.jitted

    def host_check(t):
        if int(t) == 3:  # optimizer tick t==3 <-> pipeline step index 2
            raise RuntimeError("xla chaos at t=3")
        return np.float32(0)

    def wrapped(feed_tuple, scope_vals, slots, lr, t, key):
        probe = io_callback(host_check,
                            jax.ShapeDtypeStruct((), jnp.float32), t,
                            ordered=True)
        fetches, new_scope, new_slots = orig_step(
            feed_tuple, scope_vals, slots, lr, t, key)
        return tuple(f + probe.astype(f.dtype) for f in fetches), \
            new_scope, new_slots

    entry.jitted = jax.jit(wrapped, donate_argnums=entry.donate)
    try:
        for f in feeds[1:]:
            runner.submit(f)
        with pytest.raises(PipelineStepError, match="step 2"):
            runner.sync()
    finally:
        entry.step_fn, entry.jitted = orig_step, orig_jit


def test_executor_cache_uid_key_and_lru_bound():
    saved = paddle.get_flags(["FLAGS_executor_cache_size"])
    monitor.reset("executor/cache_evictions")
    paddle.set_flags({"FLAGS_executor_cache_size": 2})
    try:
        exe = static.Executor()
        progs = []
        for i in range(3):
            prog, loss, _ = _build(f"lru{i}")
            progs.append((prog, loss))
            exe.run(prog, feed=_feeds(1)[0], fetch_list=[loss])
        assert len(exe._cache) == 2
        assert monitor.stat_get("executor/cache_evictions") >= 1
        # keys carry program.uid, never id(program) — id reuse after GC
        # must not resolve to a stale entry
        assert all(k[0] == p.uid for k, (p, _) in
                   zip(list(exe._cache), progs[1:]))
        # evicted program recompiles instead of stale-hitting
        before = monitor.stat_get("executor/lowerings")
        exe.run(progs[0][0], feed=_feeds(1)[0], fetch_list=[progs[0][1]])
        assert monitor.stat_get("executor/lowerings") == before + 1
    finally:
        paddle.set_flags(saved)


def test_feed_conversion_fast_path():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.static.executor import _convert_feed
    aval = jax.ShapeDtypeStruct((4,), jnp.float32)
    dev = jnp.ones((4,), jnp.float32)
    assert _convert_feed(dev, aval) is dev  # no host round trip
    out = _convert_feed(np.ones(4, np.float64), aval)
    assert isinstance(out, jax.Array) and out.dtype == jnp.float32
    wrong = jnp.ones((4,), jnp.int32)
    assert _convert_feed(wrong, aval).dtype == jnp.float32


def test_run_return_handles():
    prog, loss, _ = _build("handles")
    exe = static.Executor()
    f = _feeds(1)[0]
    (h,) = exe.run(prog, feed=f, fetch_list=[loss], return_handles=True)
    assert isinstance(h, FetchHandle)
    v = np.asarray(h)  # __array__ protocol
    prog2, loss2, _ = _build("handles2")
    exe2 = static.Executor()
    (ref,) = exe2.run(prog2, feed=f, fetch_list=[loss2])
    np.testing.assert_array_equal(v, np.asarray(ref))


def test_pipeline_gauges_published():
    _run_pipelined(5, 2, 0)
    assert monitor.stat_get("executor/inflight_depth") >= 1
    assert monitor.stat_get("executor/step_wall_ms") > 0
    assert monitor.stat_get("executor/host_overhead_ms") >= 0


def test_train_from_dataset_scan_fused_via_exec_strategy(capsys):
    class _DS:
        def batches(self):
            yield from _feeds(6)

    prog, loss, _ = _build("tfd_scan")
    es = static.ExecutionStrategy()
    es.scan_fuse_steps = 3
    cp = static.CompiledProgram(prog, exec_strategy=es)
    exe = static.Executor()
    before = monitor.stat_get("executor/scan_megasteps")
    exe.train_from_dataset(cp, _DS(), fetch_list=[loss], print_period=2)
    assert monitor.stat_get("executor/scan_megasteps") == before + 2
    out = capsys.readouterr().out
    assert "batch 2:" in out and "batch 6:" in out


def test_hapi_fit_window_defers_materialization():
    """The async window must actually DELAY loss materialization (a
    bitwise test can't see this): a non-boundary step's loss is read only
    after later steps were submitted (window bound or log_freq drain)."""
    paddle.disable_static()
    from paddle_tpu.hapi import Model

    log = []

    class _LazyLoss:
        def __init__(self, i):
            self.i = i

        def __array__(self, dtype=None, copy=None):
            log.append(("mat", self.i))
            return np.zeros((), "float32")

    paddle.seed(0)
    net = nn.Linear(4, 1)
    m = Model(net)
    m.prepare(optimizer.SGD(learning_rate=0.1,
                            parameters=net.parameters()),
              loss=nn.MSELoss())
    counter = {"i": 0}

    def fake_train_batch(inputs, labels, update=True):
        i = counter["i"]
        counter["i"] += 1
        log.append(("submit", i))
        return _LazyLoss(i), []

    m._engine.train_batch = fake_train_batch
    saved = paddle.get_flags(["FLAGS_executor_max_inflight"])
    paddle.set_flags({"FLAGS_executor_max_inflight": 2})
    try:
        batches = [(np.zeros((2, 4), "float32"), np.zeros((2, 1),
                                                          "float32"))] * 8
        m.fit(batches, epochs=1, log_freq=4, verbose=0)
    finally:
        paddle.set_flags(saved)
    # every loss materializes exactly once, in order
    mats = [i for kind, i in log if kind == "mat"]
    assert mats == list(range(8)), mats
    # step 1's loss is NOT read in step 1's iteration: step 2 (and 3) are
    # submitted first, then the log_freq=4 boundary drains 1..3
    assert log.index(("submit", 2)) < log.index(("mat", 1)), log
    assert log.index(("submit", 3)) < log.index(("mat", 1)), log


def test_hapi_fit_async_matches_sync():
    """Model.fit's async loss window (drained at log_freq boundaries)
    must not change training: final weights bitwise-equal to the
    synchronous per-step loop."""
    paddle.disable_static()
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import Dataset

    class _Reg(Dataset):
        def __init__(self):
            rng = np.random.RandomState(3)
            self.x = rng.rand(32, 4).astype("float32")
            self.y = rng.rand(32, 1).astype("float32")

        def __len__(self):
            return 32

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    def fit(inflight):
        saved = paddle.get_flags(["FLAGS_executor_max_inflight"])
        paddle.set_flags({"FLAGS_executor_max_inflight": inflight})
        try:
            paddle.seed(0)
            net = nn.Linear(4, 1)
            m = Model(net)
            m.prepare(optimizer.Adam(learning_rate=0.05,
                                     parameters=net.parameters()),
                      loss=nn.MSELoss())
            m.fit(_Reg(), batch_size=8, epochs=2, shuffle=False,
                  log_freq=3, verbose=0)
            return [np.asarray(p) for p in net.parameters()]
        finally:
            paddle.set_flags(saved)

    sync_w = fit(0)
    async_w = fit(2)
    for a, b in zip(sync_w, async_w):
        np.testing.assert_array_equal(a, b)
