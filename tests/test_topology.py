"""Two-tier topology (ISSUE 16): the mesh link-tier grammar, the
hierarchical all-reduce's numerics + lowering, and nested-mesh axis
plumbing (`mesh_axis_size` / `MeshGuard` on a {pod, dp, tp} dryrun
mesh). The analyzer/planner halves live in test_spmd_analyzer.py /
test_spmd_planner.py; this file covers the EXECUTION half.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddle_tpu.core import flags as flags_mod
from paddle_tpu.distributed import collective
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.collective import ReduceOp

NESTED = {"pod": {"size": 2, "tier": "dcn"}, "dp": 4}


@pytest.fixture
def pod_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    m = mesh_mod.init_mesh(NESTED, name="default")
    yield m
    mesh_mod.init_mesh({"dp": 8})


# ---------------------------------------------------------------------------
# the {axis: {"size", "tier", "gbps"}} mesh grammar
# ---------------------------------------------------------------------------

def test_axis_grammar_sizes_and_tiers():
    shape = {"pod": {"size": 2, "tier": "dcn"}, "dp": 2,
             "tp": {"size": 2, "gbps": 45.0}}
    assert mesh_mod.axis_sizes(shape) == {"pod": 2, "dp": 2, "tp": 2}
    tiers = mesh_mod.axis_tiers(shape)
    assert tiers["pod"]["tier"] == "dcn"
    assert tiers["pod"]["gbps"] == flags_mod.flag("FLAGS_topology_dcn_gbps")
    assert tiers["dp"]["tier"] == "ici"  # plain int = fast default
    assert tiers["tp"] == {"tier": "ici", "gbps": 45.0}  # explicit override
    with pytest.raises(ValueError):
        mesh_mod.axis_tiers({"pod": {"size": 2, "tier": "carrier-pigeon"}})


def test_init_mesh_carries_link_tiers(pod_mesh):
    tiers = mesh_mod.axis_tiers(pod_mesh)
    assert tiers["pod"]["tier"] == "dcn" and tiers["dp"]["tier"] == "ici"
    assert tuple(pod_mesh.axis_names) == ("pod", "dp")
    assert pod_mesh.shape["pod"] == 2 and pod_mesh.shape["dp"] == 4
    # re-initing the same device set WITHOUT tiers must not leak the old
    # annotation through jax's Mesh interning
    flat = mesh_mod.init_mesh({"pod": 2, "dp": 4}, name="default")
    assert all(t == {"tier": "ici",
                     "gbps": flags_mod.flag("FLAGS_topology_ici_gbps")}
               for t in mesh_mod.axis_tiers(flat).values())
    mesh_mod.init_mesh(NESTED, name="default")


# ---------------------------------------------------------------------------
# hierarchical_all_reduce: numerics == flat nested reduction
# ---------------------------------------------------------------------------

def _flat_then_hier(x, op, pod_mesh, shape_spec=P(("pod", "dp"))):
    def body(xl):
        flat = collective.all_reduce(
            collective.all_reduce(xl + 0.0, op=op, group="dp"),
            op=op, group="pod")
        hier = collective.hierarchical_all_reduce(
            xl + 0.0, op=op, inner_axis="dp", outer_axis="pod")
        return flat, hier

    return mesh_mod.shard_map(body, mesh=pod_mesh, in_specs=shape_spec,
                              out_specs=shape_spec)(x)


def test_hierarchical_all_reduce_matches_flat_sum(pod_mesh):
    x = jnp.arange(16.0).reshape(8, 2)
    flat, hier = _flat_then_hier(x, ReduceOp.SUM, pod_mesh)
    np.testing.assert_array_equal(np.asarray(flat), np.asarray(hier))


def test_hierarchical_all_reduce_avg_and_fallback_ops(pod_mesh):
    x = jnp.arange(8.0).reshape(8, 1) * 0.5
    flat, hier = _flat_then_hier(x, ReduceOp.AVG, pod_mesh)
    np.testing.assert_allclose(np.asarray(flat), np.asarray(hier),
                               rtol=1e-6)
    # MAX has no reduce-scatter decomposition: the nested fallback must
    # still give the flat answer
    flat, hier = _flat_then_hier(x, ReduceOp.MAX, pod_mesh)
    np.testing.assert_array_equal(np.asarray(flat), np.asarray(hier))


def test_hierarchical_all_reduce_pads_non_divisible_payload(pod_mesh):
    # 3 elements per device: not divisible by the inner dp=4 ring, so
    # the reduce-scatter path must pad and unpad losslessly
    x = jnp.arange(24.0).reshape(8, 3)
    flat, hier = _flat_then_hier(x, ReduceOp.SUM, pod_mesh)
    np.testing.assert_array_equal(np.asarray(flat), np.asarray(hier))


def test_hierarchical_lowering_is_three_phase(pod_mesh):
    """The decomposition must actually lower to reduce-scatter +
    outer-axis psum + all-gather — not a flat 8-way all-reduce."""
    def body(xl):
        return collective.hierarchical_all_reduce(
            xl + 0.0, op=ReduceOp.SUM, inner_axis="dp",
            outer_axis="pod")

    fn = mesh_mod.shard_map(body, mesh=pod_mesh,
                            in_specs=P(("pod", "dp")),
                            out_specs=P(("pod", "dp")))
    jaxpr = str(jax.make_jaxpr(fn)(jnp.arange(16.0).reshape(8, 2)))
    assert "reduce_scatter" in jaxpr  # lax.psum_scatter's primitive
    assert "all_gather" in jaxpr
    assert "psum" in jaxpr


# ---------------------------------------------------------------------------
# nested-mesh axis plumbing: mesh_axis_size / MeshGuard (satellite 3)
# ---------------------------------------------------------------------------

def test_mesh_axis_size_on_nested_dryrun_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    m = mesh_mod.init_mesh({"pod": {"size": 2, "tier": "dcn"},
                            "dp": 2, "tp": 2}, name="_topo_nested")
    try:
        # registry path (no trace context): every axis resolves
        assert mesh_mod.mesh_axis_size("pod", "_topo_nested") == 2
        assert mesh_mod.mesh_axis_size("dp", "_topo_nested") == 2
        assert mesh_mod.mesh_axis_size("tp", "_topo_nested") == 2
        assert mesh_mod.mesh_axis_size("nope", "_topo_nested") == 1

        # bound path: inside shard_map the trace's sizes win
        def body(xl):
            sizes = (mesh_mod.mesh_axis_size("pod"),
                     mesh_mod.mesh_axis_size("dp"),
                     mesh_mod.mesh_axis_size("tp"))
            assert sizes == (2, 2, 2)
            assert mesh_mod.in_spmd_region("pod")
            return xl

        mesh_mod.shard_map(body, mesh=m,
                           in_specs=P(("pod", "dp", "tp")),
                           out_specs=P(("pod", "dp", "tp")))(
            jnp.arange(8.0))
    finally:
        mesh_mod.reset_mesh("_topo_nested")


def test_meshguard_scopes_nested_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    m = mesh_mod.init_mesh({"pod": {"size": 2, "tier": "dcn"},
                            "dp": 2, "tp": 2}, name="_topo_guard")
    try:
        with mesh_mod.MeshGuard(m):
            sh = mesh_mod.named_sharding(P(("pod", "dp"), "tp"),
                                         name="_topo_guard")
            x = jax.device_put(jnp.zeros((4, 2)), sh)
            assert x.sharding.spec == P(("pod", "dp"), "tp")
        # tier annotation survives the guard round-trip
        assert mesh_mod.axis_tiers(m)["pod"]["tier"] == "dcn"
    finally:
        mesh_mod.reset_mesh("_topo_guard")
