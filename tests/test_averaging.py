"""EMA / ModelAverage / LookAhead (reference fluid/optimizer.py
ExponentialMovingAverage :4316, ModelAverage :4790, Lookahead :5700)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _fit_steps(net, opt, steps, ema=None, mavg=None):
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(16, 3).astype("float32"))
    w = rng.randn(3, 1).astype("float32")
    y = paddle.to_tensor(np.asarray(x._value) @ w)
    loss_fn = nn.MSELoss()
    for _ in range(steps):
        loss = loss_fn(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if ema is not None:
            ema.update()
        if mavg is not None:
            mavg.update()
    return float(loss.numpy())


def test_ema_apply_restore_roundtrip():
    paddle.seed(0)
    net = nn.Linear(3, 1, bias_attr=False)
    opt = optimizer.SGD(learning_rate=0.2, parameters=net.parameters())
    ema = optimizer.ExponentialMovingAverage(net, decay=0.5)
    _fit_steps(net, opt, 20, ema=ema)
    raw = np.asarray(net.weight._value).copy()
    with ema.average_weights():
        avg = np.asarray(net.weight._value).copy()
        assert not np.allclose(avg, raw)
    np.testing.assert_allclose(np.asarray(net.weight._value), raw)
    # EMA trails but tracks training: close to the trained weights
    assert np.abs(avg - raw).max() < 0.5
    st = ema.state_dict()
    ema2 = optimizer.ExponentialMovingAverage(net, decay=0.5)
    ema2.set_state_dict(st)
    with ema2.average_weights():
        np.testing.assert_allclose(np.asarray(net.weight._value), avg,
                                   rtol=1e-6)


def test_ema_high_decay_few_steps_unbiased():
    """decay=0.999, t=5: zero-init shadow + /(1-decay^t) correction must
    reconstruct ~the parameter scale, not over-scale it ~200x (the failure
    mode of a param-initialized shadow with the same correction)."""
    paddle.seed(3)
    net = nn.Linear(3, 1, bias_attr=False)
    opt = optimizer.SGD(learning_rate=0.0, parameters=net.parameters())
    ema = optimizer.ExponentialMovingAverage(net, decay=0.999)
    w0 = np.asarray(net.weight._value).copy()
    _fit_steps(net, opt, 5, ema=ema)  # lr=0 -> weights constant
    with ema.average_weights():
        avg = np.asarray(net.weight._value)
    # with constant weights, bias-corrected EMA == the weights exactly
    np.testing.assert_allclose(avg, w0, rtol=1e-4)


def test_model_average_window():
    paddle.seed(1)
    net = nn.Linear(3, 1, bias_attr=False)
    opt = optimizer.SGD(learning_rate=0.2, parameters=net.parameters())
    mavg = optimizer.ModelAverage(net, average_window_rate=1.0,
                                  min_average_window=2,
                                  max_average_window=4)
    _fit_steps(net, opt, 12, mavg=mavg)
    raw = np.asarray(net.weight._value).copy()
    with mavg.average_weights():
        avg = np.asarray(net.weight._value)
        assert np.isfinite(avg).all() and not np.allclose(avg, raw)
    np.testing.assert_allclose(np.asarray(net.weight._value), raw)


def test_lookahead_converges_and_blends():
    paddle.seed(2)
    net = nn.Linear(3, 1, bias_attr=False)
    inner = optimizer.SGD(learning_rate=0.3, parameters=net.parameters())
    opt = optimizer.LookAhead(inner, alpha=0.5, k=3)
    final = _fit_steps(net, opt, 30)
    assert final < 0.05, final
    assert opt._slow is not None
    # slow weights equal fast weights right after a sync step (30 % 3 == 0)
    np.testing.assert_allclose(np.asarray(opt._slow[0]),
                               np.asarray(net.weight._value), rtol=1e-6)
