"""Pipeline & expert-parallel planner (ISSUE 11 tentpole).

Golden: on the GPT workload with a {pp: 4} mesh the stage-cut search
must produce a zero-diagnostic 4-stage partition whose per-stage
analyzer FLOPs balance is within 10% of the brute-force optimum over
the same legal cut set, matching-or-beating the hand (equal-segments)
cut on the weighted objective; an ep-mesh MoE plan must place experts
on 'ep' with the all-to-all dispatch/combine wire priced in the
report.

Execution: `StagedPipelineRunner` runs the planned stage chunks as an
SPMD 1F1B/interleaved schedule on the 8-device virtual mesh — a
planned pp (and dp/pp) run trains to loss identical to the hand-tuned
stage assignment and to the non-pipelined sequential reference.
"""
import itertools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn, ops, static
from paddle_tpu.core import monitor
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.moe import MoELayer, switch_route
from paddle_tpu.distributed.pipeline import (bubble_fraction,
                                             schedule_collectives,
                                             schedule_ticks)
from paddle_tpu.static import spmd_planner
from paddle_tpu.static.pipeline_runner import StagedPipelineRunner
from paddle_tpu.static.spmd_planner import (PipelinePlan, ShardingPlan,
                                            legal_cut_points,
                                            plan_pipeline)
from paddle_tpu.text.models.gpt import GPT, GPTConfig


@pytest.fixture()
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _gpt_program(layers=4, hidden=64, heads=2, vocab=1024, batch=8,
                 seq=16):
    main = static.Program("pp_plan_gpt")
    with static.program_guard(main):
        ids = static.data("input_ids", [batch, seq], "int64")
        net = GPT(GPTConfig(vocab_size=vocab, hidden_size=hidden,
                            num_layers=layers, num_heads=heads,
                            intermediate_size=4 * hidden,
                            max_seq_len=max(seq, 8)))
        logits = net(ids)
    main._jit_fetch_vars = [logits]
    return main, net, logits


def _mlp_program(widths, batch=16, name="pp_mlp"):
    """A tanh-MLP stack (one Linear per layer, no bias), with the op
    boundary list of each layer start — the unit grid the staged
    runner executes. widths[i] is layer i's square width multiplier
    via an inner expansion (wider layers cost more flops)."""
    main = static.Program(name)
    lins = []
    with static.program_guard(main):
        x = static.data("x", [batch, 32], "float32")
        h = x
        for w in widths:
            lin = nn.Linear(32, 32, bias_attr=False) if w == 1 else None
            if lin is None:
                lin_a = nn.Linear(32, 32 * w, bias_attr=False)
                lin_b = nn.Linear(32 * w, 32, bias_attr=False)
                h = ops.tanh(lin_b(lin_a(h)))
                lins.append((lin_a, lin_b))
            else:
                h = ops.tanh(lin(h))
                lins.append(lin)
    main._jit_fetch_vars = [h]
    return main, lins


# ---------------------------------------------------------------------------
# cut legality
# ---------------------------------------------------------------------------

def test_legal_cut_points_are_single_tensor_frontiers(static_mode):
    main, _net, _ = _gpt_program(layers=4)
    cuts = legal_cut_points(main)
    assert cuts, "a 4-layer GPT must have legal cut boundaries"
    # every frontier is ONE hidden-shaped activation
    for c in cuts:
        assert c.aval is not None
    hidden = [c for c in cuts if tuple(c.aval.shape) == (8, 16, 64)]
    # at least one boundary per block transition
    assert len(hidden) >= 4
    # boundaries are strictly increasing op indices inside the program
    bs = [c.boundary for c in cuts]
    assert bs == sorted(bs) and bs[0] >= 1 and bs[-1] < len(main.ops)


# ---------------------------------------------------------------------------
# the golden stage cut: {pp: 4} GPT
# ---------------------------------------------------------------------------

def _brute_force_best_balance(program, plan):
    """Minimal max-stage-flops over ALL cut vectors from the plan's
    candidate boundary set (the optimum the golden bound references)."""
    from paddle_tpu.static.spmd_analyzer import analyze_flops
    per = analyze_flops(program)["per_op"]
    n_ops = len(program.ops)
    bounds = [c.boundary for c in plan.cut_points]
    best = float("inf")
    for cut in itertools.combinations(bounds, 3):
        edges = [0] + list(cut) + [n_ops]
        mx = max(sum(per[edges[k]:edges[k + 1]])
                 for k in range(len(edges) - 1))
        best = min(best, mx)
    return best


def test_pp4_gpt_golden_stage_cut(static_mode):
    main, net, _ = _gpt_program(layers=4)
    plan = plan_pipeline(main, {"pp": 4}, layer=net)
    assert isinstance(plan, PipelinePlan)
    assert plan.diagnostics == []
    assert len(plan.stages) == 4
    assert all(s.diagnostics == 0 for s in plan.stages)
    # stages tile the whole program
    assert plan.stages[0].op_range[0] == 0
    assert plan.stages[-1].op_range[1] == len(main.ops)
    for a, b in zip(plan.stages, plan.stages[1:]):
        assert a.op_range[1] == b.op_range[0]
    # compute balance within 10% of the brute-force optimum over the
    # same candidate set
    best = _brute_force_best_balance(main, plan)
    got = max(s.flops for s in plan.stages)
    assert got <= 1.10 * best, (got, best)
    # matches-or-beats the hand equal-segments cut on the objective
    assert plan.hand, "hand baseline must be priced"
    assert plan.objective <= plan.hand["objective"] + 1e-9
    # wire: ppermute of one hidden microbatch per tick
    assert plan.wire["kind"] == "ppermute"
    assert plan.wire["count"] == schedule_ticks(plan.num_micro, 4,
                                                "gpipe", 1)
    assert plan.frontier_bytes_per_tick > 0
    assert plan.bubble == pytest.approx(bubble_fraction(plan.num_micro,
                                                        4))
    # monitor gauges
    assert monitor.stat_get("spmd.pipeline_stages") == 4
    assert monitor.stat_get("spmd.pipeline_objective") \
        == pytest.approx(plan.objective)


def test_heterogeneous_stack_planner_beats_equal_cut(static_mode):
    """Uneven layer widths make the equal-segments hand cut genuinely
    suboptimal — the searched cut must be strictly better."""
    widths = [4, 4, 1, 1, 1, 1, 1, 1]
    main, _lins = _mlp_program(widths)
    plan = plan_pipeline(main, {"pp": 4}, num_micro=8)
    assert plan.diagnostics == []
    assert plan.objective < plan.hand["objective"]
    fl = [s.flops for s in plan.stages]
    hand_max = plan.hand["max_stage_flops"]
    assert max(fl) < hand_max


def test_per_stage_hbm_prices_op_ranges(static_mode):
    """Each stage's HBM comes from analyze_memory restricted to its op
    range: stage param bytes must partition the program's params and
    every stage peak must be BELOW the whole-program peak."""
    from paddle_tpu.static.shape_infer import analyze_memory
    main, net, _ = _gpt_program(layers=4)
    plan = plan_pipeline(main, {"pp": 4}, layer=net)
    full = analyze_memory(main)
    for s in plan.stages:
        assert 0 < s.hbm_peak < full["peak_bytes"]
        assert analyze_memory(main, op_range=s.op_range)["peak_bytes"] \
            == s.hbm_peak


def test_explicit_cuts_and_boundary_restriction(static_mode):
    main, _lins = _mlp_program([1] * 8)
    opl = len(main.ops) // 8
    bounds = [k * opl for k in range(1, 8)]
    plan = plan_pipeline(main, {"pp": 4}, num_micro=8, boundaries=bounds)
    assert plan.cuts == [2 * opl, 4 * opl, 6 * opl]  # homogeneous: equal
    assert plan.n_segments == 8
    assert plan.stage_segments() == [[0, 1], [2, 3], [4, 5], [6, 7]]
    # explicit pricing of a given (worse) cut vector
    skew = plan_pipeline(main, {"pp": 4}, num_micro=8,
                         boundaries=bounds, cuts=[opl, 2 * opl, 3 * opl])
    assert skew.cuts == [opl, 2 * opl, 3 * opl]
    assert skew.objective > plan.objective
    # an illegal requested cut is diagnosed, not silently dropped
    bad = plan_pipeline(main, {"pp": 4}, num_micro=8,
                        boundaries=bounds, cuts=[opl + 1, 2 * opl,
                                                 3 * opl])
    assert any("not a legal" in d for d in bad.diagnostics)


def test_interleaved_plan_assigns_round_robin(static_mode):
    main, _lins = _mlp_program([1] * 8)
    opl = len(main.ops) // 8
    bounds = [k * opl for k in range(1, 8)]
    plan = plan_pipeline(main, {"pp": 4}, num_micro=8, num_virtual=2,
                         boundaries=bounds)
    assert plan.schedule == "interleaved"
    assert len(plan.stages) == 8
    # global stage g = chunk g//n on rank g%n: rank 0 holds segs 0 and 4
    segs = plan.stage_segments()
    assert segs == [[k] for k in range(8)]
    assert plan.wire["count"] == schedule_ticks(8, 4, "interleaved", 2)
    assert plan.bubble == pytest.approx(
        bubble_fraction(8, 4, "interleaved", 2))


# ---------------------------------------------------------------------------
# MoE expert placement
# ---------------------------------------------------------------------------

def _moe_program(layers=4, hidden=16, experts=4, batch=4, seq=8):
    main = static.Program("pp_moe")
    names = {}
    with static.program_guard(main):
        x = static.data("x", [batch, seq, hidden], "float32")
        h = x
        for i in range(layers):
            lin = nn.Linear(hidden, hidden)
            moe = MoELayer(hidden, 2 * hidden, experts, axis="ep")
            h = ops.tanh(lin(h))
            h = moe(h)
            for suffix, p in (("fc.weight", lin.weight),
                              ("fc.bias", lin.bias),
                              ("moe.gate.weight", moe.gate.weight),
                              ("moe.w_up", moe.w_up),
                              ("moe.b_up", moe.b_up),
                              ("moe.w_down", moe.w_down),
                              ("moe.b_down", moe.b_down)):
                names[p.scope_name] = f"blocks.{i}.{suffix}"
    main._jit_fetch_vars = [h]
    return main, names


def test_ep_mesh_places_experts_with_priced_all_to_all(static_mode):
    main, names = _moe_program()
    plan = plan_pipeline(main, {"pp": 2, "ep": 2}, names=names)
    assert plan.diagnostics == []
    inner = plan.inner
    assert isinstance(inner, ShardingPlan)
    # expert stacks sharded over ep, dim 0
    assert inner.spec_for("blocks.0.moe.w_up", 3) == P("ep", None, None)
    assert inner.spec_for("blocks.3.moe.w_down", 3) \
        == P("ep", None, None)
    # 2 all-to-alls (dispatch + combine) per MoE layer, priced on ep
    a2a = [c for c in inner.report.collectives
           if c.kind == "all_to_all"]
    assert len(a2a) == 2 * 4
    assert all(c.axis == "ep" and c.bytes > 0 for c in a2a)
    assert plan.expert["axis"] == "ep"
    assert plan.expert["all_to_all_count"] == 8
    assert plan.expert["all_to_all_bytes"] == sum(c.bytes for c in a2a)


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual CPU mesh")
def test_planned_ep_specs_drive_expert_parallel_execution():
    """The planned expert placement EXECUTES: shard the global expert
    stacks with the plan's specs over the 8-way ep mesh and run the
    real MoELayer all-to-all path (the ep dryrun), matching the dense
    single-device forward."""
    paddle.enable_static()
    try:
        main, names = _moe_program(layers=1, hidden=8, experts=8)
        plan = plan_pipeline(main, {"ep": 8}, names=names)
    finally:
        paddle.disable_static()
    inner = plan.inner
    assert inner.spec_for("blocks.0.moe.w_up", 3) == P("ep", None, None)

    mesh = mesh_mod.init_mesh({"ep": 8}, name="default")
    paddle.seed(7)
    moe = MoELayer(d_model=8, d_hidden=16, num_experts=8, axis="ep")
    x = np.random.RandomState(7).randn(2, 4, 8).astype("float32")
    params, _ = moe.functional_state()
    rng = np.random.RandomState(8)
    globals_, specs = {}, {}
    for k, v in params.items():
        stack = next((s for s in ("w_up", "b_up", "w_down", "b_down")
                      if s in k), None)
        if stack is not None:
            shape = (8,) + tuple(v.shape[1:])
            globals_[k] = jnp.asarray(
                rng.randn(*shape).astype("float32") * 0.1)
            # the PLAN's spec for this stack, not a hand-written one
            specs[k] = inner.spec_for(f"blocks.0.moe.{stack}",
                                      len(shape))
        else:
            globals_[k] = v
            specs[k] = P()
    assert all(tuple(specs[k]) and tuple(specs[k])[0] == "ep"
               for k in specs if any(s in k for s in ("w_up", "w_down")))

    def spmd(p, xv):
        moe.load_functional_state(p)
        out = moe(paddle.Tensor(xv, _internal=True))
        return out._value

    out = mesh_mod.shard_map(spmd, mesh=mesh, in_specs=(specs, P()),
                             out_specs=P())(globals_, jnp.asarray(x))
    assert np.asarray(out).shape == (2, 4, 8)
    assert np.isfinite(np.asarray(out)).all()
    mesh_mod.init_mesh({"dp": 8})


def test_ep_conflicts_are_diagnosed(static_mode):
    """Disagreeing expert stacks and an expert axis that also shards
    the tokens must surface as reshard diagnostics, not silent drops."""
    from paddle_tpu.static import spmd_analyzer as spmd
    main, names = _moe_program(layers=1)
    inv = {v: k for k, v in names.items()}
    specs = {inv["blocks.0.moe.w_up"]: P("ep"),
             inv["blocks.0.moe.w_down"]: P()}
    rep = spmd.analyze_program(main, mesh={"ep": 2}, param_specs=specs)
    # w_up sharded, w_down replicated: legal (disagreement means two
    # DIFFERENT axes, not sharded-vs-replicated)
    assert rep.diagnostics == []
    specs2 = {inv["blocks.0.moe.w_up"]: P("ep"),
              inv["blocks.0.moe.w_down"]: P("other")}
    rep2 = spmd.analyze_program(main, mesh={"ep": 2, "other": 2},
                                param_specs=specs2)
    assert any(d.code == "reshard" for d in rep2.diagnostics)
    # expert axis colliding with token sharding
    rep3 = spmd.analyze_program(
        main, mesh={"ep": 2},
        param_specs={inv["blocks.0.moe.w_up"]: P("ep"),
                     inv["blocks.0.moe.b_up"]: P("ep"),
                     inv["blocks.0.moe.w_down"]: P("ep"),
                     inv["blocks.0.moe.b_down"]: P("ep")},
        data_specs={"x": P("ep")})
    assert any(d.code == "reshard" for d in rep3.diagnostics)


# ---------------------------------------------------------------------------
# satellites: moe overflow counter + degenerate schedule math
# ---------------------------------------------------------------------------

def test_moe_dropped_tokens_counter_bumps_on_overflow():
    before = monitor.stat_get("moe.dropped_tokens")
    # all 8 tokens route to expert 0 with capacity 2 -> 6 dropped
    logits = jnp.asarray(np.tile([10.0, -10.0], (8, 1)))
    dispatch, combine = switch_route(logits, 2, 2)
    assert monitor.stat_get("moe.dropped_tokens") == before + 6
    # the dropped rows really are zeroed out of dispatch
    assert float(jnp.sum(dispatch)) == 2.0
    # no overflow -> no bump
    mid = monitor.stat_get("moe.dropped_tokens")
    switch_route(jnp.asarray(np.tile([10.0, -10.0], (2, 1))), 2, 2)
    assert monitor.stat_get("moe.dropped_tokens") == mid


def test_schedule_math_degenerate_edges():
    # single stage: zero bubble, zero ppermute wire, M ticks
    assert bubble_fraction(8, 1) == 0.0
    assert schedule_ticks(8, 1) == 8
    assert schedule_collectives(8, 1, 4096)["total_bytes"] == 0
    assert schedule_collectives(8, 1, 4096)["count"] == 0
    # fewer microbatches than stages: still M+n-1 ticks, bubble < 1
    assert schedule_ticks(2, 4) == 5
    assert 0.0 < bubble_fraction(2, 4) < 1.0
    # zero microbatches: nothing scheduled, no division by zero
    assert schedule_ticks(0, 4) == 0
    assert bubble_fraction(0, 4) == 0.0
    assert bubble_fraction(0, 1) == 0.0
    # interleaved variant
    assert bubble_fraction(8, 4, "interleaved", 2) \
        == pytest.approx(3 / 19)
    assert schedule_ticks(8, 4, "interleaved", 2) == 19


# ---------------------------------------------------------------------------
# execution: the planned partition trains, identically to the hand one
# ---------------------------------------------------------------------------

L, D, B = 8, 32, 16


def _plan_mlp(pp, num_micro=8, num_virtual=1, cuts=None, mesh_extra=()):
    paddle.enable_static()
    try:
        main, _lins = _mlp_program([1] * L, batch=B,
                                   name=f"pp_exec_{pp}_{num_virtual}")
        opl = len(main.ops) // L
        bounds = [k * opl for k in range(1, L)]
        mesh = {"pp": pp}
        mesh.update(dict(mesh_extra))
        return plan_pipeline(
            main, mesh, num_micro=num_micro, num_virtual=num_virtual,
            boundaries=bounds,
            cuts=None if cuts is None else [c * opl for c in cuts])
    finally:
        paddle.disable_static()


def _train(plan, mesh, ws, x, y, steps=3, lr=0.1):
    runner = StagedPipelineRunner(
        plan, lambda h, w: jnp.tanh(h @ w),
        [jnp.asarray(w) for w in ws],
        lambda h, t: jnp.mean((h - t) ** 2), mesh=mesh,
        learning_rate=lr)
    losses = [float(runner.step(x, y)) for _ in range(steps)]
    return losses, runner.unit_params()


def _reference(ws, x, y, steps=3, lr=0.1):
    def loss_of(ww):
        h = jnp.asarray(x)
        for w in ww:
            h = jnp.tanh(h @ w)
        return jnp.mean((h - jnp.asarray(y)) ** 2)

    wl = [jnp.asarray(w) for w in ws]
    losses = []
    for _ in range(steps):
        loss, grads = jax.value_and_grad(loss_of)(wl)
        losses.append(float(loss))
        wl = [w - lr * g for w, g in zip(wl, grads)]
    return losses, wl


def _data(seed=0):
    rng = np.random.RandomState(seed)
    ws = [(rng.randn(D, D) / np.sqrt(D)).astype("float32")
          for _ in range(L)]
    x = rng.randn(B, D).astype("float32")
    y = rng.randn(B, D).astype("float32")
    return ws, x, y


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual CPU mesh")
def test_planned_pp_trains_identical_to_hand_cut():
    """The MULTICHIP acceptance: a planned {pp: 4} run trains to loss
    IDENTICAL to the hand-tuned equal-layers stage assignment, and both
    match the non-pipelined sequential reference."""
    ws, x, y = _data()
    plan = _plan_mlp(4)
    hand = _plan_mlp(4, cuts=[2, 4, 6])
    mesh = mesh_mod.init_mesh({"pp": 4}, name="_pp_exec",
                              devices=jax.devices()[:4])
    try:
        lp, wp = _train(plan, mesh, ws, x, y)
        lh, _wh = _train(hand, mesh, ws, x, y)
        lr, wr = _reference(ws, x, y)
        assert lp == lh  # planned == hand, bitwise
        np.testing.assert_allclose(lp, lr, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(wp[0]),
                                   np.asarray(wr[0]), rtol=1e-5,
                                   atol=1e-6)
    finally:
        mesh_mod.reset_mesh("_pp_exec")


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual CPU mesh")
def test_planned_dp_pp_trains_identical_to_hand_cut():
    """dp x pp: the microbatch dim shards over dp, stages over pp —
    planned and hand assignments land on the same loss as sequential."""
    ws, x, y = _data(1)
    plan = _plan_mlp(4, mesh_extra={"dp": 2})
    hand = _plan_mlp(4, cuts=[2, 4, 6], mesh_extra={"dp": 2})
    mesh = mesh_mod.init_mesh({"dp": 2, "pp": 4}, name="_dp_pp_exec",
                              devices=jax.devices()[:8])
    try:
        lp, _ = _train(plan, mesh, ws, x, y)
        lh, _ = _train(hand, mesh, ws, x, y)
        lr, _ = _reference(ws, x, y)
        assert lp == lh
        np.testing.assert_allclose(lp, lr, rtol=1e-5)
    finally:
        mesh_mod.reset_mesh("_dp_pp_exec")


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device virtual CPU mesh")
def test_planned_interleaved_1f1b_matches_sequential():
    """v=2 interleaved 1F1B (8 global stages on 4 ranks, round-robin)
    through the staged runner's bounded in-flight window."""
    ws, x, y = _data(2)
    plan = _plan_mlp(4, num_virtual=2)
    assert plan.schedule == "interleaved"
    mesh = mesh_mod.init_mesh({"pp": 4}, name="_il_exec",
                              devices=jax.devices()[:4])
    try:
        li, _ = _train(plan, mesh, ws, x, y)
        lr, _ = _reference(ws, x, y)
        np.testing.assert_allclose(li, lr, rtol=1e-5)
    finally:
        mesh_mod.reset_mesh("_il_exec")


def test_staged_runner_window_is_bounded():
    ws, x, y = _data(3)
    plan = _plan_mlp(2)
    mesh = mesh_mod.init_mesh({"pp": 2}, name="_win_exec",
                              devices=jax.devices()[:2])
    try:
        runner = StagedPipelineRunner(
            plan, lambda h, w: jnp.tanh(h @ w),
            [jnp.asarray(w) for w in ws],
            lambda h, t: jnp.mean((h - t) ** 2), mesh=mesh,
            max_inflight=2)
        handles = [runner.step(x, y) for _ in range(6)]
        runner.sync()
        assert runner.inflight_depth_peak <= 3
        vals = [float(h) for h in handles]
        assert all(np.isfinite(v) for v in vals)
        # losses decrease under SGD
        assert vals[-1] < vals[0]
    finally:
        mesh_mod.reset_mesh("_win_exec")


def test_staged_runner_validates_unit_count():
    ws, _x, _y = _data(4)
    plan = _plan_mlp(2)
    mesh = mesh_mod.init_mesh({"pp": 2}, name="_val_exec",
                              devices=jax.devices()[:2])
    try:
        with pytest.raises(ValueError, match="segments"):
            StagedPipelineRunner(
                plan, lambda h, w: jnp.tanh(h @ w),
                [jnp.asarray(w) for w in ws[:3]],
                lambda h, t: jnp.mean((h - t) ** 2), mesh=mesh)
    finally:
        mesh_mod.reset_mesh("_val_exec")


# ---------------------------------------------------------------------------
# strategy round-trip: planned stages resolve at Executor compile
# ---------------------------------------------------------------------------

def test_as_strategy_pipeline_roundtrip_resolves_stages(static_mode):
    """Planned strategy -> DistributedOptimizer.minimize -> Executor
    `_prepare` resolves the stage assignment onto the Program BEFORE
    the VERIFY_SPMD hook (mirrors the PR 10 auto_shard resolution
    test)."""
    from paddle_tpu import optimizer as opt_mod
    from paddle_tpu.distributed import fleet
    from paddle_tpu.static import spmd_analyzer

    main, _lins = _mlp_program([1] * 4, batch=8, name="strategy_pp")
    opl = len(main.ops) // 4
    plan = plan_pipeline(main, {"pp": 2}, num_micro=4,
                         boundaries=[k * opl for k in range(1, 4)])
    assert plan.inner is not None and plan.inner.pipeline is plan

    strategy = plan.inner.as_strategy()
    assert strategy.auto_shard is True
    assert strategy.pipeline is True
    cfgs = strategy.pipeline_configs
    assert cfgs["schedule_mode"] == "1F1B"
    assert cfgs["accumulate_steps"] == 4
    assert cfgs["pp_degree"] == 2
    assert cfgs["stage_op_ranges"] \
        == [tuple(s.op_range) for s in plan.stages]

    main2 = static.Program("strategy_pp_run")
    with static.program_guard(main2):
        x = static.data("x", [8, 32], "float32")
        h = x
        for _ in range(4):
            h = ops.tanh(nn.Linear(32, 32, bias_attr=False)(h))
        loss = ops.mean(h)
        opt = fleet.distributed_optimizer(
            opt_mod.SGD(learning_rate=0.1), strategy)
        opt.minimize(loss)
    assert getattr(main2, "_auto_shard", None) is not None
    # re-plan against THIS program at compile: drop the pre-searched
    # plan, keep the pipeline mesh request
    main2._auto_shard = {"mesh": {"pp": 2}, "num_micro": 4}

    old = spmd_analyzer.set_verify_spmd(True)
    try:
        exe = static.Executor()
        (out,) = exe.run(main2, feed={"x": np.ones((8, 32), "float32")},
                         fetch_list=[loss])
        assert np.isfinite(out)
    finally:
        spmd_analyzer.set_verify_spmd(old)
    stages = getattr(main2, "_pipeline_stages", None)
    assert stages is not None, "stages must resolve at compile"
    assert stages["num_stages"] == 2
    assert stages["schedule"] == "1f1b"
    assert len(stages["stage_op_ranges"]) == 2
    # every persistable is assigned a stage
    assert set(stages["param_stages"]) == set(main2.persist_ids)
    assert set(stages["param_stages"].values()) <= {0, 1}


def test_resolve_auto_shard_pp_mesh_routes_to_pipeline(static_mode):
    main, _lins = _mlp_program([1] * 4, batch=8, name="resolve_pp")
    main._auto_shard = {"mesh": {"pp": 2}, "num_micro": 4}
    plan = spmd_planner.resolve_auto_shard(main)
    assert isinstance(plan, ShardingPlan)
    assert plan.pipeline is not None
    assert main._pipeline_stages["num_stages"] == 2
    # memoized: a second resolve returns the same plan object
    assert spmd_planner.resolve_auto_shard(main) is plan
