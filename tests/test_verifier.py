"""Program verifier (ISSUE 1 tentpole): a seeded corpus of deliberately
broken Programs — each the signature of a real pass bug (dropped
producer, reordered ops, duplicated SSA ids, desynced out_ids, DCE'd
fetch/state roots, corrupted control-flow sub-blocks) — must each raise
`ProgramVerifyError` naming the offending op/var, and every builtin pass
must run clean under the verify-before/verify-after harness."""
import copy

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops
from paddle_tpu.static.passes import (apply_pass, register_pass,
                                      set_verify_passes, _PASS_REGISTRY)
from paddle_tpu.static.program import _Ref
from paddle_tpu.static.verifier import ProgramVerifyError, verify_program


def _static():
    import paddle_tpu.static as static
    paddle.enable_static()
    return static


def _chain_program(static):
    """data -> exp -> add(exp, data) -> sum, fetched."""
    main = static.Program("chain")
    with static.program_guard(main):
        x = static.data("x", [2, 3], "float32")
        a = ops.exp(x)
        b = ops.add(a, x)
        out = ops.sum(b)
    main._jit_fetch_vars = [out]
    return main, out


# ---------------------------------------------------------------------------
# the seeded broken-program corpus
# ---------------------------------------------------------------------------

def test_corpus_1_dangling_ref_after_dropped_producer():
    static = _static()
    try:
        main, _ = _chain_program(static)
        broken = copy.copy(main)
        broken.ops = main.ops[1:]  # a "DCE" that drops exp but keeps add
        with pytest.raises(ProgramVerifyError, match="dangling-ref") as e:
            verify_program(broken)
        assert e.value.op_name == "add"
        assert e.value.var is not None
    finally:
        paddle.disable_static()


def test_corpus_2_use_before_def_after_reorder():
    static = _static()
    try:
        main, _ = _chain_program(static)
        broken = copy.copy(main)
        broken.ops = [main.ops[1], main.ops[0], main.ops[2]]
        with pytest.raises(ProgramVerifyError, match="use-before-def") as e:
            verify_program(broken)
        assert e.value.op_name == "add"
        assert "exp" in str(e.value)  # names the too-late producer
    finally:
        paddle.disable_static()


def test_corpus_3_double_assignment():
    static = _static()
    try:
        main, _ = _chain_program(static)
        broken = copy.copy(main)
        broken.ops = [main.ops[0], main.ops[0]] + main.ops[1:]
        with pytest.raises(ProgramVerifyError,
                           match="single-assignment") as e:
            verify_program(broken)
        assert e.value.op_name == "exp"
    finally:
        paddle.disable_static()


def test_corpus_4_out_ids_desynced_from_out_vars():
    static = _static()
    try:
        main, _ = _chain_program(static)
        broken = copy.copy(main)
        bad_op = copy.copy(main.ops[0])
        bad_op.out_ids = [bad_op.out_ids[0] + 999_999]
        broken.ops = [bad_op] + main.ops[1:]
        with pytest.raises(ProgramVerifyError, match="out-ids-sync"):
            verify_program(broken)
    finally:
        paddle.disable_static()


def test_corpus_5_output_shadows_data_var():
    static = _static()
    try:
        main, _ = _chain_program(static)
        x_id = next(iter(main.data_vars.values())).var_id
        broken = copy.copy(main)
        bad_op = copy.copy(main.ops[0])
        bad_op.out_ids = [x_id]
        bad_op.out_vars = list(bad_op.out_vars)
        bad_op.out_vars[0].var_id = x_id
        broken.ops = [bad_op] + main.ops[1:]
        with pytest.raises(ProgramVerifyError, match="shadows"):
            verify_program(broken)
    finally:
        paddle.disable_static()


def test_corpus_6_fetch_root_eliminated():
    static = _static()
    try:
        main, out = _chain_program(static)
        broken = copy.copy(main)
        broken.ops = main.ops[:-1]  # drops the fetched sum
        with pytest.raises(ProgramVerifyError, match="root-liveness") as e:
            verify_program(broken)
        assert e.value.var == out.name
    finally:
        paddle.disable_static()


def test_corpus_7_state_write_target_eliminated():
    static = _static()
    try:
        main, _ = _chain_program(static)
        broken = copy.copy(main)
        broken.state_writes = {"bn_mean": 987_654_321}  # producer gone
        with pytest.raises(ProgramVerifyError, match="root-liveness") as e:
            verify_program(broken)
        assert e.value.var == "bn_mean"
    finally:
        paddle.disable_static()


def test_corpus_8_backward_loss_eliminated():
    static = _static()
    try:
        main, out = _chain_program(static)
        broken = copy.copy(main)
        broken._jit_fetch_vars = []
        broken.backward_section = (out, [])
        broken.ops = main.ops[:-1]
        with pytest.raises(ProgramVerifyError, match="root-liveness") as e:
            verify_program(broken)
        assert out.name in str(e.value)
    finally:
        paddle.disable_static()


def _while_program(static):
    main = static.Program("loop")
    with static.program_guard(main):
        x = static.data("x", [4], "float32")
        i = ops.zeros([], "int32")
        n = ops.full([], 3, "int32")
        _, acc = static.nn.while_loop(
            lambda i, a: ops.less_than(i, n),
            lambda i, a: (i + 1, a * 2.0), [i, x])
    main._jit_fetch_vars = [acc]
    return main


def test_corpus_9_subblock_dangling_inner_ref():
    static = _static()
    try:
        main = _while_program(static)
        broken = copy.copy(main)
        widx, wop = next((i, op) for i, op in enumerate(main.ops)
                         if op.name == "while_loop")
        bad_op = copy.copy(wop)
        bad_fn = copy.copy(wop.fn)
        bad_blk = copy.copy(bad_fn.body_block)
        bad_blk.ops = list(bad_blk.ops)
        inner = copy.copy(bad_blk.ops[-1])
        inner.flat = [(_corrupt_ref(r) if isinstance(r, _Ref) else r)
                      for r in inner.flat]
        bad_blk.ops[-1] = inner
        bad_fn.body_block = bad_blk
        bad_op.fn = bad_fn
        broken.ops = list(main.ops)
        broken.ops[widx] = bad_op
        with pytest.raises(ProgramVerifyError, match="sub-block") as e:
            verify_program(broken)
        assert e.value.op_name == "while_loop"
    finally:
        paddle.disable_static()


def _corrupt_ref(r):
    r2 = copy.copy(r)
    r2.var_id = r.var_id + 999_999
    return r2


def test_corpus_10_subblock_free_arity_mismatch():
    static = _static()
    try:
        main = _while_program(static)
        broken = copy.copy(main)
        widx, wop = next((i, op) for i, op in enumerate(main.ops)
                         if op.name == "while_loop")
        bad_op = copy.copy(wop)
        bad_fn = copy.copy(wop.fn)
        bad_blk = copy.copy(bad_fn.cond_block)
        bad_blk.free_ids = list(bad_blk.free_ids) + [123_456_789]
        bad_fn.cond_block = bad_blk
        bad_op.fn = bad_fn
        broken.ops = list(main.ops)
        broken.ops[widx] = bad_op
        with pytest.raises(ProgramVerifyError, match="sub-block") as e:
            verify_program(broken)
        assert e.value.op_name == "while_loop"
    finally:
        paddle.disable_static()


def test_corpus_11_subblock_undefined_output():
    static = _static()
    try:
        main = _while_program(static)
        broken = copy.copy(main)
        widx, wop = next((i, op) for i, op in enumerate(main.ops)
                         if op.name == "while_loop")
        bad_op = copy.copy(wop)
        bad_fn = copy.copy(wop.fn)
        bad_blk = copy.copy(bad_fn.body_block)
        bad_blk.out_ids = [999_999_999] * len(bad_blk.out_ids)
        bad_fn.body_block = bad_blk
        bad_op.fn = bad_fn
        broken.ops = list(main.ops)
        broken.ops[widx] = bad_op
        with pytest.raises(ProgramVerifyError, match="sub-block"):
            verify_program(broken)
    finally:
        paddle.disable_static()


# ---------------------------------------------------------------------------
# well-formed programs verify clean; passes run under the harness
# ---------------------------------------------------------------------------

def test_wellformed_programs_verify_clean():
    static = _static()
    try:
        main, _ = _chain_program(static)
        assert verify_program(main) is main
        assert verify_program(_while_program(static)) is not None
    finally:
        paddle.disable_static()


def test_every_builtin_pass_runs_under_verify_harness():
    static = _static()
    try:
        main, out = _chain_program(static)
        old = set_verify_passes(True)
        try:
            for name in ("eliminate_dead_ops",
                         "common_subexpression_elimination",
                         "fold_constants"):
                reg_name = {"common_subexpression_elimination": "cse"}.get(
                    name, name)
                assert reg_name in _PASS_REGISTRY
                result = apply_pass(main, reg_name)
                verify_program(result, pass_name=reg_name)
        finally:
            set_verify_passes(old)
        # and the fetched value still computes correctly end-to-end
        exe = static.Executor()
        pruned = apply_pass(main, ["cse", "eliminate_dead_ops"])
        xs = np.ones((2, 3), "float32")
        got = exe.run(pruned, feed={"x": xs}, fetch_list=[out])[0]
        np.testing.assert_allclose(
            got, np.sum(np.exp(xs) + xs), rtol=1e-6)
    finally:
        paddle.disable_static()


def test_harness_blames_the_breaking_pass():
    static = _static()
    try:
        main, _ = _chain_program(static)

        @register_pass("_test_broken_pass")
        def _broken(program):
            new = copy.copy(program)
            new.ops = program.ops[1:]  # drops a live producer
            return new

        old = set_verify_passes(True)
        try:
            with pytest.raises(ProgramVerifyError) as e:
                apply_pass(main, "_test_broken_pass")
            assert e.value.pass_name == "_test_broken_pass"
            assert "_test_broken_pass" in str(e.value)
        finally:
            set_verify_passes(old)
            _PASS_REGISTRY.pop("_test_broken_pass", None)
    finally:
        paddle.disable_static()


def test_analysis_pass_only_legal_at_chain_tail():
    static = _static()
    try:
        main, _ = _chain_program(static)
        dot = apply_pass(main, ["eliminate_dead_ops", "graph_viz"])
        assert isinstance(dot, str) and dot.startswith("digraph")
        with pytest.raises(TypeError, match="must come last"):
            apply_pass(main, ["graph_viz", "eliminate_dead_ops"])
    finally:
        paddle.disable_static()


def test_harness_env_flag_gates_verification(monkeypatch):
    from paddle_tpu.static import passes as passes_mod
    set_verify_passes(None)
    monkeypatch.setenv("PADDLE_TPU_VERIFY_PASSES", "0")
    assert not passes_mod.verify_passes_enabled()
    monkeypatch.setenv("PADDLE_TPU_VERIFY_PASSES", "1")
    assert passes_mod.verify_passes_enabled()
