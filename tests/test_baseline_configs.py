"""Direct evidence for BASELINE.md tracked configs at test scale:
config 3 (BERT pretrain, STATIC graph), config 4 (collective
data-parallel conv net), config 5 shape lives in test_recompute."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer
from paddle_tpu.distributed import mesh as mesh_mod


def test_static_graph_bert_trains():
    """BASELINE config 3: BERT built and trained in static-graph mode —
    Program recorded once, Executor lowers to one jitted step, loss
    drops over steps."""
    import paddle_tpu.static as static
    from paddle_tpu.text.models.bert import Bert, BertConfig

    cfg = BertConfig.tiny()
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            paddle.seed(0)
            ids = static.data("ids", [4, 16], "int64")
            labels = static.data("labels", [4, 16], "int64")
            net = Bert(cfg)
            logits = net(ids)
            b, s, v = 4, 16, cfg.vocab_size
            loss = nn.CrossEntropyLoss(ignore_index=-100)(
                ops.reshape(logits, [b * s, v]),
                ops.reshape(labels, [b * s]))
            optimizer.AdamW(learning_rate=1e-3).minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        losses = []
        for step in range(6):
            x = rng.randint(4, cfg.vocab_size, (4, 16)).astype("int64")
            y = np.where(rng.rand(4, 16) < 0.15, x, -100).astype("int64")
            losses.append(float(exe.run(main, feed={"ids": x, "labels": y},
                                        fetch_list=[loss])[0]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses
    finally:
        paddle.disable_static()


def test_collective_dp_convnet_fit():
    """BASELINE config 4: data-parallel conv-net Model.fit over the
    8-device mesh via fleet (the c_allreduce path, compiler-emitted)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.io import TensorDataset

    mesh_mod.init_mesh({"dp": 8})
    paddle.seed(3)
    np.random.seed(3)
    X = np.random.rand(64, 3, 8, 8).astype("float32")
    Y = np.random.randint(0, 4, (64,)).astype("int64")
    net = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
        nn.AdaptiveAvgPool2D(1), nn.Flatten(), nn.Linear(8, 4))
    strategy = fleet.DistributedStrategy()
    fleet.init(is_collective=True, strategy=strategy)
    opt = fleet.distributed_optimizer(
        optimizer.Momentum(learning_rate=0.05,
                           parameters=net.parameters()))
    model = paddle.Model(net)
    model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss())
    from paddle_tpu.hapi.callbacks import History
    h = History()
    model.fit(TensorDataset([X, Y]), batch_size=32, epochs=4, verbose=0,
              shuffle=False, callbacks=[h], drop_last=True)
    losses = h.history["loss"]
    assert losses[-1] < losses[0], losses
    mesh_mod.init_mesh({"dp": 8})


def test_model_parallel_recompute_gpt_config5():
    """BASELINE config 5 (ERNIE/Transformer-XL-class: model parallel +
    recompute; reference c_allgather + RecomputeOptimizer,
    fluid/optimizer.py:4526): GPT-tiny trains on a dp2 x tp4 mesh with
    every block rematerialized — loss drops, and the first recomputed
    step equals the non-recompute step bit-for-bit in f32 tolerance
    (remat changes memory, never math)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu import optimizer as opt_mod
    from paddle_tpu.core import rng as _rng
    from paddle_tpu.core import tape as _tape
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.sharding import build_param_shardings
    from paddle_tpu.text.models.gpt import GPT, GPTConfig

    mesh = mesh_mod.init_mesh({"dp": 2, "tp": 4})
    try:
        def build(recompute):
            paddle.seed(0)
            net = GPT(GPTConfig(vocab_size=256, hidden_size=64,
                                num_layers=2, num_heads=4,
                                intermediate_size=128, max_seq_len=64,
                                dropout=0.0))
            net.train()
            if recompute:
                for blk in net.blocks:
                    blk.enable_recompute()
            opt = opt_mod.AdamW(learning_rate=1e-3,
                                parameters=net.parameters())
            params, buffers = net.functional_state()
            named = dict(net.named_parameters())
            opt._ensure_slots(params)
            slots = dict(opt._slots)
            meta = opt._param_meta(named)
            shard = build_param_shardings(params, mesh)
            repl = NamedSharding(mesh, P())
            data_sh = NamedSharding(mesh, P("dp"))

            def step(params, slots, ids, labels, lr, t, key):
                with _rng.rng_state(key), _tape.no_grad():
                    def loss_of(p):
                        net.load_functional_state(p, buffers)
                        loss = net(Tensor(ids, _internal=True),
                                   labels=Tensor(labels, _internal=True))
                        return loss._value.mean().astype(jnp.float32)

                    loss, grads = jax.value_and_grad(loss_of)(params)
                    new_p, new_s = opt.apply_gradients_pure(
                        params, grads, slots, lr, t, param_meta=meta)
                return loss, new_p, new_s

            slot_sh = {k: {s: shard[k] for s in slots[k]} for k in slots}
            jitted = jax.jit(step,
                             in_shardings=(shard, slot_sh, data_sh,
                                           data_sh, repl, repl, repl),
                             out_shardings=(repl, shard, slot_sh))
            return jitted, params, slots

        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(4, 256, (4, 32)), jnp.int32)
        labels = jnp.asarray(np.roll(np.asarray(ids), -1, 1), jnp.int32)
        lr = jnp.asarray(1e-3, jnp.float32)
        t = jnp.asarray(1, jnp.int32)
        key = jax.random.PRNGKey(0)

        losses = {}
        for recompute in (False, True):
            stepf, params, slots = build(recompute)
            ls = []
            with mesh:
                for i in range(4):
                    loss, params, slots = stepf(params, slots, ids, labels,
                                                lr, t,
                                                jax.random.fold_in(key, i))
                    ls.append(float(np.asarray(loss)))
            losses[recompute] = ls
        np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
        assert losses[True][-1] < losses[True][0], losses[True]
    finally:
        mesh_mod.init_mesh({"dp": 8})
