"""Direct evidence for BASELINE.md tracked configs at test scale:
config 3 (BERT pretrain, STATIC graph), config 4 (collective
data-parallel conv net), config 5 shape lives in test_recompute."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, ops, optimizer
from paddle_tpu.distributed import mesh as mesh_mod


def test_static_graph_bert_trains():
    """BASELINE config 3: BERT built and trained in static-graph mode —
    Program recorded once, Executor lowers to one jitted step, loss
    drops over steps."""
    import paddle_tpu.static as static
    from paddle_tpu.text.models.bert import Bert, BertConfig

    cfg = BertConfig.tiny()
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            paddle.seed(0)
            ids = static.data("ids", [4, 16], "int64")
            labels = static.data("labels", [4, 16], "int64")
            net = Bert(cfg)
            logits = net(ids)
            b, s, v = 4, 16, cfg.vocab_size
            loss = nn.CrossEntropyLoss(ignore_index=-100)(
                ops.reshape(logits, [b * s, v]),
                ops.reshape(labels, [b * s]))
            optimizer.AdamW(learning_rate=1e-3).minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        losses = []
        for step in range(6):
            x = rng.randint(4, cfg.vocab_size, (4, 16)).astype("int64")
            y = np.where(rng.rand(4, 16) < 0.15, x, -100).astype("int64")
            losses.append(float(exe.run(main, feed={"ids": x, "labels": y},
                                        fetch_list=[loss])[0]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses
    finally:
        paddle.disable_static()


def test_collective_dp_convnet_fit():
    """BASELINE config 4: data-parallel conv-net Model.fit over the
    8-device mesh via fleet (the c_allreduce path, compiler-emitted)."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.io import TensorDataset

    mesh_mod.init_mesh({"dp": 8})
    paddle.seed(3)
    np.random.seed(3)
    X = np.random.rand(64, 3, 8, 8).astype("float32")
    Y = np.random.randint(0, 4, (64,)).astype("int64")
    net = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
        nn.AdaptiveAvgPool2D(1), nn.Flatten(), nn.Linear(8, 4))
    strategy = fleet.DistributedStrategy()
    fleet.init(is_collective=True, strategy=strategy)
    opt = fleet.distributed_optimizer(
        optimizer.Momentum(learning_rate=0.05,
                           parameters=net.parameters()))
    model = paddle.Model(net)
    model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss())
    from paddle_tpu.hapi.callbacks import History
    h = History()
    model.fit(TensorDataset([X, Y]), batch_size=32, epochs=4, verbose=0,
              shuffle=False, callbacks=[h], drop_last=True)
    losses = h.history["loss"]
    assert losses[-1] < losses[0], losses
    mesh_mod.init_mesh({"dp": 8})
