"""Trace-context propagation through the PS transport under chaos
(testing/faults.py): retries and server-side replays must reuse the
originating trace id, and spans must survive a mid-call reconnect. The
servers run in-process, so client- AND server-side spans land in one
trace ring and the correlation is directly assertable."""
import numpy as np
import pytest

from paddle_tpu.core import trace
from paddle_tpu.distributed.ps import PSClient, PSServer
from paddle_tpu.testing import faults

pytestmark = pytest.mark.chaos

DIM = 4
FAST = dict(timeout=5.0, max_retries=3, backoff_base=0.01,
            backoff_max=0.05, connect_retry_s=5.0)


@pytest.fixture()
def server():
    srv = PSServer(tables={"emb": {"type": "sparse", "dim": DIM,
                                   "optimizer": "sgd", "lr": 1.0,
                                   "init": "zeros"}})
    srv.start()
    yield srv
    srv.shutdown()


@pytest.fixture(autouse=True)
def _clean():
    trace.reset()
    yield
    faults.uninstall()
    trace.reset()


def _spans(name):
    return [s for s in trace.recent() if s.name == name]


def test_server_span_parents_to_client_call(server):
    client = PSClient([server.endpoint], **FAST)
    client.pull_sparse("emb", [1, 2, 3])
    client.close()
    csp = _spans("ps.rpc/pull_sparse")[-1]
    ssp = _spans("ps.server/pull_sparse")[-1]
    # cross-"process" correlation: same trace id, parented to the call
    assert ssp.trace_id == csp.trace_id
    assert ssp.parent_id == csp.span_id
    assert ssp.attrs["outcome"] == "apply"
    assert csp.attrs["attempts"] == 1
    assert ssp.tid != csp.tid  # handler ran on the server's conn thread


def test_replayed_mutation_reuses_originating_trace_id(server):
    client = PSClient([server.endpoint], **FAST)
    grads = np.ones((2, DIM), np.float32)
    # drop exactly the first push reply: the request WAS applied, the
    # retry must hit the replay cache — both server spans one trace
    with faults.inject(faults.Fault("server", "reply", faults.DROP,
                                    method="push_sparse_grad")) as inj:
        client.push_sparse_grad("emb", [1, 2], grads)
    assert inj.fired(faults.DROP) == 1
    client.close()
    csp = _spans("ps.rpc/push_sparse_grad")[-1]
    server_spans = [s for s in _spans("ps.server/push_sparse_grad")
                    if s.trace_id == csp.trace_id]
    outcomes = [s.attrs["outcome"] for s in server_spans]
    assert outcomes == ["apply", "replay"], outcomes
    # the retry carried the SAME frame bytes: both server spans parent
    # to the one client span of the one logical call
    assert {s.parent_id for s in server_spans} == {csp.span_id}
    assert csp.attrs["attempts"] == 2
    assert csp.attrs["mutating"] is True
    # exactly-once still holds under the shared trace context
    assert client_applied(server) == 1


def client_applied(server):
    c = PSClient([server.endpoint], **FAST)
    try:
        return c.table_applied("emb")
    finally:
        c.close()


def test_span_survives_mid_call_reconnect(server):
    client = PSClient([server.endpoint], **FAST)
    # two resets at the send boundary force teardown + re-dial (and a
    # re-auth handshake path) INSIDE one logical call
    with faults.inject(faults.Fault("client", "send", faults.RESET,
                                    method="pull_sparse", times=2)) as inj:
        rows = client.pull_sparse("emb", [5, 6])
    assert rows.shape == (2, DIM)
    assert inj.fired(faults.RESET) == 2
    client.close()
    csp = _spans("ps.rpc/pull_sparse")[-1]
    assert csp.attrs["attempts"] == 3      # one span across all attempts
    assert csp.t1 is not None
    ssp = [s for s in _spans("ps.server/pull_sparse")
           if s.trace_id == csp.trace_id]
    # the attempt that finally landed still correlates to the call
    assert ssp and ssp[-1].parent_id == csp.span_id


def test_chaos_run_keeps_traces_connected(server):
    """Seeded chaos: every server-side span observed during the storm
    belongs to SOME client call span's trace (no orphan traces), and
    mutations stay exactly-once."""
    client = PSClient([server.endpoint], **FAST)
    grads = np.ones((3, DIM), np.float32)
    with faults.inject(seed=11, p={faults.RESET: 0.1, faults.DROP: 0.1}):
        for i in range(20):
            client.push_sparse_grad("emb", [i, i + 1, i + 2], grads)
    client.close()
    client_traces = {s.trace_id
                     for s in _spans("ps.rpc/push_sparse_grad")}
    server_spans = _spans("ps.server/push_sparse_grad")
    assert len(client_traces) == 20
    assert len(server_spans) >= 20
    orphans = [s for s in server_spans
               if s.trace_id not in client_traces]
    assert not orphans, f"server spans outside any call trace: {orphans}"
    assert client_applied(server) == 20
