"""Continuous-batching serve tier: paged KV pool + block-table kernel +
admission scheduler (inference/serving.py, nn/kv_pool.py,
ops/pallas/decode_attention.paged_decode_attention).

THE proof: greedy continuous-batched decode — ragged prompts admitted
mid-flight, retiring early on EOS, evicted and replayed under pool
pressure — is TOKEN-IDENTICAL to per-request sequential GPT.generate.
Plus: block-table kernel parity vs the jnp gather fallback at several
fill levels, pool-exhaustion backpressure then admission-on-retire, and
an injected kernel crash demoting via run_guarded with the serve loop
still completing correctly.
"""
import threading

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor, trace
from paddle_tpu.inference import ServeConfig, ServeLoop
from paddle_tpu.nn.kv_pool import (KVBlockPool, PagedKVCache,
                                   paged_attention_ref, write_kv)
from paddle_tpu.text.models.gpt import GPT, GPTConfig


@pytest.fixture(scope="module")
def net():
    paddle.seed(0)
    m = GPT(GPTConfig.tiny())
    m.eval()
    return m


@pytest.fixture
def interpret():
    paddle.set_flags({"FLAGS_pallas_interpret": True})
    yield
    paddle.set_flags({"FLAGS_pallas_interpret": False})


def _ref_generate(net, prompt, n, eos=None):
    """Sequential single-request oracle: greedy generate, truncated at
    the first eos like the serve loop retires."""
    out = np.asarray(net.generate(
        paddle.to_tensor(np.asarray(prompt, np.int64)[None]),
        max_new_tokens=n, temperature=0, use_cache=True)
        .numpy())[0, len(prompt):]
    if eos is None:
        return out
    hits = np.where(out == eos)[0]
    return out[: hits[0] + 1] if hits.size else out


# --------------------------------------------------------------------------
# pool
# --------------------------------------------------------------------------

def test_pool_alloc_free_invariants():
    pool = KVBlockPool(4, 16)
    assert pool.free_blocks == 4 and pool.used_blocks == 0
    a = pool.alloc(3)
    assert len(a) == 3 and pool.used_blocks == 3
    assert 0 not in a, "trash block must never be allocated"
    assert pool.alloc(2) is None, "all-or-nothing alloc"
    assert pool.used_blocks == 3, "failed alloc must not leak"
    b = pool.alloc(1)
    assert pool.free_blocks == 0
    assert not pool.can_alloc(1)
    pool.free(a)
    assert pool.free_blocks == 3
    with pytest.raises(ValueError, match="double free"):
        pool.free([a[0]])
    with pytest.raises(ValueError, match="invalid block"):
        pool.free([0])
    pool.free(b)
    assert pool.blocks_for(0) == 0 and pool.blocks_for(1) == 1 \
        and pool.blocks_for(16) == 1 and pool.blocks_for(17) == 2


def test_pool_rejects_bad_block_size():
    with pytest.raises(ValueError, match="sublane"):
        KVBlockPool(4, 12)


# --------------------------------------------------------------------------
# block-table kernel parity vs the jnp fallback
# --------------------------------------------------------------------------

@pytest.mark.parametrize("s", [1, 8])
def test_paged_kernel_parity_fill_levels(interpret, s):
    """Several fill levels across slots — from one partial block to a
    full table — kernel vs gather fallback."""
    from paddle_tpu.ops.pallas.decode_attention import (
        paged_decode_attention, paged_supported)
    rng = np.random.RandomState(0)
    b, h, d, bs, MB, NB = 4, 2, 16, 16, 4, 14
    pool = KVBlockPool(NB, bs)
    ka = jnp.zeros((NB + 1, h, bs, d), jnp.float32)
    va = jnp.zeros((NB + 1, h, bs, d), jnp.float32)
    bt = np.zeros((b, MB), np.int32)
    fills = [9, 16, 37, 64]          # 1 part, 1 full, 3 part, 4 full blocks
    for i, ln in enumerate(fills):
        blocks = pool.alloc(pool.blocks_for(ln))
        bt[i, :len(blocks)] = blocks
    bt = jnp.asarray(bt)
    for i, ln in enumerate(fills):
        ka = write_kv(ka, bt[i:i + 1], jnp.zeros((1,), jnp.int32),
                      jnp.asarray(rng.randn(1, ln, h, d), jnp.float32))
        va = write_kv(va, bt[i:i + 1], jnp.zeros((1,), jnp.int32),
                      jnp.asarray(rng.randn(1, ln, h, d), jnp.float32))
    assert paged_supported((b, h, s, d), tuple(ka.shape))
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
    lens = jnp.asarray([ln - s for ln in fills], jnp.int32)
    out = paged_decode_attention(q, ka, va, bt, lens)
    ref = paged_attention_ref(q, ka, va, bt, lens, d ** -0.5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5)


def test_mha_paged_matches_static_cache_bitwise():
    """The MHA PagedKVCache branch (jnp path) must be BITWISE equal to
    the StaticKVCache path across a prefill + decode sequence — the
    foundation of serve-vs-generate token identity."""
    from paddle_tpu import nn
    paddle.seed(3)
    mha = nn.MultiHeadAttention(32, 2, dropout=0.0)
    mha.eval()
    b, bs, NB, MB = 2, 16, 10, 4
    static = mha.gen_static_cache(b, 64)
    pool = KVBlockPool(NB, bs)
    bt = np.zeros((b, MB), np.int32)
    for i in range(b):
        bt[i, :] = pool.alloc(MB)
    paged = PagedKVCache(jnp.zeros((NB + 1, 2, bs, 16), jnp.float32),
                         jnp.zeros((NB + 1, 2, bs, 16), jnp.float32),
                         jnp.asarray(bt), jnp.zeros((b,), jnp.int32))
    rng = np.random.RandomState(5)
    for chunk in (7, 1, 1, 1):
        x = paddle.to_tensor(rng.randn(b, chunk, 32).astype(np.float32))
        os_, static = mha(x, cache=static)
        op_, paged = mha(x, cache=paged)
        np.testing.assert_array_equal(np.asarray(os_._value),
                                      np.asarray(op_._value))
    assert np.asarray(paged.lengths).tolist() == [10, 10]


# --------------------------------------------------------------------------
# THE proof: continuous batching == sequential generate
# --------------------------------------------------------------------------

def test_serve_greedy_token_identical_ragged_admission(net):
    """More ragged-prompt requests than slots: admission happens
    mid-flight while earlier streams are still decoding, and every
    stream's tokens must equal its sequential generate run."""
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 1024, (n,)).astype(np.int64)
               for n in (5, 9, 3, 17, 7, 12)]
    loop = ServeLoop(net, ServeConfig(max_active=3, kv_blocks=32,
                                      block_size=16, max_seq_len=64))
    results = loop.serve(prompts, max_new_tokens=8)
    for p, got in zip(prompts, results):
        np.testing.assert_array_equal(got, _ref_generate(net, p, 8))
    st = loop.stats()
    assert st["kv_pool_used_blocks"] == 0 and st["active_slots"] == 0


def test_serve_eos_retires_early_and_frees_blocks(net):
    rng = np.random.RandomState(1)
    p = rng.randint(1, 1024, (6,)).astype(np.int64)
    eos = int(_ref_generate(net, p, 10)[0])
    loop = ServeLoop(net, ServeConfig(max_active=2, kv_blocks=16,
                                      block_size=16, max_seq_len=64))
    monitor.reset(prefix="serve.")
    out = loop.serve([p], max_new_tokens=10, eos_token_id=eos)[0]
    np.testing.assert_array_equal(out, _ref_generate(net, p, 10, eos))
    assert len(out) < 10, "eos must retire the stream early"
    assert loop.stats()["kv_pool_used_blocks"] == 0
    assert monitor.stat_get("serve.requests_completed") == 1


def test_pool_exhaustion_backpressure_then_admission_on_retire(net):
    """Pool fits ONE stream's worst case: the queue must drain strictly
    serially (peak one active) and still produce exact tokens."""
    rng = np.random.RandomState(2)
    prompts = [rng.randint(1, 1024, (10,)).astype(np.int64)
               for _ in range(3)]
    loop = ServeLoop(net, ServeConfig(max_active=4, kv_blocks=2,
                                      block_size=16, max_seq_len=32))
    monitor.reset(prefix="serve.")
    peak = [0]
    orig = loop._dispatch_decode

    def spying_dispatch():
        peak[0] = max(peak[0],
                      sum(s is not None for s in loop._slots))
        return orig()

    loop._dispatch_decode = spying_dispatch
    results = loop.serve(prompts, max_new_tokens=12)
    for p, got in zip(prompts, results):
        np.testing.assert_array_equal(got, _ref_generate(net, p, 12))
    assert peak[0] == 1, "pool for one stream must serialize admissions"
    assert monitor.stat_get("serve.requests_completed") == 3
    assert loop.stats()["kv_pool_used_blocks"] == 0


def test_preemption_replays_token_identical(net):
    """Overcommitted pool: growth preempts the youngest stream, which
    re-queues with its generated prefix and must still end
    token-identical (fold-in sampling keys make the replay exact)."""
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 1024, (6,)).astype(np.int64)
               for _ in range(3)]
    loop = ServeLoop(net, ServeConfig(max_active=4, kv_blocks=3,
                                      block_size=8, max_seq_len=16))
    monitor.reset(prefix="serve.")
    results = loop.serve(prompts, max_new_tokens=8)
    for p, got in zip(prompts, results):
        np.testing.assert_array_equal(got, _ref_generate(net, p, 8))
    assert monitor.stat_get("serve.preempted") > 0, \
        "this config must exercise eviction"
    assert loop.stats()["kv_pool_used_blocks"] == 0


def test_serve_threaded_concurrent_clients(net):
    loop = ServeLoop(net, ServeConfig(max_active=4, kv_blocks=32,
                                      block_size=16,
                                      max_seq_len=64)).start()
    rng = np.random.RandomState(4)
    prompts = [rng.randint(1, 1024, (4 + i % 5,)).astype(np.int64)
               for i in range(10)]
    outs = {}

    def client(i):
        outs[i] = loop.submit(prompts[i],
                              max_new_tokens=6).result(timeout=120)

    ts = [threading.Thread(target=client, args=(i,)) for i in range(10)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    loop.stop()
    for i, p in enumerate(prompts):
        np.testing.assert_array_equal(outs[i], _ref_generate(net, p, 6))


def test_submit_rejects_over_cap(net):
    loop = ServeLoop(net, ServeConfig(max_active=2, kv_blocks=4,
                                      block_size=16, max_seq_len=32))
    with pytest.raises(ValueError, match="serving cap"):
        loop.submit(np.arange(1, 30), max_new_tokens=10)


# --------------------------------------------------------------------------
# crash-to-fallback + observability
# --------------------------------------------------------------------------

def test_injected_kernel_crash_demotes_and_serve_completes(
        net, interpret, monkeypatch):
    """With the paged kernel eligible (interpret backend) but crashing,
    run_guarded must demote every dispatch to the jnp fallback and the
    serve loop must finish with exact tokens."""
    import importlib
    # the pallas package __init__ shadows the module name with the
    # function; importlib reaches the module itself
    da = importlib.import_module("paddle_tpu.ops.pallas.decode_attention")

    def boom(*a, **k):
        raise RuntimeError("injected Mosaic crash")

    monkeypatch.setattr(da, "_paged_call", boom)
    for name in list(monitor.stats("pallas.")):
        monitor.reset(name)
    rng = np.random.RandomState(5)
    prompts = [rng.randint(1, 1024, (n,)).astype(np.int64)
               for n in (5, 8)]
    loop = ServeLoop(net, ServeConfig(max_active=2, kv_blocks=16,
                                      block_size=16, max_seq_len=64))
    with pytest.warns(RuntimeWarning, match="paged_decode_attention"):
        results = loop.serve(prompts, max_new_tokens=6)
    for p, got in zip(prompts, results):
        np.testing.assert_array_equal(got, _ref_generate(net, p, 6))
    assert monitor.stat_get(
        "pallas.fallback.paged_decode_attention.RuntimeError") > 0
    assert monitor.stat_get("pallas.hit.paged_decode_attention") == 0


def test_paged_kernel_engages_in_serve(net, interpret):
    """With interpret on and no crash, the block-table kernel actually
    serves the loop (hit counter) and tokens stay exact."""
    for name in list(monitor.stats("pallas.")):
        monitor.reset(name)
    rng = np.random.RandomState(6)
    p = rng.randint(1, 1024, (7,)).astype(np.int64)
    loop = ServeLoop(net, ServeConfig(max_active=2, kv_blocks=16,
                                      block_size=16, max_seq_len=64))
    out = loop.serve([p], max_new_tokens=4)[0]
    np.testing.assert_array_equal(out, _ref_generate(net, p, 4))
    assert monitor.stat_get("pallas.hit.paged_decode_attention") > 0
    assert monitor.stat_get(
        "pallas.fallback.paged_decode_attention.RuntimeError") == 0


def test_serve_spans_and_gauges(net):
    trace.reset()
    monitor.reset(prefix="serve.")
    monitor.reset(prefix="serve/")   # the ttft/token histograms
    rng = np.random.RandomState(7)
    prompts = [rng.randint(1, 1024, (5,)).astype(np.int64)
               for _ in range(2)]
    loop = ServeLoop(net, ServeConfig(max_active=2, kv_blocks=16,
                                      block_size=16, max_seq_len=64))
    loop.serve(prompts, max_new_tokens=4)
    names = {sp.name for sp in trace.recent()}
    for want in ("serve/admit", "serve/prefill", "serve/decode_step",
                 "serve/retire", "serve/dispatch", "serve/retire_wait"):
        assert want in names, f"missing span {want} (have {names})"
    stats = monitor.stats("serve.")
    for g in ("serve.queue_depth", "serve.active_slots",
              "serve.kv_pool_used_blocks", "serve.kv_pool_free_blocks",
              "serve.tokens_generated", "serve.requests_completed"):
        assert g in stats, f"missing gauge {g}"
    assert monitor.stat_get("serve.requests_completed") == 2
    # latency histograms feed bench's serve snapshot
    assert monitor.histogram_summary("serve/ttft_ms")["count"] == 2


# --------------------------------------------------------------------------
# satellite: per-request EOS handling in batched generate
# --------------------------------------------------------------------------

def test_batched_generate_eos_matches_sequential(net):
    """Batched cached generate with per-request EOS: finished rows
    freeze to eos and every row equals its single-request run — the
    contract that lets the serve loop retire rows early."""
    rng = np.random.RandomState(8)
    prompts = np.stack([rng.randint(1, 1024, (5,)) for _ in range(3)])
    refs = [np.asarray(net.generate(
        paddle.to_tensor(prompts[i][None]), max_new_tokens=10,
        temperature=0, use_cache=True).numpy())[0, 5:]
        for i in range(3)]
    eos = int(refs[0][1])  # row 0 finishes after <= 2 tokens
    batched = np.asarray(net.generate(
        paddle.to_tensor(prompts.astype(np.int64)), max_new_tokens=10,
        temperature=0, use_cache=True,
        eos_token_id=eos).numpy())[:, 5:]
    for i in range(3):
        ref = refs[i]
        hits = np.where(ref == eos)[0]
        if hits.size:
            cut = hits[0] + 1
            assert batched[i][:cut].tolist() == ref[:cut].tolist()
            assert (batched[i][cut:] == eos).all(), \
                "finished rows must stay frozen at eos"
        else:
            assert batched[i].tolist() == ref.tolist()
