"""Round-4 sequence_ops completion (reference operators/sequence_ops/:
sequence_mask, expand_as, enumerate, erase, reshape, scatter, conv,
topk_avg_pooling)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.ragged import RaggedTensor
from paddle_tpu.ops import sequence as S


def _rag(rows, dtype="float32"):
    return RaggedTensor.from_rows([np.asarray(r, dtype) for r in rows])


def test_sequence_mask():
    m = S.sequence_mask(paddle.to_tensor(np.array([2, 0, 3])), maxlen=4)
    np.testing.assert_array_equal(
        np.asarray(m), [[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 0]])


def test_sequence_enumerate():
    r = _rag([[1, 2, 3], [4, 5]], "int64")
    out = S.sequence_enumerate(r, win_size=2, pad_value=0)
    np.testing.assert_array_equal(
        np.asarray(out.values),
        [[1, 2], [2, 3], [3, 0], [4, 5], [5, 0]])


def test_sequence_erase():
    r = _rag([[1, 2, 2, 3], [2, 4]], "int64")
    out = S.sequence_erase(r, [2])
    assert out.recursive_sequence_lengths() == [[2, 1]]
    np.testing.assert_array_equal(np.asarray(out.values), [1, 3, 4])


def test_sequence_reshape():
    r = _rag([[[1, 1], [2, 2], [3, 3]], [[4, 4]]])   # widths 2, lens 3/1
    out = S.sequence_reshape(r, new_dim=1)
    assert out.recursive_sequence_lengths() == [[6, 2]]
    out2 = S.sequence_reshape(out, new_dim=2)
    assert out2.recursive_sequence_lengths() == [[3, 1]]


def test_sequence_scatter():
    x = np.zeros((2, 5), "float32")
    idx = RaggedTensor.from_rows([np.array([0, 2], np.int64),
                                  np.array([1], np.int64)])
    upd = _rag([[1.0, 3.0], [5.0]])
    out = S.sequence_scatter(paddle.to_tensor(x), idx, upd)
    np.testing.assert_array_equal(
        np.asarray(out), [[1, 0, 3, 0, 0], [0, 5, 0, 0, 0]])


def test_sequence_conv_window_stays_in_sequence():
    # identity filter on the center tap isolates the window logic
    d = 2
    r = _rag([[[1, 10], [2, 20], [3, 30]], [[4, 40]]])
    w = np.zeros((3 * d, d), "float32")
    w[2, 0] = 1.0   # center tap (c=1), feature 0 -> out 0
    w[3, 1] = 1.0
    out = S.sequence_conv(r, w, context_length=3)
    np.testing.assert_allclose(np.asarray(out.values),
                               np.asarray(r.values))
    # edge tap: previous element, zero at sequence starts (no bleed from
    # the prior sequence)
    w2 = np.zeros((3 * d, d), "float32")
    w2[0, 0] = 1.0  # c=0 (offset -1), feature 0
    out2 = S.sequence_conv(r, w2, context_length=3)
    vals = np.asarray(out2.values)
    assert vals[0, 0] == 0.0          # first of seq 0
    assert vals[1, 0] == 1.0          # sees [1, 10]
    assert vals[3, 0] == 0.0          # first of seq 1 — no cross-seq bleed


def test_sequence_topk_avg_pooling():
    r = _rag([[3.0, 1.0, 2.0], [5.0]])
    out = S.sequence_topk_avg_pooling(r, topks=[2])
    np.testing.assert_allclose(np.asarray(out), [2.5, 5.0])


def test_sequence_expand_as():
    ref = _rag([[1, 1], [2, 2, 2]])
    x = paddle.to_tensor(np.array([[7.0], [9.0]], "float32"))
    out = S.sequence_expand_as(x, ref)
    np.testing.assert_array_equal(np.asarray(out.values).ravel(),
                                  [7, 7, 9, 9, 9])


def test_registry_contains_sequence_family():
    from paddle_tpu.ops._dispatch import OP_REGISTRY
    for name in ("sequence_mask", "sequence_conv", "sequence_scatter",
                 "sequence_enumerate", "sequence_topk_avg_pooling"):
        assert name in OP_REGISTRY
